"""Native shm queue + DataLoader shared-memory transport tests
(SURVEY §2.1: MemoryMapAllocation / shm DataLoader IPC analog)."""
import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu import native


pytestmark = pytest.mark.skipif(not native.shm_queue_available(),
                                reason=f"no native toolchain: {native.build_error()}")


class TestShmQueue:
    def test_roundtrip(self):
        q = native.ShmQueue("/pq_t_rt", slot_size=1 << 20, n_slots=4)
        try:
            arrs = [np.random.randn(16, 8).astype(np.float32),
                    np.arange(5, dtype=np.int64)]
            q.push(native.encode_batch(arrs), seq=3)
            seq, buf = q.pop()
            assert seq == 3
            back = native.decode_batch(buf)
            np.testing.assert_array_equal(back[0], arrs[0])
            np.testing.assert_array_equal(back[1], arrs[1])
        finally:
            q.close()

    def test_pop_timeout(self):
        q = native.ShmQueue("/pq_t_to", slot_size=1024, n_slots=2)
        try:
            assert q.pop(timeout_ms=50) is None
        finally:
            q.close()

    def test_oversize_payload_raises(self):
        q = native.ShmQueue("/pq_t_big", slot_size=64, n_slots=2)
        try:
            with pytest.raises(ValueError, match="slot size"):
                q.push(b"x" * 128, seq=0)
        finally:
            q.close()

    def test_ring_wraps(self):
        q = native.ShmQueue("/pq_t_wrap", slot_size=256, n_slots=2)
        try:
            for i in range(6):  # 3x the slot count
                q.push(np.uint64(i).tobytes(), seq=i)
                seq, buf = q.pop()
                assert seq == i
        finally:
            q.close()

    def test_cross_process(self):
        import multiprocessing as mp

        q = native.ShmQueue("/pq_t_xp", slot_size=1 << 16, n_slots=4)

        def child(name):
            from paddle_tpu import native as nv

            q2 = nv.ShmQueue(name, create=False)
            for i in range(8):
                q2.push(nv.encode_batch([np.full((3,), i, np.float32)]), seq=i)
            q2.close()

        p = mp.get_context("fork").Process(target=child, args=("/pq_t_xp",))
        p.start()
        got = sorted(q.pop()[0] for _ in range(8))
        p.join()
        q.close()
        assert got == list(range(8))


class _DS:
    def __len__(self):
        return 24

    def __getitem__(self, i):
        return np.full((4,), float(i), np.float32), np.int64(i)


class TestDataLoaderShmTransport:
    def test_loader_uses_shm(self):
        from paddle_tpu.io import DataLoader

        dl = DataLoader(_DS(), batch_size=4, num_workers=2, use_shared_memory=True)
        it = iter(dl)
        assert it._shm is not None  # native transport active
        seen = []
        for xb, yb in it:
            seen.extend(np.asarray(yb._value).tolist())
        assert seen == list(range(24))

    def test_loader_without_shm_matches(self):
        from paddle_tpu.io import DataLoader

        dl = DataLoader(_DS(), batch_size=4, num_workers=2, use_shared_memory=False)
        it = iter(dl)
        assert it._shm is None
        seen = []
        for xb, yb in it:
            seen.extend(np.asarray(yb._value).tolist())
        assert seen == list(range(24))
