"""Continuous-batching serving engine tests: greedy parity with
models.generate, mixed-length admission/retirement across steps WITHOUT
recompilation, and block-pool recycling (VERDICT r4 item 1 done-criteria)."""
import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu.inference import ServingEngine
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

pytestmark = pytest.mark.quick


@pytest.fixture(scope="module")
def model():
    # a leaked fleet hybrid group (e.g. an earlier test file's mp>1 init)
    # would silently make this llama build TP-parallel layers and break
    # engine-vs-generate parity — build single-process explicitly
    from paddle_tpu.distributed.topology import set_hybrid_communicate_group

    set_hybrid_communicate_group(None)
    P.seed(11)
    # narrow config (ROADMAP item 6, tier-1 budget): these tests exercise
    # scheduling/admission/parity, none of which depends on width — but
    # KEEP 2 layers so the per-layer cache/scale threading stays covered
    from paddle_tpu.models.llama import LlamaConfig

    # (vocab stays 512: test prompts carry ids up to 410)
    return LlamaForCausalLM(LlamaConfig(
        vocab_size=512, hidden_size=64, intermediate_size=160,
        num_hidden_layers=2, num_attention_heads=2,
        max_position_embeddings=256))


def ref_greedy(model, prompt, n):
    from paddle_tpu.models.generation import generate

    ids = P.to_tensor(np.asarray(prompt, np.int32)[None, :])
    out = generate(model, ids, max_new_tokens=n, do_sample=False)
    return list(np.asarray(out.numpy()).reshape(-1))


class TestBlockManagerGuards:
    """ISSUE 2 satellite: double-free silently corrupts allocation (two
    sequences handed the same block) — it must raise, naming the ids."""

    def test_double_free_raises_with_ids(self):
        from paddle_tpu.inference import BlockManager

        bm = BlockManager(8)
        blocks = bm.allocate(3)
        bm.free(blocks)
        with pytest.raises(RuntimeError, match="double-free"):
            bm.free([blocks[0]])
        # the error names the offending ids
        with pytest.raises(RuntimeError, match=str(blocks[1])):
            bm.free([blocks[1]])

    def test_repeated_ids_in_one_free_raise(self):
        from paddle_tpu.inference import BlockManager

        bm = BlockManager(8)
        a, b = bm.allocate(2)
        with pytest.raises(RuntimeError, match="repeated"):
            bm.free([a, a, b])
        # the failed free must not have mutated the free list
        assert bm.num_free == 6
        bm.free([a, b])
        assert bm.num_free == 8

    def test_out_of_range_ids_raise(self):
        from paddle_tpu.inference import BlockManager

        bm = BlockManager(4)
        with pytest.raises(RuntimeError, match="outside the pool"):
            bm.free([99])

    def test_allocate_returns_unique_ids(self):
        from paddle_tpu.inference import BlockManager

        bm = BlockManager(16)
        out = bm.allocate(16)
        assert len(set(out)) == 16
        bm.free(out)
        # interleaved alloc/free keeps ids unique
        x = bm.allocate(5)
        y = bm.allocate(5)
        assert not set(x) & set(y)


class TestServingEngine:
    def test_single_request_matches_generate(self, model):
        eng = ServingEngine(model, max_batch_size=2, max_seq_len=64,
                            block_size=8, token_budget=16)
        prompt = [3, 17, 101, 7, 250]
        rid = eng.add_request(prompt, max_new_tokens=8)
        out = eng.run()
        assert out[rid] == ref_greedy(model, prompt, 8)

    def test_mixed_lengths_no_recompile(self, model):
        """Admit sequences of different lengths at different times; the whole
        service runs from ONE compiled step program."""
        eng = ServingEngine(model, max_batch_size=3, max_seq_len=64,
                            block_size=8, token_budget=12)
        p1 = [3, 17, 101, 7, 250, 9, 12]
        p2 = [42, 5]
        p3 = [400, 401, 402, 403, 404, 405, 406, 407, 408, 409, 410]
        r1 = eng.add_request(p1, max_new_tokens=6)
        r2 = eng.add_request(p2, max_new_tokens=4)
        # a few steps in, admit a third request mid-flight
        eng.step()
        eng.step()
        r3 = eng.add_request(p3, max_new_tokens=5)
        out = eng.run()
        assert out[r1] == ref_greedy(model, p1, 6)
        assert out[r2] == ref_greedy(model, p2, 4)
        assert out[r3] == ref_greedy(model, p3, 5)
        if hasattr(eng._step_fn, "_cache_size"):
            # exactly two programs regardless of traffic: the mixed/prefill
            # step (mq=T) and the tight pure-decode step (mq=1)
            assert eng._step_fn._cache_size() <= 2

    def test_engines_share_compiled_programs(self, model):
        """Engines with identical trace-shaping config share one jitted
        program (and so its XLA compile cache): weights/caches/rope are
        call arguments, so nothing per-engine is baked into the trace.
        A different geometry (here token_budget) must NOT share."""
        kw = dict(max_batch_size=3, max_seq_len=64, block_size=8,
                  token_budget=12)
        e1 = ServingEngine(model, **kw)
        e2 = ServingEngine(model, **kw)
        assert e1._step_fn is e2._step_fn
        assert e1._forward is e2._forward
        e3 = ServingEngine(model, **{**kw, "token_budget": 16})
        assert e3._step_fn is not e1._step_fn
        # sharing must not change results: both engines serve correctly
        p = [3, 17, 101, 7]
        r1 = e1.add_request(p, max_new_tokens=5)
        r2 = e2.add_request(p, max_new_tokens=5)
        ref = ref_greedy(model, p, 5)
        assert e1.run()[r1] == ref
        assert e2.run()[r2] == ref

    def test_run_raises_on_max_steps_exhaustion(self, model):
        """ADVICE r5 low #1: a truncated run (max_steps hit with work still
        queued/active) must raise, not return a dict missing tokens."""
        eng = ServingEngine(model, max_batch_size=2, max_seq_len=64,
                            block_size=8, token_budget=16)
        eng.add_request([3, 17, 101], max_new_tokens=8)
        # one step = prefill + first token; the megastep would finish the
        # remaining 7 in step two, so step ONE is the truncation point
        with pytest.raises(RuntimeError, match="max_steps"):
            eng.run(max_steps=1)
        # draining the remaining steps finishes normally
        out = eng.run()
        assert len(next(iter(out.values()))) == 8

    def test_eviction_recycles_blocks_for_queued_requests(self, model):
        """More requests than slots/blocks: later requests wait, get admitted
        as earlier ones retire, and still decode correctly."""
        eng = ServingEngine(model, max_batch_size=2, max_seq_len=32,
                            block_size=8, token_budget=8,
                            num_blocks=8)  # tight pool: 2 seqs of 4 blocks
        prompts = [[3, 17, 101], [42, 5, 7, 9], [250, 4], [88, 13, 77]]
        rids = [eng.add_request(p, max_new_tokens=4) for p in prompts]
        assert eng.num_active <= 2
        out = eng.run()
        for rid, p in zip(rids, prompts):
            assert out[rid] == ref_greedy(model, p, 4)
        assert eng.blocks.num_free == 8  # everything returned to the pool

    def test_eos_early_retirement(self, model):
        prompt = [3, 17, 101, 7]
        full = ref_greedy(model, prompt, 8)
        eos = full[2]  # force early stop at the 3rd generated token
        eng = ServingEngine(model, max_batch_size=2, max_seq_len=64,
                            block_size=8, token_budget=16)
        rid = eng.add_request(prompt, max_new_tokens=8, eos_token_id=eos)
        out = eng.run()
        assert out[rid] == full[:3]

    def test_int8_paged_cache(self, model):
        """int8 cache-quant serving (VERDICT r4 item 1 tail): uint8 paged
        blocks + per-(slot, kv-head) dynamic scales frozen at prefill;
        outputs stay token-identical to the fp engine on this model."""
        import jax.numpy as jnp

        eng = ServingEngine(model, max_batch_size=2, max_seq_len=64,
                            block_size=8, token_budget=16,
                            cache_quant="int8")
        assert eng.key_caches[0].dtype == jnp.uint8
        p1, p2 = [3, 17, 101, 7, 250], [42, 5, 9]
        r1 = eng.add_request(p1, max_new_tokens=6)
        r2 = eng.add_request(p2, max_new_tokens=6)
        out = eng.run()
        assert out[r1] == ref_greedy(model, p1, 6)
        assert out[r2] == ref_greedy(model, p2, 6)
        # prefill froze real scales for the active slots
        kd = np.asarray(eng.cache_scales[0]["kd"])
        assert (kd > 0).all()
        # the one-shot-prefill contract is enforced
        with pytest.raises(ValueError, match="one step"):
            eng.add_request(list(range(20)), max_new_tokens=2)

    def test_int8_prefill_never_chunked_under_load(self, model):
        """With decode traffic eating budget, an int8 prefill must WAIT for
        a one-shot slot rather than chunk (chunked prefills would freeze
        wrong dynamic scales) — and still decode correctly."""
        eng = ServingEngine(model, max_batch_size=2, max_seq_len=64,
                            block_size=8, token_budget=8,
                            cache_quant="int8")
        p1 = [3, 17, 101]
        r1 = eng.add_request(p1, max_new_tokens=10)
        eng.step()  # r1 prefills
        p2 = list(range(40, 48))  # exactly the budget: needs a full step
        r2 = eng.add_request(p2, max_new_tokens=4)
        out = eng.run()
        assert out[r1] == ref_greedy(model, p1, 10)
        assert out[r2] == ref_greedy(model, p2, 4)

    def test_chunked_prefill_long_prompt(self, model):
        """Prompt longer than the token budget: prefill spans several steps,
        output still matches."""
        eng = ServingEngine(model, max_batch_size=2, max_seq_len=64,
                            block_size=8, token_budget=8)
        prompt = list(range(30, 50))  # 20 tokens > budget 8
        rid = eng.add_request(prompt, max_new_tokens=5)
        out = eng.run()
        assert out[rid] == ref_greedy(model, prompt, 5)
