"""paddle.audio + paddle.text parity tests (VERDICT r1 item 6 tail)."""
import os

import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu import audio, text


RNG = np.random.RandomState(21)


class TestAudioFunctional:
    def test_mel_hz_roundtrip(self):
        f = np.array([100.0, 440.0, 4000.0, 10000.0])
        mel = audio.functional.hz_to_mel(f.tolist())
        back = audio.functional.mel_to_hz(mel)
        np.testing.assert_allclose(back, f, rtol=1e-5)

    def test_mel_hz_htk(self):
        # htk formula closed form
        np.testing.assert_allclose(audio.functional.hz_to_mel(700.0, htk=True),
                                   2595.0 * np.log10(2.0), rtol=1e-6)

    def test_fbank_shape_and_partition(self):
        fb = np.asarray(audio.functional.compute_fbank_matrix(16000, 512, n_mels=40)._value)
        assert fb.shape == (40, 257)
        assert (fb >= 0).all()
        # every filter has some mass
        assert (fb.sum(1) > 0).all()

    def test_window_types(self):
        for w in ["hann", "hamming", "blackman", "bartlett", "rectangular"]:
            arr = np.asarray(audio.functional.get_window(w, 64)._value)
            assert arr.shape == (64,)
            assert arr.max() <= 1.0 + 1e-6
        g = np.asarray(audio.functional.get_window(("gaussian", 7.0), 32)._value)
        assert g.argmax() in (15, 16)

    def test_power_to_db(self):
        s = P.to_tensor(np.array([1.0, 0.1, 0.01], np.float32))
        db = np.asarray(audio.functional.power_to_db(s, top_db=None)._value)
        np.testing.assert_allclose(db, [0.0, -10.0, -20.0], atol=1e-4)

    def test_dct_orthonormal(self):
        d = np.asarray(audio.functional.create_dct(13, 40)._value)
        assert d.shape == (40, 13)
        np.testing.assert_allclose(d.T @ d, np.eye(13), atol=1e-5)


class TestAudioFeatures:
    def test_spectrogram_parseval_vs_numpy(self):
        x = RNG.randn(1, 2048).astype(np.float32)
        spec = audio.features.Spectrogram(n_fft=256, hop_length=128, window="hann",
                                          power=2.0, center=False)
        out = np.asarray(spec(P.to_tensor(x))._value)
        assert out.shape[1] == 129  # bins
        # frame 0 against numpy stft
        w = 0.5 - 0.5 * np.cos(2 * np.pi * np.arange(256) / 256)
        ref = np.abs(np.fft.rfft(x[0, :256] * w)) ** 2
        np.testing.assert_allclose(out[0, :, 0], ref, rtol=1e-3, atol=1e-3)

    def test_melspectrogram_and_mfcc_shapes(self):
        x = P.to_tensor(RNG.randn(2, 4000).astype(np.float32))
        mel = audio.features.MelSpectrogram(sr=16000, n_fft=512, n_mels=40)
        m = mel(x)
        assert list(m.shape)[:2] == [2, 40]
        mfcc = audio.features.MFCC(sr=16000, n_mfcc=13, n_fft=512, n_mels=40)
        c = mfcc(x)
        assert list(c.shape)[:2] == [2, 13]

    def test_gradient_flows_to_waveform(self):
        x = P.to_tensor(RNG.randn(1, 1024).astype(np.float32))
        x.stop_gradient = False
        lm = audio.features.LogMelSpectrogram(sr=8000, n_fft=256, n_mels=20)
        P.sum(lm(x)).backward()
        assert x.grad is not None
        assert np.isfinite(np.asarray(x.grad._value)).all()


class TestAudioBackend:
    def test_wav_roundtrip(self, tmp_path):
        path = os.path.join(str(tmp_path), "t.wav")
        sig = (0.5 * np.sin(2 * np.pi * 440 * np.arange(8000) / 8000)).astype(np.float32)
        audio.save(path, P.to_tensor(sig[None, :]), 8000)
        back, sr = audio.load(path)
        assert sr == 8000
        np.testing.assert_allclose(np.asarray(back._value)[0], sig, atol=1e-3)


class TestViterbi:
    def _brute(self, pot, trans, include=False):
        T, N = pot.shape
        best, arg = -1e30, None
        import itertools

        for path in itertools.product(range(N), repeat=T):
            s = pot[0, path[0]] + (trans[N - 2, path[0]] if include else 0)
            for t in range(1, T):
                s += trans[path[t - 1], path[t]] + pot[t, path[t]]
            if include:
                s += trans[path[-1], N - 1]
            if s > best:
                best, arg = s, path
        return best, list(arg)

    def test_matches_brute_force(self):
        pot = RNG.randn(1, 4, 3).astype(np.float32)
        trans = RNG.randn(3, 3).astype(np.float32)
        scores, paths = text.viterbi_decode(P.to_tensor(pot), P.to_tensor(trans),
                                            P.to_tensor(np.array([4])),
                                            include_bos_eos_tag=False)
        ref_s, ref_p = self._brute(pot[0], trans, include=False)
        np.testing.assert_allclose(float(np.asarray(scores._value)[0]), ref_s, rtol=1e-5)
        assert np.asarray(paths._value)[0].tolist() == ref_p

    def test_bos_eos_mode(self):
        pot = RNG.randn(1, 3, 5).astype(np.float32)
        trans = RNG.randn(5, 5).astype(np.float32)
        scores, paths = text.viterbi_decode(P.to_tensor(pot), P.to_tensor(trans),
                                            P.to_tensor(np.array([3])),
                                            include_bos_eos_tag=True)
        ref_s, ref_p = self._brute(pot[0], trans, include=True)
        np.testing.assert_allclose(float(np.asarray(scores._value)[0]), ref_s, rtol=1e-5)
        assert np.asarray(paths._value)[0].tolist() == ref_p

    def test_batch_with_lengths(self):
        pot = RNG.randn(2, 5, 3).astype(np.float32)
        trans = RNG.randn(3, 3).astype(np.float32)
        scores, paths = text.viterbi_decode(P.to_tensor(pot), P.to_tensor(trans),
                                            P.to_tensor(np.array([5, 3])),
                                            include_bos_eos_tag=False)
        # batch element 1 decoded over its first 3 steps only
        s1, p1 = self._brute(pot[1, :3], trans, include=False)
        np.testing.assert_allclose(float(np.asarray(scores._value)[1]), s1, rtol=1e-4)
        assert np.asarray(paths._value)[1, :3].tolist() == p1

    def test_decoder_layer(self):
        trans = RNG.randn(4, 4).astype(np.float32)
        dec = text.ViterbiDecoder(P.to_tensor(trans), include_bos_eos_tag=False)
        pot = P.to_tensor(RNG.randn(2, 6, 4).astype(np.float32))
        scores, paths = dec(pot, P.to_tensor(np.array([6, 6])))
        assert list(paths.shape) == [2, 6]


class TestTextDatasets:
    def test_uci_housing_local(self, tmp_path):
        f = os.path.join(str(tmp_path), "housing.data")
        np.savetxt(f, RNG.rand(50, 14))
        ds = text.UCIHousing(data_file=f, mode="train")
        assert len(ds) == 40
        x, y = ds[0]
        assert x.shape == (13,) and y.shape == (1,)

    def test_missing_data_raises(self):
        with pytest.raises(RuntimeError, match="no network"):
            text.UCIHousing()
        with pytest.raises(RuntimeError, match="no network"):
            audio.datasets.ESC50(data_dir=None)


class TestHapiCallbacks:
    def test_early_stopping_and_history(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.hapi import Model
        from paddle_tpu.hapi.callbacks import EarlyStopping

        class DS:
            def __len__(self):
                return 16

            def __getitem__(self, i):
                x = np.zeros(4, np.float32)
                return x, np.zeros(1, np.float32)

        net = nn.Linear(4, 1)
        m = Model(net)
        m.prepare(P.optimizer.SGD(parameters=net.parameters(), learning_rate=0.0),
                  loss=lambda o, y: P.mean((o - y) ** 2))
        es = EarlyStopping(monitor="loss", patience=1, min_delta=1e-9)
        hist = m.fit(DS(), batch_size=8, epochs=10, verbose=0, callbacks=[es])
        # zero LR -> loss never improves -> stops after ~2-3 epochs, not 10
        assert len(hist["loss"]) < 10

    def test_lr_scheduler_callback_steps(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.hapi import Model
        from paddle_tpu.hapi.callbacks import LRScheduler as LRCb

        class DS:
            def __len__(self):
                return 8

            def __getitem__(self, i):
                return np.zeros(4, np.float32), np.zeros(1, np.float32)

        net = nn.Linear(4, 1)
        sched = P.optimizer.lr.StepDecay(learning_rate=0.1, step_size=2, gamma=0.5)
        opt = P.optimizer.SGD(parameters=net.parameters(), learning_rate=sched)
        m = Model(net)
        m.prepare(opt, loss=lambda o, y: P.mean((o - y) ** 2))
        m.fit(DS(), batch_size=4, epochs=1, verbose=0, callbacks=[LRCb(by_step=True)])
        assert sched.last_lr < 0.1  # stepped twice -> decayed at step 4


class TestASP:
    def test_prune_model_2of4(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.incubate import asp

        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        asp.prune_model(net)
        w = np.asarray(net[0].weight._value)
        assert abs(asp.calculate_density(net[0].weight) - 0.5) < 1e-6
        # every group of 4 along rows has exactly 2 nonzeros
        groups = w.reshape(-1)[: (w.size // 4) * 4].reshape(-1, 4)
        assert ((groups != 0).sum(1) == 2).all()

    def test_decorated_optimizer_keeps_sparsity(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.incubate import asp

        net = nn.Linear(8, 8)
        masks = asp.prune_model(net)
        assert masks
        opt = asp.decorate(P.optimizer.SGD(parameters=net.parameters(), learning_rate=0.1))
        x = P.to_tensor(np.random.randn(4, 8).astype(np.float32))
        for _ in range(3):
            loss = P.mean(net(x) ** 2)
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert abs(asp.calculate_density(net.weight) - 0.5) < 1e-6

    def test_excluded_layers(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.incubate import asp

        net = nn.Sequential(nn.Linear(8, 8), nn.Linear(8, 8))
        asp.set_excluded_layers(net, ["0"])
        asp.prune_model(net)
        asp.reset_excluded_layers(net)
        assert asp.calculate_density(net[0].weight) == 1.0
        assert abs(asp.calculate_density(net[1].weight) - 0.5) < 1e-6

    def test_asp_custom_nm(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.incubate import asp

        net = nn.Linear(8, 8)
        asp.prune_model(net, n=1, m=4)
        assert abs(asp.calculate_density(net.weight) - 0.25) < 1e-6
