"""RPC + parameter-server sharded embedding (VERDICT r2 item 9; reference:
python/paddle/distributed/rpc/rpc.py:73, distributed/ps/the_one_ps.py)."""
import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fresh_rpc():
    from paddle_tpu.distributed import rpc

    rpc.shutdown()
    return rpc


def test_rpc_sync_async_in_process():
    rpc = _fresh_rpc()
    rpc.init_rpc("solo", rank=0, world_size=1)
    try:
        import operator

        assert rpc.rpc_sync("solo", operator.add, args=(2, 3)) == 5
        fut = rpc.rpc_async("solo", pow, args=(2, 10))
        assert fut.wait() == 1024
        info = rpc.get_worker_info()
        assert info.name == "solo" and info.rank == 0
        # errors propagate
        with pytest.raises(ZeroDivisionError):
            rpc.rpc_sync("solo", operator.truediv, args=(1, 0))
    finally:
        rpc.shutdown()


SERVER = textwrap.dedent("""
    import os, sys, time
    sys.path.insert(0, os.environ["PADDLE_TPU_REPO"])
    from paddle_tpu.distributed import rpc, ps
    from paddle_tpu.distributed.launch.master import KVClient

    name = sys.argv[1]
    rank = int(sys.argv[2])
    master = sys.argv[3]
    rpc.init_rpc(name, rank=rank, world_size=3, master_endpoint=master)
    ps.start_server(name, dim=4, initializer="uniform", seed=rank)
    kv = KVClient(master)
    kv.put(f"/ps/ready/{name}", "1")
    while kv.get("/ps/done") is None:
        time.sleep(0.1)
    rpc.shutdown()
""")

TRAINER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, os.environ["PADDLE_TPU_REPO"])
    import numpy as np
    from paddle_tpu.distributed import rpc, ps
    from paddle_tpu.distributed.launch.master import KVClient

    master = sys.argv[1]
    rpc.init_rpc("trainer", rank=2, world_size=3, master_endpoint=master)
    kv = KVClient(master)
    kv.wait_n("/ps/ready/", 2, timeout=60)

    emb = ps.ShardedEmbedding("emb", dim=4, servers=["server0", "server1"])
    ids = np.array([[0, 1], [5, 0]])
    rows = emb.pull(ids)
    assert rows.shape == (2, 2, 4)
    # same id pulls the same row
    np.testing.assert_allclose(rows[0, 0], rows[1, 1])

    # push a sparse gradient: row 0 appears twice -> both updates apply
    g = np.ones((2, 2, 4), np.float32)
    emb.push(ids, g, lr=0.5)
    rows2 = emb.pull(ids)
    np.testing.assert_allclose(rows2[0, 0], rows[0, 0] - 2 * 0.5, atol=1e-6)
    np.testing.assert_allclose(rows2[0, 1], rows[0, 1] - 0.5, atol=1e-6)
    # rows are hash-sharded across both servers (0 -> s0, 1/5 -> s1)
    sizes = emb.server_sizes()
    assert sizes[0] >= 1 and sizes[1] >= 2, sizes

    kv.put("/ps/done", "1")
    rpc.shutdown()
    print("PS_OK")
""")


def test_sharded_embedding_push_pull_cross_process(tmp_path):
    from paddle_tpu.distributed.launch.master import KVServer

    srv = KVServer(0).start()
    master = f"127.0.0.1:{srv.port}"
    env = dict(os.environ)
    env["PADDLE_TPU_REPO"] = REPO
    sfile = tmp_path / "server.py"
    sfile.write_text(SERVER)
    tfile = tmp_path / "trainer.py"
    tfile.write_text(TRAINER)
    procs = [
        subprocess.Popen([sys.executable, str(sfile), f"server{i}", str(i), master],
                         env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True)
        for i in range(2)
    ]
    try:
        r = subprocess.run([sys.executable, str(tfile), master], env=env,
                           capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, (r.stdout[-1500:], r.stderr[-1500:])
        assert "PS_OK" in r.stdout
        for p in procs:
            out, err = p.communicate(timeout=30)
            assert p.returncode == 0, err[-1500:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        srv.stop()


def test_table_accessors_match_dense_reference():
    """Adagrad/Adam PS accessors == the dense numpy update (VERDICT r3 weak
    #6: PS was SGD-only)."""
    from paddle_tpu.distributed.ps import Table

    rng = np.random.RandomState(0)
    g1 = rng.randn(4).astype(np.float32)
    g2 = rng.randn(4).astype(np.float32)

    # adagrad
    t = Table("t", 4, accessor="adagrad")
    t.push([7], g1[None], lr=0.1)
    t.push([7], g2[None], lr=0.1)
    acc = g1 * g1
    ref = -0.1 * g1 / (np.sqrt(acc) + 1e-8)
    acc = acc + g2 * g2
    ref = ref - 0.1 * g2 / (np.sqrt(acc) + 1e-8)
    np.testing.assert_allclose(t.pull([7])[0], ref, rtol=1e-6)

    # adam
    t = Table("t", 4, accessor="adam")
    t.push([3], g1[None], lr=0.1)
    m = 0.1 * g1
    v = 0.001 * g1 * g1
    ref = -0.1 * (m / (1 - 0.9)) / (np.sqrt(v / (1 - 0.999)) + 1e-8)
    np.testing.assert_allclose(t.pull([3])[0], ref, rtol=1e-5)


def test_table_entry_admission():
    """CountFilterEntry gates row creation until enough pushes arrive."""
    from paddle_tpu.distributed.entry_attr import CountFilterEntry
    from paddle_tpu.distributed.ps import Table

    t = Table("t", 2, accessor="sgd", entry=CountFilterEntry(3))
    g = np.ones((1, 2), np.float32)
    t.push([5], g, lr=1.0)
    t.push([5], g, lr=1.0)
    assert t.size() == 0  # not admitted yet
    t.push([5], g, lr=1.0)  # third sighting admits the row
    assert t.size() == 1
    np.testing.assert_allclose(t.pull([5])[0], [-1.0, -1.0])


def test_table_save_load_roundtrip(tmp_path):
    from paddle_tpu.distributed.ps import Table

    t = Table("t", 3, accessor="adam")
    t.push([1, 9], np.random.RandomState(1).randn(2, 3).astype(np.float32), lr=0.05)
    t.save(str(tmp_path / "shard0"))
    t2 = Table("t", 3, accessor="adam")
    t2.load(str(tmp_path / "shard0"))
    np.testing.assert_allclose(t2.pull([1, 9]), t.pull([1, 9]))
    # optimizer state survived: identical next update
    g = np.random.RandomState(2).randn(2, 3).astype(np.float32)
    t.push([1, 9], g, lr=0.05)
    t2.push([1, 9], g, lr=0.05)
    np.testing.assert_allclose(t2.pull([1, 9]), t.pull([1, 9]), rtol=1e-6)


def test_geo_sharded_embedding_in_process():
    """Geo-async mode: local cache + delta sync every geo_steps pushes."""
    from paddle_tpu.distributed import rpc
    from paddle_tpu.distributed.ps import GeoShardedEmbedding, start_server
    from paddle_tpu.distributed.ps import _worker

    rpc.init_rpc("geo_solo", rank=0, world_size=1)
    try:
        start_server("geo_solo", dim=2, table_name="geo_emb", initializer="zeros")
        emb = GeoShardedEmbedding("geo_emb", 2, ["geo_solo"], geo_steps=2)
        g = np.ones((1, 2), np.float32)
        emb.pull(np.array([4]))
        emb.push(np.array([4]), g, lr=0.5)       # local only
        # server row untouched until the geo sync fires
        np.testing.assert_allclose(_worker.TABLES["geo_emb"].pull([4])[0], [0.0, 0.0])
        emb.push(np.array([4]), g, lr=0.5)       # second push -> geo sync
        server_row = _worker.TABLES["geo_emb"].pull([4])[0]
        np.testing.assert_allclose(server_row, [-1.0, -1.0])  # both deltas merged
        # cache dropped at sync: next pull refetches the merged row
        np.testing.assert_allclose(emb.pull(np.array([4]))[0], [-1.0, -1.0])
    finally:
        rpc.shutdown()


def test_pull_async_overlaps_and_matches_sync():
    """VERDICT r4 weak #5: trainer-side lookups can overlap the XLA step —
    pull_async prefetches on a background thread and returns the same rows
    the synchronous pull would."""
    from paddle_tpu.distributed import rpc
    from paddle_tpu.distributed.ps import ShardedEmbedding, start_server

    rpc.init_rpc("ps_async_solo", rank=0, world_size=1)
    try:
        start_server("ps_async_solo", dim=4, table_name="aemb", seed=3)
        emb = ShardedEmbedding("aemb", 4, ["ps_async_solo"])
        ids = np.arange(64)
        emb.push(ids, np.random.RandomState(0).randn(64, 4).astype(np.float32),
                 lr=0.1)
        fut = emb.pull_async(ids)  # overlaps "the XLA step" (any host work)
        busy = sum(i * i for i in range(10000))  # stand-in for step dispatch
        rows_async = fut.result(timeout=30)
        rows_sync = emb.pull(ids)
        np.testing.assert_array_equal(rows_async, rows_sync)
        assert busy > 0
        emb.close()
        with pytest.raises(RuntimeError, match="close"):
            emb.pull_async(ids)  # fail-loud after close, no pool resurrection
    finally:
        rpc.shutdown()


def test_ps_pull_push_throughput_recorded():
    """VERDICT r4 weak #5: measure (don't just claim) PS pull/push rates.
    In-process loopback, dim=64: prints rows/s and asserts a generous floor
    so a pathological regression (e.g. per-row RPC) fails loudly."""
    import time as _t

    from paddle_tpu.distributed import rpc
    from paddle_tpu.distributed.ps import ShardedEmbedding, start_server

    rpc.init_rpc("ps_bench_solo", rank=0, world_size=1)
    try:
        start_server("ps_bench_solo", dim=64, table_name="bemb")
        emb = ShardedEmbedding("bemb", 64, ["ps_bench_solo"])
        n = 4096
        ids = np.arange(n)
        g = np.ones((n, 64), np.float32)
        emb.push(ids, g, lr=0.1)  # warm/admit
        t0 = _t.perf_counter()
        for _ in range(3):
            emb.pull(ids)
        pull_rps = 3 * n / (_t.perf_counter() - t0)
        t0 = _t.perf_counter()
        for _ in range(3):
            emb.push(ids, g, lr=0.1)
        push_rps = 3 * n / (_t.perf_counter() - t0)
        print(f"\nps throughput: pull {pull_rps:,.0f} rows/s, "
              f"push {push_rps:,.0f} rows/s (dim=64, loopback)")
        assert pull_rps > 2000 and push_rps > 2000
    finally:
        rpc.shutdown()


def test_pull_does_not_bypass_entry_admission():
    """Reads must not admit rows: the standard pull-then-push flow still
    goes through the entry policy (review regression)."""
    from paddle_tpu.distributed.entry_attr import CountFilterEntry
    from paddle_tpu.distributed.ps import Table

    t = Table("t", 2, accessor="sgd", entry=CountFilterEntry(2))
    g = np.ones((1, 2), np.float32)
    np.testing.assert_allclose(t.pull([5])[0], [0.0, 0.0])  # read-only
    assert t.size() == 0
    t.push([5], g, lr=1.0)   # first sighting: below count filter
    assert t.size() == 0
    t.push([5], g, lr=1.0)   # second sighting admits
    assert t.size() == 1
