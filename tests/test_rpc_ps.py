"""RPC + parameter-server sharded embedding (VERDICT r2 item 9; reference:
python/paddle/distributed/rpc/rpc.py:73, distributed/ps/the_one_ps.py)."""
import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fresh_rpc():
    from paddle_tpu.distributed import rpc

    rpc.shutdown()
    return rpc


def test_rpc_sync_async_in_process():
    rpc = _fresh_rpc()
    rpc.init_rpc("solo", rank=0, world_size=1)
    try:
        import operator

        assert rpc.rpc_sync("solo", operator.add, args=(2, 3)) == 5
        fut = rpc.rpc_async("solo", pow, args=(2, 10))
        assert fut.wait() == 1024
        info = rpc.get_worker_info()
        assert info.name == "solo" and info.rank == 0
        # errors propagate
        with pytest.raises(ZeroDivisionError):
            rpc.rpc_sync("solo", operator.truediv, args=(1, 0))
    finally:
        rpc.shutdown()


SERVER = textwrap.dedent("""
    import os, sys, time
    sys.path.insert(0, os.environ["PADDLE_TPU_REPO"])
    from paddle_tpu.distributed import rpc, ps
    from paddle_tpu.distributed.launch.master import KVClient

    name = sys.argv[1]
    rank = int(sys.argv[2])
    master = sys.argv[3]
    rpc.init_rpc(name, rank=rank, world_size=3, master_endpoint=master)
    ps.start_server(name, dim=4, initializer="uniform", seed=rank)
    kv = KVClient(master)
    kv.put(f"/ps/ready/{name}", "1")
    while kv.get("/ps/done") is None:
        time.sleep(0.1)
    rpc.shutdown()
""")

TRAINER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, os.environ["PADDLE_TPU_REPO"])
    import numpy as np
    from paddle_tpu.distributed import rpc, ps
    from paddle_tpu.distributed.launch.master import KVClient

    master = sys.argv[1]
    rpc.init_rpc("trainer", rank=2, world_size=3, master_endpoint=master)
    kv = KVClient(master)
    kv.wait_n("/ps/ready/", 2, timeout=60)

    emb = ps.ShardedEmbedding("emb", dim=4, servers=["server0", "server1"])
    ids = np.array([[0, 1], [5, 0]])
    rows = emb.pull(ids)
    assert rows.shape == (2, 2, 4)
    # same id pulls the same row
    np.testing.assert_allclose(rows[0, 0], rows[1, 1])

    # push a sparse gradient: row 0 appears twice -> both updates apply
    g = np.ones((2, 2, 4), np.float32)
    emb.push(ids, g, lr=0.5)
    rows2 = emb.pull(ids)
    np.testing.assert_allclose(rows2[0, 0], rows[0, 0] - 2 * 0.5, atol=1e-6)
    np.testing.assert_allclose(rows2[0, 1], rows[0, 1] - 0.5, atol=1e-6)
    # rows are hash-sharded across both servers (0 -> s0, 1/5 -> s1)
    sizes = emb.server_sizes()
    assert sizes[0] >= 1 and sizes[1] >= 2, sizes

    kv.put("/ps/done", "1")
    rpc.shutdown()
    print("PS_OK")
""")


def test_sharded_embedding_push_pull_cross_process(tmp_path):
    from paddle_tpu.distributed.launch.master import KVServer

    srv = KVServer(0).start()
    master = f"127.0.0.1:{srv.port}"
    env = dict(os.environ)
    env["PADDLE_TPU_REPO"] = REPO
    sfile = tmp_path / "server.py"
    sfile.write_text(SERVER)
    tfile = tmp_path / "trainer.py"
    tfile.write_text(TRAINER)
    procs = [
        subprocess.Popen([sys.executable, str(sfile), f"server{i}", str(i), master],
                         env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True)
        for i in range(2)
    ]
    try:
        r = subprocess.run([sys.executable, str(tfile), master], env=env,
                           capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, (r.stdout[-1500:], r.stderr[-1500:])
        assert "PS_OK" in r.stdout
        for p in procs:
            out, err = p.communicate(timeout=30)
            assert p.returncode == 0, err[-1500:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        srv.stop()
