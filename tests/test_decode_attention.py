"""Pallas decode-attention kernel (ops/pallas/decode_attention.py): interpret-
mode parity vs the jnp reference, ring-write aliasing semantics, GQA
indexing. (On the real chip the EINSUM decode path is the default — measured
faster than this kernel on v5e; see PROFILE_r04.md — but the kernel must stay
numerically correct.)"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.decode_attention import (
    decode_attention,
    kv_ring_write,
    ref_decode_attention,
)

RNG = np.random.RandomState(0)


class TestDecodeKernelInterpret:
    @pytest.mark.parametrize("pos", [0, 5, 130, 255])
    def test_matches_reference(self, pos):
        B, H, KVH, D, L = 2, 4, 4, 128, 256
        q = jnp.asarray(RNG.randn(B, 1, H, D), jnp.float32)
        kb = jnp.asarray(RNG.randn(B, L, KVH, D), jnp.float32)
        vb = jnp.asarray(RNG.randn(B, L, KVH, D), jnp.float32)
        out = decode_attention(q, kb, vb, jnp.int32(pos), interpret=True)
        ref = ref_decode_attention(q, kb, vb, jnp.int32(pos))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)

    def test_gqa_grouped_heads(self):
        B, H, KVH, D, L = 2, 4, 2, 128, 256
        q = jnp.asarray(RNG.randn(B, 1, H, D), jnp.float32)
        kb = jnp.asarray(RNG.randn(B, L, KVH, D), jnp.float32)
        vb = jnp.asarray(RNG.randn(B, L, KVH, D), jnp.float32)
        out = decode_attention(q, kb, vb, jnp.int32(100), interpret=True)
        ref = ref_decode_attention(q, kb, vb, jnp.int32(100))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)

    def test_ring_write(self):
        B, KVH, D, L = 2, 4, 128, 64
        buf = jnp.asarray(RNG.randn(B, L, KVH, D), jnp.float32)
        new = jnp.asarray(RNG.randn(B, 1, KVH, D), jnp.float32)
        out = kv_ring_write(buf, new, jnp.int32(7), interpret=True)
        ref = buf.at[:, 7].set(new[:, 0])
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref))

    def test_under_jit(self):
        B, H, D, L = 2, 4, 128, 256
        q = jnp.asarray(RNG.randn(B, 1, H, D), jnp.float32)
        kb = jnp.asarray(RNG.randn(B, L, H, D), jnp.float32)
        vb = jnp.asarray(RNG.randn(B, L, H, D), jnp.float32)

        @jax.jit
        def f(q, pos):
            return decode_attention(q, kb, vb, pos, interpret=True)

        out = f(q, jnp.int32(50))
        ref = ref_decode_attention(q, kb, vb, jnp.int32(50))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)
