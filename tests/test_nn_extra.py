"""nn/functional/linalg/optimizer tail tests — closes the remaining
namespace gaps (paddle.nn 0/140, paddle.nn.functional 0/128 missing)."""
import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as P
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


RNG = np.random.RandomState(41)


def _v(t):
    return np.asarray(t._value)


class TestNamespaces:
    @pytest.mark.parametrize("ref,mod", [
        ("/root/reference/python/paddle/nn/__init__.py", nn),
        ("/root/reference/python/paddle/nn/functional/__init__.py", F),
    ], ids=["nn", "functional"])
    def test_zero_missing(self, ref, mod):
        import os

        if not os.path.exists(ref):
            pytest.skip("reference not mounted")
        names = set(re.findall(r"^\s+'([A-Za-z_0-9]+)',\s*$", open(ref).read(), re.M))
        missing = sorted(n for n in names if not hasattr(mod, n))
        assert missing == [], missing


class TestSampling:
    def test_affine_grid_identity(self):
        theta = np.array([[[1.0, 0, 0], [0, 1.0, 0]]], np.float32)
        grid = F.affine_grid(P.to_tensor(theta), [1, 1, 4, 4])
        g = _v(grid)
        np.testing.assert_allclose(g[0, 0, 0], [-1, -1], atol=1e-6)
        np.testing.assert_allclose(g[0, -1, -1], [1, 1], atol=1e-6)

    def test_grid_sample_identity(self):
        x = RNG.randn(1, 2, 5, 5).astype(np.float32)
        theta = np.array([[[1.0, 0, 0], [0, 1.0, 0]]], np.float32)
        grid = F.affine_grid(P.to_tensor(theta), [1, 2, 5, 5])
        out = F.grid_sample(P.to_tensor(x), grid)
        np.testing.assert_allclose(_v(out), x, rtol=1e-4, atol=1e-5)

    def test_grid_sample_gradients(self):
        x = P.to_tensor(RNG.randn(1, 1, 4, 4).astype(np.float32))
        x.stop_gradient = False
        grid = P.to_tensor(RNG.rand(1, 3, 3, 2).astype(np.float32) * 0.8 - 0.4)
        P.sum(F.grid_sample(x, grid)).backward()
        assert x.grad is not None


class TestSequenceOps:
    def test_sequence_mask(self):
        m = F.sequence_mask(P.to_tensor(np.array([2, 4])), maxlen=5)
        np.testing.assert_array_equal(_v(m), [[1, 1, 0, 0, 0], [1, 1, 1, 1, 0]])

    def test_temporal_shift_shapes(self):
        x = P.to_tensor(RNG.randn(4, 8, 3, 3).astype(np.float32))  # 2 videos x 2 segs
        out = F.temporal_shift(x, seg_num=2, shift_ratio=0.25)
        assert list(out.shape) == [4, 8, 3, 3]

    def test_gather_tree(self):
        ids = np.array([[[2, 5]], [[3, 6]], [[4, 7]]], np.int64)  # [T=3, B=1, beam=2]
        parents = np.array([[[0, 0]], [[0, 0]], [[1, 0]]], np.int64)
        out = _v(F.gather_tree(P.to_tensor(ids), P.to_tensor(parents)))
        # beam 0 at final step came from parent 1 at t=2
        assert out.shape == (3, 1, 2)


class TestLossTail:
    def test_dice_loss_perfect(self):
        p = np.zeros((2, 3), np.float32)
        p[:, 1] = 1.0
        lbl = np.full((2, 1), 1, np.int64)
        loss = F.dice_loss(P.to_tensor(p), P.to_tensor(lbl))
        assert float(_v(loss)) < 1e-4

    def test_pairwise_distance(self):
        x = RNG.randn(4, 8).astype(np.float32)
        y = RNG.randn(4, 8).astype(np.float32)
        d = _v(F.pairwise_distance(P.to_tensor(x), P.to_tensor(y)))
        np.testing.assert_allclose(d, np.linalg.norm(x - y + 1e-6, axis=1), rtol=1e-4)

    def test_gaussian_nll(self):
        x = P.to_tensor(np.zeros(4, np.float32))
        y = P.to_tensor(np.zeros(4, np.float32))
        var = P.to_tensor(np.ones(4, np.float32))
        np.testing.assert_allclose(float(_v(F.gaussian_nll_loss(x, y, var))), 0.0, atol=1e-6)

    def test_multi_margin(self):
        x = P.to_tensor(np.array([[0.1, 0.9, 0.2]], np.float32))
        y = P.to_tensor(np.array([1], np.int64))
        v = float(_v(F.multi_margin_loss(x, y, margin=1.0)))
        expect = (max(0, 1 - 0.9 + 0.1) + max(0, 1 - 0.9 + 0.2)) / 3
        np.testing.assert_allclose(v, expect, rtol=1e-5)

    def test_triplet_with_distance(self):
        a = P.to_tensor(np.zeros((2, 4), np.float32))
        p = P.to_tensor(np.zeros((2, 4), np.float32))
        n = P.to_tensor(np.full((2, 4), 10.0, np.float32))
        v = float(_v(F.triplet_margin_with_distance_loss(a, p, n, margin=1.0)))
        assert v == 0.0  # d(a,p)=0, d(a,n)=20 >> margin

    def test_hsigmoid_loss_trains(self):
        layer = nn.HSigmoidLoss(8, 6)
        x = P.to_tensor(RNG.randn(4, 8).astype(np.float32))
        x.stop_gradient = False
        y = P.to_tensor(np.array([0, 1, 2, 3], np.int64))
        loss = layer(x, y)
        loss.backward()
        assert layer.weight.grad is not None and np.isfinite(float(_v(loss)))

    def test_margin_cross_entropy(self):
        logits = P.to_tensor((RNG.rand(4, 10).astype(np.float32) - 0.5) * 1.8)
        y = P.to_tensor(np.array([1, 2, 3, 4], np.int64))
        loss, sm = F.margin_cross_entropy(logits, y, return_softmax=True)
        assert np.isfinite(float(_v(loss)))
        np.testing.assert_allclose(_v(sm).sum(1), 1.0, rtol=1e-5)

    def test_rnnt_loss_single_path(self):
        # V=2 (blank=0, label=1), T=2, U=1: enumerate paths by brute force
        B, T, U1, V = 1, 2, 2, 2
        logits = RNG.randn(B, T, U1, V).astype(np.float32)
        lp = np.asarray(jax.nn.log_softmax(jnp.asarray(logits), -1))
        # paths emitting exactly label [1]: emit@t0 then blanks, or blank, emit@t1, blank
        p1 = lp[0, 0, 0, 1] + lp[0, 0, 1, 0] + lp[0, 1, 1, 0]
        p2 = lp[0, 0, 0, 0] + lp[0, 1, 0, 1] + lp[0, 1, 1, 0]
        expect = -np.logaddexp(p1, p2)
        got = float(_v(F.rnnt_loss(P.to_tensor(logits), P.to_tensor(np.array([[1]])),
                                   P.to_tensor(np.array([2])), P.to_tensor(np.array([1])))))
        np.testing.assert_allclose(got, expect, rtol=1e-4)


class TestPoolTail:
    def test_unpool2d_roundtrip(self):
        x = P.to_tensor(RNG.randn(1, 2, 6, 6).astype(np.float32))
        pooled, idx = F.max_pool2d_with_index(x, 2)
        up = F.max_unpool2d(pooled, idx, 2)
        assert list(up.shape) == [1, 2, 6, 6]
        # the max positions carry their values; everything else is zero
        total_nonzero = (_v(up) != 0).sum()
        assert total_nonzero == 2 * 3 * 3

    def test_lp_pool2d_limits(self):
        x = P.to_tensor(np.abs(RNG.randn(1, 1, 4, 4)).astype(np.float32))
        # p=1 -> sum pooling
        out = _v(F.lp_pool2d(x, 1.0, 2))
        ref = _v(x).reshape(1, 1, 2, 2, 2, 2).transpose(0, 1, 2, 4, 3, 5)
        ref = np.abs(ref).reshape(1, 1, 2, 2, 4).sum(-1)
        np.testing.assert_allclose(out, ref, rtol=1e-4)

    def test_fractional_pool(self):
        x = P.to_tensor(RNG.randn(1, 2, 9, 9).astype(np.float32))
        out = F.fractional_max_pool2d(x, 4)
        assert list(out.shape) == [1, 2, 4, 4]
        x3 = P.to_tensor(RNG.randn(1, 1, 6, 6, 6).astype(np.float32))
        out3 = F.fractional_max_pool3d(x3, 3)
        assert list(out3.shape) == [1, 1, 3, 3, 3]

    def test_feature_alpha_dropout(self):
        x = P.to_tensor(RNG.randn(8, 16, 4).astype(np.float32))
        out = F.feature_alpha_dropout(x, p=0.5, training=True)
        assert list(out.shape) == [8, 16, 4]
        out_eval = F.feature_alpha_dropout(x, p=0.5, training=False)
        np.testing.assert_allclose(_v(out_eval), _v(x))


class TestLayerTail:
    def test_small_layers(self):
        x = P.to_tensor(RNG.randn(2, 4, 3, 3).astype(np.float32))
        assert list(nn.Softmax2D()(x).shape) == [2, 4, 3, 3]
        np.testing.assert_allclose(_v(nn.Softmax2D()(x)).sum(1), 1.0, rtol=1e-5)
        assert list(nn.Silu()(x).shape) == [2, 4, 3, 3]
        u = nn.Unflatten(1, [2, 2])(P.to_tensor(RNG.randn(3, 4).astype(np.float32)))
        assert list(u.shape) == [3, 2, 2]
        zp = nn.ZeroPad1D(2)(P.to_tensor(RNG.randn(1, 2, 5).astype(np.float32)))
        assert list(zp.shape) == [1, 2, 9]

    def test_adaptive_log_softmax(self):
        layer = nn.AdaptiveLogSoftmaxWithLoss(16, 20, cutoffs=[5, 10])
        x = P.to_tensor(RNG.randn(6, 16).astype(np.float32))
        y = P.to_tensor(np.array([0, 4, 6, 9, 12, 19], np.int64))
        lp, loss = layer(x, y)
        assert list(lp.shape) == [6]
        assert np.isfinite(float(_v(loss)))

    def test_birnn(self):
        cell_fw = nn.GRUCell(8, 16)
        cell_bw = nn.GRUCell(8, 16)
        rnn = nn.BiRNN(cell_fw, cell_bw)
        x = P.to_tensor(RNG.randn(2, 5, 8).astype(np.float32))
        out, _ = rnn(x)
        assert list(out.shape) == [2, 5, 32]

    def test_rnnt_loss_layer(self):
        crit = nn.RNNTLoss()
        logits = P.to_tensor(RNG.randn(1, 3, 2, 4).astype(np.float32))
        loss = crit(logits, P.to_tensor(np.array([[1]])),
                    P.to_tensor(np.array([3])), P.to_tensor(np.array([1])))
        assert np.isfinite(float(_v(loss)))


class TestLinalgTail:
    def test_cholesky_inverse(self):
        a = RNG.randn(4, 4).astype(np.float32)
        a = a @ a.T + 4 * np.eye(4, dtype=np.float32)
        l = np.linalg.cholesky(a)  # noqa: E741
        inv = _v(P.linalg.cholesky_inverse(P.to_tensor(l)))
        np.testing.assert_allclose(inv, np.linalg.inv(a), rtol=1e-3, atol=1e-4)

    def test_cond_and_norms(self):
        a = RNG.randn(4, 4).astype(np.float32) + 4 * np.eye(4, dtype=np.float32)
        np.testing.assert_allclose(float(_v(P.linalg.cond(P.to_tensor(a)))),
                                   np.linalg.cond(a), rtol=1e-3)
        np.testing.assert_allclose(float(_v(P.linalg.matrix_norm(P.to_tensor(a)))),
                                   np.linalg.norm(a), rtol=1e-5)
        v = RNG.randn(6).astype(np.float32)
        np.testing.assert_allclose(float(_v(P.linalg.vector_norm(P.to_tensor(v), 3.0))),
                                   np.linalg.norm(v, 3), rtol=1e-5)

    def test_matrix_exp(self):
        from scipy.linalg import expm

        a = RNG.randn(3, 3).astype(np.float32) * 0.3
        np.testing.assert_allclose(_v(P.linalg.matrix_exp(P.to_tensor(a))), expm(a),
                                   rtol=1e-3, atol=1e-4)

    def test_svd_lowrank(self):
        a = RNG.randn(12, 4).astype(np.float32) @ RNG.randn(4, 10).astype(np.float32)
        u, s, v = P.linalg.svd_lowrank(P.to_tensor(a), q=4)
        approx = _v(u) @ np.diag(_v(s)) @ _v(v).T
        np.testing.assert_allclose(approx, a, rtol=1e-2, atol=1e-2)

    def test_lu_unpack(self):
        import scipy.linalg as sla

        a = RNG.randn(4, 4).astype(np.float32)
        lu, piv = sla.lu_factor(a)
        Pm, L, U = P.linalg.lu_unpack(P.to_tensor(lu), P.to_tensor(piv + 1))
        np.testing.assert_allclose(_v(Pm) @ _v(L) @ _v(U), a, rtol=1e-3, atol=1e-4)


class TestOptimizerTail:
    @pytest.mark.parametrize("opt_cls,kw", [
        ("ASGD", {"learning_rate": 0.05, "batch_num": 2}),
        ("Rprop", {"learning_rate": 0.01}),
        ("NAdam", {"learning_rate": 0.05}),
        ("RAdam", {"learning_rate": 0.05}),
    ], ids=["asgd", "rprop", "nadam", "radam"])
    def test_quadratic_descent(self, opt_cls, kw):
        x = P.to_tensor(np.array([3.0, -2.0], np.float32))
        x.stop_gradient = False
        x.is_parameter = True
        opt = getattr(P.optimizer, opt_cls)(parameters=[x], **kw)
        first = None
        for _ in range(60):
            loss = P.sum(x * x)
            loss.backward()
            opt.step()
            opt.clear_grad()
            first = first if first is not None else float(_v(loss))
        assert float(_v(loss)) < first * 0.5, (opt_cls, first, float(_v(loss)))


class TestReviewRegressions:
    def test_nadam_radam_under_trainstep(self):
        """Step-dependent factors must be traced (not frozen at compile)."""
        for cls in ("NAdam", "RAdam"):
            net = nn.Linear(4, 1)
            opt = getattr(P.optimizer, cls)(learning_rate=0.05,
                                            parameters=net.parameters())
            step = P.jit.TrainStep(net, lambda m, x, y: P.mean((m(x) - y) ** 2), opt)
            x = P.to_tensor(RNG.randn(8, 4).astype(np.float32))
            y = P.to_tensor(RNG.randn(8, 1).astype(np.float32))
            losses = [float(_v(step(x, y))) for _ in range(25)]
            assert losses[-1] < losses[0] * 0.8, (cls, losses[0], losses[-1])

    def test_max_unpool_padding_output_shape(self):
        x = P.to_tensor(RNG.randn(1, 1, 4, 4).astype(np.float32))
        idx = P.to_tensor(np.arange(16, dtype=np.int32).reshape(1, 1, 4, 4) * 2 % 36)
        out = F.max_unpool2d(x, idx, kernel_size=2, stride=2, padding=1)
        assert list(out.shape) == [1, 1, 6, 6]

    def test_lu_unpack_batched(self):
        import scipy.linalg as sla

        a = RNG.randn(3, 4, 4).astype(np.float32)
        lus, pivs = zip(*(sla.lu_factor(ai) for ai in a))
        lu = np.stack(lus)
        piv = np.stack(pivs) + 1
        Pm, L, U = P.linalg.lu_unpack(P.to_tensor(lu), P.to_tensor(piv))
        rec = np.einsum("bij,bjk,bkl->bil", _v(Pm), _v(L), _v(U))
        np.testing.assert_allclose(rec, a, rtol=1e-3, atol=1e-4)

    def test_svd_lowrank_with_M(self):
        a = RNG.randn(10, 6).astype(np.float32)
        m = np.broadcast_to(a.mean(0, keepdims=True), a.shape).astype(np.float32)
        u, s, v = P.linalg.svd_lowrank(P.to_tensor(a), q=6, M=P.to_tensor(m))
        approx = _v(u) @ np.diag(_v(s)) @ _v(v).T
        np.testing.assert_allclose(approx, a - m, rtol=1e-2, atol=1e-2)

    def test_adaptive_log_prob_covers_all_classes(self):
        layer = nn.AdaptiveLogSoftmaxWithLoss(8, 20, cutoffs=[5, 10])
        x = P.to_tensor(RNG.randn(3, 8).astype(np.float32))
        lp = layer.log_prob(x)
        assert list(lp.shape) == [3, 20]

    def test_worker_info_inside_worker(self):
        from paddle_tpu.io import DataLoader

        dl = DataLoader(_InfoDS(), batch_size=2, num_workers=2)
        infos = []
        for (ids, nums) in dl:
            infos.extend(zip(_v(ids).tolist(), _v(nums).tolist()))
        assert all(n == 2 for _, n in infos)  # num_workers visible in workers
        # a fast worker can drain the whole queue; ids must be valid worker ids
        assert {i for i, _ in infos} <= {0, 1} and infos


class _InfoDS:
    def __len__(self):
        return 8

    def __getitem__(self, i):
        import paddle_tpu.io as io

        info = io.get_worker_info()
        return np.int64(info.id if info else -1), np.int64(info.num_workers if info else -1)
