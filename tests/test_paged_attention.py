"""Parity tests for the paged-KV serving attention
(block_multihead_attention) against a naive dense reference — mirrors the
reference's test matrix (test/legacy_test/test_block_multihead_attention.py:
EncDec, GQA, RoPE, PreCache, cache-KV quant) plus a mixed prefill+decode
batch, which is the continuous-batching serving case."""
import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu.incubate.nn.functional import block_multihead_attention
from paddle_tpu.ops.paged_attention import build_padding_metadata

pytestmark = pytest.mark.quick


def naive_attn(q, k, v, cache_k=None, cache_v=None, pre_k=None, pre_v=None,
               mask=None, causal=False):
    """Dense attention oracle: q [B,H,S,D], k/v [B,KV,S,D]; caches
    [B,KV,L,D] prepend along the key axis; fp32 softmax; GQA tiles KV
    heads."""
    B, H, S, D = q.shape
    KV = k.shape[1]
    rep = H // KV

    def expand(x):
        return np.repeat(x, rep, axis=1) if x.shape[1] != H else x

    keys = expand(k)
    vals = expand(v)
    offset = 0
    if cache_k is not None:
        keys = np.concatenate([expand(cache_k), keys], axis=2)
        vals = np.concatenate([expand(cache_v), vals], axis=2)
        offset = cache_k.shape[2]
    pre = 0
    if pre_k is not None:
        keys = np.concatenate([expand(pre_k), keys], axis=2)
        vals = np.concatenate([expand(pre_v), vals], axis=2)
        pre = pre_k.shape[2]
    logits = np.einsum("bhsd,bhld->bhsl", q.astype(np.float64),
                       keys.astype(np.float64)) / np.sqrt(D)
    if causal:
        L = keys.shape[2]
        qpos = offset + np.arange(S)
        kpos = np.arange(L) - pre
        viz = kpos[None, :] <= qpos[:, None]
        logits = np.where(viz[None, None], logits, -1e30)
    if mask is not None:
        logits = logits + mask.astype(np.float64)
    w = np.exp(logits - logits.max(-1, keepdims=True))
    w = w / w.sum(-1, keepdims=True)
    return np.einsum("bhsl,bhld->bhsd", w, vals.astype(np.float64))


def pack_qkv(q, k, v):
    """[B,H,S,D]x3 -> [sum(S), (H+2KV)D] packed tokens (all seqs full S)."""
    B, H, S, D = q.shape
    KV = k.shape[1]

    def flat(x, nh):
        return x.transpose(0, 2, 1, 3).reshape(B * S, nh * D)

    return np.concatenate([flat(q, H), flat(k, KV), flat(v, KV)], axis=1)


def make_blocks(B, blocks_per_seq):
    """Sequential free-list allocation like the reference test."""
    bt = np.zeros((B, blocks_per_seq), np.int32)
    nxt = 0
    for i in range(B):
        for j in range(blocks_per_seq):
            bt[i, j] = nxt
            nxt += 1
    return bt, nxt


def paged_to_dense(cache, bt, length):
    """[NB,KV,bs,D] + block table row-major -> [B,KV,length,D]."""
    NB, KV, bs, D = cache.shape
    B = bt.shape[0]
    out = np.zeros((B, KV, length, D), np.float32)
    for i in range(B):
        for j in range(length):
            out[i, :, j] = np.asarray(cache[bt[i, j // bs], :, j % bs],
                                      np.float32)
    return out


def run_blha(qkv, kc, vc, enc, dec, now, bt, block_size, **kw):
    _, _, cu, _ = build_padding_metadata(now)
    kc_t, vc_t = P.to_tensor(kc), P.to_tensor(vc)
    out = block_multihead_attention(
        P.to_tensor(qkv), kc_t, vc_t,
        P.to_tensor(np.asarray(enc, np.int32)),
        P.to_tensor(np.asarray(dec, np.int32)),
        P.to_tensor(np.asarray(now, np.int32)),
        None, None, P.to_tensor(cu), P.to_tensor(cu),
        P.to_tensor(bt), block_size=block_size, **kw)
    return (np.asarray(out[0].numpy()), np.asarray(out[2].numpy()),
            np.asarray(out[3].numpy()))


class TestEncDec:
    B, H, S, D, bs = 2, 4, 16, 32, 8

    def setup_method(self, _):
        self.rng = np.random.RandomState(7)
        self.blocks_per_seq = (self.S + 8 + self.bs - 1) // self.bs
        self.bt, self.nb = make_blocks(self.B, self.blocks_per_seq)

    def _rand(self, shape):
        return self.rng.uniform(-1, 1, shape).astype(np.float32)

    def test_prefill_then_decode_parity(self):
        B, H, S, D = self.B, self.H, self.S, self.D
        kc = np.zeros((self.nb, H, self.bs, D), np.float32)
        vc = np.zeros_like(kc)
        q, k, v = self._rand((B, H, S, D)), self._rand((B, H, S, D)), self._rand((B, H, S, D))
        out, kc, vc = run_blha(pack_qkv(q, k, v), kc, vc,
                               [S] * B, [0] * B, [S] * B, self.bt, self.bs)
        ref = naive_attn(q, k, v, causal=True)
        np.testing.assert_allclose(
            out, ref.transpose(0, 2, 1, 3).reshape(B * S, H * D),
            rtol=2e-4, atol=2e-4)
        # the paged cache now holds this step's K/V
        np.testing.assert_allclose(paged_to_dense(kc, self.bt, S),
                                   k, rtol=1e-5, atol=1e-5)

        # --- decode step: 1 token per sequence, random additive tgt_mask
        q1, k1, v1 = (self._rand((B, H, 1, D)) for _ in range(3))
        tgt = self._rand((B, H, 1, S + 1))
        out1, kc, vc = run_blha(pack_qkv(q1, k1, v1), kc, vc,
                                [0] * B, [S] * B, [1] * B, self.bt, self.bs,
                                tgt_mask=P.to_tensor(tgt))
        cache_k = paged_to_dense(kc, self.bt, S)
        cache_v = paged_to_dense(vc, self.bt, S)
        ref1 = naive_attn(q1, k1, v1, cache_k, cache_v, mask=tgt)
        np.testing.assert_allclose(
            out1, ref1.transpose(0, 2, 1, 3).reshape(B, H * D),
            rtol=2e-4, atol=2e-4)

    def test_gqa(self):
        B, H, S, D, KV = self.B, self.H, self.S, self.D, 2
        kc = np.zeros((self.nb, KV, self.bs, D), np.float32)
        vc = np.zeros_like(kc)
        q = self._rand((B, H, S, D))
        k, v = self._rand((B, KV, S, D)), self._rand((B, KV, S, D))
        out, kc2, vc2 = run_blha(pack_qkv(q, k, v), kc, vc,
                                 [S] * B, [0] * B, [S] * B, self.bt, self.bs)
        ref = naive_attn(q, k, v, causal=True)
        np.testing.assert_allclose(
            out, ref.transpose(0, 2, 1, 3).reshape(B * S, H * D),
            rtol=2e-4, atol=2e-4)
        # decode on the GQA cache
        q1 = self._rand((B, H, 1, D))
        k1, v1 = self._rand((B, KV, 1, D)), self._rand((B, KV, 1, D))
        out1, _, _ = run_blha(pack_qkv(q1, k1, v1), kc2, vc2,
                              [0] * B, [S] * B, [1] * B, self.bt, self.bs)
        ck = paged_to_dense(kc2, self.bt, S)[:, :KV]
        cv = paged_to_dense(vc2, self.bt, S)[:, :KV]
        ref1 = naive_attn(q1, k1, v1, ck, cv, causal=True)
        np.testing.assert_allclose(
            out1, ref1.transpose(0, 2, 1, 3).reshape(B, H * D),
            rtol=2e-4, atol=2e-4)

    def test_mixed_prefill_and_decode_one_call(self):
        """Sequence 0 decodes (ctx=S) while sequence 1 prefills — one call,
        outputs match the two phases run against the dense oracle."""
        B, H, S, D = self.B, self.H, self.S, self.D
        kc = np.zeros((self.nb, H, self.bs, D), np.float32)
        vc = np.zeros_like(kc)
        # pre-populate seq 0's context via a normal prefill of both
        q0, k0, v0 = (self._rand((B, H, S, D)) for _ in range(3))
        _, kc, vc = run_blha(pack_qkv(q0, k0, v0), kc, vc,
                             [S] * B, [0] * B, [S] * B, self.bt, self.bs)
        # now: seq0 1 decode token; seq1 re-prefills S2 fresh tokens
        S2 = 6
        qd, kd, vd = (self._rand((1, H, 1, D)) for _ in range(3))
        qp, kp, vp = (self._rand((1, H, S2, D)) for _ in range(3))
        tok0 = np.concatenate([
            qd.transpose(0, 2, 1, 3).reshape(1, H * D),
            kd.transpose(0, 2, 1, 3).reshape(1, H * D),
            vd.transpose(0, 2, 1, 3).reshape(1, H * D)], axis=1)
        tokp = np.concatenate([
            qp.transpose(0, 2, 1, 3).reshape(S2, H * D),
            kp.transpose(0, 2, 1, 3).reshape(S2, H * D),
            vp.transpose(0, 2, 1, 3).reshape(S2, H * D)], axis=1)
        qkv = np.concatenate([tok0, tokp], axis=0)  # [1+S2, 3HD]
        out, kc, vc = run_blha(qkv, kc, vc,
                               [0, S2], [S, 0], [1, S2], self.bt, self.bs)
        # seq 0: decode against its cached context
        ck = paged_to_dense(kc, self.bt, S)[0:1]
        cv = paged_to_dense(vc, self.bt, S)[0:1]
        ref0 = naive_attn(qd, kd, vd, ck, cv, causal=True)
        np.testing.assert_allclose(out[0], ref0.transpose(0, 2, 1, 3).reshape(H * D),
                                   rtol=2e-4, atol=2e-4)
        # seq 1: fresh causal prefill (its block rows were overwritten)
        ref1 = naive_attn(qp, kp, vp, causal=True)
        np.testing.assert_allclose(
            out[1:], ref1.transpose(0, 2, 1, 3).reshape(S2, H * D),
            rtol=2e-4, atol=2e-4)

    def test_rope_interleaved(self):
        """In-kernel rope, reference layout [2, B, Smax, 1, D/2] with
        interleaved (non-neox) rotation."""
        B, H, S, D = self.B, self.H, self.S, self.D
        kc = np.zeros((self.nb, H, self.bs, D), np.float32)
        vc = np.zeros_like(kc)
        q, k, v = (self._rand((B, H, S, D)) for _ in range(3))
        pos = np.arange(S + 8)
        inv = 10000.0 ** (-np.arange(0, D, 2) / D)
        freqs = np.einsum("i,j->ij", pos, inv)  # [Smax, D/2]
        rope = np.stack([np.cos(freqs), np.sin(freqs)])[:, None, :, None, :]
        out, _, _ = run_blha(pack_qkv(q, k, v), kc, vc,
                             [S] * B, [0] * B, [S] * B, self.bt, self.bs,
                             rope_emb=P.to_tensor(rope.astype(np.float32)))

        def rot(x):  # interleaved pairs at absolute position
            c = np.cos(freqs)[:S][None, None]
            s = np.sin(freqs)[:S][None, None]
            x1, x2 = x[..., 0::2], x[..., 1::2]
            o = np.stack([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
            return o.reshape(x.shape)

        qs = rot(q.transpose(0, 1, 2, 3))  # [B,H,S,D] rotate over S axis
        ks = rot(k)
        ref = naive_attn(qs, ks, v, causal=True)
        np.testing.assert_allclose(
            out, ref.transpose(0, 2, 1, 3).reshape(B * S, H * D),
            rtol=2e-4, atol=2e-4)

    def test_pre_cache(self):
        B, H, S, D = self.B, self.H, self.S, self.D
        P_len = 4
        kc = np.zeros((self.nb, H, self.bs, D), np.float32)
        vc = np.zeros_like(kc)
        q, k, v = (self._rand((B, H, S, D)) for _ in range(3))
        pk, pv = self._rand((B, H, P_len, D)), self._rand((B, H, P_len, D))
        out, _, _ = run_blha(pack_qkv(q, k, v), kc, vc,
                             [S] * B, [0] * B, [S] * B, self.bt, self.bs,
                             pre_key_cache=P.to_tensor(pk),
                             pre_value_cache=P.to_tensor(pv))
        ref = naive_attn(q, k, v, pre_k=pk, pre_v=pv, causal=True)
        np.testing.assert_allclose(
            out, ref.transpose(0, 2, 1, 3).reshape(B * S, H * D),
            rtol=2e-4, atol=2e-4)

    def test_qkv_bias_and_int32_dequant(self):
        B, H, S, D = self.B, self.H, 4, self.D
        kc = np.zeros((self.nb, H, self.bs, D), np.float32)
        vc = np.zeros_like(kc)
        q, k, v = (self._rand((B, H, S, D)) for _ in range(3))
        bias = self.rng.uniform(-0.5, 0.5, (3 * H * D,)).astype(np.float32)
        scale = np.full((3 * H * D,), 0.01, np.float32)
        qkv_f = pack_qkv(q, k, v)
        qkv_i = np.round(qkv_f / 0.01).astype(np.int32)
        out, _, _ = run_blha(qkv_i, kc, vc, [S] * B, [0] * B, [S] * B,
                             self.bt, self.bs,
                             qkv_out_scale=P.to_tensor(scale),
                             qkv_bias=P.to_tensor(bias),
                             compute_dtype="fp32")

        def unpack(x, o, nh):
            return x[:, o:o + nh * D].reshape(B, S, nh, D).transpose(0, 2, 1, 3)

        deq = qkv_i.astype(np.float32) * 0.01 + bias[None]
        ref = naive_attn(unpack(deq, 0, H), unpack(deq, H * D, H),
                         unpack(deq, 2 * H * D, H), causal=True)
        np.testing.assert_allclose(
            out, ref.transpose(0, 2, 1, 3).reshape(B * S, H * D),
            rtol=5e-3, atol=5e-3)


class TestCacheQuant:
    B, H, S, D, bs = 2, 4, 16, 32, 8

    def setup_method(self, _):
        self.rng = np.random.RandomState(3)
        self.blocks_per_seq = (self.S + 8 + self.bs - 1) // self.bs
        self.bt, self.nb = make_blocks(self.B, self.blocks_per_seq)

    def _run_quant(self, dynamic):
        B, H, S, D = self.B, self.H, self.S, self.D
        kc = np.zeros((self.nb, H, self.bs, D), np.uint8)
        vc = np.zeros_like(kc)
        q, k, v = (self.rng.uniform(-1, 1, (B, H, S, D)).astype(np.float32)
                   for _ in range(3))
        if dynamic:
            shape = (B, H)
            kq = P.to_tensor(np.zeros(shape, np.float32))
            vq = P.to_tensor(np.zeros(shape, np.float32))
            kd = P.to_tensor(np.zeros(shape, np.float32))
            vd = P.to_tensor(np.zeros(shape, np.float32))
        else:
            kmax = np.abs(k).max(axis=(0, 2, 3)) + 1e-6  # per head
            vmax = np.abs(v).max(axis=(0, 2, 3)) + 1e-6
            kq = P.to_tensor((127.0 / kmax).astype(np.float32))
            vq = P.to_tensor((127.0 / vmax).astype(np.float32))
            kd = P.to_tensor((kmax / 127.0).astype(np.float32))
            vd = P.to_tensor((vmax / 127.0).astype(np.float32))
        out, kc, vc = run_blha(
            pack_qkv(q, k, v), kc, vc, [S] * B, [0] * B, [S] * B,
            self.bt, self.bs, cache_k_quant_scales=kq,
            cache_v_quant_scales=vq, cache_k_dequant_scales=kd,
            cache_v_dequant_scales=vd, use_dynamic_cachekv_quant=dynamic)
        # prefill output itself is full precision
        ref = naive_attn(q, k, v, causal=True)
        np.testing.assert_allclose(
            out, ref.transpose(0, 2, 1, 3).reshape(B * S, H * D),
            rtol=2e-4, atol=2e-4)
        assert kc.dtype == np.uint8
        # decode reads the dequantized cache: compare against dequant oracle
        q1, k1, v1 = (self.rng.uniform(-1, 1, (B, H, 1, D)).astype(np.float32)
                      for _ in range(3))
        out1, _, _ = run_blha(
            pack_qkv(q1, k1, v1), kc, vc, [0] * B, [S] * B, [1] * B,
            self.bt, self.bs, cache_k_quant_scales=kq,
            cache_v_quant_scales=vq, cache_k_dequant_scales=kd,
            cache_v_dequant_scales=vd, use_dynamic_cachekv_quant=dynamic)
        kdv = np.asarray(kd.numpy())
        vdv = np.asarray(vd.numpy())
        if dynamic:
            kdq = (paged_to_dense(kc, self.bt, S) - 128.0) * kdv[:, :, None, None]
            vdq = (paged_to_dense(vc, self.bt, S) - 128.0) * vdv[:, :, None, None]
        else:
            kdq = (paged_to_dense(kc, self.bt, S) - 128.0) * kdv[None, :, None, None]
            vdq = (paged_to_dense(vc, self.bt, S) - 128.0) * vdv[None, :, None, None]
        ref1 = naive_attn(q1, k1, v1, kdq, vdq, causal=True)
        np.testing.assert_allclose(
            out1, ref1.transpose(0, 2, 1, 3).reshape(B, H * D),
            rtol=0.05, atol=0.05)
        # quantization error vs the fp cache stays small
        np.testing.assert_allclose(kdq, k, atol=2.5 / 127.0)

    def test_static_quant(self):
        self._run_quant(dynamic=False)

    def test_dynamic_quant(self):
        self._run_quant(dynamic=True)

    def test_dynamic_quant_writes_scales_inplace(self):
        B, H, S, D = self.B, self.H, self.S, self.D
        kc = np.zeros((self.nb, H, self.bs, D), np.uint8)
        vc = np.zeros_like(kc)
        q, k, v = (self.rng.uniform(-1, 1, (B, H, S, D)).astype(np.float32)
                   for _ in range(3))
        kq, vq, kd, vd = (P.to_tensor(np.zeros((B, H), np.float32))
                          for _ in range(4))
        run_blha(pack_qkv(q, k, v), kc, vc, [S] * B, [0] * B, [S] * B,
                 self.bt, self.bs, cache_k_quant_scales=kq,
                 cache_v_quant_scales=vq, cache_k_dequant_scales=kd,
                 cache_v_dequant_scales=vd, use_dynamic_cachekv_quant=True)
        expect = np.abs(k).max(axis=(2, 3)) / 127.0  # [B, H]
        np.testing.assert_allclose(np.asarray(kd.numpy()), expect, rtol=1e-4)
        assert (np.asarray(kq.numpy()) > 0).all()


class TestBlhaGetMaxLen:
    def test_max_lens(self):
        from paddle_tpu.incubate.nn.functional import blha_get_max_len

        enc = P.to_tensor(np.array([3, 9, 0], np.int32))
        dec = P.to_tensor(np.array([5, 0, 2], np.int32))
        me, md = blha_get_max_len(enc, dec, P.to_tensor(np.array([3])))
        assert int(np.asarray(me.numpy())[0]) == 9
        assert int(np.asarray(md.numpy())[0]) == 5


class TestTracedSeqLens:
    def test_traced_seq_lens_raises_clear_error(self):
        """ADVICE r5 low #3: the padded-query bucket is a HOST-side read of
        max(seq_lens_this_time); under jit tracing there is no concrete
        value, so the op must raise a clear error instead of crashing deep
        in numpy."""
        import jax
        import jax.numpy as jnp

        B, H, KV, D, bs = 2, 4, 4, 8, 8
        qkv = np.zeros((B, (H + 2 * KV) * D), np.float32)
        kc = np.zeros((4, KV, bs, D), np.float32)
        vc = np.zeros_like(kc)
        bt = np.zeros((B, 2), np.int32)
        cu = np.zeros((B + 1,), np.int32)
        zeros = np.zeros(B, np.int32)

        def f(lens):
            out = block_multihead_attention(
                P.to_tensor(qkv), P.to_tensor(kc), P.to_tensor(vc),
                P.to_tensor(zeros), P.to_tensor(zeros), lens,
                None, None, P.to_tensor(cu), P.to_tensor(cu),
                P.to_tensor(bt), block_size=bs)
            return out[0]._value

        with pytest.raises(ValueError, match="eagerly|ServingEngine"):
            jax.jit(f)(jnp.ones((B,), jnp.int32))
