"""Speculative decoding: n-gram drafting + in-graph multi-token verify
(ISSUE 19).

Contracts under test:

* the drafter is a PURE function of one request's own token history —
  deterministic across processes (no hash-seed dependence), never
  crossing a request boundary, empty on empty/short histories, and
  capped at k;
* spec-on is TOKEN-IDENTICAL to spec-off for greedy AND seeded streams
  at spec_k=1 and spec_k=8 — the verify redraws every position with the
  request's exact ``(seed, sample_index)`` key stream, so speculation
  only changes how many forwards it takes, never which tokens come out;
* the identity survives preempt/resume (``sample_offset`` carries the
  accepted-token count), replica failover, and journal recovery;
* multi-token extension of the r12 categorical-shift test: with
  ``capture_sample_probs`` on, redrawing each committed token from the
  exposed q(x) under ``fold_in(PRNGKey(seed), i)`` reproduces the
  engine's tokens exactly — including tokens committed in multi-token
  verify bursts;
* ``SamplingParams.spec=False`` opts a request out (identical tokens,
  zero verify launches), int8 KV-quant rows are excluded at the
  scheduler, and the ``spec`` knob survives the RPC wire dict;
* r16-remain regression (ISSUE 19 satellite): a deadline-frozen row's
  slot is freed at megastep harvest, so the queue head admits into the
  freed slot within the SAME ``step()``.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu.inference import (
    RequestJournal,
    RequestStatus,
    SamplingParams,
    ServingEngine,
    ServingFrontend,
)
from paddle_tpu.inference.serving import ngram_draft

pytestmark = pytest.mark.quick

ENGINE = dict(max_batch_size=2, max_seq_len=64, block_size=8,
              token_budget=16)
SAMPLED = dict(temperature=0.8, top_k=50, top_p=0.95, seed=13)
# near-greedy sampled stream: the argmax dominates every categorical
# draw, so the greedy repetition cycles (and therefore real multi-token
# accepts) survive sampling — used where a test needs accepted > 0 on a
# SAMPLED stream
NEAR_GREEDY = dict(temperature=0.001, seed=21)
# repetitive prompts: this prompt drives the tiny greedy model into a
# recurring token cycle (verified: the n-gram drafter's accepts > 0 on
# it), the drafting showcase; the alphabets are disjoint for the
# contamination check
PROMPT_A = [1, 2, 3, 1, 2, 3, 1, 2]
PROMPT_B = [9, 4, 9, 4, 9, 4, 9, 4]
N_LONG = 48   # long enough for greedy cycles to form and accept


@pytest.fixture(scope="module")
def model(serving_model):
    # shared session-scoped sub-tiny model (tests/conftest.py, ROADMAP
    # item 6); topology reset stays per-module for leaked fleet groups
    from paddle_tpu.distributed.topology import set_hybrid_communicate_group

    set_hybrid_communicate_group(None)
    return serving_model


def ref_greedy(model, prompt, n):
    from paddle_tpu.models.generation import generate

    ids = P.to_tensor(np.asarray(prompt, np.int32)[None, :])
    out = generate(model, ids, max_new_tokens=n, do_sample=False)
    return list(np.asarray(out.numpy()).reshape(-1))


def run_engine(model, prompt, n, sampling=None, **kw):
    eng = ServingEngine(model, megastep_k=4, **{**ENGINE, **kw})
    rid = eng.add_request(prompt, max_new_tokens=n, sampling=sampling)
    return eng.run()[rid], eng


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


# ---------------------------------------------------------------- drafter
class TestDrafter:
    def test_continuation_of_repeated_ngram(self):
        # history [5,6,7,5,6]: the longest repeated tail n-gram is
        # [5,6] at position 0 — the draft is its historical continuation
        assert ngram_draft([5, 6, 7, 5, 6], 3) == [7, 5, 6]
        # most-recent match wins when the pattern repeats
        assert ngram_draft([1, 2, 9, 1, 2, 8, 1, 2], 1) == [8]

    def test_edges_and_cap(self):
        assert ngram_draft([], 4) == []
        assert ngram_draft([5], 4) == []
        assert ngram_draft([5, 6], 4) == []       # no prior occurrence
        assert ngram_draft([5, 6, 7], 0) == []    # k=0
        assert ngram_draft([5, 5, 5, 5], -1) == []
        for k in range(1, 6):
            assert len(ngram_draft(PROMPT_A, k)) <= k

    def test_deterministic_across_processes(self):
        """Model-free and seed-free: a fresh interpreter with a
        different PYTHONHASHSEED computes the same drafts."""
        cases = [(PROMPT_A, 8), (PROMPT_B, 3), ([1, 2, 9, 1, 2], 4)]
        here = [ngram_draft(h, k) for h, k in cases]
        code = ("import json,sys\n"
                "from paddle_tpu.inference.serving import ngram_draft\n"
                f"cases = {cases!r}\n"
                "print(json.dumps([ngram_draft(h, k) for h, k in cases]))")
        env = {**os.environ, "PYTHONHASHSEED": "271828",
               "JAX_PLATFORMS": "cpu"}
        out = subprocess.run(
            [sys.executable, "-c", code], env=env, capture_output=True,
            text=True, cwd=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), timeout=120)
        assert out.returncode == 0, out.stderr
        assert json.loads(out.stdout.strip().splitlines()[-1]) == here

    def test_no_cross_request_contamination(self, model):
        """Engine-level: each row's draft is a function of ITS history
        only — two co-resident requests over disjoint alphabets draft
        strictly inside their own alphabets, and each equals the pure
        function of its own prompt."""
        eng = ServingEngine(model, megastep_k=4, spec_k=8, **ENGINE)
        ra = eng.add_request(PROMPT_A, max_new_tokens=N_LONG)
        rb = eng.add_request(PROMPT_B, max_new_tokens=N_LONG)
        eng._try_admit()
        reqs = list(eng._active.values())
        drafts = eng._draft(reqs)
        assert drafts[ra] == ngram_draft(PROMPT_A, 8)
        assert drafts[rb] == ngram_draft(PROMPT_B, 8)
        assert drafts[ra] and set(drafts[ra]) <= set(PROMPT_A)
        assert drafts[rb] and set(drafts[rb]) <= set(PROMPT_B)


# ----------------------------------------------------------- token parity
class TestSpecParity:
    @pytest.mark.parametrize("spec_k", [1, 8])
    def test_greedy_parity_and_fewer_forwards(self, model, spec_k):
        """spec-on ≡ spec-off greedy, and on the repetitive workload the
        drafter genuinely pays: accepted tokens > 0, so the verify
        launches number strictly fewer than the committed tokens."""
        want = ref_greedy(model, PROMPT_A, N_LONG)
        off, _ = run_engine(model, PROMPT_A, N_LONG)
        assert off == want
        on, eng = run_engine(model, PROMPT_A, N_LONG, spec_k=spec_k)
        assert on == want, f"spec_k={spec_k} diverged from spec-off"
        assert eng.spec_verify_forwards > 0, "spec never armed"
        assert eng.spec_accepted_tokens > 0, "nothing accepted"
        assert eng.spec_draft_tokens >= eng.spec_accepted_tokens
        summ = eng.state_summary()["spec"]
        assert summ == {"k": spec_k,
                        "accepted": eng.spec_accepted_tokens,
                        "drafted": eng.spec_draft_tokens,
                        "verify_forwards": eng.spec_verify_forwards}

    @pytest.mark.parametrize("spec_k", [1, 8])
    def test_seeded_parity(self, model, spec_k):
        off, _ = run_engine(model, PROMPT_A, N_LONG, sampling=SAMPLED)
        on, eng = run_engine(model, PROMPT_A, N_LONG, sampling=SAMPLED,
                             spec_k=spec_k)
        assert on == off, f"spec_k={spec_k} seeded stream diverged"
        assert eng.spec_verify_forwards > 0, "spec never armed"

    def test_two_rows_batched_parity(self, model):
        """Both slots speculate in one packed verify launch; each row's
        stream is identical to its solo spec-off run."""
        want_a = ref_greedy(model, PROMPT_A, N_LONG)
        want_b = ref_greedy(model, PROMPT_B, N_LONG)
        eng = ServingEngine(model, megastep_k=4, spec_k=8, **ENGINE)
        ra = eng.add_request(PROMPT_A, max_new_tokens=N_LONG)
        rb = eng.add_request(PROMPT_B, max_new_tokens=N_LONG)
        out = eng.run()
        assert out[ra] == want_a
        assert out[rb] == want_b
        assert eng.spec_accepted_tokens > 0

    def test_per_request_opt_out(self, model):
        """sampling.spec=False on a spec_k>0 engine: identical tokens,
        zero verify launches (the scheduler never arms)."""
        want = ref_greedy(model, PROMPT_A, N_LONG)
        sp = SamplingParams(spec=False)
        out, eng = run_engine(model, PROMPT_A, N_LONG, sampling=sp,
                              spec_k=8)
        assert out == want
        assert eng.spec_verify_forwards == 0
        assert eng.spec_draft_tokens == 0

    def test_spec_rides_the_wire_dict(self):
        w = SamplingParams(spec=False).to_wire()
        assert w["spec"] is False
        assert SamplingParams.coerce(w).spec is False
        assert SamplingParams.coerce(SamplingParams().to_wire()).spec

    def test_int8_rows_excluded(self, model):
        """cache_quant='int8' decodes through the megastep, never the
        verify (the scheduler excludes quantized caches from spec)."""
        out, eng = run_engine(model, PROMPT_A, 8, spec_k=2,
                              cache_quant="int8")
        assert out == ref_greedy(model, PROMPT_A, 8)
        assert eng.spec_verify_forwards == 0

    def test_spec_k_validation(self, model):
        with pytest.raises(ValueError):
            ServingEngine(model, spec_k=-1, **ENGINE)
        with pytest.raises(ValueError):
            ServingEngine(model, prefill_chunk_tokens=0, **ENGINE)
        with pytest.raises(ValueError):
            ServingEngine(model,
                          prefill_chunk_tokens=ENGINE["block_size"] + 1,
                          **ENGINE)


# ------------------------------------------------- categorical-shift, multi
class TestMultiTokenCategoricalShift:
    def test_redraw_from_qx_reproduces_spec_committed_tokens(self, model):
        """r12 property, multi-token extension: tokens committed in
        verify BURSTS still expose one q(x) per position, and redrawing
        position i from q_i under fold_in(PRNGKey(seed), i) reproduces
        the engine's token exactly — the acceptance rule collapses to
        redraw-compare precisely because of this shift-invariance."""
        import jax
        import jax.numpy as jnp

        eng = ServingEngine(model, megastep_k=4, spec_k=8,
                            capture_sample_probs=True, **ENGINE)
        rid = eng.add_request(PROMPT_A, max_new_tokens=N_LONG,
                              sampling=NEAR_GREEDY)
        toks = eng.run()[rid]
        assert eng.spec_accepted_tokens > 0, (
            "no multi-token commit — the property was only exercised "
            "one token at a time")
        qs = eng.pop_sample_probs()[rid]
        assert len(qs) == len(toks)
        for i, (q, t) in enumerate(zip(qs, toks)):
            key = jax.random.fold_in(
                jax.random.PRNGKey(NEAR_GREEDY["seed"]), i)
            redraw = int(jax.random.categorical(
                key, jnp.log(jnp.asarray(q))))
            assert redraw == t, f"sample index {i}"

    def test_capture_does_not_change_spec_tokens(self, model):
        on, _ = run_engine(model, PROMPT_A, N_LONG, sampling=SAMPLED,
                           spec_k=8, capture_sample_probs=True)
        off, _ = run_engine(model, PROMPT_A, N_LONG, sampling=SAMPLED,
                            spec_k=8)
        assert on == off


# ----------------------------------------------------- recovery identity
class TestSpecRecoveryIdentity:
    @pytest.mark.parametrize("spec_k", [1, 8])
    def test_preempt_resume_greedy_and_seeded(self, model, spec_k):
        """Evict mid-generation, resume with prompt+generated and
        sample_offset=len(generated): the accepted-token count rides the
        generated list, so the concatenated stream equals the
        unpreempted spec-off run — greedy AND seeded."""
        for sampling in (None, SAMPLED):
            full, _ = run_engine(model, PROMPT_A, N_LONG,
                                 sampling=sampling)
            eng = ServingEngine(model, megastep_k=4, spec_k=spec_k,
                                **ENGINE)
            rid = eng.add_request(PROMPT_A, max_new_tokens=N_LONG,
                                  sampling=sampling)
            eng.step()      # prefill + first token
            eng.step()      # one spec verify (or megastep) burst
            req = eng.evict(rid)
            assert 0 < len(req.generated) < N_LONG
            assert full[:len(req.generated)] == req.generated
            rid2 = eng.add_request(
                PROMPT_A + req.generated,
                max_new_tokens=N_LONG - len(req.generated),
                sampling=sampling, sample_offset=len(req.generated))
            out = eng.run()[rid2]
            assert req.generated + out == full, (
                f"spec_k={spec_k} sampled={sampling is not None}")

    @pytest.mark.parametrize("spec_k", [1, 8])
    def test_failover_to_spec_survivor(self, model, spec_k):
        """One of two spec-armed replicas dies mid-flight: every request
        completes on the survivor with the spec-off token stream."""
        def mk():
            return ServingEngine(model, megastep_k=4, spec_k=spec_k,
                                 **ENGINE)

        fe = ServingFrontend([mk(), mk()])
        prompts = [PROMPT_A, PROMPT_B, [5, 6, 7, 5, 6], [3, 9, 3, 9, 3]]
        rids = [fe.submit(p, max_new_tokens=24) for p in prompts]
        fe.step()
        doomed = fe.replicas[1]
        assert doomed.requests, "routing should have spread the load"

        def boom():
            raise RuntimeError("injected replica failure")

        doomed.engine.step = boom
        res = fe.run()
        for rid, p in zip(rids, prompts):
            assert res[rid].ok
            assert res[rid].tokens == ref_greedy(model, p, 24)
        assert fe.metrics.counter("replica_deaths_total") == 1

    def test_journal_recovery_token_identical(self, model, tmp_path):
        """Crash mid-flight, recover onto a FRESH spec engine: journal
        replay re-prefills prompt+generated with the carried
        sample_offset, so greedy and seeded streams complete exactly."""
        reqs = [(PROMPT_A, 24, {}),
                (PROMPT_B, 24, dict(**SAMPLED))]
        ref = ServingFrontend([ServingEngine(model, megastep_k=4,
                                             **ENGINE)])
        want = []
        rr = [ref.submit(p, max_new_tokens=m, **kw) for p, m, kw in reqs]
        rres = ref.run()
        want = [rres[r].tokens for r in rr]

        j = RequestJournal(str(tmp_path / "req.wal"), fsync=False)
        fe = ServingFrontend([ServingEngine(model, megastep_k=4,
                                            spec_k=8, **ENGINE)],
                             journal=j)
        rids = [fe.submit(p, max_new_tokens=m, **kw) for p, m, kw in reqs]
        fe.step()
        fe.step()       # mid-flight "crash" (abandon)
        fe2 = ServingFrontend.recover(
            j.path, [ServingEngine(model, megastep_k=4, spec_k=8,
                                   **ENGINE)])
        res = fe2.run()
        for i, rid in enumerate(rids):
            assert res[rid].status is RequestStatus.COMPLETED
            assert res[rid].tokens == want[i], f"request {i} diverged"


# ------------------------------------------- frozen-slot reuse (satellite)
class TestFrozenSlotReuse:
    def test_queue_head_admits_into_freed_slot_same_step(self, model):
        """r16 remain: both slots freeze in-graph on their deadline
        inside one megastep; harvest frees them, and the queued request
        is admitted within the SAME step() instead of parking behind
        frozen rows until the control plane's shed."""
        clock = FakeClock()
        eng = ServingEngine(model, megastep_k=4,
                            deadline_token_seconds=1.0, clock=clock,
                            **ENGINE)
        ra = eng.add_request([3, 17, 101], max_new_tokens=30,
                             deadline_s=100.0)
        rb = eng.add_request([42, 5, 9], max_new_tokens=30,
                             deadline_s=100.0)
        eng.step()                  # prefill both + first token at t=0
        clock.t = 97.0              # 3 iteration budgets remain
        rq = eng.add_request([7, 7, 9], max_new_tokens=4)
        assert rq not in eng._active        # no free slot: still queued
        eng.step()                  # scan freezes A and B in-graph
        # frozen rows released but still active (awaiting the typed
        # shed); the queue head claimed a freed slot THIS step
        assert eng._active[ra].slot < 0 and not eng._active[ra].done
        assert eng._active[rb].slot < 0 and not eng._active[rb].done
        assert rq in eng._active and eng._active[rq].slot >= 0, (
            "queue head did not admit into the freed slot")
        for _ in range(8):
            if rq in eng._finished:
                break
            eng.step()
        assert eng.pop_finished()[rq] == ref_greedy(model, [7, 7, 9], 4)
        # the control plane's shed path (evict) re-releases safely
        for r in (ra, rb):
            req = eng.evict(r)
            assert 0 < len(req.generated) < 30

    def test_frontend_shed_still_typed_after_early_free(self, model):
        """End to end: the early slot free does not change the control
        plane's observable contract — the frozen row still turns into
        DEADLINE_EXCEEDED with zero token overshoot."""
        clock = FakeClock()
        eng = ServingEngine(model, megastep_k=4,
                            deadline_token_seconds=1.0, clock=clock,
                            **ENGINE)
        fe = ServingFrontend([eng], clock=clock)
        rid = fe.submit([3, 17, 101], max_new_tokens=30, deadline_s=100.0)
        fe.step()
        clock.t = 97.0
        fe.step()
        assert fe.result(rid) is None
        clock.t = 101.0
        fe.step()
        res = fe.result(rid)
        assert res is not None
        assert res.status is RequestStatus.DEADLINE_EXCEEDED
        assert len(res.tokens) == 4
        assert res.tokens == ref_greedy(model, [3, 17, 101], 30)[:4]
