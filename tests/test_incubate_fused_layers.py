"""incubate.nn fused layer classes (reference: incubate/nn/layer/)."""
import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu.incubate.nn import (
    FusedBiasDropoutResidualLayerNorm,
    FusedDropoutAdd,
    FusedEcMoe,
    FusedFeedForward,
    FusedLinear,
    FusedMultiHeadAttention,
    FusedMultiTransformer,
    FusedTransformerEncoderLayer,
)


def test_fused_linear_and_dropout_add():
    P.seed(0)
    lin = FusedLinear(8, 4)
    x = P.randn([3, 8])
    out = lin(x)
    assert out.shape == [3, 4]
    da = FusedDropoutAdd(p=0.0)
    y = P.randn([3, 4])
    np.testing.assert_allclose(da(out, y).numpy(), out.numpy() + y.numpy(), rtol=1e-6)


def test_fused_bias_dropout_residual_ln():
    P.seed(0)
    m = FusedBiasDropoutResidualLayerNorm(8, dropout_rate=0.0)
    m.eval()
    x = P.randn([2, 5, 8])
    r = P.randn([2, 5, 8])
    out = m(x, r)
    assert out.shape == [2, 5, 8]
    # layer norm output: per-element mean ~0, var ~1 (fresh scale=1, bias=0)
    v = out.numpy().reshape(-1, 8)
    np.testing.assert_allclose(v.mean(-1), 0, atol=1e-5)


@pytest.mark.parametrize("normalize_before", [False, True])
def test_fused_mha_and_ffn_and_encoder(normalize_before):
    P.seed(0)
    x = P.randn([2, 6, 16])
    mha = FusedMultiHeadAttention(16, 4, dropout_rate=0.0, attn_dropout_rate=0.0,
                                  normalize_before=normalize_before)
    mha.eval()
    out = mha(x)
    assert out.shape == [2, 6, 16]
    ffn = FusedFeedForward(16, 32, dropout_rate=0.0,
                           normalize_before=normalize_before)
    ffn.eval()
    out2 = ffn(out)
    assert out2.shape == [2, 6, 16]
    enc = FusedTransformerEncoderLayer(16, 4, 32, dropout_rate=0.0,
                                       normalize_before=normalize_before)
    enc.eval()
    out3 = enc(x)
    assert out3.shape == [2, 6, 16]
    # trains end to end
    enc.train()
    opt = P.optimizer.AdamW(learning_rate=1e-3, parameters=enc.parameters())
    step = P.jit.TrainStep(enc, lambda m, xx, yy: P.nn.functional.mse_loss(m(xx), yy), opt)
    y = P.randn([2, 6, 16])
    l0 = float(step(x, y).numpy())
    for _ in range(3):
        l1 = float(step(x, y).numpy())
    assert l1 < l0


def test_fused_multi_transformer():
    P.seed(0)
    m = FusedMultiTransformer(16, 4, 32, num_layers=2)
    m.eval()
    out = m(P.randn([2, 5, 16]))
    assert out.shape == [2, 5, 16]


def test_fused_ec_moe():
    P.seed(0)
    moe = FusedEcMoe(16, 32, num_experts=4, act_type="gelu")
    x = P.randn([2, 8, 16])
    gate = P.randn([2, 8, 4])
    out = moe(x, gate)
    assert out.shape == [2, 8, 16]
    x.stop_gradient = False
    out = moe(x, gate)
    out.sum().backward()
    assert moe.bmm_weight0.grad is not None and x.grad is not None


class TestIncubateFunctionalTail:
    def test_fused_dot_product_attention(self):
        import jax.numpy as jnp

        from paddle_tpu.incubate.nn import functional as IF
        from paddle_tpu.nn.functional import scaled_dot_product_attention

        P.seed(0)
        q, k, v = P.randn([2, 8, 4, 16]), P.randn([2, 8, 4, 16]), P.randn([2, 8, 4, 16])
        out = IF.fused_dot_product_attention(q, k, v, is_causal=True)
        ref = scaled_dot_product_attention(q, k, v, is_causal=True)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5)

    def test_blha_get_max_len(self):
        from paddle_tpu.incubate.nn import functional as IF

        enc = P.to_tensor(np.array([3, 9, 5], np.int32))
        dec = P.to_tensor(np.array([1, 2, 7], np.int32))
        me, md = IF.blha_get_max_len(enc, dec, P.to_tensor(np.array([3])))
        assert int(me.numpy()) == 9 and int(md.numpy()) == 7

    def test_masked_multihead_attention_decode_steps(self):
        """Two decode steps through the [2,B,H,S,D] cache match a dense
        attention over the accumulated keys."""
        from paddle_tpu.incubate.nn import functional as IF

        rng = np.random.RandomState(0)
        B, H, S, D = 2, 2, 6, 8
        cache = P.to_tensor(np.zeros((2, B, H, S, D), np.float32))
        ks, vs, qs = [], [], []
        for step in range(2):
            x = rng.randn(B, 3 * H * D).astype(np.float32)
            qkv = x.reshape(B, 3, H, D)
            qs.append(qkv[:, 0]); ks.append(qkv[:, 1]); vs.append(qkv[:, 2])
            seq_lens = P.to_tensor(np.full((B, 1), step, np.int32))
            out, cache = IF.masked_multihead_attention(
                P.to_tensor(x), cache_kv=cache, sequence_lengths=seq_lens)
        # dense reference at the second step
        K = np.stack(ks, axis=2)  # [B,H,t,D]
        V = np.stack(vs, axis=2)
        q = qs[-1]
        logits = np.einsum("bhd,bhtd->bht", q, K) / np.sqrt(D)
        p = np.exp(logits - logits.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        ref = np.einsum("bht,bhtd->bhd", p, V).reshape(B, H * D)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)

    def test_fused_gate_attention(self):
        from paddle_tpu.incubate.nn import functional as IF

        rng = np.random.RandomState(1)
        B, M, S, Dq, Hh, D = 1, 2, 4, 8, 2, 4
        query = P.to_tensor(rng.randn(B, M, S, Dq).astype(np.float32))
        qkvw = P.to_tensor(rng.randn(3, Hh, D, Dq).astype(np.float32))
        gw = P.to_tensor(rng.randn(Dq, Hh, D).astype(np.float32))
        gb = P.to_tensor(np.zeros((Hh, D), np.float32))
        ow = P.to_tensor(rng.randn(Hh, D, Dq).astype(np.float32))
        ob = P.to_tensor(np.zeros((Dq,), np.float32))
        out = IF.fused_gate_attention(query, qkv_weight=qkvw,
                                      gate_linear_weight=gw, gate_linear_bias=gb,
                                      out_linear_weight=ow, out_linear_bias=ob)
        assert out.shape == [B, M, S, Dq]
        assert np.isfinite(out.numpy()).all()
        # no gating path
        out2 = IF.fused_gate_attention(query, qkv_weight=qkvw, has_gating=False,
                                       out_linear_weight=ow, out_linear_bias=ob)
        assert out2.shape == [B, M, S, Dq]

    def test_block_mha_is_real(self):
        # r5: block_multihead_attention is implemented (paged-KV serving
        # attention); full behavior coverage lives in test_paged_attention.py
        from paddle_tpu.incubate.nn import functional as IF

        assert callable(IF.block_multihead_attention)

    def test_mmha_timestep_from_mask_and_guards(self):
        import pytest as _pt

        from paddle_tpu.incubate.nn import functional as IF

        rng = np.random.RandomState(2)
        B, H, S, D = 1, 2, 8, 4
        cache = P.to_tensor(np.zeros((2, B, H, S, D), np.float32))
        x0 = P.to_tensor(rng.randn(B, 3 * H * D).astype(np.float32))
        # step 0 via mask of length 1, step 1 via mask of length 2
        m0 = P.to_tensor(np.zeros((B, 1, 1, 1), np.float32))
        out0, cache = IF.masked_multihead_attention(x0, cache_kv=cache, src_mask=m0)
        x1 = P.to_tensor(rng.randn(B, 3 * H * D).astype(np.float32))
        m1 = P.to_tensor(np.zeros((B, 1, 1, 2), np.float32))
        out1, cache = IF.masked_multihead_attention(x1, cache_kv=cache, src_mask=m1)
        # both cache rows written (non-zero)
        c = np.asarray(cache._value)
        assert np.abs(c[0, :, :, 0]).sum() > 0 and np.abs(c[0, :, :, 1]).sum() > 0
        assert np.abs(c[0, :, :, 2]).sum() == 0
        with _pt.raises(ValueError, match="sequence_lengths"):
            IF.masked_multihead_attention(x0, cache_kv=cache)
        with _pt.raises(NotImplementedError, match="beam"):
            IF.masked_multihead_attention(x0, cache_kv=cache, src_mask=m1,
                                          beam_cache_offset=m1)

    def test_fdpa_causal_mask_assertion(self):
        import pytest as _pt

        from paddle_tpu.incubate.nn import functional as IF

        q = P.randn([1, 4, 2, 8])
        with _pt.raises(AssertionError, match="attn_mask"):
            IF.fused_dot_product_attention(q, q, q, attn_mask=q, is_causal=True)
