"""incubate.nn fused layer classes (reference: incubate/nn/layer/)."""
import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu.incubate.nn import (
    FusedBiasDropoutResidualLayerNorm,
    FusedDropoutAdd,
    FusedEcMoe,
    FusedFeedForward,
    FusedLinear,
    FusedMultiHeadAttention,
    FusedMultiTransformer,
    FusedTransformerEncoderLayer,
)


def test_fused_linear_and_dropout_add():
    P.seed(0)
    lin = FusedLinear(8, 4)
    x = P.randn([3, 8])
    out = lin(x)
    assert out.shape == [3, 4]
    da = FusedDropoutAdd(p=0.0)
    y = P.randn([3, 4])
    np.testing.assert_allclose(da(out, y).numpy(), out.numpy() + y.numpy(), rtol=1e-6)


def test_fused_bias_dropout_residual_ln():
    P.seed(0)
    m = FusedBiasDropoutResidualLayerNorm(8, dropout_rate=0.0)
    m.eval()
    x = P.randn([2, 5, 8])
    r = P.randn([2, 5, 8])
    out = m(x, r)
    assert out.shape == [2, 5, 8]
    # layer norm output: per-element mean ~0, var ~1 (fresh scale=1, bias=0)
    v = out.numpy().reshape(-1, 8)
    np.testing.assert_allclose(v.mean(-1), 0, atol=1e-5)


@pytest.mark.parametrize("normalize_before", [False, True])
def test_fused_mha_and_ffn_and_encoder(normalize_before):
    P.seed(0)
    x = P.randn([2, 6, 16])
    mha = FusedMultiHeadAttention(16, 4, dropout_rate=0.0, attn_dropout_rate=0.0,
                                  normalize_before=normalize_before)
    mha.eval()
    out = mha(x)
    assert out.shape == [2, 6, 16]
    ffn = FusedFeedForward(16, 32, dropout_rate=0.0,
                           normalize_before=normalize_before)
    ffn.eval()
    out2 = ffn(out)
    assert out2.shape == [2, 6, 16]
    enc = FusedTransformerEncoderLayer(16, 4, 32, dropout_rate=0.0,
                                       normalize_before=normalize_before)
    enc.eval()
    out3 = enc(x)
    assert out3.shape == [2, 6, 16]
    # trains end to end
    enc.train()
    opt = P.optimizer.AdamW(learning_rate=1e-3, parameters=enc.parameters())
    step = P.jit.TrainStep(enc, lambda m, xx, yy: P.nn.functional.mse_loss(m(xx), yy), opt)
    y = P.randn([2, 6, 16])
    l0 = float(step(x, y).numpy())
    for _ in range(3):
        l1 = float(step(x, y).numpy())
    assert l1 < l0


def test_fused_multi_transformer():
    P.seed(0)
    m = FusedMultiTransformer(16, 4, 32, num_layers=2)
    m.eval()
    out = m(P.randn([2, 5, 16]))
    assert out.shape == [2, 5, 16]


def test_fused_ec_moe():
    P.seed(0)
    moe = FusedEcMoe(16, 32, num_experts=4, act_type="gelu")
    x = P.randn([2, 8, 16])
    gate = P.randn([2, 8, 4])
    out = moe(x, gate)
    assert out.shape == [2, 8, 16]
    x.stop_gradient = False
    out = moe(x, gate)
    out.sum().backward()
    assert moe.bmm_weight0.grad is not None and x.grad is not None
