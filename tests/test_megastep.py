"""Megastep decode + in-graph sampling + token streaming (ISSUE 9).

Contracts under test:

* K>1 megastep decode is token-identical to K=1 per-token stepping and
  to the engine-independent greedy reference (``models.generate``) —
  with the prefix cache on AND off, and across recompute preemption
  (evict at a megastep boundary, resume with prompt+generated);
* ``temperature=0`` sampling is the argmax path exactly (same tokens as
  the greedy engine), and seeded sampling is deterministic: same seed →
  same tokens across K values, across an engine rebuild (the worker-
  restart shape), and across a preempt/resume with ``sample_offset``;
* streaming surfaces every token exactly once, in order, both through
  ``on_token`` callbacks and the ``stream()`` iterator;
* deadline sheds fire at megastep boundaries with the overshoot bounded
  by the engine's K (the documented small-fix semantics);
* logprobs align 1:1 with tokens and survive the result plumbing.
"""
import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu.inference import (
    Priority,
    RequestStatus,
    SamplingParams,
    ServingEngine,
    ServingFrontend,
)

pytestmark = pytest.mark.quick

ENGINE = dict(max_batch_size=2, max_seq_len=64, block_size=8,
              token_budget=16)
SAMPLED = dict(temperature=0.8, top_k=50, top_p=0.95, seed=13)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture(scope="module")
def model(serving_model):
    # shared session-scoped sub-tiny model (tests/conftest.py, ROADMAP
    # item 6); topology reset stays per-module for leaked fleet groups
    from paddle_tpu.distributed.topology import set_hybrid_communicate_group

    set_hybrid_communicate_group(None)
    return serving_model


def ref_greedy(model, prompt, n):
    from paddle_tpu.models.generation import generate

    ids = P.to_tensor(np.asarray(prompt, np.int32)[None, :])
    out = generate(model, ids, max_new_tokens=n, do_sample=False)
    return list(np.asarray(out.numpy()).reshape(-1))


def run_engine(model, prompt, n, k, sampling=None, **kw):
    eng = ServingEngine(model, megastep_k=k, **{**ENGINE, **kw})
    rid = eng.add_request(prompt, max_new_tokens=n, sampling=sampling)
    return eng.run()[rid], eng


class TestTokenIdentity:
    def test_k_gt_1_identical_to_k1_and_reference(self, model):
        """The headline contract: megastep partitioning of decode never
        changes greedy output — K=1, K=2, K=8 and the pre-megastep
        per-step reference all agree."""
        prompt = [3, 17, 101, 7, 250]
        ref = ref_greedy(model, prompt, 12)
        for k in (1, 2, 8):
            out, eng = run_engine(model, prompt, 12, k)
            assert out == ref, f"megastep_k={k} diverged"
            if k > 1:
                assert eng.megasteps > 0          # the scan path actually ran
                assert eng.megastep_tokens > 0

    def test_identical_with_prefix_cache_on_and_off(self, model):
        """Cache-on and cache-off megastep runs are token-identical (the
        shared-prefix second request prefill-skips into a megastep)."""
        shared = list(range(30, 46))              # 2 full blocks
        prompts = [shared + [7, 9], shared + [5]]
        outs = {}
        for cache in (False, "auto"):
            eng = ServingEngine(model, prefix_cache=cache, megastep_k=8,
                                **ENGINE)
            r0 = eng.add_request(prompts[0], max_new_tokens=8)
            first = eng.run()[r0]
            r1 = eng.add_request(prompts[1], max_new_tokens=8)
            outs[cache] = (first, eng.run()[r1])
            if cache == "auto":
                assert eng.prefix_hit_blocks > 0  # the cache really engaged
        assert outs[False] == outs["auto"]

    def test_preempt_resume_across_megastep_boundary(self, model):
        """Evict at a megastep boundary mid-generation, resume with
        prompt+generated: the concatenated stream equals the unpreempted
        run (greedy-deterministic contract carried through megastep)."""
        prompt = [3, 17, 101]
        full = ref_greedy(model, prompt, 12)
        eng = ServingEngine(model, megastep_k=4, **ENGINE)
        rid = eng.add_request(prompt, max_new_tokens=12)
        eng.step()       # prefill + first token
        eng.step()       # one K=4 megastep -> 5 tokens
        req = eng.evict(rid)
        assert 0 < len(req.generated) < 12
        rid2 = eng.add_request(prompt + req.generated,
                               max_new_tokens=12 - len(req.generated))
        out = eng.run()[rid2]
        assert req.generated + out == full


class TestSamplingDeterminism:
    def test_temperature_zero_is_argmax(self, model):
        """temperature=0 sampling takes the exact greedy path."""
        prompt = [3, 17, 101, 7]
        ref = ref_greedy(model, prompt, 10)
        out, _ = run_engine(model, prompt, 10, 8,
                            sampling={"temperature": 0.0, "seed": 99})
        assert out == ref

    def test_same_seed_same_tokens_across_k(self, model):
        """The key depends only on (seed, sample index): K=1 and K=8
        produce the same sampled stream; a different seed diverges."""
        prompt = [3, 17, 101, 7]
        out1, _ = run_engine(model, prompt, 10, 1, sampling=SAMPLED)
        out8, _ = run_engine(model, prompt, 10, 8, sampling=SAMPLED)
        assert out1 == out8
        other, _ = run_engine(model, prompt, 10, 8,
                              sampling={**SAMPLED, "seed": 14})
        assert other != out8

    def test_replay_across_engine_rebuild(self, model):
        """The worker-restart shape: a fresh engine (rebuilt caches and
        programs, same seeded model) replays the same sampled stream."""
        prompt = [42, 5, 7]
        first, eng = run_engine(model, prompt, 8, 8, sampling=SAMPLED)
        del eng
        again, _ = run_engine(model, prompt, 8, 8, sampling=SAMPLED)
        assert first == again

    def test_resume_continues_key_stream_via_sample_offset(self, model):
        """A preempted sampled request resumed with ``sample_offset``
        continues the seeded stream exactly where it stopped."""
        prompt = [3, 17, 101]
        full, _ = run_engine(model, prompt, 12, 8, sampling=SAMPLED)
        eng = ServingEngine(model, megastep_k=4, **ENGINE)
        rid = eng.add_request(prompt, max_new_tokens=12, sampling=SAMPLED)
        eng.step()
        eng.step()
        req = eng.evict(rid)
        assert 0 < len(req.generated) < 12
        assert full[:len(req.generated)] == req.generated
        rid2 = eng.add_request(prompt + req.generated,
                               max_new_tokens=12 - len(req.generated),
                               sampling=SAMPLED,
                               sample_offset=len(req.generated))
        out = eng.run()[rid2]
        assert req.generated + out == full

    def test_frontend_preemption_preserves_sampled_stream(self, model):
        """End to end through the control plane: a LOW sampled request
        preempted for a HIGH one resumes (the frontend passes
        sample_offset) and finishes with the unpreempted stream."""
        plo = [3, 17, 101]
        want, _ = run_engine(model, plo, 8, 8, sampling=SAMPLED,
                             max_seq_len=32, num_blocks=4)
        eng = ServingEngine(model, megastep_k=8, **{**ENGINE,
                                                    "max_seq_len": 32,
                                                    "num_blocks": 4})
        fe = ServingFrontend([eng])
        rlo = fe.submit(plo, max_new_tokens=8, priority=Priority.LOW,
                        **SAMPLED)
        fe.step()                                # prefill + first token
        rhi = fe.submit(list(range(40, 50)), max_new_tokens=8,
                        priority=Priority.HIGH)
        res = fe.run()
        assert res[rhi].ok
        assert res[rlo].ok and res[rlo].preemptions >= 1
        assert res[rlo].tokens == want

    def test_sampling_validation(self, model):
        with pytest.raises(ValueError, match="temperature"):
            SamplingParams(temperature=-0.1)
        with pytest.raises(ValueError, match="top_p"):
            SamplingParams(top_p=0.0)
        with pytest.raises(ValueError, match="top_k"):
            SamplingParams(top_k=-1)


class TestSampleProbs:
    """capture_sample_probs=True (ISSUE 11 satellite): the engine
    exposes the renormalized POST-top-k/top-p distribution each token
    was drawn from — the q(x) a speculative-decode verifier scores draft
    tokens against — harvested like pop_token_logprobs()."""

    def test_probs_align_and_respect_filters(self, model):
        eng = ServingEngine(model, megastep_k=4,
                            capture_sample_probs=True, **ENGINE)
        rs = eng.add_request([3, 17, 101, 7], max_new_tokens=6,
                             sampling={"temperature": 0.8, "top_k": 8,
                                       "top_p": 0.9, "seed": 13})
        rg = eng.add_request([42, 5], max_new_tokens=6)     # greedy
        toks = eng.run()
        probs = eng.pop_sample_probs()
        assert set(probs) == {rs, rg}
        for rid in (rs, rg):
            assert len(probs[rid]) == len(toks[rid])   # 1:1 with tokens
            for q, t in zip(probs[rid], toks[rid]):
                assert float(q.sum()) == pytest.approx(1.0, abs=1e-4)
                assert q[t] > 0          # drawn token is inside support
        for q in probs[rs]:
            assert int((q > 0).sum()) <= 8        # top-k support bound
        for q, t in zip(probs[rg], toks[rg]):
            assert q[t] == 1.0 and int((q > 0).sum()) == 1   # one-hot
        assert eng.pop_sample_probs() == {}       # drained

    def test_capture_does_not_change_tokens(self, model):
        """Bit-identical draws with the capture on and off, single-step
        (K=1) and megastep (K=8) paths both."""
        prompt = [3, 17, 101, 7]
        for k in (1, 8):
            off, _ = run_engine(model, prompt, 8, k, sampling=SAMPLED)
            on, _ = run_engine(model, prompt, 8, k, sampling=SAMPLED,
                               capture_sample_probs=True)
            assert on == off, f"K={k}"
            goff, _ = run_engine(model, prompt, 8, k)
            gon, _ = run_engine(model, prompt, 8, k,
                                capture_sample_probs=True)
            assert gon == goff, f"K={k} greedy"

    def test_probs_are_the_sampled_distribution(self, model):
        """The spec-decode verification property: redrawing under the
        request's own (seed, sample-index) key from the EXPOSED
        distribution reproduces the engine's token exactly (categorical
        is shift-invariant, so log q and the filtered logits draw the
        same sample)."""
        import jax
        import jax.numpy as jnp

        sp = {"temperature": 0.8, "top_k": 16, "top_p": 0.95, "seed": 21}
        eng = ServingEngine(model, megastep_k=4,
                            capture_sample_probs=True, **ENGINE)
        rid = eng.add_request([9, 2, 77], max_new_tokens=6, sampling=sp)
        toks = eng.run()[rid]
        qs = eng.pop_sample_probs()[rid]
        for i, (q, t) in enumerate(zip(qs, toks)):
            key = jax.random.fold_in(jax.random.PRNGKey(sp["seed"]), i)
            redraw = int(jax.random.categorical(
                key, jnp.log(jnp.asarray(q))))
            assert redraw == t, f"sample index {i}"


class TestStreaming:
    def test_on_token_callback_order_and_completeness(self, model):
        fe = ServingFrontend([ServingEngine(model, **ENGINE)])
        seen = {}
        rids = [fe.submit([3 + i, 17, 101], max_new_tokens=10,
                          on_token=lambda rid, t: seen.setdefault(
                              rid, []).append(t))
                for i in range(3)]
        res = fe.run()
        for rid in rids:
            assert res[rid].ok
            assert seen[rid] == res[rid].tokens   # every token, in order

    def test_stream_iterator(self, model):
        fe = ServingFrontend([ServingEngine(model, **ENGINE)])
        rid = fe.submit([3, 17, 101], max_new_tokens=10)
        toks = list(fe.stream(rid))
        assert toks == fe.result(rid).tokens
        assert fe.result(rid).ok
        with pytest.raises(KeyError):
            next(fe.stream(999))

    def test_raising_callback_disables_stream_not_replica(self, model):
        """A buggy on_token callback must not kill the replica or the
        request — the callback is dropped, the request completes."""
        def boom(rid, tok):
            raise RuntimeError("consumer bug")

        fe = ServingFrontend([ServingEngine(model, **ENGINE)])
        rid = fe.submit([3, 17, 101], max_new_tokens=8, on_token=boom)
        res = fe.run()
        assert res[rid].ok
        assert res[rid].tokens == ref_greedy(model, [3, 17, 101], 8)
        assert fe.metrics.counter("stream_callback_errors_total") == 1
        assert fe.metrics.counter("replica_deaths_total") == 0


class TestMegastepBoundaries:
    def test_deadline_overshoot_bounded_by_k(self, model):
        """The small-fix contract: shed/cancel fire at megastep
        boundaries, so a request past deadline carries at most K extra
        tokens from the megastep that straddled it — never unbounded."""
        clock = FakeClock()
        eng = ServingEngine(model, megastep_k=4, **ENGINE)
        fe = ServingFrontend([eng], clock=clock)
        rid = fe.submit([3, 17, 101], max_new_tokens=30, deadline_s=5.0)
        fe.step()                     # prefill + first token
        clock.advance(10.0)           # deadline passes between boundaries
        fe.step()                     # boundary: shed fires HERE
        res = fe.result(rid)
        assert res is not None
        assert res.status is RequestStatus.DEADLINE_EXCEEDED
        # 1 pre-deadline token; the straddling megastep can add at most K
        assert len(res.tokens) <= 1 + eng.megastep_k
        assert res.tokens == ref_greedy(model, [3, 17, 101],
                                        30)[:len(res.tokens)]

    def test_logprobs_align_with_tokens(self, model):
        fe = ServingFrontend([ServingEngine(model, **ENGINE)])
        r1 = fe.submit([3, 17, 101], max_new_tokens=9, logprobs=True)
        r2 = fe.submit([42, 5], max_new_tokens=6, logprobs=True,
                       **SAMPLED)
        res = fe.run()
        for rid in (r1, r2):
            lps = res[rid].logprobs
            assert lps is not None and len(lps) == len(res[rid].tokens)
            assert all(lp <= 0.0 for lp in lps)   # log-probabilities
        # greedy default requests don't pay for logprob plumbing
        r3 = fe.submit([9, 9], max_new_tokens=4)
        assert fe.run()[r3].logprobs is None

    def test_megastep_counters_and_state_summary(self, model):
        eng = ServingEngine(model, megastep_k=8, **ENGINE)
        fe = ServingFrontend([eng])
        rid = fe.submit([3, 17, 101], max_new_tokens=10)
        res = fe.run()
        assert res[rid].ok
        ms = eng.state_summary()["megastep"]
        assert ms["k"] == 8
        assert ms["megasteps"] == eng.megasteps > 0
        assert ms["tokens"] == eng.megastep_tokens > 0
        assert fe.metrics.counter("megasteps_total") == eng.megasteps
        assert (fe.metrics.counter("megastep_tokens_total")
                == eng.megastep_tokens)

    def test_megastep_k1_never_scans(self, model):
        out_ref = ref_greedy(model, [3, 17, 101], 8)
        eng = ServingEngine(model, megastep_k=1, **ENGINE)
        rid = eng.add_request([3, 17, 101], max_new_tokens=8)
        assert eng.run()[rid] == out_ref
        assert eng.megasteps == 0 and eng._mega_fn is None

    def test_megastep_k_validation(self, model):
        with pytest.raises(ValueError, match="megastep_k"):
            ServingEngine(model, megastep_k=0, **ENGINE)
