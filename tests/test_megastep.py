"""Megastep decode + in-graph sampling + token streaming (ISSUE 9),
mixed-phase chunked prefill + in-graph deadlines + int8 scan carry
(ISSUE 16).

Contracts under test:

* K>1 megastep decode is token-identical to K=1 per-token stepping and
  to the engine-independent greedy reference (``models.generate``) —
  with the prefix cache on AND off, and across recompute preemption
  (evict at a megastep boundary, resume with prompt+generated);
* MIXED-PHASE (ISSUE 16): under staggered open-loop admission the scan
  packs one prompt chunk per prefilling row alongside the decode rows —
  token-identical to per-token stepping (greedy AND seeded), with the
  prefix cache entering prefill mid-chunk, across a preempt/resume that
  straddles a chunk boundary, and with ``prefill_chunk`` span events
  attributing TTFT chunk by chunk;
* ``temperature=0`` sampling is the argmax path exactly (same tokens as
  the greedy engine), and seeded sampling is deterministic: same seed →
  same tokens across K values, across an engine rebuild (the worker-
  restart shape), and across a preempt/resume with ``sample_offset``;
* streaming surfaces every token exactly once, in order, both through
  ``on_token`` callbacks and the ``stream()`` iterator;
* deadline budgets ride the scan carry as data (ISSUE 16): a row
  freezes in-graph AT its deadline — zero token overshoot when the
  engine has a per-iteration time estimate, K-bounded before the first
  measurement (the superseded ISSUE 9 contract, kept as the fallback);
* ``cache_quant='int8'`` decodes through the scan (scales in the
  carry) with token parity vs the per-token int8 path;
* logprobs align 1:1 with tokens and survive the result plumbing.
"""
import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu.inference import (
    FlightRecorder,
    Priority,
    RequestStatus,
    SamplingParams,
    ServingEngine,
    ServingFrontend,
    TraceContext,
    Tracer,
)
from paddle_tpu.inference.tracing import tree_complete

pytestmark = pytest.mark.quick

ENGINE = dict(max_batch_size=2, max_seq_len=64, block_size=8,
              token_budget=16)
SAMPLED = dict(temperature=0.8, top_k=50, top_p=0.95, seed=13)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture(scope="module")
def model(serving_model):
    # shared session-scoped sub-tiny model (tests/conftest.py, ROADMAP
    # item 6); topology reset stays per-module for leaked fleet groups
    from paddle_tpu.distributed.topology import set_hybrid_communicate_group

    set_hybrid_communicate_group(None)
    return serving_model


def ref_greedy(model, prompt, n):
    from paddle_tpu.models.generation import generate

    ids = P.to_tensor(np.asarray(prompt, np.int32)[None, :])
    out = generate(model, ids, max_new_tokens=n, do_sample=False)
    return list(np.asarray(out.numpy()).reshape(-1))


def run_engine(model, prompt, n, k, sampling=None, **kw):
    eng = ServingEngine(model, megastep_k=k, **{**ENGINE, **kw})
    rid = eng.add_request(prompt, max_new_tokens=n, sampling=sampling)
    return eng.run()[rid], eng


class TestTokenIdentity:
    def test_k_gt_1_identical_to_k1_and_reference(self, model):
        """The headline contract: megastep partitioning of decode never
        changes greedy output — K=1, K=2, K=8 and the pre-megastep
        per-step reference all agree."""
        prompt = [3, 17, 101, 7, 250]
        ref = ref_greedy(model, prompt, 12)
        for k in (1, 2, 8):
            out, eng = run_engine(model, prompt, 12, k)
            assert out == ref, f"megastep_k={k} diverged"
            if k > 1:
                assert eng.megasteps > 0          # the scan path actually ran
                assert eng.megastep_tokens > 0

    def test_identical_with_prefix_cache_on_and_off(self, model):
        """Cache-on and cache-off megastep runs are token-identical (the
        shared-prefix second request prefill-skips into a megastep)."""
        shared = list(range(30, 46))              # 2 full blocks
        prompts = [shared + [7, 9], shared + [5]]
        outs = {}
        for cache in (False, "auto"):
            eng = ServingEngine(model, prefix_cache=cache, megastep_k=8,
                                **ENGINE)
            r0 = eng.add_request(prompts[0], max_new_tokens=8)
            first = eng.run()[r0]
            r1 = eng.add_request(prompts[1], max_new_tokens=8)
            outs[cache] = (first, eng.run()[r1])
            if cache == "auto":
                assert eng.prefix_hit_blocks > 0  # the cache really engaged
        assert outs[False] == outs["auto"]

    def test_preempt_resume_across_megastep_boundary(self, model):
        """Evict at a megastep boundary mid-generation, resume with
        prompt+generated: the concatenated stream equals the unpreempted
        run (greedy-deterministic contract carried through megastep)."""
        prompt = [3, 17, 101]
        full = ref_greedy(model, prompt, 12)
        eng = ServingEngine(model, megastep_k=4, **ENGINE)
        rid = eng.add_request(prompt, max_new_tokens=12)
        eng.step()       # prefill + first token
        eng.step()       # one K=4 megastep -> 5 tokens
        req = eng.evict(rid)
        assert 0 < len(req.generated) < 12
        rid2 = eng.add_request(prompt + req.generated,
                               max_new_tokens=12 - len(req.generated))
        out = eng.run()[rid2]
        assert req.generated + out == full


class TestSamplingDeterminism:
    def test_temperature_zero_is_argmax(self, model):
        """temperature=0 sampling takes the exact greedy path."""
        prompt = [3, 17, 101, 7]
        ref = ref_greedy(model, prompt, 10)
        out, _ = run_engine(model, prompt, 10, 8,
                            sampling={"temperature": 0.0, "seed": 99})
        assert out == ref

    def test_same_seed_same_tokens_across_k(self, model):
        """The key depends only on (seed, sample index): K=1 and K=8
        produce the same sampled stream; a different seed diverges."""
        prompt = [3, 17, 101, 7]
        out1, _ = run_engine(model, prompt, 10, 1, sampling=SAMPLED)
        out8, _ = run_engine(model, prompt, 10, 8, sampling=SAMPLED)
        assert out1 == out8
        other, _ = run_engine(model, prompt, 10, 8,
                              sampling={**SAMPLED, "seed": 14})
        assert other != out8

    def test_replay_across_engine_rebuild(self, model):
        """The worker-restart shape: a fresh engine (rebuilt caches and
        programs, same seeded model) replays the same sampled stream."""
        prompt = [42, 5, 7]
        first, eng = run_engine(model, prompt, 8, 8, sampling=SAMPLED)
        del eng
        again, _ = run_engine(model, prompt, 8, 8, sampling=SAMPLED)
        assert first == again

    def test_resume_continues_key_stream_via_sample_offset(self, model):
        """A preempted sampled request resumed with ``sample_offset``
        continues the seeded stream exactly where it stopped."""
        prompt = [3, 17, 101]
        full, _ = run_engine(model, prompt, 12, 8, sampling=SAMPLED)
        eng = ServingEngine(model, megastep_k=4, **ENGINE)
        rid = eng.add_request(prompt, max_new_tokens=12, sampling=SAMPLED)
        eng.step()
        eng.step()
        req = eng.evict(rid)
        assert 0 < len(req.generated) < 12
        assert full[:len(req.generated)] == req.generated
        rid2 = eng.add_request(prompt + req.generated,
                               max_new_tokens=12 - len(req.generated),
                               sampling=SAMPLED,
                               sample_offset=len(req.generated))
        out = eng.run()[rid2]
        assert req.generated + out == full

    def test_frontend_preemption_preserves_sampled_stream(self, model):
        """End to end through the control plane: a LOW sampled request
        preempted for a HIGH one resumes (the frontend passes
        sample_offset) and finishes with the unpreempted stream."""
        plo = [3, 17, 101]
        want, _ = run_engine(model, plo, 8, 8, sampling=SAMPLED,
                             max_seq_len=32, num_blocks=4)
        eng = ServingEngine(model, megastep_k=8, **{**ENGINE,
                                                    "max_seq_len": 32,
                                                    "num_blocks": 4})
        fe = ServingFrontend([eng])
        rlo = fe.submit(plo, max_new_tokens=8, priority=Priority.LOW,
                        **SAMPLED)
        fe.step()                                # prefill + first token
        rhi = fe.submit(list(range(40, 50)), max_new_tokens=8,
                        priority=Priority.HIGH)
        res = fe.run()
        assert res[rhi].ok
        assert res[rlo].ok and res[rlo].preemptions >= 1
        assert res[rlo].tokens == want

    def test_sampling_validation(self, model):
        with pytest.raises(ValueError, match="temperature"):
            SamplingParams(temperature=-0.1)
        with pytest.raises(ValueError, match="top_p"):
            SamplingParams(top_p=0.0)
        with pytest.raises(ValueError, match="top_k"):
            SamplingParams(top_k=-1)


class TestSampleProbs:
    """capture_sample_probs=True (ISSUE 11 satellite): the engine
    exposes the renormalized POST-top-k/top-p distribution each token
    was drawn from — the q(x) a speculative-decode verifier scores draft
    tokens against — harvested like pop_token_logprobs()."""

    def test_probs_align_and_respect_filters(self, model):
        eng = ServingEngine(model, megastep_k=4,
                            capture_sample_probs=True, **ENGINE)
        rs = eng.add_request([3, 17, 101, 7], max_new_tokens=6,
                             sampling={"temperature": 0.8, "top_k": 8,
                                       "top_p": 0.9, "seed": 13})
        rg = eng.add_request([42, 5], max_new_tokens=6)     # greedy
        toks = eng.run()
        probs = eng.pop_sample_probs()
        assert set(probs) == {rs, rg}
        for rid in (rs, rg):
            assert len(probs[rid]) == len(toks[rid])   # 1:1 with tokens
            for q, t in zip(probs[rid], toks[rid]):
                assert float(q.sum()) == pytest.approx(1.0, abs=1e-4)
                assert q[t] > 0          # drawn token is inside support
        for q in probs[rs]:
            assert int((q > 0).sum()) <= 8        # top-k support bound
        for q, t in zip(probs[rg], toks[rg]):
            assert q[t] == 1.0 and int((q > 0).sum()) == 1   # one-hot
        assert eng.pop_sample_probs() == {}       # drained

    def test_capture_does_not_change_tokens(self, model):
        """Bit-identical draws with the capture on and off, single-step
        (K=1) and megastep (K=8) paths both."""
        prompt = [3, 17, 101, 7]
        for k in (1, 8):
            off, _ = run_engine(model, prompt, 8, k, sampling=SAMPLED)
            on, _ = run_engine(model, prompt, 8, k, sampling=SAMPLED,
                               capture_sample_probs=True)
            assert on == off, f"K={k}"
            goff, _ = run_engine(model, prompt, 8, k)
            gon, _ = run_engine(model, prompt, 8, k,
                                capture_sample_probs=True)
            assert gon == goff, f"K={k} greedy"

    def test_probs_are_the_sampled_distribution(self, model):
        """The spec-decode verification property: redrawing under the
        request's own (seed, sample-index) key from the EXPOSED
        distribution reproduces the engine's token exactly (categorical
        is shift-invariant, so log q and the filtered logits draw the
        same sample)."""
        import jax
        import jax.numpy as jnp

        sp = {"temperature": 0.8, "top_k": 16, "top_p": 0.95, "seed": 21}
        eng = ServingEngine(model, megastep_k=4,
                            capture_sample_probs=True, **ENGINE)
        rid = eng.add_request([9, 2, 77], max_new_tokens=6, sampling=sp)
        toks = eng.run()[rid]
        qs = eng.pop_sample_probs()[rid]
        for i, (q, t) in enumerate(zip(qs, toks)):
            key = jax.random.fold_in(jax.random.PRNGKey(sp["seed"]), i)
            redraw = int(jax.random.categorical(
                key, jnp.log(jnp.asarray(q))))
            assert redraw == t, f"sample index {i}"


class TestStreaming:
    def test_on_token_callback_order_and_completeness(self, model):
        fe = ServingFrontend([ServingEngine(model, **ENGINE)])
        seen = {}
        rids = [fe.submit([3 + i, 17, 101], max_new_tokens=10,
                          on_token=lambda rid, t: seen.setdefault(
                              rid, []).append(t))
                for i in range(3)]
        res = fe.run()
        for rid in rids:
            assert res[rid].ok
            assert seen[rid] == res[rid].tokens   # every token, in order

    def test_stream_iterator(self, model):
        fe = ServingFrontend([ServingEngine(model, **ENGINE)])
        rid = fe.submit([3, 17, 101], max_new_tokens=10)
        toks = list(fe.stream(rid))
        assert toks == fe.result(rid).tokens
        assert fe.result(rid).ok
        with pytest.raises(KeyError):
            next(fe.stream(999))

    def test_raising_callback_disables_stream_not_replica(self, model):
        """A buggy on_token callback must not kill the replica or the
        request — the callback is dropped, the request completes."""
        def boom(rid, tok):
            raise RuntimeError("consumer bug")

        fe = ServingFrontend([ServingEngine(model, **ENGINE)])
        rid = fe.submit([3, 17, 101], max_new_tokens=8, on_token=boom)
        res = fe.run()
        assert res[rid].ok
        assert res[rid].tokens == ref_greedy(model, [3, 17, 101], 8)
        assert fe.metrics.counter("stream_callback_errors_total") == 1
        assert fe.metrics.counter("replica_deaths_total") == 0


class TestMegastepBoundaries:
    def test_deadline_shed_zero_overshoot_in_graph(self, model):
        """ISSUE 16 (supersedes test_deadline_overshoot_bounded_by_k):
        the deadline rides the scan carry as a per-row iteration budget
        decremented in-graph, so the row freezes AT its deadline — zero
        token overshoot — and the frontend's next boundary check turns
        the frozen row into the typed shed.  ``deadline_token_seconds``
        injects the per-iteration estimate so the budget is exact."""
        clock = FakeClock()
        eng = ServingEngine(model, megastep_k=4,
                            deadline_token_seconds=1.0, clock=clock,
                            **ENGINE)
        fe = ServingFrontend([eng], clock=clock)
        rid = fe.submit([3, 17, 101], max_new_tokens=30, deadline_s=100.0)
        fe.step()                 # prefill + first token at t=0
        clock.t = 97.0            # 3 iteration budgets remain
        fe.step()                 # K=4 scan with in-graph budget dl=3
        assert fe.result(rid) is None      # frozen, not yet expired
        clock.t = 101.0
        fe.step()                 # boundary: typed shed of the frozen row
        res = fe.result(rid)
        assert res is not None
        assert res.status is RequestStatus.DEADLINE_EXCEEDED
        # 1 prefill-step token + the in-graph budget of exactly 3: the
        # K=4 scan stopped one short of its sweep — ZERO overshoot
        assert len(res.tokens) == 4
        assert res.tokens == ref_greedy(model, [3, 17, 101], 30)[:4]
        assert eng.megasteps > 0           # the scan path really ran

    def test_deadline_fallback_bounded_by_k_without_estimate(self, model):
        """Before the engine has measured a megastep (no injected
        ``deadline_token_seconds``, first launch is a compile), the
        in-graph budget is unarmed and the ISSUE 9 bound is the worst
        case: at most K extra tokens from the straddling megastep."""
        clock = FakeClock()
        eng = ServingEngine(model, megastep_k=4, **ENGINE)
        fe = ServingFrontend([eng], clock=clock)
        rid = fe.submit([3, 17, 101], max_new_tokens=30, deadline_s=5.0)
        fe.step()                     # prefill + first token
        clock.advance(10.0)           # deadline passes between boundaries
        fe.step()                     # boundary: shed fires HERE
        res = fe.result(rid)
        assert res is not None
        assert res.status is RequestStatus.DEADLINE_EXCEEDED
        # 1 pre-deadline token; the straddling megastep can add at most K
        assert len(res.tokens) <= 1 + eng.megastep_k
        assert res.tokens == ref_greedy(model, [3, 17, 101],
                                        30)[:len(res.tokens)]

    def test_logprobs_align_with_tokens(self, model):
        fe = ServingFrontend([ServingEngine(model, **ENGINE)])
        r1 = fe.submit([3, 17, 101], max_new_tokens=9, logprobs=True)
        r2 = fe.submit([42, 5], max_new_tokens=6, logprobs=True,
                       **SAMPLED)
        res = fe.run()
        for rid in (r1, r2):
            lps = res[rid].logprobs
            assert lps is not None and len(lps) == len(res[rid].tokens)
            assert all(lp <= 0.0 for lp in lps)   # log-probabilities
        # greedy default requests don't pay for logprob plumbing
        r3 = fe.submit([9, 9], max_new_tokens=4)
        assert fe.run()[r3].logprobs is None

    def test_megastep_counters_and_state_summary(self, model):
        eng = ServingEngine(model, megastep_k=8, **ENGINE)
        fe = ServingFrontend([eng])
        rid = fe.submit([3, 17, 101], max_new_tokens=10)
        res = fe.run()
        assert res[rid].ok
        ms = eng.state_summary()["megastep"]
        assert ms["k"] == 8
        assert ms["megasteps"] == eng.megasteps > 0
        assert ms["tokens"] == eng.megastep_tokens > 0
        assert fe.metrics.counter("megasteps_total") == eng.megasteps
        assert (fe.metrics.counter("megastep_tokens_total")
                == eng.megastep_tokens)

    def test_megastep_k1_never_scans(self, model):
        out_ref = ref_greedy(model, [3, 17, 101], 8)
        eng = ServingEngine(model, megastep_k=1, **ENGINE)
        rid = eng.add_request([3, 17, 101], max_new_tokens=8)
        assert eng.run()[rid] == out_ref
        # never armed: zero scan launches (the program object itself may
        # be pre-warmed from the process-wide shared program cache)
        assert eng.megasteps == 0

    def test_megastep_k_validation(self, model):
        with pytest.raises(ValueError, match="megastep_k"):
            ServingEngine(model, megastep_k=0, **ENGINE)


def run_staggered(model, prompts, arrivals, k, n=8, sampling=None, **kw):
    """Open-loop staggered admission in engine-step time: request i is
    admitted once the step counter reaches ``arrivals[i]`` — the traffic
    shape where the r11 arming rule (megastep only when EVERY scheduled
    row is past prefill) degraded to per-token stepping."""
    eng = ServingEngine(model, megastep_k=k, **{**ENGINE, **kw})
    out, rids, nxt, steps = {}, [], 0, 0
    while True:
        while nxt < len(prompts) and arrivals[nxt] <= steps:
            rid = eng.add_request(prompts[nxt], max_new_tokens=n,
                                  sampling=sampling)
            rids.append(rid)
            out[rid] = []
            nxt += 1
        st = eng.state_summary()
        if st["num_active"] == 0 and st["queue_depth"] == 0:
            if nxt >= len(prompts):
                break
            steps = arrivals[nxt]     # idle gap: jump to the next arrival
            continue
        for rid, toks in eng.step().items():
            out[rid].extend(toks)
        steps += 1
    return [out[r] for r in rids], eng


class TestMixedPhaseMegastep:
    """ISSUE 16: chunked prefill INSIDE the scan.  Each iteration
    processes, per row, one decode token or one ≤block_size prompt
    chunk (fed as data through ``prefill_pos`` carries), so the
    megastep arms whenever any row is decoding and never disarms under
    open-loop admission."""

    PROMPTS = ([3, 17, 101],
               [40, 41, 42, 43, 44, 45, 46, 47, 48, 49],
               [7, 9],
               [90, 91, 92, 93, 94])
    ARRIVALS = (0, 1, 3, 5)

    def test_staggered_greedy_parity_and_stays_armed(self, model):
        """The headline contract both ways: chunked-on/off token
        identity under staggered admission, and the scan actually
        stayed armed (mixed launches + chunks fed happened)."""
        on, eng = run_staggered(model, self.PROMPTS, self.ARRIVALS, 4)
        off, _ = run_staggered(model, self.PROMPTS, self.ARRIVALS, 1)
        assert on == off
        for p, toks in zip(self.PROMPTS, on):
            assert toks == ref_greedy(model, p, 8)
        assert eng.megasteps_mixed > 0        # prefill rode the scan
        assert eng.prefill_chunks > 0
        ms = eng.state_summary()["megastep"]
        assert ms["mixed"] == eng.megasteps_mixed
        assert ms["prefill_chunks"] == eng.prefill_chunks

    def test_staggered_seeded_parity(self, model):
        """Seeded sampling through the mixed scan: the (seed, sample
        index) key contract is phase-blind, so chunked-on/off streams
        are identical."""
        on, eng = run_staggered(model, self.PROMPTS, self.ARRIVALS, 4,
                                sampling=SAMPLED)
        off, _ = run_staggered(model, self.PROMPTS, self.ARRIVALS, 1,
                               sampling=SAMPLED)
        assert on == off
        assert eng.megasteps_mixed > 0

    def test_prefix_hit_enters_mid_chunk(self, model):
        """A prefix-cache hit drops a prompt into prefill at its first
        uncached position — mid-chunk from the scan's point of view (the
        chunk window starts at ``prefill_pos``, not a chunk-0 boundary).
        Cache-on and cache-off runs stay token-identical."""
        shared = list(range(30, 46))          # 16 tokens = 2 full blocks
        outs = {}
        for cache in (False, "auto"):
            eng = ServingEngine(model, prefix_cache=cache, megastep_k=4,
                                **ENGINE)
            r0 = eng.add_request(shared + [7, 9], max_new_tokens=8)
            first = eng.run()[r0]             # seeds the cache
            rd = eng.add_request([3, 17, 101], max_new_tokens=10)
            eng.step()                        # rd past prefill: decoding
            r1 = eng.add_request(shared + [5], max_new_tokens=8)
            rest = eng.run()
            outs[cache] = (first, rest[rd], rest[r1])
            if cache == "auto":
                assert eng.prefix_hit_blocks > 0   # the cache engaged
                assert eng.megasteps_mixed > 0     # hit rode the scan
        assert outs[False] == outs["auto"]

    def test_preempt_resume_across_chunk_boundary(self, model):
        """Evict a request mid-prefill — after the mixed scan fed some
        chunks but before the prompt completed — and resume it: the
        re-queued run and the concurrent decode row both match the
        unpreempted greedy reference."""
        long = list(range(40, 64))            # 24 tokens = 3 chunks of 8
        eng = ServingEngine(model, megastep_k=2, **ENGINE)
        r0 = eng.add_request([3, 17, 101], max_new_tokens=12)
        eng.step()                            # prefill + first token
        r1 = eng.add_request(long, max_new_tokens=6)
        eng.step()                # mixed K=2 scan: 2 chunks of r1 fed
        req = eng._active[r1]
        assert 0 < req.prefill_pos < len(long)    # mid-prefill
        assert req.chunks_fed >= 1            # crossed a chunk boundary
        evicted = eng.evict(r1)
        assert evicted.generated == []        # preempted before token 1
        r2 = eng.add_request(long, max_new_tokens=6)
        out = eng.run()
        assert out[r2] == ref_greedy(model, long, 6)
        assert out[r0] == ref_greedy(model, [3, 17, 101], 12)

    def test_int8_scan_carry_parity(self, model):
        """cache_quant='int8' rides the pure-decode scan (the quant
        scales travel in the carry): K>1 matches the per-token int8
        path exactly, greedy and seeded."""
        prompt = [3, 17, 101, 7]
        off, eoff = run_engine(model, prompt, 10, 1, cache_quant="int8")
        on, eon = run_engine(model, prompt, 10, 4, cache_quant="int8")
        assert on == off
        assert eon.megasteps > 0 and eoff.megasteps == 0
        s_off, _ = run_engine(model, prompt, 10, 1, cache_quant="int8",
                              sampling=SAMPLED)
        s_on, _ = run_engine(model, prompt, 10, 4, cache_quant="int8",
                             sampling=SAMPLED)
        assert s_on == s_off

    def test_int8_staggered_excludes_mixed_but_scans_decode(self, model):
        """int8's one-shot prefill contract (scales freeze at the full
        prompt) keeps prefill OUT of the mixed scan — chunk feeds would
        re-freeze scales per chunk — but decode still megasteps, and
        parity holds under staggered admission."""
        on, eng = run_staggered(model, self.PROMPTS, self.ARRIVALS, 4,
                                cache_quant="int8")
        off, _ = run_staggered(model, self.PROMPTS, self.ARRIVALS, 1,
                               cache_quant="int8")
        assert on == off
        assert eng.megasteps_mixed == 0       # contract: no int8 chunks
        assert eng.megasteps > 0              # decode rode the scan

    def test_mixed_counters_fold_through_frontend(self, model):
        eng = ServingEngine(model, megastep_k=4, **ENGINE)
        fe = ServingFrontend([eng])
        r0 = fe.submit([3, 17, 101], max_new_tokens=10)
        fe.step()                             # r0 decoding
        r1 = fe.submit(list(range(40, 50)), max_new_tokens=8)
        res = fe.run()
        assert res[r0].ok and res[r1].ok
        assert eng.megasteps_mixed > 0
        assert (fe.metrics.counter("megastep_mixed_total")
                == eng.megasteps_mixed)
        assert (fe.metrics.counter("prefill_chunks_total")
                == eng.prefill_chunks > 0)

    def test_prefill_chunk_trace_events(self, model):
        """r15 span events at chunk boundaries: every chunk feed lands
        a ``prefill_chunk`` event (index + token count) on the request's
        attempt span, so TTFT attributes across chunks fleet-wide."""
        clock = FakeClock()
        tracer = Tracer(clock=clock, proc="frontend")
        rec = FlightRecorder(clock=clock, proc="engine")
        eng = ServingEngine(model, megastep_k=2, trace_recorder=rec,
                            clock=clock, **ENGINE)
        fe = ServingFrontend([eng], tracer=tracer, clock=clock)
        r0 = fe.submit([3, 17, 101], max_new_tokens=8)
        fe.step()
        long = list(range(40, 60))            # 20 tokens: chunks 8, 8, 4
        r1 = fe.submit(long, max_new_tokens=4)
        res = fe.run()
        assert res[r0].ok and res[r1].ok
        tree = tracer.tree_for(TraceContext.mint(r1).trace_id)
        ok, why = tree_complete(tree)
        assert ok, why
        chunks = [e["attrs"] for evs in tree.values() for e in evs
                  if e["event"] == "prefill_chunk"]
        assert len(chunks) >= 2               # fed across scan launches
        assert sorted(a["chunk"] for a in chunks) == \
            list(range(len(chunks)))
        assert sum(a["tokens"] for a in chunks) == len(long)
