"""r5 final stub graduations: fused_multi_head_attention (with cache),
sparse_attention (CSR), incubate.jit.inference decorator."""
import numpy as np
import pytest

import paddle_tpu as P
import paddle_tpu.nn as nn

pytestmark = pytest.mark.quick


class TestFusedMHAFunctional:
    def _weights(self, E, H, seed=0):
        rng = np.random.RandomState(seed)
        hd = E // H
        return (rng.randn(3, H, hd, E).astype(np.float32) * 0.2,
                rng.randn(3, H, hd).astype(np.float32) * 0.1,
                rng.randn(E, E).astype(np.float32) * 0.2,
                rng.randn(E).astype(np.float32) * 0.1)

    def test_matches_composed_ops(self):
        from paddle_tpu.incubate.nn.functional import fused_multi_head_attention

        E, H, B, S = 16, 4, 2, 5
        qkvw, qkvb, lw, lb = self._weights(E, H)
        rng = np.random.RandomState(1)
        x = rng.randn(B, S, E).astype(np.float32)
        ones = np.ones(E, np.float32)
        zeros = np.zeros(E, np.float32)
        out = fused_multi_head_attention(
            P.to_tensor(x), P.to_tensor(qkvw), P.to_tensor(lw),
            pre_layer_norm=True, pre_ln_scale=P.to_tensor(ones),
            pre_ln_bias=P.to_tensor(zeros), qkv_bias=P.to_tensor(qkvb),
            linear_bias=P.to_tensor(lb), dropout_rate=0.0,
            attn_dropout_rate=0.0, training=False)
        # oracle: LN -> qkv -> softmax attention -> proj -> +residual
        mu = x.mean(-1, keepdims=True)
        sd = x.std(-1, keepdims=True)
        h = (x - mu) / np.sqrt(sd ** 2 + 1e-5)
        qkv = np.einsum("bse,xhde->bsxhd", h, qkvw) + qkvb[None, None]
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        hd = E // H
        lg = np.einsum("bshd,bthd->bhst", q, k) / np.sqrt(hd)
        w = np.exp(lg - lg.max(-1, keepdims=True))
        w /= w.sum(-1, keepdims=True)
        att = np.einsum("bhst,bthd->bshd", w, v).reshape(B, S, E)
        ref = x + att @ lw + lb
        np.testing.assert_allclose(np.asarray(out.numpy()), ref,
                                   rtol=2e-4, atol=2e-4)

    def test_ring_id_raises_not_silently_skips(self):
        """ADVICE r5 low #2: with an ACTIVE TP group (mp > 1), ring_id >= 0
        means the reference runs a TP all-reduce after the output
        projection; returning partial sums silently would be wrong — it
        must raise. With no TP group (the common ported-code pattern
        nranks=1, ring_id=0) the all-reduce is the identity and the call
        must still work."""
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.topology import (
            set_hybrid_communicate_group,
        )
        from paddle_tpu.incubate.nn.functional import fused_multi_head_attention

        E, H, B, S = 16, 4, 2, 5
        qkvw, _, lw, _ = self._weights(E, H)
        x = np.zeros((B, S, E), np.float32)
        ones = np.ones(E, np.float32)
        zeros = np.zeros(E, np.float32)
        # no TP group: ring_id=0 is a 1-rank group — identity, no raise
        set_hybrid_communicate_group(None)
        out = fused_multi_head_attention(
            P.to_tensor(x), P.to_tensor(qkvw), P.to_tensor(lw),
            ln_scale=P.to_tensor(ones), ln_bias=P.to_tensor(zeros),
            dropout_rate=0.0, attn_dropout_rate=0.0, training=False,
            ring_id=0)
        assert np.isfinite(np.asarray(out.numpy())).all()
        # active mp=2 group: skipping the all-reduce would be wrong
        s = dist.fleet.DistributedStrategy()
        s.hybrid_configs = {"dp_degree": 1, "mp_degree": 2, "pp_degree": 1,
                            "sharding_degree": 1, "sep_degree": 1}
        dist.fleet.init(is_collective=True, strategy=s)
        try:
            with pytest.raises(NotImplementedError, match="ring_id"):
                fused_multi_head_attention(P.to_tensor(x), P.to_tensor(qkvw),
                                           P.to_tensor(lw), ring_id=0)
        finally:
            set_hybrid_communicate_group(None)

    def test_cache_decode_incremental(self):
        """Layer-level cache decode equals the full-sequence forward at the
        appended position (post-LN self-attn block)."""
        from paddle_tpu.incubate.nn import FusedMultiHeadAttention

        P.seed(4)
        E, H, B, S = 16, 4, 1, 4
        layer = FusedMultiHeadAttention(E, H, dropout_rate=0.0,
                                        attn_dropout_rate=0.0,
                                        normalize_before=True)
        layer.eval()
        rng = np.random.RandomState(5)
        full = rng.randn(B, S + 1, E).astype(np.float32)
        ref = np.asarray(layer(P.to_tensor(full)).numpy())
        hd = E // H
        # build the cache from the first S tokens' K/V (pre-LN projections)
        x0 = full[:, :S]
        mu = x0.mean(-1, keepdims=True)
        sd = x0.std(-1, keepdims=True)
        h0 = (x0 - mu) / np.sqrt(sd ** 2 + 1e-5)
        qw = np.asarray(layer.qkv_weight.numpy())
        qb = np.asarray(layer.qkv_bias.numpy())
        qkv = np.einsum("bse,xhde->bsxhd", h0, qw) + qb[None, None]
        cache = np.stack([qkv[:, :, 1].transpose(0, 2, 1, 3),
                          qkv[:, :, 2].transpose(0, 2, 1, 3)])  # [2,B,H,S,D]
        out, new_cache = layer(P.to_tensor(full[:, S:S + 1]),
                               cache=P.to_tensor(cache.astype(np.float32)))
        np.testing.assert_allclose(np.asarray(out.numpy())[:, 0],
                                   ref[:, S], rtol=2e-4, atol=2e-4)
        assert tuple(new_cache.shape) == (2, B, H, S + 1, hd)


class TestSparseAttention:
    def test_csr_matches_dense_mask(self):
        from paddle_tpu.nn.functional.extra import sparse_attention

        rng = np.random.RandomState(2)
        B, H, S, D = 1, 2, 8, 4
        q = rng.randn(B, H, S, D).astype(np.float32)
        k = rng.randn(B, H, S, D).astype(np.float32)
        v = rng.randn(B, H, S, D).astype(np.float32)
        # random CSR pattern: each row keeps a random nonempty subset
        offs = np.zeros((B, H, S + 1), np.int32)
        cols_l = [[] for _ in range(B * H)]
        dense = np.full((B, H, S, S), -1e30, np.float32)
        for b in range(B):
            for hh in range(H):
                cur = 0
                for i in range(S):
                    sel = sorted({0} | set(rng.choice(
                        S, rng.randint(1, S + 1), replace=False).tolist()))
                    cols_l[b * H + hh].extend(sel)
                    cur += len(sel)
                    offs[b, hh, i + 1] = cur
                    dense[b, hh, i, sel] = 0.0
        nnz = max(len(c) for c in cols_l)
        cols = np.zeros((B, H, nnz), np.int32)
        for b in range(B):
            for hh in range(H):
                c = cols_l[b * H + hh]
                cols[b, hh, :len(c)] = c
        out = sparse_attention(P.to_tensor(q), P.to_tensor(k), P.to_tensor(v),
                               P.to_tensor(offs), P.to_tensor(cols))
        lg = np.einsum("bhid,bhjd->bhij", q, k) / np.sqrt(D) + dense
        w = np.exp(lg - lg.max(-1, keepdims=True))
        w /= w.sum(-1, keepdims=True)
        ref = np.einsum("bhij,bhjd->bhid", w, v)
        np.testing.assert_allclose(np.asarray(out.numpy()), ref,
                                   rtol=2e-4, atol=2e-4)
        # key_padding_mask: 0 = masked key (reference 0/1 semantics). The
        # CSR pattern keeps every row attending col 0, so zero it out.
        kpm = np.ones((B, S), np.float32)
        kpm[:, -1] = 0.0
        out2 = sparse_attention(P.to_tensor(q), P.to_tensor(k),
                                P.to_tensor(v), P.to_tensor(offs),
                                P.to_tensor(cols),
                                key_padding_mask=P.to_tensor(kpm))
        dense2 = dense.copy()
        dense2[..., -1] = -1e30
        # rows whose every kept column is masked would renormalize over
        # nothing — the random pattern keeps ≥1 live col per row here
        lg2 = np.einsum("bhid,bhjd->bhij", q, k) / np.sqrt(D) + dense2
        w2 = np.exp(lg2 - lg2.max(-1, keepdims=True))
        w2 /= w2.sum(-1, keepdims=True)
        ref2 = np.einsum("bhij,bhjd->bhid", w2, v)
        np.testing.assert_allclose(np.asarray(out2.numpy()), ref2,
                                   rtol=2e-4, atol=2e-4)


class TestIncubateJitInference:
    def test_decorates_layer_and_function(self):
        import paddle_tpu.incubate as incubate

        P.seed(6)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
        x = P.to_tensor(np.random.RandomState(7).randn(4, 8).astype(np.float32))
        ref = np.asarray(net(x).numpy())
        opt = incubate.jit.inference(net)
        out = opt(x)
        np.testing.assert_allclose(np.asarray(out.numpy()), ref,
                                   rtol=1e-5, atol=1e-5)
        assert out.stop_gradient  # no-grad inference path

        @incubate.jit.inference
        def fn(a):
            return a * 2.0 + 1.0

        np.testing.assert_allclose(
            np.asarray(fn(P.to_tensor(np.ones((2, 2), np.float32))).numpy()),
            3.0)
