"""paddle.distribution parity tests (VERDICT r1 item 8).

log_prob checked against scipy.stats, KL closed forms against Monte-Carlo
estimates, transforms against round-trip + autodiff log-det, rsample
gradient flow through the tape."""
import math

import numpy as np
import pytest
import scipy.stats as st

import jax
import jax.numpy as jnp

import paddle_tpu as P
from paddle_tpu import distribution as D


def _lp(dist, x):
    return np.asarray(dist.log_prob(P.to_tensor(np.asarray(x, np.float32)))._value)


SCIPY_CASES = [
    ("Normal", lambda: D.Normal(0.5, 2.0), lambda x: st.norm.logpdf(x, 0.5, 2.0), np.linspace(-4, 4, 7)),
    ("Uniform", lambda: D.Uniform(-1.0, 3.0), lambda x: st.uniform.logpdf(x, -1, 4), np.linspace(-0.5, 2.5, 5)),
    ("Laplace", lambda: D.Laplace(0.0, 1.5), lambda x: st.laplace.logpdf(x, 0, 1.5), np.linspace(-3, 3, 5)),
    ("Gumbel", lambda: D.Gumbel(1.0, 2.0), lambda x: st.gumbel_r.logpdf(x, 1, 2), np.linspace(-2, 6, 5)),
    ("Cauchy", lambda: D.Cauchy(0.0, 1.0), lambda x: st.cauchy.logpdf(x), np.linspace(-3, 3, 5)),
    ("Exponential", lambda: D.Exponential(1.7), lambda x: st.expon.logpdf(x, scale=1/1.7), np.linspace(0.1, 3, 5)),
    ("Gamma", lambda: D.Gamma(2.5, 1.3), lambda x: st.gamma.logpdf(x, 2.5, scale=1/1.3), np.linspace(0.2, 4, 5)),
    ("Beta", lambda: D.Beta(2.0, 3.0), lambda x: st.beta.logpdf(x, 2, 3), np.linspace(0.1, 0.9, 5)),
    ("LogNormal", lambda: D.LogNormal(0.3, 0.8), lambda x: st.lognorm.logpdf(x, 0.8, scale=math.exp(0.3)), np.linspace(0.2, 4, 5)),
    ("Chi2", lambda: D.Chi2(3.0), lambda x: st.chi2.logpdf(x, 3), np.linspace(0.5, 6, 5)),
    ("StudentT", lambda: D.StudentT(4.0, 0.5, 2.0), lambda x: st.t.logpdf(x, 4, 0.5, 2.0), np.linspace(-3, 4, 5)),
    ("Poisson", lambda: D.Poisson(2.5), lambda x: st.poisson.logpmf(x, 2.5), np.arange(0, 6, dtype=np.float32)),
    ("Bernoulli", lambda: D.Bernoulli(probs=0.3), lambda x: st.bernoulli.logpmf(x, 0.3), np.array([0.0, 1.0])),
    ("Geometric", lambda: D.Geometric(0.4), lambda x: st.geom.logpmf(x + 1, 0.4), np.arange(0, 5, dtype=np.float32)),
    ("Binomial", lambda: D.Binomial(10.0, 0.35), lambda x: st.binom.logpmf(x, 10, 0.35), np.arange(0, 10, 2, dtype=np.float32)),
]


class TestLogProbVsScipy:
    @pytest.mark.parametrize("name,mk,ref,xs", SCIPY_CASES, ids=[c[0] for c in SCIPY_CASES])
    def test_matches(self, name, mk, ref, xs):
        np.testing.assert_allclose(_lp(mk(), xs), ref(xs), rtol=2e-4, atol=2e-5)

    @pytest.mark.quick
    def test_categorical(self):
        logits = np.array([0.1, 1.2, -0.5], np.float32)
        d = D.Categorical(logits=logits)
        expect = logits - np.log(np.exp(logits).sum())
        got = np.asarray(d.log_prob(P.to_tensor(np.array([0, 1, 2])))._value)
        np.testing.assert_allclose(got, expect, rtol=1e-5)

    def test_dirichlet(self):
        c = np.array([1.5, 2.0, 3.0], np.float32)
        d = D.Dirichlet(c)
        x = np.array([0.2, 0.3, 0.5], np.float32)
        np.testing.assert_allclose(_lp(d, x), st.dirichlet.logpdf(x, c), rtol=1e-4)

    def test_multinomial(self):
        d = D.Multinomial(6, np.array([0.2, 0.3, 0.5], np.float32))
        x = np.array([1.0, 2.0, 3.0], np.float32)
        np.testing.assert_allclose(_lp(d, x), st.multinomial.logpmf(x, 6, [0.2, 0.3, 0.5]),
                                   rtol=1e-4)

    def test_multivariate_normal(self):
        mu = np.array([0.5, -1.0], np.float32)
        cov = np.array([[2.0, 0.5], [0.5, 1.0]], np.float32)
        d = D.MultivariateNormal(mu, covariance_matrix=cov)
        x = np.array([0.3, 0.2], np.float32)
        np.testing.assert_allclose(_lp(d, x), st.multivariate_normal.logpdf(x, mu, cov),
                                   rtol=1e-4)


class TestMomentsAndSampling:
    @pytest.mark.parametrize("mk,mean,var", [
        (lambda: D.Normal(1.0, 2.0), 1.0, 4.0),
        (lambda: D.Exponential(2.0), 0.5, 0.25),
        (lambda: D.Beta(2.0, 2.0), 0.5, 1.0 / 20),
        (lambda: D.Gamma(3.0, 2.0), 1.5, 0.75),
        (lambda: D.Laplace(0.0, 1.0), 0.0, 2.0),
        (lambda: D.Uniform(0.0, 2.0), 1.0, 4.0 / 12),
    ])
    def test_sample_moments(self, mk, mean, var):
        P.seed(0)
        d = mk()
        s = np.asarray(d.sample([20000])._value)
        assert abs(s.mean() - mean) < 0.08
        assert abs(s.var() - var) < 0.15
        np.testing.assert_allclose(float(d.mean._value), mean, rtol=1e-5)
        np.testing.assert_allclose(float(d.variance._value), var, rtol=1e-5)

    def test_entropy_normal(self):
        d = D.Normal(0.0, 2.0)
        np.testing.assert_allclose(float(d.entropy()._value), st.norm.entropy(0, 2), rtol=1e-5)

    def test_rsample_gradient_flows(self):
        loc = P.to_tensor(np.float32(0.0))
        loc.stop_gradient = False
        scale = P.to_tensor(np.float32(1.0))
        scale.stop_gradient = False
        P.seed(1)
        s = D.Normal(loc, scale).rsample([256])
        s.sum().backward()
        assert loc.grad is not None and abs(float(loc.grad._value) - 256.0) < 1e-3
        assert scale.grad is not None


class TestKL:
    @pytest.mark.parametrize("p,q", [
        (lambda: D.Normal(0.0, 1.0), lambda: D.Normal(1.0, 2.0)),
        (lambda: D.Exponential(1.0), lambda: D.Exponential(2.5)),
        (lambda: D.Gamma(2.0, 1.0), lambda: D.Gamma(3.0, 2.0)),
        (lambda: D.Beta(2.0, 2.0), lambda: D.Beta(3.0, 1.5)),
        (lambda: D.Laplace(0.0, 1.0), lambda: D.Laplace(0.5, 2.0)),
        (lambda: D.Bernoulli(probs=0.3), lambda: D.Bernoulli(probs=0.6)),
        (lambda: D.Poisson(2.0), lambda: D.Poisson(3.5)),
    ])
    def test_closed_form_vs_monte_carlo(self, p, q):
        P.seed(3)
        dp, dq = p(), q()
        kl = float(D.kl_divergence(dp, dq)._value)
        s = dp.sample([200000])
        mc = float((dp.log_prob(s) - dq.log_prob(s)).mean()._value)
        assert abs(kl - mc) < max(0.05, 0.1 * abs(kl)), (kl, mc)

    def test_categorical_kl(self):
        p = D.Categorical(logits=np.array([0.0, 1.0, 2.0], np.float32))
        q = D.Categorical(logits=np.array([1.0, 1.0, 1.0], np.float32))
        pp = np.exp([0, 1, 2]) / np.exp([0, 1, 2]).sum()
        expect = float((pp * np.log(pp / (np.ones(3) / 3))).sum())
        np.testing.assert_allclose(float(D.kl_divergence(p, q)._value), expect, rtol=1e-5)

    def test_unregistered_raises(self):
        with pytest.raises(NotImplementedError):
            D.kl_divergence(D.Cauchy(0.0, 1.0), D.Normal(0.0, 1.0))


class TestTransforms:
    @pytest.mark.parametrize("t,x", [
        (D.ExpTransform(), 0.7), (D.SigmoidTransform(), 0.3),
        (D.TanhTransform(), 0.4), (D.AffineTransform(1.0, 3.0), 0.9),
        (D.PowerTransform(2.0), 1.3),
    ])
    def test_roundtrip_and_logdet(self, t, x):
        xv = P.to_tensor(np.float32(x))
        y = t.forward(xv)
        back = t.inverse(y)
        np.testing.assert_allclose(float(back._value), x, rtol=1e-5)
        # log|dy/dx| via jax autodiff
        g = jax.grad(lambda v: t._forward(v))(jnp.float32(x))
        np.testing.assert_allclose(float(t.forward_log_det_jacobian(xv)._value),
                                   math.log(abs(float(g))), rtol=1e-4)

    def test_chain(self):
        chain = D.ChainTransform([D.AffineTransform(0.0, 2.0), D.ExpTransform()])
        x = P.to_tensor(np.float32(0.5))
        y = chain.forward(x)
        np.testing.assert_allclose(float(y._value), math.exp(1.0), rtol=1e-5)
        np.testing.assert_allclose(float(chain.inverse(y)._value), 0.5, rtol=1e-5)

    def test_stickbreaking_simplex(self):
        t = D.StickBreakingTransform()
        x = P.to_tensor(np.array([0.2, -0.3, 0.5], np.float32))
        y = t.forward(x)
        s = np.asarray(y._value)
        assert s.shape == (4,)
        np.testing.assert_allclose(s.sum(), 1.0, rtol=1e-5)
        back = t.inverse(y)
        np.testing.assert_allclose(np.asarray(back._value), np.asarray(x._value),
                                   rtol=1e-4, atol=1e-5)

    def test_transformed_distribution_lognormal(self):
        P.seed(7)
        base = D.Normal(0.3, 0.8)
        td = D.TransformedDistribution(base, [D.ExpTransform()])
        ref = D.LogNormal(0.3, 0.8)
        xs = P.to_tensor(np.linspace(0.3, 3.0, 5).astype(np.float32))
        np.testing.assert_allclose(np.asarray(td.log_prob(xs)._value),
                                   np.asarray(ref.log_prob(xs)._value), rtol=1e-4)

    def test_independent(self):
        d = D.Independent(D.Normal(np.zeros((3, 4), np.float32), 1.0), 1)
        assert d.batch_shape == (3,)
        assert d.event_shape == (4,)
        lp = d.log_prob(P.to_tensor(np.zeros((3, 4), np.float32)))
        assert lp.shape == [3]
        np.testing.assert_allclose(np.asarray(lp._value),
                                   4 * st.norm.logpdf(0.0) * np.ones(3), rtol=1e-5)

    def test_transformed_distribution_param_grad(self):
        """Gradients must reach the base distribution's parameters through
        TransformedDistribution.log_prob (review regression)."""
        loc = P.to_tensor(np.float32(0.3))
        loc.stop_gradient = False
        td = D.TransformedDistribution(D.Normal(loc, 1.0), [D.ExpTransform()])
        lp = td.log_prob(P.to_tensor(np.float32(2.0)))
        lp.backward()
        assert loc.grad is not None
        np.testing.assert_allclose(
            float(np.asarray(loc.grad._value)), float(np.log(2.0) - 0.3), rtol=1e-5)

    def test_binomial_kl_mismatched_counts(self):
        # p wider than q: support not nested -> +inf
        kl = D.kl_divergence(D.Binomial(20.0, 0.3), D.Binomial(10.0, 0.3))
        assert np.isinf(float(np.asarray(kl._value)))
        # p narrower than q: finite but not implemented -> loud failure
        with pytest.raises(NotImplementedError):
            D.kl_divergence(D.Binomial(10.0, 0.3), D.Binomial(20.0, 0.3))
        kl2 = D.kl_divergence(D.Binomial(10.0, 0.3), D.Binomial(10.0, 0.4))
        v = float(np.asarray(kl2._value))
        assert np.isfinite(v) and v > 0

    def test_categorical_scalar_value_batched_logits(self):
        d = D.Categorical(logits=np.ones((2, 3), np.float32))
        lp = d.log_prob(P.to_tensor(np.float32(1.0)))
        assert tuple(lp.shape) == (2,)
        np.testing.assert_allclose(np.asarray(lp._value), np.log(1 / 3) * np.ones(2), rtol=1e-5)

    def test_transform_param_grad(self):
        loc = P.to_tensor(np.float32(1.0))
        loc.stop_gradient = False
        td = D.TransformedDistribution(D.Normal(0.0, 1.0), [D.AffineTransform(loc, 2.0)])
        td.log_prob(P.to_tensor(np.float32(2.0))).backward()
        assert loc.grad is not None
        np.testing.assert_allclose(float(np.asarray(loc.grad._value)), 0.25, rtol=1e-5)

    def test_nested_base_param_grad(self):
        """Params of a nested Independent base must get grads (review regression)."""
        loc = P.to_tensor(np.array([0.3, 0.1], np.float32))
        loc.stop_gradient = False
        td = D.TransformedDistribution(
            D.Independent(D.Normal(loc, 1.0), 1), [D.ExpTransform()])
        td.log_prob(P.to_tensor(np.array([2.0, 1.0], np.float32))).backward()
        assert loc.grad is not None
        np.testing.assert_allclose(
            np.asarray(loc.grad._value),
            np.log([2.0, 1.0]) - np.array([0.3, 0.1]), rtol=1e-4, atol=1e-5)
