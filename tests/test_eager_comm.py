"""Eager cross-process collectives + p2p (VERDICT r2 item 3).

Two real processes on CPU, launched through the paddle_tpu launcher, bring up
the jax distributed runtime via init_parallel_env and exchange actual tensor
data: send/recv (ppermute over the process mesh), all_reduce, reduce(dst),
broadcast. Reference: paddle/phi/core/distributed/collective/process_group.h:48.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""
    import json, os, sys
    sys.path.insert(0, os.environ["PADDLE_TPU_REPO"])
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("XLA_FLAGS", None)
    for k in list(os.environ):
        if k.startswith(("TPU_", "LIBTPU", "AXON")):
            os.environ.pop(k)
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu as P
    import paddle_tpu.distributed as dist

    dist.init_parallel_env()
    rank = dist.get_rank()
    assert dist.get_world_size() == 2
    res = {}

    # ---- p2p: rank 0 -> rank 1
    if rank == 0:
        dist.send(P.to_tensor(np.arange(6, dtype=np.float32) * 3), dst=1)
    else:
        buf = P.zeros([6], dtype="float32")
        dist.recv(buf, src=0)
        res["recv"] = buf.numpy().tolist()

    # ---- all_reduce: sum of (rank+1)
    t = P.to_tensor(np.full((4,), float(rank + 1), np.float32))
    dist.all_reduce(t)
    res["all_reduce"] = t.numpy().tolist()

    # ---- reduce to dst=1: rank 0 keeps its input
    r = P.to_tensor(np.full((3,), float(rank + 1), np.float32))
    dist.reduce(r, dst=1)
    res["reduce"] = r.numpy().tolist()

    # ---- broadcast from 0
    b = P.to_tensor(np.full((2,), float(rank * 7 + 5), np.float32))
    dist.broadcast(b, src=0)
    res["broadcast"] = b.numpy().tolist()

    out_dir = sys.argv[1]
    json.dump(res, open(os.path.join(out_dir, f"res_{rank}.json"), "w"))
""")


# ISSUE 7 satellite triage: fails in THIS container on every run (solo
# included) — workerlogs show jaxlib raising "INVALID_ARGUMENT:
# Multiprocess computations aren't implemented on the CPU backend" from
# the ppermute under dist.send, i.e. the pinned jax 0.4.37 CPU backend
# dropped multiprocess collectives (same environment wall as the
# skipif-gated dp/mp mesh tests, see ROADMAP item 5).  Non-strict xfail:
# the jax upgrade that un-gates those meshes flips this to XPASS.
@pytest.mark.xfail(
    strict=False,
    reason="container jaxlib CPU backend: 'Multiprocess computations "
           "aren't implemented on the CPU backend' (jax 0.4.37); lifted "
           "by the ROADMAP item-5 jax upgrade")
def test_two_process_eager_comm(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    env = dict(os.environ)
    env["PADDLE_TPU_REPO"] = REPO
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--log_dir", str(tmp_path / "logs"),
         str(script), str(tmp_path)],
        cwd=REPO, capture_output=True, text=True, timeout=240, env=env,
    )
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    r0 = json.load(open(tmp_path / "res_0.json"))
    r1 = json.load(open(tmp_path / "res_1.json"))
    # p2p delivered real data across the process boundary
    assert r1["recv"] == [0.0, 3.0, 6.0, 9.0, 12.0, 15.0]
    # all_reduce: 1 + 2
    assert r0["all_reduce"] == [3.0] * 4
    assert r1["all_reduce"] == [3.0] * 4
    # reduce(dst=1): rank 0 keeps its input, rank 1 holds the sum
    assert r0["reduce"] == [1.0] * 3
    assert r1["reduce"] == [3.0] * 3
    # broadcast from rank 0
    assert r0["broadcast"] == [5.0] * 2
    assert r1["broadcast"] == [5.0] * 2
