"""Compiled pipeline: full microbatch schedule in one XLA program
(VERDICT r2 item 2; reference analog: pipeline_scheduler_pass/)."""
import numpy as np
import pytest

import paddle_tpu as P
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed.fleet.meta_parallel import (
    CompiledPipelineTrainStep,
    LayerDesc,
    PipelineLayer,
    pipeline_bubble_fraction,
)
from paddle_tpu.distributed.topology import set_hybrid_communicate_group

# old jax (no top-level jax.shard_map) aborts XLA's SPMD partitioner when
# the compiled pipeline's manual 'pp' axis meets a real (size>1) auto axis;
# CompiledPipelineTrainStep refuses such meshes cleanly, and the tests that
# specifically exercise dp/mp composition only run on modern jax
import jax as _jax

_AUTO_AXES_OK = hasattr(_jax, "shard_map")
needs_auto_axes = pytest.mark.skipif(
    not _AUTO_AXES_OK,
    reason="partial-manual shard_map with size>1 auto axes needs "
           "jax.shard_map (>=0.8)")
# composition degree: tests that WANT a real dp/mp axis keep it on modern
# jax and degrade to 1 (pp-only, still exercising the schedule) on old jax
_D2 = 2 if _AUTO_AXES_OK else 1


def _init(dp, pp):
    set_hybrid_communicate_group(None)
    s = dist.fleet.DistributedStrategy()
    s.hybrid_configs = {"dp_degree": dp, "mp_degree": 1, "pp_degree": pp,
                        "sharding_degree": 1, "sep_degree": 1}
    dist.fleet.init(is_collective=True, strategy=s)


def _mlp_descs(n, width=16):
    return [LayerDesc(nn.Linear, width, width) for _ in range(n)]


class TestCompiledPipeline:
    def test_trains_and_matches_sequential(self):
        _init(dp=_D2, pp=4)
        P.seed(7)
        pipe = PipelineLayer(layers=_mlp_descs(8), num_stages=4,
                             loss_fn=lambda o, y: F.mse_loss(o, y))
        # snapshot weights for the sequential reference
        w0 = [np.asarray(p._value) for ps in
              [[p for l in pipe._stage_layers[s] for p in l.parameters()]
               for s in range(4)] for p in ps]

        opt = P.optimizer.SGD(0.05, parameters=pipe.parameters())
        step = CompiledPipelineTrainStep(pipe, opt, num_micro=4)
        x = P.randn([8, 16])
        y = P.randn([8, 16])
        l0 = float(step(x, y).numpy())

        # sequential single-device reference with identical weights
        set_hybrid_communicate_group(None)
        P.seed(7)
        layers = [nn.Linear(16, 16) for _ in range(8)]
        flat = [p for l in layers for p in l.parameters()]
        for p, v in zip(flat, w0):
            p._value = P.to_tensor(v)._value
        net = nn.Sequential(*layers)
        ref = float(F.mse_loss(net(x), y).numpy())
        np.testing.assert_allclose(l0, ref, rtol=1e-4)

        # trains
        _init(dp=_D2, pp=4)
        for _ in range(10):
            l1 = float(step(x, y).numpy())
        assert l1 < l0

    def test_optimizer_state_is_stacked_and_sync_back(self):
        _init(dp=1, pp=2)
        P.seed(0)
        pipe = PipelineLayer(layers=_mlp_descs(4), num_stages=2,
                             loss_fn=lambda o, y: F.mse_loss(o, y))
        opt = P.optimizer.AdamW(learning_rate=0.01, parameters=pipe.parameters())
        step = CompiledPipelineTrainStep(pipe, opt, num_micro=2)
        x, y = P.randn([4, 16]), P.randn([4, 16])
        step(x, y)
        # accumulators exist per stacked [P, ...] weight
        accs = opt._accumulators.get("moment1") or next(iter(opt._accumulators.values()))
        shapes = {tuple(v.shape) for v in accs.values()}
        assert all(s[0] == 2 for s in shapes), shapes
        # sync back: per-stage tensors updated
        before = np.asarray(pipe._stage_layers[0][0].parameters()[0]._value).copy()
        step.sync_to_model()
        after = np.asarray(pipe._stage_layers[0][0].parameters()[0]._value)
        assert not np.allclose(before, after)

    def test_bubble_fraction(self):
        assert pipeline_bubble_fraction(4, 4) == pytest.approx(3 / 7)
        assert pipeline_bubble_fraction(32, 4) < 0.09

    def test_rejects_heterogeneous_stages(self):
        _init(dp=1, pp=2)
        descs = [LayerDesc(nn.Linear, 16, 16), LayerDesc(nn.Linear, 16, 32)]
        pipe = PipelineLayer(layers=descs, num_stages=2,
                             loss_fn=lambda o, y: F.mse_loss(o, y))
        opt = P.optimizer.SGD(0.1, parameters=pipe.parameters())
        with pytest.raises(ValueError, match="homogeneous"):
            CompiledPipelineTrainStep(pipe, opt, num_micro=2)

    def test_scaler_integration(self):
        _init(dp=1, pp=2)
        P.seed(1)
        pipe = PipelineLayer(layers=_mlp_descs(4), num_stages=2,
                             loss_fn=lambda o, y: F.mse_loss(o, y))
        opt = P.optimizer.SGD(0.05, parameters=pipe.parameters())
        scaler = P.amp.GradScaler(init_loss_scaling=2.0 ** 10)
        step = CompiledPipelineTrainStep(pipe, opt, num_micro=2, scaler=scaler)
        x, y = P.randn([4, 16]), P.randn([4, 16])
        l0 = float(step(x, y).numpy())
        for _ in range(8):
            l1 = float(step(x, y).numpy())
        assert np.isfinite(l1) and l1 < l0


def _init4d(dp, mp, pp):
    set_hybrid_communicate_group(None)
    s = dist.fleet.DistributedStrategy()
    s.hybrid_configs = {"dp_degree": dp, "mp_degree": mp, "pp_degree": pp,
                        "sharding_degree": 1, "sep_degree": 1}
    dist.fleet.init(is_collective=True, strategy=s)


class TestCompiledPipelineRealModel:
    """VERDICT r3 item 1: the compiled pipeline must run the real llama —
    heterogeneous stages (embed head / lm-head tail), tied embeddings, and
    optimizers with existing state / multiple groups."""

    def _llama(self, tie=False, seg="uniform"):
        from paddle_tpu.models import (
            LlamaPretrainingCriterion,
            llama_pipeline_descs,
            llama_tiny,
        )

        cfg = llama_tiny()
        crit = LlamaPretrainingCriterion()
        pipe = PipelineLayer(
            layers=llama_pipeline_descs(cfg, tie_embeddings=tie),
            num_stages=2, loss_fn=lambda lo, la: crit(lo, la), seg_method=seg)
        return cfg, pipe

    @needs_auto_axes
    def test_4d_llama_trains_compiled(self):
        _init4d(dp=2, mp=2, pp=2)
        P.seed(3)
        cfg, pipe = self._llama()
        opt = P.optimizer.AdamW(learning_rate=1e-3, parameters=pipe.parameters())
        step = CompiledPipelineTrainStep(pipe, opt, num_micro=2)
        ids = P.to_tensor(np.random.RandomState(0).randint(
            0, cfg.vocab_size, (4, 16)).astype(np.int32))
        l0 = float(step(ids, ids).numpy())
        assert np.isfinite(l0)
        for _ in range(6):
            l1 = float(step(ids, ids).numpy())
        assert l1 < l0

    def test_compiled_matches_sequential_forward(self):
        _init4d(dp=1, mp=1, pp=2)
        P.seed(11)
        cfg, pipe = self._llama()
        # zero-LR: the compiled loss must equal the eager sequential loss on
        # the very same weights (reference computed BEFORE construction —
        # building the compiled step re-places head/tail params on the full
        # mesh, which the eager per-stage path doesn't expect)
        ids = P.to_tensor(np.random.RandomState(1).randint(
            0, cfg.vocab_size, (4, 16)).astype(np.int32))
        from paddle_tpu.models import LlamaPretrainingCriterion

        crit = LlamaPretrainingCriterion()
        logits = pipe.forward(ids)  # eager sequential through the same stages
        ref = float(crit(logits, ids).numpy())
        opt = P.optimizer.SGD(0.0, parameters=pipe.parameters())
        step = CompiledPipelineTrainStep(pipe, opt, num_micro=2)
        compiled = float(step(ids, ids).numpy())
        np.testing.assert_allclose(compiled, ref, rtol=2e-3)

    def test_tied_embeddings_shared_grad(self):
        _init4d(dp=_D2, mp=_D2, pp=2)
        P.seed(5)
        cfg, pipe = self._llama(tie=True, seg="layer:_PipeDecoder")
        # ONE embedding layer object shared between stage 0 and stage 1
        emb = pipe.get_shared_layer("embed")
        assert any(l is emb for l in pipe._stage_layers[0])
        assert any(l is emb for l in pipe._stage_layers[-1])
        opt = P.optimizer.AdamW(learning_rate=1e-2, parameters=pipe.parameters())
        step = CompiledPipelineTrainStep(pipe, opt, num_micro=2)
        ids = P.to_tensor(np.random.RandomState(2).randint(
            0, cfg.vocab_size, (4, 16)).astype(np.int32))
        w_before = np.asarray(emb.embed_tokens.weight._value).copy()
        l0 = float(step(ids, ids).numpy())
        w_after = np.asarray(emb.embed_tokens.weight._value)
        assert np.isfinite(l0)
        assert not np.allclose(w_before, w_after)  # tied weight got grads
        for _ in range(6):
            l1 = float(step(ids, ids).numpy())
        assert l1 < l0

    def test_existing_optimizer_state_survives(self):
        # momentum accumulated on the eager engine must carry into the
        # compiled engine (restacked [P, ...])
        _init4d(dp=1, mp=1, pp=2)
        P.seed(9)
        cfg, pipe = self._llama()
        opt = P.optimizer.AdamW(learning_rate=1e-3, parameters=pipe.parameters())
        ids = P.to_tensor(np.random.RandomState(3).randint(
            0, cfg.vocab_size, (4, 16)).astype(np.int32))
        # a few eager steps accumulate per-stage state
        from paddle_tpu.models import LlamaPretrainingCriterion

        crit = LlamaPretrainingCriterion()
        for _ in range(2):
            loss = crit(pipe.forward(ids), ids)
            loss.backward()
            opt.step()
            opt.clear_grad()
        moment_sum_before = sum(
            float(np.abs(np.asarray(v)).sum())
            for v in opt._accumulators["moment1"].values())
        assert moment_sum_before > 0
        step = CompiledPipelineTrainStep(pipe, opt, num_micro=2)
        # restacked state: every body accumulator now leads with P=2
        decoder_param_count = len(step._body_segs[0].params)
        stacked_accs = [v for v in opt._accumulators["moment1"].values()
                        if np.ndim(v) > 0 and v.shape[0] == 2]
        assert len(stacked_accs) >= decoder_param_count
        l = float(step(ids, ids).numpy())
        assert np.isfinite(l)

    def test_multiple_param_groups(self):
        _init4d(dp=1, mp=1, pp=2)
        P.seed(13)
        cfg, pipe = self._llama()
        # split params by kind — uniform across stages (decay vs no-decay)
        decay, no_decay = [], []
        for p in pipe.parameters():
            (no_decay if p.ndim <= 1 else decay).append(p)
        opt = P.optimizer.AdamW(learning_rate=1e-3, parameters=[
            {"params": decay, "weight_decay": 0.1},
            {"params": no_decay, "weight_decay": 0.0},
        ])
        step = CompiledPipelineTrainStep(pipe, opt, num_micro=2)
        assert len(opt._param_groups) == 2
        ids = P.to_tensor(np.random.RandomState(4).randint(
            0, cfg.vocab_size, (4, 16)).astype(np.int32))
        l0 = float(step(ids, ids).numpy())
        for _ in range(4):
            l1 = float(step(ids, ids).numpy())
        assert np.isfinite(l1) and l1 < l0

    def test_sync_to_model_restores_eager_engine(self):
        _init4d(dp=1, mp=1, pp=2)
        P.seed(17)
        cfg, pipe = self._llama()
        opt = P.optimizer.AdamW(learning_rate=1e-3, parameters=pipe.parameters())
        step = CompiledPipelineTrainStep(pipe, opt, num_micro=2)
        ids = P.to_tensor(np.random.RandomState(5).randint(
            0, cfg.vocab_size, (4, 16)).astype(np.int32))
        compiled_loss = float(step(ids, ids).numpy())
        step.sync_to_model()
        # eager per-stage engine must run again after the placement restore
        from paddle_tpu.models import LlamaPretrainingCriterion

        crit = LlamaPretrainingCriterion()
        eager_loss = float(crit(pipe.forward(ids), ids).numpy())
        assert np.isfinite(eager_loss)


class TestCompiledVPP:
    """VPP chunks compiled (closing the r4 scope note): weights [C, P, ...],
    chunk-sequential rings with exit hop back to stage 0."""

    def test_vpp_matches_sequential_and_trains(self):
        _init(dp=_D2, pp=2)
        P.seed(21)
        # 8 layers, pp=2, 2 virtual chunks -> 4 segments of 2 layers
        pipe = PipelineLayer(layers=_mlp_descs(8), num_stages=2,
                             num_virtual_pipeline_stages=2,
                             loss_fn=lambda o, y: F.mse_loss(o, y))
        assert pipe._num_chunks == 2 and pipe._num_segments == 4
        w0 = [np.asarray(p._value) for s in range(4)
              for l in pipe._stage_layers[s] for p in l.parameters()]
        opt = P.optimizer.SGD(0.0, parameters=pipe.parameters())  # zero-LR parity
        step = CompiledPipelineTrainStep(pipe, opt, num_micro=2)
        assert step.num_chunks == 2
        x, y = P.randn([4, 16]), P.randn([4, 16])
        compiled = float(step(x, y).numpy())
        # sequential single-device reference with identical weights
        set_hybrid_communicate_group(None)
        layers = [nn.Linear(16, 16) for _ in range(8)]
        for p, v in zip([p for l in layers for p in l.parameters()], w0):
            p._value = P.to_tensor(v)._value
        ref = float(F.mse_loss(nn.Sequential(*layers)(x), y).numpy())
        np.testing.assert_allclose(compiled, ref, rtol=1e-4)
        # trains with a real LR
        _init(dp=_D2, pp=2)
        pipe2 = PipelineLayer(layers=_mlp_descs(8), num_stages=2,
                              num_virtual_pipeline_stages=2,
                              loss_fn=lambda o, y: F.mse_loss(o, y))
        opt2 = P.optimizer.AdamW(learning_rate=0.02, parameters=pipe2.parameters())
        step2 = CompiledPipelineTrainStep(pipe2, opt2, num_micro=2)
        l0 = float(step2(x, y).numpy())
        for _ in range(8):
            l1 = float(step2(x, y).numpy())
        assert l1 < l0
        # accumulators carry the [C, P, ...] leading dims
        accs = opt2._accumulators["moment1"]
        assert any(tuple(v.shape[:2]) == (2, 2) for v in accs.values())

    def test_vpp_interleaved_matches_chunk_sequential(self, monkeypatch):
        """r6: the branch-free interleaved ordering (AUTOMATIC when legal —
        PROFILE_r06.md §1) computes the SAME loss as the chunk-sequential
        rings (forced with PADDLE_TPU_VPP_INTERLEAVED=0) and as the r5
        lax.switch interleaved tick
        (PADDLE_TPU_VPP_INTERLEAVED_IMPL=switch)."""
        x, y = P.randn([8, 16]), P.randn([8, 16])

        def run(schedule):
            monkeypatch.delenv("PADDLE_TPU_VPP_INTERLEAVED", raising=False)
            monkeypatch.delenv("PADDLE_TPU_VPP_INTERLEAVED_IMPL",
                               raising=False)
            if schedule == "sequential":
                monkeypatch.setenv("PADDLE_TPU_VPP_INTERLEAVED", "0")
            elif schedule == "switch":
                monkeypatch.setenv("PADDLE_TPU_VPP_INTERLEAVED_IMPL",
                                   "switch")
            _init(dp=_D2, pp=2)
            P.seed(33)
            pipe = PipelineLayer(layers=_mlp_descs(8), num_stages=2,
                                 num_virtual_pipeline_stages=2,
                                 loss_fn=lambda o, y: F.mse_loss(o, y))
            opt = P.optimizer.SGD(0.0, parameters=pipe.parameters())
            step = CompiledPipelineTrainStep(pipe, opt, num_micro=4)
            return float(step(x, y).numpy())

        seq = run("sequential")
        np.testing.assert_allclose(seq, run("auto"), rtol=1e-5)
        np.testing.assert_allclose(seq, run("switch"), rtol=1e-5)

    def test_vpp_interleaved_tied_embeddings_parity(self, monkeypatch):
        """Heterogeneous stages under VPP — tied-embedding head/tail riding
        as shared aux params — must compute the same loss on all three
        schedules: chunk-sequential rings, the branch-free interleaved tick
        (auto-selected), and the lax.switch fallback tick."""
        from paddle_tpu.models import (
            LlamaPretrainingCriterion,
            llama_pipeline_descs,
            llama_tiny,
        )

        cfg = llama_tiny()
        cfg.num_hidden_layers = 4
        ids = P.to_tensor(np.random.RandomState(7).randint(
            0, cfg.vocab_size, (4, 16)).astype(np.int32))

        def build(schedule, lr=0.0):
            monkeypatch.delenv("PADDLE_TPU_VPP_INTERLEAVED", raising=False)
            monkeypatch.delenv("PADDLE_TPU_VPP_INTERLEAVED_IMPL",
                               raising=False)
            if schedule == "sequential":
                monkeypatch.setenv("PADDLE_TPU_VPP_INTERLEAVED", "0")
            elif schedule == "switch":
                monkeypatch.setenv("PADDLE_TPU_VPP_INTERLEAVED_IMPL",
                                   "switch")
            _init(dp=1, pp=2)
            P.seed(41)
            crit = LlamaPretrainingCriterion()
            pipe = PipelineLayer(
                layers=llama_pipeline_descs(cfg, tie_embeddings=True),
                num_stages=2, num_virtual_pipeline_stages=2,
                loss_fn=lambda lo, la: crit(lo, la),
                seg_method="layer:_PipeDecoder")
            opt = P.optimizer.SGD(lr, parameters=pipe.parameters())
            return CompiledPipelineTrainStep(pipe, opt, num_micro=2), pipe

        step, _ = build("sequential")
        assert step._chunks_homogeneous
        ref = float(step(ids, ids).numpy())
        step_i, _ = build("auto")
        np.testing.assert_allclose(float(step_i(ids, ids).numpy()), ref,
                                   rtol=2e-3)
        step_sw, _ = build("switch")
        np.testing.assert_allclose(float(step_sw(ids, ids).numpy()), ref,
                                   rtol=2e-3)

        # the tied weight gets grads through the interleaved schedule too
        _, pipe_t = build("auto", lr=0.0)
        emb = pipe_t.get_shared_layer("embed")
        opt2 = P.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=pipe_t.parameters())
        step_t2 = CompiledPipelineTrainStep(pipe_t, opt2, num_micro=2)
        w_before = np.asarray(emb.embed_tokens.weight._value).copy()
        l0 = float(step_t2(ids, ids).numpy())
        assert np.isfinite(l0)
        assert not np.allclose(w_before,
                               np.asarray(emb.embed_tokens.weight._value))

    def test_vpp_interleaved_optimizer_roundtrip(self):
        """Optimizer state stacks [C, P, ...] under the auto-selected
        interleaved schedule and round-trips through sync_to_model back to
        the eager per-stage engine."""
        _init(dp=1, pp=2)
        P.seed(37)
        pipe = PipelineLayer(layers=_mlp_descs(8), num_stages=2,
                             num_virtual_pipeline_stages=2,
                             loss_fn=lambda o, y: F.mse_loss(o, y))
        opt = P.optimizer.AdamW(learning_rate=0.01,
                                parameters=pipe.parameters())
        step = CompiledPipelineTrainStep(pipe, opt, num_micro=4)
        x, y = P.randn([8, 16]), P.randn([8, 16])
        l0 = float(step(x, y).numpy())
        for _ in range(4):
            l1 = float(step(x, y).numpy())
        assert np.isfinite(l1) and l1 < l0
        accs = opt._accumulators["moment1"]
        assert any(tuple(v.shape[:2]) == (2, 2) for v in accs.values())
        before = np.asarray(
            pipe._stage_layers[3][0].parameters()[0]._value).copy()
        step.sync_to_model()
        after = np.asarray(pipe._stage_layers[3][0].parameters()[0]._value)
        assert not np.allclose(before, after)
        # eager per-stage engine runs again after the placement restore
        eager = float(F.mse_loss(pipe.forward(x), y).numpy())
        assert np.isfinite(eager)

    def test_vpp_sync_to_model(self):
        _init(dp=1, pp=2)
        P.seed(23)
        pipe = PipelineLayer(layers=_mlp_descs(8), num_stages=2,
                             num_virtual_pipeline_stages=2,
                             loss_fn=lambda o, y: F.mse_loss(o, y))
        opt = P.optimizer.SGD(0.05, parameters=pipe.parameters())
        step = CompiledPipelineTrainStep(pipe, opt, num_micro=2)
        x, y = P.randn([4, 16]), P.randn([4, 16])
        step(x, y)
        before = np.asarray(pipe._stage_layers[3][0].parameters()[0]._value).copy()
        step.sync_to_model()
        after = np.asarray(pipe._stage_layers[3][0].parameters()[0]._value)
        assert not np.allclose(before, after)

    def test_vpp_existing_state_restacks_cpxx(self):
        """Eager-accumulated optimizer state restacks [C, P, ...] (review
        regression: it previously stacked [C*P, ...])."""
        _init(dp=1, pp=2)
        P.seed(29)
        pipe = PipelineLayer(layers=_mlp_descs(8), num_stages=2,
                             num_virtual_pipeline_stages=2,
                             loss_fn=lambda o, y: F.mse_loss(o, y))
        opt = P.optimizer.AdamW(learning_rate=0.01, parameters=pipe.parameters())
        x, y = P.randn([4, 16]), P.randn([4, 16])
        # a few eager 1F1B-engine steps accumulate per-segment state
        for _ in range(2):
            loss = F.mse_loss(pipe.forward(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
        step = CompiledPipelineTrainStep(pipe, opt, num_micro=2)
        accs = opt._accumulators["moment1"]
        stacked_shapes = [tuple(v.shape) for v in accs.values() if np.ndim(v) >= 3]
        assert any(s[:2] == (2, 2) for s in stacked_shapes), stacked_shapes
        l = float(step(x, y).numpy())
        assert np.isfinite(l)
