"""Compiled pipeline: full microbatch schedule in one XLA program
(VERDICT r2 item 2; reference analog: pipeline_scheduler_pass/)."""
import numpy as np
import pytest

import paddle_tpu as P
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed.fleet.meta_parallel import (
    CompiledPipelineTrainStep,
    LayerDesc,
    PipelineLayer,
    pipeline_bubble_fraction,
)
from paddle_tpu.distributed.topology import set_hybrid_communicate_group


def _init(dp, pp):
    set_hybrid_communicate_group(None)
    s = dist.fleet.DistributedStrategy()
    s.hybrid_configs = {"dp_degree": dp, "mp_degree": 1, "pp_degree": pp,
                        "sharding_degree": 1, "sep_degree": 1}
    dist.fleet.init(is_collective=True, strategy=s)


def _mlp_descs(n, width=16):
    return [LayerDesc(nn.Linear, width, width) for _ in range(n)]


class TestCompiledPipeline:
    def test_trains_and_matches_sequential(self):
        _init(dp=2, pp=4)
        P.seed(7)
        pipe = PipelineLayer(layers=_mlp_descs(8), num_stages=4,
                             loss_fn=lambda o, y: F.mse_loss(o, y))
        # snapshot weights for the sequential reference
        w0 = [np.asarray(p._value) for ps in
              [[p for l in pipe._stage_layers[s] for p in l.parameters()]
               for s in range(4)] for p in ps]

        opt = P.optimizer.SGD(0.05, parameters=pipe.parameters())
        step = CompiledPipelineTrainStep(pipe, opt, num_micro=4)
        x = P.randn([8, 16])
        y = P.randn([8, 16])
        l0 = float(step(x, y).numpy())

        # sequential single-device reference with identical weights
        set_hybrid_communicate_group(None)
        P.seed(7)
        layers = [nn.Linear(16, 16) for _ in range(8)]
        flat = [p for l in layers for p in l.parameters()]
        for p, v in zip(flat, w0):
            p._value = P.to_tensor(v)._value
        net = nn.Sequential(*layers)
        ref = float(F.mse_loss(net(x), y).numpy())
        np.testing.assert_allclose(l0, ref, rtol=1e-4)

        # trains
        _init(dp=2, pp=4)
        for _ in range(10):
            l1 = float(step(x, y).numpy())
        assert l1 < l0

    def test_optimizer_state_is_stacked_and_sync_back(self):
        _init(dp=1, pp=2)
        P.seed(0)
        pipe = PipelineLayer(layers=_mlp_descs(4), num_stages=2,
                             loss_fn=lambda o, y: F.mse_loss(o, y))
        opt = P.optimizer.AdamW(learning_rate=0.01, parameters=pipe.parameters())
        step = CompiledPipelineTrainStep(pipe, opt, num_micro=2)
        x, y = P.randn([4, 16]), P.randn([4, 16])
        step(x, y)
        # accumulators exist per stacked [P, ...] weight
        accs = opt._accumulators.get("moment1") or next(iter(opt._accumulators.values()))
        shapes = {tuple(v.shape) for v in accs.values()}
        assert all(s[0] == 2 for s in shapes), shapes
        # sync back: per-stage tensors updated
        before = np.asarray(pipe._stage_layers[0][0].parameters()[0]._value).copy()
        step.sync_to_model()
        after = np.asarray(pipe._stage_layers[0][0].parameters()[0]._value)
        assert not np.allclose(before, after)

    def test_bubble_fraction(self):
        assert pipeline_bubble_fraction(4, 4) == pytest.approx(3 / 7)
        assert pipeline_bubble_fraction(32, 4) < 0.09

    def test_rejects_heterogeneous_stages(self):
        _init(dp=1, pp=2)
        descs = [LayerDesc(nn.Linear, 16, 16), LayerDesc(nn.Linear, 16, 32)]
        pipe = PipelineLayer(layers=descs, num_stages=2,
                             loss_fn=lambda o, y: F.mse_loss(o, y))
        opt = P.optimizer.SGD(0.1, parameters=pipe.parameters())
        with pytest.raises(ValueError, match="homogeneous"):
            CompiledPipelineTrainStep(pipe, opt, num_micro=2)

    def test_scaler_integration(self):
        _init(dp=1, pp=2)
        P.seed(1)
        pipe = PipelineLayer(layers=_mlp_descs(4), num_stages=2,
                             loss_fn=lambda o, y: F.mse_loss(o, y))
        opt = P.optimizer.SGD(0.05, parameters=pipe.parameters())
        scaler = P.amp.GradScaler(init_loss_scaling=2.0 ** 10)
        step = CompiledPipelineTrainStep(pipe, opt, num_micro=2, scaler=scaler)
        x, y = P.randn([4, 16]), P.randn([4, 16])
        l0 = float(step(x, y).numpy())
        for _ in range(8):
            l1 = float(step(x, y).numpy())
        assert np.isfinite(l1) and l1 < l0
