"""String tensor tier (parity: /root/reference/paddle/phi/kernels/strings/ —
strings_empty / strings_lower_upper / strings_copy kernels over
phi::StringTensor, paddle/phi/ops/yaml/strings_ops.yaml).

TPU-native stance: strings never touch the accelerator (no XLA string type);
a StringTensor is a host-side numpy object array with the same op surface.
The utf8/ascii split mirrors the reference kernels' use_utf8_encoding flag
(case_utils.h: ascii fast path vs unicode conversion).
"""
from __future__ import annotations

from typing import Sequence, Union

import numpy as np

__all__ = ["StringTensor", "empty", "empty_like", "copy", "lower", "upper",
           "to_string_tensor"]


class StringTensor:
    """Host string tensor: shape + numpy object array of ``str``."""

    def __init__(self, data: Union[np.ndarray, Sequence, str]):
        if isinstance(data, StringTensor):
            data = data._data
        arr = np.asarray(data, dtype=object)
        # normalize elements to str
        self._data = np.vectorize(lambda x: "" if x is None else str(x),
                                  otypes=[object])(arr) if arr.size else arr

    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def size(self):
        return int(self._data.size)

    def numpy(self) -> np.ndarray:
        return self._data

    def tolist(self):
        return self._data.tolist()

    def __getitem__(self, idx):
        out = self._data[idx]
        return out if isinstance(out, str) else StringTensor(out)

    def __eq__(self, other):
        other = to_string_tensor(other)
        return np.array_equal(self._data, other._data)

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, data={self._data!r})"


def to_string_tensor(x) -> StringTensor:
    return x if isinstance(x, StringTensor) else StringTensor(x)


def empty(shape: Sequence[int]) -> StringTensor:
    """parity: strings_empty_kernel — a tensor of empty strings."""
    arr = np.empty(tuple(shape), dtype=object)
    arr.fill("")
    return StringTensor(arr)


def empty_like(x) -> StringTensor:
    return empty(to_string_tensor(x).shape)


def copy(x) -> StringTensor:
    """parity: strings_copy_kernel."""
    return StringTensor(to_string_tensor(x)._data.copy())


def _case_map(x, fn, use_utf8_encoding: bool):
    x = to_string_tensor(x)
    if use_utf8_encoding:
        out = np.vectorize(fn, otypes=[object])(x._data) if x.size else x._data.copy()
    else:
        # ascii fast path: only [A-Za-z] change case (case_utils.h semantics)
        def ascii_fn(s: str) -> str:
            return "".join(fn(c) if ("a" <= c <= "z" or "A" <= c <= "Z") else c
                           for c in s)

        out = np.vectorize(ascii_fn, otypes=[object])(x._data) if x.size else x._data.copy()
    return StringTensor(out)


def lower(x, use_utf8_encoding: bool = False) -> StringTensor:
    """parity: strings_lower_upper_kernel StringLower."""
    return _case_map(x, str.lower, use_utf8_encoding)


def upper(x, use_utf8_encoding: bool = False) -> StringTensor:
    """parity: strings_lower_upper_kernel StringUpper."""
    return _case_map(x, str.upper, use_utf8_encoding)
