"""paddle_tpu — a TPU-native deep learning framework with PaddlePaddle's
capability surface, built on JAX/XLA/Pallas/pjit idioms.

Top-level namespace mirrors ``paddle``: tensors, ops, nn, optimizer, amp, io,
jit, distributed, vision, etc. See SURVEY.md for the reference layer map this
rebuild tracks.
"""
from __future__ import annotations

__version__ = "0.1.0"

import os as _os

import jax as _jax

# Paddle-parity numerics: float32 ops mean float32. This environment's default
# lets XLA truncate f32 matmul operands to bf16; we pin HIGHEST and make low
# precision an explicit choice (bf16 dtype / amp), exactly like the reference's
# fp32-by-default kernels. Override with FLAGS_matmul_precision=default|high.
if "FLAGS_matmul_precision" not in _os.environ:
    _jax.config.update("jax_default_matmul_precision", "highest")
else:
    _prec = _os.environ["FLAGS_matmul_precision"]
    if _prec != "default":
        _jax.config.update("jax_default_matmul_precision", _prec)

# framework primitives
from .framework import (  # noqa: F401
    bfloat16,
    bool_,
    float8_e4m3fn,
    float8_e5m2,
    complex64,
    complex128,
    float16,
    float32,
    float64,
    get_default_dtype,
    get_flags,
    int8,
    int16,
    int32,
    int64,
    seed,
    set_default_dtype,
    set_flags,
    uint8,
)
from .framework import random as _random_mod  # noqa: F401
from .framework.dtype import dtype  # noqa: F401
from .framework.random import get_rng_state, set_rng_state  # noqa: F401

# tensor + ops (this import also patches Tensor methods)
from .tensor import Tensor  # noqa: F401
from .tensor import *  # noqa: F401,F403
from .tensor import creation as _creation  # noqa: F401

# autograd
from . import autograd  # noqa: F401
from .autograd import no_grad, enable_grad, set_grad_enabled, is_grad_enabled, grad  # noqa: F401

# device
from . import device  # noqa: F401
from .device import (  # noqa: F401
    CPUPlace,
    CUDAPlace,
    TPUPlace,
    get_device,
    set_device,
    is_compiled_with_cuda,
    is_compiled_with_rocm,
    is_compiled_with_xpu,
)

# subsystems (imported lazily-tolerant during bootstrap; all present by v0.1)
import importlib as _importlib

for _sub in ("nn", "optimizer", "metric", "amp", "io", "jit", "vision", "distributed",
             "models", "profiler", "hapi", "regularizer", "distribution", "fft",
             "sparse", "static", "quantization", "inference", "audio", "text",
             "callbacks", "incubate", "signal", "strings"):
    try:
        globals()[_sub] = _importlib.import_module(f".{_sub}", __name__)
    except ModuleNotFoundError as _e:
        if f"paddle_tpu.{_sub}" not in str(_e):
            raise

try:
    from .framework_io import load, save  # noqa: F401
except ModuleNotFoundError:
    pass

from .base.param_attr import ParamAttr  # noqa: F401
from .device import CUDAPinnedPlace  # noqa: F401
from .distributed.parallel import DataParallel  # noqa: F401


class LazyGuard:
    """parity: paddle.LazyGuard — defers parameter materialization in the
    reference (meta tensors). Host-side numpy init is cheap here, so layers
    initialize eagerly; the guard exists for API compatibility."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


bool = bool_  # noqa: A001  (paddle exports the dtype as paddle.bool)


def tolist(x):
    """paddle.tolist parity."""
    return x.tolist() if hasattr(x, "tolist") else list(x)

try:
    from .hapi import Model, summary  # noqa: F401
except ModuleNotFoundError:
    pass

# ---------------------------------------------------------- execution mode
# dynamic (eager-over-XLA) by default; enable_static() switches the dispatch
# chokepoint into lazy Program capture (see paddle_tpu.static)
_dynamic_mode = True


def in_dynamic_mode() -> bool:
    return _dynamic_mode


def enable_static():
    global _dynamic_mode
    _dynamic_mode = False
    from .ops import dispatch as _dispatch

    _dispatch._static_capture = True


def disable_static(place=None):
    global _dynamic_mode
    _dynamic_mode = True
    from .ops import dispatch as _dispatch

    _dispatch._static_capture = False


def is_grad_enabled_():
    return is_grad_enabled()
