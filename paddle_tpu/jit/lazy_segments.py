"""Mid-function graph break: segmented lazy execution (reference analog: the
SOT bytecode executor's split-at-the-failing-op resume,
/root/reference/python/paddle/jit/sot/opcode_translator/executor/opcode_executor.py:1594
+ pycode_generator.py resume functions).

TPU-native formulation (LazyTensor-style): the reference rewrites bytecode so
the compiled prefix hands control back to eager Python at the breaking op and
a resume function re-enters compilation. Here Python always runs the WHOLE
function, but ops dispatched while a :class:`SegmentContext` is active don't
execute — they record into the current segment with abstract
(ShapeDtypeStruct) results. A host read (``.numpy()``, ``bool()``, ``item``,
…) on a pending tensor FLUSHES the segment: the recorded ops replay as one
XLA computation, pending tensors materialize, and Python proceeds with
concrete values — then subsequent ops open the next segment. One ``.numpy()``
mid-model therefore yields exactly two compiled segments instead of dropping
the whole function to per-op eager.

Guards are per segment: each flush re-traces the recorded ops to a jaxpr
(cheap abstract eval) whose printed form + input avals key the compiled-
executable cache; the jaxpr's constants are passed as runtime arguments, so
per-call constants (fresh RNG keys, host-read scalars folded into later
segments) hit the same executable instead of recompiling.

Backward: each flushed segment becomes ONE tape GradNode over its external
inputs (params included), so ``loss.backward()`` through a segmented forward
matches full-eager — host-read values are constants w.r.t. grad in both
worlds.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..autograd import tape

__all__ = ["SegmentContext", "current", "run_segmented"]


# compiled segment executables keyed by (jaxpr text, const avals, in avals) —
# process-global so every StaticFunction shares hits. Host-read Python
# scalars folded into later segments appear as jaxpr literals, so such a
# segment re-specializes per distinct value — the SOT value-guard semantics
# (executor_cache.py guards on read values); the LRU bound keeps that from
# growing without limit.
from collections import OrderedDict

_segment_cache: "OrderedDict[Any, Any]" = OrderedDict()
_SEGMENT_CACHE_MAX = 256


def _cache_get(key):
    hit = _segment_cache.get(key)
    if hit is not None:
        _segment_cache.move_to_end(key)
    return hit


def _cache_put(key, fn):
    _segment_cache[key] = fn
    if len(_segment_cache) > _SEGMENT_CACHE_MAX:
        _segment_cache.popitem(last=False)
    return fn


def current() -> Optional["SegmentContext"]:
    from ..ops import dispatch

    return dispatch._lazy_ctx


class SegmentContext:
    def __init__(self, name: str = "fn", dump_name: Optional[str] = None):
        self.name = name
        self.dump_name = dump_name
        # one queued segment: (fn, input value-refs, output abstract refs)
        self.ops: List[Tuple[Callable, List, List]] = []
        # identity of every PENDING abstract value object -> its holder
        # tensors (tensors whose ._value is that abstract); op inputs and
        # host reads resolve by VALUE identity, so rewraps and in-place
        # adoptions of a pending value are all covered
        self.pending: Dict[int, List] = {}
        # abstract-value id -> (ref, concrete result) for values from past
        # flushes; the REF is kept alive on purpose — keying by id() of a
        # collected object would let CPython reuse the address for a fresh
        # abstract value and silently substitute a stale array
        self.materialized: Dict[int, Any] = {}
        self.segments_run = 0

    def resolve_tensor(self, t) -> None:
        """Fix up a tensor whose abstract value a past flush materialized."""
        hit = self.materialized.get(id(t._value))
        if hit is not None:
            t._value = hit[1]

    def forget_holder(self, t) -> None:
        """A raw value overwrite (set_value/zero_/fill_) on a pending tensor:
        drop it from the holder list so the flush won't clobber the write."""
        holders = self.pending.get(id(t._value))
        if holders is not None:
            holders[:] = [h for h in holders if h is not t]

    def alias(self, target, result) -> None:
        """``target`` adopted ``result``'s pending value (in-place op): the
        flush must materialize (and grad-wire) target too."""
        holders = self.pending.get(id(result._value))
        # identity membership (``in`` would run Tensor.__eq__ elementwise)
        if holders is not None and all(h is not target for h in holders):
            holders.append(target)

    def _resolve(self, t):
        """Fix up a tensor whose value was materialized by an earlier flush."""
        self.resolve_tensor(t)
        return t._value

    # ------------------------------------------------------------ recording
    def __enter__(self):
        from ..ops import dispatch

        self._prev = dispatch._lazy_ctx
        dispatch._lazy_ctx = self
        return self

    def __exit__(self, *exc):
        from ..ops import dispatch

        dispatch._lazy_ctx = self._prev
        return False

    def record(self, fn: Callable, inputs, op_name: str):
        """Defer one op: abstract-eval the result, queue the application."""
        from ..tensor.tensor import Tensor

        in_vals = [self._resolve(t) for t in inputs]
        metas = [
            v if isinstance(v, jax.ShapeDtypeStruct)
            else jax.ShapeDtypeStruct(jnp.shape(v), jnp.result_type(v))
            for v in in_vals
        ]
        out = jax.eval_shape(fn, *metas)
        multi = isinstance(out, (tuple, list))
        outs = list(out) if multi else [out]
        stop = all(t.stop_gradient for t in inputs) or not tape.grad_enabled()
        out_tensors = [Tensor(o, stop_gradient=stop) for o in outs]
        # capture the out VALUE refs NOW — the out tensor may later adopt a
        # different pending value (in-place ops), but the dataflow is by ref
        out_pairs = [(t, t._value) for t in out_tensors]
        for t, ref in out_pairs:
            self.pending[id(ref)] = [t]
        # the op keeps (input VALUE refs, grad-relevant input tensors)
        self.ops.append((fn, list(zip(in_vals, inputs)), out_pairs))
        if multi:
            return out_tensors if isinstance(out, list) else tuple(out_tensors)
        return out_tensors[0]

    # -------------------------------------------------------------- flushing
    def flush(self):
        """Compile + run the queued segment; materialize pending tensors."""
        if not self.ops:
            return
        from ..ops import dispatch

        ops, self.ops = self.ops, []
        pending, self.pending = self.pending, {}
        saved_ctx, dispatch._lazy_ctx = dispatch._lazy_ctx, None
        try:
            self._flush_impl(ops, pending)
        finally:
            dispatch._lazy_ctx = saved_ctx
        self.segments_run += 1

    def _flush_impl(self, ops, pending):
        # env is keyed by VALUE-object identity (abstract refs for produced
        # values, concrete arrays for externals)
        produced = set()
        for _, _, outs in ops:
            produced.update(id(ref) for _, ref in outs)
        ext_vals_list, ext_tensors, seen = [], [], set()
        for _, ins, _ in ops:
            for vref, t in ins:
                if id(vref) not in produced and id(vref) not in seen:
                    seen.add(id(vref))
                    ext_vals_list.append(vref)
                    ext_tensors.append(t)
        flat_pairs = [pair for _, _, outs in ops for pair in outs]
        flat_outs = [t for t, _ in flat_pairs]
        out_refs = [ref for _, ref in flat_pairs]

        def replay(*ext_in):
            env = {id(v): x for v, x in zip(ext_vals_list, ext_in)}
            for fn, ins, outs in ops:
                vals = [env[id(vref)] if id(vref) in env else vref
                        for vref, _ in ins]
                res = fn(*vals)
                rs = list(res) if isinstance(res, (tuple, list)) else [res]
                for (_, ref), r in zip(outs, rs):
                    env[id(ref)] = r
            return tuple(env[id(r)] for r in out_refs)

        ext = ext_tensors
        ext_vals = ext_vals_list
        needs_grad = tape.grad_enabled() and any(not t.stop_gradient for t in ext)

        # one compiled executable per segment, fwd and (lazily keyed) bwd —
        # jaxpr text + avals are the per-segment guards; consts ride as
        # runtime args so per-call constants reuse the executable
        closed = jax.make_jaxpr(replay)(*ext_vals)
        const_avals = tuple((jnp.shape(c), str(jnp.result_type(c)))
                            for c in closed.consts)
        in_avals = tuple((jnp.shape(v), str(jnp.result_type(v))) for v in ext_vals)
        key = (str(closed.jaxpr), const_avals, in_avals)
        fwd = _cache_get(key)
        if fwd is None:
            def run_jaxpr(consts, args, _jaxpr=closed.jaxpr):
                return jax.core.eval_jaxpr(_jaxpr, consts, *args)

            fwd = _cache_put(key, jax.jit(run_jaxpr))
        self._maybe_dump(replay, ext_vals)
        out_vals = fwd(list(closed.consts), list(ext_vals))

        node = None
        if needs_grad:
            bkey = (key, "bwd")
            bwd = _cache_get(bkey)
            if bwd is None:
                def run_bwd(consts, args, cots, _jaxpr=closed.jaxpr):
                    # recompute-forward vjp in ONE program (remat — the
                    # TPU-favored memory/compute tradeoff, same as
                    # StaticFunction's fwd_bwd)
                    _, vjp = jax.vjp(
                        lambda *a: tuple(jax.core.eval_jaxpr(_jaxpr, consts, *a)),
                        *args)
                    return vjp(tuple(cots))

                bwd = _cache_put(bkey, jax.jit(run_bwd))
            consts_now, ext_now = list(closed.consts), list(ext_vals)

            def vjp_fn(cots, _bwd=bwd, _c=consts_now, _e=ext_now):
                return _bwd(_c, _e, list(cots))

            node = tape.GradNode(vjp_fn, ext, list(out_vals),
                                 name=f"segment_{self.segments_run}", fn=replay,
                                 out_struct="tuple")

        for i, (t, ref, v) in enumerate(zip(flat_outs, out_refs, out_vals)):
            self.materialized[id(ref)] = (ref, v)  # ref kept alive (id reuse)
            for holder in pending.get(id(ref), [t]):
                holder._value = v
                if node is not None and not holder.stop_gradient:
                    holder._grad_node = node
                    holder._out_index = i

    def _maybe_dump(self, replay, ext_vals):
        if self.dump_name is None:
            return
        from .hlo_dump import dump_dir, maybe_dump

        if dump_dir():
            maybe_dump(f"{self.dump_name}_seg{self.segments_run}",
                       jax.jit(lambda *vs: replay(*vs)), tuple(ext_vals))


def run_segmented(fn: Callable, args, kwargs, name: str = "fn",
                  dump_name: Optional[str] = None):
    """Execute ``fn`` with op recording + flush-on-host-read; returns
    (output, segment_count)."""
    ctx = SegmentContext(name=name, dump_name=dump_name)
    with ctx:
        out = fn(*args, **kwargs)
    ctx.flush()  # trailing segment (also materializes the outputs)
    # fix up output leaves that hold already-materialized refs (rewraps)
    from ..tensor.tensor import Tensor

    def fix(o):
        if isinstance(o, Tensor):
            ctx.resolve_tensor(o)
        elif isinstance(o, (list, tuple)):
            for x in o:
                fix(x)
        elif isinstance(o, dict):
            for x in o.values():
                fix(x)

    fix(out)
    return out, ctx.segments_run
