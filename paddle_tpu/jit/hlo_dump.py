"""HLO/IR inspection layer.

Reference capability: CINN's ability to *see* what was compiled/fused
(/root/reference/paddle/cinn/hlir/framework/pir_compiler.h:23 and the PIR
program print/dump machinery). TPU-native: every compiled program has two
interesting artifacts — the lowered StableHLO (what we handed XLA) and the
optimized HLO (what XLA made of it: fusions, layouts, rematerialization).

Enable with ``paddle.set_flags({'FLAGS_dump_hlo': '/some/dir'})`` or
``FLAGS_dump_hlo=/some/dir`` in the environment; TrainStep and to_static
write ``<name>.stablehlo.txt`` + ``<name>.optimized.txt`` there on first
compile. ``lower_text()`` gives the same artifacts programmatically.
"""
from __future__ import annotations

import os
import re
from typing import Optional

__all__ = ["dump_dir", "maybe_dump", "lower_text"]

_counter = [0]


def dump_dir() -> Optional[str]:
    from ..framework.flags import flag_value

    d = flag_value("dump_hlo")
    return d or None


def lower_text(jitted, *args, optimized: bool = True, **kwargs):
    """Lower a jax.jit'd callable with the given args.

    Returns (stablehlo_text, optimized_hlo_text_or_None). The optimized text
    is post-XLA-pipeline: fusion decisions, layout assignment, and collective
    lowering are all visible in it.
    """
    lowered = jitted.lower(*args, **kwargs)
    shlo = lowered.as_text()
    opt = None
    if optimized:
        try:
            opt = lowered.compile().as_text()
        except Exception as e:  # pragma: no cover - backend-specific
            opt = f"<optimized HLO unavailable: {e}>"
    return shlo, opt


def maybe_dump(name: str, jitted, args, kwargs=None) -> None:
    """If FLAGS_dump_hlo names a directory, write both artifacts there."""
    d = dump_dir()
    if not d:
        return
    try:
        os.makedirs(d, exist_ok=True)
        safe = re.sub(r"[^A-Za-z0-9_.-]", "_", name)
        _counter[0] += 1
        stem = os.path.join(d, f"{_counter[0]:03d}_{safe}")
        shlo, opt = lower_text(jitted, *args, **(kwargs or {}))
        with open(stem + ".stablehlo.txt", "w") as f:
            f.write(shlo)
        if opt is not None:
            with open(stem + ".optimized.txt", "w") as f:
                f.write(opt)
    except Exception as e:  # never break the training step for a dump
        import warnings

        warnings.warn(f"FLAGS_dump_hlo: dump of {name} failed: {e}")
