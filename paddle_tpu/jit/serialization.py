"""jit.save / jit.load (parity: python/paddle/jit/api.py:954 jit.save →
pdmodel/pdiparams).

TPU-native format: a directory with
  - ``<path>.pdiparams.npz``  — parameter/buffer arrays
  - ``<path>.pdmodel.json``   — structure metadata + input spec
  - ``<path>.stablehlo``      — (when an input_spec is given) the StableHLO
    text of the traced forward, the portable deployment artifact XLA serving
    stacks consume (maps the reference's inference program export).
Loading restores a callable that runs the compiled forward.
"""
from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

__all__ = ["save", "load"]


def save(layer, path: str, input_spec=None, **configs):
    from ..nn.layer.layers import Layer
    from .api import InputSpec, StaticFunction

    static_fn = None
    if isinstance(layer, StaticFunction):
        static_fn = layer
        layer = layer._layer
    if not isinstance(layer, Layer):
        raise TypeError("jit.save expects a Layer or to_static-wrapped Layer")

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    state = layer.state_dict()
    arrays = {k: np.asarray(v._value) for k, v in state.items()}
    np.savez(path + ".pdiparams.npz", **arrays)

    meta = {
        "format_version": 1,
        "layer_class": type(layer).__name__,
        "params": {k: {"shape": list(v.shape), "dtype": str(v.dtype)} for k, v in arrays.items()},
        "input_spec": None,
    }

    if input_spec:
        spec_meta = []
        for s in input_spec:
            if isinstance(s, InputSpec):
                spec_meta.append({"shape": s.shape, "dtype": str(s.dtype)})
            else:
                spec_meta.append({"shape": list(s.shape), "dtype": s.dtype.name if hasattr(s.dtype, "name") else str(s.dtype)})
        meta["input_spec"] = spec_meta
        # export StableHLO for the traced forward
        try:
            import jax
            import jax.numpy as jnp

            from ..framework.dtype import to_jax_dtype
            from ..tensor.tensor import Tensor
            from .api import StaticFunction as SF, _SwapValues, flatten_tensors, trace_state
            from ..autograd import tape

            params = list(layer.parameters()) + [b for b in layer.buffers() if b is not None]
            param_vals = [p._value for p in params]

            def fwd(pv, *xs):
                ctx = trace_state.TraceContext(jax.random.key(0))
                with trace_state.activate(ctx), _SwapValues(params, pv), tape.no_grad():
                    out = layer(*[Tensor(x) for x in xs])
                outs, _ = flatten_tensors(out)
                return tuple(t._value for t in outs)

            abstract = [
                jax.ShapeDtypeStruct(tuple(d if d is not None else 1 for d in sm["shape"]),
                                     to_jax_dtype(sm["dtype"].replace("paddle_tpu.", "")))
                for sm in spec_meta
            ]
            was_training = layer.training
            layer.eval()
            lowered = jax.jit(fwd).lower(param_vals, *abstract)
            with open(path + ".stablehlo", "w") as f:
                f.write(lowered.as_text())
            # runnable artifact: params baked, deserializable by jit.load /
            # the inference Predictor without the model class
            from jax import export as jexport

            exported = jexport.export(jax.jit(lambda *xs: fwd(param_vals, *xs)))(*abstract)
            with open(path + ".jaxexport", "wb") as f:
                f.write(exported.serialize())
            if was_training:
                layer.train()
        except Exception as e:  # export is best-effort; params always saved
            meta["stablehlo_error"] = str(e)

    with open(path + ".pdmodel.json", "w") as f:
        json.dump(meta, f, indent=1)


class LoadedLayer:
    """Inference callable restored by jit.load. When the save produced a
    ``.jaxexport`` artifact (input_spec given), calling runs the compiled
    forward directly — the load-and-run path (parity: AnalysisPredictor's
    load of __model__, analysis_predictor.h:105)."""

    def __init__(self, path: str):
        self._path = path
        with open(path + ".pdmodel.json") as f:
            self.meta = json.load(f)
        self._arrays = dict(np.load(path + ".pdiparams.npz"))
        self._exported = None
        if os.path.exists(path + ".jaxexport"):
            from jax import export as jexport

            with open(path + ".jaxexport", "rb") as f:
                self._exported = jexport.deserialize(bytearray(f.read()))

    def state_dict(self):
        from ..tensor.tensor import Tensor

        return {k: Tensor(v) for k, v in self._arrays.items()}

    def set_onto(self, layer):
        layer.set_state_dict(self.state_dict())
        return layer

    def __call__(self, *args, **kwargs):
        if self._exported is None:
            raise RuntimeError(
                "This artifact was saved without input_spec, so no compiled forward "
                "was exported. Rebuild the model class and call loaded.set_onto(model)."
            )
        from ..tensor.tensor import Tensor

        vals = [a._value if isinstance(a, Tensor) else a for a in args]
        out = self._exported.call(*vals)
        outs = [Tensor(o) for o in (out if isinstance(out, (tuple, list)) else [out])]
        return outs if len(outs) > 1 else outs[0]


def load(path: str, **configs) -> LoadedLayer:
    return LoadedLayer(path)
