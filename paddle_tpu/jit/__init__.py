"""paddle_tpu.jit (parity: python/paddle/jit)."""
from . import trace_state  # noqa: F401
from .api import InputSpec, StaticFunction, TrainStep, ignore_module, not_to_static, to_static  # noqa: F401
from .serialization import load, save  # noqa: F401

from .serialization import LoadedLayer as TranslatedLayer  # noqa: F401  (paddle name)


def enable_to_static(flag: bool = True):
    """Globally toggle to_static compilation (parity: jit.enable_to_static).
    When off, StaticFunction calls fall through to eager."""
    from . import api

    api._to_static_enabled = bool(flag)


def set_code_level(level=100, also_to_stdout=False):
    pass  # dy2static transformed-code dumping: no AST transform stage exists


def set_verbosity(level=0, also_to_stdout=False):
    pass
