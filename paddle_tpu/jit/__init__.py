"""paddle_tpu.jit (parity: python/paddle/jit)."""
from . import trace_state  # noqa: F401
from .api import InputSpec, StaticFunction, TrainStep, ignore_module, not_to_static, to_static  # noqa: F401
from .serialization import load, save  # noqa: F401
