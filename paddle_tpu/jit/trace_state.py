"""Trace context for to_static.

Solves the two eager↔trace impedance mismatches (SURVEY.md §7.3 "eager hooks
inside compiled graphs"):
- RNG: eager ops draw concrete threefry keys; inside a trace the key must be a
  traced *input* or every compiled call replays the same randomness. The ctx
  carries a traced base key; Generator.next_key folds a counter into it.
- Mutable buffers (BN running stats): eager code writes buffer._value; inside
  a trace that would leak tracers. Updates are registered here and returned as
  extra outputs of the compiled function, then written back concretely.
"""
from __future__ import annotations

import threading
from typing import Any, List, Optional, Tuple

import jax

_tls = threading.local()


class TraceContext:
    def __init__(self, base_key):
        self.base_key = base_key
        self._key_counter = 0
        self.buffer_updates: List[Tuple[Any, Any]] = []  # (buffer Tensor, traced new value)

    def next_key(self):
        self._key_counter += 1
        return jax.random.fold_in(self.base_key, self._key_counter)

    def register_buffer_update(self, buffer, new_value):
        # replace any previous pending update for the same buffer
        for i, (b, _) in enumerate(self.buffer_updates):
            if b is buffer:
                self.buffer_updates[i] = (buffer, new_value)
                return
        self.buffer_updates.append((buffer, new_value))


def current() -> Optional[TraceContext]:
    return getattr(_tls, "ctx", None)


class activate:
    def __init__(self, ctx: TraceContext):
        self.ctx = ctx

    def __enter__(self):
        self.prev = getattr(_tls, "ctx", None)
        _tls.ctx = self.ctx
        return self.ctx

    def __exit__(self, *exc):
        _tls.ctx = self.prev
        return False
