"""jit.to_static — trace-and-compile (parity: python/paddle/jit/api.py:197).

Capability mapping (SURVEY.md §3.3): the reference needs a PEP-523 bytecode
tracer (SOT) + PIR programs + an interpreter because Python is opaque to its
compiler. Here Python IS the tracer: the eager op layer runs unchanged on jax
tracers, so to_static = run the function under jax.jit with parameters,
buffers, RNG key, and inputs as traced arguments. The SOT guard discipline
(executor_cache.py guards) survives as the specialization cache key:
(input treedef, shapes, dtypes, training flag, amp state).

Backward: calling .backward() on outputs of a compiled forward executes a
second jitted function that recomputes forward + backward in one XLA program
(rematerialization — the TPU-favored memory/compute tradeoff). For peak
training throughput use paddle_tpu.jit.TrainStep, which compiles loss + grads
+ optimizer update into a single donated-buffer step.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..autograd import tape
from ..framework.random import default_generator
from ..tensor.tensor import Tensor
from . import trace_state

__all__ = ["to_static", "not_to_static", "StaticFunction", "ignore_module", "TrainStep", "InputSpec"]

# jit.enable_to_static(False) falls every StaticFunction back to eager
_to_static_enabled = True

# exceptions that mean "this Python is untraceable", not "user bug": the
# graph-break conditions of the reference's SOT (opcode_executor.py:1594)
_TRACE_BREAK_ERRORS = tuple(
    getattr(jax.errors, n)
    for n in (
        "TracerArrayConversionError",
        "TracerBoolConversionError",
        "TracerIntegerConversionError",
        "ConcretizationTypeError",
        "UnexpectedTracerError",
    )
    if hasattr(jax.errors, n)
)


class InputSpec:
    """paddle.static.InputSpec parity (shape with None for dynamic dims)."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient


# ---------------------------------------------------------------- tree utils
def flatten_tensors(obj) -> Tuple[List[Tensor], Any]:
    """Flatten nested (list/tuple/dict) structure, extracting Tensor leaves."""
    tensors: List[Tensor] = []

    def rec(o):
        if isinstance(o, Tensor):
            tensors.append(o)
            return ("__T__", len(tensors) - 1)
        if isinstance(o, (list, tuple)):
            return (type(o).__name__, [rec(x) for x in o])
        if isinstance(o, dict):
            return ("dict", {k: rec(v) for k, v in o.items()})
        return ("leaf", o)

    spec = rec(obj)
    return tensors, spec


def unflatten_tensors(spec, tensors: List):
    kind, payload = spec
    if kind == "__T__":
        return tensors[payload]
    if kind == "list":
        return [unflatten_tensors(s, tensors) for s in payload]
    if kind == "tuple":
        return tuple(unflatten_tensors(s, tensors) for s in payload)
    if kind == "dict":
        return {k: unflatten_tensors(v, tensors) for k, v in payload.items()}
    return payload


def _spec_signature(spec) -> Any:
    """Hashable structural signature of a flatten spec."""
    kind, payload = spec
    if kind == "__T__":
        return ("T", payload)
    if kind in ("list", "tuple"):
        return (kind, tuple(_spec_signature(s) for s in payload))
    if kind == "dict":
        return ("dict", tuple(sorted((k, _spec_signature(v)) for k, v in payload.items())))
    try:
        hash(payload)
        return ("leaf", payload)
    except TypeError:
        return ("leaf", repr(payload))


class _SwapValues:
    """Temporarily swap Tensor payloads for tracers during tracing."""

    def __init__(self, tensors: List[Tensor], values):
        self.tensors = tensors
        self.values = values

    def __enter__(self):
        self.saved = [t._value for t in self.tensors]
        for t, v in zip(self.tensors, self.values):
            t._value = v
        return self

    def __exit__(self, *exc):
        for t, v in zip(self.tensors, self.saved):
            t._value = v
        return False


class StaticFunction:
    def __init__(self, function: Callable, input_spec=None, build_strategy=None, backend=None,
                 full_graph=False, donate_state=False, bucket_dynamic_batch=False,
                 state_layer=None):
        from ..nn.layer.layers import Layer

        # state_layer: trace this Layer's params/buffers as state even though
        # ``function`` is a plain callable (closures over a model, e.g. the
        # compiled decode loop in models/generation.py)
        self._layer: Optional[Layer] = state_layer
        if isinstance(function, Layer):
            self._layer = function
            self._fn = function.forward
        elif hasattr(function, "__self__") and isinstance(getattr(function, "__self__", None), Layer):
            self._layer = function.__self__
            self._fn = function
        else:
            self._fn = function
        self._input_spec = input_spec
        self._bucket_dynamic_batch = bucket_dynamic_batch
        self._cache: Dict[Any, Any] = {}
        # guard keys whose trace failed: calls fall back to eager (the SOT
        # graph-break analog, reference opcode_executor.py:1594 resume-eager)
        self._fallback_keys: set = set()
        self._full_graph = full_graph
        self._warned_fallback = False
        functools.update_wrapper(self, function if callable(function) else self._fn)

    # paddle surface
    @property
    def concrete_program(self):
        return None

    def _state_tensors(self) -> List[Tensor]:
        if self._layer is None:
            return []
        out = list(self._layer.parameters())
        out += [b for b in self._layer.buffers() if b is not None]
        return out

    def _guards(self, arg_tensors, spec, training):
        from ..amp.auto_cast import amp_state

        st = amp_state()
        return (
            _spec_signature(spec),
            tuple((tuple(t._value.shape), str(t._value.dtype), t.stop_gradient) for t in arg_tensors),
            training,
            (st.enabled, st.dtype, st.level),
            tape.grad_enabled(),
        )

    def _build(self, spec, n_state, n_args, training):
        fn = self._fn
        state_tensors = self._state_tensors()
        meta = {}

        def functional(rng_key, flat_vals):
            state_vals = flat_vals[:n_state]
            arg_vals = flat_vals[n_state:]
            ctx = trace_state.TraceContext(rng_key)
            arg_tensors = [Tensor(v, stop_gradient=False) for v in arg_vals]
            with trace_state.activate(ctx), _SwapValues(state_tensors, state_vals):
                args, kwargs = unflatten_tensors(spec, arg_tensors)
                with tape.no_grad():
                    out = fn(*args, **kwargs)
                out_tensors, out_spec = flatten_tensors(out)
                meta["out_spec"] = out_spec
                meta["updated_buffers"] = [b for b, _ in ctx.buffer_updates]
                buf_vals = tuple(v for _, v in ctx.buffer_updates)
                return tuple(t._value for t in out_tensors) + buf_vals

        jit_fwd = jax.jit(functional)

        def fwd_bwd(rng_key, flat_vals, cotangents):
            outs, vjp_fn = jax.vjp(lambda fv: functional(rng_key, fv), list(flat_vals))
            (grads,) = vjp_fn(cotangents)
            return grads

        jit_bwd = jax.jit(fwd_bwd)
        return {"fwd": jit_fwd, "bwd": jit_bwd, "meta": meta}

    # -------------------------------------------- dynamic-dim bucket policy
    def _dynamic_batch_dims(self):
        """Arg indices whose InputSpec marks dim 0 dynamic (None/-1).

        Policy for SURVEY §7.3's dynamic-shape hard part: with
        ``bucket_dynamic_batch=True`` the batch dim is zero-padded to the
        next power of two and batch-mapped outputs sliced back, bounding the
        compile cache to O(log max_batch) entries instead of one per batch
        size. OPT-IN because padding asserts batch-row independence: models
        with cross-batch coupling (train-mode BatchNorm, in-graph
        mean-over-batch losses) would see the zero rows, and every output
        whose LEADING dim equals the padded batch is treated as batch-major
        and sliced (an aux output that coincidentally matches is truncated).
        Without the flag, dynamic dims compile per exact shape — always
        correct."""
        if not self._input_spec or not self._bucket_dynamic_batch:
            return None
        dyn = []
        for i, s in enumerate(self._input_spec):
            if isinstance(s, InputSpec) and s.shape and s.shape[0] in (None, -1):
                dyn.append(i)
        return dyn or None

    @staticmethod
    def _bucket(n: int) -> int:
        b = 1
        while b < n:
            b <<= 1
        return b

    def _run_segmented(self, args, kwargs):
        """Graph-break execution: record ops lazily, compile one segment per
        host-read boundary (jit.lazy_segments)."""
        from . import lazy_segments
        from .hlo_dump import dump_dir

        name = getattr(self._fn, "__name__", "fn")
        out, nseg = lazy_segments.run_segmented(
            self._fn, args, kwargs, name=name,
            dump_name=f"to_static_{name}" if dump_dir() else None)
        self.last_segment_count = nseg
        return out

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled:
            return self._fn(*args, **kwargs)  # jit.enable_to_static(False)
        from ..ops import dispatch as _dispatch

        if _dispatch._lazy_ctx is not None:
            # called from inside a segmented (graph-broken) outer function:
            # inline — our ops record into the OUTER segment; invoking the
            # compiled entry would hand it pending abstract values
            return self._fn(*args, **kwargs)
        training = self._layer.training if self._layer is not None else True
        arg_tensors, spec = flatten_tensors((args, kwargs))

        dyn = self._dynamic_batch_dims()
        real_n = None
        if dyn and not kwargs and len(args) >= len(self._input_spec):
            real_n = int(arg_tensors[dyn[0]]._value.shape[0])
            bucket = self._bucket(real_n)
            if bucket != real_n:
                padded = []
                for i, t in enumerate(arg_tensors):
                    if i in dyn:
                        v = t._value
                        pad = [(0, bucket - v.shape[0])] + [(0, 0)] * (v.ndim - 1)
                        pt = Tensor(jnp.pad(v, pad), stop_gradient=t.stop_gradient)
                        padded.append(pt)
                    else:
                        padded.append(t)
                arg_tensors = padded
            else:
                real_n = None  # exact bucket: nothing to slice back

        state_tensors = self._state_tensors()
        key = self._guards(arg_tensors, spec, training)
        if key in self._fallback_keys:
            return self._run_segmented(args, kwargs)  # cached graph-break
        entry = self._cache.get(key)
        n_state = len(state_tensors)
        new_entry = entry is None
        if new_entry:
            entry = self._build(spec, n_state, len(arg_tensors), training)
            self._cache[key] = entry
        all_tensors = state_tensors + arg_tensors
        flat_vals = tuple(t._value for t in all_tensors)
        rng_key = default_generator().next_key()

        if new_entry:
            from .hlo_dump import dump_dir, maybe_dump

            if dump_dir():
                maybe_dump(f"to_static_{getattr(self._fn, '__name__', 'fn')}",
                           entry["fwd"], (rng_key, flat_vals))
        try:
            raw_outs = entry["fwd"](rng_key, flat_vals)
        except _TRACE_BREAK_ERRORS as e:
            # graph break: the function does data-dependent Python (e.g.
            # .numpy()/bool() on a traced value). Switch this specialization
            # to SEGMENTED execution — ops before each host read compile as
            # one program, the read runs on the materialized value, and the
            # ops after form the next compiled segment (the SOT
            # split-at-the-failing-op contract, opcode_executor.py:1594,
            # without a bytecode interpreter). full_graph=True keeps the
            # reference's strict mode.
            if self._full_graph:
                raise
            self._fallback_keys.add(key)
            self._cache.pop(key, None)
            if not self._warned_fallback:
                self._warned_fallback = True
                import warnings

                name = getattr(self._fn, "__name__", "fn")
                warnings.warn(
                    f"to_static({name}): graph break "
                    f"({type(e).__name__}); splitting this input signature "
                    "into compiled segments at host reads. Pass "
                    "full_graph=True to error instead.")
            return self._run_segmented(args, kwargs)
        meta = entry["meta"]
        out_spec = meta["out_spec"]
        updated_buffers = meta["updated_buffers"]
        n_real = len(raw_outs) - len(updated_buffers)

        # write back buffer updates (concrete device arrays)
        for b, v in zip(updated_buffers, raw_outs[n_real:]):
            b._value = v

        needs_grad = tape.grad_enabled() and any(not t.stop_gradient for t in all_tensors)
        out_vals = list(raw_outs[:n_real])
        if needs_grad:
            jit_bwd = entry["bwd"]
            n_outs_total = len(raw_outs)
            out_metas = [jax.ShapeDtypeStruct(jnp.shape(o), jnp.result_type(o)) for o in raw_outs]

            def vjp_fn(cots):
                cot_seq = list(cots) if isinstance(cots, tuple) else [cots]
                # pad zero cotangents for the buffer-update outputs
                cot_full = tuple(cot_seq) + tuple(
                    jnp.zeros(m.shape, m.dtype) for m in out_metas[n_real:]
                )
                grads = jit_bwd(rng_key, flat_vals, cot_full)
                return tuple(grads)

            def primal_fn(*vals, _fwd=entry["fwd"], _key=rng_key, _n=n_real):
                return list(_fwd(_key, list(vals))[:_n])

            node = tape.GradNode(vjp_fn, all_tensors, out_vals, name="to_static",
                                 fn=primal_fn)
            out_tensors = []
            for i, v in enumerate(out_vals):
                t = Tensor(v, stop_gradient=False)
                t._grad_node = node
                t._out_index = i
                out_tensors.append(t)
        else:
            out_tensors = [Tensor(v, stop_gradient=True) for v in out_vals]
        if real_n is not None:
            # slice padded batch rows back off every output that carries
            # them — through the tape, so cotangents zero-pad on backward
            from ..ops.dispatch import apply as _apply

            bucket = arg_tensors[dyn[0]]._value.shape[0]
            out_tensors = [
                _apply(lambda v, _n=real_n: v[:_n], t, op_name="unbucket_slice")
                if t._value.ndim >= 1 and t._value.shape[0] == bucket else t
                for t in out_tensors
            ]
        return unflatten_tensors(out_spec, out_tensors)


def to_static(function=None, input_spec=None, build_strategy=None, backend=None, **kwargs):
    """Decorator/wrapper parity with paddle.jit.to_static.

    ``full_graph=False`` (default, matching the reference's SOT mode) falls
    back to eager per input-signature on untraceable Python (graph break);
    ``full_graph=True`` raises instead (the reference's strict AST mode)."""

    def decorate(fn):
        return StaticFunction(fn, input_spec=input_spec, build_strategy=build_strategy,
                              backend=backend,
                              full_graph=kwargs.get("full_graph", False),
                              bucket_dynamic_batch=kwargs.get("bucket_dynamic_batch", False),
                              state_layer=kwargs.get("state_layer"))

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn=None):
    if fn is None:
        return lambda f: f
    return fn


def ignore_module(modules):
    return None


class TrainStep:
    """Whole-training-step compilation — the TPU-idiomatic hot path.

    Compiles loss_fn(model(x), y) + grads + THE FRAMEWORK'S OWN optimizer
    update (``Optimizer._update_param`` for all ten optimizers, param groups,
    grad clip, ``multi_precision`` fp32 master weights) into ONE XLA program
    with donated parameter/optimizer buffers.  The optimizer's accumulators
    are materialized up front (``_ensure_state``) and threaded through the
    compiled step as a pytree, so eager ``state_dict()``/checkpointing always
    sees the live state.  LR schedulers are evaluated host-side per call and
    enter the graph as a traced scalar.  Pass a ``paddle_tpu.amp.GradScaler``
    to get fp16-style dynamic loss scaling with the found-inf skip executed
    *inside* the compiled step (no per-step host sync).

    Reference anchor: python/paddle/optimizer/optimizer.py:125 (_create_
    accumulators / master-weight semantics), amp/grad_scaler.py.
    """

    def __init__(self, model, loss_fn, optimizer, donate: bool = True, scaler=None):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.scaler = scaler if (scaler is not None and scaler.is_enable()) else None
        self._params = list(model.parameters())
        self._buffers = [b for b in model.buffers() if b is not None]
        optimizer._ensure_state()
        self._pid2idx = {id(p): i for i, p in enumerate(self._params)}
        self._compiled = None
        self._multi_cache: Dict[Any, Any] = {}
        self._step_raw = None
        self._donate = donate

    # -------------------------------------------------- state pytree helpers
    def _get_opt_state(self):
        opt = self.optimizer
        accs = {
            name: {self._pid2idx[pid]: v for pid, v in d.items() if pid in self._pid2idx}
            for name, d in opt._accumulators.items()
        }
        masters = {self._pid2idx[pid]: v
                   for pid, v in opt._master_weights.items() if pid in self._pid2idx}
        return accs, masters

    def _put_opt_state(self, accs, masters):
        opt = self.optimizer
        for name, d in accs.items():
            for i, v in d.items():
                opt._accumulators[name][id(self._params[i])] = v
        for i, v in masters.items():
            opt._master_weights[id(self._params[i])] = v

    def _scaler_state(self):
        s = self.scaler
        if s is None:
            return {}
        return {
            "scale": jnp.asarray(s._scale, jnp.float32),
            "good": jnp.asarray(s._good_steps, jnp.int32),
            "bad": jnp.asarray(s._bad_steps, jnp.int32),
        }

    # ------------------------------------------------------------- build
    def _build(self, batch_spec):
        model = self.model
        loss_fn = self.loss_fn
        buffers = self._buffers
        params = self._params
        opt = self.optimizer
        scaler = self.scaler

        def step(param_vals, accs, masters, buf_vals, scaler_state, rng_key, batch_vals, lr):
            # ---- forward + grads (scaled loss when a GradScaler is active)
            def loss_of(pv):
                ctx = trace_state.TraceContext(rng_key)
                batch_tensors = [Tensor(v, stop_gradient=True) for v in batch_vals]
                with trace_state.activate(ctx), _SwapValues(params, pv), _SwapValues(buffers, buf_vals):
                    with tape.no_grad():
                        args = unflatten_tensors(batch_spec, batch_tensors)
                        loss = loss_fn(model, *args)
                    new_bufs = {id(b): v for b, v in ctx.buffer_updates}
                    buf_out = [new_bufs.get(id(b), bv) for b, bv in zip(buffers, buf_vals)]
                lv = loss._value
                scaled = lv * scaler_state["scale"].astype(lv.dtype) if scaler else lv
                return scaled, (lv, buf_out)

            (_, (loss_val, buf_out)), grads = jax.value_and_grad(loss_of, has_aux=True)(
                list(param_vals)
            )

            found_inf = None
            if scaler:
                inv = (1.0 / scaler_state["scale"])
                grads = [g * inv.astype(g.dtype) for g in grads]
                nonfinite = sum(jnp.sum(~jnp.isfinite(g)) for g in grads)
                found_inf = nonfinite > 0

            # ZeRO stage >= 2: constrain grads to the sharding axis so XLA
            # emits reduce-scatter instead of all-reduce (auto_parallel
            # ShardingStage2/3.shard_grad)
            shard_grad = getattr(opt, "_shard_grad", None)
            if shard_grad is not None:
                grads = [shard_grad(p, g) for p, g in zip(params, grads)]

            # ---- optimizer update: trace the framework's own _update_param.
            # Install traced state into the optimizer's dicts for the duration
            # of the trace, then restore the concrete values.
            saved_accs = {name: dict(d) for name, d in opt._accumulators.items()}
            saved_masters = dict(opt._master_weights)
            self._put_opt_state(accs, masters)
            grad_of = {id(p): g for p, g in zip(params, grads)}
            try:
                with _SwapValues(params, list(param_vals)):
                    for group in opt._param_groups:
                        pg = [
                            (p, Tensor(grad_of[id(p)], stop_gradient=True))
                            for p in group["params"]
                            if id(p) in grad_of and p.trainable
                        ]
                        if opt._grad_clip is not None:
                            pg = opt._grad_clip(pg)
                        glr = lr * group.get("learning_rate", 1.0)
                        wd = group.get("weight_decay", opt._weight_decay)
                        wd = opt._parse_decay(wd) if not isinstance(wd, float) else wd
                        with tape.no_grad():
                            for p, g in pg:
                                gv = (
                                    g._value.astype(jnp.float32)
                                    if opt._multi_precision
                                    else g._value
                                )
                                opt._update_param(p, gv, glr, wd)
                    new_params = [p._value for p in params]
                new_accs = {
                    name: {i: opt._accumulators[name][id(params[i])] for i in accs[name]}
                    for name in accs
                }
                new_masters = {i: opt._master_weights[id(params[i])] for i in masters}
            finally:
                opt._accumulators.clear()
                opt._accumulators.update(
                    {name: dict(d) for name, d in saved_accs.items()}
                )
                opt._master_weights.clear()
                opt._master_weights.update(saved_masters)

            new_scaler_state = scaler_state
            if scaler:
                # skip the whole update when any grad is nonfinite
                keep = lambda new, old: jnp.where(found_inf, old, new)  # noqa: E731
                new_params = [keep(n, o) for n, o in zip(new_params, param_vals)]
                new_accs = jax.tree_util.tree_map(keep, new_accs, accs)
                new_masters = jax.tree_util.tree_map(keep, new_masters, masters)
                if scaler._dynamic:
                    scale = scaler_state["scale"]
                    bad = jnp.where(found_inf, scaler_state["bad"] + 1, 0)
                    good = jnp.where(found_inf, 0, scaler_state["good"] + 1)
                    dec = bad >= scaler._decr_every_n
                    scale = jnp.where(dec, jnp.maximum(scale * scaler._decr_ratio, 1.0), scale)
                    bad = jnp.where(dec, 0, bad)
                    inc = good >= scaler._incr_every_n_steps
                    scale = jnp.where(inc, scale * scaler._incr_ratio, scale)
                    good = jnp.where(inc, 0, good)
                    new_scaler_state = {"scale": scale, "good": good, "bad": bad}

            return loss_val, new_params, new_accs, new_masters, buf_out, new_scaler_state

        donate = (0, 1, 2, 3) if self._donate else ()
        self._step_raw = step
        return jax.jit(step, donate_argnums=donate)

    # ------------------------------------------------------------- call
    def __call__(self, *batch):
        batch_tensors, spec = flatten_tensors(batch)
        first_call = self._compiled is None
        if first_call:
            self._spec = spec
            self._spec_sig = _spec_signature(spec)
            self._compiled = self._build(spec)
        elif _spec_signature(spec) != self._spec_sig:
            raise ValueError(
                "TrainStep is specialized to the batch structure of its first "
                "call; build a new TrainStep for a different structure")
        batch_vals = tuple(t._value for t in batch_tensors)
        rng_key = default_generator().next_key()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        buf_vals = [b._value for b in self._buffers]
        accs, masters = self._get_opt_state()
        if first_call:
            from .hlo_dump import dump_dir, maybe_dump

            if dump_dir():
                maybe_dump("train_step", self._compiled,
                           ([p._value for p in self._params], accs, masters, buf_vals,
                            self._scaler_state(), rng_key, batch_vals, lr))
        loss, new_params, new_accs, new_masters, buf_out, new_scaler = self._compiled(
            [p._value for p in self._params], accs, masters, buf_vals,
            self._scaler_state(), rng_key, batch_vals, lr,
        )
        for p, v in zip(self._params, new_params):
            p._value = v
        self._put_opt_state(new_accs, new_masters)
        for b, v in zip(self._buffers, buf_out):
            b._value = v
        if self.scaler is not None and new_scaler:
            self.scaler._scale = new_scaler["scale"]
            self.scaler._good_steps = new_scaler["good"]
            self.scaler._bad_steps = new_scaler["bad"]
        self.optimizer._step_count += 1
        return Tensor(loss)

    def sync_to_model(self):
        """Params are written back after every step; kept for API compat."""
        return self.model

    # ------------------------------------------------------- multi-step scan
    def run_steps(self, *batch_stacks):
        """Run K optimizer steps in ONE compiled dispatch.

        Each tensor leaf in ``batch_stacks`` carries a leading dim K (one
        slice per step); the whole schedule executes as a ``lax.scan`` over
        that dim, so per-dispatch host/marshalling overhead is paid once per
        K steps instead of per step (decisive for models with many small
        parameter tensors, and for remote/tunneled accelerators). Returns the
        per-step losses as a [K] tensor. The learning rate is evaluated once
        and held constant across the window (scheduler advances by K after).
        """
        batch_tensors, spec = flatten_tensors(batch_stacks)
        if not batch_tensors:
            raise ValueError("run_steps needs at least one tensor input")
        K = int(batch_tensors[0]._value.shape[0])
        spec_sig = _spec_signature(spec)
        if self._compiled is None:
            # build the single-step program for this batch structure (the
            # stacked spec has the same TREE as the per-step spec)
            self._spec = spec
            self._spec_sig = spec_sig
            self._compiled = self._build(spec)
        elif spec_sig != self._spec_sig:
            raise ValueError(
                "TrainStep is specialized to the batch structure of its first "
                "call; build a new TrainStep for a different structure")
        multi = self._multi_cache.get(spec_sig)
        if multi is None:
            step_raw = self._step_raw

            def multi_fn(param_vals, accs, masters, buf_vals, scaler_state,
                         base_key, batch_stack_vals, lr):
                # K comes from the stack itself (jit retraces per shape), so
                # the structure-keyed cache serves any window length
                n_steps = batch_stack_vals[0].shape[0]

                def body(carry, xs):
                    pv, ac, ms, bv, ss = carry
                    i, batch_vals = xs
                    key = jax.random.fold_in(base_key, i)
                    loss, pv, ac, ms, bv, ss = step_raw(
                        pv, ac, ms, bv, ss, key, batch_vals, lr)
                    return (pv, ac, ms, bv, ss), loss

                carry0 = (list(param_vals), accs, masters, list(buf_vals),
                          scaler_state)
                (pv, ac, ms, bv, ss), losses = jax.lax.scan(
                    body, carry0, (jnp.arange(n_steps), tuple(batch_stack_vals)))
                return losses, pv, ac, ms, bv, ss

            donate = (0, 1, 2, 3) if self._donate else ()
            multi = jax.jit(multi_fn, donate_argnums=donate)
            self._multi_cache[spec_sig] = multi

        batch_vals = tuple(t._value for t in batch_tensors)
        base_key = default_generator().next_key()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        accs, masters = self._get_opt_state()
        losses, new_params, new_accs, new_masters, buf_out, new_scaler = multi(
            [p._value for p in self._params], accs, masters,
            [b._value for b in self._buffers], self._scaler_state(),
            base_key, batch_vals, lr,
        )
        for p, v in zip(self._params, new_params):
            p._value = v
        self._put_opt_state(new_accs, new_masters)
        for b, v in zip(self._buffers, buf_out):
            b._value = v
        if self.scaler is not None and new_scaler:
            self.scaler._scale = new_scaler["scale"]
            self.scaler._good_steps = new_scaler["good"]
            self.scaler._bad_steps = new_scaler["bad"]
        self.optimizer._step_count += K
        return Tensor(losses)
