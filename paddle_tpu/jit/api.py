"""jit.to_static — trace-and-compile (parity: python/paddle/jit/api.py:197).

Capability mapping (SURVEY.md §3.3): the reference needs a PEP-523 bytecode
tracer (SOT) + PIR programs + an interpreter because Python is opaque to its
compiler. Here Python IS the tracer: the eager op layer runs unchanged on jax
tracers, so to_static = run the function under jax.jit with parameters,
buffers, RNG key, and inputs as traced arguments. The SOT guard discipline
(executor_cache.py guards) survives as the specialization cache key:
(input treedef, shapes, dtypes, training flag, amp state).

Backward: calling .backward() on outputs of a compiled forward executes a
second jitted function that recomputes forward + backward in one XLA program
(rematerialization — the TPU-favored memory/compute tradeoff). For peak
training throughput use paddle_tpu.jit.TrainStep, which compiles loss + grads
+ optimizer update into a single donated-buffer step.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..autograd import tape
from ..framework.random import default_generator
from ..tensor.tensor import Tensor
from . import trace_state

__all__ = ["to_static", "not_to_static", "StaticFunction", "ignore_module", "TrainStep", "InputSpec"]


class InputSpec:
    """paddle.static.InputSpec parity (shape with None for dynamic dims)."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient


# ---------------------------------------------------------------- tree utils
def flatten_tensors(obj) -> Tuple[List[Tensor], Any]:
    """Flatten nested (list/tuple/dict) structure, extracting Tensor leaves."""
    tensors: List[Tensor] = []

    def rec(o):
        if isinstance(o, Tensor):
            tensors.append(o)
            return ("__T__", len(tensors) - 1)
        if isinstance(o, (list, tuple)):
            return (type(o).__name__, [rec(x) for x in o])
        if isinstance(o, dict):
            return ("dict", {k: rec(v) for k, v in o.items()})
        return ("leaf", o)

    spec = rec(obj)
    return tensors, spec


def unflatten_tensors(spec, tensors: List):
    kind, payload = spec
    if kind == "__T__":
        return tensors[payload]
    if kind == "list":
        return [unflatten_tensors(s, tensors) for s in payload]
    if kind == "tuple":
        return tuple(unflatten_tensors(s, tensors) for s in payload)
    if kind == "dict":
        return {k: unflatten_tensors(v, tensors) for k, v in payload.items()}
    return payload


def _spec_signature(spec) -> Any:
    """Hashable structural signature of a flatten spec."""
    kind, payload = spec
    if kind == "__T__":
        return ("T", payload)
    if kind in ("list", "tuple"):
        return (kind, tuple(_spec_signature(s) for s in payload))
    if kind == "dict":
        return ("dict", tuple(sorted((k, _spec_signature(v)) for k, v in payload.items())))
    try:
        hash(payload)
        return ("leaf", payload)
    except TypeError:
        return ("leaf", repr(payload))


class _SwapValues:
    """Temporarily swap Tensor payloads for tracers during tracing."""

    def __init__(self, tensors: List[Tensor], values):
        self.tensors = tensors
        self.values = values

    def __enter__(self):
        self.saved = [t._value for t in self.tensors]
        for t, v in zip(self.tensors, self.values):
            t._value = v
        return self

    def __exit__(self, *exc):
        for t, v in zip(self.tensors, self.saved):
            t._value = v
        return False


class StaticFunction:
    def __init__(self, function: Callable, input_spec=None, build_strategy=None, backend=None,
                 full_graph=True, donate_state=False):
        from ..nn.layer.layers import Layer

        self._layer: Optional[Layer] = None
        if isinstance(function, Layer):
            self._layer = function
            self._fn = function.forward
        elif hasattr(function, "__self__") and isinstance(getattr(function, "__self__", None), Layer):
            self._layer = function.__self__
            self._fn = function
        else:
            self._fn = function
        self._input_spec = input_spec
        self._cache: Dict[Any, Any] = {}
        functools.update_wrapper(self, function if callable(function) else self._fn)

    # paddle surface
    @property
    def concrete_program(self):
        return None

    def _state_tensors(self) -> List[Tensor]:
        if self._layer is None:
            return []
        out = list(self._layer.parameters())
        out += [b for b in self._layer.buffers() if b is not None]
        return out

    def _guards(self, arg_tensors, spec, training):
        from ..amp.auto_cast import amp_state

        st = amp_state()
        return (
            _spec_signature(spec),
            tuple((tuple(t._value.shape), str(t._value.dtype), t.stop_gradient) for t in arg_tensors),
            training,
            (st.enabled, st.dtype, st.level),
            tape.grad_enabled(),
        )

    def _build(self, spec, n_state, n_args, training):
        fn = self._fn
        state_tensors = self._state_tensors()
        meta = {}

        def functional(rng_key, flat_vals):
            state_vals = flat_vals[:n_state]
            arg_vals = flat_vals[n_state:]
            ctx = trace_state.TraceContext(rng_key)
            arg_tensors = [Tensor(v, stop_gradient=False) for v in arg_vals]
            with trace_state.activate(ctx), _SwapValues(state_tensors, state_vals):
                args, kwargs = unflatten_tensors(spec, arg_tensors)
                with tape.no_grad():
                    out = fn(*args, **kwargs)
                out_tensors, out_spec = flatten_tensors(out)
                meta["out_spec"] = out_spec
                meta["updated_buffers"] = [b for b, _ in ctx.buffer_updates]
                buf_vals = tuple(v for _, v in ctx.buffer_updates)
                return tuple(t._value for t in out_tensors) + buf_vals

        jit_fwd = jax.jit(functional)

        def fwd_bwd(rng_key, flat_vals, cotangents):
            outs, vjp_fn = jax.vjp(lambda fv: functional(rng_key, fv), list(flat_vals))
            (grads,) = vjp_fn(cotangents)
            return grads

        jit_bwd = jax.jit(fwd_bwd)
        return {"fwd": jit_fwd, "bwd": jit_bwd, "meta": meta}

    def __call__(self, *args, **kwargs):
        training = self._layer.training if self._layer is not None else True
        arg_tensors, spec = flatten_tensors((args, kwargs))
        state_tensors = self._state_tensors()
        key = self._guards(arg_tensors, spec, training)
        entry = self._cache.get(key)
        n_state = len(state_tensors)
        if entry is None:
            entry = self._build(spec, n_state, len(arg_tensors), training)
            self._cache[key] = entry
        all_tensors = state_tensors + arg_tensors
        flat_vals = tuple(t._value for t in all_tensors)
        rng_key = default_generator().next_key()

        raw_outs = entry["fwd"](rng_key, flat_vals)
        meta = entry["meta"]
        out_spec = meta["out_spec"]
        updated_buffers = meta["updated_buffers"]
        n_real = len(raw_outs) - len(updated_buffers)

        # write back buffer updates (concrete device arrays)
        for b, v in zip(updated_buffers, raw_outs[n_real:]):
            b._value = v

        needs_grad = tape.grad_enabled() and any(not t.stop_gradient for t in all_tensors)
        out_vals = list(raw_outs[:n_real])
        if needs_grad:
            jit_bwd = entry["bwd"]
            n_outs_total = len(raw_outs)
            out_metas = [jax.ShapeDtypeStruct(jnp.shape(o), jnp.result_type(o)) for o in raw_outs]

            def vjp_fn(cots):
                cot_seq = list(cots) if isinstance(cots, tuple) else [cots]
                # pad zero cotangents for the buffer-update outputs
                cot_full = tuple(cot_seq) + tuple(
                    jnp.zeros(m.shape, m.dtype) for m in out_metas[n_real:]
                )
                grads = jit_bwd(rng_key, flat_vals, cot_full)
                return tuple(grads)

            node = tape.GradNode(vjp_fn, all_tensors, out_vals, name="to_static")
            out_tensors = []
            for i, v in enumerate(out_vals):
                t = Tensor(v, stop_gradient=False)
                t._grad_node = node
                t._out_index = i
                out_tensors.append(t)
        else:
            out_tensors = [Tensor(v, stop_gradient=True) for v in out_vals]
        return unflatten_tensors(out_spec, out_tensors)


def to_static(function=None, input_spec=None, build_strategy=None, backend=None, **kwargs):
    """Decorator/wrapper parity with paddle.jit.to_static."""

    def decorate(fn):
        return StaticFunction(fn, input_spec=input_spec, build_strategy=build_strategy, backend=backend)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn=None):
    if fn is None:
        return lambda f: f
    return fn


def ignore_module(modules):
    return None


class TrainStep:
    """Whole-training-step compilation — the TPU-idiomatic hot path.

    Compiles loss_fn(model(x), y) + grads + optimizer update into ONE XLA
    program with donated parameter/optimizer buffers. The eager Optimizer's
    hyperparameters are mapped onto an optax transform (optax is the
    functional optimizer library of the jax ecosystem); state lives on-device
    between steps. ``sync_to_model()`` writes params back into the Layer for
    checkpointing/eval interop.
    """

    def __init__(self, model, loss_fn, optimizer, donate: bool = True):
        import optax

        from ..optimizer.optimizers import SGD, Adam, AdamW, Momentum

        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self._params = list(model.parameters())
        self._buffers = [b for b in model.buffers() if b is not None]
        lr = optimizer.get_lr()
        self._lr_is_sched = not isinstance(optimizer._learning_rate, (int, float))
        if isinstance(optimizer, AdamW):
            self._tx = optax.adamw(self._lr_fn, b1=optimizer._beta1, b2=optimizer._beta2,
                                   eps=optimizer._epsilon, weight_decay=optimizer._wd)
        elif isinstance(optimizer, Adam):
            self._tx = optax.adam(self._lr_fn, b1=optimizer._beta1, b2=optimizer._beta2,
                                  eps=optimizer._epsilon)
        elif isinstance(optimizer, Momentum):
            self._tx = optax.sgd(self._lr_fn, momentum=optimizer._momentum,
                                 nesterov=optimizer._nesterov)
        elif isinstance(optimizer, SGD):
            self._tx = optax.sgd(self._lr_fn)
        else:
            raise NotImplementedError(f"TrainStep does not support {type(optimizer).__name__} yet")
        grad_clip = optimizer._grad_clip
        if grad_clip is not None:
            from ..nn.clip import ClipGradByGlobalNorm, ClipGradByNorm

            if isinstance(grad_clip, ClipGradByGlobalNorm):
                self._tx = optax.chain(optax.clip_by_global_norm(grad_clip.clip_norm), self._tx)
            elif isinstance(grad_clip, ClipGradByNorm):
                self._tx = optax.chain(optax.clip(grad_clip.clip_norm), self._tx)
        self._param_vals = [p._value for p in self._params]
        self._opt_state = self._tx.init(self._param_vals)
        self._step_i = jnp.zeros((), jnp.int32)
        self._compiled = None
        self._donate = donate

    def _lr_fn(self, count):
        opt = self.optimizer
        if isinstance(opt._learning_rate, (int, float)):
            return opt._learning_rate
        # LRScheduler: evaluate python-side per step; traced as a jnp scalar input
        return self._current_lr

    def _build(self, batch_spec):
        model = self.model
        loss_fn = self.loss_fn
        buffers = self._buffers
        params = self._params
        tx = self._tx

        def step(param_vals, opt_state, buf_vals, rng_key, batch_vals, lr):
            self._current_lr = lr  # read by _lr_fn during trace

            def loss_of(pv):
                ctx = trace_state.TraceContext(rng_key)
                batch_tensors = [Tensor(v, stop_gradient=True) for v in batch_vals]
                with trace_state.activate(ctx), _SwapValues(params, pv), _SwapValues(buffers, buf_vals):
                    with tape.no_grad():
                        args = unflatten_tensors(batch_spec, batch_tensors)
                        loss = loss_fn(model, *args)
                    new_bufs = {id(b): v for b, v in ctx.buffer_updates}
                    buf_out = [new_bufs.get(id(b), bv) for b, bv in zip(buffers, buf_vals)]
                return loss._value, buf_out

            (loss_val, buf_out), grads = jax.value_and_grad(loss_of, has_aux=True)(list(param_vals))
            updates, new_opt_state = tx.update(grads, opt_state, list(param_vals))
            import optax

            new_params = optax.apply_updates(list(param_vals), updates)
            return loss_val, new_params, new_opt_state, buf_out

        donate = (0, 1, 2) if self._donate else ()
        return jax.jit(step, donate_argnums=donate)

    def __call__(self, *batch):
        batch_tensors, spec = flatten_tensors(batch)
        if self._compiled is None:
            self._spec = spec
            self._compiled = self._build(spec)
        batch_vals = tuple(t._value for t in batch_tensors)
        rng_key = default_generator().next_key()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        buf_vals = [b._value for b in self._buffers]
        loss, self._param_vals, self._opt_state, buf_out = self._compiled(
            self._param_vals, self._opt_state, buf_vals, rng_key, batch_vals, lr
        )
        for b, v in zip(self._buffers, buf_out):
            b._value = v
        self.optimizer._step_count += 1
        return Tensor(loss)

    def sync_to_model(self):
        for p, v in zip(self._params, self._param_vals):
            p._value = v
        return self.model
