"""paddle.signal parity (/root/reference/python/paddle/signal.py: stft/istft).

Framing + windowed (r)fft through the tape — shares conventions with
audio.features; istft reconstructs by weighted overlap-add with the
window-power normalization (COLA)."""
from __future__ import annotations

from typing import Optional

import numpy as np

import jax.numpy as jnp

from .ops.dispatch import apply
from .tensor.tensor import Tensor

__all__ = ["stft", "istft"]


def _t(x) -> Tensor:
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def stft(x, n_fft: int, hop_length: Optional[int] = None,
         win_length: Optional[int] = None, window=None, center: bool = True,
         pad_mode: str = "reflect", normalized: bool = False,
         onesided: bool = True, name=None):
    """x [..., T] -> complex [..., n_fft//2+1 (or n_fft), frames]."""
    x = _t(x)
    hop = hop_length or n_fft // 4
    wl = win_length or n_fft
    if window is None:
        wv = jnp.ones((wl,), jnp.float32)
    else:
        wv = _t(window)._value.astype(jnp.float32)
    if wl < n_fft:
        lpad = (n_fft - wl) // 2
        wv = jnp.pad(wv, (lpad, n_fft - wl - lpad))
    win = Tensor(wv)

    def f(v, w):
        if center:
            padc = [(0, 0)] * (v.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            v = jnp.pad(v, padc, mode="reflect" if pad_mode == "reflect" else "constant")
        T = v.shape[-1]
        n_frames = 1 + (T - n_fft) // hop
        starts = jnp.arange(n_frames) * hop
        idx = starts[:, None] + jnp.arange(n_fft)[None, :]
        frames = v[..., idx] * w
        if onesided and not jnp.iscomplexobj(v):
            spec = jnp.fft.rfft(frames, axis=-1)
        else:
            spec = jnp.fft.fft(frames, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return jnp.swapaxes(spec, -1, -2)  # [..., bins, frames]

    return apply(f, x, win, op_name="stft")


def istft(x, n_fft: int, hop_length: Optional[int] = None,
          win_length: Optional[int] = None, window=None, center: bool = True,
          normalized: bool = False, onesided: bool = True,
          length: Optional[int] = None, return_complex: bool = False, name=None):
    """Inverse STFT by weighted overlap-add. x: [..., bins, frames]."""
    x = _t(x)
    hop = hop_length or n_fft // 4
    wl = win_length or n_fft
    if window is None:
        wv = jnp.ones((wl,), jnp.float32)
    else:
        wv = _t(window)._value.astype(jnp.float32)
    if wl < n_fft:
        lpad = (n_fft - wl) // 2
        wv = jnp.pad(wv, (lpad, n_fft - wl - lpad))
    win = Tensor(wv)

    def f(spec, w):
        spec = jnp.swapaxes(spec, -1, -2)  # [..., frames, bins]
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        if onesided:
            frames = jnp.fft.irfft(spec, n=n_fft, axis=-1)
        else:
            frames = jnp.fft.ifft(spec, axis=-1)
            if not return_complex:
                frames = frames.real
        frames = frames * w
        n_frames = frames.shape[-2]
        T = n_fft + hop * (n_frames - 1)
        lead = frames.shape[:-2]
        out = jnp.zeros(lead + (T,), frames.dtype)
        wsum = jnp.zeros((T,), jnp.float32)
        idx = jnp.arange(n_frames)[:, None] * hop + jnp.arange(n_fft)[None, :]
        flat_idx = idx.reshape(-1)
        out = out.reshape((-1, T)).at[:, flat_idx].add(
            frames.reshape((-1, n_frames * n_fft))).reshape(lead + (T,))
        wsum = wsum.at[flat_idx].add(jnp.tile(w * w, n_frames))
        out = out / jnp.maximum(wsum, 1e-10)
        if center:
            out = out[..., n_fft // 2:]
        if length is not None:
            out = out[..., :length]
        elif center:
            out = out[..., : T - n_fft]
        return out

    return apply(f, x, win, op_name="istft")
