"""Paged-KV attention core — the TPU-native equivalent of the reference's
serving attention kernel (reference:
/root/reference/python/paddle/incubate/nn/functional/block_multihead_attention.py:19,
kernel /root/reference/paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu).

Design (SURVEY §7.1: kernels collapse onto XLA):
- KV lives in a global pool of fixed-size blocks ``[num_blocks, KV, bs, D]``;
  a per-sequence ``block_tables [B, blocks_per_seq]`` maps logical positions
  to pool blocks — admission/eviction is host-side free-list bookkeeping, so
  sequences of different lengths share one compiled program.
- One step = (scatter this step's K/V into the pool) + (gather each
  sequence's blocks back) + (padded-batch masked attention). Scatter/gather
  are XLA dynamic-(update-)slice/gather ops that tile fine on TPU; attention
  is one fp32-softmax einsum chain the MXU eats. A hand-written Pallas paged
  kernel was deliberately NOT used: r4 measured XLA's einsum decode path at
  610-688 GB/s vs 299-366 for the Pallas small-M-dot kernel (PROFILE_r04.md).
- Everything is static-shape: the query side is a packed token buffer
  ``[T, ...]`` (mixed prefill+decode chunks), the key side is
  ``blocks_per_seq * block_size`` — both fixed by the serving engine, so
  admitting/retiring sequences never recompiles.

Supports the reference kernel's full surface: MHA/GQA, in-kernel rope
(neox + interleaved), per-sequence encoder/decoder lengths, mixed batches,
pre-caches (prompt-tuning prefix), int8 cache quantization (static +
dynamic), int32 qkv dequant (qkv_out_scale/qkv_bias), shift/smooth + int8
output quantization, additive encoder/decoder masks.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["blha_attention", "paged_gather_kv", "build_padding_metadata",
           "rope_rotate"]


def rope_rotate(x, cos, sin, neox: bool):
    """Shared rope rotation: x [..., H, D]; cos/sin broadcastable to
    [..., H|1, D/2]. neox=True rotates split halves, else interleaved
    even/odd pairs (the reference kernel's two styles). The single source of
    truth for every in-kernel rope site (paged attention,
    fused_multi_transformer)."""
    c = cos.astype(jnp.float32)
    s = sin.astype(jnp.float32)
    xf = x.astype(jnp.float32)
    if neox:
        x1, x2 = jnp.split(xf, 2, axis=-1)
        out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    else:
        x1 = xf[..., 0::2]
        x2 = xf[..., 1::2]
        out = jnp.stack([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1
                        ).reshape(xf.shape)
    return out.astype(x.dtype)


def _quantize_u8(x, scale, round_ties_away: bool, max_bound: float,
                 min_bound: float):
    """float -> uint8 cache storage: round(x*scale) clipped, biased by 128
    (dequant contract: (u8 - 128) * dequant_scale — the reference's cache
    int8 convention)."""
    v = x.astype(jnp.float32) * scale
    if round_ties_away:
        v = jnp.trunc(v + jnp.where(v >= 0, 0.5, -0.5))
    else:
        v = jnp.round(v)  # ties to even
    v = jnp.clip(v, min_bound, max_bound)
    return (v + 128.0).astype(jnp.uint8)


def paged_gather_kv(cache, block_tables):
    """cache [NB, KV, bs, D] + block_tables [B, P] -> [B, KV, P*bs, D].
    Out-of-range block ids (free slots marked -1) gather zeros."""
    nb = cache.shape[0]
    bt = jnp.where((block_tables < 0) | (block_tables >= nb), nb, block_tables)
    g = cache.at[bt].get(mode="fill", fill_value=0)  # [B, P, KV, bs, D]
    B, P, KV, bs, D = g.shape
    return jnp.transpose(g, (0, 2, 1, 3, 4)).reshape(B, KV, P * bs, D)


def build_padding_metadata(seq_lens_this_time):
    """Host-side helper mirroring the reference's get_padding_offset
    (test/legacy_test/test_block_multihead_attention.py:143): returns
    (padding_offsets, cum_offsets, cu_seqlens_q, cu_seqlens_k) as numpy."""
    import numpy as np

    lens = np.asarray(seq_lens_this_time).reshape(-1).astype(np.int64)
    bsz = lens.shape[0]
    max_len = int(lens.max()) if bsz else 0
    cum_offsets = np.zeros(bsz + 1, np.int32)
    cum_offsets[1:] = np.cumsum(max_len - lens)
    cu = np.zeros(bsz + 1, np.int32)
    cu[1:] = np.cumsum(lens)
    token_num = int(lens.sum())
    padding_offsets = np.zeros(token_num, np.int32)
    for i in range(bsz):
        padding_offsets[cu[i]:cu[i + 1]] = cum_offsets[i]
    return padding_offsets, cum_offsets[:-1], cu, cu.copy()


@partial(jax.jit, static_argnames=(
    "num_heads", "kv_num_heads", "head_dim", "block_size", "max_q_len",
    "use_neox_style", "cache_quant", "round_ties_away", "compute_dtype",
    "has_out_quant"))
def blha_attention(
    qkv,                       # [T, (H+2*KV)*D] float/bf16 (or int32 w/ qkv_out_scale)
    key_cache,                 # [NB, KV, bs, D] (uint8 when cache_quant)
    value_cache,
    seq_lens_encoder,          # [B] int32: >0 while the seq is in prefill
    seq_lens_decoder,          # [B] int32: tokens already in cache
    seq_lens_this_time,        # [B] int32: tokens this step (0 = inactive row)
    cu_seqlens_q,              # [B+1] int32: token-buffer offsets per seq
    block_tables,              # [B, P] int32 (-1 = unassigned)
    *,
    num_heads: int,
    kv_num_heads: int,
    head_dim: int,
    block_size: int,
    max_q_len: int,            # static padded per-seq query length
    use_neox_style: bool = False,
    cache_quant: str = "none",   # none | static | dynamic
    round_ties_away: bool = True,
    compute_dtype=jnp.float32,
    has_out_quant: bool = False,
    qkv_out_scale=None,        # [(H+2KV)*D] f32: dequant int32 qkv
    qkv_bias=None,             # [(H+2KV)*D]
    rope_emb=None,             # [2, Br, Smax, 1, D/2] f32 (cos, sin)
    mask=None,                 # [B, 1|H, max_q_len, Lk] additive (encoder)
    tgt_mask=None,             # [B, 1|H, 1, Lt] additive (decoder rows)
    pre_key_cache=None,        # [B, KV, Pre, D]
    pre_value_cache=None,
    cache_k_quant_scales=None,    # [KV] (static) | [B, KV] (dynamic)
    cache_v_quant_scales=None,
    cache_k_dequant_scales=None,
    cache_v_dequant_scales=None,
    out_shift=None,            # [H*D]
    out_smooth=None,           # [H*D]
    out_scale: float = -1.0,
    quant_max_bound: float = 127.0,
    quant_min_bound: float = -127.0,
):
    """One serving attention step over the paged cache.

    Returns (out [T, H*D], key_cache', value_cache',
             k_quant_scales', v_quant_scales', k_dequant_scales',
             v_dequant_scales') — scale arrays pass through unchanged except
    in dynamic quant mode, where prefill rows refresh them.
    """
    H, KV, D, bs = num_heads, kv_num_heads, head_dim, block_size
    T = qkv.shape[0]
    B = block_tables.shape[0]
    L = block_tables.shape[1] * bs

    # ---- 1. unpack + dequant + bias ------------------------------------
    if qkv_out_scale is not None:
        qkv_f = qkv.astype(jnp.float32) * qkv_out_scale[None, :]
    else:
        qkv_f = qkv.astype(compute_dtype)
    if qkv_bias is not None:
        qkv_f = qkv_f + qkv_bias[None, :].astype(qkv_f.dtype)
    q = qkv_f[:, : H * D].reshape(T, H, D)
    k = qkv_f[:, H * D:(H + KV) * D].reshape(T, KV, D)
    v = qkv_f[:, (H + KV) * D:].reshape(T, KV, D)

    # ---- 2. token coordinates ------------------------------------------
    tok = jnp.arange(T, dtype=jnp.int32)
    total = cu_seqlens_q[-1]
    b_idx = jnp.clip(
        jnp.searchsorted(cu_seqlens_q, tok, side="right").astype(jnp.int32) - 1,
        0, B - 1)
    local = tok - cu_seqlens_q[b_idx]
    ctx = seq_lens_decoder[b_idx]
    abs_pos = ctx + local
    valid = (tok < total) & (local < seq_lens_this_time[b_idx])

    # ---- 3. rope at absolute positions ---------------------------------
    if rope_emb is not None:
        rb = jnp.minimum(b_idx, rope_emb.shape[1] - 1)
        rp = jnp.clip(abs_pos, 0, rope_emb.shape[2] - 1)
        cos_t = rope_emb[0, rb, rp, 0][:, None, :]  # [T, 1, D/2]
        sin_t = rope_emb[1, rb, rp, 0][:, None, :]
        q = rope_rotate(q, cos_t, sin_t, use_neox_style)
        k = rope_rotate(k, cos_t, sin_t, use_neox_style)

    # ---- 4. (dynamic quant) refresh per-(seq, head) scales -------------
    if cache_quant == "dynamic":
        # prefill rows recompute absmax over this step's K/V (the reference
        # computes scales during the encoder pass and reuses them in decode)
        k_pad0 = jnp.zeros((B, max_q_len, KV, D), jnp.float32)
        v_pad0 = jnp.zeros((B, max_q_len, KV, D), jnp.float32)
        bs_idx = jnp.where(valid, b_idx, B)
        lc_idx = jnp.where(valid & (local < max_q_len), local, max_q_len)
        k_pad0 = k_pad0.at[bs_idx, lc_idx].set(
            k.astype(jnp.float32), mode="drop")
        v_pad0 = v_pad0.at[bs_idx, lc_idx].set(
            v.astype(jnp.float32), mode="drop")
        k_absmax = jnp.max(jnp.abs(k_pad0), axis=(1, 3))  # [B, KV]
        v_absmax = jnp.max(jnp.abs(v_pad0), axis=(1, 3))
        is_prefill = (seq_lens_encoder > 0)[:, None]
        new_kq = jnp.where(is_prefill, quant_max_bound / jnp.maximum(k_absmax, 1e-6),
                           cache_k_quant_scales)
        new_vq = jnp.where(is_prefill, quant_max_bound / jnp.maximum(v_absmax, 1e-6),
                           cache_v_quant_scales)
        new_kd = jnp.where(is_prefill, jnp.maximum(k_absmax, 1e-6) / quant_max_bound,
                           cache_k_dequant_scales)
        new_vd = jnp.where(is_prefill, jnp.maximum(v_absmax, 1e-6) / quant_max_bound,
                           cache_v_dequant_scales)
        cache_k_quant_scales, cache_v_quant_scales = new_kq, new_vq
        cache_k_dequant_scales, cache_v_dequant_scales = new_kd, new_vd

    # ---- 5. scatter K/V into the block pool ----------------------------
    nb = key_cache.shape[0]
    blk = block_tables[b_idx, jnp.clip(abs_pos // bs, 0, block_tables.shape[1] - 1)]
    blk = jnp.where(valid & (blk >= 0) & (blk < nb), blk, nb)  # OOB -> drop
    slot = abs_pos % bs
    if cache_quant != "none":
        if cache_quant == "static":
            ksc = cache_k_quant_scales[None, :, None]          # [1, KV, 1]
            vsc = cache_v_quant_scales[None, :, None]
        else:
            ksc = cache_k_quant_scales[b_idx][:, :, None]      # [T, KV, 1]
            vsc = cache_v_quant_scales[b_idx][:, :, None]
        k_store = _quantize_u8(k, ksc, round_ties_away, quant_max_bound,
                               quant_min_bound)
        v_store = _quantize_u8(v, vsc, round_ties_away, quant_max_bound,
                               quant_min_bound)
    else:
        k_store = k.astype(key_cache.dtype)
        v_store = v.astype(value_cache.dtype)
    key_cache = key_cache.at[blk, :, slot, :].set(k_store, mode="drop")
    value_cache = value_cache.at[blk, :, slot, :].set(v_store, mode="drop")

    # ---- 6. gather each sequence's context back ------------------------
    k_all = paged_gather_kv(key_cache, block_tables)   # [B, KV, L, D]
    v_all = paged_gather_kv(value_cache, block_tables)
    if cache_quant != "none":
        if cache_quant == "static":
            kd = cache_k_dequant_scales[None, :, None, None]
            vd = cache_v_dequant_scales[None, :, None, None]
        else:
            kd = cache_k_dequant_scales[:, :, None, None]
            vd = cache_v_dequant_scales[:, :, None, None]
        k_all = (k_all.astype(jnp.float32) - 128.0) * kd
        v_all = (v_all.astype(jnp.float32) - 128.0) * vd
        # overlay this step's K/V at full precision: the reference kernel
        # attends the fresh tokens unquantized (only the stored cache is
        # int8), which keeps prefill outputs exact
        ov_b = jnp.where(valid, b_idx, B)
        ov_p = jnp.where(valid, abs_pos, L)
        k_all = k_all.at[ov_b, :, ov_p].set(k.astype(k_all.dtype), mode="drop")
        v_all = v_all.at[ov_b, :, ov_p].set(v.astype(v_all.dtype), mode="drop")
    pre_len = 0
    if pre_key_cache is not None:
        pre_len = pre_key_cache.shape[2]
        k_all = jnp.concatenate([pre_key_cache.astype(k_all.dtype), k_all], axis=2)
        v_all = jnp.concatenate([pre_value_cache.astype(v_all.dtype), v_all], axis=2)
    Lf = pre_len + L

    # ---- 7. padded-batch attention -------------------------------------
    S = max_q_len
    bs_idx = jnp.where(valid, b_idx, B)
    lc_idx = jnp.where(valid & (local < S), local, S)
    q_pad = jnp.zeros((B, S, H, D), q.dtype).at[bs_idx, lc_idx].set(
        q, mode="drop")
    group = H // KV
    qg = q_pad.reshape(B, S, KV, group, D).astype(jnp.float32)
    kf = k_all.astype(jnp.float32)
    logits = jnp.einsum("bskgd,bkld->bkgsl", qg, kf) / (D ** 0.5)

    # causal visibility: query at absolute position p sees keys [0, p] of
    # its own context plus the whole pre-cache prefix
    qpos = (seq_lens_decoder[:, None]
            + jnp.arange(S, dtype=jnp.int32)[None, :])  # [B, S] (rows past the real length are masked on output)
    kpos = jnp.arange(Lf, dtype=jnp.int32)[None, None, :] - pre_len  # [1,1,Lf]
    vis = kpos <= qpos[:, :, None]                                   # [B, S, Lf]
    neg = jnp.asarray(-1e30, jnp.float32)
    logits = jnp.where(vis[:, None, None, :, :], logits, neg)

    def _add_mask(lg, m):
        # m: [B, 1|H, Sq, Lm] additive; key axis aligned at column 0 (the
        # pre-cache prefix occupies the first ``pre_len`` columns, matching
        # the reference's create_attn_mask layout)
        m = m.astype(jnp.float32)
        if m.shape[1] == 1:
            m = jnp.broadcast_to(m, (B, H, m.shape[2], m.shape[3]))
        mh = m.reshape(B, KV, group, m.shape[2], m.shape[3])
        Lm, Sq = m.shape[3], m.shape[2]
        if Lm < Lf:
            mh = jnp.pad(mh, ((0, 0),) * 4 + ((0, Lf - Lm),))
        elif Lm > Lf:
            mh = mh[..., :Lf]
        if Sq < S:
            mh = jnp.pad(mh, ((0, 0),) * 3 + ((0, S - Sq), (0, 0)))
        elif Sq > S:
            mh = mh[..., :S, :]
        return lg + mh

    if mask is not None:
        # encoder-phase custom mask applies to prefill rows only
        enc_rows = (seq_lens_encoder > 0)[:, None, None, None, None]
        logits = jnp.where(enc_rows, _add_mask(logits, mask), logits)
    if tgt_mask is not None:
        dec_rows = ((seq_lens_encoder <= 0) &
                    (seq_lens_this_time > 0))[:, None, None, None, None]
        logits = jnp.where(dec_rows, _add_mask(logits, tgt_mask), logits)

    p = jax.nn.softmax(logits, axis=-1)
    out_pad = jnp.einsum("bkgsl,bkld->bskgd", p, v_all.astype(jnp.float32))
    out_pad = out_pad.reshape(B, S, H, D)

    # ---- 8. gather back to the packed token buffer ---------------------
    out = out_pad.at[bs_idx, lc_idx].get(mode="fill", fill_value=0)  # [T, H, D]
    out = out.reshape(T, H * D)
    # smooth-quant epilogue: (x + shift) * smooth — the reference kernel's
    # order (shift first, then the per-channel smoothing scale)
    if out_shift is not None:
        out = out + out_shift[None, :].astype(out.dtype)
    if out_smooth is not None:
        out = out * out_smooth[None, :].astype(out.dtype)
    if has_out_quant:
        vq = out.astype(jnp.float32) * out_scale * quant_max_bound
        if round_ties_away:
            vq = jnp.trunc(vq + jnp.where(vq >= 0, 0.5, -0.5))
        else:
            vq = jnp.round(vq)
        out = jnp.clip(vq, quant_min_bound, quant_max_bound).astype(jnp.int8)
    else:
        out = out.astype(compute_dtype)
    return (out, key_cache, value_cache,
            cache_k_quant_scales, cache_v_quant_scales,
            cache_k_dequant_scales, cache_v_dequant_scales)
