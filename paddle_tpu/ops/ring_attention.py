"""Ring attention — sequence-parallel exact flash attention over the 'sep' axis.

The reference has only Megatron-SP activation sharding + a SEP axis that
requires seq-shardable attention (SURVEY.md §5 long-context: "ring attention
absent — the TPU build supplies the capability natively"). This implements
blockwise ring attention (Liu et al.) TPU-style:

* each device holds a local Q/K/V sequence block; K/V rotate around the ring
  via ``lax.ppermute`` (ICI neighbor exchange);
* the **per-block body is the Pallas flash kernel** (ops/pallas/flash_attention)
  — no [Sl, Sl] logits matrix is ever materialized; block results merge via
  streaming logsumexp, so device memory is O(Sl·D);
* under causal masking, ring steps whose K/V block is entirely in the masked
  future are **skipped** (rotate only — no QK^T is computed);
* GQA K/V heads are indexed inside the kernel (never repeated);
* the backward is a hand-written second ring pass (custom_vjp): dK/dV partials
  ride the ring alongside K/V and arrive home after n steps, dQ accumulates
  locally — residual memory is O(Sl·D), not O(n·Sl²) as autodiff-through-scan
  would give.

Layout: paddle's [B, S, H, D]; sequence dim sharded on ``axis_name``.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .pallas.flash_attention import block_bwd, block_fwd

NEG_INF = -1e30


def _axis_size(axis_name) -> int:
    """lax.axis_size is absent before jax 0.5; inside a bound axis context
    old jax exposes the static size through jax.core.axis_frame."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    frame = jax.core.axis_frame(axis_name)
    return frame if isinstance(frame, int) else frame.size


# ------------------------------------------------------------ per-block body
def _block_fwd(qb, kb, vb, causal, scale, kv_rep, interpret):
    """qb [BH, Sl, D], kb/vb [BHk, Sl, D] → (o f32 [BH,Sl,D], lse f32 [BH,Sl])."""
    o, lse = block_fwd(qb, kb, vb, causal, scale, kv_rep, interpret)
    return o.astype(jnp.float32), lse


def _block_bwd(qb, kb, vb, o, lse, g, causal, scale, kv_rep, interpret, delta):
    """→ (dq [BH], dk [BHk], dv [BHk]) all f32 (ring accumulators)."""
    dq, dk, dv = block_bwd(qb, kb, vb, o, lse, g, causal, scale, kv_rep, interpret,
                           delta=delta)
    return (dq.astype(jnp.float32), dk.astype(jnp.float32), dv.astype(jnp.float32))


def _case_of(j, idx, causal):
    """0 = skip (fully masked), 1 = diagonal (causal in-block), 2 = full."""
    if not causal:
        return jnp.int32(2)
    return jnp.where(j > idx, jnp.int32(0), jnp.where(j == idx, jnp.int32(1), jnp.int32(2)))


# ------------------------------------------------- local fwd/bwd ring loops
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring_local(q, k, v, axis_name, causal, scale, kv_rep, interpret):
    out, _ = _ring_local_fwd(q, k, v, axis_name, causal, scale, kv_rep, interpret)
    return out


def _ring_local_fwd(q, k, v, axis_name, causal, scale, kv_rep, interpret):
    """q [B,Sl,H,D], k/v [B,Sl,Hk,D] local shards (inside shard_map)."""
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    B, Sl, H, D = q.shape
    Hk = k.shape[2]
    qb = jnp.moveaxis(q, 2, 1).reshape(B * H, Sl, D)
    kb0 = jnp.moveaxis(k, 2, 1).reshape(B * Hk, Sl, D)
    vb0 = jnp.moveaxis(v, 2, 1).reshape(B * Hk, Sl, D)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def merge(acc, lse, o_j, lse_j):
        lse_new = jnp.logaddexp(lse, lse_j)
        w_old = jnp.exp(lse - lse_new)[..., None]
        w_new = jnp.exp(lse_j - lse_new)[..., None]
        return acc * w_old + o_j * w_new, lse_new

    def step(t, carry):
        kb, vb, acc, lse = carry
        j = (idx - t) % n  # global block id currently held

        def do_skip(acc, lse):
            return acc, lse

        def do_diag(acc, lse):
            o_j, lse_j = _block_fwd(qb, kb, vb, True, scale, kv_rep, interpret)
            return merge(acc, lse, o_j, lse_j)

        def do_full(acc, lse):
            o_j, lse_j = _block_fwd(qb, kb, vb, False, scale, kv_rep, interpret)
            return merge(acc, lse, o_j, lse_j)

        acc, lse = lax.switch(_case_of(j, idx, causal), [do_skip, do_diag, do_full],
                              acc, lse)
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return kb, vb, acc, lse

    acc0 = jnp.zeros((B * H, Sl, D), jnp.float32)
    lse0 = jnp.full((B * H, Sl), NEG_INF, jnp.float32)
    _, _, acc, lse = lax.fori_loop(0, n, step, (kb0, vb0, acc0, lse0))
    out = jnp.moveaxis(acc.astype(q.dtype).reshape(B, H, Sl, D), 1, 2)
    return out, (q, k, v, acc, lse)


def _ring_local_bwd(axis_name, causal, scale, kv_rep, interpret, res, g):
    q, k, v, acc, lse = res
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    B, Sl, H, D = q.shape
    Hk = k.shape[2]
    qb = jnp.moveaxis(q, 2, 1).reshape(B * H, Sl, D)
    kb0 = jnp.moveaxis(k, 2, 1).reshape(B * Hk, Sl, D)
    vb0 = jnp.moveaxis(v, 2, 1).reshape(B * Hk, Sl, D)
    gb = jnp.moveaxis(g, 2, 1).reshape(B * H, Sl, D).astype(jnp.float32)
    o = acc  # f32 normalized output saved by the forward
    # delta = rowsum(g∘o) is ring-invariant: compute once, reuse every step
    delta = jnp.sum(gb * o, axis=-1)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(t, carry):
        kb, vb, dkb, dvb, dq = carry
        j = (idx - t) % n

        def do_skip(dq, dkb, dvb):
            return dq, dkb, dvb

        def do_diag(dq, dkb, dvb):
            dq_j, dk_j, dv_j = _block_bwd(qb, kb, vb, o, lse, gb, True, scale,
                                          kv_rep, interpret, delta)
            return dq + dq_j, dkb + dk_j, dvb + dv_j

        def do_full(dq, dkb, dvb):
            dq_j, dk_j, dv_j = _block_bwd(qb, kb, vb, o, lse, gb, False, scale,
                                          kv_rep, interpret, delta)
            return dq + dq_j, dkb + dk_j, dvb + dv_j

        dq, dkb, dvb = lax.switch(_case_of(j, idx, causal),
                                  [do_skip, do_diag, do_full], dq, dkb, dvb)
        # dK/dV partials travel WITH their K/V block; after n rotations the
        # block (and its fully-accumulated gradient) is back home
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        dkb = lax.ppermute(dkb, axis_name, perm)
        dvb = lax.ppermute(dvb, axis_name, perm)
        return kb, vb, dkb, dvb, dq

    z_kv = jnp.zeros((B * Hk, Sl, D), jnp.float32)
    dq0 = jnp.zeros((B * H, Sl, D), jnp.float32)
    _, _, dkb, dvb, dqb = lax.fori_loop(0, n, step, (kb0, vb0, z_kv, z_kv, dq0))
    dq = jnp.moveaxis(dqb.astype(q.dtype).reshape(B, H, Sl, D), 1, 2)
    dk = jnp.moveaxis(dkb.astype(k.dtype).reshape(B, Hk, Sl, D), 1, 2)
    dv = jnp.moveaxis(dvb.astype(v.dtype).reshape(B, Hk, Sl, D), 1, 2)
    return dq, dk, dv


_ring_local.defvjp(_ring_local_fwd, _ring_local_bwd)


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool, scale: float,
                          interpret: bool = False):
    """Per-device body (inside shard_map). q [B,Sl,H,D], k/v [B,Sl,Hk,D]."""
    H, Hk = q.shape[2], k.shape[2]
    kv_rep = H // Hk if Hk != H else 1
    return _ring_local(q, k, v, axis_name, causal, scale, kv_rep, interpret)


def ring_attention(q, k, v, *, mesh, axis_name: str = "sep", causal: bool = False,
                   scale: Optional[float] = None, batch_axis: Optional[str] = "dp",
                   head_axis: Optional[str] = "mp", interpret: bool = False):
    """Global entry on sep-sharded [B, S, H, D] jax arrays.

    Composes with dp (batch) and mp (head) sharding: those axes simply shrink
    the local block; collectives ride only the sep ring. K/V may carry fewer
    (GQA) heads than Q.
    """
    from ..distributed.shard_map_compat import shard_map_compat

    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    names = set(mesh.axis_names)
    b_ax = batch_axis if batch_axis in names and mesh.shape[batch_axis] > 1 else None
    h_ax = head_axis if head_axis in names and mesh.shape[head_axis] > 1 else None
    spec = P(b_ax, axis_name, h_ax, None)

    fn = shard_map_compat(
        functools.partial(_ring_attention_local, axis_name=axis_name, causal=causal,
                          scale=scale, interpret=interpret),
        mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
