"""Ring attention — sequence-parallel exact attention over the 'sep' mesh axis.

The reference has only Megatron-SP activity sharding + a SEP axis that
requires seq-shardable attention (SURVEY.md §5 long-context: "ring attention
absent — the TPU build supplies the capability natively"). This implements
blockwise ring attention (Liu et al.) TPU-style: each device holds a local
Q/K/V sequence block; K/V blocks rotate around the ring via lax.ppermute
(ICI neighbor exchange) while an online-softmax accumulator builds the exact
global attention — memory O(S/n), communication fully overlappable by XLA's
latency-hiding scheduler.

Layout: paddle's [B, S, H, D]; sequence dim sharded on ``axis_name``.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool, scale: float):
    """Per-device body (inside shard_map). q/k/v local: [B, Sl, H, D]."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    B, Sl, H, D = q.shape
    if k.shape[2] != H:  # grouped-query attention: repeat kv heads
        rep = H // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qh = jnp.moveaxis(q, 2, 1).astype(jnp.float32) * scale  # [B,H,Sl,D]

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(t, carry):
        k_blk, v_blk, acc, m_prev, l_prev = carry
        j = (idx - t) % n  # global block id currently held
        kh = jnp.moveaxis(k_blk, 2, 1).astype(jnp.float32)
        vh = jnp.moveaxis(v_blk, 2, 1).astype(jnp.float32)
        s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh)
        if causal:
            rows = idx * Sl + lax.broadcasted_iota(jnp.int32, (Sl, Sl), 0)
            cols = j * Sl + lax.broadcasted_iota(jnp.int32, (Sl, Sl), 1)
            s = jnp.where(rows[None, None] >= cols[None, None], s, NEG_INF)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, vh)
        # rotate K/V to the next device (receive the previous block)
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return k_next, v_next, acc, m_new, l_new

    acc0 = jnp.zeros((B, H, Sl, D), jnp.float32)
    m0 = jnp.full((B, H, Sl), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Sl), jnp.float32)
    _, _, acc, m, l = lax.fori_loop(0, n, step, (k, v, acc0, m0, l0))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.moveaxis(out, 1, 2).astype(q.dtype)  # [B,Sl,H,D]


def ring_attention(q, k, v, *, mesh, axis_name: str = "sep", causal: bool = False,
                   scale: Optional[float] = None, batch_axis: Optional[str] = "dp",
                   head_axis: Optional[str] = "mp"):
    """Global entry on sep-sharded [B, S, H, D] jax arrays.

    Composes with dp (batch) and mp (head) sharding: those axes simply shrink
    the local block; collectives ride only the sep ring.
    """
    from jax.experimental.shard_map import shard_map

    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    names = set(mesh.axis_names)
    b_ax = batch_axis if batch_axis in names and mesh.shape[batch_axis] > 1 else None
    h_ax = head_axis if head_axis in names and mesh.shape[head_axis] > 1 else None
    spec = P(b_ax, axis_name, h_ax, None)

    fn = shard_map(
        functools.partial(_ring_attention_local, axis_name=axis_name, causal=causal, scale=scale),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_rep=False,
    )
    return fn(q, k, v)
