"""Eager op dispatch.

This is the TPU-native collapse of the reference's dispatch stack
(/root/reference/paddle/phi/api/generator/api_base.py:1300 kernel selection,
paddle/fluid/eager/auto_code_generator/generator/eager_gen.py grad-node
creation): one function, ``apply``, that (a) runs the op's pure JAX function
on the operands and (b) when gradients are required, obtains the op's VJP from
``jax.vjp`` and tapes it as a GradNode. There is no kernel registry — XLA is
the kernel library — and no generated per-op autograd classes.
"""
from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

from ..autograd import tape
from ..framework import flags

# op-call counter sink for amp.debugging.collect_operator_stats
_stats_sink = None

# paddle.enable_static() flips this: ops whose inputs include a symbolic
# (ShapeDtypeStruct-valued) tensor are recorded into the current Program
# instead of executing (see paddle_tpu.static)
_static_capture = False

# mid-function graph break (jit.lazy_segments.SegmentContext): when set, ops
# record into the current segment instead of executing; host reads flush
_lazy_ctx = None

# abstract-eval failures that mean "this op is inherently data-dependent"
# (e.g. masked_select's dynamic shape) — the segment flushes and the op runs
# eagerly on the materialized values
_LAZY_BREAK_ERRORS = tuple(
    getattr(jax.errors, n)
    for n in ("TracerArrayConversionError", "TracerBoolConversionError",
              "TracerIntegerConversionError", "ConcretizationTypeError")
    if hasattr(jax.errors, n)
)


def _wrap(val, node, index, stop_gradient):
    from ..tensor.tensor import Tensor

    t = Tensor(val, stop_gradient=stop_gradient)
    if node is not None:
        t._grad_node = node
        t._out_index = index
    return t


def _amp_cast_vals(op_name: str, vals):
    """AMP autocast at the dispatch boundary — the TPU-native analog of the
    generated AmpAutoCast calls (reference eager_gen.py / amp_auto_cast.h:40)."""
    from ..amp.auto_cast import amp_state
    from ..framework.dtype import to_jax_dtype

    st = amp_state()
    if not st.enabled:
        return vals
    low = to_jax_dtype(st.dtype)
    f32 = jnp.float32

    def is_float(v):
        return jnp.issubdtype(jnp.result_type(v), jnp.floating)

    if op_name in st.black:
        return tuple(v.astype(f32) if is_float(v) and jnp.result_type(v) != f32 else v for v in vals)
    if op_name in st.white or st.level == "O2":
        return tuple(v.astype(low) if is_float(v) and jnp.result_type(v) == f32 else v for v in vals)
    return vals


def apply(fn: Callable, *inputs, op_name: str = "", n_outs: int = 1):
    """Run ``fn(*raw_values)`` and tape its vjp if needed.

    ``inputs`` must all be Tensors (op wrappers normalize scalars either by
    closing over them inside ``fn`` or by converting to Tensor). ``fn`` must be
    a pure function of the raw jax arrays. Returns Tensor or list of Tensors
    matching fn's output arity.
    """
    if _stats_sink is not None:
        _stats_sink[op_name or "<anonymous>"] = _stats_sink.get(op_name or "<anonymous>", 0) + 1
    if _static_capture and any(isinstance(t._value, jax.ShapeDtypeStruct) for t in inputs):
        from ..static import _capture

        return _capture(fn, inputs, op_name)
    if _lazy_ctx is not None:
        ctx = _lazy_ctx

        def amp_fn(*vs, _fn=fn, _op=op_name):
            return _fn(*_amp_cast_vals(_op, vs))

        try:
            return ctx.record(amp_fn, inputs, op_name)
        except _LAZY_BREAK_ERRORS:
            # op can't abstract-eval (data-dependent shape): flush the
            # segment so its inputs are concrete, then run it eagerly below.
            # Inputs that merely SHARE a pending value (rewraps/detach) are
            # not holders — resolve them through the materialized map.
            ctx.flush()
            for t in inputs:
                ctx.resolve_tensor(t)
    vals = tuple(t._value for t in inputs)
    vals = _amp_cast_vals(op_name, vals)
    needs_grad = tape.grad_enabled() and any(not t.stop_gradient for t in inputs)
    if needs_grad:
        outs, vjp_fn = jax.vjp(fn, *vals)
        multi = isinstance(outs, (tuple, list))
        outs_seq = list(outs) if multi else [outs]
        # primal fn (with this call's amp casts baked in) enables
        # create_graph=True to re-derive the vjp through the tape
        dtypes = tuple(getattr(v, "dtype", None) for v in vals)

        def primal_fn(*raw, _fn=fn, _dts=dtypes):
            cast = tuple(
                r.astype(d) if d is not None and getattr(r, "dtype", None) != d else r
                for r, d in zip(raw, _dts))
            return _fn(*cast)

        struct = "list" if isinstance(outs, list) else ("tuple" if multi else "single")
        node = tape.GradNode(vjp_fn, inputs, outs_seq, name=op_name, fn=primal_fn,
                             out_struct=struct)
        results = [_wrap(o, node, i, False) for i, o in enumerate(outs_seq)]
    else:
        outs = fn(*vals)
        multi = isinstance(outs, (tuple, list))
        outs_seq = list(outs) if multi else [outs]
        results = [_wrap(o, None, 0, True) for o in outs_seq]

    if flags.flag_value("check_nan_inf"):
        _check_nan_inf(op_name, outs_seq)
    return results if multi else results[0]


def _check_nan_inf(op_name, outs):
    # Reference capability: FLAGS_check_nan_inf per-op scan
    # (/root/reference/paddle/fluid/eager/nan_inf_utils.h). Only meaningful on
    # concrete (non-traced) values.
    for o in outs:
        if isinstance(o, jax.core.Tracer):
            return
        if jnp.issubdtype(jnp.result_type(o), jnp.floating):
            if bool(jnp.any(~jnp.isfinite(o))):
                raise FloatingPointError(f"nan/inf detected in output of op {op_name or '<anonymous>'}")
