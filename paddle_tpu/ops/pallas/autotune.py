"""Flash-attention block-size autotuning.

Reference analog: the kernel autotune cache + timing harness
(/root/reference/paddle/phi/kernels/autotune/switch_autotune.h, cache.h) that
picks cudnn/cutlass algorithms by measurement. Here the tunable is the
(block_q, block_k) tiling of the Pallas flash kernels.

Two tiers:
  * a measured default table (tuned on TPU v5e, see ``tune()``) keyed by
    (kind, seq bucket, head_dim) — zero-cost lookup, always available;
  * optional on-line measurement: ``paddle.set_flags({'FLAGS_flash_autotune':
    True})`` times every candidate on first encounter of a new shape key
    (eager, cached for the process, persisted to
    ``PADDLE_TPU_AUTOTUNE_CACHE`` if set).
"""
from __future__ import annotations

import functools
import json
import os
import time
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

__all__ = ["get_flash_blocks", "tune", "clear_cache"]


def _pick_block(s: int, target: int) -> int:
    b = min(target, s)
    while s % b:
        b //= 2
    return max(b, 1)


def _bucket_seq(s: int) -> int:
    """Round down to a power of two (tables are per-magnitude, not per-shape)."""
    b = 1
    while b * 2 <= s:
        b *= 2
    return b


# Measured on TPU v5e-1 via tune() with in-graph iteration loops (bf16,
# causal, seq 2048, head_dim 128: fwd 256x256 ≈ 9.2ms vs 512x512 10.4ms;
# bwd within noise of each other — keep 256x256). Values are *targets* —
# _pick_block snaps them to divisors of the actual seq.
_DEFAULT_TARGETS: Dict[Tuple[str, int], Tuple[int, int]] = {
    ("fwd", 128): (256, 256),
    ("bwd", 128): (256, 256),
    ("fwd", 64): (256, 256),
    ("bwd", 64): (256, 256),
    # large head_dim: smaller tiles keep K/V + fp32 staging inside VMEM
    ("fwd", 256): (256, 256),
    ("bwd", 256): (128, 256),
    ("fwd", 512): (128, 128),
    ("bwd", 512): (128, 128),
}

# process-level measured cache: (kind, sq_bucket, sk_bucket, d) -> (bq, bk)
_measured: Dict[Tuple, Tuple[int, int]] = {}
_cache_loaded = False


def _cache_path():
    return os.environ.get("PADDLE_TPU_AUTOTUNE_CACHE")


def _load_cache():
    global _cache_loaded
    if _cache_loaded:
        return
    _cache_loaded = True
    p = _cache_path()
    if p and os.path.exists(p):
        try:
            with open(p) as f:
                for k, v in json.load(f).items():
                    _measured[tuple(json.loads(k))] = tuple(v)
        except Exception:
            pass


def _save_cache():
    p = _cache_path()
    if not p:
        return
    try:
        with open(p, "w") as f:
            json.dump({json.dumps(list(k)): list(v) for k, v in _measured.items()}, f)
    except Exception:
        pass


def clear_cache():
    _measured.clear()


def get_flash_blocks(kind: str, sq: int, sk: int, d: int) -> Tuple[int, int]:
    """Block sizes for the flash kernel. kind: 'fwd' | 'bwd'."""
    _load_cache()
    key = (kind, _bucket_seq(sq), _bucket_seq(sk), d)
    hit = _measured.get(key)
    if hit is not None:
        return _pick_block(sq, hit[0]), _pick_block(sk, hit[1])

    from ...framework.flags import flag_value

    try:
        autotune_on = flag_value("flash_autotune")
    except KeyError:  # flags module import cycle during bootstrap
        autotune_on = False
    if autotune_on and jax.default_backend() in ("tpu", "axon"):
        bq, bk = _measure(kind, sq, sk, d)
        _measured[key] = (bq, bk)
        _save_cache()
        return _pick_block(sq, bq), _pick_block(sk, bk)

    tq, tk = _DEFAULT_TARGETS.get((kind, d), (512, 512) if kind == "fwd" else (256, 256))
    return _pick_block(sq, tq), _pick_block(sk, tk)


def _candidates(kind: str, sq: int, sk: int):
    opts = [128, 256, 512, 1024]
    for bq in opts:
        for bk in opts:
            if sq % bq == 0 and sk % bk == 0 and bq * bk <= 512 * 1024:
                yield bq, bk


def _measure(kind: str, sq: int, sk: int, d: int, n_iter: int = 20) -> Tuple[int, int]:
    """Time candidates with an IN-GRAPH iteration loop: each candidate runs
    ``n_iter`` chained kernel invocations inside one jit dispatch, so
    per-dispatch latency (large on remote/tunneled accelerators) and async
    readback cannot corrupt the measurement."""
    from jax import lax

    from . import flash_attention as fa

    bh = 8
    rng = jax.random.key(0)
    q = jax.random.normal(rng, (bh, sq, d), jnp.bfloat16)
    k = jax.random.normal(rng, (bh, sk, d), jnp.bfloat16)
    v = jax.random.normal(rng, (bh, sk, d), jnp.bfloat16)
    scale = 1.0 / (d ** 0.5)

    def run_chained(body):
        f = jax.jit(lambda x: lax.fori_loop(0, n_iter, lambda i, x: body(x), x))
        out = f(q)
        float(out.reshape(-1)[0])  # warm + sync
        t0 = time.perf_counter()
        out = f(q)
        float(out.reshape(-1)[0])
        return (time.perf_counter() - t0) / n_iter

    best, best_t = None, float("inf")
    if kind != "fwd":
        o, lse = fa._pallas_fwd(q, k, v, True, scale,
                                _pick_block(sq, 256), _pick_block(sk, 256), False)
        g = jnp.ones_like(o)
    for bq, bk in _candidates(kind, sq, sk):
        try:
            if kind == "fwd":
                dt = run_chained(lambda x, bq=bq, bk=bk: fa._pallas_fwd(
                    x, k, v, True, scale, bq, bk, False)[0].astype(q.dtype))
            else:
                dt = run_chained(lambda x, bq=bq, bk=bk: fa._pallas_bwd(
                    x, k, v, o, lse, g, True, scale, bq, bk,
                    False)[0].astype(q.dtype))
            if dt < best_t:
                best, best_t = (bq, bk), dt
        except Exception:
            continue
    return best or (_pick_block(sq, 256), _pick_block(sk, 256))


def tune(seqs=(1024, 2048, 4096, 8192), head_dims=(64, 128), verbose=True):
    """Offline tuner: measure all (kind, seq, head_dim) combos and return the
    results table (also fills the in-process cache)."""
    out = {}
    for d in head_dims:
        for s in seqs:
            for kind in ("fwd", "bwd"):
                bq, bk = _measure(kind, s, s, d)
                _measured[(kind, _bucket_seq(s), _bucket_seq(s), d)] = (bq, bk)
                out[(kind, s, d)] = (bq, bk)
                if verbose:
                    print(f"tune {kind} seq={s} d={d}: block_q={bq} block_k={bk}")
    _save_cache()
    return out
