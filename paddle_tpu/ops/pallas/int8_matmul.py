"""Weight-only int8 matmul — Pallas TPU kernel.

Reference analog: the int8 weight-only GEMM tier
(/root/reference/paddle/phi/kernels/fusion/cutlass/ + the weight_only_linear
op behind python/paddle/nn/quant/). Serving-path motivation: weights stream
from HBM at 1 byte/element (half the bf16 traffic) and are dequantized
per-tile in VMEM right before the MXU — the memory win of int8 storage
without writing a dequantized copy back to HBM.

x [M, K] (bf16/f32) @ qw [K, N] (int8, per-out-channel scales [N]) -> [M, N].
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl

    try:
        from jax.experimental.pallas import tpu as pltpu
    except Exception:  # pragma: no cover
        pltpu = None
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False
    pltpu = None

__all__ = ["int8_matmul"]


def _kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, n_k: int):
    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...]  # [bm, bk]
    w = q_ref[...].astype(x.dtype)  # dequant int8 tile in VMEM (scale at end)
    acc_ref[...] += jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(2) == n_k - 1)
    def _done():
        s = s_ref[...].astype(jnp.float32)  # [bn]
        o_ref[...] = (acc_ref[...] * s[None, :]).astype(o_ref.dtype)


def _pick(n: int, target: int) -> int:
    b = min(target, n)
    while n % b:
        b //= 2
    return max(b, 1)


def _use_kernel(m, k, n, interpret) -> bool:
    return (_HAS_PALLAS and pltpu is not None
            and (interpret or jax.default_backend() in ("tpu", "axon"))
            and m % 8 == 0 and k % 128 == 0 and n % 128 == 0)


def _int8_mm_impl(x2, qw, scale, interpret):
    m, k = x2.shape
    n = qw.shape[1]
    if not _use_kernel(m, k, n, interpret):
        return x2 @ (qw.astype(x2.dtype) * scale.astype(x2.dtype)[None, :])
    bm = _pick(m, 512)
    bk = _pick(k, 512)
    bn = _pick(n, 512)
    n_k = k // bk
    return pl.pallas_call(
        functools.partial(_kernel, n_k=n_k),
        grid=(m // bm, n // bn, n_k),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, l: (i, l)),
            pl.BlockSpec((bk, bn), lambda i, j, l: (l, j)),
            pl.BlockSpec((bn,), lambda i, j, l: (j,)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, l: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), x2.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )(x2, qw, scale)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _int8_mm(x2, qw, scale, interpret):
    return _int8_mm_impl(x2, qw, scale, interpret)


def _int8_mm_fwd(x2, qw, scale, interpret):
    return _int8_mm_impl(x2, qw, scale, interpret), (qw, scale)


def _int8_mm_bwd(interpret, res, g):
    qw, scale = res
    # dx = g @ W^T with W dequantized on the fly; weights are frozen int8
    # storage (fine-tune-over-quantized pattern) so their cotangent is zero
    w = qw.astype(g.dtype) * scale.astype(g.dtype)[None, :]
    dx = g @ w.T
    d_qw = np.zeros(qw.shape, dtype=jax.dtypes.float0)
    return dx, d_qw, jnp.zeros_like(scale)


_int8_mm.defvjp(_int8_mm_fwd, _int8_mm_bwd)


def int8_matmul(x, qw, scale, interpret: bool = False):
    """x [..., K] @ qw [K, N] int8 * scale [N] -> [..., N]. Differentiable
    w.r.t. x (dequantized transpose matmul in the backward).

    Small/odd row counts (autoregressive decode: m = batch) are zero-padded
    to the 8-row sublane so the int8-streaming kernel still serves them —
    the dense-dequant fallback would re-materialize the full bf16 weight."""
    orig_shape = x.shape
    x2 = x.reshape(-1, orig_shape[-1])
    m = x2.shape[0]
    pad = (-m) % 8
    if pad and _use_kernel(m + pad, x2.shape[1], qw.shape[1], interpret):
        out = _int8_mm(jnp.concatenate(
            [x2, jnp.zeros((pad, x2.shape[1]), x2.dtype)]), qw, scale, interpret)[:m]
    else:
        out = _int8_mm(x2, qw, scale, interpret)
    return out.reshape(*orig_shape[:-1], qw.shape[1])
