"""Flash attention — Pallas TPU kernel.

Replaces the reference's FlashAttention2 CUDA dependency
(/root/reference/third_party/flashattn, paddle/phi/kernels/flash_attn_kernel.h)
with a TPU kernel: online-softmax tiling in VMEM, fp32 accumulators, MXU
matmuls. Layout is paddle's [batch, seq, heads, head_dim].

Forward: pallas kernel (one grid cell per (batch*head, q-block); streamed
K/V with a fori_loop of MXU tiles). Backward: recompute-based VJP in jnp —
rematerialization is the standard TPU tradeoff; a pallas backward kernel is a
planned upgrade.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

try:  # pallas import is TPU/CPU-interpret capable
    from jax.experimental import pallas as pl

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

NEG_INF = -1e30


def _ref_impl(q, k, v, causal: bool, scale: float):
    """[BH, S, D] reference with fp32 softmax."""
    logits = jnp.einsum("bqd,bkd->bqk", q, k).astype(jnp.float32) * scale
    if causal:
        sq, sk = logits.shape[1], logits.shape[2]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(mask, logits, NEG_INF)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bqk,bkd->bqd", p, v)


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool, scale: float, seq_k: int):
    """One (bh, q_block) grid cell: online softmax over K tiles."""
    q = q_ref[0].astype(jnp.float32) * scale  # [block_q, D]
    block_q, d = q.shape
    q_idx = pl.program_id(1)
    q_offset = q_idx * block_q

    num_kb = seq_k // block_k

    def body(kb, carry):
        acc, m_prev, l_prev = carry
        k_tile = k_ref[0, pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)  # [block_k, D]
        v_tile = v_ref[0, pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_tile, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_k]
        if causal:
            rows = q_offset + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l_prev * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v_tile, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return acc, m_new, l_new

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, num_kb, body, (acc0, m0, l0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _pallas_fwd_bhsd(q, k, v, causal: bool, scale: float, block_q: int, block_k: int, interpret: bool):
    """q,k,v: [BH, S, D]."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    grid = (bh, sq // block_q)
    kernel = functools.partial(
        _attn_kernel, block_k=block_k, causal=causal, scale=scale, seq_k=sk
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        interpret=interpret,
    )(q, k, v)


def _pick_block(s: int, target: int) -> int:
    b = min(target, s)
    while s % b:
        b //= 2
    return max(b, 1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_core(q, k, v, causal, scale, interpret):
    out, _ = _flash_core_fwd(q, k, v, causal, scale, interpret)
    return out


def _flash_core_fwd(q, k, v, causal, scale, interpret):
    bh, sq, d = q.shape
    sk = k.shape[1]
    use_kernel = (
        _HAS_PALLAS
        and (interpret or jax.default_backend() in ("tpu", "axon"))
        and sq % 8 == 0
        and sk % 8 == 0
    )
    if use_kernel:
        block_q = _pick_block(sq, 256)
        block_k = _pick_block(sk, 512)
        out = _pallas_fwd_bhsd(q, k, v, causal, scale, block_q, block_k, interpret)
    else:
        out = _ref_impl(q, k, v, causal, scale)
    return out, (q, k, v)


def _flash_core_bwd(causal, scale, interpret, res, g):
    q, k, v = res
    # Recompute-based backward through the reference formulation (one fused
    # XLA program; memory-light).
    def f(q_, k_, v_):
        return _ref_impl(q_, k_, v_, causal, scale)

    _, vjp_fn = jax.vjp(f, q, k, v)
    return vjp_fn(g)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention_fwd(q, k, v, *, causal: bool = False, scale: float | None = None,
                        interpret: bool = False):
    """Public entry: q,k,v [B, S, H, D] (paddle layout) → [B, S, H, D]."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    hk = k.shape[2]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if hk != h:  # grouped-query attention: repeat kv heads
        rep = h // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qb = jnp.moveaxis(q, 2, 1).reshape(b * h, sq, d)
    kb = jnp.moveaxis(k, 2, 1).reshape(b * h, sk, d)
    vb = jnp.moveaxis(v, 2, 1).reshape(b * h, sk, d)
    ob = _flash_core(qb, kb, vb, causal, scale, interpret)
    return jnp.moveaxis(ob.reshape(b, h, sq, d), 1, 2)
