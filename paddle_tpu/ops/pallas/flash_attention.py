"""Flash attention — Pallas TPU kernels, forward AND backward.

Replaces the reference's FlashAttention2 CUDA dependency
(/root/reference/third_party/flashattn, paddle/phi/kernels/flash_attn_kernel.h)
with TPU kernels: online-softmax tiling in VMEM, fp32 accumulators, MXU
matmuls. Layout is paddle's [batch, seq, heads, head_dim].

Forward: one grid cell per (batch*head, q-block); K/V streamed through a
fori_loop of MXU tiles; emits per-row logsumexp (LSE) for the backward.

Backward (FlashAttention-2 algorithm): two kernels.
  * dQ:  grid (bh, q-block) — recompute P = exp(S - LSE) tile by tile,
         dS = P * (dO·Vᵀ - Δ), dQ += dS·K, where Δ = rowsum(dO ∘ O).
  * dKV: grid (bh, k-block) — same recomputation streaming Q/dO tiles,
         dV += Pᵀ·dO, dK += dSᵀ·Q.
No S×S matrix is ever materialized; memory is O(S·D) like the forward.

Causal masking uses FlashAttention-2's bottom-right alignment
(row + seq_k - seq_q >= col) in every path, so kernel and jnp fallback agree
for seq_q != seq_k. Causal loops skip fully-masked tiles via traced loop
bounds.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

try:  # pallas import is TPU/CPU-interpret capable
    from jax.experimental import pallas as pl

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

from .autotune import get_flash_blocks

NEG_INF = -1e30


# --------------------------------------------------------------- jnp fallback
def _ref_fwd_impl(q, k, v, causal: bool, scale: float):
    """[BH, S, D] reference with fp32 softmax; returns (out, lse).

    Rows with no visible key (causal with seq_q > seq_k) produce zeros, the
    same convention as the Pallas kernel (FlashAttention-2 behavior)."""
    logits = jnp.einsum("bqd,bkd->bqk", q, k).astype(jnp.float32) * scale
    row_valid = None
    if causal:
        sq, sk = logits.shape[1], logits.shape[2]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(mask, logits, NEG_INF)
        row_valid = jnp.any(mask, axis=-1)  # [Sq]
    m = jnp.max(logits, axis=-1, keepdims=True)
    p_un = jnp.exp(logits - m)
    l = jnp.sum(p_un, axis=-1, keepdims=True)  # noqa: E741
    lse = (m + jnp.log(l))[..., 0]
    p = (p_un / l).astype(q.dtype)
    if row_valid is not None:
        p = jnp.where(row_valid[None, :, None], p, jnp.zeros((), p.dtype))
    return jnp.einsum("bqk,bkd->bqd", p, v), lse


def _ref_impl(q, k, v, causal: bool, scale: float):
    return _ref_fwd_impl(q, k, v, causal, scale)[0]


def _ref_bwd_impl(q, k, v, o, lse, g, causal: bool, scale: float, delta=None):
    """jnp backward from saved LSE (used on CPU / odd shapes)."""
    s = jnp.einsum("bqd,bkd->bqk", q, k).astype(jnp.float32) * scale
    row_valid = None
    if causal:
        sq, sk = s.shape[1], s.shape[2]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, NEG_INF)
        row_valid = jnp.any(mask, axis=-1)
    p = jnp.exp(s - lse[..., None])
    if row_valid is not None:
        # fully-masked rows: output/grads are zero by convention
        p = jnp.where(row_valid[None, :, None], p, 0.0)
    gf = g.astype(jnp.float32)
    if delta is None:
        delta = jnp.sum(gf * o.astype(jnp.float32), axis=-1)  # [BH, Sq]
    dv = jnp.einsum("bqk,bqd->bkd", p, gf)
    dp = jnp.einsum("bqd,bkd->bqk", gf, v.astype(jnp.float32))
    ds = p * (dp - delta[..., None]) * scale
    dq = jnp.einsum("bqk,bkd->bqd", ds, k.astype(jnp.float32))
    dk = jnp.einsum("bqk,bqd->bkd", ds, q.astype(jnp.float32))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# ------------------------------------------------------------ forward kernel
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int, causal: bool,
                scale: float, seq_k: int, causal_offset: int):
    q = q_ref[0].astype(jnp.float32) * scale  # [block_q, D]
    block_q, d = q.shape
    q_idx = pl.program_id(1)
    q_offset = q_idx * block_q + causal_offset

    num_kb = seq_k // block_k

    def body(kb, carry):
        acc, m_prev, l_prev = carry
        k_tile = k_ref[0, pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        v_tile = v_ref[0, pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_tile, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_k]
        valid = None
        if causal:
            rows = q_offset + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            valid = rows >= cols
            s = jnp.where(valid, s, NEG_INF)
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        if valid is not None:
            # explicit zero: a fully-masked row has m_new == NEG_INF and would
            # otherwise get p == 1 at masked positions
            p = jnp.where(valid, p, 0.0)
        l_new = l_prev * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v_tile, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return acc, m_new, l_new

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    if causal:
        # last k tile that any row of this q block can see
        hi = jnp.minimum(
            num_kb, (q_offset + block_q - 1) // block_k + 1
        ).astype(jnp.int32)
        hi = jnp.maximum(hi, 0)
    else:
        hi = num_kb
    acc, m, l = jax.lax.fori_loop(0, hi, body, (acc0, m0, l0))  # noqa: E741
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(l_safe))[:, None]  # [block_q, 1] lane-broadcastable


def _pallas_fwd(q, k, v, causal: bool, scale: float, block_q: int, block_k: int,
                interpret: bool, kv_rep: int = 1):
    """q: [BH, S, D], k/v: [BHk, S, D] with BH == BHk*kv_rep → (o, lse[f32]).

    GQA is handled in the BlockSpec index map (q batch b reads k/v batch
    b // kv_rep) — K/V are never materialized at full head count."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    grid = (bh, sq // block_q)
    kernel = functools.partial(
        _fwd_kernel, block_k=block_k, causal=causal, scale=scale, seq_k=sk,
        causal_offset=sk - sq,
    )
    out, lse3 = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i, r=kv_rep: (b // r, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i, r=kv_rep: (b // r, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse3[..., 0]


# ------------------------------------------------------------ backward: dQ
def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               block_k: int, causal: bool, scale: float, seq_k: int, causal_offset: int):
    q = q_ref[0].astype(jnp.float32)  # [block_q, D]
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]  # [block_q, 1] — broadcasts over the lane (k) dim
    delta = delta_ref[0]
    block_q, d = q.shape
    q_idx = pl.program_id(1)
    q_offset = q_idx * block_q + causal_offset
    num_kb = seq_k // block_k

    def body(kb, dq_acc):
        k_tile = k_ref[0, pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        v_tile = v_ref[0, pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_tile, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        valid = None
        if causal:
            rows = q_offset + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            valid = rows >= cols
            s = jnp.where(valid, s, NEG_INF)
        p = jnp.exp(s - lse)  # [block_q, block_k]
        if valid is not None:
            # fully-masked rows carry a sentinel lse; zero p explicitly
            p = jnp.where(valid, p, 0.0)
        dp = jax.lax.dot_general(
            do, v_tile, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        return dq_acc + jax.lax.dot_general(
            ds, k_tile, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    if causal:
        hi = jnp.maximum(jnp.minimum(num_kb, (q_offset + block_q - 1) // block_k + 1), 0)
    else:
        hi = num_kb
    dq = jax.lax.fori_loop(0, hi, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


# ----------------------------------------------------------- backward: dK/dV
def _dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *,
                block_q: int, causal: bool, scale: float, seq_q: int, causal_offset: int):
    k = k_ref[0].astype(jnp.float32)  # [block_k, D]
    v = v_ref[0].astype(jnp.float32)
    block_k, d = k.shape
    k_idx = pl.program_id(1)
    k_offset = k_idx * block_k
    num_qb = seq_q // block_q

    def body(qb, carry):
        dk_acc, dv_acc = carry
        q_tile = q_ref[0, pl.dslice(qb * block_q, block_q), :].astype(jnp.float32)
        do_tile = do_ref[0, pl.dslice(qb * block_q, block_q), :].astype(jnp.float32)
        lse_tile = lse_ref[0, pl.dslice(qb * block_q, block_q), :]   # [block_q, 1]
        delta_tile = delta_ref[0, pl.dslice(qb * block_q, block_q), :]
        s = jax.lax.dot_general(
            q_tile, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [block_q, block_k]
        valid = None
        if causal:
            rows = qb * block_q + causal_offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            cols = k_offset + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            valid = rows >= cols
            s = jnp.where(valid, s, NEG_INF)
        p = jnp.exp(s - lse_tile)
        if valid is not None:
            p = jnp.where(valid, p, 0.0)
        dv_acc = dv_acc + jax.lax.dot_general(
            p, do_tile, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # pᵀ·dO : [block_k, D]
        dp = jax.lax.dot_general(
            do_tile, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_tile)
        dk_acc = dk_acc + jax.lax.dot_general(
            ds, q_tile, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # dSᵀ·Q : [block_k, D]
        return dk_acc, dv_acc

    if causal:
        # first q tile whose last row can see this k block
        lo = jnp.maximum(jnp.minimum((k_offset - causal_offset) // block_q, num_qb), 0)
    else:
        lo = 0
    z = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(lo, num_qb, body, (z, z))
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _pallas_bwd(q, k, v, o, lse, g, causal: bool, scale: float,
                block_q: int, block_k: int, interpret: bool, kv_rep: int = 1,
                delta=None):
    bh, sq, d = q.shape
    bhk, sk, _ = k.shape
    off = sk - sq
    if delta is None:
        delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)  # [BH, Sq]
    lse3 = lse[..., None]      # trailing singleton lane dim for TPU tiling
    delta3 = delta[..., None]

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, block_k=block_k, causal=causal, scale=scale,
                          seq_k=sk, causal_offset=off),
        grid=(bh, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),   # q
            pl.BlockSpec((1, sk, d), lambda b, i, r=kv_rep: (b // r, 0, 0)),   # k
            pl.BlockSpec((1, sk, d), lambda b, i, r=kv_rep: (b // r, 0, 0)),   # v
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),   # do
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),   # lse
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),   # delta
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        interpret=interpret,
    )(q, k, v, g, lse3, delta3)

    # dK/dV at query-head granularity (fp32 when reducing over a GQA group),
    # then segment-summed back to kv heads — inputs stay unrepeated.
    acc_dt = jnp.float32 if kv_rep > 1 else k.dtype
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, block_q=block_q, causal=causal, scale=scale,
                          seq_q=sq, causal_offset=off),
        grid=(bh, sk // block_k),
        in_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, r=kv_rep: (b // r, j, 0)),  # k
            pl.BlockSpec((1, block_k, d), lambda b, j, r=kv_rep: (b // r, j, 0)),  # v
            pl.BlockSpec((1, sq, d), lambda b, j: (b, 0, 0)),        # q
            pl.BlockSpec((1, sq, d), lambda b, j: (b, 0, 0)),        # do
            pl.BlockSpec((1, sq, 1), lambda b, j: (b, 0, 0)),        # lse
            pl.BlockSpec((1, sq, 1), lambda b, j: (b, 0, 0)),        # delta
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), acc_dt),
            jax.ShapeDtypeStruct((bh, sk, d), acc_dt),
        ],
        interpret=interpret,
    )(k, v, q, g, lse3, delta3)
    if kv_rep > 1:
        dk = dk.reshape(bhk, kv_rep, sk, d).sum(axis=1).astype(k.dtype)
        dv = dv.reshape(bhk, kv_rep, sk, d).sum(axis=1).astype(v.dtype)
    return dq, dk, dv


# --------------------------------------------------------------- vjp wiring
def _use_kernel(sq: int, sk: int, interpret: bool) -> bool:
    return (
        _HAS_PALLAS
        and (interpret or jax.default_backend() in ("tpu", "axon"))
        and sq % 8 == 0
        and sk % 8 == 0
    )


def _rep_kv(x, rep):
    """[BHk, S, D] → [BHk*rep, S, D] with j → j // rep (jnp fallback only)."""
    return jnp.repeat(x, rep, axis=0)


def block_fwd(qb, kb, vb, causal, scale, kv_rep=1, interpret=False):
    """One attention block: qb [BH, Sq, D], kb/vb [BHk, Sk, D] → (o, lse f32).

    The single dispatch point (kernel vs jnp reference, GQA handling) shared
    by the flash custom_vjp and ring attention's per-ring-step body."""
    sq, sk = qb.shape[1], kb.shape[1]
    if _use_kernel(sq, sk, interpret):
        bq, bk = get_flash_blocks("fwd", sq, sk, qb.shape[-1])
        return _pallas_fwd(qb, kb, vb, causal, scale, bq, bk, interpret,
                           kv_rep=kv_rep)
    kr = _rep_kv(kb, kv_rep) if kv_rep > 1 else kb
    vr = _rep_kv(vb, kv_rep) if kv_rep > 1 else vb
    return _ref_fwd_impl(qb, kr, vr, causal, scale)


def block_bwd(qb, kb, vb, o, lse, g, causal, scale, kv_rep=1, interpret=False,
              delta=None):
    """Backward of one attention block → (dq [BH], dk [BHk], dv [BHk]).
    ``delta`` (rowsum(g∘o)) may be precomputed by callers that reuse it
    across blocks (ring attention)."""
    sq, sk = qb.shape[1], kb.shape[1]
    if _use_kernel(sq, sk, interpret):
        bq, bk = get_flash_blocks("bwd", sq, sk, qb.shape[-1])
        return _pallas_bwd(qb, kb, vb, o, lse, g, causal, scale, bq, bk,
                           interpret, kv_rep=kv_rep, delta=delta)
    if kv_rep > 1:
        bhk, _, d = kb.shape
        dq, dkr, dvr = _ref_bwd_impl(qb, _rep_kv(kb, kv_rep), _rep_kv(vb, kv_rep),
                                     o, lse, g, causal, scale, delta=delta)
        dk = dkr.reshape(bhk, kv_rep, sk, d).sum(axis=1).astype(kb.dtype)
        dv = dvr.reshape(bhk, kv_rep, sk, d).sum(axis=1).astype(vb.dtype)
        return dq, dk, dv
    return _ref_bwd_impl(qb, kb, vb, o, lse, g, causal, scale, delta=delta)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_core(q, k, v, causal, scale, interpret, kv_rep=1):
    out, _ = _flash_core_fwd(q, k, v, causal, scale, interpret, kv_rep)
    return out


def _flash_core_fwd(q, k, v, causal, scale, interpret, kv_rep=1):
    out, lse = block_fwd(q, k, v, causal, scale, kv_rep, interpret)
    return out, (q, k, v, out, lse)


def _flash_core_bwd(causal, scale, interpret, kv_rep, res, g):
    q, k, v, o, lse = res
    return block_bwd(q, k, v, o, lse, g, causal, scale, kv_rep, interpret)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention_fwd(q, k, v, *, causal: bool = False, scale: float | None = None,
                        interpret: bool = False):
    """Public entry: q,k,v [B, S, H, D] (paddle layout) → [B, S, H, D].

    GQA (fewer KV heads than query heads) is handled inside the kernel via
    index maps — K/V are never repeated to full head count."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    hk = k.shape[2]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    rep = h // hk if hk != h else 1
    qb = jnp.moveaxis(q, 2, 1).reshape(b * h, sq, d)
    kb = jnp.moveaxis(k, 2, 1).reshape(b * hk, sk, d)
    vb = jnp.moveaxis(v, 2, 1).reshape(b * hk, sk, d)
    ob = _flash_core(qb, kb, vb, causal, scale, interpret, rep)
    return jnp.moveaxis(ob.reshape(b, h, sq, d), 1, 2)
