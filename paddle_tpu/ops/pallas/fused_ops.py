"""Fused rotary embedding + SwiGLU — Pallas TPU kernels.

Reference analogs: the CUDA fused kernels behind
/root/reference/python/paddle/incubate/nn/functional/fused_rotary_position_embedding.py
and .../swiglu.py (paddle/phi/kernels/fusion/gpu/). Both ops are
HBM-bandwidth bound; the kernels do exactly one read of each input and one
write of each output with fp32 math in VMEM, instead of the
split/concat/mul/add chain the jnp forms lower to.

Rope backward is rope with negated sin (a rotation by -theta), so the same
kernel serves fwd and bwd. SwiGLU backward is a second single-pass kernel
recomputing sigmoid from the saved inputs (no activation stash in HBM).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

__all__ = ["rope_fused", "swiglu_fused"]


def _enabled(name: str) -> bool:
    import os

    dis = os.environ.get("PADDLE_TPU_DISABLE_FUSED", "")
    return name not in [s.strip() for s in dis.split(",") if s.strip()]


def _on_tpu(interpret: bool) -> bool:
    return _HAS_PALLAS and (interpret or jax.default_backend() in ("tpu", "axon"))


# ---------------------------------------------------------------- fused rope
def _rope_kernel(x_ref, cos_ref, sin_ref, o_ref):
    c = cos_ref[...].astype(jnp.float32)[:, None, :]  # [block_s, 1, D/2]
    s = sin_ref[...].astype(jnp.float32)[:, None, :]
    xf = x_ref[0].astype(jnp.float32)  # [block_s, H, D]
    half = xf.shape[-1] // 2
    x1, x2 = xf[..., :half], xf[..., half:]
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    o_ref[0] = out.astype(o_ref.dtype)


def _pick_s_block(s: int, h: int, d: int) -> int:
    # keep the fp32 staging block [bs, h, d] ≤ ~1MB: scoped VMEM holds the
    # bf16 in/out blocks (double-buffered) + fp32 intermediates
    target = max((1 << 20) // max(h * d * 4, 1), 8)
    b = 1
    while b * 2 <= min(target, s):
        b *= 2
    while s % b:
        b //= 2
    return max(b, 1)


def _rope_one_pallas(x, cos, sin, interpret):
    """x [B,S,H,D] — blocks keep H and D whole (TPU last-two-dims rule);
    the grid walks (batch, seq block)."""
    b, s, h, d = x.shape
    bs = _pick_s_block(s, h, d)
    return pl.pallas_call(
        _rope_kernel,
        grid=(b, s // bs),
        in_specs=[
            pl.BlockSpec((1, bs, h, d), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((bs, d // 2), lambda i, j: (j, 0)),
            pl.BlockSpec((bs, d // 2), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((1, bs, h, d), lambda i, j: (i, j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=interpret,
    )(x, cos, sin)


def _rope_pallas(q, k, cos, sin, interpret):
    return (_rope_one_pallas(q, cos, sin, interpret),
            _rope_one_pallas(k, cos, sin, interpret))


def _rope_ref(q, k, cos, sin):
    def rot(x):
        xf = x.astype(jnp.float32)
        half = xf.shape[-1] // 2
        x1, x2 = xf[..., :half], xf[..., half:]
        c = cos[None, :, None, :]
        s = sin[None, :, None, :]
        return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1).astype(x.dtype)

    return rot(q), rot(k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def rope_fused(q, k, cos, sin, interpret: bool = False):
    """q [B,S,H,D], k [B,S,Hk,D], cos/sin [S, D/2] (already sliced to the
    sequence window) -> rotated (q, k)."""
    out, _ = _rope_fwd(q, k, cos, sin, interpret)
    return out


def _dims_ok(q, k) -> bool:
    return q.shape[-1] % 2 == 0 and q.shape[1] == k.shape[1]


def _rope_fwd(q, k, cos, sin, interpret):
    if _on_tpu(interpret) and _dims_ok(q, k) and _enabled("rope"):
        out = tuple(_rope_pallas(q, k, cos, sin, interpret))
    else:
        out = _rope_ref(q, k, cos, sin)
    return out, (cos, sin)


def _rope_bwd(interpret, res, g):
    cos, sin = res
    gq, gk = g
    # d/dx of a rotation by theta is a rotation of the cotangent by -theta
    if _on_tpu(interpret) and _dims_ok(gq, gk) and _enabled("rope"):
        dq, dk = _rope_pallas(gq, gk, cos, -sin, interpret)
    else:
        dq, dk = _rope_ref(gq, gk, cos, -sin)
    return dq, dk, None, None


rope_fused.defvjp(_rope_fwd, _rope_bwd)


# ------------------------------------------------------------- fused swiglu
def _swiglu_kernel(a_ref, b_ref, o_ref):
    a = a_ref[...].astype(jnp.float32)
    b = b_ref[...].astype(jnp.float32)
    o_ref[...] = (a * jax.nn.sigmoid(a) * b).astype(o_ref.dtype)


def _swiglu_bwd_kernel(a_ref, b_ref, g_ref, da_ref, db_ref):
    a = a_ref[...].astype(jnp.float32)
    b = b_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    sig = jax.nn.sigmoid(a)
    silu = a * sig
    da_ref[...] = (g * b * (sig + silu * (1.0 - sig))).astype(da_ref.dtype)
    db_ref[...] = (g * silu).astype(db_ref.dtype)


def _grid_2d(n: int, h: int):
    # cap each [br, h] bf16 block at ~256KB: the bwd holds 5 io blocks
    # (double-buffered) plus fp32 staging, all inside the 16MB scoped VMEM
    cap = max((256 << 10) // max(h * 2, 1), 8)
    br = 1
    while br * 2 <= min(cap, 256):
        br *= 2
    while n % br:
        br //= 2
    return max(br, 1)


def _swiglu_pallas(a2, b2, interpret):
    n, h = a2.shape
    br = _grid_2d(n, h)
    return pl.pallas_call(
        _swiglu_kernel,
        grid=(n // br,),
        in_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                  pl.BlockSpec((br, h), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((br, h), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h), a2.dtype),
        interpret=interpret,
    )(a2, b2)


def _swiglu_bwd_pallas(a2, b2, g2, interpret):
    n, h = a2.shape
    br = _grid_2d(n, h)
    return pl.pallas_call(
        _swiglu_bwd_kernel,
        grid=(n // br,),
        in_specs=[pl.BlockSpec((br, h), lambda i: (i, 0))] * 3,
        out_specs=[pl.BlockSpec((br, h), lambda i: (i, 0))] * 2,
        out_shape=[jax.ShapeDtypeStruct((n, h), a2.dtype),
                   jax.ShapeDtypeStruct((n, h), b2.dtype)],
        interpret=interpret,
    )(a2, b2, g2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def swiglu_fused(a, b, interpret: bool = False):
    """silu(a) * b, one HBM pass. a/b any shape with matching dims."""
    out, _ = _swiglu_fwd(a, b, interpret)
    return out


def _swiglu_fwd(a, b, interpret):
    if _on_tpu(interpret) and _enabled("swiglu"):
        shape = a.shape
        out = _swiglu_pallas(a.reshape(-1, shape[-1]), b.reshape(-1, shape[-1]),
                             interpret).reshape(shape)
    else:
        af = a.astype(jnp.float32)
        out = (af * jax.nn.sigmoid(af) * b.astype(jnp.float32)).astype(a.dtype)
    return out, (a, b)


def _swiglu_bwd(interpret, res, g):
    a, b = res
    if _on_tpu(interpret) and _enabled("swiglu"):
        shape = a.shape
        da, db = _swiglu_bwd_pallas(a.reshape(-1, shape[-1]), b.reshape(-1, shape[-1]),
                                    g.reshape(-1, shape[-1]), interpret)
        return da.reshape(shape), db.reshape(shape)
    af = a.astype(jnp.float32)
    bf = b.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    sig = jax.nn.sigmoid(af)
    silu = af * sig
    da = gf * bf * (sig + silu * (1.0 - sig))
    db = gf * silu
    return da.astype(a.dtype), db.astype(b.dtype)


swiglu_fused.defvjp(_swiglu_fwd, _swiglu_bwd)
