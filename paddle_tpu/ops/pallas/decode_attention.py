"""Serving decode attention — Pallas TPU kernels (reference analog:
/root/reference/paddle/phi/kernels/fusion/gpu/masked_multihead_attention_kernel.cu:88
and block_multi_head_attention_kernel.cu:1007 — the fused single-token-q
decode path of the reference's serving stack).

Two kernels shape the decode hot loop:

* :func:`kv_ring_write` — writes the step's K/V row into the static ring
  IN PLACE: the pallas_call aliases the ring buffer input to its output and
  the block is exactly the written row, so HBM traffic is one [KVH, D] row
  instead of the full-ring copy XLA's ``dynamic_update_slice`` makes when it
  cannot prove exclusivity (measured: 68 µs/write → ~0, ×18 writes/step on
  the 1B flagship).

* :func:`decode_attention` — q [B, 1, H, D] against the ring [B, L, KVH, D]
  in the ring's NATIVE layout (the jnp path's head-major transposes cost a
  full extra KV pass: measured 325 GB/s effective vs 736 GB/s streaming).
  One grid cell per (batch, head): fp32 online softmax over K tiles, GQA
  resolved in the BlockSpec index map (head h reads kv head h·KVH∕H — K/V
  never repeat), and a traced tile bound skips tiles past the valid length
  so read traffic scales with ``pos``, not the ring capacity.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

NEG_INF = -1e30


# ---------------------------------------------------------------- reference
def ref_decode_attention(q, kbuf, vbuf, pos, scale=None):
    """jnp reference: q [B,1,H,D], kbuf/vbuf [B,L,KVH,D], pos scalar —
    attend to cols <= pos. Matches the pre-kernel `_static_cache_attn` math."""
    b, _, h, d = q.shape
    l, kvh = kbuf.shape[1], kbuf.shape[2]
    scale = scale or 1.0 / math.sqrt(d)
    rep = h // kvh
    qh = jnp.swapaxes(q, 1, 2)  # [B,H,1,D]
    kh = jnp.swapaxes(kbuf, 1, 2)  # [B,KVH,L,D]
    vh = jnp.swapaxes(vbuf, 1, 2)
    if rep > 1:
        kh = jnp.repeat(kh, rep, axis=1)
        vh = jnp.repeat(vh, rep, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", qh.astype(jnp.float32),
                   kh.astype(jnp.float32)) * scale
    cols = jnp.arange(l)
    s = jnp.where(cols[None, None, None, :] <= pos, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, vh.astype(jnp.float32))
    return jnp.swapaxes(o, 1, 2).astype(q.dtype)


# ------------------------------------------------------------ ring write
def _write_kernel(pos_ref, new_ref, buf_ref, out_ref):
    out_ref[...] = new_ref[...]


def kv_ring_write(buf, new, pos, *, interpret=False):
    """In-place ring write: ``buf[:, pos] = new[:, 0]``.

    buf: [B, L, KVH, D] (ALIASED — returned buffer reuses the input's
    memory); new: [B, 1, KVH, D]; pos: scalar int32.
    """
    if not _HAS_PALLAS:
        return jax.lax.dynamic_update_slice(
            buf, new.astype(buf.dtype), (0, pos.astype(jnp.int32), 0, 0))
    b, l, kvh, d = buf.shape
    pos_arr = jnp.reshape(pos, (1,)).astype(jnp.int32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, 1, kvh, d), lambda i, pos_ref: (i, 0, 0, 0)),
            pl.BlockSpec((1, 1, kvh, d), lambda i, pos_ref: (i, pos_ref[0], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, kvh, d),
                               lambda i, pos_ref: (i, pos_ref[0], 0, 0)),
    )
    return pl.pallas_call(
        _write_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(buf.shape, buf.dtype),
        input_output_aliases={2: 0},  # buf aliases the output (0=pos, 1=new)
        interpret=interpret,
    )(pos_arr, new.astype(buf.dtype), buf)


# ------------------------------------------------------------ decode kernel
def _decode_kernel(pos_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
                   *, block_l: int, num_l: int, heads: int, kv_heads: int,
                   scale: float):
    """Grid (B, L-tiles). Blocks keep the ring's native [L, KVH, D] layout
    (TPU block rule: trailing dims equal the array's). Per-head online
    softmax state lives in VMEM scratch and carries across the sequential
    L-tile grid dim; tiles wholly past ``pos`` skip their compute."""
    pos = pos_ref[0]
    li = pl.program_id(1)
    rep = heads // kv_heads

    @pl.when(li == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        for h in range(heads):  # SMEM admits only scalar stores
            m_ref[h, 0] = NEG_INF
            l_ref[h, 0] = 0.0

    base = li * block_l

    @pl.when(base <= pos)
    def _tile():
        cols = base + jax.lax.broadcasted_iota(jnp.int32, (1, block_l), 1)
        valid = cols <= pos
        for h in range(heads):
            kh = h // rep
            q = q_ref[0, 0, h, :].reshape(1, -1).astype(jnp.float32) * scale
            k_tile = k_ref[0, :, kh, :].astype(jnp.float32)  # [block_l, D]
            v_tile = v_ref[0, :, kh, :].astype(jnp.float32)
            s = jax.lax.dot_general(
                q, k_tile, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # [1, block_l]
            s = jnp.where(valid, s, NEG_INF)
            m_prev = m_ref[h, 0]  # SMEM scalar
            l_prev = l_ref[h, 0]
            m_cur = jnp.max(s)
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
            l_ref[h, 0] = l_prev * alpha + jnp.sum(p)
            m_ref[h, 0] = m_new
            pv = jax.lax.dot_general(
                p, v_tile, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)  # [1, D]
            acc_ref[h:h + 1, :] = acc_ref[h:h + 1, :] * alpha + pv

    @pl.when(li == num_l - 1)
    def _emit():
        for h in range(heads):
            l_safe = jnp.maximum(l_ref[h, 0], 1e-30)
            o_ref[0, 0, h, :] = (acc_ref[h, :] / l_safe).astype(o_ref.dtype)


def decode_attention(q, kbuf, vbuf, pos, scale=None, *, block_l: int = 256,
                     interpret=False):
    """Fused single-token decode attention over the static KV ring.

    q: [B, 1, H, D]; kbuf/vbuf: [B, L, KVH, D] (native ring layout — no
    transposes); pos: scalar, attend to cols <= pos. Returns [B, 1, H, D].
    """
    b, s, h, d = q.shape
    l, kvh = kbuf.shape[1], kbuf.shape[2]
    scale = scale or 1.0 / math.sqrt(d)
    if not _HAS_PALLAS or s != 1 or h % kvh != 0:
        return ref_decode_attention(q, kbuf, vbuf, pos, scale)
    bl = min(block_l, l)
    if l % bl != 0:
        bl = l  # tiny/odd rings: one tile
    num_l = l // bl
    pos_arr = jnp.reshape(pos, (1,)).astype(jnp.int32)
    kernel = functools.partial(_decode_kernel, block_l=bl, num_l=num_l,
                               heads=h, kv_heads=kvh, scale=scale)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, num_l),
        in_specs=[
            pl.BlockSpec((1, 1, h, d), lambda i, j, p_ref: (i, 0, 0, 0)),
            pl.BlockSpec((1, bl, kvh, d), lambda i, j, p_ref: (i, j, 0, 0)),
            pl.BlockSpec((1, bl, kvh, d), lambda i, j, p_ref: (i, j, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, h, d), lambda i, j, p_ref: (i, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, d), jnp.float32),   # acc
            pltpu.SMEM((h, 1), jnp.float32),   # m (per-head scalar)
            pltpu.SMEM((h, 1), jnp.float32),   # l
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, 1, h, d), q.dtype),
        interpret=interpret,
    )(pos_arr, q, kbuf, vbuf)
