"""Fused RMSNorm (+ optional residual add) — Pallas TPU kernel.

Replaces the reference's fused_rms_norm CUDA kernel
(/root/reference/paddle/phi/kernels/fusion/gpu/fused_layernorm_kernel.cu
behind python/paddle/incubate/nn/functional/fused_rms_norm.py): one HBM
read of x (+residual), one write of each output — the residual-add and
normalization never round-trip through HBM separately. Backward is the
analytic RMSNorm vjp in jnp (elementwise + one row reduction; XLA fuses).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


def _kernel(x_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    o_ref[...] = (x * inv * w_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _kernel_residual(x_ref, r_ref, w_ref, o_ref, res_out_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32) + r_ref[...].astype(jnp.float32)
    res_out_ref[...] = x.astype(res_out_ref.dtype)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    o_ref[...] = (x * inv * w_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _rows_block(n_rows: int) -> int:
    for b in (256, 128, 64, 32, 16, 8, 4, 2, 1):
        if n_rows % b == 0:
            return b
    return 1


def _pallas_rms(x2, w, eps, interpret):
    n, h = x2.shape
    br = _rows_block(n)
    return pl.pallas_call(
        functools.partial(_kernel, eps=eps),
        grid=(n // br,),
        in_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                  pl.BlockSpec((h,), lambda i: (0,))],
        out_specs=pl.BlockSpec((br, h), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h), x2.dtype),
        interpret=interpret,
    )(x2, w)


def _pallas_rms_residual(x2, r2, w, eps, interpret):
    n, h = x2.shape
    br = _rows_block(n)
    return pl.pallas_call(
        functools.partial(_kernel_residual, eps=eps),
        grid=(n // br,),
        in_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                  pl.BlockSpec((br, h), lambda i: (i, 0)),
                  pl.BlockSpec((h,), lambda i: (0,))],
        out_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                   pl.BlockSpec((br, h), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((n, h), x2.dtype),
                   jax.ShapeDtypeStruct((n, h), x2.dtype)],
        interpret=interpret,
    )(x2, r2, w)


def _ref_rms(x, w, eps):
    xf = x.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * inv * w.astype(jnp.float32)).astype(x.dtype)


def _use_kernel(interpret: bool) -> bool:
    return _HAS_PALLAS and (interpret or jax.default_backend() in ("tpu", "axon"))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def rms_norm_fused(x, w, eps: float = 1e-6, interpret: bool = False):
    """x [..., H], w [H] -> same shape; fp32 statistics."""
    out, _ = _fwd(x, w, eps, interpret)
    return out


def _fwd(x, w, eps, interpret):
    shape = x.shape
    x2 = x.reshape(-1, shape[-1])
    if _use_kernel(interpret):
        out = _pallas_rms(x2, w, eps, interpret).reshape(shape)
    else:
        out = _ref_rms(x, w, eps)
    return out, (x, w)


def _bwd(eps, interpret, res, g):
    x, w = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    xhat = xf * inv
    gw = jnp.sum((gf * xhat).reshape(-1, x.shape[-1]), axis=0).astype(w.dtype)
    gx_hat = gf * wf
    dx = inv * (gx_hat - xhat * jnp.mean(gx_hat * xhat, axis=-1, keepdims=True))
    return dx.astype(x.dtype), gw


rms_norm_fused.defvjp(_fwd, _bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def rms_norm_residual_fused(x, residual, w, eps: float = 1e-6, interpret: bool = False):
    """-> (normed, residual_out) with residual_out = x + residual fused in."""
    out, _ = _fwd_res(x, residual, w, eps, interpret)
    return out


def _fwd_res(x, residual, w, eps, interpret):
    shape = x.shape
    x2 = x.reshape(-1, shape[-1])
    r2 = residual.reshape(-1, shape[-1])
    if _use_kernel(interpret):
        out, res_out = _pallas_rms_residual(x2, r2, w, eps, interpret)
        out, res_out = out.reshape(shape), res_out.reshape(shape)
    else:
        s = x + residual
        out, res_out = _ref_rms(s, w, eps), s
    return (out, res_out), (x, residual, w)


def _bwd_res(eps, interpret, res, gs):
    x, residual, w = res
    g_out, g_res = gs
    # keep the recomputed pre-norm stream in fp32: the forward's statistics
    # were computed from the fp32 sum
    s = x.astype(jnp.float32) + residual.astype(jnp.float32)
    dx, gw = _bwd(eps, interpret, (s, w), g_out)
    dsum = dx.astype(jnp.float32) + g_res.astype(jnp.float32)
    return dsum.astype(x.dtype), dsum.astype(residual.dtype), gw


rms_norm_residual_fused.defvjp(_fwd_res, _bwd_res)
