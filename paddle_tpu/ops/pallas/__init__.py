"""Pallas TPU kernel tier.

TPU-native analog of the reference's handwritten kernel layer
(/root/reference/paddle/phi/kernels/fusion/, third_party/flashattn, and the
Kernel Primitive API paddle/phi/kernels/primitive/kernel_primitives.h): the
small set of ops XLA does not fuse optimally gets hand-tiled VMEM kernels.
Every kernel has a jnp reference implementation used on CPU and as the
backward recompute path.
"""
