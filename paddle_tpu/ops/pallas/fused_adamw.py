"""Fused AdamW update — one Pallas pass per parameter (reference analog:
/root/reference/paddle/phi/kernels/gpu/adamw_kernel.cu — the fused multi-
tensor AdamW the reference runs instead of an op-per-expression chain).

Measured motivation (v5e, slope method): the jnp AdamW expression chain runs
at ~160 GB/s effective — XLA materializes intermediates between the moment
updates — while the ideal is ONE read-modify-write pass over grad (bf16),
master/m/v (fp32) at streaming bandwidth. This kernel does exactly that
pass: read g,w,m,v → write p(bf16),w,m,v, with the bias-correction factors
computed host-side per step and prefetched as scalars.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

# flattened [rows, 512] tiles, 256 rows per block → 512KB fp32 per operand
_LANES = 512
_ROWS = 256


def _kernel(scal_ref, g_ref, w_ref, m_ref, v_ref, p_out, w_out, m_out, v_out,
            *, b1: float, b2: float, eps: float, wd: float):
    lr = scal_ref[0]
    c1 = scal_ref[1]  # 1 - b1**t
    c2 = scal_ref[2]  # 1 - b2**t
    gf = g_ref[...].astype(jnp.float32)
    w = w_ref[...] * (1.0 - lr * wd)
    m = b1 * m_ref[...] + (1.0 - b1) * gf
    v = b2 * v_ref[...] + (1.0 - b2) * gf * gf
    upd = (m / c1) / (jnp.sqrt(v / c2) + eps)
    w = w - lr * upd
    w_out[...] = w
    m_out[...] = m
    v_out[...] = v
    p_out[...] = w.astype(p_out.dtype)


def fused_adamw_supported(n: int) -> bool:
    return _HAS_PALLAS and n % (_LANES * _ROWS) == 0


def fused_adamw(param, master, m, v, grad, lr, beta1_pow_t, beta2_pow_t, *,
                b1: float, b2: float, eps: float, wd: float, interpret=False):
    """One-pass AdamW with decoupled weight decay.

    param: bf16/fp32 [*shape]; master/m/v: fp32; grad: any float dtype.
    ``lr``/``beta?_pow_t`` may be traced scalars (beta?_pow_t = b?**t).
    Returns (new_param, new_master, new_m, new_v); master/m/v alias their
    inputs (donated in the compiled train step).
    """
    n = param.size
    shape = param.shape
    rows = n // _LANES
    grid = (rows // _ROWS,)
    scal = jnp.stack([
        jnp.asarray(lr, jnp.float32),
        1.0 - jnp.asarray(beta1_pow_t, jnp.float32),
        1.0 - jnp.asarray(beta2_pow_t, jnp.float32),
    ])

    def r2(x, dt=None):
        return x.reshape(rows, _LANES) if dt is None else x.reshape(rows, _LANES).astype(dt)

    kernel = functools.partial(_kernel, b1=b1, b2=b2, eps=eps, wd=wd)
    spec = pl.BlockSpec((_ROWS, _LANES), lambda i, s_ref: (i, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[spec, spec, spec, spec],
        out_specs=[spec, spec, spec, spec],
    )
    p_new, w_new, m_new, v_new = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((rows, _LANES), param.dtype),
            jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
        ],
        # master/m/v update in place (operand order: scal, g, w, m, v)
        input_output_aliases={2: 1, 3: 2, 4: 3},
        interpret=interpret,
    )(scal, r2(grad), r2(master, jnp.float32), r2(m), r2(v))
    return (p_new.reshape(shape), w_new.reshape(shape),
            m_new.reshape(shape), v_new.reshape(shape))
