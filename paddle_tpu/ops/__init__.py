"""Op dispatch layer (TPU-native analog of PHI dispatch, see dispatch.py)."""
from .dispatch import apply  # noqa: F401
