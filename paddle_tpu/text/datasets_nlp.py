"""NLP dataset classes (parity: /root/reference/python/paddle/text/datasets/
imdb.py, imikolov.py, wmt14.py, wmt16.py, conll05.py, movielens.py).

Sandbox stance: no network — every class takes ``data_file`` pointing at the
same archive format the reference downloads (aclImdb tar, PTB
simple-examples tar, WMT dicts+parallel-corpus tar, CoNLL-2005 release tar,
MovieLens 1M zip) and parses it identically, so locally-provided copies of
the official archives work unchanged.
"""
from __future__ import annotations

import collections
import gzip
import re
import string
import tarfile
import zipfile
from typing import Dict, List, Optional

import numpy as np

from ..io.dataset import Dataset

__all__ = ["Imdb", "Imikolov", "WMT14", "WMT16", "Conll05st", "Movielens"]

UNK_IDX = 0
_START = "<s>"
_END = "<e>"


def _require(data_file: Optional[str], name: str) -> str:
    if not data_file:
        raise RuntimeError(
            f"{name}: pass data_file pointing at a local copy of the official "
            "archive (downloading is disabled in this environment)")
    return data_file


class Imdb(Dataset):
    """IMDB sentiment (aclImdb tar). Labels: pos=0, neg=1 and samples are
    word-id arrays — BOTH per the reference's imdb.py `_load_anno` (note:
    this corrects the pre-round-3 class, which emitted raw tokens with
    inverted labels).

    Accepts either the official tar (``data_file``) or an extracted directory
    (``data_dir`` convenience; same reference label/id semantics).
    """

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 cutoff: int = 150, download: bool = False, data_dir=None):
        assert mode.lower() in ("train", "test"), mode
        self.mode = mode.lower()
        if data_dir is not None:
            self._init_from_dir(data_dir, cutoff)
            return
        self.data_file = _require(data_file, "Imdb")
        self.word_idx = self._build_word_dict(cutoff)
        self._load_anno()

    # ---- directory fallback (non-reference convenience)
    def _init_from_dir(self, data_dir, cutoff):
        import os

        docs = {}
        for sub in ("pos", "neg"):
            out = []
            d = os.path.join(data_dir, self.mode, sub)
            if os.path.isdir(d):
                for fn in sorted(os.listdir(d)):
                    with open(os.path.join(d, fn), "rb") as f:
                        out.append(self._clean(f.read()))
            docs[sub] = out
        freq = collections.defaultdict(int)
        for ds in docs.values():
            for doc in ds:
                for w in doc:
                    freq[w] += 1
        self.word_idx = self._freq_to_idx(freq, cutoff)
        unk = self.word_idx["<unk>"]
        self.docs, self.labels = [], []
        for label, sub in ((0, "pos"), (1, "neg")):
            for doc in docs[sub]:
                self.docs.append([self.word_idx.get(w, unk) for w in doc])
                self.labels.append(label)

    @staticmethod
    def _clean(raw: bytes) -> List[bytes]:
        return (raw.rstrip(b"\n\r")
                .translate(None, string.punctuation.encode("latin-1"))
                .lower().split())

    @staticmethod
    def _freq_to_idx(freq, cutoff) -> Dict[bytes, int]:
        kept = [x for x in freq.items() if x[1] > cutoff]
        kept = sorted(kept, key=lambda x: (-x[1], x[0]))
        word_idx = {w: i for i, (w, _) in enumerate(kept)}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    def _tokenize(self, pattern):
        data = []
        with tarfile.open(self.data_file) as tarf:
            tf = tarf.next()
            while tf is not None:
                if pattern.match(tf.name):
                    data.append(self._clean(tarf.extractfile(tf).read()))
                tf = tarf.next()
        return data

    def _build_word_dict(self, cutoff):
        pattern = re.compile(r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$")
        freq = collections.defaultdict(int)
        for doc in self._tokenize(pattern):
            for w in doc:
                freq[w] += 1
        return self._freq_to_idx(freq, cutoff)

    def _load_anno(self):
        unk = self.word_idx["<unk>"]
        self.docs, self.labels = [], []
        for label, sub in ((0, "pos"), (1, "neg")):
            pattern = re.compile(rf"aclImdb/{self.mode}/{sub}/.*\.txt$")
            for doc in self._tokenize(pattern):
                self.docs.append([self.word_idx.get(w, unk) for w in doc])
                self.labels.append(label)

    def __getitem__(self, idx):
        return np.array(self.docs[idx]), np.array([self.labels[idx]])

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB language-model dataset (simple-examples tar): NGRAM or SEQ mode."""

    def __init__(self, data_file: Optional[str] = None, data_type: str = "NGRAM",
                 window_size: int = -1, mode: str = "train",
                 min_word_freq: int = 50, download: bool = False):
        assert data_type.upper() in ("NGRAM", "SEQ"), data_type
        assert mode.lower() in ("train", "test"), mode
        self.data_type = data_type.upper()
        self.mode = mode.lower()
        self.window_size = window_size
        self.min_word_freq = min_word_freq
        self.data_file = _require(data_file, "Imikolov")
        self.word_idx = self._build_word_dict()
        self._load_anno()

    @staticmethod
    def _word_count(f, freq):
        for line in f:
            for w in line.strip().split():
                freq[w] += 1
            freq[b"<s>"] += 1
            freq[b"<e>"] += 1
        return freq

    def _build_word_dict(self):
        with tarfile.open(self.data_file) as tf:
            freq = collections.defaultdict(int)
            self._word_count(tf.extractfile("./simple-examples/data/ptb.train.txt"), freq)
            self._word_count(tf.extractfile("./simple-examples/data/ptb.valid.txt"), freq)
        freq.pop(b"<unk>", None)
        kept = [x for x in freq.items() if x[1] > self.min_word_freq]
        kept = sorted(kept, key=lambda x: (-x[1], x[0]))
        word_idx = {w: i for i, (w, _) in enumerate(kept)}
        word_idx[b"<unk>"] = len(word_idx)
        return word_idx

    def _load_anno(self):
        self.data = []
        # reference maps mode 'test' -> ptb.valid.txt? No: ptb.{mode}.txt with
        # mode in {train, valid}; paddle passes 'test' through — keep parity
        name = {"train": "train", "test": "valid"}[self.mode]
        unk = self.word_idx[b"<unk>"]
        with tarfile.open(self.data_file) as tf:
            f = tf.extractfile(f"./simple-examples/data/ptb.{name}.txt")
            for line in f:
                if self.data_type == "NGRAM":
                    assert self.window_size > -1, "Invalid gram length"
                    toks = [b"<s>"] + line.strip().split() + [b"<e>"]
                    if len(toks) >= self.window_size:
                        ids = [self.word_idx.get(w, unk) for w in toks]
                        for i in range(self.window_size, len(ids) + 1):
                            self.data.append(tuple(ids[i - self.window_size:i]))
                else:
                    ids = [self.word_idx.get(w, unk) for w in line.strip().split()]
                    src = [self.word_idx[b"<s>"]] + ids
                    trg = ids + [self.word_idx[b"<e>"]]
                    if self.window_size > 0 and len(src) > self.window_size:
                        continue
                    self.data.append((src, trg))

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)


class WMT14(Dataset):
    """WMT14 en→fr (paddle-preprocessed tar: src.dict/trg.dict + parallel
    corpus under {mode}/{mode})."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 dict_size: int = -1, download: bool = False):
        assert mode.lower() in ("train", "test", "gen"), mode
        self.mode = mode.lower()
        self.data_file = _require(data_file, "WMT14")
        assert dict_size > 0, "dict_size should be set as positive number"
        self.dict_size = dict_size
        self._load_data()

    def _load_data(self):
        def to_dict(fd, size):
            out = {}
            for i, line in enumerate(fd):
                if i >= size:
                    break
                out[line.strip().decode()] = i
            return out

        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(self.data_file) as f:
            members = f.getmembers()
            src_dicts = [m for m in members if m.name.endswith("src.dict")]
            trg_dicts = [m for m in members if m.name.endswith("trg.dict")]
            assert len(src_dicts) == 1 and len(trg_dicts) == 1
            self.src_dict = to_dict(f.extractfile(src_dicts[0]), self.dict_size)
            self.trg_dict = to_dict(f.extractfile(trg_dicts[0]), self.dict_size)
            suffix = f"{self.mode}/{self.mode}"
            for m in members:
                if not m.name.endswith(suffix):
                    continue
                for line in f.extractfile(m):
                    parts = line.decode().strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src_words = parts[0].split()
                    src = [self.src_dict.get(w, UNK_IDX)
                           for w in [_START] + src_words + [_END]]
                    trg_words = parts[1].split()
                    trg = [self.trg_dict.get(w, UNK_IDX) for w in trg_words]
                    if len(src) > 80 or len(trg) > 80:
                        continue
                    self.src_ids.append(src)
                    self.trg_ids.append([self.trg_dict[_START]] + trg)
                    self.trg_ids_next.append(trg + [self.trg_dict[_END]])

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]), np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)


class WMT16(WMT14):
    """WMT16 en↔de over the same preprocessed-archive surface.

    ``lang`` selects the SOURCE language (reference semantics): lang='en'
    reads the corpus as stored; lang='de' swaps source and target sides
    (ids and dicts). ``src_dict_size``/``trg_dict_size`` truncate each dict
    independently."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 src_dict_size: int = -1, trg_dict_size: int = -1,
                 lang: str = "en", download: bool = False):
        self.lang = lang
        self._src_size = src_dict_size if src_dict_size > 0 else 1 << 30
        self._trg_size = trg_dict_size if trg_dict_size > 0 else 1 << 30
        super().__init__(data_file=_require(data_file, "WMT16"), mode=mode,
                         dict_size=1 << 30)
        # re-truncate each side independently, then optionally swap direction
        self.src_dict = {w: i for w, i in self.src_dict.items() if i < self._src_size}
        self.trg_dict = {w: i for w, i in self.trg_dict.items() if i < self._trg_size}
        clip = lambda seq, n: [i if i < n else UNK_IDX for i in seq]  # noqa: E731
        self.src_ids = [clip(s, self._src_size) for s in self.src_ids]
        self.trg_ids = [clip(s, self._trg_size) for s in self.trg_ids]
        self.trg_ids_next = [clip(s, self._trg_size) for s in self.trg_ids_next]
        if lang != "en":
            # swap translation direction: target words become sources
            trg_words = [t[1:] for t in self.trg_ids]      # strip <s>
            src_words = [s[1:-1] for s in self.src_ids]    # strip <s>/<e>
            self.src_dict, self.trg_dict = self.trg_dict, self.src_dict
            s_start = self.src_dict.get(_START, UNK_IDX)
            s_end = self.src_dict.get(_END, UNK_IDX)
            t_start = self.trg_dict.get(_START, UNK_IDX)
            t_end = self.trg_dict.get(_END, UNK_IDX)
            self.src_ids = [[s_start] + w + [s_end] for w in trg_words]
            self.trg_ids = [[t_start] + w for w in src_words]
            self.trg_ids_next = [w + [t_end] for w in src_words]


class Conll05st(Dataset):
    """CoNLL-2005 SRL test.wsj split (words + props gz inside the release
    tar), emitting (sentence words, predicate, BIO labels) triples."""

    def __init__(self, data_file: Optional[str] = None, download: bool = False,
                 **kw):
        self.data_file = _require(data_file, "Conll05st")
        self._load_anno()

    def _load_anno(self):
        self.sentences, self.predicates, self.labels = [], [], []
        with tarfile.open(self.data_file) as tf:
            wf = tf.extractfile("conll05st-release/test.wsj/words/test.wsj.words.gz")
            pf = tf.extractfile("conll05st-release/test.wsj/props/test.wsj.props.gz")
            with gzip.GzipFile(fileobj=wf) as words_f, gzip.GzipFile(fileobj=pf) as props_f:
                sent, seg = [], []
                for word, prop in zip(words_f, props_f):
                    word = word.strip().decode()
                    cols = prop.strip().decode().split()
                    if not cols:  # sentence boundary
                        self._emit(sent, seg)
                        sent, seg = [], []
                    else:
                        sent.append(word)
                        seg.append(cols)
        # trailing sentence without a final blank line
        if sent:
            self._emit(sent, seg)

    def _emit(self, sent, seg):
        if not seg:
            return
        n_cols = len(seg[0])
        cols = [[row[i] for row in seg] for i in range(n_cols)]
        verbs = [v for v in cols[0] if v != "-"]
        for i, col in enumerate(cols[1:]):
            cur, inside, out = "O", False, []
            for tag in col:
                if tag == "*" and not inside:
                    out.append("O")
                elif tag == "*" and inside:
                    out.append("I-" + cur)
                elif tag == "*)":
                    out.append("I-" + cur)
                    inside = False
                elif "(" in tag and ")" in tag:
                    cur = tag[1:tag.find("*")]
                    out.append("B-" + cur)
                    inside = False
                elif "(" in tag:
                    cur = tag[1:tag.find("*")]
                    out.append("B-" + cur)
                    inside = True
                else:
                    raise RuntimeError(f"Unexpected label: {tag}")
            self.sentences.append(list(sent))
            self.predicates.append(verbs[i] if i < len(verbs) else verbs[-1])
            self.labels.append(out)

    def __getitem__(self, idx):
        return self.sentences[idx], self.predicates[idx], self.labels[idx]

    def __len__(self):
        return len(self.sentences)


class Movielens(Dataset):
    """MovieLens 1M ratings (official ml-1m zip: users.dat/movies.dat/
    ratings.dat with '::' separators)."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 test_ratio: float = 0.1, rand_seed: int = 0, download: bool = False):
        assert mode.lower() in ("train", "test"), mode
        self.mode = mode.lower()
        self.data_file = _require(data_file, "Movielens")
        rng = np.random.RandomState(rand_seed)
        with zipfile.ZipFile(self.data_file) as z:
            root = next(n for n in z.namelist() if n.endswith("ratings.dat"))
            base = root.rsplit("/", 1)[0]
            self.movie_info = {}
            with z.open(f"{base}/movies.dat") as f:
                for line in f.read().decode("latin-1").splitlines():
                    mid, title, genres = line.split("::")
                    self.movie_info[int(mid)] = {
                        "title": title, "genres": genres.split("|")}
            self.user_info = {}
            with z.open(f"{base}/users.dat") as f:
                for line in f.read().decode("latin-1").splitlines():
                    uid, gender, age, job, _zip = line.split("::")
                    self.user_info[int(uid)] = {
                        "gender": gender, "age": int(age), "job": int(job)}
            self.data = []
            with z.open(root) as f:
                for line in f.read().decode("latin-1").splitlines():
                    uid, mid, rating, _ts = line.split("::")
                    is_test = rng.rand() < test_ratio
                    if (self.mode == "test") == is_test:
                        self.data.append((int(uid), int(mid), float(rating)))

    def __getitem__(self, idx):
        uid, mid, rating = self.data[idx]
        u = self.user_info[uid]
        m = self.movie_info[mid]
        return (np.array([uid]), np.array([u["age"]]), np.array([u["job"]]),
                np.array([mid]), m["title"], m["genres"], np.array([rating]))

    def __len__(self):
        return len(self.data)
