"""paddle.text parity (/root/reference/python/paddle/text/__init__.py):
viterbi_decode / ViterbiDecoder + dataset classes.

viterbi is a lax.scan dynamic program — compiled control flow, no Python
loop over time steps (reference: text/viterbi_decode.py:31 binding the
viterbi_decode phi kernel).
"""
from __future__ import annotations

import os

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..io.dataset import Dataset
from ..nn.layer.layers import Layer
from ..ops.dispatch import apply
from ..tensor.tensor import Tensor

from .datasets_nlp import (  # noqa: E402,F401
    WMT14,
    WMT16,
    Conll05st,
    Imdb,
    Imikolov,
    Movielens,
)

__all__ = ["viterbi_decode", "ViterbiDecoder", "UCIHousing", "Imdb",
           "Imikolov", "WMT14", "WMT16", "Conll05st", "Movielens"]


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag: bool = True, name=None):
    """-> (scores [B], paths [B, T]) — highest-scoring tag sequences.

    potentials: [B, T, N] unary emission scores; transition_params: [N, N];
    lengths: [B] actual sequence lengths.
    """
    potentials = potentials if isinstance(potentials, Tensor) else Tensor(jnp.asarray(potentials))
    transition_params = transition_params if isinstance(transition_params, Tensor) \
        else Tensor(jnp.asarray(transition_params))
    lengths = lengths if isinstance(lengths, Tensor) else Tensor(jnp.asarray(lengths))

    def f(pot, trans, lens):
        B, T, N = pot.shape
        lens = lens.astype(jnp.int32)
        if include_bos_eos_tag:
            # tags N-2 = BOS, N-1 = EOS (paddle convention): sequences start
            # from BOS and end at EOS
            init = pot[:, 0] + trans[N - 2][None, :]
        else:
            init = pot[:, 0]

        def step(carry, inp):
            alpha, t = carry
            emit = inp  # [B, N]
            scores = alpha[:, :, None] + trans[None, :, :]  # [B, from, to]
            best_prev = jnp.argmax(scores, axis=1)  # [B, N]
            new_alpha = jnp.max(scores, axis=1) + emit
            active = (t < lens)[:, None]
            alpha = jnp.where(active, new_alpha, alpha)
            return (alpha, t + 1), jnp.where(active, best_prev, -1)

        (alpha, _), backptrs = lax.scan(step, (init, jnp.ones((), jnp.int32)),
                                        jnp.swapaxes(pot[:, 1:], 0, 1))
        if include_bos_eos_tag:
            alpha = alpha + trans[:, N - 1][None, :]
        scores = jnp.max(alpha, axis=-1)
        last_tag = jnp.argmax(alpha, axis=-1).astype(jnp.int32)  # [B]

        # walk pointers backward (scan over reversed time)
        def back(carry, bp_t):
            tag, t = carry
            # bp_t: [B, N] pointers for transition into step index t
            prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
            use = (t < lens - 1)  # only steps inside the sequence
            new_tag = jnp.where(use, prev, tag).astype(jnp.int32)
            return (new_tag, t - 1), tag

        (first_tag, _), rev_tags = lax.scan(
            back, (last_tag, (jnp.zeros((), jnp.int32) + T - 2)),
            backptrs, reverse=True)
        # rev_tags[t] is the tag at position t+1; prepend the first tag
        paths = jnp.concatenate([first_tag[None, :], rev_tags], axis=0)
        paths = jnp.swapaxes(paths, 0, 1)  # [B, T]
        # mask positions beyond each length with the last valid tag repeated
        pos = jnp.arange(T)[None, :]
        paths = jnp.where(pos < lens[:, None], paths, 0)
        return scores, paths

    return apply(f, potentials, transition_params, lengths, op_name="viterbi_decode", n_outs=2)


class ViterbiDecoder(Layer):
    """parity: paddle.text.ViterbiDecoder — holds transitions, decodes."""

    def __init__(self, transitions, include_bos_eos_tag: bool = True, name=None):
        super().__init__()
        self.transitions = transitions if isinstance(transitions, Tensor) \
            else Tensor(jnp.asarray(transitions))
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


# --------------------------------------------------------------- datasets
class UCIHousing(Dataset):
    """parity: text/datasets/uci_housing.py — reads a local housing.data
    (whitespace table, 13 features + target); no network in this env."""

    def __init__(self, data_file=None, mode="train"):
        if data_file is None or not os.path.exists(data_file):
            raise RuntimeError(
                "UCIHousing: pass data_file pointing at a local housing.data "
                "(no network access in this environment)")
        raw = np.loadtxt(data_file).astype(np.float32)
        feats, target = raw[:, :-1], raw[:, -1:]
        mn, mx = feats.min(0), feats.max(0)
        feats = (feats - mn) / np.maximum(mx - mn, 1e-8)
        split = int(len(raw) * 0.8)
        if mode == "train":
            self.x, self.y = feats[:split], target[:split]
        else:
            self.x, self.y = feats[split:], target[split:]

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]
