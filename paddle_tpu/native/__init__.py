"""Native (C++) runtime components, bound via ctypes.

Current components:
- ``shm_queue``: POSIX shared-memory ring buffer for DataLoader worker→parent
  batch transfer (reference analog: paddle/fluid/memory/allocation/
  mmap_allocator.h + the shm path of io/dataloader/worker.py).

The library is compiled on demand with the system C++ toolchain and cached
next to the sources; environments without a compiler fall back cleanly
(callers check ``shm_queue_available()``).
"""
from __future__ import annotations

import ctypes
import io
import os
import subprocess
import threading
from typing import List, Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "_shm_queue.so")
_SRC = os.path.join(_HERE, "shm_queue.cpp")
_lock = threading.Lock()
_lib = None
_build_err: Optional[str] = None


def _build() -> Optional[str]:
    """Compile the shared library if missing/stale; returns error or None."""
    try:
        if (not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
            cmd = ["g++", "-O2", "-shared", "-fPIC", "-o", _SO, _SRC, "-lpthread", "-lrt"]
            proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
            if proc.returncode != 0:
                return proc.stderr[-2000:]
        return None
    except Exception as e:  # no compiler / sandboxed fs
        return str(e)


def _load():
    global _lib, _build_err
    with _lock:
        if _lib is not None or _build_err is not None:
            return _lib
        _build_err = _build()
        if _build_err is None:
            lib = ctypes.CDLL(_SO)
            lib.shmq_create.restype = ctypes.c_void_p
            lib.shmq_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32]
            lib.shmq_open.restype = ctypes.c_void_p
            lib.shmq_open.argtypes = [ctypes.c_char_p]
            lib.shmq_push.restype = ctypes.c_int
            lib.shmq_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int]
            lib.shmq_pop.restype = ctypes.c_int64
            lib.shmq_pop.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
                                     ctypes.POINTER(ctypes.c_uint64), ctypes.c_int]
            lib.shmq_slot_size.restype = ctypes.c_uint64
            lib.shmq_slot_size.argtypes = [ctypes.c_void_p]
            lib.shmq_close.argtypes = [ctypes.c_void_p]
            _lib = lib
        return _lib


def shm_queue_available() -> bool:
    return _load() is not None


def build_error() -> Optional[str]:
    _load()
    return _build_err


# ------------------------------------------------------- batch (de)serialize
def encode_batch(arrays: List[np.ndarray]) -> bytes:
    """numpy .npy concatenation — C-speed, no pickle."""
    bio = io.BytesIO()
    bio.write(np.uint32(len(arrays)).tobytes())
    for a in arrays:
        sub = io.BytesIO()
        np.save(sub, np.ascontiguousarray(a), allow_pickle=False)
        raw = sub.getvalue()
        bio.write(np.uint64(len(raw)).tobytes())
        bio.write(raw)
    return bio.getvalue()


def decode_batch(buf: memoryview) -> List[np.ndarray]:
    n = int(np.frombuffer(buf[:4], np.uint32)[0])
    off = 4
    out = []
    for _ in range(n):
        ln = int(np.frombuffer(buf[off:off + 8], np.uint64)[0])
        off += 8
        out.append(np.load(io.BytesIO(bytes(buf[off:off + ln])), allow_pickle=False))
        off += ln
    return out


class ShmQueue:
    """Python face of the native ring buffer."""

    def __init__(self, name: str, slot_size: int = 16 << 20, n_slots: int = 8,
                 create: bool = True):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native shm_queue unavailable: {_build_err}")
        self._lib = lib
        self.name = name.encode()
        if create:
            self._h = lib.shmq_create(self.name, slot_size, n_slots)
        else:
            self._h = lib.shmq_open(self.name)
        if not self._h:
            raise RuntimeError(f"shm_queue {'create' if create else 'open'} failed for {name}")
        self.slot_size = lib.shmq_slot_size(self._h)
        self._rx = None  # lazily allocated: push-only workers never pay for it

    def push(self, payload: bytes, seq: int, timeout_ms: int = -1) -> bool:
        rc = self._lib.shmq_push(self._h, payload, len(payload), seq, timeout_ms)
        if rc == -1:
            raise ValueError(
                f"payload of {len(payload)} bytes exceeds slot size {self.slot_size}")
        if rc == -2:
            raise RuntimeError("shm_queue push failed (semaphore/mutex error)")
        return rc == 0

    def pop(self, timeout_ms: int = -1):
        """-> (seq, memoryview) or None on timeout. The view aliases the
        shared receive buffer: consume it before the next pop()."""
        if self._rx is None:
            # one reusable receive buffer — pop() runs in a poll loop and
            # must not allocate+memset slot_size bytes per call
            self._rx = ctypes.create_string_buffer(int(self.slot_size))
        seq = ctypes.c_uint64()
        n = self._lib.shmq_pop(self._h, self._rx, self.slot_size, ctypes.byref(seq), timeout_ms)
        if n == -3:
            return None  # timeout (distinct code: n == 0 is a valid empty payload)
        if n == -1:
            raise RuntimeError("shm_queue pop: receive buffer smaller than payload")
        if n < 0:
            raise RuntimeError("shm_queue pop failed (semaphore/mutex error)")
        return int(seq.value), memoryview(self._rx)[:n]

    def close(self):
        if self._h:
            self._lib.shmq_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
