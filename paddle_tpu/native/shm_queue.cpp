// Shared-memory ring-buffer batch queue for the DataLoader worker pipeline.
//
// TPU-native analog of the reference's DataLoader IPC tier
// (/root/reference/paddle/fluid/memory/allocation/mmap_allocator.h:45
// MemoryMapAllocation + python/paddle/io/dataloader/worker.py shm transfer):
// worker processes serialize collated numpy batches straight into a POSIX
// shared-memory ring (no pickle over a pipe); the parent maps the same ring
// and hands slot payloads to numpy zero-copy. Flow control is two
// process-shared semaphores (free slots / filled slots) + a mutex for the
// ring indices.
//
// C ABI so Python binds via ctypes (no pybind11 in this image).
#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <semaphore.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Header {
  uint64_t magic;
  uint32_t n_slots;
  uint64_t slot_size;
  uint32_t head;  // next slot to pop
  uint32_t tail;  // next slot to push
  pthread_mutex_t mu;
  sem_t free_slots;
  sem_t filled_slots;
};

struct Slot {
  uint64_t seq;
  uint64_t len;
  // payload follows
};

constexpr uint64_t kMagic = 0x707173686d71ULL;  // "pqshmq"

struct Handle {
  Header* hdr;
  size_t map_len;
  char name[256];
  bool owner;
};

char* slot_at(Header* h, uint32_t i) {
  return reinterpret_cast<char*>(h) + sizeof(Header) +
         static_cast<size_t>(i) * (sizeof(Slot) + h->slot_size);
}

}  // namespace

extern "C" {

// Create a new queue; returns an opaque handle or nullptr.
void* shmq_create(const char* name, uint64_t slot_size, uint32_t n_slots) {
  shm_unlink(name);  // stale segment from a crashed run
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  size_t len = sizeof(Header) + static_cast<size_t>(n_slots) * (sizeof(Slot) + slot_size);
  if (ftruncate(fd, static_cast<off_t>(len)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  Header* h = static_cast<Header*>(mem);
  h->magic = kMagic;
  h->n_slots = n_slots;
  h->slot_size = slot_size;
  h->head = 0;
  h->tail = 0;
  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  // a worker killed mid-push must not wedge the parent forever
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mu, &ma);
  sem_init(&h->free_slots, 1, n_slots);
  sem_init(&h->filled_slots, 1, 0);
  Handle* hd = new Handle{h, len, {0}, true};
  strncpy(hd->name, name, sizeof(hd->name) - 1);
  return hd;
}

// Open an existing queue (workers).
void* shmq_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, static_cast<size_t>(st.st_size), PROT_READ | PROT_WRITE,
                   MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Header* h = static_cast<Header*>(mem);
  if (h->magic != kMagic) {
    munmap(mem, static_cast<size_t>(st.st_size));
    return nullptr;
  }
  Handle* hd = new Handle{h, static_cast<size_t>(st.st_size), {0}, false};
  strncpy(hd->name, name, sizeof(hd->name) - 1);
  return hd;
}

static int lock_robust(pthread_mutex_t* mu) {
  int rc = pthread_mutex_lock(mu);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(mu);
    rc = 0;
  }
  return rc;
}

// Push one payload (blocks while full; timeout_ms<0 -> wait forever).
// Returns 0 ok, 1 timeout, -1 payload larger than slot, -2 sem/lock failure.
int shmq_push(void* handle, const void* data, uint64_t len, uint64_t seq,
              int timeout_ms) {
  Handle* hd = static_cast<Handle*>(handle);
  Header* h = hd->hdr;
  if (len > h->slot_size) return -1;
  if (timeout_ms < 0) {
    while (sem_wait(&h->free_slots) != 0 && errno == EINTR) {}
  } else {
    struct timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    ts.tv_sec += timeout_ms / 1000;
    ts.tv_nsec += (timeout_ms % 1000) * 1000000L;
    ts.tv_sec += ts.tv_nsec / 1000000000L;
    ts.tv_nsec %= 1000000000L;
    while (sem_timedwait(&h->free_slots, &ts) != 0) {
      if (errno == ETIMEDOUT) return 1;
      if (errno != EINTR) return -2;
    }
  }
  if (lock_robust(&h->mu) != 0) return -2;
  uint32_t i = h->tail;
  h->tail = (h->tail + 1) % h->n_slots;
  Slot* s = reinterpret_cast<Slot*>(slot_at(h, i));
  s->seq = seq;
  s->len = len;
  memcpy(reinterpret_cast<char*>(s) + sizeof(Slot), data, len);
  pthread_mutex_unlock(&h->mu);
  sem_post(&h->filled_slots);
  return 0;
}

// Pop one payload into out (cap bytes). Returns payload length (>= 0 —
// empty payloads are valid), -3 on timeout, -1 on too-small buffer, -2 on
// sem/lock failure; seq written to *seq_out.
int64_t shmq_pop(void* handle, void* out, uint64_t cap, uint64_t* seq_out,
                 int timeout_ms) {
  Handle* hd = static_cast<Handle*>(handle);
  Header* h = hd->hdr;
  if (timeout_ms < 0) {
    while (sem_wait(&h->filled_slots) != 0 && errno == EINTR) {}
  } else {
    struct timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    ts.tv_sec += timeout_ms / 1000;
    ts.tv_nsec += (timeout_ms % 1000) * 1000000L;
    ts.tv_sec += ts.tv_nsec / 1000000000L;
    ts.tv_nsec %= 1000000000L;
    while (sem_timedwait(&h->filled_slots, &ts) != 0) {
      if (errno == ETIMEDOUT) return -3;
      if (errno != EINTR) return -2;
    }
  }
  if (lock_robust(&h->mu) != 0) return -2;
  Slot* s = reinterpret_cast<Slot*>(slot_at(h, h->head));
  uint64_t len = s->len;
  if (len > cap) {
    // head NOT advanced: the slot stays at the front for a retry with a
    // bigger buffer, and its filled token is returned
    pthread_mutex_unlock(&h->mu);
    sem_post(&h->filled_slots);
    return -1;
  }
  h->head = (h->head + 1) % h->n_slots;
  *seq_out = s->seq;
  memcpy(out, reinterpret_cast<char*>(s) + sizeof(Slot), len);
  pthread_mutex_unlock(&h->mu);
  sem_post(&h->free_slots);
  return static_cast<int64_t>(len);
}

uint64_t shmq_slot_size(void* handle) {
  return static_cast<Handle*>(handle)->hdr->slot_size;
}

void shmq_close(void* handle) {
  Handle* hd = static_cast<Handle*>(handle);
  bool owner = hd->owner;
  char name[256];
  strncpy(name, hd->name, sizeof(name));
  munmap(hd->hdr, hd->map_len);
  if (owner) shm_unlink(name);
  delete hd;
}

}  // extern "C"
