"""hapi callbacks (parity: python/paddle/hapi/callbacks.py — Callback,
ProgBarLogger, ModelCheckpoint, EarlyStopping, LRScheduler,
ReduceLROnPlateau surfaces)."""
from __future__ import annotations

import time
from typing import List, Optional

import numpy as np

__all__ = ["Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
           "EarlyStopping", "LRScheduler", "ReduceLROnPlateau", "config_callbacks"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = dict(params or {})

    # train
    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    # eval
    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks: Optional[List[Callback]] = None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def fan_out(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)

            return fan_out
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """Step/epoch console logging (parity: hapi ProgBarLogger)."""

    def __init__(self, log_freq: int = 1, verbose: int = 2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._t0 = time.time()

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and self.log_freq and (step + 1) % self.log_freq == 0:
            loss = (logs or {}).get("loss")
            msg = f"step {step + 1}"
            if loss is not None:
                msg += f": loss {float(loss):.4f}"
            print(msg)

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            loss = (logs or {}).get("loss")
            extra = f" loss {float(loss):.4f}" if loss is not None else ""
            print(f"Epoch {epoch + 1}:{extra} ({time.time() - self._t0:.1f}s)")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq: int = 1, save_dir: Optional[str] = None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and self.model is not None and (epoch + 1) % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")

    def on_train_end(self, logs=None):
        if self.save_dir and self.model is not None:
            self.model.save(f"{self.save_dir}/final")


class EarlyStopping(Callback):
    def __init__(self, monitor: str = "loss", mode: str = "auto", patience: int = 0,
                 verbose: int = 1, min_delta: float = 0.0, baseline=None,
                 save_best_model: bool = True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.best = None
        self.wait = 0
        self.stopped_epoch = 0

    def _better(self, cur, best):
        if self.mode == "min":
            return cur < best - self.min_delta
        return cur > best + self.min_delta

    def on_train_begin(self, logs=None):
        self.wait = 0
        self.best = self.baseline

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        cur = float(np.asarray(cur).reshape(-1)[0])
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped_epoch = epoch
                if self.model is not None:
                    self.model.stop_training = True


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (by_step or by_epoch)."""

    def __init__(self, by_step: bool = True, by_epoch: bool = False):
        super().__init__()
        if by_step and by_epoch:
            raise ValueError("by_step and by_epoch are mutually exclusive")
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched

        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class ReduceLROnPlateau(Callback):
    def __init__(self, monitor: str = "loss", factor: float = 0.1, patience: int = 10,
                 verbose: int = 1, mode: str = "auto", min_delta: float = 1e-4,
                 cooldown: int = 0, min_lr: float = 0.0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.best = None
        self.wait = 0
        self.cooldown_counter = 0

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        cur = float(np.asarray(cur).reshape(-1)[0])
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        better = (self.best is None or
                  (cur < self.best - self.min_delta if self.mode == "min"
                   else cur > self.best + self.min_delta))
        if better:
            self.best = cur
            self.wait = 0
        elif self.cooldown_counter <= 0:
            self.wait += 1
            if self.wait >= self.patience:
                opt = getattr(self.model, "_optimizer", None)
                if opt is not None:
                    lr = opt.get_lr() if hasattr(opt, "get_lr") else float(opt._learning_rate)
                    new_lr = max(lr * self.factor, self.min_lr)
                    if hasattr(opt, "set_lr"):
                        opt.set_lr(new_lr)
                    else:
                        opt._learning_rate = new_lr
                self.cooldown_counter = self.cooldown
                self.wait = 0


def config_callbacks(callbacks=None, model=None, epochs=None, steps=None,
                     log_freq=2, verbose=2, save_freq=1, save_dir=None,
                     metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks.append(ProgBarLogger(log_freq, verbose=verbose))
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    cl = CallbackList(cbks)
    cl.set_model(model)
    cl.set_params({"epochs": epochs, "steps": steps, "verbose": verbose,
                   "metrics": metrics or []})
    return cl
