"""High-level Model API (parity: /root/reference/python/paddle/hapi/model.py:1081
paddle.Model.fit/evaluate/predict + callbacks + summary)."""
from __future__ import annotations

import time
from typing import List, Optional

import numpy as np

from ..io import DataLoader
from ..metric import Metric
from ..tensor.tensor import Tensor

__all__ = ["Model", "summary"]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._train_step = None

    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) else [metrics]
        return self

    # ------------------------------------------------------------ training
    def _loss_fn(self, net, *batch):
        *xs, y = batch
        out = net(*xs)
        return self._loss(out, y)

    def train_batch(self, inputs, labels=None):
        from .. import jit

        if self._train_step is None:
            self._train_step = jit.TrainStep(self.network, self._loss_fn, self._optimizer)
        batch = list(inputs if isinstance(inputs, (list, tuple)) else [inputs])
        if labels is not None:
            batch += list(labels if isinstance(labels, (list, tuple)) else [labels])
        loss = self._train_step(*batch)
        return [float(loss.numpy())]

    def eval_batch(self, inputs, labels=None):
        was_training = self.network.training
        self.network.eval()
        xs = list(inputs if isinstance(inputs, (list, tuple)) else [inputs])
        out = self.network(*xs)
        loss = None
        if self._loss is not None and labels is not None:
            y = labels[0] if isinstance(labels, (list, tuple)) else labels
            loss = float(self._loss(out, y).numpy())
        if was_training:
            self.network.train()
        return loss, out

    def predict_batch(self, inputs):
        was_training = self.network.training
        self.network.eval()
        xs = list(inputs if isinstance(inputs, (list, tuple)) else [inputs])
        out = self.network(*xs)
        if was_training:
            self.network.train()
        return out

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1, eval_freq=1,
            log_freq=10, save_dir=None, save_freq=1, verbose=2, drop_last=False,
            shuffle=True, num_workers=0, callbacks=None, accumulate_grad_batches=1, num_iters=None):
        from .callbacks import config_callbacks

        loader = train_data if isinstance(train_data, DataLoader) else DataLoader(
            train_data, batch_size=batch_size, shuffle=shuffle, drop_last=drop_last,
            num_workers=num_workers,
        )
        cbks = config_callbacks(callbacks, model=self, epochs=epochs,
                                steps=len(loader) if hasattr(loader, "__len__") else None,
                                log_freq=log_freq, verbose=verbose,
                                save_freq=save_freq, save_dir=save_dir,
                                metrics=[m.name() for m in self._metrics
                                         if callable(getattr(m, "name", None))])
        self.stop_training = False
        history = {"loss": []}
        it = 0
        accum = max(int(accumulate_grad_batches), 1)
        cbks.on_train_begin()
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            epoch_losses = []
            for bi, batch in enumerate(loader):
                cbks.on_train_batch_begin(bi)
                xs, y = batch[:-1], batch[-1]
                if accum > 1:
                    # gradient accumulation rides the eager path: backward each
                    # micro-batch, step every `accum` batches
                    xs_l = list(xs if isinstance(xs, (list, tuple)) else [xs])
                    out = self.network(*xs_l)
                    loss_t = self._loss(out, y) / accum
                    loss_t.backward()
                    loss = float(loss_t.numpy()) * accum
                    if (bi + 1) % accum == 0:
                        self._optimizer.step()
                        self._optimizer.clear_grad()
                else:
                    loss = self.train_batch(xs, y)[0]
                epoch_losses.append(loss)
                it += 1
                cbks.on_train_batch_end(bi, {"loss": loss})
                if num_iters is not None and it >= num_iters:
                    break
            epoch_loss = float(np.mean(epoch_losses)) if epoch_losses else None
            history["loss"].append(epoch_loss)
            logs = {"loss": epoch_loss}
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                eval_res = self.evaluate(eval_data, batch_size=batch_size, verbose=verbose)
                for k, v in eval_res.items():
                    if isinstance(v, list):
                        v = v[0] if v else None
                    # eval loss lands as 'val_loss'; metric names verbatim —
                    # what EarlyStopping/ReduceLROnPlateau monitor
                    logs["val_loss" if k == "loss" else k] = v
            cbks.on_epoch_end(epoch, logs)
            if getattr(self, "stop_training", False):
                break
            if num_iters is not None and it >= num_iters:
                break
        cbks.on_train_end({"loss": history["loss"][-1] if history["loss"] else None})
        if self._train_step is not None:
            self._train_step.sync_to_model()
        return history

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2, num_workers=0,
                 callbacks=None, num_iters=None):
        loader = eval_data if isinstance(eval_data, DataLoader) else DataLoader(
            eval_data, batch_size=batch_size, num_workers=num_workers,
        )
        if self._train_step is not None:
            self._train_step.sync_to_model()
        for m in self._metrics:
            m.reset()
        losses = []
        for i, batch in enumerate(loader):
            xs, y = batch[:-1], batch[-1]
            loss, out = self.eval_batch(xs, y)
            if loss is not None:
                losses.append(loss)
            for m in self._metrics:
                computed = m.compute(out, y)
                if isinstance(computed, (list, tuple)):
                    m.update(*computed)
                else:
                    m.update(computed)
            if num_iters is not None and i + 1 >= num_iters:
                break
        result = {"loss": [float(np.mean(losses))] if losses else []}
        for m in self._metrics:
            result[m.name()] = m.accumulate()
        if verbose:
            print("Eval:", result)
        return result

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False, verbose=1, callbacks=None):
        loader = test_data if isinstance(test_data, DataLoader) else DataLoader(
            test_data, batch_size=batch_size, num_workers=num_workers,
        )
        outputs = []
        for batch in loader:
            xs = batch[:-1] if isinstance(batch, (list, tuple)) and len(batch) > 1 else [batch[0] if isinstance(batch, (list, tuple)) else batch]
            outputs.append(self.predict_batch(xs))
        return outputs

    # ------------------------------------------------------------ persistence
    def save(self, path, training=True):
        from .. import framework_io

        if self._train_step is not None:
            self._train_step.sync_to_model()
        framework_io.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            framework_io.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from .. import framework_io

        state = framework_io.load(path + ".pdparams")
        self.network.set_state_dict(state)
        if not reset_optimizer and self._optimizer is not None:
            try:
                self._optimizer.set_state_dict(framework_io.load(path + ".pdopt"))
            except FileNotFoundError:
                pass

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        return summary(self.network, input_size, dtype)


def summary(net, input_size=None, dtypes=None):
    """parity: paddle.summary — parameter count table."""
    rows = []
    total = 0
    trainable = 0
    for name, p in net.named_parameters():
        n = p.size
        total += n
        if not p.stop_gradient:
            trainable += n
        rows.append((name, tuple(p.shape), n))
    width = max((len(r[0]) for r in rows), default=20) + 2
    lines = ["-" * (width + 30), f"{'Layer (param)':<{width}}{'Shape':<18}{'Params':>10}", "-" * (width + 30)]
    for name, shape, n in rows:
        lines.append(f"{name:<{width}}{str(shape):<18}{n:>10}")
    lines += ["-" * (width + 30), f"Total params: {total}", f"Trainable params: {trainable}"]
    out = "\n".join(lines)
    print(out)
    return {"total_params": total, "trainable_params": trainable}
