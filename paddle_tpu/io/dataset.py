"""Datasets (parity: python/paddle/io/dataloader/dataset.py)."""
from __future__ import annotations

import bisect
from typing import List, Sequence

import numpy as np

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset", "ChainDataset",
    "ConcatDataset", "Subset", "random_split",
]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence):
        from ..tensor.tensor import Tensor

        assert all(t.shape[0] == tensors[0].shape[0] for t in tensors)
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets: List[Dataset]):
        self.datasets = datasets
        assert all(len(d) == len(datasets[0]) for d in datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            if isinstance(sample, (list, tuple)):
                out.extend(sample)
            else:
                out.append(sample)
        return tuple(out)

    def __len__(self):
        return len(self.datasets[0])


class ChainDataset(IterableDataset):
    def __init__(self, datasets: List[IterableDataset]):
        self.datasets = datasets

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets: List[Dataset]):
        self.datasets = list(datasets)
        self.cumulative_sizes = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx = len(self) + idx
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = 0 if ds_idx == 0 else self.cumulative_sizes[ds_idx - 1]
        return self.datasets[ds_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset: Dataset, indices: Sequence[int]):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    from ..framework.random import default_generator

    if all(isinstance(l, float) for l in lengths):
        n = len(dataset)
        counts = [int(np.floor(n * frac)) for frac in lengths]
        rem = n - sum(counts)
        for i in range(rem):
            counts[i % len(counts)] += 1
        lengths = counts
    total = sum(lengths)
    assert total == len(dataset)
    import jax

    key = (generator or default_generator()).next_key()
    perm = np.asarray(jax.random.permutation(key, total))
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[off : off + l].tolist()))
        off += l
    return out
