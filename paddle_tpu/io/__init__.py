"""Data loading (parity: python/paddle/io).

Reference design: worker processes + shared-memory mmap handoff
(/root/reference/python/paddle/io/dataloader/dataloader_iter.py:370,
paddle/fluid/memory/allocation/mmap_allocator.h:45). TPU-native: the hot
requirement is keeping the accelerator fed — a background prefetch pipeline
(threads by default; numpy collation releases the GIL) with a bounded queue,
then a single H2D device_put per batch. Static shapes are the contract
(SURVEY.md §7.3): collation pads/stacks to fixed shapes.
"""
from .dataset import (  # noqa: F401
    ChainDataset,
    ComposeDataset,
    ConcatDataset,
    Dataset,
    IterableDataset,
    Subset,
    TensorDataset,
    random_split,
)
from .sampler import (  # noqa: F401
    BatchSampler,
    DistributedBatchSampler,
    RandomSampler,
    Sampler,
    SequenceSampler,
    SubsetRandomSampler,
    WeightedRandomSampler,
)
from .reader import DataLoader, default_collate_fn  # noqa: F401


class WorkerInfo:
    """paddle.io.get_worker_info parity: per-worker id/num/seed/dataset."""

    def __init__(self, id, num_workers, dataset=None):  # noqa: A002
        self.id = id
        self.num_workers = num_workers
        self.seed = id
        self.dataset = dataset


_worker_info = None


def get_worker_info():
    """Inside a DataLoader worker process returns its WorkerInfo; None in the
    main process (parity: io/dataloader/worker.py get_worker_info)."""
    return _worker_info
