"""DataLoader (parity: python/paddle/io/reader.py:266 and the process+shm
worker pipeline of python/paddle/io/dataloader/dataloader_iter.py:370).

Pipeline: index batches from the BatchSampler → worker pool fetches+collates
numpy batches → bounded prefetch queue → main thread converts to device
Tensors with one batch of device-transfer lookahead (PJRT transfers are
async, so the next batch is in flight while the current one trains).
``num_workers>0`` forks real worker processes (the reference's
_worker_loop analog; batches ride a multiprocessing queue). ``num_workers=0``
uses GIL-releasing prefetch threads. Unpicklable datasets fall back to
threads with a warning.
"""
from __future__ import annotations

import multiprocessing as mp
import os
import pickle
import queue
import threading
import warnings
from typing import Callable, Optional

import numpy as np

from ..tensor.tensor import Tensor

__all__ = ["DataLoader", "default_collate_fn"]


def default_collate_fn(batch):
    """Stack samples into batch arrays (parity: collate.py default_collate_fn)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        import jax.numpy as jnp

        return Tensor(jnp.stack([b._value for b in batch]))
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(s)) for s in transposed]
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return batch


class _ProbeBigButFine(Exception):
    pass


def _probe_picklable(obj, cap: int = 1 << 20):
    """Raise if ``obj`` is unpicklable; succeed early (without serializing
    everything) once ``cap`` bytes prove it pickles fine so far."""

    class _Sink:
        def __init__(self):
            self.n = 0

        def write(self, b):
            self.n += len(b)
            if self.n > cap:
                raise _ProbeBigButFine

    try:
        pickle.Pickler(_Sink()).dump(obj)
    except _ProbeBigButFine:
        pass


def _to_tensor(obj):
    if isinstance(obj, Tensor):
        return obj
    if isinstance(obj, np.ndarray):
        return Tensor(obj)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_tensor(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _to_tensor(v) for k, v in obj.items()}
    return obj


class _PrefetchIter:
    _SENTINEL = object()

    def __init__(self, loader):
        self.loader = loader
        ds = loader.dataset
        self.batches = iter(loader.batch_sampler)
        self.collate = loader.collate_fn or default_collate_fn
        depth = max(2, loader.prefetch_factor)
        self.q: queue.Queue = queue.Queue(maxsize=depth)
        self.workers = []
        self._idx_q: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        n_workers = max(1, loader.num_workers)
        self._out_buf = {}
        self._next_out = 0
        for indices in self.batches:
            self._idx_q.put(indices)
        self._total = self._idx_q.qsize()
        self._emitted = 0
        # order-preserving: tag batches with sequence numbers
        self._tagged_q: queue.Queue = queue.Queue()
        i = 0
        while not self._idx_q.empty():
            self._tagged_q.put((i, self._idx_q.get()))
            i += 1
        for _ in range(n_workers):
            t = threading.Thread(target=self._worker, daemon=True)
            t.start()
            self.workers.append(t)

    def _worker(self):
        while not self._stop.is_set():
            try:
                seq, indices = self._tagged_q.get_nowait()
            except queue.Empty:
                return
            try:
                samples = [self.loader.dataset[i] for i in indices]
                batch = self.collate(samples)
                self.q.put((seq, batch))
            except Exception as e:  # propagate to main thread
                self.q.put((seq, e))

    def __iter__(self):
        return self

    def __next__(self):
        if self._emitted >= self._total:
            self._stop.set()
            raise StopIteration
        while self._next_out not in self._out_buf:
            seq, item = self.q.get()
            self._out_buf[seq] = item
        item = self._out_buf.pop(self._next_out)
        self._next_out += 1
        self._emitted += 1
        if isinstance(item, Exception):
            self._stop.set()
            raise item
        return _to_tensor(item)


def _tree_flatten(obj):
    """(arrays, spec) for nested list/tuple/dict of numpy arrays/scalars."""
    arrays = []

    def walk(o):
        if isinstance(o, np.ndarray):
            arrays.append(o)
            return {"t": "a"}
        if isinstance(o, (int, float, np.integer, np.floating, bool, np.bool_)):
            arrays.append(np.asarray(o))
            return {"t": "a"}
        if isinstance(o, (list, tuple)):
            return {"t": "l" if isinstance(o, list) else "u",
                    "c": [walk(x) for x in o]}
        if isinstance(o, dict):
            keys = list(o)
            return {"t": "d", "k": keys, "c": [walk(o[k]) for k in keys]}
        raise TypeError(f"unsupported type for shm transport: {type(o)}")

    spec = walk(obj)
    return arrays, spec


def _tree_unflatten(spec, arrays, pos=None):
    pos = pos or [0]
    t = spec["t"]
    if t == "a":
        a = arrays[pos[0]]
        pos[0] += 1
        return a
    if t in ("l", "u"):
        items = [_tree_unflatten(c, arrays, pos) for c in spec["c"]]
        return items if t == "l" else tuple(items)
    return {k: _tree_unflatten(c, arrays, pos)
            for k, c in zip(spec["k"], spec["c"])}


def _worker_loop(dataset, collate, idx_q, out_q, init_fn, wid, shm_name=None,
                 num_workers=0, base_seed=0):
    """Runs in a forked worker process (parity: dataloader_iter._worker_loop).

    With ``shm_name`` the collated batch rides the native shared-memory ring
    (paddle_tpu.native.ShmQueue) — no pickle; the mp queue carries only
    errors and oversized/unsupported fallbacks."""
    import paddle_tpu.io as _io

    info = _io.WorkerInfo(wid, num_workers, dataset)
    info.seed = base_seed + wid  # per-run seed, reference base_seed contract
    _io._worker_info = info
    if init_fn is not None:
        init_fn(wid)
    shm = None
    if shm_name is not None:
        try:
            from ..native import ShmQueue, encode_batch

            shm = ShmQueue(shm_name, create=False)
        except Exception:
            shm = None
    while True:
        item = idx_q.get()
        if item is None:
            if shm is not None:
                shm.close()
            return
        seq, indices = item
        try:
            batch = collate([dataset[i] for i in indices])
            if shm is not None:
                try:
                    import json

                    from ..native import encode_batch

                    arrays, spec = _tree_flatten(batch)
                    payload = json.dumps(spec).encode() + b"\x00" + encode_batch(arrays)
                    shm.push(payload, seq)
                    continue
                except (TypeError, ValueError):
                    pass  # unsupported structure / too big: fall back to mp queue
            out_q.put((seq, batch))
        except Exception as e:  # must cross the pickle boundary
            import traceback

            out_q.put((seq, RuntimeError(
                f"DataLoader worker {wid} failed: {e}\n{traceback.format_exc()}")))


class _ProcessIter:
    """Process-worker pipeline: N forked workers pull tagged index batches
    and push collated numpy batches; the parent restores order and overlaps
    the host->device transfer one batch ahead."""

    def __init__(self, loader):
        self.loader = loader
        collate = loader.collate_fn or default_collate_fn
        batches = list(loader.batch_sampler)
        self._total = len(batches)
        self._emitted = 0
        self._next_out = 0
        self._out_buf = {}
        self._lookahead = None
        self._shm = None
        shm_name = None
        if loader.use_shared_memory:
            try:
                from ..native import ShmQueue

                shm_name = f"/pq_dl_{os.getpid()}_{id(self) & 0xFFFFFF:x}"
                self._shm = ShmQueue(
                    shm_name, slot_size=64 << 20,
                    n_slots=max(2, loader.prefetch_factor) * max(1, loader.num_workers))
            except Exception:
                self._shm, shm_name = None, None
        method = "fork" if "fork" in mp.get_all_start_methods() else "spawn"
        ctx = mp.get_context(method)
        self._idx_q = ctx.Queue()
        self._out_q = ctx.Queue(maxsize=max(2, loader.prefetch_factor) * max(1, loader.num_workers))
        for i, b in enumerate(batches):
            self._idx_q.put((i, list(b)))
        self.workers = []
        base_seed = int(np.random.randint(0, 2**31 - 1))
        for wid in range(loader.num_workers):
            self._idx_q.put(None)
            p = ctx.Process(target=_worker_loop,
                            args=(loader.dataset, collate, self._idx_q, self._out_q,
                                  loader.worker_init_fn, wid, shm_name,
                                  loader.num_workers, base_seed), daemon=True)
            p.start()
            self.workers.append(p)

    def _recv_one(self) -> bool:
        """Pull one batch from either transport into _out_buf; False if none."""
        if self._shm is not None:
            # errors and oversized fallbacks on the mp queue first (cheap,
            # non-blocking) so they aren't delayed behind the shm wait
            try:
                seq, item = self._out_q.get_nowait()
                self._out_buf[seq] = item
                return True
            except queue.Empty:
                pass
            got = self._shm.pop(timeout_ms=200)
            if got is not None:
                import json

                from ..native import decode_batch

                seq, buf = got
                sep = bytes(buf).index(b"\x00")
                spec = json.loads(bytes(buf[:sep]).decode())
                arrays = decode_batch(buf[sep + 1:])
                self._out_buf[seq] = _tree_unflatten(spec, arrays)
                return True
            return False
        try:
            seq, item = self._out_q.get(timeout=1.0)
        except queue.Empty:
            return False
        self._out_buf[seq] = item
        return True

    def _fetch(self):
        import time as _time

        deadline = (_time.time() + self.loader.timeout) if self.loader.timeout else None
        while self._next_out not in self._out_buf:
            if self._recv_one():
                continue
            # a dead worker (fork deadlock, OOM-kill) must surface as an
            # error, not a permanent hang
            if any(not p.is_alive() and p.exitcode not in (0, None)
                   for p in self.workers):
                self._shutdown()
                raise RuntimeError(
                    "DataLoader worker process died unexpectedly "
                    "(killed or crashed before reporting an error)")
            if deadline is not None and _time.time() > deadline:
                self._shutdown()
                raise RuntimeError(
                    f"DataLoader timed out after {self.loader.timeout}s "
                    "waiting for a worker batch")
        item = self._out_buf.pop(self._next_out)
        self._next_out += 1
        if isinstance(item, Exception):
            self._shutdown()
            raise item
        return _to_tensor(item)  # starts the async device transfer

    def _shutdown(self):
        for p in self.workers:
            if p.is_alive():
                p.terminate()
        self.workers = []
        if self._shm is not None:
            self._shm.close()
            self._shm = None

    def __iter__(self):
        return self

    def __next__(self):
        if self._emitted >= self._total:
            self._shutdown()
            raise StopIteration
        if self._lookahead is None:
            self._lookahead = self._fetch()
        current = self._lookahead
        self._lookahead = self._fetch() if self._next_out < self._total else None
        self._emitted += 1
        return current

    def __del__(self):
        self._shutdown()


class _IterableIter:
    def __init__(self, loader):
        self.loader = loader
        self.it = iter(loader.dataset)
        self.collate = loader.collate_fn or default_collate_fn
        self.batch_size = loader.batch_size
        self.drop_last = loader.drop_last

    def __iter__(self):
        return self

    def __next__(self):
        batch = []
        try:
            for _ in range(self.batch_size):
                batch.append(next(self.it))
        except StopIteration:
            if not batch or self.drop_last:
                raise
        return _to_tensor(self.collate(batch))


class DataLoader:
    def __init__(
        self, dataset, feed_list=None, places=None, return_list=True, batch_sampler=None,
        batch_size=1, shuffle=False, drop_last=False, collate_fn=None, num_workers=0,
        use_buffer_reader=True, prefetch_factor=2, use_shared_memory=True, timeout=0,
        worker_init_fn=None, persistent_workers=False,
    ):
        from .dataset import IterableDataset

        self.dataset = dataset
        self.collate_fn = collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.use_shared_memory = use_shared_memory
        self._iterable = isinstance(dataset, IterableDataset)
        if not self._iterable:
            if batch_sampler is not None:
                self.batch_sampler = batch_sampler
            else:
                from .sampler import BatchSampler

                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size, drop_last=drop_last
                )

    def __iter__(self):
        if self._iterable:
            return _IterableIter(self)
        if self.num_workers > 0:
            # fork inherits the dataset without pickling; a spawn-only
            # platform pickles for real, so probe the instance — but cap the
            # probe at 1MB so a huge in-memory dataset isn't serialized twice
            if "fork" in mp.get_all_start_methods():
                return _ProcessIter(self)
            try:
                _probe_picklable(self.dataset)
                if self.collate_fn is not None:
                    _probe_picklable(self.collate_fn)
                return _ProcessIter(self)
            except Exception as e:
                warnings.warn(
                    f"DataLoader: dataset/collate_fn not picklable ({e}); "
                    "falling back to thread workers")
        return _PrefetchIter(self)

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    def __call__(self):
        return self.__iter__()
