"""Concrete distributions (parity: /root/reference/python/paddle/distribution/
normal.py, uniform.py, bernoulli.py, beta.py, binomial.py, categorical.py,
cauchy.py, chi2.py, dirichlet.py, exponential.py, gamma.py, geometry.py,
gumbel.py, laplace.py, lognormal.py, multinomial.py, multivariate_normal.py,
poisson.py, student_t.py, independent.py).

Math rides jnp / jax.scipy.special; sampling rides jax.random with threefry
keys from the framework Generator; everything is taped through dispatch so
parameters receive gradients (reparameterized where the reference is)."""
from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from ..tensor.tensor import Tensor
from .distribution import Distribution, _shape, _t

__all__ = [
    "Normal", "Uniform", "Bernoulli", "Beta", "Binomial", "Categorical",
    "Cauchy", "Chi2", "ContinuousBernoulli", "Dirichlet", "Exponential",
    "ExponentialFamily", "Gamma", "Geometric", "Gumbel", "Laplace",
    "LogNormal", "Multinomial", "MultivariateNormal", "Poisson", "StudentT",
    "Independent", "LKJCholesky",
]

_HALF_LOG_2PI = 0.5 * math.log(2 * math.pi)


def _bshape(*vals) -> tuple:
    return jnp.broadcast_shapes(*(jnp.shape(v._value) for v in vals))


class ExponentialFamily(Distribution):
    """Marker base (parity: exponential_family.py); closed-form KLs are
    registered pairwise in kl.py instead of via Bregman divergences."""


class Normal(ExponentialFamily):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(batch_shape=_bshape(self.loc, self.scale))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return self._apply(lambda s: jnp.broadcast_to(s * s, self.batch_shape), self.scale)

    def rsample(self, shape=()):
        shp = self._extend_shape(shape)
        key = self._key()
        return self._apply(
            lambda l, s: l + s * jax.random.normal(key, shp, jnp.result_type(l)),
            self.loc, self.scale, op_name="normal_rsample")

    def log_prob(self, value):
        value = _t(value)
        return self._apply(
            lambda v, l, s: -((v - l) ** 2) / (2 * s * s) - jnp.log(s) - _HALF_LOG_2PI,
            value, self.loc, self.scale, op_name="normal_log_prob")

    def entropy(self):
        return self._apply(
            lambda s: jnp.broadcast_to(0.5 + _HALF_LOG_2PI + jnp.log(s), self.batch_shape),
            self.scale)

    def cdf(self, value):
        value = _t(value)
        return self._apply(
            lambda v, l, s: 0.5 * (1 + jsp.erf((v - l) / (s * jnp.sqrt(2.0)))),
            value, self.loc, self.scale)

    def icdf(self, value):
        value = _t(value)
        return self._apply(
            lambda v, l, s: l + s * jnp.sqrt(2.0) * jsp.erfinv(2 * v - 1),
            value, self.loc, self.scale)

    def probs(self, value):
        return self.prob(value)


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        self._base = Normal(self.loc, self.scale)
        super().__init__(batch_shape=self._base.batch_shape)

    @property
    def mean(self):
        return self._apply(lambda l, s: jnp.exp(l + s * s / 2), self.loc, self.scale)

    @property
    def variance(self):
        return self._apply(
            lambda l, s: (jnp.exp(s * s) - 1) * jnp.exp(2 * l + s * s), self.loc, self.scale)

    def rsample(self, shape=()):
        from ..tensor.math import exp

        return exp(self._base.rsample(shape))

    def log_prob(self, value):
        value = _t(value)
        return self._apply(
            lambda v, l, s: -((jnp.log(v) - l) ** 2) / (2 * s * s) - jnp.log(v * s) - _HALF_LOG_2PI,
            value, self.loc, self.scale)

    def entropy(self):
        return self._apply(
            lambda l, s: jnp.broadcast_to(0.5 + _HALF_LOG_2PI + jnp.log(s) + l, self.batch_shape),
            self.loc, self.scale)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _t(low)
        self.high = _t(high)
        super().__init__(batch_shape=_bshape(self.low, self.high))

    @property
    def mean(self):
        return self._apply(lambda a, b: (a + b) / 2, self.low, self.high)

    @property
    def variance(self):
        return self._apply(lambda a, b: (b - a) ** 2 / 12, self.low, self.high)

    def rsample(self, shape=()):
        shp = self._extend_shape(shape)
        key = self._key()
        return self._apply(
            lambda a, b: a + (b - a) * jax.random.uniform(key, shp, jnp.result_type(a)),
            self.low, self.high, op_name="uniform_rsample")

    def log_prob(self, value):
        value = _t(value)
        return self._apply(
            lambda v, a, b: jnp.where((v >= a) & (v < b), -jnp.log(b - a), -jnp.inf),
            value, self.low, self.high)

    def entropy(self):
        return self._apply(lambda a, b: jnp.log(b - a), self.low, self.high)


class Bernoulli(ExponentialFamily):
    def __init__(self, probs=None, logits=None, name=None):
        if (probs is None) == (logits is None):
            raise ValueError("pass exactly one of probs/logits")
        if probs is not None:
            self.probs = _t(probs)
            self.logits = self._apply(
                lambda p: jnp.log(p) - jnp.log1p(-p), self.probs)
        else:
            self.logits = _t(logits)
            self.probs = self._apply(jax.nn.sigmoid, self.logits)
        super().__init__(batch_shape=jnp.shape(self.probs._value))

    @property
    def mean(self):
        return self.probs

    @property
    def variance(self):
        return self._apply(lambda p: p * (1 - p), self.probs)

    def sample(self, shape=()):
        shp = self._extend_shape(shape)
        key = self._key()
        with __import__("paddle_tpu").no_grad():
            return self._apply(
                lambda p: jax.random.bernoulli(key, p, shp).astype(jnp.result_type(p)),
                self.probs)

    rsample = sample

    def log_prob(self, value):
        value = _t(value)
        return self._apply(
            lambda v, lg: v * jax.nn.log_sigmoid(lg) + (1 - v) * jax.nn.log_sigmoid(-lg),
            value, self.logits)

    def entropy(self):
        return self._apply(
            lambda p: -(p * jnp.log(jnp.clip(p, 1e-30)) + (1 - p) * jnp.log(jnp.clip(1 - p, 1e-30))),
            self.probs)


class ContinuousBernoulli(Distribution):
    """parity: continuous_bernoulli.py (lims handling simplified)."""

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs = _t(probs)
        self._lims = lims
        super().__init__(batch_shape=jnp.shape(self.probs._value))

    def _const(self, p):
        # normalizing constant C(p) = 2 atanh(1-2p) / (1-2p), -> 2 near p=.5
        near = (p > self._lims[0]) & (p < self._lims[1])
        safe = jnp.where(near, 0.4, p)
        c = 2 * jnp.arctanh(1 - 2 * safe) / (1 - 2 * safe)
        # 2*atanh(y)/y = 2*(1 + y^2/3 + ...) = 2 + (2/3) y^2 for y = 1-2p
        taylor = 2.0 + (1 - 2 * p) ** 2 * 2 / 3
        return jnp.where(near, taylor, c)

    @property
    def mean(self):
        def f(p):
            near = (p > self._lims[0]) & (p < self._lims[1])
            safe = jnp.where(near, 0.4, p)
            m = safe / (2 * safe - 1) + 1 / (2 * jnp.arctanh(1 - 2 * safe))
            return jnp.where(near, 0.5, m)

        return self._apply(f, self.probs)

    @property
    def variance(self):
        def f(p):
            near = (p > self._lims[0]) & (p < self._lims[1])
            safe = jnp.where(near, 0.4, p)
            x = jnp.arctanh(1 - 2 * safe)
            v = safe * (safe - 1) / (1 - 2 * safe) ** 2 + 1 / (4 * x * x)
            return jnp.where(near, 1.0 / 12, v)

        return self._apply(f, self.probs)

    def log_prob(self, value):
        value = _t(value)
        return self._apply(
            lambda v, p: v * jnp.log(jnp.clip(p, 1e-30)) + (1 - v) * jnp.log(jnp.clip(1 - p, 1e-30))
            + jnp.log(self._const(p)),
            value, self.probs)

    def rsample(self, shape=()):
        shp = self._extend_shape(shape)
        key = self._key()

        def icdf(p):
            u = jax.random.uniform(key, shp, jnp.result_type(p))
            near = (p > self._lims[0]) & (p < self._lims[1])
            safe = jnp.where(near, 0.4, p)
            s = (jnp.log1p(u * (2 * safe - 1) / (1 - safe)) /
                 (jnp.log(safe) - jnp.log1p(-safe)))
            return jnp.where(near, u, s)

        return self._apply(icdf, self.probs, op_name="cb_rsample")


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        # paddle's Categorical(logits) treats input as unnormalized log-probs
        # only if negative/unnormalized; we follow torch/paddle: logits arg
        if logits is None and probs is None:
            raise ValueError("pass logits or probs")
        if logits is not None:
            self.logits = _t(logits)
            self.probs = self._apply(lambda lg: jax.nn.softmax(lg, -1), self.logits)
        else:
            self.probs = _t(probs)
            self.logits = self._apply(lambda p: jnp.log(jnp.clip(p / p.sum(-1, keepdims=True), 1e-30)), self.probs)
        shape = jnp.shape(self.probs._value)
        super().__init__(batch_shape=shape[:-1])
        self._num_events = shape[-1]

    def sample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        key = self._key()
        with __import__("paddle_tpu").no_grad():
            return self._apply(
                lambda lg: jax.random.categorical(key, lg, -1, shape=shp), self.logits)

    def log_prob(self, value):
        value = _t(value)
        def _lp(v, lg):
            lp = jax.nn.log_softmax(lg, -1)
            batch = jnp.broadcast_shapes(jnp.shape(v), lp.shape[:-1])
            lp = jnp.broadcast_to(lp, batch + lp.shape[-1:])
            v = jnp.broadcast_to(v, batch)
            return jnp.take_along_axis(
                lp, v[..., None].astype(jnp.int32), -1)[..., 0]

        return self._apply(_lp, value, self.logits)

    def probs_of(self, value):
        return self.prob(value)

    def entropy(self):
        return self._apply(
            lambda lg: -jnp.sum(jax.nn.softmax(lg, -1) * jax.nn.log_softmax(lg, -1), -1),
            self.logits)


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs = _t(probs)
        shape = jnp.shape(self.probs._value)
        super().__init__(batch_shape=shape[:-1], event_shape=shape[-1:])

    @property
    def mean(self):
        return self._apply(lambda p: self.total_count * p / p.sum(-1, keepdims=True), self.probs)

    @property
    def variance(self):
        return self._apply(
            lambda p: self.total_count * (p / p.sum(-1, keepdims=True)) * (1 - p / p.sum(-1, keepdims=True)),
            self.probs)

    def sample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        key = self._key()

        def f(p):
            p = p / p.sum(-1, keepdims=True)
            idx = jax.random.categorical(key, jnp.log(p), -1,
                                         shape=(self.total_count,) + shp)
            onehot = jax.nn.one_hot(idx, p.shape[-1], dtype=jnp.result_type(p))
            return onehot.sum(0)

        with __import__("paddle_tpu").no_grad():
            return self._apply(f, self.probs)

    def log_prob(self, value):
        value = _t(value)

        def f(v, p):
            p = p / p.sum(-1, keepdims=True)
            logc = (jsp.gammaln(self.total_count + 1.0)
                    - jnp.sum(jsp.gammaln(v + 1.0), -1))
            return logc + jnp.sum(v * jnp.log(jnp.clip(p, 1e-30)), -1)

        return self._apply(f, value, self.probs)


class Dirichlet(ExponentialFamily):
    def __init__(self, concentration, name=None):
        self.concentration = _t(concentration)
        shape = jnp.shape(self.concentration._value)
        super().__init__(batch_shape=shape[:-1], event_shape=shape[-1:])

    @property
    def mean(self):
        return self._apply(lambda c: c / c.sum(-1, keepdims=True), self.concentration)

    @property
    def variance(self):
        def f(c):
            a0 = c.sum(-1, keepdims=True)
            m = c / a0
            return m * (1 - m) / (a0 + 1)

        return self._apply(f, self.concentration)

    def rsample(self, shape=()):
        shp = _shape(shape) + self.batch_shape + self.event_shape
        key = self._key()
        return self._apply(
            lambda c: jax.random.dirichlet(key, jnp.broadcast_to(c, shp), shape=shp[:-1]),
            self.concentration, op_name="dirichlet_rsample")

    def log_prob(self, value):
        value = _t(value)
        return self._apply(
            lambda v, c: jnp.sum((c - 1) * jnp.log(v), -1)
            + jsp.gammaln(c.sum(-1)) - jnp.sum(jsp.gammaln(c), -1),
            value, self.concentration)

    def entropy(self):
        def f(c):
            a0 = c.sum(-1)
            k = c.shape[-1]
            return (jnp.sum(jsp.gammaln(c), -1) - jsp.gammaln(a0)
                    + (a0 - k) * jsp.digamma(a0)
                    - jnp.sum((c - 1) * jsp.digamma(c), -1))

        return self._apply(f, self.concentration)


class Beta(ExponentialFamily):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _t(alpha)
        self.beta = _t(beta)
        super().__init__(batch_shape=_bshape(self.alpha, self.beta))

    @property
    def mean(self):
        return self._apply(lambda a, b: a / (a + b), self.alpha, self.beta)

    @property
    def variance(self):
        return self._apply(
            lambda a, b: a * b / ((a + b) ** 2 * (a + b + 1)), self.alpha, self.beta)

    def rsample(self, shape=()):
        shp = self._extend_shape(shape)
        key = self._key()
        return self._apply(
            lambda a, b: jax.random.beta(key, a, b, shp), self.alpha, self.beta,
            op_name="beta_rsample")

    def log_prob(self, value):
        value = _t(value)
        return self._apply(
            lambda v, a, b: (a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v)
            - (jsp.gammaln(a) + jsp.gammaln(b) - jsp.gammaln(a + b)),
            value, self.alpha, self.beta)

    def entropy(self):
        def f(a, b):
            ab = a + b
            logB = jsp.gammaln(a) + jsp.gammaln(b) - jsp.gammaln(ab)
            return (logB - (a - 1) * jsp.digamma(a) - (b - 1) * jsp.digamma(b)
                    + (ab - 2) * jsp.digamma(ab))

        return self._apply(f, self.alpha, self.beta)


class Gamma(ExponentialFamily):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _t(concentration)
        self.rate = _t(rate)
        super().__init__(batch_shape=_bshape(self.concentration, self.rate))

    @property
    def mean(self):
        return self._apply(lambda c, r: c / r, self.concentration, self.rate)

    @property
    def variance(self):
        return self._apply(lambda c, r: c / (r * r), self.concentration, self.rate)

    def rsample(self, shape=()):
        shp = self._extend_shape(shape)
        key = self._key()
        return self._apply(
            lambda c, r: jax.random.gamma(key, jnp.broadcast_to(c, shp)) / r,
            self.concentration, self.rate, op_name="gamma_rsample")

    def log_prob(self, value):
        value = _t(value)
        return self._apply(
            lambda v, c, r: c * jnp.log(r) + (c - 1) * jnp.log(v) - r * v - jsp.gammaln(c),
            value, self.concentration, self.rate)

    def entropy(self):
        return self._apply(
            lambda c, r: c - jnp.log(r) + jsp.gammaln(c) + (1 - c) * jsp.digamma(c),
            self.concentration, self.rate)


class Chi2(Gamma):
    def __init__(self, df, name=None):
        df_t = _t(df)
        half = Tensor(jnp.asarray(0.5, jnp.result_type(df_t._value)))
        from ..tensor.math import multiply

        super().__init__(multiply(df_t, half), Tensor(jnp.broadcast_to(jnp.asarray(0.5), jnp.shape(df_t._value))))
        self.df = df_t


class Exponential(ExponentialFamily):
    def __init__(self, rate, name=None):
        self.rate = _t(rate)
        super().__init__(batch_shape=jnp.shape(self.rate._value))

    @property
    def mean(self):
        return self._apply(lambda r: 1 / r, self.rate)

    @property
    def variance(self):
        return self._apply(lambda r: 1 / (r * r), self.rate)

    def rsample(self, shape=()):
        shp = self._extend_shape(shape)
        key = self._key()
        return self._apply(
            lambda r: jax.random.exponential(key, shp, jnp.result_type(r)) / r,
            self.rate, op_name="exponential_rsample")

    def log_prob(self, value):
        value = _t(value)
        return self._apply(lambda v, r: jnp.log(r) - r * v, value, self.rate)

    def entropy(self):
        return self._apply(lambda r: 1 - jnp.log(r), self.rate)

    def cdf(self, value):
        value = _t(value)
        return self._apply(lambda v, r: 1 - jnp.exp(-r * v), value, self.rate)


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p, k = 0, 1, ... (paddle convention)."""

    def __init__(self, probs, name=None):
        self.probs = _t(probs)
        super().__init__(batch_shape=jnp.shape(self.probs._value))

    @property
    def mean(self):
        return self._apply(lambda p: (1 - p) / p, self.probs)

    @property
    def variance(self):
        return self._apply(lambda p: (1 - p) / (p * p), self.probs)

    def sample(self, shape=()):
        shp = self._extend_shape(shape)
        key = self._key()
        with __import__("paddle_tpu").no_grad():
            return self._apply(
                lambda p: jnp.floor(jnp.log1p(-jax.random.uniform(key, shp, jnp.result_type(p)))
                                    / jnp.log1p(-p)),
                self.probs)

    rsample = sample

    def log_prob(self, value):
        value = _t(value)
        return self._apply(
            lambda v, p: v * jnp.log1p(-p) + jnp.log(p), value, self.probs)

    def entropy(self):
        return self._apply(
            lambda p: (-(1 - p) * jnp.log1p(-p) - p * jnp.log(p)) / p, self.probs)

    def cdf(self, value):
        value = _t(value)
        return self._apply(lambda v, p: 1 - (1 - p) ** (v + 1), value, self.probs)


class Binomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = _t(total_count)
        self.probs = _t(probs)
        super().__init__(batch_shape=_bshape(self.total_count, self.probs))

    @property
    def mean(self):
        return self._apply(lambda n, p: n * p, self.total_count, self.probs)

    @property
    def variance(self):
        return self._apply(lambda n, p: n * p * (1 - p), self.total_count, self.probs)

    def sample(self, shape=()):
        shp = self._extend_shape(shape)
        key = self._key()
        with __import__("paddle_tpu").no_grad():
            return self._apply(
                lambda n, p: jax.random.binomial(key, jnp.broadcast_to(n, shp).astype(jnp.float32),
                                                 jnp.broadcast_to(p, shp)),
                self.total_count, self.probs)

    rsample = sample

    def log_prob(self, value):
        value = _t(value)
        return self._apply(
            lambda v, n, p: (jsp.gammaln(n + 1.0) - jsp.gammaln(v + 1.0)
                             - jsp.gammaln(n - v + 1.0)
                             + v * jnp.log(jnp.clip(p, 1e-30))
                             + (n - v) * jnp.log(jnp.clip(1 - p, 1e-30))),
            value, self.total_count, self.probs)


class Poisson(ExponentialFamily):
    def __init__(self, rate, name=None):
        self.rate = _t(rate)
        super().__init__(batch_shape=jnp.shape(self.rate._value))

    @property
    def mean(self):
        return self.rate

    @property
    def variance(self):
        return self.rate

    def sample(self, shape=()):
        shp = self._extend_shape(shape)
        key = self._key()
        with __import__("paddle_tpu").no_grad():
            return self._apply(
                lambda r: jax.random.poisson(key, jnp.broadcast_to(r, shp)).astype(jnp.result_type(r)),
                self.rate)

    rsample = sample

    def log_prob(self, value):
        value = _t(value)
        return self._apply(
            lambda v, r: v * jnp.log(r) - r - jsp.gammaln(v + 1.0), value, self.rate)


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(batch_shape=_bshape(self.loc, self.scale))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return self._apply(lambda s: 2 * s * s, self.scale)

    def rsample(self, shape=()):
        shp = self._extend_shape(shape)
        key = self._key()

        def f(l, s):
            u = jax.random.uniform(key, shp, jnp.result_type(l), minval=-0.5 + 1e-7, maxval=0.5)
            return l - s * jnp.sign(u) * jnp.log1p(-2 * jnp.abs(u))

        return self._apply(f, self.loc, self.scale, op_name="laplace_rsample")

    def log_prob(self, value):
        value = _t(value)
        return self._apply(
            lambda v, l, s: -jnp.abs(v - l) / s - jnp.log(2 * s),
            value, self.loc, self.scale)

    def entropy(self):
        return self._apply(
            lambda s: jnp.broadcast_to(1 + jnp.log(2 * s), self.batch_shape), self.scale)

    def cdf(self, value):
        value = _t(value)
        return self._apply(
            lambda v, l, s: 0.5 - 0.5 * jnp.sign(v - l) * jnp.expm1(-jnp.abs(v - l) / s),
            value, self.loc, self.scale)

    def icdf(self, value):
        value = _t(value)
        return self._apply(
            lambda q, l, s: l - s * jnp.sign(q - 0.5) * jnp.log1p(-2 * jnp.abs(q - 0.5)),
            value, self.loc, self.scale)


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(batch_shape=_bshape(self.loc, self.scale))

    @property
    def mean(self):
        return self._apply(lambda l, s: l + s * np.euler_gamma, self.loc, self.scale)

    @property
    def variance(self):
        return self._apply(lambda s: (math.pi ** 2 / 6) * s * s, self.scale)

    @property
    def stddev(self):
        from ..tensor.math import sqrt

        return sqrt(self.variance)

    def rsample(self, shape=()):
        shp = self._extend_shape(shape)
        key = self._key()
        return self._apply(
            lambda l, s: l + s * jax.random.gumbel(key, shp, jnp.result_type(l)),
            self.loc, self.scale, op_name="gumbel_rsample")

    def log_prob(self, value):
        value = _t(value)

        def f(v, l, s):
            z = (v - l) / s
            return -(z + jnp.exp(-z)) - jnp.log(s)

        return self._apply(f, value, self.loc, self.scale)

    def entropy(self):
        return self._apply(
            lambda s: jnp.broadcast_to(jnp.log(s) + 1 + np.euler_gamma, self.batch_shape),
            self.scale)


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(batch_shape=_bshape(self.loc, self.scale))

    @property
    def mean(self):
        raise ValueError("Cauchy distribution has no mean")

    @property
    def variance(self):
        raise ValueError("Cauchy distribution has no variance")

    def rsample(self, shape=()):
        shp = self._extend_shape(shape)
        key = self._key()
        return self._apply(
            lambda l, s: l + s * jax.random.cauchy(key, shp, jnp.result_type(l)),
            self.loc, self.scale, op_name="cauchy_rsample")

    def log_prob(self, value):
        value = _t(value)
        return self._apply(
            lambda v, l, s: -jnp.log(math.pi) - jnp.log(s) - jnp.log1p(((v - l) / s) ** 2),
            value, self.loc, self.scale)

    def entropy(self):
        return self._apply(
            lambda s: jnp.broadcast_to(jnp.log(4 * math.pi * s), self.batch_shape), self.scale)

    def cdf(self, value):
        value = _t(value)
        return self._apply(
            lambda v, l, s: jnp.arctan((v - l) / s) / math.pi + 0.5,
            value, self.loc, self.scale)


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = _t(df)
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(batch_shape=_bshape(self.df, self.loc, self.scale))

    @property
    def mean(self):
        return self._apply(
            lambda df, l: jnp.where(df > 1, jnp.broadcast_to(l, self.batch_shape), jnp.nan),
            self.df, self.loc)

    @property
    def variance(self):
        return self._apply(
            lambda df, s: jnp.where(df > 2, s * s * df / (df - 2), jnp.nan),
            self.df, self.scale)

    def rsample(self, shape=()):
        shp = self._extend_shape(shape)
        key = self._key()
        return self._apply(
            lambda df, l, s: l + s * jax.random.t(key, jnp.broadcast_to(df, shp)),
            self.df, self.loc, self.scale, op_name="studentt_rsample")

    def log_prob(self, value):
        value = _t(value)

        def f(v, df, l, s):
            z = (v - l) / s
            return (jsp.gammaln((df + 1) / 2) - jsp.gammaln(df / 2)
                    - 0.5 * jnp.log(df * math.pi) - jnp.log(s)
                    - (df + 1) / 2 * jnp.log1p(z * z / df))

        return self._apply(f, value, self.df, self.loc, self.scale)

    def entropy(self):
        def f(df, s):
            return ((df + 1) / 2 * (jsp.digamma((df + 1) / 2) - jsp.digamma(df / 2))
                    + 0.5 * jnp.log(df) + jsp.betaln(df / 2, jnp.asarray(0.5, df.dtype))
                    + jnp.log(s))

        return self._apply(f, self.df, self.scale)


class MultivariateNormal(Distribution):
    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None, name=None):
        self.loc = _t(loc)
        given = [x for x in (covariance_matrix, precision_matrix, scale_tril) if x is not None]
        if len(given) != 1:
            raise ValueError("pass exactly one of covariance_matrix / precision_matrix / scale_tril")
        if scale_tril is not None:
            self.scale_tril = _t(scale_tril)
        elif covariance_matrix is not None:
            cov = _t(covariance_matrix)
            self.scale_tril = self._apply(jnp.linalg.cholesky, cov)
            self.covariance_matrix = cov
        else:
            prec = _t(precision_matrix)
            self.scale_tril = self._apply(
                lambda pm: jnp.linalg.cholesky(jnp.linalg.inv(pm)), prec)
        d = jnp.shape(self.loc._value)[-1]
        super().__init__(
            batch_shape=jnp.broadcast_shapes(jnp.shape(self.loc._value)[:-1],
                                             jnp.shape(self.scale_tril._value)[:-2]),
            event_shape=(d,))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return self._apply(lambda st: jnp.sum(st * st, -1), self.scale_tril)

    def rsample(self, shape=()):
        shp = self._extend_shape(shape)
        key = self._key()
        return self._apply(
            lambda l, st: l + jnp.einsum("...ij,...j->...i",
                                         st, jax.random.normal(key, shp, jnp.result_type(l))),
            self.loc, self.scale_tril, op_name="mvn_rsample")

    def log_prob(self, value):
        value = _t(value)
        d = self.event_shape[0]

        def f(v, l, st):
            diff = v - l
            sol = jax.scipy.linalg.solve_triangular(st, diff[..., None], lower=True)[..., 0]
            m = jnp.sum(sol * sol, -1)
            logdet = jnp.sum(jnp.log(jnp.diagonal(st, axis1=-2, axis2=-1)), -1)
            return -0.5 * (d * math.log(2 * math.pi) + m) - logdet

        return self._apply(f, value, self.loc, self.scale_tril)

    def entropy(self):
        d = self.event_shape[0]
        return self._apply(
            lambda st: 0.5 * d * (1 + math.log(2 * math.pi))
            + jnp.sum(jnp.log(jnp.diagonal(st, axis1=-2, axis2=-1)), -1),
            self.scale_tril)


class Independent(Distribution):
    """parity: independent.py — reinterpret batch dims as event dims."""

    def __init__(self, base, reinterpreted_batch_ndims=None, reinterpreted_batch_rank=None):
        n = reinterpreted_batch_ndims if reinterpreted_batch_ndims is not None else reinterpreted_batch_rank
        if n is None:
            raise ValueError("pass reinterpreted_batch_rank")
        self.base = base
        self._n = int(n)
        bs = base.batch_shape
        super().__init__(batch_shape=bs[:len(bs) - self._n],
                         event_shape=bs[len(bs) - self._n:] + base.event_shape)

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def sample(self, shape=()):
        return self.base.sample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        from ..tensor.math import sum as psum  # noqa: A004

        return psum(lp, axis=list(range(lp.ndim - self._n, lp.ndim))) if self._n else lp

    def entropy(self):
        ent = self.base.entropy()
        from ..tensor.math import sum as psum  # noqa: A004

        return psum(ent, axis=list(range(ent.ndim - self._n, ent.ndim))) if self._n else ent


class LKJCholesky(Distribution):
    """LKJ prior over Cholesky factors of correlation matrices (parity:
    distribution/lkj_cholesky.py; onion-method sampling)."""

    def __init__(self, dim=2, concentration=1.0, sample_method="onion", name=None):
        if dim < 2:
            raise ValueError("dim must be >= 2")
        self.dim = dim
        self.concentration = _t(concentration)
        self.sample_method = sample_method
        batch = jnp.shape(self.concentration._value)
        super().__init__(batch_shape=batch, event_shape=(dim, dim))

    def sample(self, shape=()):
        import numpy as np

        with __import__("paddle_tpu").no_grad():
            key = self._key()
            d = self.dim
            eta = float(jnp.reshape(self.concentration._value, (-1,))[0])
            shp = tuple(shape)
            n = int(np.prod(shp)) if shp else 1

            def one(k):
                # onion method; radius and direction need INDEPENDENT keys
                ks = jax.random.split(k, d)
                L = jnp.zeros((d, d))
                L = L.at[0, 0].set(1.0)
                for i in range(1, d):
                    beta_i = eta + (d - 1 - i) / 2.0
                    ky, ku = jax.random.split(ks[i])
                    y = jax.random.beta(ky, i / 2.0, beta_i)
                    u = jax.random.normal(ku, (i,))
                    u = u / jnp.linalg.norm(u)
                    w = jnp.sqrt(y) * u
                    L = L.at[i, :i].set(w)
                    L = L.at[i, i].set(jnp.sqrt(jnp.maximum(1 - y, 1e-12)))
                return L

            keys = jax.random.split(key, n)
            outs = jax.vmap(one)(keys)
            outs = outs.reshape(shp + (d, d)) if shp else outs[0]
            return Tensor(outs)

    def log_prob(self, value):
        value = _t(value)
        d = self.dim

        def f(L, eta):
            diag = jnp.diagonal(L, axis1=-2, axis2=-1)[..., 1:]
            orders = jnp.arange(2, d + 1, dtype=jnp.float32)
            exponents = 2 * (eta - 1) + d - orders
            unnorm = jnp.sum(exponents * jnp.log(jnp.maximum(diag, 1e-30)), axis=-1)
            # normalization (Stan reference): product of beta normalizers
            ks = jnp.arange(1, d, dtype=jnp.float32)
            alpha = eta + (d - 1 - ks) / 2.0
            lognorm = jnp.sum(
                0.5 * ks * jnp.log(jnp.pi)
                + jax.scipy.special.gammaln(alpha)
                - jax.scipy.special.gammaln(alpha + ks / 2.0))
            return unnorm - lognorm

        return self._apply(f, value, self.concentration, op_name="lkj_log_prob")
