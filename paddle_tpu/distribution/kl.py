"""KL divergence registry (parity:
/root/reference/python/paddle/distribution/kl.py:52 kl_divergence, :84
register_kl — same multi-dispatch-with-MRO-resolution contract)."""
from __future__ import annotations

import math
from functools import total_ordering

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from ..ops.dispatch import apply
from .distribution import Distribution
from .distributions import (
    Bernoulli, Beta, Binomial, Categorical, Dirichlet, Exponential, Gamma,
    Geometric, Laplace, LogNormal, Normal, Poisson, Uniform,
)

__all__ = ["register_kl", "kl_divergence"]

_REGISTRY = {}


@total_ordering
class _Match:
    def __init__(self, *types):
        self.types = types

    def __eq__(self, other):
        return self.types == other.types

    def __le__(self, other):
        return all(issubclass(a, b) for a, b in zip(self.types, other.types))


def register_kl(cls_p, cls_q):
    def decorator(fn):
        _REGISTRY[(cls_p, cls_q)] = fn
        return fn

    return decorator


def _dispatch(cls_p, cls_q):
    matches = [(p, q) for (p, q) in _REGISTRY
               if issubclass(cls_p, p) and issubclass(cls_q, q)]
    if not matches:
        raise NotImplementedError(
            f"kl_divergence({cls_p.__name__}, {cls_q.__name__}) is not registered")
    left = min(_Match(p, q) for p, q in matches)
    return _REGISTRY[left.types]


def kl_divergence(p: Distribution, q: Distribution):
    return _dispatch(type(p), type(q))(p, q)


# ----------------------------------------------------------------- pairs
@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    return apply(
        lambda pl, ps, ql, qs: (jnp.log(qs / ps) + (ps * ps + (pl - ql) ** 2) / (2 * qs * qs) - 0.5),
        p.loc, p.scale, q.loc, q.scale, op_name="kl_normal")


@register_kl(LogNormal, LogNormal)
def _kl_lognormal(p, q):
    return _kl_normal_normal(p._base, q._base)


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    return apply(
        lambda pa, pb, qa, qb: jnp.where(
            (qa <= pa) & (pb <= qb), jnp.log((qb - qa) / (pb - pa)), jnp.inf),
        p.low, p.high, q.low, q.high)


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    def f(pp, qp):
        t1 = pp * (jnp.log(jnp.clip(pp, 1e-30)) - jnp.log(jnp.clip(qp, 1e-30)))
        t2 = (1 - pp) * (jnp.log(jnp.clip(1 - pp, 1e-30)) - jnp.log(jnp.clip(1 - qp, 1e-30)))
        return t1 + t2

    return apply(f, p.probs, q.probs, op_name="kl_bernoulli")


@register_kl(Geometric, Geometric)
def _kl_geometric(p, q):
    return apply(
        lambda pp, qp: (-(-pp * jnp.log(pp) - (1 - pp) * jnp.log1p(-pp)) / pp)
        + (-jnp.log(qp) - (1 - pp) / pp * jnp.log1p(-qp)),
        p.probs, q.probs)


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    import jax

    return apply(
        lambda pl, ql: jnp.sum(
            jax.nn.softmax(pl, -1) * (jax.nn.log_softmax(pl, -1) - jax.nn.log_softmax(ql, -1)), -1),
        p.logits, q.logits, op_name="kl_categorical")


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    def f(pa, pb, qa, qb):
        pt = pa + pb
        return (jsp.gammaln(pt) - jsp.gammaln(pa) - jsp.gammaln(pb)
                - jsp.gammaln(qa + qb) + jsp.gammaln(qa) + jsp.gammaln(qb)
                + (pa - qa) * jsp.digamma(pa) + (pb - qb) * jsp.digamma(pb)
                + (qa + qb - pt) * jsp.digamma(pt))

    return apply(f, p.alpha, p.beta, q.alpha, q.beta, op_name="kl_beta")


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet(p, q):
    def f(pc, qc):
        p0 = pc.sum(-1)
        return (jsp.gammaln(p0) - jnp.sum(jsp.gammaln(pc), -1)
                - jsp.gammaln(qc.sum(-1)) + jnp.sum(jsp.gammaln(qc), -1)
                + jnp.sum((pc - qc) * (jsp.digamma(pc) - jsp.digamma(p0)[..., None]), -1))

    return apply(f, p.concentration, q.concentration, op_name="kl_dirichlet")


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    return apply(
        lambda pr, qr: jnp.log(pr) - jnp.log(qr) + qr / pr - 1, p.rate, q.rate)


@register_kl(Gamma, Gamma)
def _kl_gamma(p, q):
    def f(pc, pr, qc, qr):
        return ((pc - qc) * jsp.digamma(pc) - jsp.gammaln(pc) + jsp.gammaln(qc)
                + qc * (jnp.log(pr) - jnp.log(qr)) + pc * (qr - pr) / pr)

    return apply(f, p.concentration, p.rate, q.concentration, q.rate)


@register_kl(Laplace, Laplace)
def _kl_laplace(p, q):
    def f(pl, ps, ql, qs):
        d = jnp.abs(pl - ql)
        return (jnp.log(qs / ps) + d / qs
                + ps / qs * jnp.exp(-d / ps) - 1)

    return apply(f, p.loc, p.scale, q.loc, q.scale)


@register_kl(Poisson, Poisson)
def _kl_poisson(p, q):
    return apply(
        lambda pr, qr: pr * (jnp.log(pr) - jnp.log(qr)) - pr + qr, p.rate, q.rate)


@register_kl(Binomial, Binomial)
def _kl_binomial(p, q):
    # p.total_count < q.total_count has a finite KL the closed form below
    # doesn't cover; fail loudly rather than return a wrong value (torch
    # parity). Only checkable on concrete counts.
    pn_v, qn_v = p.total_count._value, q.total_count._value
    if not isinstance(pn_v, jax.core.Tracer) and not isinstance(qn_v, jax.core.Tracer):
        if bool(jnp.any(pn_v < qn_v)):
            raise NotImplementedError(
                "KL(Binomial||Binomial) with p.total_count < q.total_count "
                "is finite but not implemented")

    def f(pn, qn, pp, qp):
        kl = pn * (pp * (jnp.log(pp) - jnp.log(qp))
                   + (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qp)))
        # pn > qn: p's support exceeds q's -> KL is +inf. pn < qn is finite
        # but uncomputed here: under tracing (where the eager guard above
        # can't fire) surface NaN, never a silently wrong finite/inf value.
        return jnp.where(pn == qn, kl, jnp.where(pn > qn, jnp.inf, jnp.nan))

    return apply(f, p.total_count, q.total_count, p.probs, q.probs)
