"""paddle.distribution parity (reference:
/root/reference/python/paddle/distribution/__init__.py — ~20 distributions,
transforms, TransformedDistribution, KL registry)."""
from .distribution import Distribution  # noqa: F401
from .distributions import (  # noqa: F401
    Bernoulli,
    Beta,
    Binomial,
    Categorical,
    Cauchy,
    Chi2,
    ContinuousBernoulli,
    Dirichlet,
    Exponential,
    ExponentialFamily,
    Gamma,
    Geometric,
    Gumbel,
    Independent,
    Laplace,
    LogNormal,
    Multinomial,
    MultivariateNormal,
    Normal,
    Poisson,
    StudentT,
    Uniform,
)
from .kl import kl_divergence, register_kl  # noqa: F401
from .transform import (  # noqa: F401
    AbsTransform,
    AffineTransform,
    ChainTransform,
    ExpTransform,
    IndependentTransform,
    PowerTransform,
    ReshapeTransform,
    SigmoidTransform,
    SoftmaxTransform,
    StackTransform,
    StickBreakingTransform,
    TanhTransform,
    Transform,
    TransformedDistribution,
)

__all__ = [
    "Distribution", "ExponentialFamily",
    "Bernoulli", "Beta", "Binomial", "Categorical", "Cauchy", "Chi2",
    "ContinuousBernoulli", "Dirichlet", "Exponential", "Gamma", "Geometric",
    "Gumbel", "Independent", "Laplace", "LogNormal", "Multinomial",
    "MultivariateNormal", "Normal", "Poisson", "StudentT", "Uniform",
    "kl_divergence", "register_kl",
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
    "TransformedDistribution",
]
