"""Probability transforms + TransformedDistribution (parity:
/root/reference/python/paddle/distribution/transform.py,
transformed_distribution.py). Pure jnp bijector algebra taped through
dispatch."""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from ..ops.dispatch import apply
from ..tensor.tensor import Tensor
from .distribution import Distribution, _shape, _t

__all__ = [
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
    "TransformedDistribution",
]


class Transform:
    _event_rank = 0  # rank consumed by the jacobian determinant

    def forward(self, x):
        return apply(self._forward, _t(x), op_name=f"{type(self).__name__}.fwd")

    def inverse(self, y):
        return apply(self._inverse, _t(y), op_name=f"{type(self).__name__}.inv")

    def forward_log_det_jacobian(self, x):
        return apply(self._fldj, _t(x), op_name=f"{type(self).__name__}.fldj")

    def inverse_log_det_jacobian(self, y):
        return apply(lambda v: -self._fldj(self._inverse(v)), _t(y))

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)

    # raw-jnp hooks
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _fldj(self, x):
        raise NotImplementedError

    def __call__(self, x):
        return self.forward(x)


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        return x


class AbsTransform(Transform):
    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y  # one branch of the two-valued preimage (paddle convention)

    def _fldj(self, x):
        raise NotImplementedError("AbsTransform is not bijective")


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)

    def _forward(self, x):
        return self.loc._value + self.scale._value * x

    def _inverse(self, y):
        return (y - self.loc._value) / self.scale._value

    def _fldj(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale._value)), jnp.shape(x))


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _t(power)

    def _forward(self, x):
        return jnp.power(x, self.power._value)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power._value)

    def _fldj(self, x):
        p = self.power._value
        return jnp.log(jnp.abs(p * jnp.power(x, p - 1)))


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _fldj(self, x):
        return jax.nn.log_sigmoid(x) + jax.nn.log_sigmoid(-x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _fldj(self, x):
        # log(1 - tanh(x)^2) = 2(log2 - x - softplus(-2x))
        return 2.0 * (jnp.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class SoftmaxTransform(Transform):
    _event_rank = 1

    def _forward(self, x):
        return jax.nn.softmax(x, -1)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        raise NotImplementedError("softmax is not bijective; no log-det")


class StickBreakingTransform(Transform):
    _event_rank = 1

    def _forward(self, x):
        # R^{K-1} -> K-simplex
        offset = jnp.cumsum(jnp.ones_like(x)[..., ::-1], -1)[..., ::-1]
        z = jax.nn.sigmoid(x - jnp.log(offset))
        zpad = jnp.concatenate([z, jnp.ones_like(z[..., :1])], -1)
        rem = jnp.concatenate([jnp.ones_like(z[..., :1]),
                               jnp.cumprod(1 - z, -1)], -1)
        return zpad * rem

    def _inverse(self, y):
        ycum = jnp.cumsum(y[..., :-1], -1)
        rem = 1 - jnp.concatenate([jnp.zeros_like(ycum[..., :1]), ycum[..., :-1]], -1)
        z = y[..., :-1] / rem
        offset = jnp.cumsum(jnp.ones_like(z)[..., ::-1], -1)[..., ::-1]
        return jnp.log(z) - jnp.log1p(-z) + jnp.log(offset)

    def _fldj(self, x):
        offset = jnp.cumsum(jnp.ones_like(x)[..., ::-1], -1)[..., ::-1]
        z = jax.nn.sigmoid(x - jnp.log(offset))
        rem = jnp.concatenate([jnp.ones_like(z[..., :1]),
                               jnp.cumprod(1 - z, -1)[..., :-1]], -1)
        return jnp.sum(jnp.log(z) + jnp.log1p(-z) + jnp.log(rem), -1)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = _shape(in_event_shape)
        self.out_event_shape = _shape(out_event_shape)
        self._event_rank = len(self.in_event_shape)

    def _forward(self, x):
        batch = jnp.shape(x)[: jnp.ndim(x) - len(self.in_event_shape)]
        return jnp.reshape(x, batch + self.out_event_shape)

    def _inverse(self, y):
        batch = jnp.shape(y)[: jnp.ndim(y) - len(self.out_event_shape)]
        return jnp.reshape(y, batch + self.in_event_shape)

    def _fldj(self, x):
        batch = jnp.shape(x)[: jnp.ndim(x) - len(self.in_event_shape)]
        return jnp.zeros(batch, jnp.result_type(x))

    def forward_shape(self, shape):
        n = len(self.in_event_shape)
        return tuple(shape[:-n] if n else shape) + self.out_event_shape

    def inverse_shape(self, shape):
        n = len(self.out_event_shape)
        return tuple(shape[:-n] if n else shape) + self.in_event_shape


class IndependentTransform(Transform):
    def __init__(self, base: Transform, reinterpreted_batch_rank: int):
        self.base = base
        self._n = int(reinterpreted_batch_rank)
        self._event_rank = base._event_rank + self._n

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _fldj(self, x):
        ld = self.base._fldj(x)
        return jnp.sum(ld, axis=tuple(range(jnp.ndim(ld) - self._n, jnp.ndim(ld))))


class ChainTransform(Transform):
    def __init__(self, transforms: Sequence[Transform]):
        self.transforms = list(transforms)
        self._event_rank = max((t._event_rank for t in self.transforms), default=0)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _fldj(self, x):
        total = None
        for t in self.transforms:
            ld = t._fldj(x)
            # reduce lower-rank jacobians to this chain's event rank
            extra = self._event_rank - t._event_rank
            if extra > 0:
                ld = jnp.sum(ld, axis=tuple(range(jnp.ndim(ld) - extra, jnp.ndim(ld))))
            total = ld if total is None else total + ld
            x = t._forward(x)
        return total

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return tuple(shape)

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return tuple(shape)


class StackTransform(Transform):
    def __init__(self, transforms: Sequence[Transform], axis: int = 0):
        self.transforms = list(transforms)
        self.axis = axis

    def _split(self, x):
        return [jnp.squeeze(s, self.axis)
                for s in jnp.split(x, len(self.transforms), self.axis)]

    def _forward(self, x):
        return jnp.stack([t._forward(p) for t, p in zip(self.transforms, self._split(x))],
                         self.axis)

    def _inverse(self, y):
        return jnp.stack([t._inverse(p) for t, p in zip(self.transforms, self._split(y))],
                         self.axis)

    def _fldj(self, x):
        return jnp.stack([t._fldj(p) for t, p in zip(self.transforms, self._split(x))],
                         self.axis)


def _collect_param_tensors(objs):
    """All Tensor attributes reachable from ``objs`` (Distributions and
    Transforms, recursively through nested bases / chain members)."""
    out, seen = [], set()

    def walk(o):
        if id(o) in seen:
            return
        seen.add(id(o))
        if isinstance(o, Tensor):
            if id(o) not in {id(p) for p in out}:
                out.append(o)
            return
        if isinstance(o, (list, tuple)):
            for item in o:
                walk(item)
            return
        if isinstance(o, (Distribution, Transform)):
            for v in vars(o).values():
                walk(v)

    for o in objs:
        walk(o)
    return out


class TransformedDistribution(Distribution):
    """parity: transformed_distribution.py — base dist pushed through a
    transform chain; log_prob via the change-of-variables formula."""

    def __init__(self, base: Distribution, transforms: Sequence[Transform]):
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.base = base
        self.transforms = list(transforms)
        chain = ChainTransform(self.transforms)
        shape = base.batch_shape + base.event_shape
        out_shape = chain.forward_shape(shape)
        # event rank of the result: the transform's event rank, never below
        # the base's (an elementwise transform of an Independent base keeps
        # the base's event dims) — torch/paddle semantics
        er = max(chain._event_rank, len(base.event_shape))
        super().__init__(batch_shape=out_shape[: len(out_shape) - er],
                         event_shape=out_shape[len(out_shape) - er:])

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def sample(self, shape=()):
        with __import__("paddle_tpu").no_grad():
            return self.rsample(shape)

    def log_prob(self, value):
        value = _t(value)
        chain = ChainTransform(self.transforms)
        # thread every parameter Tensor reachable from the base distribution
        # and the transforms (including nested Independent/Transformed bases
        # and chain members) through the outer apply so gradients reach them
        params = _collect_param_tensors([self.base, *self.transforms])

        def f(v, *pvals):
            saved = [p._value for p in params]
            for p, pv in zip(params, pvals):
                p._value = pv
            try:
                x = chain._inverse(v)
                ildj = -chain._fldj(x)
                base_lp = self.base.log_prob(Tensor(x))._value
            finally:
                for p, s in zip(params, saved):
                    p._value = s
            extra = chain._event_rank - len(self.base.event_shape)
            if extra > 0:
                # chain promoted batch dims to event dims: reduce base_lp
                base_lp = jnp.sum(
                    base_lp, axis=tuple(range(jnp.ndim(base_lp) - extra, jnp.ndim(base_lp))))
            elif extra < 0:
                # base has higher event rank (e.g. Independent) than the
                # elementwise chain: the per-element log-dets belong to one
                # event — reduce ildj over the base's extra event dims
                ildj = jnp.sum(
                    ildj, axis=tuple(range(jnp.ndim(ildj) + extra, jnp.ndim(ildj))))
            return base_lp + ildj

        return apply(f, value, *params, op_name="transformed_log_prob")
