"""Distribution base + shared helpers (parity:
/root/reference/python/paddle/distribution/distribution.py).

TPU-native: parameters are Tensors; all math runs through ops.dispatch.apply
so log_prob/entropy/rsample are differentiable w.r.t. parameters on the
eager tape and traceable under jit; sampling uses the framework's threefry
Generator (framework/random.py) rather than a mutable global RNG state.
"""
from __future__ import annotations

from typing import Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.random import default_generator
from ..ops.dispatch import apply
from ..tensor.tensor import Tensor

__all__ = ["Distribution"]


def _t(x) -> Tensor:
    if isinstance(x, Tensor):
        return x
    return Tensor(jnp.asarray(x, jnp.result_type(float) if not hasattr(x, "dtype") else None))


def _shape(shape) -> Tuple[int, ...]:
    if shape is None:
        return ()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = _shape(batch_shape)
        self._event_shape = _shape(event_shape)

    @property
    def batch_shape(self) -> Tuple[int, ...]:
        return self._batch_shape

    @property
    def event_shape(self) -> Tuple[int, ...]:
        return self._event_shape

    @property
    def mean(self) -> Tensor:
        raise NotImplementedError

    @property
    def variance(self) -> Tensor:
        raise NotImplementedError

    @property
    def stddev(self) -> Tensor:
        from ..tensor.math import sqrt

        return sqrt(self.variance)

    def sample(self, shape=()):
        """Draw (non-reparameterized) samples; gradients do not flow."""
        with __import__("paddle_tpu").no_grad():
            return self.rsample(shape)

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value) -> Tensor:
        raise NotImplementedError

    def prob(self, value) -> Tensor:
        from ..tensor.math import exp

        return exp(self.log_prob(value))

    def entropy(self) -> Tensor:
        raise NotImplementedError

    def kl_divergence(self, other) -> Tensor:
        from .kl import kl_divergence

        return kl_divergence(self, other)

    # ------------------------------------------------------------ helpers
    @staticmethod
    def _key():
        return default_generator().next_key()

    @staticmethod
    def _apply(fn, *tensors, op_name=""):
        return apply(fn, *tensors, op_name=op_name)

    def _extend_shape(self, sample_shape) -> Tuple[int, ...]:
        return _shape(sample_shape) + self._batch_shape + self._event_shape

    def __repr__(self):
        return f"{type(self).__name__}(batch_shape={self._batch_shape}, event_shape={self._event_shape})"
