"""Regularizers (parity: python/paddle/regularizer.py)."""
from __future__ import annotations

__all__ = ["L1Decay", "L2Decay"]


class L1Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)


class L2Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)
