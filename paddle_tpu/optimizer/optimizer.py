"""Optimizer base (parity: python/paddle/optimizer/optimizer.py:125).

Keeps the reference's contracts: parameter groups, per-state accumulators,
grad clip plug-in, weight decay, LRScheduler integration, state_dict with
master weights (multi_precision). TPU-native: the update math is pure jnp on
the raw arrays under no_grad; the jit'd training-step path fuses these updates
into the compiled step.
"""
from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional, Union

import numpy as np

import jax.numpy as jnp

from ..autograd import tape
from ..tensor.tensor import Tensor
from .lr import LRScheduler

__all__ = ["Optimizer"]


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None, grad_clip=None, multi_precision=False, name=None):
        if parameters is None:
            raise ValueError("parameters is required in eager mode (pass model.parameters())")
        params = list(parameters)
        if params and isinstance(params[0], dict):
            self._param_groups = params
            self._parameter_list = [p for g in params for p in g["params"]]
        else:
            self._param_groups = [{"params": params}]
            self._parameter_list = params
        self._learning_rate = learning_rate
        self._weight_decay = self._parse_decay(weight_decay)
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        # accumulators: name -> {id(param): jnp array}
        self._accumulators: Dict[str, Dict[int, jnp.ndarray]] = defaultdict(dict)
        self._master_weights: Dict[int, jnp.ndarray] = {}
        self._step_count = 0

    @staticmethod
    def _parse_decay(weight_decay):
        if weight_decay is None:
            return 0.0
        from ..regularizer import L2Decay

        if isinstance(weight_decay, L2Decay):
            return float(weight_decay.coeff)
        if isinstance(weight_decay, float) or isinstance(weight_decay, int):
            return float(weight_decay)
        return float(weight_decay)

    # ----------------------------------------------------------- lr
    def get_lr(self) -> float:
        lr = self._learning_rate
        return lr() if isinstance(lr, LRScheduler) else float(lr)

    def set_lr(self, value: float):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    def _group_lr(self, group) -> float:
        base = self.get_lr()
        return base * group.get("learning_rate", 1.0)

    # ----------------------------------------------------------- accumulators
    def _acc(self, name: str, p: Tensor, init=None):
        d = self._accumulators[name]
        if id(p) not in d:
            d[id(p)] = jnp.zeros_like(self._master(p)) if init is None else init
        return d[id(p)]

    def _set_acc(self, name: str, p: Tensor, value):
        self._accumulators[name][id(p)] = value

    def _master(self, p: Tensor):
        """fp32 master weight when multi_precision and p is low precision."""
        if self._multi_precision and p._value.dtype in (jnp.float16, jnp.bfloat16):
            if id(p) not in self._master_weights:
                self._master_weights[id(p)] = p._value.astype(jnp.float32)
            return self._master_weights[id(p)]
        return p._value

    def _write_back(self, p: Tensor, new_master):
        if id(p) in self._master_weights:
            self._master_weights[id(p)] = new_master
            p._value = new_master.astype(p._value.dtype)
        else:
            p._value = new_master

    # ----------------------------------------------------------- step
    @tape.no_grad()
    def step(self):
        shard_grad = getattr(self, "_shard_grad", None)
        if shard_grad is not None:  # ZeRO stage >= 2: grads live sharded
            for p in self._parameter_list:
                if p.grad is not None:
                    p.grad._value = shard_grad(p, p.grad._value)
        for group in self._param_groups:
            params_grads = [(p, p.grad) for p in group["params"] if p.grad is not None and p.trainable]
            if self._grad_clip is not None:
                params_grads = self._grad_clip(params_grads)
            lr = self._group_lr(group)
            wd = group.get("weight_decay", self._weight_decay)
            wd = self._parse_decay(wd) if not isinstance(wd, float) else wd
            for p, g in params_grads:
                if g is None:
                    continue
                gv = g._value.astype(jnp.float32) if self._multi_precision else g._value
                self._update_param(p, gv, lr, wd)
        self._step_count += 1

    def _update_param(self, p: Tensor, grad, lr: float, weight_decay: float):
        raise NotImplementedError

    # ------------------------------------------------- state pre-creation
    def _create_accumulators(self, p: Tensor):
        """Eagerly create this optimizer's accumulators for ``p`` (paddle
        parity: Optimizer._create_accumulators). Gives jit.TrainStep a stable
        state pytree before the first traced step."""

    def _ensure_state(self):
        """Materialize accumulators + master weights for every parameter so
        the optimizer state structure is fixed (required before tracing the
        update into a compiled step)."""
        for group in self._param_groups:
            for p in group["params"]:
                self._master(p)
                self._create_accumulators(p)

    def clear_grad(self, set_to_zero: bool = False):
        for p in self._parameter_list:
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        import jax

        if isinstance(loss._value, jax.ShapeDtypeStruct):
            # static-graph capture: mark the program for Executor training
            from ..static import default_main_program

            prog = default_main_program()
            prog._loss = loss
            prog._optimizer = self
            return None, None
        loss.backward()
        self.step()
        return None, None

    # ----------------------------------------------------------- state
    def state_dict(self) -> dict:
        out = {}
        name_of = {id(p): (p.name or f"param_{i}") for i, p in enumerate(self._parameter_list)}
        for acc_name, d in self._accumulators.items():
            for pid, val in d.items():
                out[f"{name_of.get(pid, pid)}__{acc_name}"] = Tensor(val)
        for pid, mw in self._master_weights.items():
            out[f"{name_of.get(pid, pid)}__master_weight"] = Tensor(mw)
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        out["@step"] = self._step_count
        return out

    def set_state_dict(self, state: dict):
        name_of = {(p.name or f"param_{i}"): p for i, p in enumerate(self._parameter_list)}
        for key, val in state.items():
            if key == "LR_Scheduler":
                if isinstance(self._learning_rate, LRScheduler):
                    self._learning_rate.set_state_dict(val)
                continue
            if key == "@step":
                self._step_count = int(val)
                continue
            if "__" not in key:
                continue
            pname, acc_name = key.rsplit("__", 1)
            p = name_of.get(pname)
            if p is None:
                continue
            arr = jnp.asarray(val.numpy() if isinstance(val, Tensor) else np.asarray(val))
            if acc_name == "master_weight":
                self._master_weights[id(p)] = arr
            else:
                self._accumulators[acc_name][id(p)] = arr
