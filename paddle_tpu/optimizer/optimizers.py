"""Concrete optimizers (parity: python/paddle/optimizer/{sgd,momentum,adam,adamw,...}.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..tensor.tensor import Tensor
from .optimizer import Optimizer

__all__ = ["SGD", "Momentum", "Adam", "AdamW", "Adamax", "Adagrad", "Adadelta", "RMSProp", "Lamb", "Lars", "LBFGS", "ASGD", "Rprop", "NAdam", "RAdam"]


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)

    def _update_param(self, p, grad, lr, weight_decay):
        w = self._master(p)
        if weight_decay:
            grad = grad + weight_decay * w
        self._write_back(p, w - lr * grad.astype(w.dtype))


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None, use_nesterov=False,
                 weight_decay=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _create_accumulators(self, p):
        self._acc("velocity", p)

    def _update_param(self, p, grad, lr, weight_decay):
        w = self._master(p)
        if weight_decay:
            grad = grad + weight_decay * w
        v = self._acc("velocity", p)
        v = self._momentum * v + grad
        self._set_acc("velocity", p, v)
        if self._nesterov:
            update = grad + self._momentum * v
        else:
            update = v
        self._write_back(p, w - lr * update.astype(w.dtype))


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, parameters=None,
                 weight_decay=None, grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_multi_tensor=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, p):
        self._acc("moment1", p)
        self._acc("moment2", p)
        self._acc("beta_pow", p, init=jnp.zeros((), jnp.float32))

    def _update_param(self, p, grad, lr, weight_decay):
        w = self._master(p)
        if weight_decay:  # paddle Adam applies decay as L2 regularization on grads
            grad = grad + weight_decay * w
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        t = self._acc("beta_pow", p, init=jnp.zeros((), jnp.float32)) + 1
        self._set_acc("beta_pow", p, t)
        m = self._beta1 * m + (1 - self._beta1) * grad
        v = self._beta2 * v + (1 - self._beta2) * grad * grad
        self._set_acc("moment1", p, m)
        self._set_acc("moment2", p, v)
        mhat = m / (1 - self._beta1**t)
        vhat = v / (1 - self._beta2**t)
        self._write_back(p, w - (lr * mhat / (jnp.sqrt(vhat) + self._epsilon)).astype(w.dtype))


class AdamW(Adam):
    """Decoupled weight decay (parity: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, parameters=None,
                 weight_decay=0.01, lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision, name=name)
        self._wd = float(weight_decay) if not callable(weight_decay) else weight_decay
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _update_param(self, p, grad, lr, weight_decay):
        if self._lr_ratio is not None:
            lr = lr * self._lr_ratio(p)
        do_decay = True
        if self._apply_decay_param_fun is not None:
            do_decay = self._apply_decay_param_fun(p.name)
        wd = self._wd() if callable(self._wd) else self._wd
        if self._try_fused_update(p, grad, lr, wd if do_decay else 0.0):
            return
        w = self._master(p)
        if do_decay and wd:
            w = w * (1 - lr * wd)
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        t = self._acc("beta_pow", p, init=jnp.zeros((), jnp.float32)) + 1
        self._set_acc("beta_pow", p, t)
        m = self._beta1 * m + (1 - self._beta1) * grad
        v = self._beta2 * v + (1 - self._beta2) * grad * grad
        self._set_acc("moment1", p, m)
        self._set_acc("moment2", p, v)
        mhat = m / (1 - self._beta1**t)
        vhat = v / (1 - self._beta2**t)
        self._write_back(p, w - (lr * mhat / (jnp.sqrt(vhat) + self._epsilon)).astype(w.dtype))

    def _try_fused_update(self, p, grad, lr, wd) -> bool:
        """One-pass Pallas AdamW (ops/pallas/fused_adamw.py) for large
        multi-precision params on the accelerator: the jnp expression chain
        runs at ~160 GB/s effective in isolation (XLA materializes the
        moment intermediates), the fused pass at streaming bandwidth.
        OPT-IN (PADDLE_TPU_FUSED_ADAMW=1): measured INSIDE the full compiled
        train step the custom-call boundary costs more than the fusion wins
        (flagship 0.4163 vs 0.4408 MFU — XLA fuses the optimizer chain with
        its surroundings better than an isolated microbench suggests; see
        PROFILE_r04.md). Exact same math — golden-tested vs the jnp path."""
        import os

        import jax as _jax

        if os.environ.get("PADDLE_TPU_FUSED_ADAMW", "0") != "1":
            return False
        if id(p) not in self._master_weights or not isinstance(wd, (int, float)):
            return False
        if _jax.default_backend() == "cpu":
            return False
        from ..ops.pallas.fused_adamw import fused_adamw, fused_adamw_supported

        if not fused_adamw_supported(p.size):
            return False
        w = self._master(p)
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        t = self._acc("beta_pow", p, init=jnp.zeros((), jnp.float32)) + 1
        self._set_acc("beta_pow", p, t)
        p_new, w_new, m_new, v_new = fused_adamw(
            p._value, w, m, v, grad, lr,
            self._beta1 ** t, self._beta2 ** t,
            b1=self._beta1, b2=self._beta2, eps=self._epsilon, wd=float(wd))
        self._set_acc("moment1", p, m_new)
        self._set_acc("moment2", p, v_new)
        self._master_weights[id(p)] = w_new
        p._value = p_new
        return True


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, False, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, p):
        self._acc("moment", p)
        self._acc("inf_norm", p)
        self._acc("beta_pow", p, init=jnp.zeros((), jnp.float32))

    def _update_param(self, p, grad, lr, weight_decay):
        w = self._master(p)
        if weight_decay:
            grad = grad + weight_decay * w
        m = self._acc("moment", p)
        u = self._acc("inf_norm", p)
        t = self._acc("beta_pow", p, init=jnp.zeros((), jnp.float32)) + 1
        self._set_acc("beta_pow", p, t)
        m = self._beta1 * m + (1 - self._beta1) * grad
        u = jnp.maximum(self._beta2 * u, jnp.abs(grad))
        self._set_acc("moment", p, m)
        self._set_acc("inf_norm", p, u)
        self._write_back(p, w - (lr / (1 - self._beta1**t) * m / (u + self._epsilon)).astype(w.dtype))


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None, weight_decay=None,
                 grad_clip=None, initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, False, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _create_accumulators(self, p):
        self._acc("moment", p, init=jnp.full_like(self._master(p), self._init_acc))

    def _update_param(self, p, grad, lr, weight_decay):
        w = self._master(p)
        if weight_decay:
            grad = grad + weight_decay * w
        acc = self._acc("moment", p, init=jnp.full_like(w, self._init_acc))
        acc = acc + grad * grad
        self._set_acc("moment", p, acc)
        self._write_back(p, w - (lr * grad / (jnp.sqrt(acc) + self._epsilon)).astype(w.dtype))


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, False, name)
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, p):
        self._acc("avg_squared_grad", p)
        self._acc("avg_squared_update", p)

    def _update_param(self, p, grad, lr, weight_decay):
        w = self._master(p)
        if weight_decay:
            grad = grad + weight_decay * w
        avg_sq = self._acc("avg_squared_grad", p)
        avg_up = self._acc("avg_squared_update", p)
        avg_sq = self._rho * avg_sq + (1 - self._rho) * grad * grad
        update = jnp.sqrt(avg_up + self._epsilon) / jnp.sqrt(avg_sq + self._epsilon) * grad
        avg_up = self._rho * avg_up + (1 - self._rho) * update * update
        self._set_acc("avg_squared_grad", p, avg_sq)
        self._set_acc("avg_squared_update", p, avg_up)
        self._write_back(p, w - (lr * update).astype(w.dtype))


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0, centered=False,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, False, name)
        self._rho, self._epsilon, self._momentum, self._centered = rho, epsilon, momentum, centered

    def _create_accumulators(self, p):
        self._acc("mean_square", p)
        self._acc("momentum", p)
        if self._centered:
            self._acc("mean_grad", p)

    def _update_param(self, p, grad, lr, weight_decay):
        w = self._master(p)
        if weight_decay:
            grad = grad + weight_decay * w
        ms = self._acc("mean_square", p)
        ms = self._rho * ms + (1 - self._rho) * grad * grad
        self._set_acc("mean_square", p, ms)
        if self._centered:
            mg = self._acc("mean_grad", p)
            mg = self._rho * mg + (1 - self._rho) * grad
            self._set_acc("mean_grad", p, mg)
            denom = jnp.sqrt(ms - mg * mg + self._epsilon)
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._acc("momentum", p)
        mom = self._momentum * mom + lr * grad / denom
        self._set_acc("momentum", p, mom)
        self._write_back(p, w - mom.astype(w.dtype))


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, parameters=None, grad_clip=None, exclude_from_weight_decay_fn=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, multi_precision, name)
        self._wd = lamb_weight_decay
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _create_accumulators(self, p):
        self._acc("moment1", p)
        self._acc("moment2", p)
        self._acc("beta_pow", p, init=jnp.zeros((), jnp.float32))

    def _update_param(self, p, grad, lr, weight_decay):
        w = self._master(p)
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        t = self._acc("beta_pow", p, init=jnp.zeros((), jnp.float32)) + 1
        self._set_acc("beta_pow", p, t)
        m = self._beta1 * m + (1 - self._beta1) * grad
        v = self._beta2 * v + (1 - self._beta2) * grad * grad
        self._set_acc("moment1", p, m)
        self._set_acc("moment2", p, v)
        mhat = m / (1 - self._beta1**t)
        vhat = v / (1 - self._beta2**t)
        r = mhat / (jnp.sqrt(vhat) + self._epsilon)
        wd = self._wd
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        update = r + wd * w
        w_norm = jnp.linalg.norm(w.astype(jnp.float32))
        u_norm = jnp.linalg.norm(update.astype(jnp.float32))
        trust = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
        self._write_back(p, w - (lr * trust * update).astype(w.dtype))


class Lars(Momentum):
    """LARS (parity: incubate lars_momentum op + fleet LarsOptimizer)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001, lars_weight_decay=0.0005,
                 parameters=None, grad_clip=None, exclude_from_weight_decay=None, epsilon=1e-9,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, momentum, parameters, False, None, grad_clip, multi_precision, name)
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._lars_eps = epsilon

    def _update_param(self, p, grad, lr, weight_decay):
        w = self._master(p)
        w_norm = jnp.linalg.norm(w.astype(jnp.float32))
        g_norm = jnp.linalg.norm(grad.astype(jnp.float32))
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self._lars_coeff * w_norm / (g_norm + self._lars_wd * w_norm + self._lars_eps),
            1.0,
        )
        eff_lr = lr * local_lr
        grad = grad + self._lars_wd * w
        v = self._acc("velocity", p)
        v = self._momentum * v + eff_lr * grad
        self._set_acc("velocity", p, v)
        self._write_back(p, w - v.astype(w.dtype))


class LBFGS(Optimizer):
    """Limited-memory BFGS with strong-Wolfe line search (parity:
    python/paddle/optimizer/lbfgs.py). Closure-based: ``step(closure)``
    re-evaluates the loss during the line search; history lives as flat
    vectors (the standard two-loop recursion)."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, False, name)
        self.max_iter = max_iter
        self.max_eval = max_eval if max_eval is not None else max_iter * 5 // 4
        self.tolerance_grad = tolerance_grad
        self.tolerance_change = tolerance_change
        self.history_size = history_size
        self.line_search_fn = line_search_fn
        self._s_hist, self._y_hist, self._rho = [], [], []
        self._prev_flat_grad = None

    def _flat(self, grads=False):
        parts = []
        for p in self._parameter_list:
            v = (p.grad._value if p.grad is not None else jnp.zeros_like(p._value)) if grads else p._value
            parts.append(jnp.ravel(v).astype(jnp.float32))
        return jnp.concatenate(parts)

    def _assign(self, flat):
        off = 0
        for p in self._parameter_list:
            n = int(jnp.size(p._value))
            p._value = jnp.reshape(flat[off:off + n], p._value.shape).astype(p._value.dtype)
            off += n

    def _eval(self, closure, x):
        self._assign(x)
        self.clear_grad()
        loss = closure()
        return float(loss._value), self._flat(grads=True)

    def _direction(self, g):
        q = g
        alphas = []
        for s, y, rho in zip(reversed(self._s_hist), reversed(self._y_hist), reversed(self._rho)):
            a = rho * jnp.dot(s, q)
            alphas.append(a)
            q = q - a * y
        if self._y_hist:
            y, s = self._y_hist[-1], self._s_hist[-1]
            q = q * (jnp.dot(s, y) / jnp.dot(y, y))
        for (s, y, rho), a in zip(zip(self._s_hist, self._y_hist, self._rho), reversed(alphas)):
            b = rho * jnp.dot(y, q)
            q = q + (a - b) * s
        return -q

    def step(self, closure=None):
        if closure is None:
            raise RuntimeError("LBFGS.step requires a closure that recomputes the loss")
        from ..autograd import tape

        self.clear_grad()  # stale grads from the previous step must not accumulate
        with tape.enable_grad():
            loss0 = closure()
        loss = float(loss0._value)
        x = self._flat()
        g = self._flat(grads=True)
        n_eval = 1
        lr = self._base_lr()
        for _ in range(self.max_iter):
            if float(jnp.max(jnp.abs(g))) <= self.tolerance_grad:
                break
            d = self._direction(g)
            gtd = float(jnp.dot(g, d))
            if gtd > -1e-15:
                self._s_hist, self._y_hist, self._rho = [], [], []
                d = -g
                gtd = float(jnp.dot(g, d))
            # backtracking Armijo line search (strong_wolfe simplified)
            t = lr
            ok = False
            for _ls in range(20):
                new_loss, new_g = self._eval(closure, x + t * d)
                n_eval += 1
                if new_loss <= loss + 1e-4 * t * gtd:
                    ok = True
                    break
                t *= 0.5
                if n_eval >= self.max_eval:
                    break
            if not ok:
                self._assign(x)
                break
            s = t * d
            y = new_g - g
            sy = float(jnp.dot(s, y))
            if sy > 1e-10:
                self._s_hist.append(s)
                self._y_hist.append(y)
                self._rho.append(1.0 / sy)
                if len(self._s_hist) > self.history_size:
                    self._s_hist.pop(0)
                    self._y_hist.pop(0)
                    self._rho.pop(0)
            x = x + s
            if abs(new_loss - loss) < self.tolerance_change:
                loss, g = new_loss, new_g
                break
            loss, g = new_loss, new_g
            if n_eval >= self.max_eval:
                break
        self._assign(x)
        self._step_count += 1
        from ..tensor.tensor import Tensor

        return Tensor(jnp.float32(loss))

    def _base_lr(self):
        lr = self._learning_rate
        from .lr import LRScheduler

        return lr() if isinstance(lr, LRScheduler) else (lr.get_lr() if hasattr(lr, "get_lr") else float(lr))


class ASGD(Optimizer):
    """Averaged SGD (parity: optimizer/asgd.py) — keeps a running average of
    the last n gradients."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._batch_num = max(int(batch_num), 1)

    def _create_accumulators(self, p):
        self._acc("d", p)  # running gradient sum

    def _update_param(self, p, grad, lr, weight_decay):
        w = self._master(p)
        if weight_decay:
            grad = grad + weight_decay * w
        d = self._acc("d", p)
        n = self._batch_num
        d = d + (grad - d) / n
        self._set_acc("d", p, d)
        self._write_back(p, w - lr * d.astype(w.dtype))


class Rprop(Optimizer):
    """Resilient backprop (parity: optimizer/rprop.py) — sign-based step-size
    adaptation; full-batch semantics."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, multi_precision, name)
        self._lr_min, self._lr_max = learning_rate_range
        self._eta_neg, self._eta_pos = etas

    def _init_step(self, p):
        lr0 = self._learning_rate if not callable(self._learning_rate) else 0.001
        return jnp.full_like(self._master(p), float(lr0))

    def _create_accumulators(self, p):
        self._acc("prev_grad", p)
        self._acc("step_size", p, init=self._init_step(p))

    def _update_param(self, p, grad, lr, weight_decay):
        w = self._master(p)
        prev = self._acc("prev_grad", p)
        step = self._acc("step_size", p, init=self._init_step(p))
        sign = jnp.sign(grad * prev)
        step = jnp.where(sign > 0, jnp.minimum(step * self._eta_pos, self._lr_max),
                         jnp.where(sign < 0, jnp.maximum(step * self._eta_neg, self._lr_min),
                                   step))
        grad_eff = jnp.where(sign < 0, 0.0, grad)
        self._set_acc("prev_grad", p, grad_eff)
        self._set_acc("step_size", p, step)
        self._write_back(p, w - jnp.sign(grad_eff) * step)


class NAdam(Adam):
    """Nesterov Adam (parity: optimizer/nadam.py)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 momentum_decay=0.004, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, False, multi_precision, False, name)
        self._momentum_decay = momentum_decay

    def _create_accumulators(self, p):
        super()._create_accumulators(p)
        self._acc("mu_prod", p, init=jnp.ones((), jnp.float32))

    def _update_param(self, p, grad, lr, weight_decay):
        w = self._master(p)
        if weight_decay:
            grad = grad + weight_decay * w
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        # traced step + running mu-product: O(1) per step and correct under
        # jit.TrainStep (a Python step count would freeze at trace time)
        t = self._acc("beta_pow", p, init=jnp.zeros((), jnp.float32)) + 1
        self._set_acc("beta_pow", p, t)
        b1, b2 = self._beta1, self._beta2
        psi = self._momentum_decay
        mu_t = b1 * (1 - 0.5 * 0.96 ** (t * psi))
        mu_t1 = b1 * (1 - 0.5 * 0.96 ** ((t + 1) * psi))
        prod = self._acc("mu_prod", p, init=jnp.ones((), jnp.float32)) * mu_t
        self._set_acc("mu_prod", p, prod)
        m = b1 * m + (1 - b1) * grad
        v = b2 * v + (1 - b2) * grad * grad
        self._set_acc("moment1", p, m)
        self._set_acc("moment2", p, v)
        m_hat = mu_t1 * m / (1 - prod * mu_t1) + (1 - mu_t) * grad / (1 - prod)
        v_hat = v / (1 - b2 ** t)
        self._write_back(p, w - lr * m_hat / (jnp.sqrt(v_hat) + self._epsilon))


class RAdam(Adam):
    """Rectified Adam (parity: optimizer/radam.py)."""

    def _update_param(self, p, grad, lr, weight_decay):
        w = self._master(p)
        if weight_decay:
            grad = grad + weight_decay * w
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        # traced step count: the rectification branch must be a jnp.where so
        # the compiled TrainStep crosses the rho threshold at runtime
        t = self._acc("beta_pow", p, init=jnp.zeros((), jnp.float32)) + 1
        self._set_acc("beta_pow", p, t)
        b1, b2 = self._beta1, self._beta2
        m = b1 * m + (1 - b1) * grad
        v = b2 * v + (1 - b2) * grad * grad
        self._set_acc("moment1", p, m)
        self._set_acc("moment2", p, v)
        m_hat = m / (1 - b1 ** t)
        rho_inf = 2.0 / (1 - b2) - 1
        rho_t = rho_inf - 2 * t * b2 ** t / (1 - b2 ** t)
        v_hat = jnp.sqrt(v / (1 - b2 ** t))
        safe_rho = jnp.maximum(rho_t, 4.0 + 1e-3)
        r = jnp.sqrt((safe_rho - 4) * (safe_rho - 2) * rho_inf
                     / ((rho_inf - 4) * (rho_inf - 2) * safe_rho))
        rect = lr * r * m_hat / (v_hat + self._epsilon)
        plain = lr * m_hat
        self._write_back(p, w - jnp.where(rho_t > 5.0, rect, plain))
