"""paddle_tpu.optimizer (parity: python/paddle/optimizer)."""
from . import lr  # noqa: F401
from .optimizer import Optimizer  # noqa: F401
from .optimizers import (  # noqa: F401
    ASGD,
    LBFGS,
    NAdam,
    RAdam,
    Rprop,
    SGD,
    Adadelta,
    Adagrad,
    Adam,
    Adamax,
    AdamW,
    Lamb,
    Lars,
    Momentum,
    RMSProp,
)
