"""Profiler (parity: python/paddle/profiler — Profiler ctx mgr with
CLOSED→READY→RECORD scheduler profiler.py:79,346, chrome-trace export,
summary tables profiler_statistic.py, step timer/ips timer.py).

TPU-native: device tracing is jax.profiler (XPlane → TensorBoard/Perfetto,
replacing the reference's CUPTI tracer); host spans use
jax.profiler.TraceAnnotation (the RecordEvent analog); the step-timer /
throughput surface is reimplemented natively.
"""
from __future__ import annotations

import contextlib
import json
import os
import time
from collections import defaultdict
from enum import Enum
from typing import Callable, Iterable, Optional

import jax

__all__ = [
    "Profiler", "ProfilerState", "ProfilerTarget", "make_scheduler", "export_chrome_tracing",
    "RecordEvent", "benchmark", "SummaryView",
]


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    CUSTOM_DEVICE = 2
    TPU = 3


def make_scheduler(closed: int, ready: int, record: int, repeat: int = 0, skip_first: int = 0):
    """parity: profiler.make_scheduler — step-indexed state machine."""
    cycle = closed + ready + record

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= cycle * repeat:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        path = os.path.join(dir_name, f"{worker_name or 'worker'}_{int(time.time())}.json")
        prof._export_host_events(path)

    return handler


class RecordEvent:
    """Host span (parity: paddle.profiler.RecordEvent / C++ RecordEvent)."""

    _active_sink = None

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._jax_ann = None

    def begin(self):
        self._t0 = time.perf_counter()
        self._jax_ann = jax.profiler.TraceAnnotation(self.name)
        self._jax_ann.__enter__()

    def end(self):
        if self._jax_ann is not None:
            self._jax_ann.__exit__(None, None, None)
        dt = time.perf_counter() - self._t0
        sink = RecordEvent._active_sink
        if sink is not None:
            sink.append((self.name, self._t0, dt))

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


class SummaryView(Enum):
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6


class Profiler:
    def __init__(self, *, targets: Optional[Iterable] = None, scheduler=None,
                 on_trace_ready: Optional[Callable] = None, timer_only: bool = False,
                 record_shapes: bool = False, profile_memory: bool = False,
                 with_flops: bool = False, emit_nvtx: bool = False):
        self._scheduler = scheduler if callable(scheduler) else (
            make_scheduler(*scheduler) if isinstance(scheduler, (tuple, list)) else None
        )
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._step = 0
        self._state = ProfilerState.CLOSED
        self._host_events = []
        self._jax_active = False
        self._logdir = os.environ.get("PADDLE_TPU_PROFILE_DIR", "/tmp/paddle_tpu_profile")
        self._step_times = []
        self._last_step_t = None

    # ---- lifecycle ----
    def start(self):
        RecordEvent._active_sink = self._host_events
        self._last_step_t = time.perf_counter()
        self._transition(self._scheduler(self._step) if self._scheduler else ProfilerState.RECORD)

    def stop(self):
        if self._jax_active:
            jax.profiler.stop_trace()
            self._jax_active = False
        RecordEvent._active_sink = None
        if self._on_trace_ready:
            self._on_trace_ready(self)

    def _transition(self, new_state: ProfilerState):
        if self._timer_only:
            self._state = new_state
            return
        if new_state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN) and not self._jax_active:
            os.makedirs(self._logdir, exist_ok=True)
            jax.profiler.start_trace(self._logdir)
            self._jax_active = True
        if new_state == ProfilerState.CLOSED and self._jax_active:
            jax.profiler.stop_trace()
            self._jax_active = False
        self._state = new_state

    def step(self, num_samples: Optional[int] = None):
        now = time.perf_counter()
        if self._last_step_t is not None:
            self._step_times.append((now - self._last_step_t, num_samples))
        self._last_step_t = now
        self._step += 1
        if self._scheduler:
            self._transition(self._scheduler(self._step))

    def step_info(self, unit: str = "samples") -> str:
        if not self._step_times:
            return "no steps recorded"
        dts = [d for d, _ in self._step_times[-10:]]
        avg = sum(dts) / len(dts)
        info = f"avg step {avg*1e3:.2f} ms"
        samples = [n for _, n in self._step_times[-10:] if n]
        if samples:
            ips = sum(samples) / sum(dts)
            info += f", ips {ips:.2f} {unit}/s"
        return info

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # ---- reporting ----
    def summary(self, sorted_by=None, op_detail=True, thread_sep=False, time_unit="ms", views=None):
        agg = defaultdict(lambda: [0, 0.0])
        for name, _, dt in self._host_events:
            agg[name][0] += 1
            agg[name][1] += dt
        lines = ["-" * 64, f"{'Event':<36}{'Calls':>8}{'Total(ms)':>12}", "-" * 64]
        for name, (calls, total) in sorted(agg.items(), key=lambda kv: -kv[1][1]):
            lines.append(f"{name:<36}{calls:>8}{total*1e3:>12.3f}")
        if self._step_times:
            lines.append("-" * 64)
            lines.append(f"steps: {len(self._step_times)}  {self.step_info()}")
        out = "\n".join(lines)
        print(out)
        return out

    def _export_host_events(self, path: str):
        events = [
            {"name": name, "ph": "X", "pid": 0, "tid": 0,
             "ts": t0 * 1e6, "dur": dt * 1e6}
            for name, t0, dt in self._host_events
        ]
        with open(path, "w") as f:
            json.dump({"traceEvents": events}, f)

    def export(self, path: str, format: str = "json"):  # noqa: A002
        self._export_host_events(path)


class benchmark:
    """parity: paddle.profiler.benchmark timer (timer.py) — begin/step/end."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._times = []
        self._t = None

    def begin(self):
        self._t = time.perf_counter()

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._t is not None:
            self._times.append((now - self._t, num_samples))
        self._t = now

    def end(self):
        self._t = None

    def report(self):
        if not self._times:
            return {}
        dts = [d for d, _ in self._times]
        rep = {"avg_step_s": sum(dts) / len(dts), "steps": len(dts)}
        samples = [n for _, n in self._times if n]
        if samples:
            rep["ips"] = sum(samples) / sum(dts)
        return rep


class SortedKeys:
    """Sort keys for summary tables (parity: profiler.SortedKeys)."""

    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


def export_protobuf(path):
    raise NotImplementedError(
        "protobuf trace export: use Profiler(timer_only=False) chrome-trace "
        "export (perfetto-compatible), the XLA-native trace format")


def load_profiler_result(filename):
    import json

    with open(filename) as f:
        return json.load(f)
