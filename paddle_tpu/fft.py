"""Discrete Fourier transforms (parity: /root/reference/python/paddle/fft.py
fft/ifft/rfft/irfft/hfft/ihfft + n-d/2-d variants + helpers).

TPU-native: every transform lowers to the XLA FFT HLO through ``jnp.fft`` and
is routed through ``ops.dispatch.apply`` so forward and gradient both run on
the tape (the reference binds cuFFT/onemkl through fft_c2c/r2c/c2r kernels —
here XLA owns the kernel choice).
"""
from __future__ import annotations

import jax.numpy as jnp

from .ops.dispatch import apply
from .tensor.tensor import Tensor

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
    "fftn", "ifftn", "rfftn", "irfftn", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]

_NORMS = ("backward", "forward", "ortho")


def _t(x) -> Tensor:
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _norm(norm):
    if norm is None:
        return "backward"
    if norm not in _NORMS:
        raise ValueError(
            f"Unexpected norm: {norm!r}. Norm should be forward, backward or ortho")
    return norm


def _op1(jfn, x, n, axis, norm, name):
    x = _t(x)
    norm = _norm(norm)
    return apply(lambda v: jfn(v, n=n, axis=axis, norm=norm), x, op_name=name)


def _opn(jfn, x, s, axes, norm, name):
    x = _t(x)
    norm = _norm(norm)
    return apply(lambda v: jfn(v, s=s, axes=axes, norm=norm), x, op_name=name)


# ------------------------------------------------------------------ 1-d
def fft(x, n=None, axis=-1, norm="backward", name=None):
    return _op1(jnp.fft.fft, x, n, axis, norm, "fft")


def ifft(x, n=None, axis=-1, norm="backward", name=None):
    return _op1(jnp.fft.ifft, x, n, axis, norm, "ifft")


def rfft(x, n=None, axis=-1, norm="backward", name=None):
    return _op1(jnp.fft.rfft, x, n, axis, norm, "rfft")


def irfft(x, n=None, axis=-1, norm="backward", name=None):
    return _op1(jnp.fft.irfft, x, n, axis, norm, "irfft")


def hfft(x, n=None, axis=-1, norm="backward", name=None):
    return _op1(jnp.fft.hfft, x, n, axis, norm, "hfft")


def ihfft(x, n=None, axis=-1, norm="backward", name=None):
    return _op1(jnp.fft.ihfft, x, n, axis, norm, "ihfft")


# ------------------------------------------------------------------ n-d
def fftn(x, s=None, axes=None, norm="backward", name=None):
    return _opn(jnp.fft.fftn, x, s, axes, norm, "fftn")


def ifftn(x, s=None, axes=None, norm="backward", name=None):
    return _opn(jnp.fft.ifftn, x, s, axes, norm, "ifftn")


def rfftn(x, s=None, axes=None, norm="backward", name=None):
    return _opn(jnp.fft.rfftn, x, s, axes, norm, "rfftn")


def irfftn(x, s=None, axes=None, norm="backward", name=None):
    return _opn(jnp.fft.irfftn, x, s, axes, norm, "irfftn")


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    """Hermitian-input n-d FFT (real output). jnp has no hfftn; compose a
    forward c2c FFT over the leading axes with hfft along the last axis —
    matches scipy.fft.hfftn (paddle fftn_c2r parity)."""
    x = _t(x)
    norm = _norm(norm)

    def f(v):
        ax = tuple(range(v.ndim)) if axes is None else tuple(axes)
        lead, last = ax[:-1], ax[-1]
        n_last = None if s is None else s[-1]
        if lead:
            s_lead = None if s is None else tuple(s[:-1])
            v = jnp.fft.fftn(v, s=s_lead, axes=lead, norm=norm)
        return jnp.fft.hfft(v, n=n_last, axis=last, norm=norm)

    return apply(f, x, op_name="hfftn")


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    """Inverse of hfftn: ihfft along the last axis, inverse c2c over the
    leading axes — matches scipy.fft.ihfftn."""
    x = _t(x)
    norm = _norm(norm)

    def f(v):
        ax = tuple(range(v.ndim)) if axes is None else tuple(axes)
        lead, last = ax[:-1], ax[-1]
        n_last = None if s is None else s[-1]
        out = jnp.fft.ihfft(v, n=n_last, axis=last, norm=norm)
        if lead:
            s_lead = None if s is None else tuple(s[:-1])
            out = jnp.fft.ifftn(out, s=s_lead, axes=lead, norm=norm)
        return out

    return apply(f, x, op_name="ihfftn")


# ------------------------------------------------------------------ 2-d
def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _opn(jnp.fft.fft2, x, s, axes, norm, "fft2")


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _opn(jnp.fft.ifft2, x, s, axes, norm, "ifft2")


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _opn(jnp.fft.rfft2, x, s, axes, norm, "rfft2")


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _opn(jnp.fft.irfft2, x, s, axes, norm, "irfft2")


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return hfftn(x, s, axes, norm, name)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ihfftn(x, s, axes, norm, name)


# ------------------------------------------------------------------ helpers
def fftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.fftfreq(n, d=d)
    if dtype is not None:
        from .framework.dtype import to_jax_dtype

        out = out.astype(to_jax_dtype(dtype))
    return Tensor(out)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.rfftfreq(n, d=d)
    if dtype is not None:
        from .framework.dtype import to_jax_dtype

        out = out.astype(to_jax_dtype(dtype))
    return Tensor(out)


def fftshift(x, axes=None, name=None):
    return apply(lambda v: jnp.fft.fftshift(v, axes=axes), _t(x), op_name="fftshift")


def ifftshift(x, axes=None, name=None):
    return apply(lambda v: jnp.fft.ifftshift(v, axes=axes), _t(x), op_name="ifftshift")
