"""GPT family — the second decoder LM (BASELINE ladder rung 5 is GPT-3 1.3B
4-D hybrid; PaddleNLP's GPT implementation is the reference capability,
built from the same framework pieces: fleet TP layers, flash attention,
fused dropout-add-ln analogs).

Architecture (GPT-2/3 style, vs Llama): learned positional embeddings, pre-LN
LayerNorm (not RMSNorm), gelu MLP (not swiglu), standard MHA with bias terms.
TPU-first construction mirrors models/llama.py: TP layers lower to GSPMD
shardings, flash attention kernel on the hot path, KV-cache decode interface
compatible with models.generate.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

import jax.numpy as jnp

from .. import nn
from ..distributed.fleet.mp_layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from ..nn import functional as F
from ..ops.dispatch import apply
from ..tensor import manipulation as M
from ..tensor.tensor import Tensor

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM", "GPTPretrainingCriterion",
           "gpt_tiny", "gpt3_1_3b", "gpt_pipeline_descs"]


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 2048
    intermediate_size: Optional[int] = None
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    max_position_embeddings: int = 2048
    layer_norm_epsilon: float = 1e-5
    use_flash_attention: bool = True
    recompute: bool = False
    dtype: str = "float32"

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    # generate() compatibility (no GQA in GPT)
    @property
    def num_key_value_heads(self) -> int:
        return self.num_attention_heads


def gpt_tiny(**kw) -> "GPTConfig":
    return GPTConfig(vocab_size=512, hidden_size=128, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=256, **kw)


def gpt3_1_3b(**kw) -> "GPTConfig":
    """GPT-3 XL shape (the BASELINE 4-D hybrid rung)."""
    return GPTConfig(vocab_size=50304, hidden_size=2048, num_hidden_layers=24,
                     num_attention_heads=16, max_position_embeddings=2048, **kw)


class GPTAttention(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.head_dim = config.head_dim
        self.qkv = ColumnParallelLinear(h, 3 * h, gather_output=False)
        self.out_proj = RowParallelLinear(h, h, input_is_parallel=True)

    def forward(self, hidden, attn_mask=None, cache=None):
        b, s = hidden.shape[0], hidden.shape[1]
        qkv = self.qkv(hidden)

        def split_qkv(v):
            # [B, S, 3H] -> three [B, S, nh, hd]. 3-major layout (all q, then
            # k, then v along 3H): under mp sharding of the 3H dim the
            # reshape crosses shard boundaries, so GSPMD reshards here; XLA
            # folds that into the surrounding fusion on the bench shapes.
            v = v.reshape(b, s, 3, self.num_heads, self.head_dim)
            return v[:, :, 0], v[:, :, 1], v[:, :, 2]

        q, k, v = apply(lambda t: tuple(split_qkv(t)), qkv, op_name="split_qkv",
                        n_outs=3)
        new_cache = None
        if cache is not None:
            k = M.concat([cache[0], k], axis=1)
            v = M.concat([cache[1], v], axis=1)
            new_cache = (k, v)
        if attn_mask is None:
            out, _ = F.flash_attention(q, k, v, causal=True)
        else:
            out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask)
        out = self.out_proj(M.reshape(out, [b, s, self.num_heads * self.head_dim]))
        if cache is not None:
            return out, new_cache
        return out


class GPTBlock(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        h = config.hidden_size
        self.ln_1 = nn.LayerNorm(h, epsilon=config.layer_norm_epsilon)
        self.attn = GPTAttention(config)
        self.ln_2 = nn.LayerNorm(h, epsilon=config.layer_norm_epsilon)
        self.fc_in = ColumnParallelLinear(h, config.intermediate_size,
                                          gather_output=False)
        self.fc_out = RowParallelLinear(config.intermediate_size, h,
                                        input_is_parallel=True)

    def forward(self, hidden, attn_mask=None, cache=None):
        attn_out = self.attn(self.ln_1(hidden), attn_mask, cache)
        if cache is not None:
            attn_out, new_cache = attn_out
        hidden = hidden + attn_out
        hidden = hidden + self.fc_out(F.gelu(self.fc_in(self.ln_2(hidden))))
        if cache is not None:
            return hidden, new_cache
        return hidden


class GPTModel(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.wte = VocabParallelEmbedding(config.vocab_size, config.hidden_size)
        self.wpe = nn.Embedding(config.max_position_embeddings, config.hidden_size)
        self.h = nn.LayerList([GPTBlock(config) for _ in range(config.num_hidden_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)

    def forward(self, input_ids, attn_mask=None, caches=None):
        b, s = input_ids.shape
        offset = 0 if caches is None else int(caches[0][0].shape[1])
        pos = Tensor(jnp.arange(offset, offset + s, dtype=jnp.int32))
        hidden = self.wte(input_ids) + self.wpe(pos)
        if self.config.dtype == "bfloat16":
            hidden = hidden.astype("bfloat16")
        use_recompute = self.config.recompute and caches is None and self.training
        new_caches = []
        for i, block in enumerate(self.h):
            if caches is not None:
                hidden, c = block(hidden, attn_mask, caches[i])
                new_caches.append(c)
            elif use_recompute:
                from ..distributed.fleet.utils.recompute import recompute

                hidden = recompute(block, hidden) if attn_mask is None \
                    else recompute(block, hidden, attn_mask)
            else:
                hidden = block(hidden, attn_mask)
        hidden = self.ln_f(hidden)
        if caches is not None:
            return hidden, new_caches
        return hidden


class GPTForCausalLM(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        self.lm_head = ColumnParallelLinear(config.hidden_size, config.vocab_size,
                                            has_bias=False, gather_output=True)

    def forward(self, input_ids, attn_mask=None, caches=None):
        out = self.gpt(input_ids, attn_mask, caches)
        hidden = out[0] if caches is not None else out
        logits = self.lm_head(hidden)
        if caches is not None:
            return logits, out[1]
        return logits

    @property
    def num_params(self) -> int:
        return sum(p.size for p in self.parameters())


class GPTPretrainingCriterion(nn.Layer):
    """Shifted next-token CE."""

    def forward(self, logits, labels):
        shift_logits = logits[:, :-1, :]
        shift_labels = labels[:, 1:]
        return F.cross_entropy(
            M.reshape(shift_logits, [-1, shift_logits.shape[-1]]),
            M.reshape(shift_labels, [-1]),
        )


# ------------------------------------------------- pipeline-parallel mapping
class _GPTPipeEmbed(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.wte = VocabParallelEmbedding(config.vocab_size, config.hidden_size)
        self.wpe = nn.Embedding(config.max_position_embeddings, config.hidden_size)

    def forward(self, input_ids):
        s = input_ids.shape[1]
        pos = Tensor(jnp.arange(s, dtype=jnp.int32))
        hidden = self.wte(input_ids) + self.wpe(pos)
        if self.config.dtype == "bfloat16":
            hidden = hidden.astype("bfloat16")
        return hidden


class _GPTPipeHead(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln_f = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.lm_head = ColumnParallelLinear(config.hidden_size, config.vocab_size,
                                            has_bias=False, gather_output=True)

    def forward(self, hidden):
        return self.lm_head(self.ln_f(hidden))


def gpt_pipeline_descs(config: GPTConfig):
    """LayerDescs for fleet's PipelineLayer (see llama_pipeline_descs)."""
    from ..distributed.fleet.meta_parallel import LayerDesc

    return ([LayerDesc(_GPTPipeEmbed, config)]
            + [LayerDesc(GPTBlock, config) for _ in range(config.num_hidden_layers)]
            + [LayerDesc(_GPTPipeHead, config)])
