"""paddle_tpu.models — NLP model families (the PaddleNLP-capability surface
BASELINE exercises; vision models live in paddle_tpu.vision.models)."""
from .llama import (  # noqa: F401
    LlamaConfig,
    LlamaForCausalLM,
    LlamaModel,
    LlamaPretrainingCriterion,
    llama_7b,
    llama_pipeline_descs,
    llama_tiny,
)
from .generation import generate, greedy_decode  # noqa: F401,E402
from .gpt import (  # noqa: F401,E402
    GPTConfig,
    GPTForCausalLM,
    GPTModel,
    GPTPretrainingCriterion,
    gpt3_1_3b,
    gpt_pipeline_descs,
    gpt_tiny,
)
