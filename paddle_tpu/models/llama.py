"""Llama model family — the flagship decoder LM.

Capability target: PaddleNLP's Llama implementation exercised by BASELINE
(Llama-7B pretrain tokens/sec/chip); the reference framework supplies its
building blocks (fused rope/rms_norm/swiglu:
/root/reference/python/paddle/incubate/nn/functional/, flash attention:
python/paddle/nn/functional/flash_attention.py:198, TP layers:
fleet/layers/mpu/mp_layers.py).

TPU-first construction: bf16 params, Pallas flash attention, RMSNorm in fp32
accumulation, rotary embeddings precomputed once, Column/RowParallel layers
that lower to GSPMD shardings on the 'mp' axis, batch sharded on 'dp', and
optional sequence-parallel activation sharding on 'sep'.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from .. import nn
from ..distributed.fleet.mp_layers import ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding
from ..nn import functional as F
from ..ops.dispatch import apply
from ..tensor import manipulation as M
from ..tensor.tensor import Tensor

__all__ = ["LlamaConfig", "LlamaModel", "LlamaForCausalLM", "LlamaPretrainingCriterion",
           "llama_tiny", "llama_7b", "llama_pipeline_descs"]


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: Optional[int] = None
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    use_flash_attention: bool = True
    recompute: bool = False  # activation checkpointing per decoder layer
    dtype: str = "float32"

    def __post_init__(self):
        if self.num_key_value_heads is None:
            self.num_key_value_heads = self.num_attention_heads

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


def llama_tiny(**kw) -> "LlamaConfig":
    return LlamaConfig(vocab_size=512, hidden_size=128, intermediate_size=352,
                       num_hidden_layers=2, num_attention_heads=4,
                       max_position_embeddings=256, **kw)


def llama_7b(**kw) -> "LlamaConfig":
    return LlamaConfig(**kw)


def _rope_cache(config: LlamaConfig):
    dim = config.head_dim
    inv_freq = 1.0 / (config.rope_theta ** (np.arange(0, dim, 2, dtype=np.float64) / dim))
    t = np.arange(config.max_position_embeddings, dtype=np.float64)
    freqs = np.outer(t, inv_freq)  # [S, dim/2]
    return np.cos(freqs).astype(np.float32), np.sin(freqs).astype(np.float32)


def apply_rotary_pos_emb(q, k, cos, sin, position_offset=0):
    """q/k: [B, S, H, D]; cos/sin buffers [Smax, D/2] (reference fused analog:
    incubate fused_rotary_position_embedding). ``position_offset`` may be a
    scalar Tensor (traced — the static-cache decode path slices the rope
    window with lax.dynamic_slice).

    Default path is the jnp rotation — measured on v5e, XLA fuses it into the
    surrounding projections as fast as the Pallas rope kernel and without the
    custom-call layout copies (0.4354 vs 0.4325 MFU on the 1B bench).
    Set PADDLE_TPU_FUSED_LLAMA=1 to route through ops/pallas/fused_ops.py."""
    import os

    if isinstance(position_offset, Tensor):
        def f_dyn(qv, kv, c, s, off):
            S = qv.shape[1]
            off = off.astype(jnp.int32)
            cw = jax.lax.dynamic_slice_in_dim(c, off, S)
            sw = jax.lax.dynamic_slice_in_dim(s, off, S)

            def rot(x):
                x1, x2 = jnp.split(x, 2, axis=-1)
                cb = cw[None, :, None, :]
                sb = sw[None, :, None, :]
                return jnp.concatenate([x1 * cb - x2 * sb, x2 * cb + x1 * sb],
                                       axis=-1).astype(x.dtype)

            return rot(qv), rot(kv)

        return apply(lambda *a: tuple(f_dyn(*a)), q, k, cos, sin, position_offset,
                     op_name="fused_rope_dyn", n_outs=2)

    if os.environ.get("PADDLE_TPU_FUSED_LLAMA") == "1":
        from ..ops.pallas.fused_ops import rope_fused

        def f(qv, kv, c, s):
            S = qv.shape[1]
            cw = c[position_offset : position_offset + S]
            sw = s[position_offset : position_offset + S]
            return tuple(rope_fused(qv, kv, cw, sw))

        return apply(f, q, k, cos, sin, op_name="fused_rope", n_outs=2)

    def rope(x, c, s):
        S = x.shape[1]
        c = c[position_offset : position_offset + S][None, :, None, :]  # [1,S,1,D/2]
        s_ = s[position_offset : position_offset + S][None, :, None, :]
        x1, x2 = jnp.split(x, 2, axis=-1)
        return jnp.concatenate([x1 * c - x2 * s_, x2 * c + x1 * s_], axis=-1).astype(x.dtype)

    return apply(lambda qv, kv, c, s: (rope(qv, c, s), rope(kv, c, s)),
                 q, k, cos, sin, op_name="fused_rope", n_outs=2)


def _hcg():
    from ..distributed.topology import get_hybrid_communicate_group

    return get_hybrid_communicate_group()


class LlamaAttention(nn.Layer):
    @staticmethod
    def _sep_mesh():
        hcg = _hcg()
        if hcg is not None and hcg.axis_size("sep") > 1:
            return hcg.mesh
        return None

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        h = config.hidden_size
        self.head_dim = config.head_dim
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        self.q_proj = ColumnParallelLinear(h, self.num_heads * self.head_dim, has_bias=False, gather_output=False)
        self.k_proj = ColumnParallelLinear(h, self.num_kv_heads * self.head_dim, has_bias=False, gather_output=False)
        self.v_proj = ColumnParallelLinear(h, self.num_kv_heads * self.head_dim, has_bias=False, gather_output=False)
        self.o_proj = RowParallelLinear(self.num_heads * self.head_dim, h, has_bias=False, input_is_parallel=True)

    def _mp_active(self):
        hcg = _hcg()
        return hcg is not None and hcg.axis_size("mp") > 1

    def forward(self, hidden, cos, sin, attn_mask=None, cache=None):
        import os

        b, s = hidden.shape[0], hidden.shape[1]
        nh, nkv, hd = self.num_heads, self.num_kv_heads, self.head_dim
        fuse_train = os.environ.get("PADDLE_TPU_FUSED_QKV", "0") == "1"
        if ((s == 1 and cache is not None) or fuse_train) and not self._mp_active():
            # decode step: ONE fused qkv matmul — the weight concat is loop-
            # invariant, so XLA hoists it out of the decode scan and the step
            # streams one [h, (nh+2·nkv)·hd] weight (measured 621→773 GB/s
            # vs three separate matmuls at decode shapes)
            def qkv_fused(hv, wq, wk, wv):
                w = jnp.concatenate([wq, wk, wv], axis=1)
                return hv @ w.astype(hv.dtype)

            qkv = apply(qkv_fused, hidden, self.q_proj.weight, self.k_proj.weight,
                        self.v_proj.weight, op_name="qkv_fused")
            qd, kd = nh * hd, nkv * hd
            q = M.reshape(qkv[:, :, :qd], [b, s, nh, hd])
            k = M.reshape(qkv[:, :, qd:qd + kd], [b, s, nkv, hd])
            v = M.reshape(qkv[:, :, qd + kd:], [b, s, nkv, hd])
        else:
            q = M.reshape(self.q_proj(hidden), [b, s, nh, hd])
            k = M.reshape(self.k_proj(hidden), [b, s, nkv, hd])
            v = M.reshape(self.v_proj(hidden), [b, s, nkv, hd])
        if cache is not None and len(cache) == 3:
            return self._static_cache_attn(q, k, v, cos, sin, cache, b, s)
        offset = 0
        if cache is not None:
            offset = cache[0].shape[1]
        q, k = apply_rotary_pos_emb(q, k, cos, sin, position_offset=offset)
        new_cache = None
        if cache is not None:
            k = M.concat([cache[0], k], axis=1)
            v = M.concat([cache[1], v], axis=1)
            new_cache = (k, v)
        ring_mesh = self._sep_mesh() if (cache is None and attn_mask is None) else None
        if ring_mesh is not None:
            # sequence parallelism: exact blockwise ring attention over 'sep'
            from ..ops.ring_attention import ring_attention

            hcg = _hcg()
            b_ax = "dp" if hcg.axis_size("dp") > 1 else None
            mp_deg = hcg.axis_size("mp")
            h_ax = "mp" if mp_deg > 1 else None
            rep = self.num_heads // self.num_kv_heads

            def ring_fn(qv, kv, vv):
                # GQA KV heads are indexed inside the ring/flash kernels;
                # only when the KV head count cannot be sharded on mp do we
                # fall back to repeating them up front
                if rep > 1 and h_ax is not None and self.num_kv_heads % mp_deg:
                    kv = jnp.repeat(kv, rep, axis=2)
                    vv = jnp.repeat(vv, rep, axis=2)
                return ring_attention(qv, kv, vv, mesh=ring_mesh, axis_name="sep",
                                      causal=True, batch_axis=b_ax, head_axis=h_ax)

            out = apply(ring_fn, q, k, v, op_name="ring_attention")
        elif attn_mask is None and cache is None:
            out, _ = F.flash_attention(q, k, v, causal=True)
        else:
            out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                                 is_causal=attn_mask is None)
        out = M.reshape(out, [b, s, self.num_heads * self.head_dim])
        out = self.o_proj(out)
        if cache is not None:
            return out, new_cache
        return out

    def _static_cache_attn(self, q, k, v, cos, sin, cache, b, s):
        """Fixed-size KV ring (serving decode): cache = (k_buf [B,L,KVH,D],
        v_buf, pos ()) — every decode step has identical shapes, so the whole
        loop runs from ONE compiled program. The single-token step runs the
        fused Pallas decode path (ops/pallas/decode_attention.py): aliased
        in-place ring writes + native-layout online-softmax attention — the
        reference's masked_multihead_attention decode kernel analog."""
        import os

        kbuf, vbuf, pos = cache
        q, k = apply_rotary_pos_emb(q, k, cos, sin, position_offset=pos)
        mode = os.environ.get("PADDLE_TPU_DECODE_KERNEL", "einsum")
        if s == 1 and mode != "0" and self.num_heads % kbuf.shape[2] == 0:
            if mode == "pallas":
                # kept for study: measured SLOWER than the einsum path on
                # v5e (299-366 vs 610-688 GB/s — per-head M=1 MXU dots don't
                # pipeline; see PROFILE_r04.md)
                from ..ops.pallas.decode_attention import decode_attention, kv_ring_write

                def fused(qv, kv_, vv, kb, vb, p):
                    p32 = p.astype(jnp.int32)
                    kb = kv_ring_write(kb, kv_, p32)
                    vb = kv_ring_write(vb, vv, p32)
                    o = decode_attention(qv, kb, vb, p32)
                    return o, kb, vb
            else:
                # native-layout decode attention: NO head-major transposes of
                # the ring (the sdpa path's swapaxes cost a full extra KV
                # pass); fp32 softmax; GQA via grouped reshape, K/V never
                # repeated. Ring writes stay XLA dynamic_update_slice — in a
                # scan carry they are in-place (measured free).
                import math as _math

                scale = 1.0 / _math.sqrt(self.head_dim)

                def fused(qv, kv_, vv, kb, vb, p):
                    p32 = p.astype(jnp.int32)
                    kb = jax.lax.dynamic_update_slice(
                        kb, kv_.astype(kb.dtype), (0, p32, 0, 0))
                    vb = jax.lax.dynamic_update_slice(
                        vb, vv.astype(vb.dtype), (0, p32, 0, 0))
                    bq, _, nh, hd = qv.shape
                    kvh = kb.shape[2]
                    rep = nh // kvh
                    L = kb.shape[1]
                    qg = qv.reshape(bq, 1, kvh, rep, hd)
                    sc = jnp.einsum("bqgrd,blgd->bgrql", qg, kb).astype(jnp.float32) * scale
                    cols = jnp.arange(L)
                    sc = jnp.where(cols[None, None, None, None, :] <= p32, sc, -1e30)
                    pr = jax.nn.softmax(sc, axis=-1).astype(qv.dtype)
                    o = jnp.einsum("bgrql,blgd->bqgrd", pr, vb)
                    return o.reshape(bq, 1, nh, hd), kb, vb

            out, kbuf, vbuf = apply(fused, q, k, v, kbuf, vbuf, pos,
                                    op_name="decode_attention", n_outs=3)
            out = M.reshape(out, [b, s, self.num_heads * self.head_dim])
            return self.o_proj(out), (kbuf, vbuf, pos + s)

        def write(buf, new, p):
            return jax.lax.dynamic_update_slice(
                buf, new.astype(buf.dtype), (0, p.astype(jnp.int32), 0, 0))

        kbuf = apply(write, kbuf, k, pos, op_name="kv_write")
        vbuf = apply(write, vbuf, v, pos, op_name="kv_write")
        L = kbuf.shape[1]

        def mk_mask(p):
            rows = p.astype(jnp.int32) + jnp.arange(s)[:, None]
            cols = jnp.arange(L)[None, :]
            return jnp.where(cols <= rows, 0.0, -1e30)[None, None]  # [1,1,s,L]

        mask = apply(mk_mask, pos, op_name="kv_mask")
        out = F.scaled_dot_product_attention(q, kbuf, vbuf, attn_mask=mask)
        out = M.reshape(out, [b, s, self.num_heads * self.head_dim])
        return self.o_proj(out), (kbuf, vbuf, pos + s)


class LlamaMLP(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        h, m = config.hidden_size, config.intermediate_size
        self.gate_proj = ColumnParallelLinear(h, m, has_bias=False, gather_output=False)
        self.up_proj = ColumnParallelLinear(h, m, has_bias=False, gather_output=False)
        self.down_proj = RowParallelLinear(m, h, has_bias=False, input_is_parallel=True)

    def forward(self, x):
        # swiglu: XLA fuses silu*mul into the projections (measured equal to
        # the Pallas kernel minus its layout copies; see apply_rotary_pos_emb)
        import os

        if os.environ.get("PADDLE_TPU_FUSED_LLAMA") == "1":
            from ..ops.pallas.fused_ops import swiglu_fused

            gated = apply(lambda a, b: swiglu_fused(a, b),
                          self.gate_proj(x), self.up_proj(x), op_name="swiglu")
            return self.down_proj(gated)
        hcg = _hcg()
        mp_on = hcg is not None and hcg.axis_size("mp") > 1
        fuse_train = os.environ.get("PADDLE_TPU_FUSED_QKV", "0") == "1"
        if (x.shape[1] == 1 or fuse_train) and not mp_on:
            # decode step: gate|up as ONE streamed weight (concat hoisted
            # out of the decode scan; measured 621→773 GB/s)
            m = self.gate_proj.weight.shape[1]

            def gu_fused(hv, wg, wu):
                w = jnp.concatenate([wg, wu], axis=1)
                return hv @ w.astype(hv.dtype)

            gu = apply(gu_fused, x, self.gate_proj.weight, self.up_proj.weight,
                       op_name="gate_up_fused")
            return self.down_proj(F.silu(gu[:, :, :m]) * gu[:, :, m:])
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.self_attn = LlamaAttention(config)
        self.mlp = LlamaMLP(config)
        self.input_layernorm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        self.post_attention_layernorm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)

    def forward(self, hidden, cos, sin, attn_mask=None, cache=None):
        residual = hidden
        attn_out = self.self_attn(self.input_layernorm(hidden), cos, sin, attn_mask, cache)
        if cache is not None:
            attn_out, new_cache = attn_out
        hidden = residual + attn_out
        hidden = hidden + self.mlp(self.post_attention_layernorm(hidden))
        if cache is not None:
            return hidden, new_cache
        return hidden


class LlamaModel(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = VocabParallelEmbedding(config.vocab_size, config.hidden_size)
        self.layers = nn.LayerList([LlamaDecoderLayer(config) for _ in range(config.num_hidden_layers)])
        self.norm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        cos, sin = _rope_cache(config)
        self.register_buffer("rope_cos", Tensor(cos), persistable=False)
        self.register_buffer("rope_sin", Tensor(sin), persistable=False)

    def forward(self, input_ids, attn_mask=None, caches=None):
        hidden = self.embed_tokens(input_ids)
        if self.config.dtype == "bfloat16":
            hidden = hidden.astype("bfloat16")
        hcg = _hcg()
        if hcg is not None and hcg.axis_size("sep") > 1 and caches is None:
            sep = hcg.axis_size("sep")
            if input_ids.shape[1] % sep != 0:
                raise ValueError(
                    f"sequence length {input_ids.shape[1]} must be divisible by "
                    f"sep_degree={sep} for sequence parallelism (pad the batch; "
                    "XLA needs static equal shards)"
                )
            # sequence parallelism: shard activations [B, S, H] on (dp, sep)
            from jax.sharding import NamedSharding, PartitionSpec

            b_ax = "dp" if hcg.axis_size("dp") > 1 else None
            sharding = NamedSharding(hcg.mesh, PartitionSpec(b_ax, "sep", None))
            hidden = apply(lambda v: jax.lax.with_sharding_constraint(v, sharding),
                           hidden, op_name="sep_shard")
        cos, sin = self._buffers["rope_cos"], self._buffers["rope_sin"]
        new_caches = []
        use_recompute = self.config.recompute and caches is None and self.training
        for i, layer in enumerate(self.layers):
            if caches is not None:
                hidden, c = layer(hidden, cos, sin, attn_mask, caches[i])
                new_caches.append(c)
            elif use_recompute:
                from ..distributed.fleet.utils.recompute import recompute

                if attn_mask is None:
                    hidden = recompute(layer, hidden, cos, sin)
                else:
                    hidden = recompute(layer, hidden, cos, sin, attn_mask)
            else:
                hidden = layer(hidden, cos, sin, attn_mask)
        hidden = self.norm(hidden)
        if caches is not None:
            return hidden, new_caches
        return hidden


class LlamaForCausalLM(nn.Layer):
    supports_static_kv_cache = True  # 3-tuple (k_buf, v_buf, pos) ring decode

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = ColumnParallelLinear(config.hidden_size, config.vocab_size,
                                                has_bias=False, gather_output=True)

    def forward(self, input_ids, attn_mask=None, caches=None):
        out = self.llama(input_ids, attn_mask, caches)
        hidden = out[0] if caches is not None else out
        if self.lm_head is None:
            logits = F.linear(hidden, Tensor(self.llama.embed_tokens.weight._value.T,
                                             stop_gradient=self.llama.embed_tokens.weight.stop_gradient))
        else:
            logits = self.lm_head(hidden)
        if caches is not None:
            return logits, out[1]
        return logits

    def pretraining_loss(self, input_ids, labels=None, n_chunks: int = 8):
        """Shifted next-token loss via the fused chunked head (no [N, V]
        logits in HBM). Numerically equals LlamaPretrainingCriterion(
        self(ids), ids) up to fp32-accumulated matmul precision."""
        if labels is None:
            labels = input_ids
        hidden = self.llama(input_ids)
        if self.lm_head is None:
            w = Tensor(self.llama.embed_tokens.weight._value.T,
                       stop_gradient=self.llama.embed_tokens.weight.stop_gradient)
        else:
            w = self.lm_head.weight
        return apply(lambda h, wv, y: _chunked_lm_loss(h, wv, y, n_chunks),
                     hidden, w, labels, op_name="fused_lm_loss")

    @property
    def num_params(self) -> int:
        return sum(p.size for p in self.parameters())


# ------------------------------------------------- pipeline-parallel mapping
class _PipeEmbed(nn.Layer):
    """Stage-0 module: token embedding (+ bf16 cast) — single-tensor
    in/out as the pipeline engine requires."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = VocabParallelEmbedding(config.vocab_size, config.hidden_size)

    def forward(self, input_ids):
        hidden = self.embed_tokens(input_ids)
        if self.config.dtype == "bfloat16":
            hidden = hidden.astype("bfloat16")
        return hidden


class _PipeDecoder(nn.Layer):
    """One decoder layer owning its own rope cache (stages are independent
    modules; the cache is deterministic from the config)."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.block = LlamaDecoderLayer(config)
        cos, sin = _rope_cache(config)
        self.register_buffer("rope_cos", Tensor(cos), persistable=False)
        self.register_buffer("rope_sin", Tensor(sin), persistable=False)

    def forward(self, hidden):
        return self.block(hidden, self._buffers["rope_cos"], self._buffers["rope_sin"])


class _PipeHead(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.norm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        self.lm_head = ColumnParallelLinear(config.hidden_size, config.vocab_size,
                                            has_bias=False, gather_output=True)

    def forward(self, hidden):
        return self.lm_head(self.norm(hidden))


class _PipeNorm(nn.Layer):
    """Final RMSNorm as its own tail stage piece (used with tied embeddings,
    where the logits matmul reuses the embedding weight)."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.norm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)

    def forward(self, hidden):
        return self.norm(hidden)


def _tied_logits(embed_layer, hidden):
    """SharedLayerDesc forward_func for the tail occurrence of the shared
    embedding: logits = hidden @ Wᵉᵐᵇᵀ (reference GPT tied-head contract,
    pp_layers.py SharedLayerDesc:76)."""
    from .. import matmul

    w = embed_layer.embed_tokens.weight
    return matmul(hidden.astype(w.dtype), w, transpose_y=True)


def llama_pipeline_descs(config: LlamaConfig, tie_embeddings: bool = False):
    """LayerDescs for fleet's PipelineLayer: [embed] + L×[decoder] + [head].

    Compose with pp via ``PipelineLayer(layers=llama_pipeline_descs(cfg),
    num_stages=pp, loss_fn=...)`` under a hybrid dp×pp×mp mesh — the TP
    layers inside each stage shard on the stage's mp submesh (the 4-D hybrid
    of BASELINE's GPT-3 rung).

    ``tie_embeddings=True`` shares ONE embedding layer between the stage-0
    lookup and the last-stage logits head via SharedLayerDesc — the compiled
    pipeline psums its gradient across both uses (the reference's
    shared-grad allreduce)."""
    from ..distributed.fleet.meta_parallel import LayerDesc, SharedLayerDesc

    decoders = [LayerDesc(_PipeDecoder, config)
                for _ in range(config.num_hidden_layers)]
    if tie_embeddings:
        return ([SharedLayerDesc("embed", _PipeEmbed, None, "weight", config)]
                + decoders
                + [LayerDesc(_PipeNorm, config),
                   SharedLayerDesc("embed", _PipeEmbed, _tied_logits, "weight",
                                   config)])
    return [LayerDesc(_PipeEmbed, config)] + decoders + [LayerDesc(_PipeHead, config)]


class LlamaPretrainingCriterion(nn.Layer):
    """Shifted next-token CE (PaddleNLP criterion parity)."""

    def __init__(self, config: Optional[LlamaConfig] = None):
        super().__init__()

    def forward(self, logits, labels):
        shift_logits = logits[:, :-1, :]
        shift_labels = labels[:, 1:]
        return F.cross_entropy(
            M.reshape(shift_logits, [-1, shift_logits.shape[-1]]),
            M.reshape(shift_labels, [-1]),
        )


def _chunked_lm_loss(hidden, w, labels, n_chunks: int):
    """Fused lm_head + shifted CE without materializing [N, V] logits.

    Tokens stream through in n_chunks slices; each slice's logits + fp32
    logsumexp live only inside a rematerialized (jax.checkpoint) chunk, so
    peak memory is O(N·V/n_chunks) instead of O(N·V) — the TPU analog of the
    reference's fused parallel cross-entropy
    (fleet/layers/mpu/mp_layers.py ParallelCrossEntropy + PaddleNLP's fused
    head-loss path)."""
    from jax.scipy.special import logsumexp

    B, S, H = hidden.shape
    sh = hidden[:, :-1, :].reshape(-1, H)
    sl = labels[:, 1:].reshape(-1).astype(jnp.int32)
    N = sh.shape[0]
    pad = (-N) % n_chunks
    if pad:
        sh = jnp.concatenate([sh, jnp.zeros((pad, H), sh.dtype)])
        sl = jnp.concatenate([sl, jnp.full((pad,), -1, sl.dtype)])
    hs = sh.reshape(n_chunks, -1, H)
    ys = sl.reshape(n_chunks, -1)

    def chunk_sum(h_c, y_c):
        logits = jax.lax.dot_general(
            h_c, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        lse = logsumexp(logits, axis=-1)
        valid = y_c >= 0
        tgt = jnp.take_along_axis(logits, jnp.maximum(y_c, 0)[:, None], axis=1)[:, 0]
        return jnp.sum(jnp.where(valid, lse - tgt, 0.0)), jnp.sum(valid)

    def body(carry, xy):
        tot, cnt = carry
        s, c = jax.checkpoint(chunk_sum)(*xy)
        return (tot + s, cnt + c), None

    (tot, cnt), _ = jax.lax.scan(body, (jnp.float32(0), jnp.int32(0)), (hs, ys))
    return tot / jnp.maximum(cnt.astype(jnp.float32), 1.0)
