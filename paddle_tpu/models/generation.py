"""Autoregressive generation utilities (capability parity: PaddleNLP's
``model.generate`` surface that BASELINE's serving story implies; reference
framework pieces: paddle.tensor.top_p_sampling + the KV-cache decode path
fused ops serve, incubate/nn/functional/masked_multihead_attention.py).

TPU-native notes: prefill runs as one compiled forward; the decode loop is
eager over single-token steps with KV caches threaded through the model's
``caches`` interface (each step's shapes grow, so the per-step forward is
recompiled per length unless the model buckets — acceptable for the
capability tier; serving-grade decode belongs to a fixed-size cache ring).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..tensor.tensor import Tensor

__all__ = ["generate", "greedy_decode"]


def _make_static_caches(model, B: int, S: int, max_new_tokens: int,
                        max_length: Optional[int]):
    """Validate + build the fixed-size KV ring triples (shared by generate's
    static branch and greedy_decode)."""
    cfg = model.config
    if not getattr(model, "supports_static_kv_cache", False):
        raise ValueError(
            f"{type(model).__name__} does not support static KV caches "
            "(3-tuple ring buffers); use a Llama-family model")
    L = int(max_length or (S + max_new_tokens))
    if L < S + max_new_tokens:
        raise ValueError(
            f"max_length={L} is smaller than prompt ({S}) + max_new_tokens "
            f"({max_new_tokens}); the KV ring would silently overwrite its "
            "last row")
    if L > cfg.max_position_embeddings:
        raise ValueError(
            f"max_length={L} exceeds max_position_embeddings "
            f"({cfg.max_position_embeddings}); rope rows past the table end "
            "would be clamped and rotations silently wrong")
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    caches = [(Tensor(jnp.zeros((B, L, cfg.num_key_value_heads, cfg.head_dim), dtype)),
               Tensor(jnp.zeros((B, L, cfg.num_key_value_heads, cfg.head_dim), dtype)),
               Tensor(jnp.zeros((), jnp.int32)))
              for _ in range(cfg.num_hidden_layers)]
    return L, caches


def generate(model, input_ids, max_new_tokens: int = 32, do_sample: bool = False,
             top_p: float = 1.0, temperature: float = 1.0,
             eos_token_id: Optional[int] = None, use_static_cache: bool = False,
             max_length: Optional[int] = None):
    """Greedy / nucleus decoding with KV caches.

    model: a causal LM whose forward supports ``model(ids, caches=...)``
    returning (logits, new_caches) — e.g. LlamaForCausalLM.
    Returns the generated ids [B, <=max_new_tokens] (prompt not included).

    ``use_static_cache=True`` (Llama-family): fixed-size [B, max_length] KV
    buffers + a traced write position, run through ``jit.to_static`` — every
    decode step has identical shapes, so the whole loop executes from ONE
    compiled program (two compiles total: prefill + decode) instead of one
    compile per sequence length. The serving-grade decode path.
    """
    from ..autograd import tape
    from ..tensor.search import top_p_sampling

    ids = input_ids if isinstance(input_ids, Tensor) else Tensor(jnp.asarray(input_ids))
    B, S = ids.shape
    cfg = getattr(model, "config", None)
    if cfg is None:
        raise ValueError("generate() needs a model with a .config describing "
                         "num_hidden_layers/num_key_value_heads/head_dim "
                         "(e.g. LlamaForCausalLM)")
    n_layers = cfg.num_hidden_layers
    n_kv = cfg.num_key_value_heads
    head_dim = cfg.head_dim
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32

    with tape.no_grad():
        if use_static_cache:
            from ..jit import to_static

            _, caches = _make_static_caches(model, B, S, max_new_tokens, max_length)
            # cache the traced forward ON the model so repeated generate()
            # calls reuse the compiled prefill/decode programs
            if not hasattr(model, "_decode_cache"):
                model._decode_cache = {}
            fwd = model._decode_cache.get("_static_fwd")
            if fwd is None:
                fwd = to_static(model)
                model._decode_cache["_static_fwd"] = fwd
        else:
            # growing caches: prefill with empty buffers so the forward
            # returns them populated (one recompile per decode length)
            caches = [(Tensor(jnp.zeros((B, 0, n_kv, head_dim), dtype)),
                       Tensor(jnp.zeros((B, 0, n_kv, head_dim), dtype)))
                      for _ in range(n_layers)]
            fwd = model
        logits, caches = fwd(ids, caches=caches)
        out_tokens = []
        finished = np.zeros((B,), bool)
        for step_i in range(max_new_tokens):
            last = logits._value[:, -1, :].astype(jnp.float32)
            if temperature != 1.0:
                last = last / max(temperature, 1e-6)
            if do_sample:
                probs = jax.nn.softmax(last, axis=-1)
                _, idx = top_p_sampling(Tensor(probs),
                                        Tensor(jnp.full((B,), float(top_p))))
                nxt = np.asarray(idx._value).reshape(B)
            else:
                nxt = np.asarray(jnp.argmax(last, axis=-1)).reshape(B)
            if eos_token_id is not None:
                nxt = np.where(finished, eos_token_id, nxt)
                finished |= nxt == eos_token_id
            out_tokens.append(nxt)
            done = eos_token_id is not None and finished.all()
            if done or step_i == max_new_tokens - 1:
                break  # budget spent: don't pay a decode forward we'd discard
            cur = Tensor(jnp.asarray(nxt.astype(np.int32)[:, None]))
            logits, caches = fwd(cur, caches=caches)
    if not out_tokens:
        return Tensor(jnp.zeros((B, 0), jnp.int32))
    return Tensor(jnp.asarray(np.stack(out_tokens, axis=1).astype(np.int32)))


def greedy_decode(model, input_ids, max_new_tokens: int, max_length: Optional[int] = None):
    """Whole-loop compiled greedy decoding: prefill + a lax.scan of static-
    cache decode steps run as ONE program — a single host dispatch produces
    all tokens (no per-token round trips; the device-side sampling loop of a
    serving runtime). Llama-family models."""
    from ..autograd import tape
    from ..jit import to_static
    from ..ops.dispatch import apply

    ids = input_ids if isinstance(input_ids, Tensor) else Tensor(jnp.asarray(input_ids))
    B, S = ids.shape
    if max_new_tokens <= 0:
        return Tensor(jnp.zeros((B, 0), jnp.int32))
    L, prebuilt_caches = _make_static_caches(model, B, S, max_new_tokens, max_length)

    class _Decoder:
        """to_static-traceable callable bound to the model (state traced)."""

        def __init__(self, m, n_new):
            self.m = m
            self.n_new = n_new

        def __call__(self, ids_t, caches):
            logits, caches = self.m(ids_t, caches=caches)
            n_new = self.n_new
            m = self.m

            def prog(last_logits, *cache_vals):
                def body(carry, _):
                    cur, cvals = carry
                    caches_t = [tuple(Tensor(v) for v in triple)
                                for triple in cvals]
                    lg, nc = m(Tensor(cur), caches=caches_t)
                    nxt = jnp.argmax(
                        lg._value[:, -1, :].astype(jnp.float32), -1
                    ).astype(jnp.int32)[:, None]
                    flat = tuple(tuple(x._value for x in pair) for pair in nc)
                    return (nxt, flat), nxt[:, 0]

                first = jnp.argmax(last_logits[:, -1, :].astype(jnp.float32),
                                   -1).astype(jnp.int32)[:, None]
                cvals0 = tuple(tuple(cache_vals[i * 3 + j] for j in range(3))
                               for i in range(len(cache_vals) // 3))
                if n_new == 1:
                    return first
                (_, _), toks = jax.lax.scan(body, (first, cvals0), None,
                                            length=n_new - 1)
                return jnp.concatenate([first, jnp.moveaxis(toks, 0, 1)], axis=1)

            flat_tensors = [t for triple in caches for t in triple]
            return apply(prog, logits, *flat_tensors, op_name="greedy_decode")

    key = ("_greedy_decoder", max_new_tokens, L, B, S)
    if not hasattr(model, "_decode_cache"):
        model._decode_cache = {}
    st = model._decode_cache.get(key)
    if st is None:
        dec = _Decoder(model, max_new_tokens)
        st = to_static(lambda ids_t, caches: dec(ids_t, caches),
                       state_layer=model)  # trace params/buffers as state
        # bound the per-model program cache: each entry holds a compiled
        # whole-loop XLA program. Serving with naturally varying prompt
        # lengths should pad/bucket S (see jit bucket_dynamic_batch) rather
        # than rely on one program per exact length.
        decoder_keys = [k for k in model._decode_cache
                        if isinstance(k, tuple) and k and k[0] == "_greedy_decoder"]
        if len(decoder_keys) >= 8:
            model._decode_cache.pop(decoder_keys[0], None)
        model._decode_cache[key] = st
    with tape.no_grad():
        return st(ids, prebuilt_caches)
