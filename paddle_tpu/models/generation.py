"""Autoregressive generation utilities (capability parity: PaddleNLP's
``model.generate`` surface that BASELINE's serving story implies; reference
framework pieces: paddle.tensor.top_p_sampling + the KV-cache decode path
fused ops serve, incubate/nn/functional/masked_multihead_attention.py).

TPU-native notes: prefill runs as one compiled forward; the decode loop is
eager over single-token steps with KV caches threaded through the model's
``caches`` interface (each step's shapes grow, so the per-step forward is
recompiled per length unless the model buckets — acceptable for the
capability tier; serving-grade decode belongs to a fixed-size cache ring).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..tensor.tensor import Tensor

__all__ = ["generate"]


def generate(model, input_ids, max_new_tokens: int = 32, do_sample: bool = False,
             top_p: float = 1.0, temperature: float = 1.0,
             eos_token_id: Optional[int] = None):
    """Greedy / nucleus decoding with KV caches.

    model: a causal LM whose forward supports ``model(ids, caches=...)``
    returning (logits, new_caches) — e.g. LlamaForCausalLM.
    Returns the generated ids [B, <=max_new_tokens] (prompt not included).
    """
    from ..autograd import tape
    from ..tensor.search import top_p_sampling

    ids = input_ids if isinstance(input_ids, Tensor) else Tensor(jnp.asarray(input_ids))
    B, S = ids.shape
    cfg = getattr(model, "config", None)
    if cfg is None:
        raise ValueError("generate() needs a model with a .config describing "
                         "num_hidden_layers/num_key_value_heads/head_dim "
                         "(e.g. LlamaForCausalLM)")
    n_layers = cfg.num_hidden_layers
    n_kv = cfg.num_key_value_heads
    head_dim = cfg.head_dim
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32

    with tape.no_grad():
        # prefill with empty caches so the forward returns them populated
        empty = [(Tensor(jnp.zeros((B, 0, n_kv, head_dim), dtype)),
                  Tensor(jnp.zeros((B, 0, n_kv, head_dim), dtype)))
                 for _ in range(n_layers)]
        logits, caches = model(ids, caches=empty)
        out_tokens = []
        finished = np.zeros((B,), bool)
        for step_i in range(max_new_tokens):
            last = logits._value[:, -1, :].astype(jnp.float32)
            if temperature != 1.0:
                last = last / max(temperature, 1e-6)
            if do_sample:
                probs = jax.nn.softmax(last, axis=-1)
                _, idx = top_p_sampling(Tensor(probs),
                                        Tensor(jnp.full((B,), float(top_p))))
                nxt = np.asarray(idx._value).reshape(B)
            else:
                nxt = np.asarray(jnp.argmax(last, axis=-1)).reshape(B)
            if eos_token_id is not None:
                nxt = np.where(finished, eos_token_id, nxt)
                finished |= nxt == eos_token_id
            out_tokens.append(nxt)
            done = eos_token_id is not None and finished.all()
            if done or step_i == max_new_tokens - 1:
                break  # budget spent: don't pay a decode forward we'd discard
            cur = Tensor(jnp.asarray(nxt.astype(np.int32)[:, None]))
            logits, caches = model(cur, caches=caches)
    if not out_tokens:
        return Tensor(jnp.zeros((B, 0), jnp.int32))
    return Tensor(jnp.asarray(np.stack(out_tokens, axis=1).astype(np.int32)))
