"""paddle.static parity (/root/reference/python/paddle/static/__init__.py:
Program/Executor/data/program_guard/save+load_inference_model surface).

TPU-native collapse of the reference's Program->IR->Executor stack
(static.Executor -> fluid C++ StandaloneExecutor): a Program is a *lazy op
list* captured at the single eager-dispatch chokepoint (ops.dispatch.apply).
Under ``paddle.enable_static()`` every op records (pure_fn, inputs, outputs)
with abstract ShapeDtypeStruct values instead of executing; ``Executor.run``
replays the list as ONE pure function and hands it to ``jax.jit`` — the
whole Program becomes a single XLA computation (the reference needs a whole
IR + pass + scheduler stack for this; XLA is that stack here).

Training: ``optimizer.minimize(loss)`` marks the program; Executor.run
computes grads of the replay with ``jax.grad`` and applies the framework
optimizer's own update eagerly.
"""
from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.dtype import to_jax_dtype
from ..tensor.tensor import Tensor

__all__ = [
    "Program", "program_guard", "default_main_program", "default_startup_program",
    "data", "Executor", "scope_guard", "global_scope", "name_scope",
    "save_inference_model", "load_inference_model", "InputSpec", "Variable",
    "cpu_places", "cuda_places", "xpu_places", "device_guard",
    "BuildStrategy", "CompiledProgram", "ExponentialMovingAverage",
    "create_global_var", "create_parameter", "gradients", "append_backward",
    "accuracy", "auc", "Print", "save", "load", "load_program_state",
    "set_program_state", "serialize_program", "serialize_persistables",
    "deserialize_persistables", "load_from_file", "save_to_file",
    "normalize_program", "WeightNormParamAttr",
    "PassBase", "PassManager", "DeadCodeEliminationPass",
    "CommonSubexpressionEliminationPass", "ConstantFoldingPass",
    "print_program", "program_to_str",
]

from ..jit.api import InputSpec  # noqa: E402  (shared spec type)
from .passes import (  # noqa: E402
    CommonSubexpressionEliminationPass,
    ConstantFoldingPass,
    DeadCodeEliminationPass,
    PassBase,
    PassManager,
    print_program,
    program_to_str,
)

Variable = Tensor  # static-graph "Variable" is the same symbolic Tensor


def __getattr__(name):
    # lazy: static.nn pulls in nn.functional + vision; avoid import cycles at
    # paddle_tpu package init time
    if name == "nn":
        import importlib

        mod = importlib.import_module(__name__ + ".nn")
        globals()["nn"] = mod
        return mod
    raise AttributeError(name)


class Program:
    """A captured op list + feed/fetch bookkeeping (parity:
    python/paddle/base/framework.py Program; block structure collapsed —
    XLA control flow ops don't need sub-blocks)."""

    _ids = itertools.count()

    def __init__(self):
        self.id = next(Program._ids)
        self.ops: List[tuple] = []  # (fn, input_tensors, output_tensors, name)
        self.feeds: List[Tensor] = []
        self._loss: Optional[Tensor] = None
        self._optimizer = None
        self.random_seed = 0

    # -- introspection parity helpers
    def global_block(self):
        return self

    def all_parameters(self):
        seen, out = set(), []
        for _, ins, _, _ in self.ops:
            for t in ins:
                if getattr(t, "is_parameter", False) and id(t) not in seen:
                    seen.add(id(t))
                    out.append(t)
        return out

    def clone(self, for_test=False):
        p = Program()
        p.ops = list(self.ops)
        p.feeds = list(self.feeds)
        return p

    def __str__(self):
        from .passes import program_to_str

        return program_to_str(self)

    def __repr__(self):
        return f"Program(id={self.id}, ops={len(self.ops)}, feeds={len(self.feeds)})"


_default_main = Program()
_default_startup = Program()
_prog_stack: List[Program] = []


def default_main_program() -> Program:
    return _prog_stack[-1] if _prog_stack else _default_main


def default_startup_program() -> Program:
    return _default_startup


class program_guard:
    def __init__(self, main_program: Program, startup_program: Optional[Program] = None):
        self.main = main_program
        self.startup = startup_program

    def __enter__(self):
        _prog_stack.append(self.main)
        return self.main

    def __exit__(self, *exc):
        _prog_stack.pop()
        return False


# ------------------------------------------------------------- capture hooks
def _static_enabled() -> bool:
    import paddle_tpu

    return not paddle_tpu.in_dynamic_mode()


def _capture(fn, inputs, op_name, n_outs_hint=1):
    """Record one op into the current program; return symbolic outputs."""
    prog = default_main_program()
    metas = [v._value if isinstance(v._value, jax.ShapeDtypeStruct)
             else jax.ShapeDtypeStruct(jnp.shape(v._value), jnp.result_type(v._value))
             for v in inputs]
    out = jax.eval_shape(fn, *metas)
    multi = isinstance(out, (tuple, list))
    outs = list(out) if multi else [out]
    out_tensors = [Tensor(o, stop_gradient=all(t.stop_gradient for t in inputs))
                   for o in outs]
    prog.ops.append((fn, list(inputs), out_tensors, op_name))
    return (out_tensors if isinstance(out, list) else tuple(out_tensors)) if multi else out_tensors[0]


def data(name: str, shape, dtype="float32", lod_level=0) -> Tensor:
    """Feed placeholder (parity: static.data). Dim None/-1 -> batch dim;
    materialized per-feed at run time (bucketed jit per concrete shape)."""
    shape = [s if (s is not None and s != -1) else -1 for s in shape]
    abstract = jax.ShapeDtypeStruct(tuple(1 if s == -1 else s for s in shape),
                                    to_jax_dtype(dtype))
    t = Tensor(abstract, stop_gradient=True, name=name)
    default_main_program().feeds.append(t)
    return t


# ------------------------------------------------------------------ executor
class Executor:
    """Replays a Program as one jitted pure function (parity:
    static.Executor over StandaloneExecutor,
    /root/reference/paddle/fluid/framework/new_executor/standalone_executor.h)."""

    def __init__(self, place=None):
        self.place = place
        self._cache: Dict[tuple, Any] = {}
        self._analysis: Dict[tuple, Any] = {}

    def _analyze(self, program: Program):
        """(const tensors, placeholder tensors) the op list reads — computed
        once per (program, op-count), not per step."""
        key = (program.id, len(program.ops))
        hit = self._analysis.get(key)
        if hit is not None:
            return hit
        produced = set()
        for _, _, outs, _ in program.ops:
            produced.update(id(o) for o in outs)
        placeholder_ids = {id(t): t for t in program.feeds}
        const_ts, used_placeholders, seen = [], [], set()
        for _, ins, _, _ in program.ops:
            for t in ins:
                if id(t) in produced or id(t) in seen:
                    continue
                seen.add(id(t))
                if id(t) in placeholder_ids:
                    used_placeholders.append(t)
                elif not isinstance(t._value, jax.ShapeDtypeStruct):
                    const_ts.append(t)
        self._analysis[key] = (const_ts, used_placeholders)
        return const_ts, used_placeholders

    def run(self, program: Optional[Program] = None, feed: Optional[dict] = None,
            fetch_list: Optional[Sequence] = None, return_numpy: bool = True):
        program = program or default_main_program()
        feed = feed or {}
        fetch_list = list(fetch_list or [])
        if program is _default_startup or not program.ops:
            return []  # startup collapses: params are initialized eagerly

        known = {t.name for t in program.feeds}
        unknown = set(feed) - known
        if unknown:
            raise KeyError(
                f"feed names {sorted(unknown)} match no placeholder in this "
                f"program (placeholders: {sorted(known)})")
        feed_ts = [t for t in program.feeds if t.name in feed]
        feed_vals = [jnp.asarray(feed[t.name]) for t in feed_ts]
        feed_ids = {id(t) for t in feed_ts}

        const_ts, used_placeholders = self._analyze(program)
        missing = [t.name for t in used_placeholders if id(t) not in feed_ids]
        if missing:
            raise KeyError(f"placeholders {missing} are read by the program "
                           "but not fed")
        if program._loss is not None and program._optimizer is not None:
            return self._run_train(program, feed_ts, feed_vals, const_ts, fetch_list,
                                   return_numpy)

        key = (program.id, len(program.ops), tuple(t.name for t in feed_ts),
               tuple(v.shape for v in feed_vals), tuple(id(t) for t in fetch_list))
        compiled = self._cache.get(key)
        if compiled is None:
            fetch_ids = [id(t) for t in fetch_list]

            fetch_fallback = {id(t): t for t in fetch_list}

            def replay(feed_in, const_in):
                env = {id(t): v for t, v in zip(feed_ts, feed_in)}
                env.update({id(t): v for t, v in zip(const_ts, const_in)})
                for fn, ins, outs, _ in program.ops:
                    vals = [env[id(t)] if id(t) in env else t._value for t in ins]
                    res = fn(*vals)
                    rs = list(res) if isinstance(res, (tuple, list)) else [res]
                    for o, r in zip(outs, rs):
                        env[id(o)] = r
                # a fetch target may have been constant-folded out of the op
                # list (static.passes): its value is concrete on the tensor
                return [env[i] if i in env else fetch_fallback[i]._value
                        for i in fetch_ids]

            compiled = jax.jit(replay)
            self._cache[key] = compiled
        outs = compiled(feed_vals, [t._value for t in const_ts])
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]

    def _run_train(self, program, feed_ts, feed_vals, const_ts, fetch_list,
                   return_numpy):
        """One train step: jitted loss+grads over the replay, then the
        framework optimizer's own eager update."""
        params = [t for t in const_ts if getattr(t, "is_parameter", False)
                  and not t.stop_gradient]
        param_ids = {id(t) for t in params}
        rest = [t for t in const_ts if id(t) not in param_ids]
        loss_t = program._loss
        fetch_ids = [id(t) for t in fetch_list]
        fetch_map = {id(t): t for t in fetch_list}

        key = (program.id, "train", len(program.ops), tuple(t.name for t in feed_ts),
               tuple(v.shape for v in feed_vals), tuple(fetch_ids))
        compiled = self._cache.get(key)
        if compiled is None:
            def loss_and_fetch(param_in, feed_in, rest_in):
                env = {id(t): v for t, v in zip(params, param_in)}
                env.update({id(t): v for t, v in zip(feed_ts, feed_in)})
                env.update({id(t): v for t, v in zip(rest, rest_in)})
                for fn, ins, outs, _ in program.ops:
                    vals = [env[id(t)] if id(t) in env else t._value for t in ins]
                    res = fn(*vals)
                    rs = list(res) if isinstance(res, (tuple, list)) else [res]
                    for o, r in zip(outs, rs):
                        env[id(o)] = r
                loss = env[id(loss_t)]
                return loss, [env[i] if i in env else fetch_map[i]._value
                              for i in fetch_ids]

            compiled = jax.jit(jax.value_and_grad(loss_and_fetch, has_aux=True))
            self._cache[key] = compiled
        (loss, fetched), grads = compiled([t._value for t in params], feed_vals,
                                          [t._value for t in rest])
        for p, g in zip(params, grads):
            p.grad = Tensor(g, stop_gradient=True)
        program._optimizer.step()
        program._optimizer.clear_grad()
        if return_numpy:
            return [np.asarray(o) for o in fetched]
        return [Tensor(o) for o in fetched]


# ------------------------------------------------------------------- scopes
class _Scope:
    def __init__(self):
        self.vars = {}

    def var(self, name):
        return self.vars.setdefault(name, None)

    def find_var(self, name):
        return self.vars.get(name)


_global_scope = _Scope()


def global_scope():
    return _global_scope


class scope_guard:
    def __init__(self, scope):
        self.scope = scope

    def __enter__(self):
        return self.scope

    def __exit__(self, *exc):
        return False


class name_scope:
    def __init__(self, prefix=None):
        self.prefix = prefix

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def cpu_places(device_count=None):
    return ["cpu"] * (device_count or 1)


def cuda_places(device_ids=None):
    return []


def xpu_places(device_ids=None):
    return []


class device_guard:
    def __init__(self, device=None):
        self.device = device

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


# ------------------------------------------------- inference model save/load
def save_inference_model(path_prefix: str, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Serialize the captured program as a jitted StableHLO artifact
    (parity: static.save_inference_model -> __model__ + params; here the
    jit.save path owns serialization)."""
    from ..jit.api import save as jit_save
    from ..jit.api import to_static

    program = program or default_main_program()
    feed_vars = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    fetch_vars = fetch_vars if isinstance(fetch_vars, (list, tuple)) else [fetch_vars]
    feed_ids = [id(t) for t in feed_vars]
    fetch_ids = [id(t) for t in fetch_vars]
    produced = set()
    for _, _, outs, _ in program.ops:
        produced.update(id(o) for o in outs)
    consts = {}
    for _, ins, _, _ in program.ops:
        for t in ins:
            if id(t) not in produced and id(t) not in feed_ids and \
                    not isinstance(t._value, jax.ShapeDtypeStruct):
                consts[id(t)] = t._value

    def fn(*feed_in):
        env = dict(zip(feed_ids, [t._value for t in feed_in]))
        env.update(consts)
        for f, ins, outs, _ in program.ops:
            vals = [env[id(t)] if id(t) in env else t._value for t in ins]
            res = f(*vals)
            rs = list(res) if isinstance(res, (tuple, list)) else [res]
            for o, r in zip(outs, rs):
                env[id(o)] = r
        outs_ = [Tensor(env[i]) for i in fetch_ids]
        return outs_ if len(outs_) > 1 else outs_[0]

    example = [Tensor(jnp.zeros(t._value.shape, t._value.dtype)) for t in feed_vars]
    static_fn = to_static(fn)
    static_fn(*example)
    jit_save(static_fn, path_prefix, input_spec=example)


def load_inference_model(path_prefix: str, executor=None, **kwargs):
    from ..jit.api import load as jit_load

    loaded = jit_load(path_prefix)
    return [loaded, [], []]


# ------------------------------------------------------------- nn shims
class _StaticNN:
    """static.nn.* op builders (fc/conv are Layer calls under capture)."""

    @staticmethod
    def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
           activation=None, name=None):
        from ..nn import Linear

        lin = Linear(x.shape[-1], size)
        out = lin(x)
        if activation:
            import paddle_tpu.nn.functional as F

            out = getattr(F, activation)(out)
        return out

    @staticmethod
    def batch_norm(input, **kwargs):  # noqa: A002
        from ..nn import BatchNorm1D

        bn = BatchNorm1D(input.shape[-1])
        return bn(input)


nn = _StaticNN()


# -------------------------------------------------- legacy static surface
class BuildStrategy:
    """Knob bag (XLA owns fusion/memory decisions; kept for API parity)."""

    def __init__(self):
        self.enable_inplace = True
        self.memory_optimize = True
        self.fuse_elewise_add_act_ops = True
        self.build_cinn_pass = False


class CompiledProgram:
    def __init__(self, program, build_strategy=None):
        self.program = program
        self.build_strategy = build_strategy or BuildStrategy()

    def __getattr__(self, name):
        return getattr(self.program, name)


class IpuStrategy:
    def __init__(self):
        raise NotImplementedError("IPU backend is not part of the TPU build")


def IpuCompiledProgram(*a, **k):
    raise NotImplementedError("IPU backend is not part of the TPU build")


class ipu_shard_guard:
    def __init__(self, *a, **k):
        raise NotImplementedError("IPU backend is not part of the TPU build")


class WeightNormParamAttr:
    def __init__(self, dim=None, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=False,
                 need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer


class ExponentialMovingAverage:
    """EMA of parameters with apply/restore guards (parity:
    static.ExponentialMovingAverage)."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self.decay = decay
        self._ema = {}
        self._backup = {}
        self._params = []

    def update(self, parameters=None):
        import numpy as np

        params = parameters or self._params
        if parameters is not None:
            self._params = list(parameters)
        for p in self._params:
            cur = np.asarray(p._value, np.float32)
            prev = self._ema.get(id(p))
            self._ema[id(p)] = cur if prev is None else \
                self.decay * prev + (1 - self.decay) * cur

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def guard():
            import jax.numpy as jnp

            for p in self._params:
                self._backup[id(p)] = p._value
                if id(p) in self._ema:
                    p._value = jnp.asarray(self._ema[id(p)], p._value.dtype)
            try:
                yield
            finally:
                if need_restore:
                    self.restore()

        return guard()

    def restore(self, executor=None):
        for p in self._params:
            if id(p) in self._backup:
                p._value = self._backup.pop(id(p))


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False,
                      name=None):
    import jax.numpy as jnp

    from ..framework.dtype import to_jax_dtype

    t = Tensor(jnp.full(shape, value, to_jax_dtype(dtype)), name=name)
    t.persistable = persistable
    return t


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..tensor.extras import create_parameter as _cp

    return _cp(shape, dtype, name, attr, is_bias, default_initializer)


def gradients(targets, inputs, target_gradients=None, no_grad_set=None, name=None):
    """Static-graph gradient op insertion collapses to taped autograd."""
    from ..autograd.tape import grad as _grad

    outs = targets if isinstance(targets, (list, tuple)) else [targets]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    return _grad(list(outs), list(ins), grad_outputs=target_gradients,
                 retain_graph=True, allow_unused=True)


def append_backward(loss, parameter_list=None, no_grad_set=None, callbacks=None,
                    checkpoints=None):
    """parity: static append_backward — marks the program for training via
    optimizer.minimize; returns (param, grad-placeholder) pairs."""
    prog = default_main_program()
    prog._loss = loss
    params = parameter_list or prog.all_parameters()
    return [(p, None) for p in params]


def accuracy(input, label, k=1, correct=None, total=None, name=None):  # noqa: A002
    from ..metric import accuracy as _acc

    return _acc(input, label, k=k)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1, slide_steps=1):  # noqa: A002
    from ..metric import Auc

    m = Auc(num_thresholds=min(num_thresholds, 4095))
    import numpy as np

    preds = np.asarray(input._value)
    if preds.ndim == 1 or preds.shape[-1] == 1:
        preds = np.stack([1 - preds.reshape(-1), preds.reshape(-1)], axis=1)
    m.update(preds, np.asarray(label._value))
    return Tensor(jnp.asarray(m.accumulate(), jnp.float32)), None, None


def ctr_metric_bundle(input, label, ins_tag_weight=None):  # noqa: A002
    raise NotImplementedError("parameter-server CTR metrics are out of the TPU build")


def Print(input, first_n=-1, message=None, summarize=20, print_tensor_name=True,  # noqa: A002
          print_tensor_type=True, print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=False, print_phase="both"):
    """Host-callback print (identity op)."""
    import jax

    def f(v):
        jax.debug.print((message or "") + "{x}", x=v)
        return v

    from ..ops.dispatch import apply

    return apply(f, input, op_name="Print")


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """parity: static.py_func — host Python inside the graph. Shares the
    ``static.nn.py_func`` implementation (jax.pure_callback + custom_vjp for
    the backward hook)."""
    from .nn.control_flow import py_func as _py_func

    return _py_func(func, x, out, backward_func=backward_func,
                    skip_vars_in_backward_input=skip_vars_in_backward_input)


# ------------------------------------------------ program state save/load
def save(program, model_path, protocol=4, **configs):
    """Save all parameters reachable from the program (npz)."""
    import numpy as np

    params = program.all_parameters()
    arrays = {p.name or f"param_{i}": np.asarray(p._value)
              for i, p in enumerate(params)}
    np.savez(model_path + ".pdparams.npz", **arrays)


def load(program, model_path, executor=None, var_list=None):
    import numpy as np

    import jax.numpy as jnp_

    arrays = dict(np.load(model_path + ".pdparams.npz"))
    by_name = {p.name: p for p in program.all_parameters()}
    for name, arr in arrays.items():
        if name in by_name:
            by_name[name]._value = jnp_.asarray(arr, by_name[name]._value.dtype)


def save_inference_model_pir(*a, **k):
    return save_inference_model(*a, **k)


def load_program_state(model_path, var_list=None):
    import numpy as np

    return dict(np.load(model_path + ".pdparams.npz"))


def set_program_state(program, state_dict):
    import jax.numpy as jnp_

    by_name = {p.name: p for p in program.all_parameters()}
    for name, arr in state_dict.items():
        if name in by_name:
            by_name[name]._value = jnp_.asarray(arr, by_name[name]._value.dtype)


def serialize_program(feed_vars, fetch_vars, **kwargs):
    import json as _json

    prog = default_main_program()
    return _json.dumps({"ops": [name for _, _, _, name in prog.ops]}).encode()


def deserialize_program(data):
    raise NotImplementedError(
        "programs are Python-captured op lists; use jit.save/load artifacts "
        "for portable serialization")


def serialize_persistables(feed_vars, fetch_vars, executor=None, **kwargs):
    import io as _io

    import numpy as np

    prog = default_main_program()
    bio = _io.BytesIO()
    np.savez(bio, **{p.name or f"p{i}": np.asarray(p._value)
                     for i, p in enumerate(prog.all_parameters())})
    return bio.getvalue()


def deserialize_persistables(program, data, executor=None):
    import io as _io

    import numpy as np

    import jax.numpy as jnp_

    arrays = dict(np.load(_io.BytesIO(data)))
    by_name = {p.name: p for p in program.all_parameters()}
    for name, arr in arrays.items():
        if name in by_name:
            by_name[name]._value = jnp_.asarray(arr, by_name[name]._value.dtype)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    return program


def default_startup_program_guard(*a, **k):
    raise NotImplementedError


def global_scope_guard(*a, **k):
    raise NotImplementedError


# nn alias for static.nn already defined above as `nn = _StaticNN()`


def set_ipu_shard(*a, **k):
    raise NotImplementedError("IPU backend is not part of the TPU build")
