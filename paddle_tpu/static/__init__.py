"""paddle.static parity (/root/reference/python/paddle/static/__init__.py:
Program/Executor/data/program_guard/save+load_inference_model surface).

TPU-native collapse of the reference's Program->IR->Executor stack
(static.Executor -> fluid C++ StandaloneExecutor): a Program is a *lazy op
list* captured at the single eager-dispatch chokepoint (ops.dispatch.apply).
Under ``paddle.enable_static()`` every op records (pure_fn, inputs, outputs)
with abstract ShapeDtypeStruct values instead of executing; ``Executor.run``
replays the list as ONE pure function and hands it to ``jax.jit`` — the
whole Program becomes a single XLA computation (the reference needs a whole
IR + pass + scheduler stack for this; XLA is that stack here).

Training: ``optimizer.minimize(loss)`` marks the program; Executor.run
computes grads of the replay with ``jax.grad`` and applies the framework
optimizer's own update eagerly.
"""
from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.dtype import to_jax_dtype
from ..tensor.tensor import Tensor

__all__ = [
    "Program", "program_guard", "default_main_program", "default_startup_program",
    "data", "Executor", "scope_guard", "global_scope", "name_scope",
    "save_inference_model", "load_inference_model", "InputSpec", "Variable",
    "cpu_places", "cuda_places", "xpu_places", "device_guard",
]

from ..jit.api import InputSpec  # noqa: E402  (shared spec type)

Variable = Tensor  # static-graph "Variable" is the same symbolic Tensor


class Program:
    """A captured op list + feed/fetch bookkeeping (parity:
    python/paddle/base/framework.py Program; block structure collapsed —
    XLA control flow ops don't need sub-blocks)."""

    _ids = itertools.count()

    def __init__(self):
        self.id = next(Program._ids)
        self.ops: List[tuple] = []  # (fn, input_tensors, output_tensors, name)
        self.feeds: List[Tensor] = []
        self._loss: Optional[Tensor] = None
        self._optimizer = None
        self.random_seed = 0

    # -- introspection parity helpers
    def global_block(self):
        return self

    def all_parameters(self):
        seen, out = set(), []
        for _, ins, _, _ in self.ops:
            for t in ins:
                if getattr(t, "is_parameter", False) and id(t) not in seen:
                    seen.add(id(t))
                    out.append(t)
        return out

    def clone(self, for_test=False):
        p = Program()
        p.ops = list(self.ops)
        p.feeds = list(self.feeds)
        return p

    def __repr__(self):
        return f"Program(id={self.id}, ops={len(self.ops)}, feeds={len(self.feeds)})"


_default_main = Program()
_default_startup = Program()
_prog_stack: List[Program] = []


def default_main_program() -> Program:
    return _prog_stack[-1] if _prog_stack else _default_main


def default_startup_program() -> Program:
    return _default_startup


class program_guard:
    def __init__(self, main_program: Program, startup_program: Optional[Program] = None):
        self.main = main_program
        self.startup = startup_program

    def __enter__(self):
        _prog_stack.append(self.main)
        return self.main

    def __exit__(self, *exc):
        _prog_stack.pop()
        return False


# ------------------------------------------------------------- capture hooks
def _static_enabled() -> bool:
    import paddle_tpu

    return not paddle_tpu.in_dynamic_mode()


def _capture(fn, inputs, op_name, n_outs_hint=1):
    """Record one op into the current program; return symbolic outputs."""
    prog = default_main_program()
    metas = [v._value if isinstance(v._value, jax.ShapeDtypeStruct)
             else jax.ShapeDtypeStruct(jnp.shape(v._value), jnp.result_type(v._value))
             for v in inputs]
    out = jax.eval_shape(fn, *metas)
    multi = isinstance(out, (tuple, list))
    outs = list(out) if multi else [out]
    out_tensors = [Tensor(o, stop_gradient=all(t.stop_gradient for t in inputs))
                   for o in outs]
    prog.ops.append((fn, list(inputs), out_tensors, op_name))
    return (out_tensors if isinstance(out, list) else tuple(out_tensors)) if multi else out_tensors[0]


def data(name: str, shape, dtype="float32", lod_level=0) -> Tensor:
    """Feed placeholder (parity: static.data). Dim None/-1 -> batch dim;
    materialized per-feed at run time (bucketed jit per concrete shape)."""
    shape = [s if (s is not None and s != -1) else -1 for s in shape]
    abstract = jax.ShapeDtypeStruct(tuple(1 if s == -1 else s for s in shape),
                                    to_jax_dtype(dtype))
    t = Tensor(abstract, stop_gradient=True, name=name)
    default_main_program().feeds.append(t)
    return t


# ------------------------------------------------------------------ executor
class Executor:
    """Replays a Program as one jitted pure function (parity:
    static.Executor over StandaloneExecutor,
    /root/reference/paddle/fluid/framework/new_executor/standalone_executor.h)."""

    def __init__(self, place=None):
        self.place = place
        self._cache: Dict[tuple, Any] = {}
        self._analysis: Dict[tuple, Any] = {}

    def _analyze(self, program: Program):
        """(const tensors, placeholder tensors) the op list reads — computed
        once per (program, op-count), not per step."""
        key = (program.id, len(program.ops))
        hit = self._analysis.get(key)
        if hit is not None:
            return hit
        produced = set()
        for _, _, outs, _ in program.ops:
            produced.update(id(o) for o in outs)
        placeholder_ids = {id(t): t for t in program.feeds}
        const_ts, used_placeholders, seen = [], [], set()
        for _, ins, _, _ in program.ops:
            for t in ins:
                if id(t) in produced or id(t) in seen:
                    continue
                seen.add(id(t))
                if id(t) in placeholder_ids:
                    used_placeholders.append(t)
                elif not isinstance(t._value, jax.ShapeDtypeStruct):
                    const_ts.append(t)
        self._analysis[key] = (const_ts, used_placeholders)
        return const_ts, used_placeholders

    def run(self, program: Optional[Program] = None, feed: Optional[dict] = None,
            fetch_list: Optional[Sequence] = None, return_numpy: bool = True):
        program = program or default_main_program()
        feed = feed or {}
        fetch_list = list(fetch_list or [])
        if program is _default_startup or not program.ops:
            return []  # startup collapses: params are initialized eagerly

        known = {t.name for t in program.feeds}
        unknown = set(feed) - known
        if unknown:
            raise KeyError(
                f"feed names {sorted(unknown)} match no placeholder in this "
                f"program (placeholders: {sorted(known)})")
        feed_ts = [t for t in program.feeds if t.name in feed]
        feed_vals = [jnp.asarray(feed[t.name]) for t in feed_ts]
        feed_ids = {id(t) for t in feed_ts}

        const_ts, used_placeholders = self._analyze(program)
        missing = [t.name for t in used_placeholders if id(t) not in feed_ids]
        if missing:
            raise KeyError(f"placeholders {missing} are read by the program "
                           "but not fed")
        if program._loss is not None and program._optimizer is not None:
            return self._run_train(program, feed_ts, feed_vals, const_ts, fetch_list,
                                   return_numpy)

        key = (program.id, len(program.ops), tuple(t.name for t in feed_ts),
               tuple(v.shape for v in feed_vals), tuple(id(t) for t in fetch_list))
        compiled = self._cache.get(key)
        if compiled is None:
            fetch_ids = [id(t) for t in fetch_list]

            def replay(feed_in, const_in):
                env = {id(t): v for t, v in zip(feed_ts, feed_in)}
                env.update({id(t): v for t, v in zip(const_ts, const_in)})
                for fn, ins, outs, _ in program.ops:
                    vals = [env[id(t)] if id(t) in env else t._value for t in ins]
                    res = fn(*vals)
                    rs = list(res) if isinstance(res, (tuple, list)) else [res]
                    for o, r in zip(outs, rs):
                        env[id(o)] = r
                return [env[i] for i in fetch_ids]

            compiled = jax.jit(replay)
            self._cache[key] = compiled
        outs = compiled(feed_vals, [t._value for t in const_ts])
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]

    def _run_train(self, program, feed_ts, feed_vals, const_ts, fetch_list,
                   return_numpy):
        """One train step: jitted loss+grads over the replay, then the
        framework optimizer's own eager update."""
        params = [t for t in const_ts if getattr(t, "is_parameter", False)
                  and not t.stop_gradient]
        param_ids = {id(t) for t in params}
        rest = [t for t in const_ts if id(t) not in param_ids]
        loss_t = program._loss
        fetch_ids = [id(t) for t in fetch_list]

        key = (program.id, "train", len(program.ops), tuple(t.name for t in feed_ts),
               tuple(v.shape for v in feed_vals), tuple(fetch_ids))
        compiled = self._cache.get(key)
        if compiled is None:
            def loss_and_fetch(param_in, feed_in, rest_in):
                env = {id(t): v for t, v in zip(params, param_in)}
                env.update({id(t): v for t, v in zip(feed_ts, feed_in)})
                env.update({id(t): v for t, v in zip(rest, rest_in)})
                for fn, ins, outs, _ in program.ops:
                    vals = [env[id(t)] if id(t) in env else t._value for t in ins]
                    res = fn(*vals)
                    rs = list(res) if isinstance(res, (tuple, list)) else [res]
                    for o, r in zip(outs, rs):
                        env[id(o)] = r
                loss = env[id(loss_t)]
                return loss, [env[i] for i in fetch_ids]

            compiled = jax.jit(jax.value_and_grad(loss_and_fetch, has_aux=True))
            self._cache[key] = compiled
        (loss, fetched), grads = compiled([t._value for t in params], feed_vals,
                                          [t._value for t in rest])
        for p, g in zip(params, grads):
            p.grad = Tensor(g, stop_gradient=True)
        program._optimizer.step()
        program._optimizer.clear_grad()
        if return_numpy:
            return [np.asarray(o) for o in fetched]
        return [Tensor(o) for o in fetched]


# ------------------------------------------------------------------- scopes
class _Scope:
    def __init__(self):
        self.vars = {}

    def var(self, name):
        return self.vars.setdefault(name, None)

    def find_var(self, name):
        return self.vars.get(name)


_global_scope = _Scope()


def global_scope():
    return _global_scope


class scope_guard:
    def __init__(self, scope):
        self.scope = scope

    def __enter__(self):
        return self.scope

    def __exit__(self, *exc):
        return False


class name_scope:
    def __init__(self, prefix=None):
        self.prefix = prefix

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def cpu_places(device_count=None):
    return ["cpu"] * (device_count or 1)


def cuda_places(device_ids=None):
    return []


def xpu_places(device_ids=None):
    return []


class device_guard:
    def __init__(self, device=None):
        self.device = device

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


# ------------------------------------------------- inference model save/load
def save_inference_model(path_prefix: str, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Serialize the captured program as a jitted StableHLO artifact
    (parity: static.save_inference_model -> __model__ + params; here the
    jit.save path owns serialization)."""
    from ..jit.api import save as jit_save
    from ..jit.api import to_static

    program = program or default_main_program()
    feed_vars = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    fetch_vars = fetch_vars if isinstance(fetch_vars, (list, tuple)) else [fetch_vars]
    feed_ids = [id(t) for t in feed_vars]
    fetch_ids = [id(t) for t in fetch_vars]
    produced = set()
    for _, _, outs, _ in program.ops:
        produced.update(id(o) for o in outs)
    consts = {}
    for _, ins, _, _ in program.ops:
        for t in ins:
            if id(t) not in produced and id(t) not in feed_ids and \
                    not isinstance(t._value, jax.ShapeDtypeStruct):
                consts[id(t)] = t._value

    def fn(*feed_in):
        env = dict(zip(feed_ids, [t._value for t in feed_in]))
        env.update(consts)
        for f, ins, outs, _ in program.ops:
            vals = [env[id(t)] if id(t) in env else t._value for t in ins]
            res = f(*vals)
            rs = list(res) if isinstance(res, (tuple, list)) else [res]
            for o, r in zip(outs, rs):
                env[id(o)] = r
        outs_ = [Tensor(env[i]) for i in fetch_ids]
        return outs_ if len(outs_) > 1 else outs_[0]

    example = [Tensor(jnp.zeros(t._value.shape, t._value.dtype)) for t in feed_vars]
    static_fn = to_static(fn)
    static_fn(*example)
    jit_save(static_fn, path_prefix, input_spec=example)


def load_inference_model(path_prefix: str, executor=None, **kwargs):
    from ..jit.api import load as jit_load

    loaded = jit_load(path_prefix)
    return [loaded, [], []]


# ------------------------------------------------------------- nn shims
class _StaticNN:
    """static.nn.* op builders (fc/conv are Layer calls under capture)."""

    @staticmethod
    def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
           activation=None, name=None):
        from ..nn import Linear

        lin = Linear(x.shape[-1], size)
        out = lin(x)
        if activation:
            import paddle_tpu.nn.functional as F

            out = getattr(F, activation)(out)
        return out

    @staticmethod
    def batch_norm(input, **kwargs):  # noqa: A002
        from ..nn import BatchNorm1D

        bn = BatchNorm1D(input.shape[-1])
        return bn(input)


nn = _StaticNN()
