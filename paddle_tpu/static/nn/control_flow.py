"""Static control flow (parity:
/root/reference/python/paddle/static/nn/control_flow.py — cond, while_loop,
case, switch_case; /root/reference/python/paddle/static/nn/static_pylayer.py;
/root/reference/python/paddle/base/layers/layer_function_generator.py py_func).

TPU-native lowering: the reference builds conditional sub-blocks in the
ProgramDesc and runs them through interpreter control-flow instructions
(paddle/fluid/pir/dialect/operator/ir/control_flow_op.h). Here the same API
lowers to ``lax.cond`` / ``lax.while_loop`` — XLA's native control flow —
in whichever execution world the call happens:

1. eager with a concrete predicate → plain Python branch (constant fold);
2. inside a jit/to_static trace (predicate is a tracer) → ``lax.cond`` with
   branch closures traced in place;
3. inside a captured ``static.Program`` (predicate is symbolic) → each branch
   is traced into a sub-program; ONE program op is recorded whose pure fn
   replays the branches under ``lax.cond``, with every symbolic tensor the
   branches capture passed as an explicit operand.
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ...tensor.tensor import Tensor

__all__ = ["cond", "while_loop", "case", "switch_case", "static_pylayer", "py_func"]


# ------------------------------------------------------------- tree helpers
def _flatten(out) -> Tuple[List[Tensor], Any]:
    """Flatten a nest of Tensors (tuple/list/dict) into leaves + treedef."""
    leaves, treedef = jax.tree_util.tree_flatten(
        out, is_leaf=lambda x: isinstance(x, Tensor))
    ts = [x if isinstance(x, Tensor) else Tensor(jnp.asarray(x)) for x in leaves]
    return ts, treedef


def _unflatten(treedef, tensors: Sequence[Tensor]):
    return jax.tree_util.tree_unflatten(treedef, list(tensors))


def _is_sym(t: Tensor) -> bool:
    return isinstance(t._value, jax.ShapeDtypeStruct)


def _is_tracer(v) -> bool:
    return isinstance(v, jax.core.Tracer)


def _as_tensor(x) -> Tensor:
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


# ------------------------------------------------- sub-program branch tracing
class _Branch:
    """One branch traced into its own sub-Program (capture mode)."""

    def __init__(self, fn: Callable, args: Sequence[Tensor] = ()):
        from .. import Program, program_guard

        self.args = list(args)
        sub = Program()
        with program_guard(sub):
            out = fn(*args)
        self.ops = list(sub.ops)
        self.out_ts, self.treedef = _flatten(out)
        produced = set()
        for _, _, outs, _ in self.ops:
            produced.update(id(o) for o in outs)
        arg_ids = {id(a) for a in self.args}
        # externals: symbolic tensors read (or returned) that this branch
        # neither produced nor received as a loop/branch argument
        self.externals: List[Tensor] = []
        seen = set()

        def note(t):
            if (id(t) not in produced and id(t) not in arg_ids
                    and id(t) not in seen and _is_sym(t)):
                seen.add(id(t))
                self.externals.append(t)

        for _, ins, _, _ in self.ops:
            for t in ins:
                note(t)
        for t in self.out_ts:
            note(t)

    def replay(self, env: dict):
        """Execute the recorded ops over raw values in ``env`` (id → value);
        returns the branch's raw outputs."""
        env = dict(env)
        for fn, ins, outs, _ in self.ops:
            vals = [env[id(t)] if id(t) in env else t._value for t in ins]
            res = fn(*vals)
            rs = list(res) if isinstance(res, (tuple, list)) else [res]
            for o, r in zip(outs, rs):
                env[id(o)] = r
        return tuple(env[id(t)] if id(t) in env else t._value for t in self.out_ts)


def _merge_externals(*branches: _Branch) -> List[Tensor]:
    ext, seen = [], set()
    for b in branches:
        for t in b.externals:
            if id(t) not in seen:
                seen.add(id(t))
                ext.append(t)
    return ext


def _check_same_structure(a: _Branch, b: _Branch, what: str):
    if a.treedef != b.treedef or len(a.out_ts) != len(b.out_ts):
        raise ValueError(f"{what}: branch outputs must have identical structure "
                         f"({a.treedef} vs {b.treedef})")
    for x, y in zip(a.out_ts, b.out_ts):
        sx = tuple(jnp.shape(x._value)) if not _is_sym(x) else tuple(x._value.shape)
        sy = tuple(jnp.shape(y._value)) if not _is_sym(y) else tuple(y._value.shape)
        if sx != sy:
            raise ValueError(f"{what}: branch output shapes differ: {sx} vs {sy}")


# --------------------------------------------------------------------- cond
def cond(pred, true_fn: Callable = None, false_fn: Callable = None, name=None,
         return_names=None):
    """parity: static/nn/control_flow.py cond — run ``true_fn()`` when pred
    else ``false_fn()``; both must return structurally identical nests."""
    from ...ops import dispatch

    pred_t = _as_tensor(pred)
    pv = pred_t._value

    # capture mode with a symbolic predicate → record one lax.cond op
    if dispatch._static_capture and _is_sym(pred_t):
        tb = _Branch(true_fn)
        fb = _Branch(false_fn)
        _check_same_structure(tb, fb, "cond")
        ext = _merge_externals(tb, fb)

        def cond_op(pred_val, *ext_vals):
            env = {id(t): v for t, v in zip(ext, ext_vals)}
            return lax.cond(jnp.reshape(pred_val, ()).astype(bool),
                            lambda: tb.replay(env), lambda: fb.replay(env))

        from .. import _capture

        out = _capture(cond_op, [pred_t, *ext], "cond")
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
        return _unflatten(tb.treedef, outs)

    # traced predicate inside jit/to_static → lax.cond in place
    if _is_tracer(pv):
        trees = {}

        def branch(fn, key):
            def run():
                ts, treedef = _flatten(fn())
                trees[key] = treedef
                return tuple(t._value for t in ts)

            return run

        out_vals = lax.cond(jnp.reshape(pv, ()).astype(bool),
                            branch(true_fn, "t"), branch(false_fn, "f"))
        if trees["t"] != trees["f"]:
            raise ValueError("cond: true_fn/false_fn must return the same "
                             f"structure ({trees['t']} vs {trees['f']})")
        return _unflatten(trees["t"], [Tensor(v) for v in out_vals])

    # concrete predicate → constant fold
    return true_fn() if bool(np.asarray(pv)) else false_fn()


# --------------------------------------------------------------- while_loop
def while_loop(cond_fn: Callable, body_fn: Callable, loop_vars, is_test=False,
               name=None, max_iters: Optional[int] = None):
    """parity: control_flow.py while_loop — iterate ``body`` while ``cond``;
    lowers to ``lax.while_loop`` (shapes must be loop-invariant, the XLA
    contract the reference's dynamic-shape LoD world doesn't have).

    ``max_iters`` (TPU extension): reverse-mode differentiation through an
    unbounded ``lax.while_loop`` is impossible (residual storage is unbounded
    — the reference's interpreter records the dynamic trip count instead,
    which XLA's static world cannot). Passing ``max_iters`` lowers to a
    fixed-length masked ``lax.scan`` — iterations after the condition goes
    False are identity — which XLA reverse-differentiates; required when
    training through the loop."""
    from ...ops import dispatch

    var_ts, treedef = _flatten(loop_vars)

    # capture mode: loop vars symbolic → record one lax.while_loop op
    if dispatch._static_capture and any(_is_sym(t) for t in var_ts):
        cb = _Branch(lambda *a: cond_fn(*_unflatten(treedef, a)), var_ts)
        bb = _Branch(lambda *a: body_fn(*_unflatten(treedef, a)), var_ts)
        if bb.treedef != treedef or len(bb.out_ts) != len(var_ts):
            raise ValueError("while_loop: body must return the same structure "
                             "as loop_vars")
        ext = _merge_externals(cb, bb)
        n = len(var_ts)

        def while_op(*vals):
            carry0, ext_vals = tuple(vals[:n]), vals[n:]
            env_ext = {id(t): v for t, v in zip(ext, ext_vals)}

            def c(carry):
                env = dict(env_ext)
                env.update({id(t): v for t, v in zip(var_ts, carry)})
                return jnp.reshape(cb.replay(env)[0], ()).astype(bool)

            def b(carry):
                env = dict(env_ext)
                env.update({id(t): v for t, v in zip(var_ts, carry)})
                return bb.replay(env)

            return _lower_while(c, b, carry0, max_iters)

        from .. import _capture

        out = _capture(while_op, [*var_ts, *ext], "while_loop")
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
        return _unflatten(treedef, outs)

    # traced loop vars → lax.while_loop in place
    if any(_is_tracer(t._value) for t in var_ts):
        def c(carry):
            r = cond_fn(*_unflatten(treedef, [Tensor(v) for v in carry]))
            return jnp.reshape(_as_tensor(r)._value, ()).astype(bool)

        def b(carry):
            out = body_fn(*_unflatten(treedef, [Tensor(v) for v in carry]))
            ts, td = _flatten(out)
            if td != treedef:
                raise ValueError("while_loop: body must return the same "
                                 "structure as loop_vars")
            return tuple(t._value for t in ts)

        out_vals = _lower_while(c, b, tuple(t._value for t in var_ts), max_iters)
        return _unflatten(treedef, [Tensor(v) for v in out_vals])

    # concrete eager → Python loop
    vars_now = _unflatten(treedef, var_ts)
    while bool(np.asarray(_as_tensor(cond_fn(*vars_now))._value)):
        out = body_fn(*vars_now)
        ts, td = _flatten(out)
        if td != treedef:
            raise ValueError("while_loop: body must return the same structure "
                             "as loop_vars")
        vars_now = _unflatten(td, ts)
    return vars_now


def _lower_while(c, b, carry0, max_iters: Optional[int]):
    """Unbounded lax.while_loop, or (with max_iters) the reverse-
    differentiable masked-scan form: each of the max_iters steps applies the
    body only while the condition holds, else passes the carry through."""
    if max_iters is None:
        return lax.while_loop(c, b, carry0)

    def step(carry, _):
        cont = c(carry)
        # double-where: the body also runs on dead iterations (after cont
        # goes False), so feed it the INITIAL carry there — a point where
        # the body IS in-domain, because the outer lax.cond guarantees the
        # first iteration was live — instead of the final carry, which may
        # have left the body's domain (shrinking denominators, walked-off
        # indices). Without this, dead-branch NaN/Inf residuals poison
        # reverse-mode gradients despite the output mask.
        safe_in = tuple(jnp.where(cont, cv, c0) for cv, c0 in zip(carry, carry0))
        new = b(safe_in)
        merged = tuple(jnp.where(cont, nv, cv) for nv, cv in zip(new, carry))
        return merged, None

    def run(c0):
        out, _ = lax.scan(step, c0, None, length=int(max_iters))
        return out

    # if the condition fails already at entry the body need not be total at
    # carry0 either — skip the scan entirely (matches the reference loop,
    # which returns loop_vars untouched)
    return lax.cond(c(tuple(carry0)), run, lambda c0: c0, tuple(carry0))


# --------------------------------------------------------------------- case
def case(pred_fn_pairs, default: Optional[Callable] = None, name=None):
    """parity: control_flow.py case — first true predicate wins; when
    ``default`` is None the last pair's fn is the default (reference
    contract). Lowers to a nested cond chain."""
    pairs = list(pred_fn_pairs)
    if not pairs:
        raise ValueError("case: pred_fn_pairs must be non-empty")
    for p in pairs:
        if not (isinstance(p, (tuple, list)) and len(p) == 2 and callable(p[1])):
            raise TypeError("case: each element must be a (pred, fn) pair")
    if default is None:
        default = pairs[-1][1]
        pairs = pairs[:-1]
        if not pairs:
            return default()

    pred, fn = pairs[0]
    rest = pairs[1:]
    if rest:
        return cond(pred, fn, lambda: case(rest, default))
    return cond(pred, fn, default)


def switch_case(branch_index, branch_fns, default: Optional[Callable] = None,
                name=None):
    """parity: control_flow.py switch_case — dispatch on an integer index.
    ``branch_fns``: dict{int: fn} | list[(int, fn)] | list[fn] (keys 0..n-1).
    Reduces to an equality-predicate case chain (nested ``lax.cond``)."""
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    elif branch_fns and isinstance(branch_fns[0], (tuple, list)):
        items = sorted((int(k), f) for k, f in branch_fns)
    else:
        items = list(enumerate(branch_fns))
    if not items:
        raise ValueError("switch_case: branch_fns must be non-empty")
    if default is None:
        default = items[-1][1]  # reference: highest key is the fallback
    idx_t = _as_tensor(branch_index)
    from ...tensor import logic as _logic

    pairs = [(_logic.equal(idx_t, _as_tensor(np.asarray(k, np.int64))), fn)
             for k, fn in items]
    return case(pairs, default)


# ------------------------------------------------------------ static_pylayer
def static_pylayer(forward_fn: Callable, inputs: Sequence, backward_fn=None,
                   name=None):
    """parity: static/nn/static_pylayer.py — a forward fn with a user-supplied
    backward, usable in all three execution worlds via ``jax.custom_vjp``
    dispatched through the op chokepoint (so Program capture records it)."""
    from ...autograd import tape
    from ...ops.dispatch import apply

    ins = [_as_tensor(x) for x in inputs]
    if backward_fn is None:
        out = forward_fn(*ins)
        ts, treedef = _flatten(out)
        for t in ts:
            t.stop_gradient = True  # reference: no backward ⇒ no grad path
        return _unflatten(treedef, ts)

    treedef_box = {}

    @jax.custom_vjp
    def f(*vals):
        with tape.no_grad():
            out = forward_fn(*[Tensor(v, stop_gradient=True) for v in vals])
        ts, treedef_box["td"] = _flatten(out)
        return tuple(t._value for t in ts)

    def f_fwd(*vals):
        return f(*vals), None

    def f_bwd(_, gs):
        with tape.no_grad():
            gin = backward_fn(*[Tensor(g, stop_gradient=True) for g in gs])
        gts, _ = _flatten(gin)
        return tuple(g._value for g in gts)

    f.defvjp(f_fwd, f_bwd)
    out = apply(f, *ins, op_name="static_pylayer")
    outs = out if isinstance(out, list) else [out]
    td = treedef_box.get("td")
    return _unflatten(td, outs) if td is not None else (
        outs[0] if len(outs) == 1 else tuple(outs))


# ------------------------------------------------------------------- py_func
def py_func(func: Callable, x, out, backward_func=None,
            skip_vars_in_backward_input=None, name=None):
    """parity: base py_func — run arbitrary host Python inside the graph.
    Lowers to ``jax.pure_callback`` (the XLA host-callback mechanism), so it
    stays jit-safe; ``out`` supplies the result shape/dtype contract."""
    from ...ops.dispatch import apply

    ins = [_as_tensor(t) for t in (x if isinstance(x, (list, tuple)) else [x])]
    out_list = out if isinstance(out, (list, tuple)) else [out]
    specs = [jax.ShapeDtypeStruct(tuple(t.shape), t._value.dtype)
             for t in out_list]

    def host(*arrs):
        res = func(*arrs)
        rs = res if isinstance(res, (tuple, list)) else [res]
        return tuple(np.asarray(r, s.dtype).reshape(s.shape)
                     for r, s in zip(rs, specs))

    def op(*vals):
        res = jax.pure_callback(host, tuple(specs), *vals)
        return res if len(specs) > 1 else res[0]

    if backward_func is not None:
        g_specs = [jax.ShapeDtypeStruct(tuple(jnp.shape(t._value))
                                        if not _is_sym(t) else tuple(t._value.shape),
                                        t._value.dtype) for t in ins]

        @jax.custom_vjp
        def op_vjp(*vals):
            return op(*vals)

        def fwd(*vals):
            return op_vjp(*vals), None

        def bwd(_, gs):
            gseq = gs if isinstance(gs, (tuple, list)) else (gs,)

            def ghost(*arrs):
                res = backward_func(*arrs)
                rs = res if isinstance(res, (tuple, list)) else [res]
                return tuple(np.asarray(r, s.dtype).reshape(s.shape)
                             for r, s in zip(rs, g_specs))

            return jax.pure_callback(ghost, tuple(g_specs), *gseq)

        op_vjp.defvjp(fwd, bwd)
        result = apply(op_vjp, *ins, op_name="py_func")
    else:
        result = apply(op, *ins, op_name="py_func")
    return result
