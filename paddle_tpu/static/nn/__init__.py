"""paddle.static.nn (parity:
/root/reference/python/paddle/static/nn/__init__.py — the 38-export surface:
static control flow + parameter-creating layer functions + sequence ops).

TPU-native layering: the layer functions are the reference's LayerHelper
pattern (create parameters at the call site, then apply the functional op) —
here parameters are created eagerly (concrete jax.Arrays the captured
Program closes over) and the math delegates to ``paddle_tpu.nn.functional``.
Control flow lowers to ``lax.cond``/``lax.while_loop`` (control_flow.py);
sequence ops use the padded-batch data model (sequence_lod.py).
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ...base.param_attr import ParamAttr
from ...nn import functional as F
from ...ops.dispatch import apply
from ...tensor.extras import create_parameter
from ...tensor.tensor import Tensor
from .control_flow import case, cond, py_func, static_pylayer, switch_case, while_loop  # noqa: F401
from .sequence_lod import (  # noqa: F401
    sequence_conv,
    sequence_enumerate,
    sequence_expand,
    sequence_expand_as,
    sequence_first_step,
    sequence_last_step,
    sequence_pad,
    sequence_pool,
    sequence_reshape,
    sequence_scatter,
    sequence_slice,
    sequence_softmax,
    sequence_unpad,
)

__all__ = [
    "fc", "batch_norm", "bilinear_tensor_product", "embedding", "case", "cond",
    "static_pylayer", "conv2d", "conv2d_transpose", "conv3d", "conv3d_transpose",
    "data_norm", "deform_conv2d", "group_norm", "instance_norm", "layer_norm",
    "nce", "prelu", "py_func", "row_conv", "spectral_norm", "switch_case",
    "while_loop", "sparse_embedding", "sequence_conv", "sequence_softmax",
    "sequence_pool", "sequence_first_step", "sequence_last_step",
    "sequence_slice", "sequence_expand", "sequence_expand_as", "sequence_pad",
    "sequence_unpad", "sequence_reshape", "sequence_scatter", "sequence_enumerate",
]


def _as_t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _dtype_of(t: Tensor) -> str:
    v = t._value
    return str(v.dtype) if hasattr(v, "dtype") else "float32"


def _act(out, act: Optional[str]):
    if act is None:
        return out
    return getattr(F, act)(out)


# ---------------------------------------------------------- dense / embedding
def fc(x, size: int, num_flatten_dims: int = 1, weight_attr=None, bias_attr=None,
       activation: Optional[str] = None, name=None):
    """parity: static/nn/common.py fc — flatten trailing dims and project."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = []
    for xi in xs:
        xi = _as_t(xi)
        shape = tuple(xi.shape)
        nfd = num_flatten_dims if num_flatten_dims >= 0 else len(shape) - 1
        in_dim = int(np.prod(shape[nfd:]))
        w = create_parameter([in_dim, size], _dtype_of(xi),
                             attr=ParamAttr._to_attr(weight_attr))

        def proj(v, wv, _nfd=nfd, _in=in_dim):
            lead = v.shape[:_nfd]
            return (v.reshape((*lead, _in)) @ wv)

        outs.append(apply(proj, xi, w, op_name="fc"))
    out = outs[0]
    for o in outs[1:]:
        out = out + o
    if bias_attr is not False:
        from ...nn.initializer import Constant

        b = create_parameter([size], _dtype_of(_as_t(xs[0])),
                             attr=ParamAttr._to_attr(bias_attr), is_bias=True,
                             default_initializer=Constant(0.0))
        out = out + b
    return _act(out, activation)


def embedding(input, size, is_sparse=False, is_distributed=False,  # noqa: A002
              padding_idx=None, param_attr=None, dtype="float32"):
    """parity: static/nn/common.py embedding."""
    w = create_parameter(list(size), dtype, attr=ParamAttr._to_attr(param_attr))
    return F.embedding(_as_t(input), w, padding_idx=padding_idx)


def sparse_embedding(input, size, padding_idx=None, is_test=False,  # noqa: A002
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32", slot=None):
    """parity: static/nn/common.py sparse_embedding — the PS-backed embedding.
    Dense jax.Array storage here (the PS tier handles true sparse tables);
    the admission ``entry`` policy is recorded on the parameter for the PS
    runtime (paddle_tpu.distributed.ps) to consult."""
    w = create_parameter(list(size), dtype, attr=ParamAttr._to_attr(param_attr))
    if entry is not None:
        attrs = w._optimize_attrs or {}
        attrs["ps_entry"] = entry
        w._optimize_attrs = attrs
    return F.embedding(_as_t(input), w, padding_idx=padding_idx)


# ----------------------------------------------------------------- conv zoo
def _conv_params(x, num_filters, filter_size, groups, channels_last, ndim,
                 param_attr, bias_attr, transpose=False):
    cin = int(x.shape[-1] if channels_last else x.shape[1])
    ks = list(filter_size) if isinstance(filter_size, (list, tuple)) else [filter_size] * ndim
    if transpose:
        wshape = [cin, num_filters // (groups or 1), *ks]
    else:
        wshape = [num_filters, cin // (groups or 1), *ks]
    w = create_parameter(wshape, _dtype_of(x), attr=ParamAttr._to_attr(param_attr))
    b = None
    if bias_attr is not False:
        from ...nn.initializer import Constant

        b = create_parameter([num_filters], _dtype_of(x),
                             attr=ParamAttr._to_attr(bias_attr), is_bias=True,
                             default_initializer=Constant(0.0))
    return w, b


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,  # noqa: A002
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True, act=None,
           name=None, data_format="NCHW"):
    x = _as_t(input)
    w, b = _conv_params(x, num_filters, filter_size, groups,
                        data_format == "NHWC", 2, param_attr, bias_attr)
    out = F.conv2d(x, w, b, stride=stride, padding=padding, dilation=dilation,
                   groups=groups, data_format=data_format)
    return _act(out, act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,  # noqa: A002
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True, act=None,
           name=None, data_format="NCDHW"):
    x = _as_t(input)
    w, b = _conv_params(x, num_filters, filter_size, groups,
                        data_format == "NDHWC", 3, param_attr, bias_attr)
    out = F.conv3d(x, w, b, stride=stride, padding=padding, dilation=dilation,
                   groups=groups, data_format=data_format)
    return _act(out, act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,  # noqa: A002
                     padding=0, stride=1, dilation=1, groups=1, param_attr=None,
                     bias_attr=None, use_cudnn=True, act=None, name=None,
                     data_format="NCHW"):
    x = _as_t(input)
    w, b = _conv_params(x, num_filters, filter_size or 1, groups,
                        data_format == "NHWC", 2, param_attr, bias_attr,
                        transpose=True)
    out = F.conv2d_transpose(x, w, b, stride=stride, padding=padding,
                             dilation=dilation, groups=groups,
                             output_size=output_size, data_format=data_format)
    return _act(out, act)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,  # noqa: A002
                     padding=0, stride=1, dilation=1, groups=1, param_attr=None,
                     bias_attr=None, use_cudnn=True, act=None, name=None,
                     data_format="NCDHW"):
    x = _as_t(input)
    w, b = _conv_params(x, num_filters, filter_size or 1, groups,
                        data_format == "NDHWC", 3, param_attr, bias_attr,
                        transpose=True)
    out = F.conv3d_transpose(x, w, b, stride=stride, padding=padding,
                             dilation=dilation, groups=groups,
                             output_size=output_size, data_format=data_format)
    return _act(out, act)


def deform_conv2d(input, offset, mask, num_filters, filter_size, stride=1,  # noqa: A002
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None, name=None):
    """Delegates to vision.ops.deform_conv2d (the DCNv2 kernel analog)."""
    from ...vision.ops import deform_conv2d as _dc

    x = _as_t(input)
    w, b = _conv_params(x, num_filters, filter_size, groups, False, 2,
                        param_attr, bias_attr)
    return _dc(x, _as_t(offset), w, bias=b, stride=stride, padding=padding,
               dilation=dilation, deformable_groups=deformable_groups,
               groups=groups, mask=_as_t(mask) if mask is not None else None)


# ---------------------------------------------------------------- norm zoo
def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,  # noqa: A002
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=True,
               use_global_stats=False):
    from ...nn.initializer import Constant

    x = _as_t(input)
    c = int(x.shape[-1] if data_layout == "NHWC" else x.shape[1])
    dt = _dtype_of(x)
    scale = create_parameter([c], dt, attr=ParamAttr._to_attr(param_attr),
                             default_initializer=Constant(1.0))
    bias = create_parameter([c], dt, attr=ParamAttr._to_attr(bias_attr),
                            is_bias=True, default_initializer=Constant(0.0))
    mean = create_parameter([c], dt, name=moving_mean_name,
                            default_initializer=Constant(0.0))
    var = create_parameter([c], dt, name=moving_variance_name,
                           default_initializer=Constant(1.0))
    mean.stop_gradient = var.stop_gradient = True
    out = F.batch_norm(x, mean, var, weight=scale, bias=bias,
                       training=not (is_test or use_global_stats),
                       momentum=momentum, epsilon=epsilon,
                       data_format=data_layout)
    return _act(out, act)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1, epsilon=1e-5,  # noqa: A002
               param_attr=None, bias_attr=None, act=None, name=None):
    from ...nn.initializer import Constant

    x = _as_t(input)
    norm_shape = [int(s) for s in x.shape[begin_norm_axis:]]
    dt = _dtype_of(x)
    w = create_parameter(norm_shape, dt, attr=ParamAttr._to_attr(param_attr),
                         default_initializer=Constant(1.0)) if scale else None
    b = create_parameter(norm_shape, dt, attr=ParamAttr._to_attr(bias_attr),
                         is_bias=True, default_initializer=Constant(0.0)) if shift else None
    out = F.layer_norm(x, norm_shape, weight=w, bias=b, epsilon=epsilon)
    return _act(out, act)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,  # noqa: A002
               act=None, data_layout="NCHW", name=None):
    from ...nn.initializer import Constant

    x = _as_t(input)
    c = int(x.shape[-1] if data_layout == "NHWC" else x.shape[1])
    dt = _dtype_of(x)
    w = create_parameter([c], dt, attr=ParamAttr._to_attr(param_attr),
                         default_initializer=Constant(1.0))
    b = create_parameter([c], dt, attr=ParamAttr._to_attr(bias_attr),
                         is_bias=True, default_initializer=Constant(0.0))
    out = F.group_norm(x, groups, epsilon=epsilon, weight=w, bias=b,
                       data_format=data_layout)
    return _act(out, act)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None, name=None):  # noqa: A002
    from ...nn.initializer import Constant

    x = _as_t(input)
    c = int(x.shape[1])
    dt = _dtype_of(x)
    w = None if param_attr is False else create_parameter(
        [c], dt, attr=ParamAttr._to_attr(param_attr), default_initializer=Constant(1.0))
    b = None if bias_attr is False else create_parameter(
        [c], dt, attr=ParamAttr._to_attr(bias_attr), is_bias=True,
        default_initializer=Constant(0.0))
    return F.instance_norm(x, weight=w, bias=b, eps=epsilon)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None, shift=True,  # noqa: A002
              scale=True, data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              sync_stats=False, summary_decay_rate=0.9999999,
              enable_scale_and_shift=False):
    """parity: static/nn/common.py data_norm — normalization by accumulated
    batch statistics (batch_size/batch_sum/batch_square_sum parameters), the
    PS-training normalizer. Statistics update rides the forward."""
    from ...nn.initializer import Constant

    x = _as_t(input)
    c = int(x.shape[-1])
    dt = _dtype_of(x)
    batch_size = create_parameter([c], dt, default_initializer=Constant(1e4))
    batch_sum = create_parameter([c], dt, default_initializer=Constant(0.0))
    batch_sq = create_parameter([c], dt, default_initializer=Constant(1e4))
    for p in (batch_size, batch_sum, batch_sq):
        p.stop_gradient = True

    def f(v, n, s, sq):
        means = s / n
        scales = jnp.sqrt(n / jnp.maximum(sq - s * means, epsilon))
        return (v - means) * scales

    out = apply(f, x, batch_size, batch_sum, batch_sq, op_name="data_norm")
    # accumulate batch statistics (decayed, reference summary_decay_rate) so
    # subsequent calls normalize with observed data; eager-mode only — in a
    # captured Program the accumulators stay at their feed-time values for
    # that execution (stats updates are a host-side training-loop concern)
    if not isinstance(x._value, (jax.core.Tracer, jax.ShapeDtypeStruct)):
        v = x._value.reshape(-1, c).astype(jnp.float32)
        d = summary_decay_rate
        rows = jnp.asarray(v.shape[0], jnp.float32)
        batch_size._value = (batch_size._value.astype(jnp.float32) * d + rows).astype(batch_size._value.dtype)
        batch_sum._value = (batch_sum._value.astype(jnp.float32) * d + v.sum(0)).astype(batch_sum._value.dtype)
        batch_sq._value = (batch_sq._value.astype(jnp.float32) * d + (v * v).sum(0)).astype(batch_sq._value.dtype)
    return _act(out, act)


def spectral_norm(weight, dim: int = 0, power_iters: int = 1, eps: float = 1e-12,
                  name=None):
    """parity: static/nn/common.py spectral_norm — weight / sigma_max via
    power iteration. u/v persist across eager calls (written back after each
    iteration) so sigma converges over training steps even with
    power_iters=1, matching the reference's persistent u/v buffers."""
    from ...nn.initializer import Normal

    w = _as_t(weight)
    shape = tuple(int(s) for s in w.shape)
    h = shape[dim]
    wmat_cols = int(np.prod(shape)) // h
    u = create_parameter([h], _dtype_of(w), default_initializer=Normal(0.0, 1.0))
    v = create_parameter([wmat_cols], _dtype_of(w), default_initializer=Normal(0.0, 1.0))
    u.stop_gradient = v.stop_gradient = True

    def f(wv, uv, vv):
        perm = (dim, *(i for i in range(len(shape)) if i != dim))
        m = jnp.transpose(wv, perm).reshape(h, -1)
        for _ in range(power_iters):
            vv = m.T @ uv
            vv = vv / jnp.maximum(jnp.linalg.norm(vv), eps)
            uv = m @ vv
            uv = uv / jnp.maximum(jnp.linalg.norm(uv), eps)
        sigma = uv @ m @ vv
        return wv / sigma, uv, vv

    out = apply(f, w, u, v, op_name="spectral_norm", n_outs=3)
    wn, u_new, v_new = out[0], out[1], out[2]
    if not isinstance(u_new._value, (jax.core.Tracer, jax.ShapeDtypeStruct)):
        u._value, v._value = u_new._value, v_new._value
    return wn


# ------------------------------------------------------------------ misc ops
def bilinear_tensor_product(x, y, size: int, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """parity: static/nn/common.py bilinear_tensor_product —
    out[:, i] = x · W[i] · yᵀ + b."""
    from ...nn.initializer import Constant

    xt, yt = _as_t(x), _as_t(y)
    dx, dy = int(xt.shape[-1]), int(yt.shape[-1])
    w = create_parameter([size, dx, dy], _dtype_of(xt),
                         attr=ParamAttr._to_attr(param_attr))
    out = apply(lambda a, b, wv: jnp.einsum("bi,oij,bj->bo", a, wv, b),
                xt, yt, w, op_name="bilinear_tensor_product")
    if bias_attr is not False:
        bias = create_parameter([size], _dtype_of(xt),
                                attr=ParamAttr._to_attr(bias_attr), is_bias=True,
                                default_initializer=Constant(0.0))
        out = out + bias
    return _act(out, act)


def prelu(x, mode: str = "all", param_attr=None, data_format="NCHW", name=None):
    """parity: static/nn/common.py prelu — modes all/channel/element."""
    from ...nn.initializer import Constant

    xt = _as_t(x)
    if mode == "all":
        shape = [1]
    elif mode == "channel":
        shape = [int(xt.shape[1] if data_format == "NCHW" else xt.shape[-1])]
    elif mode == "element":
        shape = [1, *(int(s) for s in xt.shape[1:])]
    else:
        raise ValueError("prelu mode must be all|channel|element")
    alpha = create_parameter(shape, _dtype_of(xt),
                             attr=ParamAttr._to_attr(param_attr),
                             default_initializer=Constant(0.25))
    return F.prelu(xt, alpha, data_format=data_format)


def row_conv(input, future_context_size: int, param_attr=None, act=None):  # noqa: A002
    """parity: static/nn/common.py row_conv — lookahead convolution over
    [B, T, D]: out[t] = Σ_{k=0..fcs} in[t+k] * w[k]."""
    x = _as_t(input)
    d = int(x.shape[-1])
    w = create_parameter([future_context_size + 1, d], _dtype_of(x),
                         attr=ParamAttr._to_attr(param_attr))

    def f(v, wv):
        out = jnp.zeros_like(v)
        tlen = v.shape[1]
        for k in range(wv.shape[0]):
            shifted = jnp.roll(v, -k, axis=1)
            valid = (jnp.arange(tlen) + k) < tlen
            out = out + jnp.where(valid[None, :, None], shifted, 0) * wv[k]
        return out

    return _act(apply(f, x, w, op_name="row_conv"), act)


def nce(input, label, num_total_classes: int, sample_weight=None,  # noqa: A002
        param_attr=None, bias_attr=None, num_neg_samples: Optional[int] = None,
        name=None, sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """parity: static/nn/common.py nce — noise-contrastive estimation loss:
    one positive logistic term + num_neg_samples uniform negatives per row."""
    from ...nn.initializer import Constant

    x, lbl = _as_t(input), _as_t(label)
    d = int(x.shape[-1])
    k = num_neg_samples or 10
    w = create_parameter([num_total_classes, d], _dtype_of(x),
                         attr=ParamAttr._to_attr(param_attr))
    b = create_parameter([num_total_classes], _dtype_of(x),
                         attr=ParamAttr._to_attr(bias_attr), is_bias=True,
                         default_initializer=Constant(0.0))
    # negatives drawn host-side per call (the reference samples inside the
    # kernel with its own generator; fixed draws keep the op pure/jit-safe)
    rng = np.random.RandomState(seed or None)
    negs = Tensor(jnp.asarray(rng.randint(0, num_total_classes, size=(k,)),
                              jnp.int32))

    def f(v, y, wv, bv, nv):
        y = jnp.reshape(y, (-1,)).astype(jnp.int32)
        pos = jnp.sum(v * wv[y], -1) + bv[y]                      # [B]
        neg = v @ wv[nv].T + bv[nv]                               # [B, k]
        ln_sig = jax.nn.log_sigmoid
        loss = -(ln_sig(pos) + ln_sig(-neg).sum(-1))
        return loss.reshape(-1, 1)

    return apply(f, x, lbl, w, b, negs, op_name="nce")
