"""Sequence ops (parity:
/root/reference/python/paddle/static/nn/sequence_lod.py — sequence_conv,
sequence_softmax, sequence_pool, sequence_first/last_step, sequence_slice,
sequence_expand(_as), sequence_pad/unpad, sequence_reshape, sequence_scatter,
sequence_enumerate).

TPU-native data model: the reference's LoD (ragged level-of-detail) tensors
are a dynamic-shape construct XLA does not admit. The capability translates
to the padded-batch form every TPU pipeline uses: a sequence batch is a dense
``[B, T, ...]`` array plus an optional per-row ``length`` vector; masking
replaces LoD boundaries. Functions that in the reference consume a 2-level
LoD take the dense batch (with ``length`` where semantics need it); functions
whose outputs would be ragged (``sequence_unpad``) return the dense array
masked to length — the shapes stay static, the values carry the raggedness.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

import jax.numpy as jnp

from ...ops.dispatch import apply
from ...tensor.tensor import Tensor

__all__ = [
    "sequence_conv", "sequence_softmax", "sequence_pool", "sequence_first_step",
    "sequence_last_step", "sequence_slice", "sequence_expand",
    "sequence_expand_as", "sequence_pad", "sequence_unpad", "sequence_reshape",
    "sequence_scatter", "sequence_enumerate",
]


def _as_t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _mask(v, length, fill=0.0):
    """[B,T,...] masked beyond per-row length."""
    if length is None:
        return v
    t = jnp.arange(v.shape[1])
    m = t[None, :] < jnp.reshape(length, (-1, 1))
    m = m.reshape(m.shape + (1,) * (v.ndim - 2))
    return jnp.where(m, v, jnp.asarray(fill, v.dtype))


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, name=None):  # noqa: A002
    """Sliding-window projection over the time dim: each step's context
    window [t+pad_start, t+pad_start+filter_size) is flattened and projected
    to num_filters (reference sequence_conv contract)."""
    from ...base.param_attr import ParamAttr
    from ...tensor.extras import create_parameter

    x = _as_t(input)
    d = int(x.shape[-1])
    w = create_parameter([filter_size * d, num_filters], str(x.dtype.name),
                         attr=ParamAttr._to_attr(param_attr))
    b = None
    if bias_attr is not False:
        from ...nn.initializer import Constant

        b = create_parameter([num_filters], str(x.dtype.name),
                             attr=ParamAttr._to_attr(bias_attr), is_bias=True,
                             default_initializer=Constant(0.0))
    start = -((filter_size - 1) // 2) if padding_start is None else padding_start

    def f(v, wv, *rest):
        ctx = []
        for k in range(filter_size):
            off = start + k
            shifted = jnp.roll(v, -off, axis=1)
            t = jnp.arange(v.shape[1])
            valid = (t + off >= 0) & (t + off < v.shape[1])
            ctx.append(jnp.where(valid[None, :, None], shifted, 0))
        win = jnp.concatenate(ctx, axis=-1)  # [B,T,fs*d]
        out = win @ wv
        if rest:
            out = out + rest[0]
        return out

    args = (x, w, b) if b is not None else (x, w)
    out = apply(f, *args, op_name="sequence_conv")
    if act is not None:
        from ...nn import functional as F

        out = getattr(F, act)(out)
    return out


def sequence_softmax(input, use_cudnn=False, name=None, length=None):  # noqa: A002
    """Softmax over the time dim, masked beyond ``length``."""
    x = _as_t(input)
    ln = _as_t(length) if length is not None else None

    def f(v, *rest):
        l = rest[0] if rest else None
        logits = v
        if l is not None:
            t = jnp.arange(v.shape[1])
            m = t[None, :] < jnp.reshape(l, (-1, 1))
            m = m.reshape(m.shape + (1,) * (v.ndim - 2))
            logits = jnp.where(m, v, -jnp.inf)
        e = jnp.exp(logits - jnp.max(logits, axis=1, keepdims=True))
        e = jnp.where(jnp.isfinite(e), e, 0)
        return e / jnp.maximum(e.sum(1, keepdims=True), 1e-12)

    return apply(f, *((x, ln) if ln is not None else (x,)), op_name="sequence_softmax")


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0, length=None,
                  name=None):  # noqa: A002
    """[B,T,D] → [B,D] with sum/average/sqrt/max/last/first over valid steps."""
    x = _as_t(input)
    ln = _as_t(length) if length is not None else None
    kind = pool_type.lower()

    def f(v, *rest):
        l = rest[0] if rest else None
        tlen = v.shape[1]
        counts = (jnp.reshape(l, (-1, 1)).astype(v.dtype) if l is not None
                  else jnp.full((v.shape[0], 1), tlen, v.dtype))
        vm = v if l is None else _mask(v, l)
        if kind == "sum":
            return vm.sum(1)
        if kind == "average":
            return vm.sum(1) / jnp.maximum(counts, 1)
        if kind == "sqrt":
            return vm.sum(1) / jnp.sqrt(jnp.maximum(counts, 1))
        if kind == "max":
            if l is not None:
                t = jnp.arange(tlen)
                m = t[None, :] < jnp.reshape(l, (-1, 1))
                m = m.reshape(m.shape + (1,) * (v.ndim - 2))
                vm = jnp.where(m, v, -jnp.inf)
            return vm.max(1)
        if kind == "last":
            idx = (jnp.reshape(l, (-1,)).astype(jnp.int32) - 1 if l is not None
                   else jnp.full((v.shape[0],), tlen - 1, jnp.int32))
            return jnp.take_along_axis(
                v, idx.reshape(-1, *([1] * (v.ndim - 1))), axis=1)[:, 0]
        if kind == "first":
            return v[:, 0]
        raise ValueError(f"unknown pool_type {pool_type}")

    return apply(f, *((x, ln) if ln is not None else (x,)), op_name="sequence_pool")


def sequence_first_step(input, name=None):  # noqa: A002
    return sequence_pool(input, "first")


def sequence_last_step(input, length=None, name=None):  # noqa: A002
    return sequence_pool(input, "last", length=length)


def sequence_slice(input, offset, length, name=None):  # noqa: A002
    """Per-row slice [offset, offset+length) along time; output padded to
    max(length) (static shape), rows masked past their own length."""
    x, off, ln = _as_t(input), _as_t(offset), _as_t(length)

    def f(v, o, l):
        o = jnp.reshape(o, (-1,)).astype(jnp.int32)
        l = jnp.reshape(l, (-1,)).astype(jnp.int32)
        width = v.shape[1]
        t = jnp.arange(width)
        idx = jnp.clip(o[:, None] + t[None, :], 0, width - 1)
        g = jnp.take_along_axis(v, idx.reshape(idx.shape + (1,) * (v.ndim - 2)), 1)
        m = t[None, :] < l[:, None]
        return jnp.where(m.reshape(m.shape + (1,) * (v.ndim - 2)), g, 0)

    return apply(f, x, off, ln, op_name="sequence_slice")


def sequence_expand(x, y, ref_level=-1, name=None):
    """Repeat each row of x per the batch of y (padded analog: broadcast x's
    rows to y's leading shape). With dense batches both carry [B,...], so the
    expansion is x broadcast against y's row count."""
    xt, yt = _as_t(x), _as_t(y)

    def f(a, b):
        if b.shape[0] % a.shape[0] != 0:
            raise ValueError(
                f"sequence_expand: y rows ({b.shape[0]}) must be a multiple "
                f"of x rows ({a.shape[0]}) in the padded-batch data model")
        reps = b.shape[0] // a.shape[0]
        return jnp.repeat(a, reps, axis=0) if reps > 1 else a

    return apply(f, xt, yt, op_name="sequence_expand")


def sequence_expand_as(x, y, name=None):
    return sequence_expand(x, y)


def sequence_pad(x, pad_value, maxlen: Optional[int] = None, length=None,
                 name=None):
    """Pad/truncate the time dim to ``maxlen``; returns (padded, lengths)
    (reference returns Out + Length)."""
    xt = _as_t(x)
    pv = _as_t(pad_value)
    ln = _as_t(length) if length is not None else None
    tgt = maxlen

    def f(v, p, *rest):
        l = rest[0] if rest else None
        t = v.shape[1]
        m = tgt or t
        if m > t:
            pad_shape = (v.shape[0], m - t) + v.shape[2:]
            v = jnp.concatenate(
                [v, jnp.full(pad_shape, jnp.reshape(p, ()).astype(v.dtype))], 1)
        elif m < t:
            v = v[:, :m]
        lengths = (jnp.minimum(jnp.reshape(l, (-1,)), m) if l is not None
                   else jnp.full((v.shape[0],), min(m, t), jnp.int64))
        if l is not None:
            tt = jnp.arange(v.shape[1])
            msk = tt[None, :] < lengths[:, None]
            msk = msk.reshape(msk.shape + (1,) * (v.ndim - 2))
            v = jnp.where(msk, v, jnp.reshape(p, ()).astype(v.dtype))
        return v, lengths

    args = (xt, pv, ln) if ln is not None else (xt, pv)
    out = apply(f, *args, op_name="sequence_pad", n_outs=2)
    return out[0], out[1]


def sequence_unpad(x, length, name=None):
    """Inverse of sequence_pad. Ragged output is impossible under static
    shapes; returns the dense array zero-masked past each row's length (the
    values equal the reference's unpadded rows; consumers read ``length``)."""
    xt, ln = _as_t(x), _as_t(length)
    return apply(lambda v, l: _mask(v, jnp.reshape(l, (-1,))), xt, ln,
                 op_name="sequence_unpad")


def sequence_reshape(input, new_dim: int, name=None):  # noqa: A002
    """Re-chunk the flattened time*feature stream into rows of new_dim."""
    x = _as_t(input)

    def f(v):
        b = v.shape[0]
        total = v.shape[1] * v.shape[2] if v.ndim == 3 else v.shape[1]
        if total % new_dim != 0:
            raise ValueError(f"sequence_reshape: {total} not divisible by {new_dim}")
        return v.reshape(b, total // new_dim, new_dim)

    return apply(f, x, op_name="sequence_reshape")


def sequence_scatter(input, index, updates, name=None):  # noqa: A002
    """Scatter ``updates`` into per-row time positions ``index``."""
    x, idx, upd = _as_t(input), _as_t(index), _as_t(updates)

    def f(v, i, u):
        i = i.astype(jnp.int32)
        rows = jnp.arange(v.shape[0])[:, None]
        rows = jnp.broadcast_to(rows, i.shape)
        return v.at[rows, i].add(u.astype(v.dtype))

    return apply(f, x, idx, upd, op_name="sequence_scatter")


def sequence_enumerate(input, win_size: int, pad_value: int = 0, name=None):  # noqa: A002
    """All length-win_size subsequences per step: [B,T] → [B,T,win_size]."""
    x = _as_t(input)

    def f(v):
        t = jnp.arange(v.shape[1])
        outs = []
        for k in range(win_size):
            idx = jnp.clip(t + k, 0, v.shape[1] - 1)
            val = v[:, idx]
            outs.append(jnp.where((t + k < v.shape[1])[None, :], val,
                                  jnp.asarray(pad_value, v.dtype)))
        return jnp.stack(outs, axis=-1)

    return apply(f, x, op_name="sequence_enumerate")
