"""Program transform & inspection passes (reference analog: the PIR pass
infrastructure — pir::PassManager, paddle/pir/include/pass/pass_manager.h:35,
with the general transforms of paddle/fluid/pir/transforms/general/
{dead_code_elimination_pass, common_subexpression_elimination_pass,
constant_folding_pass}.cc).

TPU-native position: the captured ``static.Program`` is a linear op list the
Executor replays as ONE jitted computation, so XLA performs the heavy
optimization (fusion, layout, scheduling, CSE inside the compiled program).
What a pass layer still buys on top:

* **inspection** — ``Program.__str__``/:func:`print_program` give a readable
  IR dump (op name, inputs, outputs) for debugging captured graphs;
* **host-side graph surgery XLA can't do** — dropping ops whose results are
  never fetched (smaller trace → faster compile), folding concrete-input
  subgraphs at build time (they'd otherwise re-execute per run), and
  deduplicating repeated captures before tracing cost is paid.

Passes rewrite the op list in place and report statistics, mirroring the
reference's pass instrumentation (print_stats).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import jax

__all__ = ["PassBase", "PassManager", "DeadCodeEliminationPass",
           "CommonSubexpressionEliminationPass", "ConstantFoldingPass",
           "print_program", "program_to_str"]


# ------------------------------------------------------------- inspection
def program_to_str(program) -> str:
    """Readable IR dump of a captured Program (PIR printer analog)."""
    names: Dict[int, str] = {}

    def name_of(t):
        if id(t) not in names:
            tag = "feed" if t in program.feeds else (
                "param" if getattr(t, "is_parameter", False) else "v")
            names[id(t)] = f"%{tag}{len(names)}"
        return names[id(t)]

    lines = [f"program(id={program.id}, ops={len(program.ops)}, "
             f"feeds={[t.name for t in program.feeds]})"]
    for fn, ins, outs, op_name in program.ops:
        shape = lambda t: "x".join(str(s) for s in t.shape)  # noqa: E731
        in_s = ", ".join(f"{name_of(t)}:{shape(t)}" for t in ins)
        out_s = ", ".join(f"{name_of(t)}:{shape(t)}" for t in outs)
        lines.append(f"  {out_s} = {op_name or 'op'}({in_s})")
    return "\n".join(lines)


def print_program(program) -> None:
    print(program_to_str(program))


# ------------------------------------------------------------------ passes
class PassBase:
    """One rewrite over a Program's op list (parity: pir::Pass)."""

    name = "pass"

    def run(self, program) -> int:
        """Apply; returns the number of ops changed/removed."""
        raise NotImplementedError


class DeadCodeEliminationPass(PassBase):
    """Drop ops whose outputs nothing reads (parity:
    dead_code_elimination_pass.cc). ``keep`` marks fetch targets; the
    program's loss and feeds are always live."""

    name = "dead_code_elimination"

    def __init__(self, keep: Sequence = ()):
        self.keep = list(keep)

    def run(self, program) -> int:
        live = {id(t) for t in self.keep}
        if program._loss is not None:
            live.add(id(program._loss))
        changed = True
        kept: List = list(program.ops)
        while changed:
            changed = False
            used = set(live)
            for _, ins, _, _ in kept:
                used.update(id(t) for t in ins)
            nxt = []
            for op in kept:
                _, _, outs, _ = op
                if any(id(o) in used for o in outs):
                    nxt.append(op)
                    continue
                changed = True
            # inputs of removed ops may free further ops next iteration
            kept = nxt
        removed = len(program.ops) - len(kept)
        program.ops = kept
        return removed


class CommonSubexpressionEliminationPass(PassBase):
    """Merge ops with the same pure fn + identical inputs (parity:
    common_subexpression_elimination_pass.cc). The op fns recorded at the
    dispatch chokepoint are pure by contract, so (fn identity, input ids)
    is a sound value key; RNG-bearing ops close over distinct keys and thus
    distinct fn objects, keeping them un-merged."""

    name = "common_subexpression_elimination"

    def run(self, program) -> int:
        seen: Dict = {}
        replace: Dict[int, object] = {}
        kept = []
        merged = 0
        for fn, ins, outs, op_name in program.ops:
            ins = [replace.get(id(t), t) for t in ins]
            key = (id(fn), tuple(id(t) for t in ins), op_name)
            prev = seen.get(key)
            if prev is not None and len(prev) == len(outs):
                for old, new in zip(outs, prev):
                    replace[id(old)] = new
                # externally held handles (fetch targets) must stay valid:
                # keep an identity alias op instead of orphaning the outputs
                # (the PIR passes do ReplaceAllUsesWith; a fetch list is a
                # use the pass cannot see)
                kept.append((lambda *vs: vs[0] if len(vs) == 1 else vs,
                             list(prev), outs, f"{op_name}_cse_alias"))
                merged += 1
                continue
            seen[key] = outs
            kept.append((fn, ins, outs, op_name))
        program.ops = kept
        if replace and program._loss is not None:
            program._loss = replace.get(id(program._loss), program._loss)
        return merged


class ConstantFoldingPass(PassBase):
    """Execute ops whose inputs are all CONCRETE at build time (parity:
    constant_folding_pass.cc): their outputs become constants the replay
    closes over, instead of recomputing every Executor.run."""

    name = "constant_folding"

    def run(self, program) -> int:
        folded = 0
        kept = []
        for fn, ins, outs, op_name in program.ops:
            concrete = all(not isinstance(t._value, jax.ShapeDtypeStruct)
                           for t in ins)
            if concrete:
                res = fn(*[t._value for t in ins])
                rs = list(res) if isinstance(res, (tuple, list)) else [res]
                for o, r in zip(outs, rs):
                    o._value = r  # symbolic -> constant; later ops see it
                folded += 1
                continue
            kept.append((fn, ins, outs, op_name))
        program.ops = kept
        return folded


class PassManager:
    """Ordered pass pipeline with statistics (parity: pir::PassManager)."""

    def __init__(self, passes: Optional[Sequence[PassBase]] = None,
                 print_stats: bool = False):
        self.passes: List[PassBase] = list(passes or [])
        self.print_stats = print_stats
        self.stats: List[tuple] = []

    def add_pass(self, p: PassBase) -> "PassManager":
        self.passes.append(p)
        return self

    def run(self, program) -> Dict[str, int]:
        self.stats = []
        for p in self.passes:
            n = p.run(program)
            self.stats.append((p.name, n))
            if self.print_stats:
                print(f"[pass] {p.name}: {n} ops affected")
        return dict(self.stats)
