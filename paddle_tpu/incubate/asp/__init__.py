"""ASP — automatic structured (2:4) sparsity (parity:
/root/reference/python/paddle/incubate/asp: decorate/prune_model/
set_excluded_layers/calculate_density, supported_layers_and_prune_func_map).

TPU-native: masks are computed host-side (static structure) and re-applied
after each optimizer step by the ASPOptimizer wrapper — the same
mask-after-update contract the reference implements in
OptimizerWithSparsityGuarantee. The MXU has no 2:4 sparse tensor cores, so
pruned weights buy model-compression/regularization capability, not FLOPs.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

import jax.numpy as jnp

__all__ = ["decorate", "prune_model", "set_excluded_layers",
           "reset_excluded_layers", "calculate_density", "ASPHelper"]


def calculate_density(x) -> float:
    arr = np.asarray(x._value if hasattr(x, "_value") else x)
    return float(np.count_nonzero(arr)) / max(arr.size, 1)


def _mask_n_of_m_1d(flat: np.ndarray, n: int, m: int) -> np.ndarray:
    """Keep the n largest-|w| of every m consecutive weights."""
    sz = flat.size
    pad = (-sz) % m
    v = np.abs(np.concatenate([flat, np.zeros(pad, flat.dtype)])).reshape(-1, m)
    order = np.argsort(-v, axis=1)
    mask = np.zeros_like(v, dtype=bool)
    rows = np.arange(v.shape[0])[:, None]
    mask[rows, order[:, :n]] = True
    return mask.reshape(-1)[:sz]


def _compute_mask(w: np.ndarray, n: int = 2, m: int = 4) -> np.ndarray:
    if w.ndim < 2:
        return np.ones_like(w, dtype=bool)
    flat = w.reshape(-1, w.shape[-1])
    # n:m along the input (reduction) dimension, row-major groups
    return np.stack([_mask_n_of_m_1d(row, n, m) for row in flat]).reshape(w.shape)


class ASPHelper:
    """Per-model exclusion registry; masks live ON the parameter
    (``_optimize_attrs``), so nothing leaks or collides on id() reuse
    (reference asp/asp.py ASPHelper)."""

    import weakref as _weakref

    _excluded: "ASPHelper._weakref.WeakKeyDictionary" = _weakref.WeakKeyDictionary()

    @staticmethod
    def get_mask(p):
        attrs = getattr(p, "_optimize_attrs", None)
        return attrs.get("asp_mask") if attrs else None

    @staticmethod
    def set_mask(p, mask):
        if p._optimize_attrs is None:
            p._optimize_attrs = {}
        p._optimize_attrs["asp_mask"] = mask

    @classmethod
    def is_supported(cls, layer) -> bool:
        from ...nn import Conv2D, Linear

        return isinstance(layer, (Linear, Conv2D))

    @classmethod
    def prunable_params(cls, model) -> List:
        out = []
        excluded = cls._excluded.get(model, set())
        layers = [("", model)] if cls.is_supported(model) else list(_walk(model))
        for name, layer in layers:
            if not cls.is_supported(layer) or name in excluded:
                continue
            w = getattr(layer, "weight", None)
            if w is not None and w._value.ndim >= 2:
                out.append(w)
        return out


def _walk(layer, prefix=""):
    for name, sub in layer._sub_layers.items():
        full = f"{prefix}.{name}" if prefix else name
        yield full, sub
        yield from _walk(sub, full)


def set_excluded_layers(model, layer_names: List[str]):
    ASPHelper._excluded.setdefault(model, set()).update(layer_names)


def reset_excluded_layers(model=None):
    if model is None:
        ASPHelper._excluded.clear()
    else:
        ASPHelper._excluded.pop(model, None)


def prune_model(model, n: int = 2, m: int = 4, mask_algo: str = "mask_1d",
                with_mask: bool = True):
    """Compute 2:4 masks for every supported layer and zero the pruned
    weights. Returns {param_name: mask}."""
    masks = {}
    for p in ASPHelper.prunable_params(model):
        w = np.asarray(p._value)
        mask = _compute_mask(w, n, m)
        p.set_value((w * mask).astype(w.dtype))
        if with_mask:
            ASPHelper.set_mask(p, mask)
            masks[p.name] = mask
    return masks


class _ASPOptimizer:
    """Re-applies masks after every step (reference
    OptimizerWithSparsityGuarantee)."""

    def __init__(self, optimizer):
        self._inner = optimizer

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def step(self, *args, **kwargs):
        out = self._inner.step(*args, **kwargs)
        self.step_masks_only()
        return out

    def minimize(self, loss, *args, **kwargs):
        res = self._inner.minimize(loss, *args, **kwargs)
        self.step_masks_only()
        return res

    def step_masks_only(self):
        for p in self._inner._parameter_list:
            mask = ASPHelper.get_mask(p)
            if mask is not None:
                p._value = p._value * jnp.asarray(mask, p._value.dtype)


def decorate(optimizer):
    return _ASPOptimizer(optimizer)
