"""ASP — automatic structured (2:4) sparsity (parity:
/root/reference/python/paddle/incubate/asp: decorate/prune_model/
set_excluded_layers/calculate_density, supported_layers_and_prune_func_map).

TPU-native: masks are computed host-side (static structure) and re-applied
after each optimizer step by the ASPOptimizer wrapper — the same
mask-after-update contract the reference implements in
OptimizerWithSparsityGuarantee. The MXU has no 2:4 sparse tensor cores, so
pruned weights buy model-compression/regularization capability, not FLOPs.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

import jax.numpy as jnp

__all__ = ["decorate", "prune_model", "set_excluded_layers",
           "reset_excluded_layers", "calculate_density", "ASPHelper"]


def calculate_density(x) -> float:
    arr = np.asarray(x._value if hasattr(x, "_value") else x)
    return float(np.count_nonzero(arr)) / max(arr.size, 1)


def _mask_2on4_1d(flat: np.ndarray) -> np.ndarray:
    """Keep the 2 largest-|w| of every 4 consecutive weights."""
    n = flat.size
    pad = (-n) % 4
    v = np.abs(np.concatenate([flat, np.zeros(pad, flat.dtype)])).reshape(-1, 4)
    order = np.argsort(-v, axis=1)
    mask = np.zeros_like(v, dtype=bool)
    rows = np.arange(v.shape[0])[:, None]
    mask[rows, order[:, :2]] = True
    return mask.reshape(-1)[:n]


def _compute_mask(w: np.ndarray, n: int = 2, m: int = 4) -> np.ndarray:
    if w.ndim < 2:
        return np.ones_like(w, dtype=bool)
    flat = w.reshape(-1, w.shape[-1])
    # 2:4 along the input (reduction) dimension, row-major groups
    return np.stack([_mask_2on4_1d(row) for row in flat]).reshape(w.shape)


class ASPHelper:
    """Per-model mask registry (reference asp/asp.py ASPHelper)."""

    _excluded: Dict[int, set] = {}
    _masks: Dict[int, np.ndarray] = {}

    @classmethod
    def is_supported(cls, layer) -> bool:
        from ...nn import Conv2D, Linear

        return isinstance(layer, (Linear, Conv2D))

    @classmethod
    def prunable_params(cls, model) -> List:
        out = []
        excluded = cls._excluded.get(id(model), set())
        layers = [("", model)] if cls.is_supported(model) else list(_walk(model))
        for name, layer in layers:
            if not cls.is_supported(layer) or name in excluded:
                continue
            w = getattr(layer, "weight", None)
            if w is not None and w._value.ndim >= 2:
                out.append(w)
        return out


def _walk(layer, prefix=""):
    for name, sub in layer._sub_layers.items():
        full = f"{prefix}.{name}" if prefix else name
        yield full, sub
        yield from _walk(sub, full)


def set_excluded_layers(model, layer_names: List[str]):
    ASPHelper._excluded.setdefault(id(model), set()).update(layer_names)


def reset_excluded_layers(model=None):
    if model is None:
        ASPHelper._excluded.clear()
    else:
        ASPHelper._excluded.pop(id(model), None)


def prune_model(model, n: int = 2, m: int = 4, mask_algo: str = "mask_1d",
                with_mask: bool = True):
    """Compute 2:4 masks for every supported layer and zero the pruned
    weights. Returns {param_name: mask}."""
    masks = {}
    for p in ASPHelper.prunable_params(model):
        w = np.asarray(p._value)
        mask = _compute_mask(w, n, m)
        p.set_value((w * mask).astype(w.dtype))
        if with_mask:
            ASPHelper._masks[id(p)] = mask
            masks[p.name] = mask
    return masks


class _ASPOptimizer:
    """Re-applies masks after every step (reference
    OptimizerWithSparsityGuarantee)."""

    def __init__(self, optimizer):
        self._inner = optimizer

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def step(self, *args, **kwargs):
        out = self._inner.step(*args, **kwargs)
        for p in self._inner._parameter_list:
            mask = ASPHelper._masks.get(id(p))
            if mask is not None:
                p._value = p._value * jnp.asarray(mask, p._value.dtype)
        return out

    def minimize(self, loss, *args, **kwargs):
        res = self._inner.minimize(loss, *args, **kwargs)
        self.step_masks_only()
        return res

    def step_masks_only(self):
        for p in self._inner._parameter_list:
            mask = ASPHelper._masks.get(id(p))
            if mask is not None:
                p._value = p._value * jnp.asarray(mask, p._value.dtype)


def decorate(optimizer):
    return _ASPOptimizer(optimizer)
