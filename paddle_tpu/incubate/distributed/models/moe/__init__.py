"""Mixture-of-Experts (parity:
/root/reference/python/paddle/incubate/distributed/models/moe/moe_layer.py:263
MoELayer + gating ops number_count/limit_by_capacity/prune_gate_by_capacity/
random_routing kernels).

TPU-native: GShard-style dense dispatch — routing becomes one-hot einsums and
the token shuffle becomes an all-to-all XLA inserts when expert weights are
sharded on the expert axis of the mesh. Capacity-factor token dropping matches
the reference's limit_by_capacity semantics.
"""
from __future__ import annotations

import math
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ..... import nn
from .....nn import functional as F
from .....ops.dispatch import apply
from .....tensor.tensor import Tensor
from .....distributed.topology import get_hybrid_communicate_group

__all__ = ["MoELayer", "GShardGate", "SwitchGate", "NaiveGate"]


class NaiveGate(nn.Layer):
    """Linear router (parity: gate/naive_gate.py)."""

    def __init__(self, d_model, num_experts):
        super().__init__()
        self.weight = self.create_parameter([d_model, num_experts])

    def forward(self, x):
        return F.linear(x, self.weight)


class GShardGate(NaiveGate):
    top_k = 2


class SwitchGate(NaiveGate):
    top_k = 1


class MoELayer(nn.Layer):
    """Top-k routed expert FFN bank.

    Experts are a stacked weight bank [E, ...] sharded on ``expert_axis`` of
    the active mesh ('mp' by default — the reference's moe group rides its mp
    group too unless a dedicated group is passed).
    """

    def __init__(self, d_model, d_hidden, num_experts, top_k=2, capacity_factor=1.25,
                 gate: Optional[nn.Layer] = None, expert_axis=None, activation="gelu",
                 group=None, recompute_interval=0, name=None, dispatch_mode="ragged"):
        super().__init__()
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.activation = activation
        self.dispatch_mode = dispatch_mode  # "ragged" (sort-based) | "dense"
        self.gate = gate or NaiveGate(d_model, num_experts)
        self.w1 = self.create_parameter([num_experts, d_model, d_hidden])
        self.b1 = self.create_parameter([num_experts, 1, d_hidden], is_bias=True)
        self.w2 = self.create_parameter([num_experts, d_hidden, d_model])
        self.b2 = self.create_parameter([num_experts, 1, d_model], is_bias=True)
        hcg = get_hybrid_communicate_group()
        # expert placement: the dedicated 'ep' axis when active (explicit
        # all-to-all dispatch, reference moe group analog), else 'mp' reuse
        # (GSPMD-auto sharding of the expert bank) — reuse documented in the
        # class docstring
        if expert_axis is None:
            expert_axis = "ep" if (hcg is not None and hcg.axis_size("ep") > 1) else "mp"
        self.expert_axis = expert_axis
        self._ep_size = 1
        self._ep_fn_cache = {}
        if hcg is not None and hcg.axis_size(expert_axis) > 1:
            mesh = hcg.mesh
            self._mesh = mesh
            self._ep_size = hcg.axis_size(expert_axis)
            if num_experts % self._ep_size != 0:
                raise ValueError(
                    f"num_experts={num_experts} must be a multiple of the "
                    f"'{expert_axis}' axis size {self._ep_size}")
            for p in (self.w1, self.b1, self.w2, self.b2):
                if not isinstance(p._value, jax.core.Tracer):
                    spec = PartitionSpec(expert_axis, *([None] * (p.ndim - 1)))
                    p._value = jax.device_put(p._value, NamedSharding(mesh, spec))

    def forward(self, x):
        """x: [B, S, d] (or [N, d]). Returns same shape + aux loss stored on
        ``self.l_aux`` (load-balancing, Switch/GShard style)."""
        orig_shape = x.shape
        squeeze_back = len(orig_shape) == 3
        gate_logits = self.gate(x)

        E, K = self.num_experts, self.top_k
        cap_factor = self.capacity_factor
        act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu, "silu": jax.nn.silu}[self.activation]

        mode = self.dispatch_mode

        def f_ragged(xv, gv, w1, b1, w2, b2):
            """Sort-based ragged routing (VERDICT r2 item 7b; reference
            analog: the global_scatter/global_gather all-to-all of
            moe_layer.py:263). No [N, E, C] combine tensor: token slots are
            sorted by expert, scattered into the [E*C, d] expert buffer,
            expert FFNs run as batched [E, C, ...] matmuls, results gather
            back by the same permutation. Priority and capacity-drop
            semantics are identical to the dense path (slot-major)."""
            xt = xv.reshape(-1, xv.shape[-1])  # [N, d]
            gt = gv.reshape(-1, E).astype(jnp.float32)
            N = xt.shape[0]
            C = max(int(math.ceil(N / E * cap_factor * K)), 1)
            probs = jax.nn.softmax(gt, axis=-1)
            topw, topi = jax.lax.top_k(probs, K)  # [N, K]
            topw = topw / jnp.maximum(topw.sum(-1, keepdims=True), 1e-9)

            # slot-major flatten: all slot-0 assignments first (GShard
            # priority), then slot 1, ...
            flat_e = topi.T.reshape(-1)                       # [NK]
            flat_w = topw.T.reshape(-1).astype(xt.dtype)
            flat_tok = jnp.tile(jnp.arange(N), K)
            order = jnp.argsort(flat_e, stable=True)          # group by expert
            se = flat_e[order]
            stok = flat_tok[order]
            sw = flat_w[order]
            counts = jnp.bincount(flat_e, length=E)
            start = jnp.concatenate([jnp.zeros((1,), counts.dtype),
                                     jnp.cumsum(counts)[:-1]])
            pos = jnp.arange(N * K) - jnp.take(start, se)     # rank within expert
            keep = pos < C
            dest = jnp.where(keep, se * C + pos, E * C)       # dropped -> dummy row
            buf = jnp.zeros((E * C + 1, xt.shape[-1]), xt.dtype)
            buf = buf.at[dest].set(jnp.take(xt, stok, axis=0))
            exp_in = buf[:-1].reshape(E, C, -1)
            h = act(jnp.einsum("ecd,edh->ech", exp_in, w1) + b1)
            exp_out = (jnp.einsum("ech,ehd->ecd", h, w2) + b2).reshape(E * C, -1)
            exp_out = jnp.concatenate([exp_out, jnp.zeros_like(exp_out[:1])])
            token_out = jnp.take(exp_out, dest, axis=0) * sw[:, None]
            out = jnp.zeros_like(xt).at[stok].add(
                jnp.where(keep[:, None], token_out, 0))
            me = probs.mean(0)
            ce = jax.nn.one_hot(topi[:, 0], E, dtype=jnp.float32).mean(0)
            l_aux = E * jnp.sum(me * ce)
            return out.reshape(xv.shape), l_aux

        def f(xv, gv, w1, b1, w2, b2):
            xt = xv.reshape(-1, xv.shape[-1])  # [N, d]
            gt = gv.reshape(-1, E).astype(jnp.float32)
            N = xt.shape[0]
            C = max(int(math.ceil(N / E * cap_factor * K)), 1)
            probs = jax.nn.softmax(gt, axis=-1)
            topw, topi = jax.lax.top_k(probs, K)  # [N, K]
            topw = topw / jnp.maximum(topw.sum(-1, keepdims=True), 1e-9)

            combine = jnp.zeros((N, E, C), jnp.float32)
            # GShard priority assignment: capacity positions are allocated
            # jointly across top-k slots (slot 0 first), so two tokens routed
            # to the same expert via different slots never share a slot.
            counts = jnp.zeros((E,), jnp.int32)
            for slot in range(K):
                e = topi[:, slot]  # [N]
                onehot = jax.nn.one_hot(e, E, dtype=jnp.int32)  # [N, E]
                pos = jnp.cumsum(onehot, axis=0) * onehot  # 1-based positions per expert (this slot)
                pos_tok = jnp.sum(pos, axis=-1) - 1 + jnp.take(counts, e)  # offset by prior slots
                keep = pos_tok < C  # capacity drop (limit_by_capacity parity)
                cpos = jnp.clip(pos_tok, 0, C - 1)
                oh_c = jax.nn.one_hot(cpos, C, dtype=jnp.float32) * keep[:, None]
                combine = combine + topw[:, slot, None, None] * onehot[..., None] * oh_c[:, None, :]
                counts = counts + jnp.sum(onehot, axis=0)
            dispatch = (combine > 0).astype(xt.dtype)  # [N, E, C]
            exp_in = jnp.einsum("nec,nd->ecd", dispatch, xt)
            h = act(jnp.einsum("ecd,edh->ech", exp_in, w1) + b1)
            exp_out = jnp.einsum("ech,ehd->ecd", h, w2) + b2
            out = jnp.einsum("nec,ecd->nd", combine.astype(xt.dtype), exp_out)
            # load-balance aux loss (GShard): E * sum(fraction_tokens * fraction_probs)
            me = probs.mean(0)
            ce = jax.nn.one_hot(topi[:, 0], E, dtype=jnp.float32).mean(0)
            l_aux = E * jnp.sum(me * ce)
            return out.reshape(xv.shape), l_aux

        def f_ep(xv, gv, w1, b1, w2, b2):
            """Expert-parallel ragged dispatch over the 'ep' mesh axis —
            manual shard_map: each ep rank routes ITS token shard into a
            per-expert capacity buffer, a ``lax.all_to_all`` exchanges the
            buffers so every rank receives the tokens bound for its local
            experts (from all source ranks), the batched expert FFN runs,
            and a reverse all_to_all returns results to the token owners
            (reference: global_scatter/global_gather of moe_layer.py:263).
            Capacity is per (expert, source-rank): C_local = ceil(N_local /
            E · cf · K), so total capacity matches the single-device path;
            drops are decided rank-locally, exactly the reference's
            per-worker limit_by_capacity."""
            ep = self._ep_size
            E_local = E // ep

            def local(xl, gl, w1l, b1l, w2l, b2l):
                xt = xl.reshape(-1, xl.shape[-1])           # [N_local, d]
                gt = gl.reshape(-1, E).astype(jnp.float32)
                N = xt.shape[0]
                C = max(int(math.ceil(N / E * cap_factor * K)), 1)
                probs = jax.nn.softmax(gt, axis=-1)
                topw, topi = jax.lax.top_k(probs, K)
                topw = topw / jnp.maximum(topw.sum(-1, keepdims=True), 1e-9)
                flat_e = topi.T.reshape(-1)
                flat_w = topw.T.reshape(-1).astype(xt.dtype)
                flat_tok = jnp.tile(jnp.arange(N), K)
                order = jnp.argsort(flat_e, stable=True)
                se, stok, sw = flat_e[order], flat_tok[order], flat_w[order]
                counts = jnp.bincount(flat_e, length=E)
                start = jnp.concatenate([jnp.zeros((1,), counts.dtype),
                                         jnp.cumsum(counts)[:-1]])
                pos = jnp.arange(N * K) - jnp.take(start, se)
                keep = pos < C
                dest = jnp.where(keep, se * C + pos, E * C)
                buf = jnp.zeros((E * C + 1, xt.shape[-1]), xt.dtype)
                buf = buf.at[dest].set(jnp.take(xt, stok, axis=0))
                # [E, C, d] -> exchange: each rank sends chunk r (that rank's
                # experts) and receives its own experts' tokens from every
                # source, concatenated on the capacity dim -> [E_local, ep*C, d]
                send = buf[:-1].reshape(E, C, -1)
                recv = jax.lax.all_to_all(send, self.expert_axis,
                                          split_axis=0, concat_axis=1,
                                          tiled=True)
                h = act(jnp.einsum("ecd,edh->ech", recv, w1l) + b1l)
                expert_out = jnp.einsum("ech,ehd->ecd", h, w2l) + b2l
                # reverse exchange: results go back to the source ranks
                back = jax.lax.all_to_all(expert_out, self.expert_axis,
                                          split_axis=1, concat_axis=0,
                                          tiled=True)
                exp_out = back.reshape(E * C, -1)
                exp_out = jnp.concatenate([exp_out, jnp.zeros_like(exp_out[:1])])
                token_out = jnp.take(exp_out, dest, axis=0) * sw[:, None]
                out = jnp.zeros_like(xt).at[stok].add(
                    jnp.where(keep[:, None], token_out, 0))
                me = probs.mean(0)
                ce = jax.nn.one_hot(topi[:, 0], E, dtype=jnp.float32).mean(0)
                l_aux = jax.lax.pmean(E * jnp.sum(me * ce), self.expert_axis)
                return out.reshape(xl.shape), l_aux

            axis = self.expert_axis
            key = (tuple(xv.shape), str(xv.dtype))
            fn = self._ep_fn_cache.get(key)
            if fn is None:
                tok_spec = PartitionSpec(axis, *([None] * (xv.ndim - 1)))
                w_spec = lambda p: PartitionSpec(axis, *([None] * (p.ndim - 1)))  # noqa: E731
                from .....distributed.shard_map_compat import shard_map_manual

                mapped = shard_map_manual(
                    local, self._mesh,
                    in_specs=(tok_spec, tok_spec, w_spec(self.w1), w_spec(self.b1),
                              w_spec(self.w2), w_spec(self.b2)),
                    out_specs=(tok_spec, PartitionSpec()),
                    manual_axes={axis})
                # partial-manual shard_map needs a surrounding jit scope even
                # for eager calls (auto axes resolve under the abstract mesh)
                fn = jax.jit(mapped)
                self._ep_fn_cache[key] = fn
            return fn(xv, gv, w1, b1, w2, b2)

        if self._ep_size > 1 and self.expert_axis == "ep":
            from .....distributed.shard_map_compat import (
                partial_manual_supported,
            )

            if not partial_manual_supported(self._mesh, {self.expert_axis}):
                # old jax fatally aborts XLA on partial-manual all_to_all
                # next to a size>1 auto axis — refuse cleanly instead
                raise NotImplementedError(
                    "expert-parallel MoE: this jax version cannot mix the "
                    "manual 'ep' axis with size>1 auto mesh axes — use an "
                    "ep-only mesh or a jax with top-level jax.shard_map "
                    "(>=0.8)")
            impl = f_ep
        else:
            impl = f_ragged if mode == "ragged" else f
        out, l_aux = apply(
            lambda *a: tuple(impl(*a)), x, gate_logits, self.w1, self.b1, self.w2, self.b2,
            op_name="moe", n_outs=2,
        )
        self.l_aux = l_aux
        return out
