"""paddle_tpu.incubate (parity: python/paddle/incubate — fused ops + MoE)."""
from . import nn  # noqa: F401
from . import distributed  # noqa: F401

# ----------------------------------------------------- incubate op tail
from . import asp  # noqa: F401,E402


def segment_sum(data, segment_ids, name=None):
    import jax
    import jax.numpy as jnp

    from ..ops.dispatch import apply
    from ..tensor._helpers import to_tensor_like

    data, segment_ids = to_tensor_like(data), to_tensor_like(segment_ids)
    n = int(jnp.max(segment_ids._value)) + 1
    return apply(lambda d, s: jax.ops.segment_sum(d, s.astype(jnp.int32), num_segments=n),
                 data, segment_ids, op_name="segment_sum")


def segment_mean(data, segment_ids, name=None):
    import jax
    import jax.numpy as jnp

    from ..ops.dispatch import apply
    from ..tensor._helpers import to_tensor_like

    data, segment_ids = to_tensor_like(data), to_tensor_like(segment_ids)
    n = int(jnp.max(segment_ids._value)) + 1

    def f(d, s):
        s = s.astype(jnp.int32)
        tot = jax.ops.segment_sum(d, s, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones(s.shape + (1,) * (d.ndim - 1), d.dtype),
                                  s, num_segments=n)
        return tot / jnp.maximum(cnt, 1)

    return apply(f, data, segment_ids, op_name="segment_mean")


def segment_max(data, segment_ids, name=None):
    import jax
    import jax.numpy as jnp

    from ..ops.dispatch import apply
    from ..tensor._helpers import to_tensor_like

    data, segment_ids = to_tensor_like(data), to_tensor_like(segment_ids)
    n = int(jnp.max(segment_ids._value)) + 1
    return apply(lambda d, s: jax.ops.segment_max(d, s.astype(jnp.int32), num_segments=n),
                 data, segment_ids, op_name="segment_max")


def segment_min(data, segment_ids, name=None):
    import jax
    import jax.numpy as jnp

    from ..ops.dispatch import apply
    from ..tensor._helpers import to_tensor_like

    data, segment_ids = to_tensor_like(data), to_tensor_like(segment_ids)
    n = int(jnp.max(segment_ids._value)) + 1
    return apply(lambda d, s: jax.ops.segment_min(d, s.astype(jnp.int32), num_segments=n),
                 data, segment_ids, op_name="segment_min")


def graph_send_recv(x, src_index, dst_index, pool_type="sum", out_size=None, name=None):
    """Gather messages from src nodes, reduce onto dst nodes (reference
    incubate.graph_send_recv) — one gather + one segment reduction."""
    import jax
    import jax.numpy as jnp

    from ..ops.dispatch import apply
    from ..tensor._helpers import to_tensor_like

    x = to_tensor_like(x)
    src_index, dst_index = to_tensor_like(src_index), to_tensor_like(dst_index)
    n = out_size or x.shape[0]
    if pool_type not in ("sum", "max", "min", "mean"):
        raise ValueError(f"pool_type must be sum/mean/max/min, got {pool_type!r}")
    red = {"sum": jax.ops.segment_sum, "max": jax.ops.segment_max,
           "min": jax.ops.segment_min}.get(pool_type)

    def f(xv, si, di):
        msgs = xv[si.astype(jnp.int32)]
        if red is not None:
            return red(msgs, di.astype(jnp.int32), num_segments=n)
        tot = jax.ops.segment_sum(msgs, di.astype(jnp.int32), num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones(di.shape + (1,) * (xv.ndim - 1), xv.dtype),
                                  di.astype(jnp.int32), num_segments=n)
        return tot / jnp.maximum(cnt, 1)

    return apply(f, x, src_index, dst_index, op_name="graph_send_recv")


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    """Reindex a neighborhood subgraph to contiguous local ids (host-side)."""
    import numpy as np

    import jax.numpy as jnp

    from ..tensor.tensor import Tensor

    xs = np.asarray(x._value).reshape(-1)
    nb = np.asarray(neighbors._value).reshape(-1)
    uniq = {}
    for v in xs:
        uniq.setdefault(int(v), len(uniq))
    for v in nb:
        uniq.setdefault(int(v), len(uniq))
    reindex = np.asarray([uniq[int(v)] for v in nb], np.int64)
    cnt = np.asarray(count._value).reshape(-1)
    dst = np.repeat(np.arange(len(xs)), cnt).astype(np.int64)
    nodes = np.asarray(sorted(uniq, key=uniq.get), np.int64)
    return (Tensor(jnp.asarray(reindex)), Tensor(jnp.asarray(dst)),
            Tensor(jnp.asarray(nodes)))


def graph_sample_neighbors(row, colptr, input_nodes, eids=None, perm_buffer=None,
                           sample_size=-1, return_eids=False, flag_perm_buffer=False,
                           name=None):
    """Sample up to sample_size neighbors per input node from CSC (host-side)."""
    import numpy as np

    import jax.numpy as jnp

    from ..tensor.tensor import Tensor

    rowv = np.asarray(row._value).reshape(-1)
    cp = np.asarray(colptr._value).reshape(-1)
    nodes = np.asarray(input_nodes._value).reshape(-1)
    from ..framework.random import default_generator

    import jax as _jax

    seed = int(_jax.random.randint(default_generator().next_key(), (), 0, 2**31 - 1))
    rs = np.random.RandomState(seed)
    out_nb, out_cnt = [], []
    for nd in nodes:
        lo, hi = int(cp[nd]), int(cp[nd + 1])
        nbrs = rowv[lo:hi]
        if 0 <= sample_size < len(nbrs):
            nbrs = rs.choice(nbrs, size=sample_size, replace=False)
        out_nb.append(nbrs)
        out_cnt.append(len(nbrs))
    flat = np.concatenate(out_nb) if out_nb else np.zeros(0, rowv.dtype)
    return Tensor(jnp.asarray(flat)), Tensor(jnp.asarray(np.asarray(out_cnt, np.int64)))


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes, sorted_eids=None,
                       return_eids=False, name=None):
    """K-hop sampling: repeated graph_sample_neighbors + reindex (host-side)."""
    import numpy as np

    import jax.numpy as jnp

    from ..tensor.tensor import Tensor

    frontier = np.asarray(input_nodes._value).reshape(-1)
    frontiers, all_nb, all_cnt = [], [], []
    cur = Tensor(jnp.asarray(frontier))
    for k in sample_sizes:
        frontiers.append(np.asarray(cur._value).reshape(-1))
        nb, cnt = graph_sample_neighbors(row, colptr, cur, sample_size=k)
        all_nb.append(np.asarray(nb._value))
        all_cnt.append(np.asarray(cnt._value))
        cur = nb
    # reindex against the concatenated frontiers so len(x) == len(counts)
    x_cat = np.concatenate(frontiers) if frontiers else np.zeros(0, np.int64)
    nb_cat = np.concatenate(all_nb) if all_nb else np.zeros(0, np.int64)
    cnt_cat = np.concatenate(all_cnt) if all_cnt else np.zeros(0, np.int64)
    reindex, dst, nodes = graph_reindex(
        Tensor(jnp.asarray(x_cat)), Tensor(jnp.asarray(nb_cat)),
        Tensor(jnp.asarray(cnt_cat)))
    return reindex, dst, nodes, Tensor(jnp.asarray(cnt_cat))


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) in one fusion (reference fused_softmax_mask)."""
    import jax
    import jax.numpy as jnp

    from ..ops.dispatch import apply
    from ..tensor._helpers import to_tensor_like

    return apply(lambda a, m: jax.nn.softmax(a + m.astype(a.dtype), axis=-1),
                 to_tensor_like(x), to_tensor_like(mask), op_name="softmax_mask_fuse")


def softmax_mask_fuse_upper_triangle(x, name=None):
    import jax
    import jax.numpy as jnp

    from ..ops.dispatch import apply
    from ..tensor._helpers import to_tensor_like

    def f(a):
        s = a.shape[-1]
        mask = jnp.tril(jnp.ones((s, s), bool))
        return jax.nn.softmax(jnp.where(mask, a, -1e30), axis=-1)

    return apply(f, to_tensor_like(x), op_name="softmax_mask_fuse_upper_triangle")


def identity_loss(x, reduction="none"):
    from ..tensor import math as _m
    from ..tensor._helpers import to_tensor_like

    x = to_tensor_like(x)
    if reduction in ("sum", 0):
        return _m.sum(x)
    if reduction in ("mean", 1):
        return _m.mean(x)
    if reduction in ("none", 2):
        return x
    raise ValueError(f"unsupported reduction: {reduction!r}")


class LookAhead:
    """Lookahead optimizer wrapper (reference incubate.LookAhead): every k
    steps, slow weights <- slow + alpha (fast - slow); fast <- slow."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._slow = {}
        self._steps = 0

    def __getattr__(self, name):
        return getattr(self.inner_optimizer, name)

    def step(self):
        import numpy as np

        import jax.numpy as jnp

        self.inner_optimizer.step()
        self._steps += 1
        if self._steps % self.k == 0:
            for p in self.inner_optimizer._parameter_list:
                slow = self._slow.get(id(p))
                if slow is None:
                    slow = np.asarray(p._value)
                slow = slow + self.alpha * (np.asarray(p._value, slow.dtype) - slow)
                self._slow[id(p)] = slow
                p._value = jnp.asarray(slow, p._value.dtype)

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        return None, None


class ModelAverage:
    """Running average of parameters with apply/restore (reference
    incubate.ModelAverage)."""

    def __init__(self, average_window_rate=0.15, parameters=None, min_average_window=10000,
                 max_average_window=10000000, name=None):
        self._params = list(parameters or [])
        self._sum = {}
        self._cnt = 0
        self._backup = {}

    def step(self):
        import numpy as np

        for p in self._params:
            cur = np.asarray(p._value, np.float32)
            self._sum[id(p)] = self._sum.get(id(p), 0.0) + cur
        self._cnt += 1

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def guard():
            import jax.numpy as jnp

            for p in self._params:
                self._backup[id(p)] = p._value
                if id(p) in self._sum and self._cnt:
                    p._value = jnp.asarray(self._sum[id(p)] / self._cnt, p._value.dtype)
            try:
                yield
            finally:
                if need_restore:
                    self.restore()

        return guard()

    def restore(self, executor=None):
        for p in self._params:
            if id(p) in self._backup:
                p._value = self._backup.pop(id(p))


def inference(function=None, cache_static_model=False, **kwargs):
    """parity: incubate.jit.inference — decorate a Layer (or its forward)
    so calls run through the compiled no-grad inference path. The
    reference swaps in its Paddle-Inference engine; here the equivalent is
    ``jit.to_static`` under ``no_grad`` (one XLA executable, weights traced
    as constants-by-reference). Extra reference knobs (trt/...) are
    accepted and ignored — XLA owns those decisions."""

    def wrap(fn_or_layer):
        from ..autograd import tape
        from ..jit import to_static

        from ..nn import Layer

        if isinstance(fn_or_layer, Layer):
            layer = fn_or_layer
            compiled = to_static(layer)

            def fwd(*args, **kw):
                with tape.no_grad():
                    return compiled(*args, **kw)

            layer.forward = fwd
            return layer

        compiled = to_static(fn_or_layer)

        def fwd(*args, **kw):
            with tape.no_grad():
                return compiled(*args, **kw)

        return fwd

    if function is not None:
        return wrap(function)
    return wrap


# expose the reference's ``paddle.incubate.jit`` namespace
class _JitNamespace:
    inference = staticmethod(inference)


jit = _JitNamespace()
