"""paddle_tpu.incubate (parity: python/paddle/incubate — fused ops + MoE)."""
from . import nn  # noqa: F401
from . import distributed  # noqa: F401
