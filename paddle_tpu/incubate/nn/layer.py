"""Fused layer classes (parity:
/root/reference/python/paddle/incubate/nn/layer/fused_transformer.py
FusedMultiHeadAttention:396 / FusedFeedForward / FusedTransformerEncoderLayer
/ FusedMultiTransformer:1431 / FusedBiasDropoutResidualLayerNorm:153,
fused_linear.py, fused_dropout_add.py, fused_ec_moe.py).

TPU-native stance: "fused" here is a guarantee of compilation into one XLA
program (the reference fuses into single CUDA kernels); the layer semantics
(normalize_before placement, dropout positions, cache contract) match the
reference so models port unchanged.
"""
from __future__ import annotations

import math
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ... import nn
from ...nn import functional as F
from ...ops.dispatch import apply
from ...tensor import manipulation as M
from ...tensor.tensor import Tensor

__all__ = [
    "FusedLinear", "FusedDropoutAdd", "FusedBiasDropoutResidualLayerNorm",
    "FusedMultiHeadAttention", "FusedFeedForward",
    "FusedTransformerEncoderLayer", "FusedMultiTransformer", "FusedEcMoe",
]


class FusedLinear(nn.Layer):
    """parity: fused_linear.py — Linear whose matmul+bias is one fused op."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None,
                 transpose_weight=False, name=None):
        super().__init__()
        self.transpose_weight = transpose_weight
        shape = [out_features, in_features] if transpose_weight else [in_features, out_features]
        self.weight = self.create_parameter(shape, attr=weight_attr)
        self.bias = self.create_parameter([out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        from ..nn.functional import fused_linear

        return fused_linear(x, self.weight, self.bias,
                            transpose_weight=self.transpose_weight)


class FusedDropoutAdd(nn.Layer):
    """parity: fused_dropout_add.py — y = dropout(x) + residual."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        return F.dropout(x, p=self.p, training=self.training, mode=self.mode) + y

    def extra_repr(self):
        return f"p={self.p}, mode={self.mode}"


class FusedBiasDropoutResidualLayerNorm(nn.Layer):
    """parity: fused_transformer.py:153 — ln(residual + dropout(x + bias))."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None, bias_attr=None,
                 epsilon=1e-5, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.dropout_rate = dropout_rate
        self._epsilon = epsilon
        self.linear_bias = self.create_parameter([embed_dim], is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], default_initializer=nn.initializer.Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], is_bias=True)

    def forward(self, x, residual):
        h = F.dropout(x + self.linear_bias, p=self.dropout_rate,
                      training=self.training)
        return F.layer_norm(residual + h, [self.embed_dim], self.ln_scale,
                            self.ln_bias, self._epsilon)


class FusedMultiHeadAttention(nn.Layer):
    """parity: fused_transformer.py:396 — pre/post-LN MHA with residual and
    dropouts in the reference's fused placement. Self-attention form."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5, attn_dropout_rate=0.5,
                 kdim=None, vdim=None, normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None, linear_weight_attr=None,
                 linear_bias_attr=None, pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, transpose_qkv_wb=False, name=None):
        super().__init__()
        assert embed_dim % num_heads == 0
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.normalize_before = normalize_before
        self._epsilon = epsilon
        # reference qkv_weight layout: [3, num_heads, head_dim, embed_dim]
        self.qkv_weight = self.create_parameter(
            [3, num_heads, self.head_dim, embed_dim], attr=qkv_weight_attr)
        self.qkv_bias = self.create_parameter(
            [3, num_heads, self.head_dim], attr=qkv_bias_attr, is_bias=True)
        self.linear_weight = self.create_parameter([embed_dim, embed_dim],
                                                   attr=linear_weight_attr)
        self.linear_bias = self.create_parameter([embed_dim], attr=linear_bias_attr,
                                                 is_bias=True)
        one = nn.initializer.Constant(1.0)
        self.pre_ln_scale = self.create_parameter([embed_dim], attr=pre_ln_scale_attr,
                                                  default_initializer=one)
        self.pre_ln_bias = self.create_parameter([embed_dim], attr=pre_ln_bias_attr,
                                                 is_bias=True)
        self.ln_scale = self.create_parameter([embed_dim], attr=ln_scale_attr,
                                              default_initializer=one)
        self.ln_bias = self.create_parameter([embed_dim], attr=ln_bias_attr,
                                             is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        if cache is not None:
            # generation decode: route through the functional, which appends
            # this step's K/V to the [2, B, H, S, D] cache
            from ..nn.functional import fused_multi_head_attention as fmha

            return fmha(query, self.qkv_weight, self.linear_weight,
                        pre_layer_norm=self.normalize_before,
                        pre_ln_scale=self.pre_ln_scale,
                        pre_ln_bias=self.pre_ln_bias,
                        ln_scale=self.ln_scale, ln_bias=self.ln_bias,
                        pre_ln_epsilon=self._epsilon,
                        qkv_bias=self.qkv_bias, linear_bias=self.linear_bias,
                        cache_kv=cache, attn_mask=attn_mask,
                        dropout_rate=self.dropout_rate,
                        attn_dropout_rate=self.attn_dropout_rate,
                        ln_epsilon=self._epsilon, training=self.training)
        residual = query
        x = query
        if self.normalize_before:
            x = F.layer_norm(x, [self.embed_dim], self.pre_ln_scale,
                             self.pre_ln_bias, self._epsilon)
        b, s = x.shape[0], x.shape[1]
        h, nh, hd = self.embed_dim, self.num_heads, self.head_dim

        def qkv_fn(xv, wv, bv):
            w = wv.reshape(3 * h, h)  # [3*nh*hd, embed]
            out = xv @ w.T + bv.reshape(3 * h)
            out = out.reshape(xv.shape[0], xv.shape[1], 3, nh, hd)
            return out[:, :, 0], out[:, :, 1], out[:, :, 2]

        q, k, v = apply(lambda xv, wv, bv: tuple(qkv_fn(xv, wv, bv)),
                        x, self.qkv_weight, self.qkv_bias,
                        op_name="fused_qkv", n_outs=3)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.attn_dropout_rate if self.training else 0.0,
            is_causal=False)
        out = M.reshape(out, [b, s, h])
        out = F.linear(out, self.linear_weight, self.linear_bias)
        out = F.dropout(out, p=self.dropout_rate, training=self.training)
        out = residual + out
        if not self.normalize_before:
            out = F.layer_norm(out, [self.embed_dim], self.ln_scale, self.ln_bias,
                               self._epsilon)
        return out


class FusedFeedForward(nn.Layer):
    """parity: fused_transformer.py FusedFeedForward."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1, epsilon=1e-5,
                 activation="relu", act_dropout_rate=None, normalize_before=False,
                 linear1_weight_attr=None, linear1_bias_attr=None,
                 linear2_weight_attr=None, linear2_bias_attr=None,
                 ln1_scale_attr=None, ln1_bias_attr=None,
                 ln2_scale_attr=None, ln2_bias_attr=None, nranks=1, ring_id=-1,
                 name=None):
        super().__init__()
        self.d_model = d_model
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = dropout_rate if act_dropout_rate is None else act_dropout_rate
        self.activation = activation
        self._epsilon = epsilon
        self.linear1_weight = self.create_parameter([d_model, dim_feedforward],
                                                    attr=linear1_weight_attr)
        self.linear1_bias = self.create_parameter([dim_feedforward],
                                                  attr=linear1_bias_attr, is_bias=True)
        self.linear2_weight = self.create_parameter([dim_feedforward, d_model],
                                                    attr=linear2_weight_attr)
        self.linear2_bias = self.create_parameter([d_model], attr=linear2_bias_attr,
                                                  is_bias=True)
        one = nn.initializer.Constant(1.0)
        self._ln1_scale = self.create_parameter([d_model], attr=ln1_scale_attr,
                                                default_initializer=one)
        self._ln1_bias = self.create_parameter([d_model], attr=ln1_bias_attr, is_bias=True)
        self._ln2_scale = self.create_parameter([d_model], attr=ln2_scale_attr,
                                                default_initializer=one)
        self._ln2_bias = self.create_parameter([d_model], attr=ln2_bias_attr, is_bias=True)

    def forward(self, src, cache=None):
        residual = src
        if self.normalize_before:
            src = F.layer_norm(src, [self.d_model], self._ln1_scale, self._ln1_bias,
                               self._epsilon)
        act = getattr(F, self.activation)
        src = act(F.linear(src, self.linear1_weight, self.linear1_bias))
        src = F.dropout(src, p=self.act_dropout_rate, training=self.training)
        src = F.linear(src, self.linear2_weight, self.linear2_bias)
        src = F.dropout(src, p=self.dropout_rate, training=self.training)
        out = residual + src
        if not self.normalize_before:
            out = F.layer_norm(out, [self.d_model], self._ln2_scale, self._ln2_bias,
                               self._epsilon)
        return out


class FusedTransformerEncoderLayer(nn.Layer):
    """parity: fused_transformer.py FusedTransformerEncoderLayer."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None, act_dropout_rate=None,
                 normalize_before=False, name=None):
        super().__init__()
        attn_dropout_rate = dropout_rate if attn_dropout_rate is None else attn_dropout_rate
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate, normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask)
        return self.ffn(out)


class FusedMultiTransformer(nn.Layer):
    """parity: fused_transformer.py:994 — N pre-LN decoder layers as
    per-layer weight LISTS over the fused_multi_transformer functional,
    including the reference's KV-cache generation contract (prefill writes
    `caches[i]` [2, B, H, max_seq, D] in place; `time_step` switches to
    single-token decode against the cache)."""

    def __init__(self, embed_dim, num_heads, dim_feedforward, dropout_rate=0.0,
                 activation="gelu", normalize_before=True,
                 ln_scale_attrs=None, ln_bias_attrs=None,
                 qkv_weight_attrs=None, qkv_bias_attrs=None,
                 linear_weight_attrs=None, linear_bias_attrs=None,
                 ffn_ln_scale_attrs=None, ffn_ln_bias_attrs=None,
                 ffn1_weight_attrs=None, ffn1_bias_attrs=None,
                 ffn2_weight_attrs=None, ffn2_bias_attrs=None,
                 epsilon=1e-5, residual_alpha=1.0, num_layers=-1,
                 nranks=1, trans_qkvw=True, ring_id=-1, norm_type="layernorm",
                 use_neox_rotary_style=False, gqa_group_size=-1, name=None):
        super().__init__()
        assert embed_dim % num_heads == 0
        if num_layers == -1:
            num_layers = (len(qkv_weight_attrs)
                          if isinstance(qkv_weight_attrs, (list, tuple)) else 1)
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout_rate = dropout_rate
        self.activation = activation
        self.normalize_before = normalize_before
        self._epsilon = epsilon
        self._residual_alpha = residual_alpha
        self._trans_qkvw = trans_qkvw
        self._ring_id = ring_id
        self._norm_type = norm_type
        self._use_neox_rotary_style = use_neox_rotary_style
        self._gqa_group_size = gqa_group_size
        self.num_layers = num_layers

        def pick(attrs, i):
            return attrs[i] if isinstance(attrs, (list, tuple)) else attrs

        one = nn.initializer.Constant(1.0)
        (self.ln_scales, self.ln_biases, self.qkv_weights, self.qkv_biases,
         self.linear_weights, self.linear_biases, self.ffn_ln_scales,
         self.ffn_ln_biases, self.ffn1_weights, self.ffn1_biases,
         self.ffn2_weights, self.ffn2_biases) = ([] for _ in range(12))
        nh, hd, E = num_heads, self.head_dim, embed_dim
        for i in range(num_layers):
            mk = self.create_parameter
            self.ln_scales.append(mk([E], attr=pick(ln_scale_attrs, i),
                                     default_initializer=one))
            self.ln_biases.append(mk([E], attr=pick(ln_bias_attrs, i), is_bias=True))
            self.qkv_weights.append(mk([3, nh, hd, E],
                                       attr=pick(qkv_weight_attrs, i)))
            self.qkv_biases.append(mk([3, nh, hd], attr=pick(qkv_bias_attrs, i),
                                      is_bias=True))
            self.linear_weights.append(mk([E, E],
                                          attr=pick(linear_weight_attrs, i)))
            self.linear_biases.append(mk([E], attr=pick(linear_bias_attrs, i),
                                         is_bias=True))
            self.ffn_ln_scales.append(mk([E], attr=pick(ffn_ln_scale_attrs, i),
                                         default_initializer=one))
            self.ffn_ln_biases.append(mk([E], attr=pick(ffn_ln_bias_attrs, i),
                                         is_bias=True))
            self.ffn1_weights.append(mk([E, dim_feedforward],
                                        attr=pick(ffn1_weight_attrs, i)))
            self.ffn1_biases.append(mk([dim_feedforward],
                                       attr=pick(ffn1_bias_attrs, i), is_bias=True))
            self.ffn2_weights.append(mk([dim_feedforward, E],
                                        attr=pick(ffn2_weight_attrs, i)))
            self.ffn2_biases.append(mk([E], attr=pick(ffn2_bias_attrs, i),
                                       is_bias=True))
            # register under structured names (create_parameter already adds
            # them to the layer; lists keep the reference's attribute API)

    def forward(self, src, attn_mask=None, caches=None, pre_caches=None,
                rotary_embs=None, rotary_emb_dims=0, beam_offset=None,
                seq_lens=None, time_step=None):
        from ..nn.functional import fused_multi_transformer as fmt

        out = fmt(
            src, self.ln_scales, self.ln_biases, self.qkv_weights,
            self.qkv_biases, self.linear_weights, self.linear_biases,
            self.ffn_ln_scales, self.ffn_ln_biases, self.ffn1_weights,
            self.ffn1_biases, self.ffn2_weights, self.ffn2_biases,
            pre_layer_norm=self.normalize_before, epsilon=self._epsilon,
            residual_alpha=self._residual_alpha, cache_kvs=caches,
            beam_offset=beam_offset, pre_caches=pre_caches,
            rotary_embs=rotary_embs, time_step=time_step, seq_lens=seq_lens,
            attn_mask=attn_mask, dropout_rate=self.dropout_rate,
            rotary_emb_dims=rotary_emb_dims, activation=self.activation,
            training=self.training, trans_qkvw=self._trans_qkvw,
            ring_id=self._ring_id, norm_type=self._norm_type,
            use_neox_rotary_style=self._use_neox_rotary_style,
            gqa_group_size=self._gqa_group_size, name=None)
        return out


class FusedEcMoe(nn.Layer):
    """parity: fused_ec_moe.py — expert-choice MoE: experts pick their top-C
    tokens from gate scores (capacity = S*cap_factor/E), bmm expert FFNs."""

    def __init__(self, hidden_size, inter_size, num_experts, act_type="gelu",
                 weight_attr=None, bias_attr=None):
        super().__init__()
        if act_type not in ("gelu", "relu"):
            raise ValueError("only gelu/relu supported (reference contract)")
        self.act_type = act_type
        self.num_experts = num_experts
        self.bmm_weight0 = self.create_parameter(
            [num_experts, hidden_size, inter_size], attr=weight_attr)
        self.bmm_bias0 = self.create_parameter([num_experts, 1, inter_size],
                                               attr=bias_attr, is_bias=True)
        self.bmm_weight1 = self.create_parameter(
            [num_experts, inter_size, hidden_size], attr=weight_attr)
        self.bmm_bias1 = self.create_parameter([num_experts, 1, hidden_size],
                                               attr=bias_attr, is_bias=True)

    def forward(self, x, gate_logits):
        """x [B, S, H]; gate_logits [B, S, E] -> [B, S, H]."""
        E = self.num_experts
        act = jax.nn.gelu if self.act_type == "gelu" else jax.nn.relu

        def f(xv, gv, w0, b0, w1, b1):
            B, S, H = xv.shape
            C = max(S * 2 // E, 1)  # expert capacity (cap factor 2)
            scores = jax.nn.softmax(gv.astype(jnp.float32), axis=-1)  # [B,S,E]
            # expert choice: each expert takes its top-C tokens
            topv, topi = jax.lax.top_k(jnp.swapaxes(scores, 1, 2), C)  # [B,E,C]
            # batched gather straight to [B,E,C,H] (no E-fold replication of x)
            picked = xv[jnp.arange(B)[:, None, None], topi]
            hdn = act(jnp.einsum("bech,ehi->beci", picked, w0) + b0[None])
            out_e = jnp.einsum("beci,eih->bech", hdn, w1) + b1[None]
            out_e = out_e * topv[..., None].astype(out_e.dtype)
            # scatter-add back to token positions
            out = jnp.zeros_like(xv)
            bidx = jnp.arange(B)[:, None, None]
            out = out.at[bidx, topi].add(out_e.astype(xv.dtype))
            return out

        return apply(f, x, gate_logits, self.bmm_weight0, self.bmm_bias0,
                     self.bmm_weight1, self.bmm_bias1, op_name="fused_ec_moe")
