"""Fused-op functional APIs (parity: /root/reference/python/paddle/incubate/nn/functional/ —
fused_rms_norm.py, fused_rotary_position_embedding.py, swiglu.py,
fused_dropout_add.py, fused_linear.py ...).

TPU-native: "fused" means "expressed so XLA/Pallas fuses it" — these share
implementations with the core functional ops and exist for API parity with
reference model code.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....nn import functional as F
from ....ops.dispatch import apply
from ....tensor import manipulation as M
from ....tensor._helpers import to_tensor_like
from ....tensor.tensor import Tensor

__all__ = [
    "fused_rms_norm", "fused_layer_norm", "fused_rotary_position_embedding", "swiglu",
    "fused_dot_product_attention", "blha_get_max_len", "masked_multihead_attention",
    "fused_gate_attention", "block_multihead_attention",
    "fused_linear", "fused_bias_act", "fused_dropout_add", "fused_multi_head_attention",
    "fused_matmul_bias", "fused_linear_activation",
    "fused_bias_dropout_residual_layer_norm", "fused_feedforward", "fused_moe",
    "fused_ec_moe", "fused_multi_transformer",
    "variable_length_memory_efficient_attention",
]


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6, begin_norm_axis=-1,
                   bias=None, residual=None, quant_scale=-1, **kw):
    """Pallas-fused RMSNorm (+residual): one HBM pass for add+norm on TPU
    (ops/pallas/fused_norm.py); jnp fallback elsewhere. Returns
    (out,) or (out, residual_out) matching the reference signature."""
    from ....ops.pallas.fused_norm import rms_norm_fused, rms_norm_residual_fused

    if quant_scale > 0:
        raise NotImplementedError(
            "fused_rms_norm: quantized output (quant_scale>0) is not implemented")
    x = to_tensor_like(x)
    if bias is not None:
        # reference semantics: the pre-norm stream is x + bias (+ residual)
        x = x + to_tensor_like(bias)
    norm_weight = to_tensor_like(norm_weight)
    if residual is not None:
        residual = to_tensor_like(residual)
        outs = apply(
            lambda xv, rv, wv: list(rms_norm_residual_fused(xv, rv, wv, epsilon)),
            x, residual, norm_weight, op_name="fused_rms_norm_residual")
        out, res_out = outs[0], outs[1]
        if norm_bias is not None:
            out = out + to_tensor_like(norm_bias)
        return (out, res_out)
    out = apply(lambda xv, wv: rms_norm_fused(xv, wv, epsilon), x, norm_weight,
                op_name="fused_rms_norm")
    if norm_bias is not None:
        out = out + to_tensor_like(norm_bias)
    return (out,)


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5, begin_norm_axis=1, **kw):
    shape = x.shape[begin_norm_axis:]
    return (F.layer_norm(x, shape, norm_weight, norm_bias, epsilon),)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None, position_ids=None,
                                    use_neox_rotary_style=True, time_major=False, rotary_emb_base=10000.0):
    """parity: fused_rotary_position_embedding — q/k/v [B, S, H, D]."""
    q = to_tensor_like(q)
    outs = []

    def rope_one(x, c, s):
        # c/s: [1, S, 1, D/2] or [S, D/2]
        if c.ndim == 2:
            c = c[None, :, None, :]
            s = s[None, :, None, :]
        if use_neox_rotary_style:
            x1, x2 = jnp.split(x, 2, axis=-1)
            return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1).astype(x.dtype)
        x1 = x[..., 0::2]
        x2 = x[..., 1::2]
        ro = jnp.stack([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
        return ro.reshape(x.shape).astype(x.dtype)

    if sin is None or cos is None:
        S, D = q.shape[1], q.shape[-1]
        inv = 1.0 / (rotary_emb_base ** (jnp.arange(0, D, 2, dtype=jnp.float32) / D))
        t = jnp.arange(S, dtype=jnp.float32)
        fr = jnp.outer(t, inv)
        cos_v, sin_v = jnp.cos(fr), jnp.sin(fr)
    else:
        cos_v = cos._value if isinstance(cos, Tensor) else jnp.asarray(cos)
        sin_v = sin._value if isinstance(sin, Tensor) else jnp.asarray(sin)
        if cos_v.ndim == 4:
            cos_v = cos_v[0, :, 0, :]
            sin_v = sin_v[0, :, 0, :]
        if cos_v.shape[-1] == q.shape[-1]:  # full-dim cos caches store doubled
            cos_v = cos_v[..., : cos_v.shape[-1] // 2]
            sin_v = sin_v[..., : sin_v.shape[-1] // 2]

    for t_in in (q, k, v):
        if t_in is None:
            outs.append(None)
            continue
        t_in = to_tensor_like(t_in)
        outs.append(apply(lambda x: rope_one(x, cos_v, sin_v), t_in, op_name="fused_rope"))
    return tuple(outs)


def swiglu(x, y=None, name=None):
    """parity: incubate/nn/functional/swiglu.py — silu(x) * y (y defaults to
    second half of x). Single-HBM-pass Pallas kernel on TPU."""
    from ....ops.pallas.fused_ops import swiglu_fused

    x = to_tensor_like(x)
    if y is None:
        def f(v):
            a, b = jnp.split(v, 2, axis=-1)
            return swiglu_fused(a, b)

        return apply(f, x, op_name="swiglu")
    y = to_tensor_like(y)
    return apply(lambda a, b: swiglu_fused(a, b), x, y, op_name="swiglu")


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    if transpose_weight:
        from ....tensor.linalg import transpose

        weight = transpose(to_tensor_like(weight), [1, 0])
    return F.linear(x, weight, bias)


def fused_bias_act(x, bias=None, act_method="gelu", **kw):
    if bias is not None:
        x = to_tensor_like(x) + to_tensor_like(bias)
    return getattr(F, act_method)(x)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train", name=None):
    return F.dropout(x, p, training=training, mode=mode) + to_tensor_like(y)


def _tp_group_active() -> bool:
    """True when a size>1 tensor-parallel (mp) group exists — the only
    case where the reference's ring_id >= 0 all-reduce changes results
    (over a 1-rank group it is the identity, so skipping it is exact)."""
    try:
        from ....distributed.topology import get_hybrid_communicate_group

        hcg = get_hybrid_communicate_group()
        return hcg is not None and hcg.axis_size("mp") > 1
    except Exception:
        return False


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None, ln_bias=None,
                               pre_ln_epsilon=1e-05, qkv_bias=None,
                               linear_bias=None, cache_kv=None, attn_mask=None,
                               dropout_rate=0.5, attn_dropout_rate=0.5,
                               ln_epsilon=1e-05, training=True,
                               mode="upscale_in_train", ring_id=-1,
                               add_residual=True, num_heads=-1,
                               transpose_qkv_wb=False, name=None):
    """Fused self-attention block (parity:
    /root/reference/python/paddle/incubate/nn/functional/fused_transformer.py:502):
    [pre-]LN -> qkv matmul(+bias) -> scaled attention(+mask, dropout) ->
    output projection -> dropout(+residual) [-> post-LN]. With ``cache_kv``
    [2, B, H, S, D], this step's K/V are appended (generation decode).
    One XLA fusion chain on TPU (the reference fuses it into one kernel)."""
    if ring_id is not None and ring_id >= 0 and _tp_group_active():
        # the reference runs a tensor-parallel all-reduce after the output
        # projection for ring_id >= 0; silently skipping it would return
        # partial sums on a TP mesh (with no mp group, or mp=1, skipping
        # IS the reference semantics — an all-reduce over one rank)
        raise NotImplementedError(
            "fused_multi_head_attention: ring_id >= 0 with an active "
            "tensor-parallel group (mp > 1) is not implemented — the "
            "reference all-reduces the output projection over the TP "
            "ring; use the distributed.fleet TP layers, or pass "
            "ring_id=-1 for the single-group path")
    x = to_tensor_like(x)
    qkvw = to_tensor_like(qkv_weight)
    B, S, E = x.shape
    if transpose_qkv_wb:
        if num_heads <= 0:
            raise ValueError("transpose_qkv_wb=True needs num_heads")
        nh = num_heads
        hd = E // nh
    else:
        nh, hd = qkvw.shape[1], qkvw.shape[2]

    residual = x
    h = x
    if pre_layer_norm:
        h = F.layer_norm(h, [E], pre_ln_scale, pre_ln_bias, pre_ln_epsilon)

    qb = to_tensor_like(qkv_bias) if qkv_bias is not None else None
    args = [h, qkvw] + ([qb] if qb is not None else [])

    def qkv_fn(hv, wv, *b):
        if transpose_qkv_wb:
            o = hv @ wv  # [B, S, 3E]
            if b:
                o = o + b[0]
            o = o.reshape(B, S, 3, nh, hd)
        else:
            o = jnp.einsum("bse,xhde->bsxhd", hv, wv)
            if b:
                o = o + b[0][None, None]
        return o[:, :, 0], o[:, :, 1], o[:, :, 2]

    q, k, v = apply(lambda *a: tuple(qkv_fn(*a)), *args,
                    op_name="fused_mha_qkv", n_outs=3)

    new_cache = None
    if cache_kv is not None:
        cache_t = to_tensor_like(cache_kv)

        def cat_cache(kv, vv, cv):
            ck = jnp.transpose(cv[0], (0, 2, 1, 3))  # [B, S0, H, D]
            cvv = jnp.transpose(cv[1], (0, 2, 1, 3))
            kk = jnp.concatenate([ck.astype(kv.dtype), kv], axis=1)
            vn = jnp.concatenate([cvv.astype(vv.dtype), vv], axis=1)
            nc = jnp.stack([jnp.transpose(kk, (0, 2, 1, 3)),
                            jnp.transpose(vn, (0, 2, 1, 3))])
            return kk, vn, nc.astype(cv.dtype)

        k, v, new_cache = apply(lambda *a: tuple(cat_cache(*a)), k, v, cache_t,
                                op_name="fused_mha_cache", n_outs=3)

    out = F.scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask,
        dropout_p=attn_dropout_rate if training else 0.0,
        is_causal=False, training=training)
    out = M.reshape(out, [B, S, nh * hd])
    out = F.linear(out, linear_weight, linear_bias)
    out = F.dropout(out, p=dropout_rate, training=training, mode=mode)
    if add_residual:
        out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, [E], ln_scale, ln_bias, ln_epsilon)
    if cache_kv is not None:
        return out, new_cache
    return out


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False, name=None):
    """matmul+bias in one XLA fusion (reference cublasLt epilogue kernel)."""
    from ....tensor.linalg import matmul

    out = matmul(x, y, transpose_x=transpose_x, transpose_y=transpose_y)
    if bias is not None:
        out = out + to_tensor_like(bias)
    return out


def fused_linear_activation(x, y, bias=None, trans_x=False, trans_y=False,
                            activation="gelu", name=None):
    out = fused_matmul_bias(x, y, bias, trans_x, trans_y)
    if activation in (None, "", "none", "identity"):
        return out
    return getattr(F, activation)(out)


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.5, ln_epsilon=1e-5,
                                           training=True, mode="upscale_in_train",
                                           name=None):
    """(x + bias) -> dropout -> + residual -> layer_norm, one fusion chain
    (reference fused_bias_dropout_residual_layer_norm op)."""
    out = to_tensor_like(x)
    if bias is not None:
        out = out + to_tensor_like(bias)
    out = F.dropout(out, p=dropout_rate, training=training, mode=mode)
    out = out + to_tensor_like(residual)
    h = out.shape[-1]
    return F.layer_norm(out, [h], ln_scale, ln_bias, ln_epsilon)


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu", ln1_epsilon=1e-5,
                      ln2_epsilon=1e-5, pre_layer_norm=False, training=True,
                      mode="upscale_in_train", name=None):
    """Transformer FFN block as one compiled chain (reference
    fused_feedforward op): [pre-]LN -> linear1 -> act -> dropout -> linear2
    -> dropout -> residual [-> post-LN]."""
    x = to_tensor_like(x)
    h = x.shape[-1]
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, [h], ln1_scale, ln1_bias, ln1_epsilon)
    out = fused_matmul_bias(x, linear1_weight, linear1_bias)
    out = getattr(F, activation)(out)
    out = F.dropout(out, p=dropout1_rate, training=training, mode=mode)
    out = fused_matmul_bias(out, linear2_weight, linear2_bias)
    out = F.dropout(out, p=dropout2_rate, training=training, mode=mode)
    out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, [h], ln2_scale, ln2_bias, ln2_epsilon)
    return out


def fused_moe(x, gate_weight, expert_weights1, expert_biases1, expert_weights2,
              expert_biases2, quant_method="None", moe_topk=2, norm_topk_prob=True,
              group_moe=False, name=None, act_type="gelu"):
    """Dense-dispatch MoE FFN (reference fused_moe op; GShard-style einsum
    dispatch — every expert computes every token, combine weights zero the
    non-routed ones; the XLA/TPU-idiomatic formulation)."""
    import jax
    import jax.numpy as jnp

    from ....ops.dispatch import apply

    x = to_tensor_like(x)
    args = [x, to_tensor_like(gate_weight),
            to_tensor_like(expert_weights1), to_tensor_like(expert_weights2)]
    has_b1 = expert_biases1 is not None
    has_b2 = expert_biases2 is not None
    if has_b1:
        args.append(to_tensor_like(expert_biases1))
    if has_b2:
        args.append(to_tensor_like(expert_biases2))

    def f(xv, gw, w1, w2, *bs):
        b1 = bs[0] if has_b1 else None
        b2 = bs[-1] if has_b2 else None
        orig = xv.shape
        xt = xv.reshape(-1, orig[-1])  # [N, H]
        logits = xt @ gw  # [N, E]
        probs = jax.nn.softmax(logits, axis=-1)
        topv, topi = jax.lax.top_k(probs, moe_topk)
        if norm_topk_prob:
            topv = topv / jnp.sum(topv, axis=-1, keepdims=True)
        combine = jnp.zeros_like(probs)
        combine = jax.vmap(lambda c, i, v: c.at[i].set(v))(combine, topi, topv)  # [N, E]
        h = jnp.einsum("nh,ehf->enf", xt, w1)
        if b1 is not None:
            h = h + b1[:, None, :]
        h = getattr(jax.nn, act_type)(h)
        y = jnp.einsum("enf,efh->enh", h, w2)
        if b2 is not None:
            y = y + b2[:, None, :]
        out = jnp.einsum("enh,ne->nh", y, combine)
        return out.reshape(orig)

    return apply(f, *args, op_name="fused_moe")


def fused_ec_moe(x, gate, bmm0_weight, bmm0_bias, bmm1_weight, bmm1_bias,
                 act_type="gelu", name=None):
    """Expert-choice style fused MoE (reference fused_ec_moe) — mapped onto
    the same dense-dispatch path."""
    return fused_moe(x, gate, bmm0_weight, bmm0_bias, bmm1_weight, bmm1_bias,
                     act_type=act_type)


def fused_multi_transformer(
    x, ln_scales, ln_biases, qkv_weights, qkv_biases, linear_weights,
    linear_biases, ffn_ln_scales, ffn_ln_biases, ffn1_weights, ffn1_biases,
    ffn2_weights, ffn2_biases, pre_layer_norm=True, epsilon=1e-5,
    residual_alpha=1.0, cache_kvs=None, beam_offset=None, pre_caches=None,
    rotary_embs=None, time_step=None, seq_lens=None, attn_mask=None,
    dropout_rate=0.0, rotary_emb_dims=0, activation="gelu", training=False,
    mode="upscale_in_train", trans_qkvw=True, ring_id=-1,
    norm_type="layernorm", use_neox_rotary_style=False, gqa_group_size=-1,
    name=None):
    """N pre/post-LN decoder layers with KV-cache generation support
    (parity: /root/reference/python/paddle/incubate/nn/functional/fused_multi_transformer.py,
    kernel fused_multi_transformer_op.cu).

    TPU-native: the whole stack is a chain of jnp ops one ``jit``/``to_static``
    compiles into a single XLA program — the fusion the reference gets from
    its mega-kernel. Two phases, the reference's cache contract:
    - prefill (``time_step is None``): causal attention over ``src``
      [B, S, E]; ``cache_kvs[i]`` [2, B, H, max_seq, D] rows [0, S) are
      written in place.
    - decode (``time_step`` scalar): one token per sequence attends the
      cache at positions [0, time_step], writes row ``time_step``.
    Supports rope (``rotary_embs`` [2, B, 1|S, 1, D/2] cos/sin,
    interleaved or neox), ``pre_caches`` prefixes, additive ``attn_mask``,
    layernorm/rmsnorm, residual_alpha, MHA (for GQA serving use the paged
    ``block_multihead_attention`` path). Returns out, or (out, cache_kvs)
    in-place-updated when caches are passed.
    """
    if ring_id is not None and ring_id >= 0 and _tp_group_active():
        # same contract as fused_multi_head_attention: the reference
        # all-reduces the out-projection and ffn2 outputs over the TP ring
        raise NotImplementedError(
            "fused_multi_transformer: ring_id >= 0 with an active "
            "tensor-parallel group (mp > 1) is not implemented — use the "
            "distributed.fleet TP layers, or pass ring_id=-1 for the "
            "single-group path")
    if gqa_group_size > 0:
        raise NotImplementedError(
            "fused_multi_transformer: use block_multihead_attention / the "
            "inference serving engine for GQA serving")
    if beam_offset is not None:
        raise NotImplementedError(
            "fused_multi_transformer: beam_offset (beam-search cache "
            "reordering) is not supported")

    x = to_tensor_like(x)
    num_layers = len(qkv_weights)
    decode = time_step is not None
    B, S = x.shape[0], x.shape[1]

    def _norm(h, scale, bias):
        if norm_type == "rmsnorm":
            out = fused_rms_norm(h, scale, norm_bias=bias, epsilon=epsilon)[0]
            return out
        dim = h.shape[-1]
        return F.layer_norm(h, [dim], scale, bias, epsilon)

    if decode:
        ts = to_tensor_like(time_step)
        step = jnp.asarray(ts._value).reshape(()).astype(jnp.int32)

    def _rope_pair(qv, kv_, rot, pos0):
        # rot [2, B, Sr, 1, D/2]; qv/kv_ [B, S, H, D]; pos0: int offset
        from ....ops.paged_attention import rope_rotate

        cos = rot[0, :, :, 0, :]
        sin = rot[1, :, :, 0, :]
        Sq = qv.shape[1]
        cos = jax.lax.dynamic_slice_in_dim(cos, pos0, Sq, axis=1)[:, :, None, :]
        sin = jax.lax.dynamic_slice_in_dim(sin, pos0, Sq, axis=1)[:, :, None, :]
        return (rope_rotate(qv, cos, sin, use_neox_rotary_style),
                rope_rotate(kv_, cos, sin, use_neox_rotary_style))

    out = x
    new_caches = []
    for i in range(num_layers):
        residual = out
        h = _norm(out, ln_scales[i], ln_biases[i] if ln_biases else None) \
            if pre_layer_norm else out
        qkvw = to_tensor_like(qkv_weights[i])
        if not trans_qkvw:
            raise NotImplementedError(
                "fused_multi_transformer: trans_qkvw=False layout not "
                "supported; pass [3, num_head, head_dim, embed] weights")
        nh, hd = qkvw.shape[1], qkvw.shape[2]  # [3, nh, hd, E]
        qb = to_tensor_like(qkv_biases[i]) if qkv_biases else None
        cache = to_tensor_like(cache_kvs[i]) if cache_kvs is not None else None
        pre_c = (to_tensor_like(pre_caches[i])
                 if pre_caches is not None else None)
        rot = to_tensor_like(rotary_embs) if rotary_embs is not None else None

        qkv_args = [h, qkvw] + ([qb] if qb is not None else [])

        def qkv_fn(hv, wv, *b):
            o = jnp.einsum("bse,xhde->bsxhd", hv, wv)
            if b:
                o = o + b[0][None, None]
            return o[:, :, 0], o[:, :, 1], o[:, :, 2]

        q, k, v = apply(lambda *a: tuple(qkv_fn(*a)), *qkv_args,
                        op_name="fmt_qkv", n_outs=3)

        if not decode:
            # ----- prefill: causal attention, write cache rows [0, S)
            mask_t = to_tensor_like(attn_mask) if attn_mask is not None else None
            sl = to_tensor_like(seq_lens) if seq_lens is not None else None
            args = [q, k, v] + ([rot] if rot is not None else []) \
                + ([mask_t] if mask_t is not None else []) \
                + ([pre_c] if pre_c is not None else []) \
                + ([cache] if cache is not None else []) \
                + ([sl] if sl is not None else [])

            def attn_fn(qv, kv_, vv, *rest):
                rest = list(rest)
                rt = rest.pop(0) if rot is not None else None
                mv = rest.pop(0) if mask_t is not None else None
                pc = rest.pop(0) if pre_c is not None else None
                cv = rest.pop(0) if cache is not None else None
                slv = rest.pop(0) if sl is not None else None
                if rt is not None and rotary_emb_dims > 0:
                    qv, kv_ = _rope_pair(qv, kv_, rt, 0)
                if slv is not None:
                    # per-sequence true lengths: padded tail tokens neither
                    # attend nor get attended, and their K/V rows are zeroed
                    # before the cache write
                    live = (jnp.arange(S)[None, :]
                            < slv.reshape(-1)[:, None])  # [B, S]
                    kv_ = jnp.where(live[:, :, None, None], kv_, 0)
                    vv = jnp.where(live[:, :, None, None], vv, 0)
                keys, vals = kv_, vv
                plen = 0
                if pc is not None:  # [2, B, H, P, D]
                    plen = pc.shape[3]
                    keys = jnp.concatenate(
                        [jnp.transpose(pc[0], (0, 2, 1, 3)), keys], axis=1)
                    vals = jnp.concatenate(
                        [jnp.transpose(pc[1], (0, 2, 1, 3)), vals], axis=1)
                lg = jnp.einsum("bshd,blhd->bhsl", qv.astype(jnp.float32),
                                keys.astype(jnp.float32)) / (hd ** 0.5)
                kpos = jnp.arange(lg.shape[-1]) - plen
                viz = kpos[None, :] <= jnp.arange(S)[:, None]
                lg = jnp.where(viz[None, None], lg, -1e30)
                if slv is not None:
                    kl = (kpos[None, :] < slv.reshape(-1)[:, None]) | (
                        kpos[None, :] < 0)  # pre-cache cols always live
                    lg = jnp.where(kl[:, None, None, :], lg, -1e30)
                if mv is not None:
                    m = mv.astype(jnp.float32)
                    need = lg.shape[-1]
                    if m.shape[-1] < need:
                        # pre-cache columns sit left of the mask: pad with 0
                        # (prefix always attendable)
                        m = jnp.pad(m, ((0, 0),) * (m.ndim - 1)
                                    + ((need - m.shape[-1], 0),))
                    else:
                        m = m[..., -need:]
                    lg = lg + m[..., -lg.shape[-2]:, :]
                p = jax.nn.softmax(lg, axis=-1)
                o = jnp.einsum("bhsl,blhd->bshd", p, vals.astype(jnp.float32))
                outs = [o.astype(qv.dtype)]
                if cv is not None:
                    kc = jnp.transpose(kv_, (0, 2, 1, 3))  # [B, H, S, D]
                    vc = jnp.transpose(vv, (0, 2, 1, 3))
                    ncv = jax.lax.dynamic_update_slice(
                        cv, jnp.stack([kc, vc])[:, :, :, :cv.shape[3]].astype(cv.dtype),
                        (0, 0, 0, 0, 0))
                    outs.append(ncv)
                return tuple(outs)

            n_outs = 2 if cache is not None else 1
            res = apply(lambda *a: attn_fn(*a), *args,
                        op_name="fmt_prefill", n_outs=n_outs)
            if cache is not None:
                attn_out, new_cache = res
                cache._value = new_cache._value
                new_caches.append(cache)
            else:
                attn_out = res if isinstance(res, Tensor) else res[0]
        else:
            # ----- decode: one token per sequence against the cache
            if cache is None:
                raise ValueError("decode (time_step) needs cache_kvs")
            sl = (to_tensor_like(seq_lens) if seq_lens is not None else None)
            mask_t = to_tensor_like(attn_mask) if attn_mask is not None else None
            args = [q, k, v, cache] + ([rot] if rot is not None else []) \
                + ([pre_c] if pre_c is not None else []) \
                + ([sl] if sl is not None else []) \
                + ([mask_t] if mask_t is not None else [])

            def dec_fn(qv, kv_, vv, cv, *rest):
                rest = list(rest)
                rt = rest.pop(0) if rot is not None else None
                pc = rest.pop(0) if pre_c is not None else None
                slv = rest.pop(0) if sl is not None else None
                mv = rest.pop(0) if mask_t is not None else None
                pos = (slv.reshape(-1).astype(jnp.int32) if slv is not None
                       else jnp.full((B,), step, jnp.int32))
                if rt is not None and rotary_emb_dims > 0:
                    # decode rope row: absolute position == write position;
                    # rot may carry 1 row (pre-sliced) or the full table
                    if rt.shape[2] == 1:
                        qv, kv_ = _rope_pair(qv, kv_, rt, 0)
                    else:
                        from ....ops.paged_attention import rope_rotate

                        cosb = rt[0, :, :, 0, :][jnp.arange(B), pos][:, None, None, :]
                        sinb = rt[1, :, :, 0, :][jnp.arange(B), pos][:, None, None, :]
                        qv = rope_rotate(qv, cosb, sinb, use_neox_rotary_style)
                        kv_ = rope_rotate(kv_, cosb, sinb, use_neox_rotary_style)
                bidx = jnp.arange(B)
                kc = cv[0].at[bidx, :, pos].set(
                    jnp.transpose(kv_, (0, 2, 1, 3))[bidx, :, 0].astype(cv.dtype))
                vc = cv[1].at[bidx, :, pos].set(
                    jnp.transpose(vv, (0, 2, 1, 3))[bidx, :, 0].astype(cv.dtype))
                Smax = cv.shape[3]
                keys, vals = kc, vc  # [B, H, Smax, D]
                plen = 0
                if pc is not None:
                    plen = pc.shape[3]
                    keys = jnp.concatenate([pc[0].astype(kc.dtype), keys], axis=2)
                    vals = jnp.concatenate([pc[1].astype(vc.dtype), vals], axis=2)
                lg = jnp.einsum("bhd,bhld->bhl",
                                qv[:, 0].astype(jnp.float32),
                                keys.astype(jnp.float32)) / (hd ** 0.5)
                valid = (jnp.arange(Smax + plen)[None, :] - plen) <= pos[:, None]
                lg = jnp.where(valid[:, None, :], lg, -1e30)
                if mv is not None:
                    # additive decode mask [B, 1|H, 1, Lm], keys aligned at
                    # column 0 (pre-cache prefix occupies the first plen
                    # columns when present)
                    m = mv.astype(jnp.float32).reshape(B, -1, mv.shape[-1])
                    need = lg.shape[-1]
                    if m.shape[-1] < need:
                        m = jnp.pad(m, ((0, 0), (0, 0), (0, need - m.shape[-1])))
                    else:
                        m = m[..., :need]
                    lg = lg + m
                p = jax.nn.softmax(lg, axis=-1)
                o = jnp.einsum("bhl,bhld->bhd", p, vals.astype(jnp.float32))
                # token-major [B, 1, H, D] so the common reshape below works
                return (o[:, None].astype(qv.dtype),
                        jnp.stack([kc, vc]).astype(cv.dtype))

            attn_out, new_cache = apply(lambda *a: dec_fn(*a), *args,
                                        op_name="fmt_decode", n_outs=2)
            cache._value = new_cache._value
            new_caches.append(cache)

        # common tail: out proj + residual + FFN
        ho = M.reshape(attn_out, [B, S if not decode else 1, nh * hd])
        ho = F.linear(ho, linear_weights[i],
                      linear_biases[i] if linear_biases else None)
        if training and dropout_rate > 0:
            ho = F.dropout(ho, p=dropout_rate, training=True, mode=mode)
        out = residual * residual_alpha + ho
        if not pre_layer_norm:
            out = _norm(out, ln_scales[i], ln_biases[i] if ln_biases else None)
        residual2 = out
        h2 = _norm(out, ffn_ln_scales[i],
                   ffn_ln_biases[i] if ffn_ln_biases else None) \
            if pre_layer_norm else out
        h2 = F.linear(h2, ffn1_weights[i],
                      ffn1_biases[i] if ffn1_biases else None)
        h2 = getattr(F, activation)(h2)
        if training and dropout_rate > 0:
            h2 = F.dropout(h2, p=dropout_rate, training=True, mode=mode)
        h2 = F.linear(h2, ffn2_weights[i],
                      ffn2_biases[i] if ffn2_biases else None)
        if training and dropout_rate > 0:
            h2 = F.dropout(h2, p=dropout_rate, training=True, mode=mode)
        out = residual2 * residual_alpha + h2
        if not pre_layer_norm:
            out = _norm(out, ffn_ln_scales[i],
                        ffn_ln_biases[i] if ffn_ln_biases else None)

    if cache_kvs is not None:
        return out, new_caches
    return out


def variable_length_memory_efficient_attention(query, key, value, seq_lens=None,
                                               kv_seq_lens=None, mask=None,
                                               scale=None, causal=False, **kw):
    """Memory-efficient attention with per-sequence KEY lengths: masked
    attention over the padded batch (TPU kernels are static-shape).

    Layout matches the reference op: q/k/v are [B, num_heads, S, D]; the key
    axis is masked by ``kv_seq_lens`` (``seq_lens`` is the fallback when kv
    lengths are not given)."""
    import jax.numpy as jnp

    from ....ops.dispatch import apply as _apply
    from ....tensor.linalg import transpose as _tr

    query = to_tensor_like(query)
    key = to_tensor_like(key)
    value = to_tensor_like(value)
    lens = kv_seq_lens if kv_seq_lens is not None else seq_lens
    if mask is None and lens is not None:
        sk = key.shape[2]  # [B, H, S, D]
        lens = to_tensor_like(lens)

        def build_mask(l):  # noqa: E741
            valid = jnp.arange(sk)[None, :] < l.reshape(-1, 1)
            return jnp.where(valid, 0.0, -1e30)[:, None, None, :]

        mask = _apply(build_mask, lens, op_name="varlen_mask")
    # sdpa takes [B, S, H, D]
    q_s = _tr(query, [0, 2, 1, 3])
    k_s = _tr(key, [0, 2, 1, 3])
    v_s = _tr(value, [0, 2, 1, 3])
    out = F.scaled_dot_product_attention(q_s, k_s, v_s, attn_mask=mask,
                                         is_causal=causal)
    return _tr(out, [0, 2, 1, 3])


def fused_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                is_causal=False, scaling_factor=None, training=True,
                                name=None):
    """parity: fused_dot_product_attention (cudnn fused SDPA) — [B,S,H,D]
    layout; lowers to the flash kernel / fused XLA attention."""
    if is_causal and attn_mask is not None:
        raise AssertionError(
            "attn_mask must be None when is_causal=True (reference contract)")
    if scaling_factor is not None:
        q = to_tensor_like(query)
        d = q.shape[-1]
        query = q * (scaling_factor * (d ** 0.5))  # fold custom scale into q
    return F.scaled_dot_product_attention(query, key, value, attn_mask=attn_mask,
                                          dropout_p=dropout_p, is_causal=is_causal,
                                          training=training)


def blha_get_max_len(seq_lens_encoder, seq_lens_decoder, batch_size):
    """parity: blha_get_max_len — (max encoder len, max decoder len) this
    step (used ahead of block_multihead_attention)."""
    import numpy as _np

    enc = to_tensor_like(seq_lens_encoder)
    dec = to_tensor_like(seq_lens_decoder)
    # live rows only (seq_lens arrays may be padded past the real batch)
    b = int(_np.asarray(to_tensor_like(batch_size)._value).reshape(-1)[0])
    mx = lambda t: apply(  # noqa: E731
        lambda v: jnp.max(v.astype(jnp.int32)[:b]).reshape(1), t,
        op_name="blha_max")
    return mx(enc), mx(dec)


def masked_multihead_attention(x, cache_kv=None, bias=None, src_mask=None,
                               cum_offsets=None, sequence_lengths=None,
                               rotary_tensor=None, beam_cache_offset=None,
                               qkv_out_scale=None, out_shift=None, out_smooth=None,
                               seq_len=1, rotary_emb_dims=0,
                               use_neox_rotary_style=False,
                               compute_dtype="default", out_scale=-1,
                               quant_round_type=1, quant_max_bound=127.0,
                               quant_min_bound=-127.0):
    """parity: masked_multihead_attention — single-token decode attention
    over a [2, B, H, max_seq, D] cache (the reference's fused MMHA decode
    kernel). Supported subset: bias add, src_mask, sequence_lengths write
    positions; quant/rotary-in-kernel paths raise (use apply_rotary_pos_emb
    upstream)."""
    if any(a is not None for a in (qkv_out_scale, out_shift, out_smooth)) or out_scale != -1:
        raise NotImplementedError("masked_multihead_attention: quant paths not supported")
    if rotary_tensor is not None or rotary_emb_dims:
        raise NotImplementedError(
            "masked_multihead_attention: in-kernel rotary not supported; apply "
            "rope to x before calling")
    if beam_cache_offset is not None or cum_offsets is not None:
        raise NotImplementedError(
            "masked_multihead_attention: beam_cache_offset/cum_offsets (beam "
            "search cache reordering) are not supported")
    if sequence_lengths is None and src_mask is None:
        raise ValueError(
            "masked_multihead_attention needs sequence_lengths (write "
            "positions) or src_mask (whose length infers the timestep)")
    x = to_tensor_like(x)
    cache = to_tensor_like(cache_kv)
    b_t = to_tensor_like(bias) if bias is not None else None
    m_t = to_tensor_like(src_mask) if src_mask is not None else None
    sl_t = to_tensor_like(sequence_lengths) if sequence_lengths is not None else None

    def f(xv, cv, *rest):
        rest = list(rest)
        bv = rest.pop(0) if b_t is not None else None
        mv = rest.pop(0) if m_t is not None else None
        sv = rest.pop(0) if sl_t is not None else None
        B = xv.shape[0]
        _, _, H, S, D = cv.shape
        qkv = xv.reshape(B, 3, H, D)
        if bv is not None:
            qkv = qkv + bv[None]
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]  # [B, H, D]
        if sv is not None:
            pos = sv.reshape(B).astype(jnp.int32)
        else:
            # reference behavior: the mask covers [0, timestep] — its length
            # IS timestep+1, so the write position is mask_len - 1
            pos = jnp.full((B,), mv.shape[-1] - 1, jnp.int32)
        bidx = jnp.arange(B)
        # cache layout [2, B, H, S, D]: plane 0 = K, plane 1 = V
        ck = cv[0].at[bidx, :, pos].set(k)   # write k at pos: [B,H,S,D]
        cvv = cv[1].at[bidx, :, pos].set(v)
        logits = jnp.einsum("bhd,bhsd->bhs", q.astype(jnp.float32),
                            ck.astype(jnp.float32)) / (D ** 0.5)
        valid = jnp.arange(S)[None, :] <= pos[:, None]  # [B, S]
        logits = jnp.where(valid[:, None, :], logits, -1e30)
        if mv is not None:
            # documented src_mask shape [B,1,1,t+1] may be shorter than the
            # cache capacity S: pad with zeros (those slots are already
            # masked by the validity window)
            mslice = mv.reshape(B, 1, -1)[:, :, :S].astype(jnp.float32)
            short = S - mslice.shape[-1]
            if short > 0:
                mslice = jnp.pad(mslice, ((0, 0), (0, 0), (0, short)))
            logits = logits + mslice
        p = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bhs,bhsd->bhd", p, cvv.astype(jnp.float32))
        new_cache = jnp.stack([ck, cvv], axis=0).astype(cv.dtype)
        return out.reshape(B, H * D).astype(xv.dtype), new_cache

    args = [x, cache] + [t for t in (b_t, m_t, sl_t) if t is not None]
    out, new_cache = apply(lambda *a: tuple(f(*a)), *args,
                           op_name="masked_multihead_attention", n_outs=2)
    return out, new_cache


def fused_gate_attention(query, key=None, query_weight=None, key_weight=None,
                         value_weight=None, qkv_weight=None,
                         gate_linear_weight=None, gate_linear_bias=None,
                         out_linear_weight=None, out_linear_bias=None,
                         nonbatched_bias=None, attn_mask=None, has_gating=True,
                         merge_qkv=True, use_flash_attn=False):
    """parity: fused_gate_attention (the AlphaFold gate-attention fusion).
    query [B, M, S, Dq]; merged qkv_weight [3, H, D, Dq] or separate
    q/k/v weights [Dq, H, D]; sigmoid gating + output projection."""
    q_in = to_tensor_like(query)
    k_in = to_tensor_like(key) if key is not None else q_in

    def proj(x, w):
        # x [B,M,S,Dq] @ w [Dq,H,D] -> [B,M,S,H,D]
        return apply(lambda xv, wv: jnp.einsum("bmsq,qhd->bmshd", xv, wv),
                     x, to_tensor_like(w), op_name="gate_proj")

    if merge_qkv:
        if qkv_weight is None:
            raise ValueError("merge_qkv=True needs qkv_weight")
        qkvw = to_tensor_like(qkv_weight)
        q = apply(lambda xv, wv: jnp.einsum("bmsq,hdq->bmshd", xv, wv[0]),
                  q_in, qkvw, op_name="gate_q")
        k = apply(lambda xv, wv: jnp.einsum("bmsq,hdq->bmshd", xv, wv[1]),
                  q_in, qkvw, op_name="gate_k")
        v = apply(lambda xv, wv: jnp.einsum("bmsq,hdq->bmshd", xv, wv[2]),
                  q_in, qkvw, op_name="gate_v")
    else:
        q = proj(q_in, query_weight)
        k = proj(k_in, key_weight)
        v = proj(k_in, value_weight)

    mask_t = to_tensor_like(attn_mask) if attn_mask is not None else None
    nb_t = to_tensor_like(nonbatched_bias) if nonbatched_bias is not None else None

    def attn(qv, kv, vv, *rest):
        rest = list(rest)
        mv = rest.pop(0) if mask_t is not None else None
        nb = rest.pop(0) if nb_t is not None else None
        D = qv.shape[-1]
        logits = jnp.einsum("bmqhd,bmkhd->bmhqk", qv, kv).astype(jnp.float32) / (D ** 0.5)
        if nb is not None:  # [B, 1?, H, S, S] broadcast bias
            logits = logits + nb.astype(jnp.float32)
        if mv is not None:
            logits = logits + mv.astype(jnp.float32)
        p = jax.nn.softmax(logits, axis=-1)
        return jnp.einsum("bmhqk,bmkhd->bmqhd", p, vv).astype(qv.dtype)

    a_args = [q, k, v] + [t for t in (mask_t, nb_t) if t is not None]
    out = apply(attn, *a_args, op_name="gate_attention")

    if has_gating:
        if gate_linear_weight is None:
            raise ValueError("has_gating=True needs gate_linear_weight")
        gw = to_tensor_like(gate_linear_weight)
        gb = to_tensor_like(gate_linear_bias)
        gate = apply(lambda xv, wv, bv: jax.nn.sigmoid(
            jnp.einsum("bmsq,qhd->bmshd", xv, wv) + bv),
            q_in, gw, gb, op_name="gate_gate")
        out = apply(lambda o, g: o * g.astype(o.dtype), out, gate, op_name="gate_mul")

    ow = to_tensor_like(out_linear_weight)
    ob = to_tensor_like(out_linear_bias)
    return apply(lambda o, wv, bv: jnp.einsum("bmshd,hdq->bmsq", o, wv) + bv,
                 out, ow, ob, op_name="gate_out")


def block_multihead_attention(
    qkv, key_cache, value_cache, seq_lens_encoder, seq_lens_decoder,
    seq_lens_this_time, padding_offsets, cum_offsets, cu_seqlens_q,
    cu_seqlens_k, block_tables, pre_key_cache=None, pre_value_cache=None,
    cache_k_quant_scales=None, cache_v_quant_scales=None,
    cache_k_dequant_scales=None, cache_v_dequant_scales=None,
    qkv_out_scale=None, qkv_bias=None, out_shift=None, out_smooth=None,
    max_enc_len_this_time=None, max_dec_len_this_time=None, rope_emb=None,
    mask=None, tgt_mask=None, max_seq_len=-1, block_size=64,
    use_neox_style=False, use_dynamic_cachekv_quant=False,
    quant_round_type=1, quant_max_bound=127.0, quant_min_bound=-127.0,
    out_scale=-1.0, compute_dtype="default"):
    """Paged-KV serving attention (parity:
    /root/reference/python/paddle/incubate/nn/functional/block_multihead_attention.py:19).

    TPU-native: scatter/gather over a global block pool + one padded-batch
    masked-attention einsum chain (see ops/paged_attention.py for the design
    notes). Caches, and in dynamic quant mode the scale tensors, are updated
    IN PLACE on the passed Tensors — the reference kernel's inplace
    contract — and also returned: (out, qkv, key_cache, value_cache).
    Supports MHA/GQA, mixed prefill+decode batches, in-kernel rope,
    pre-caches, int8 cache quant (static + dynamic), int32-qkv dequant and
    int8 output quant.

    Compilation note: the padded-query length is a HOST-side read of
    ``max(seq_lens_this_time)``, bucketed to the next power of two — one
    XLA program per distinct bucket (a serving loop therefore compiles at
    most log2(max_seq_len) programs: mq=1 pure decode, plus one per
    prefill-chunk bucket). Because of that host read this op must be
    called eagerly; under jit/to_static tracing ``seq_lens_this_time`` has
    no concrete value and the call raises — use ``ServingEngine``, which
    pins a static max_q_len per program, to serve from compiled code.
    """
    import numpy as _np

    from ....ops.paged_attention import blha_attention

    qkv_t = to_tensor_like(qkv)
    kc_t = to_tensor_like(key_cache)
    vc_t = to_tensor_like(value_cache)
    KV, bsz_blocks, D = kc_t.shape[1], kc_t.shape[2], kc_t.shape[3]
    if int(bsz_blocks) != int(block_size):
        raise ValueError(
            f"block_size={block_size} does not match key_cache block axis "
            f"({bsz_blocks})")
    H = qkv_t.shape[1] // D - 2 * KV

    def val(x):
        return None if x is None else to_tensor_like(x)._value

    lens_val = val(seq_lens_this_time)
    if isinstance(lens_val, jax.core.Tracer):
        raise ValueError(
            "block_multihead_attention reads max(seq_lens_this_time) on the "
            "HOST to pick the padded-query bucket, so it cannot be traced "
            "under jit/to_static — call it eagerly, or serve through "
            "ServingEngine which compiles per-bucket programs with a static "
            "max_q_len")
    lens_now = _np.asarray(lens_val).reshape(-1)
    max_q_len = int(lens_now.max()) if lens_now.size else 1
    # bucket the static padded-query length to the next power of two: a
    # serving loop with naturally varying chunk lengths otherwise compiles
    # one program per distinct max length (padded rows are masked, so this
    # only costs a bounded amount of dead compute)
    max_q_len = 1 << max(max_q_len - 1, 0).bit_length()

    if use_dynamic_cachekv_quant and cache_k_quant_scales is not None:
        cache_quant = "dynamic"
        for t in (cache_k_quant_scales, cache_v_quant_scales,
                  cache_k_dequant_scales, cache_v_dequant_scales):
            if not isinstance(t, Tensor):
                raise TypeError(
                    "use_dynamic_cachekv_quant=True refreshes the scale "
                    "tensors IN PLACE (reference contract) — pass Tensors, "
                    "not raw arrays, or the updated scales would be lost")
    elif cache_k_quant_scales is not None or cache_k_dequant_scales is not None:
        cache_quant = "static"
    else:
        cache_quant = "none"

    if compute_dtype == "default":
        cdt = qkv_t._value.dtype
        if cdt == jnp.int32:
            raise ValueError(
                "int32 qkv needs an explicit compute_dtype (e.g. 'fp16')")
    else:
        cdt = {"fp16": jnp.float16, "float16": jnp.float16,
               "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
               "fp32": jnp.float32, "float32": jnp.float32}[compute_dtype]

    outs = blha_attention(
        qkv_t._value, kc_t._value, vc_t._value,
        jnp.asarray(val(seq_lens_encoder)).reshape(-1),
        jnp.asarray(val(seq_lens_decoder)).reshape(-1),
        jnp.asarray(val(seq_lens_this_time)).reshape(-1),
        jnp.asarray(val(cu_seqlens_q)).reshape(-1),
        val(block_tables),
        num_heads=int(H), kv_num_heads=int(KV), head_dim=int(D),
        block_size=int(block_size), max_q_len=max_q_len,
        use_neox_style=bool(use_neox_style), cache_quant=cache_quant,
        round_ties_away=(quant_round_type == 1), compute_dtype=cdt,
        has_out_quant=(out_scale > 0),
        qkv_out_scale=val(qkv_out_scale), qkv_bias=val(qkv_bias),
        rope_emb=val(rope_emb), mask=val(mask), tgt_mask=val(tgt_mask),
        pre_key_cache=val(pre_key_cache), pre_value_cache=val(pre_value_cache),
        cache_k_quant_scales=val(cache_k_quant_scales),
        cache_v_quant_scales=val(cache_v_quant_scales),
        cache_k_dequant_scales=val(cache_k_dequant_scales),
        cache_v_dequant_scales=val(cache_v_dequant_scales),
        out_shift=val(out_shift), out_smooth=val(out_smooth),
        out_scale=float(out_scale), quant_max_bound=float(quant_max_bound),
        quant_min_bound=float(quant_min_bound))
    out, new_kc, new_vc, kq, vq, kd, vd = outs
    kc_t._value = new_kc
    vc_t._value = new_vc
    if cache_quant == "dynamic":
        for t, v in ((cache_k_quant_scales, kq), (cache_v_quant_scales, vq),
                     (cache_k_dequant_scales, kd), (cache_v_dequant_scales, vd)):
            t._value = v  # Tensor-ness validated up front
    return (Tensor(out), qkv_t, kc_t, vc_t)
