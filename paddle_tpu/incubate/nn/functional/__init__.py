"""Fused-op functional APIs (parity: /root/reference/python/paddle/incubate/nn/functional/ —
fused_rms_norm.py, fused_rotary_position_embedding.py, swiglu.py,
fused_dropout_add.py, fused_linear.py ...).

TPU-native: "fused" means "expressed so XLA/Pallas fuses it" — these share
implementations with the core functional ops and exist for API parity with
reference model code.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....nn import functional as F
from ....ops.dispatch import apply
from ....tensor._helpers import to_tensor_like
from ....tensor.tensor import Tensor

__all__ = [
    "fused_rms_norm", "fused_layer_norm", "fused_rotary_position_embedding", "swiglu",
    "fused_linear", "fused_bias_act", "fused_dropout_add", "fused_multi_head_attention",
]


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6, begin_norm_axis=-1,
                   bias=None, residual=None, quant_scale=-1, **kw):
    """Pallas-fused RMSNorm (+residual): one HBM pass for add+norm on TPU
    (ops/pallas/fused_norm.py); jnp fallback elsewhere. Returns
    (out,) or (out, residual_out) matching the reference signature."""
    from ....ops.pallas.fused_norm import rms_norm_fused, rms_norm_residual_fused

    if quant_scale > 0:
        raise NotImplementedError(
            "fused_rms_norm: quantized output (quant_scale>0) is not implemented")
    x = to_tensor_like(x)
    if bias is not None:
        # reference semantics: the pre-norm stream is x + bias (+ residual)
        x = x + to_tensor_like(bias)
    norm_weight = to_tensor_like(norm_weight)
    if residual is not None:
        residual = to_tensor_like(residual)
        outs = apply(
            lambda xv, rv, wv: list(rms_norm_residual_fused(xv, rv, wv, epsilon)),
            x, residual, norm_weight, op_name="fused_rms_norm_residual")
        out, res_out = outs[0], outs[1]
        if norm_bias is not None:
            out = out + to_tensor_like(norm_bias)
        return (out, res_out)
    out = apply(lambda xv, wv: rms_norm_fused(xv, wv, epsilon), x, norm_weight,
                op_name="fused_rms_norm")
    if norm_bias is not None:
        out = out + to_tensor_like(norm_bias)
    return (out,)


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5, begin_norm_axis=1, **kw):
    shape = x.shape[begin_norm_axis:]
    return (F.layer_norm(x, shape, norm_weight, norm_bias, epsilon),)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None, position_ids=None,
                                    use_neox_rotary_style=True, time_major=False, rotary_emb_base=10000.0):
    """parity: fused_rotary_position_embedding — q/k/v [B, S, H, D]."""
    q = to_tensor_like(q)
    outs = []

    def rope_one(x, c, s):
        # c/s: [1, S, 1, D/2] or [S, D/2]
        if c.ndim == 2:
            c = c[None, :, None, :]
            s = s[None, :, None, :]
        if use_neox_rotary_style:
            x1, x2 = jnp.split(x, 2, axis=-1)
            return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1).astype(x.dtype)
        x1 = x[..., 0::2]
        x2 = x[..., 1::2]
        ro = jnp.stack([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
        return ro.reshape(x.shape).astype(x.dtype)

    if sin is None or cos is None:
        S, D = q.shape[1], q.shape[-1]
        inv = 1.0 / (rotary_emb_base ** (jnp.arange(0, D, 2, dtype=jnp.float32) / D))
        t = jnp.arange(S, dtype=jnp.float32)
        fr = jnp.outer(t, inv)
        cos_v, sin_v = jnp.cos(fr), jnp.sin(fr)
    else:
        cos_v = cos._value if isinstance(cos, Tensor) else jnp.asarray(cos)
        sin_v = sin._value if isinstance(sin, Tensor) else jnp.asarray(sin)
        if cos_v.ndim == 4:
            cos_v = cos_v[0, :, 0, :]
            sin_v = sin_v[0, :, 0, :]
        if cos_v.shape[-1] == q.shape[-1]:  # full-dim cos caches store doubled
            cos_v = cos_v[..., : cos_v.shape[-1] // 2]
            sin_v = sin_v[..., : sin_v.shape[-1] // 2]

    for t_in in (q, k, v):
        if t_in is None:
            outs.append(None)
            continue
        t_in = to_tensor_like(t_in)
        outs.append(apply(lambda x: rope_one(x, cos_v, sin_v), t_in, op_name="fused_rope"))
    return tuple(outs)


def swiglu(x, y=None, name=None):
    """parity: incubate/nn/functional/swiglu.py — silu(x) * y (y defaults to
    second half of x)."""
    x = to_tensor_like(x)
    if y is None:
        def f(v):
            a, b = jnp.split(v, 2, axis=-1)
            return jax.nn.silu(a) * b

        return apply(f, x, op_name="swiglu")
    y = to_tensor_like(y)
    return apply(lambda a, b: jax.nn.silu(a) * b, x, y, op_name="swiglu")


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    if transpose_weight:
        from ....tensor.linalg import transpose

        weight = transpose(to_tensor_like(weight), [1, 0])
    return F.linear(x, weight, bias)


def fused_bias_act(x, bias=None, act_method="gelu", **kw):
    if bias is not None:
        x = to_tensor_like(x) + to_tensor_like(bias)
    return getattr(F, act_method)(x)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train", name=None):
    return F.dropout(x, p, training=training, mode=mode) + to_tensor_like(y)


def fused_multi_head_attention(*args, **kwargs):
    raise NotImplementedError(
        "use paddle_tpu.nn.functional.flash_attention / MultiHeadAttention (fused on TPU)"
    )
