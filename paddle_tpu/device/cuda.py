"""paddle.device.cuda parity surface (reference:
python/paddle/device/cuda/__init__.py + streams.py).

TPU-native: XLA owns streams — dispatch order IS the stream, PJRT manages
events. Stream/Event are therefore sequencing facades (wait/synchronize map
to dispatch-order guarantees + block-on-readback), and the memory APIs
delegate to the PJRT counters in device/memory.py. Code written against the
CUDA surface runs unchanged; nothing here launches CUDA."""
from __future__ import annotations

import contextlib
import time
from typing import Optional

import jax

from . import memory as _mem
from .memory import (  # noqa: F401
    empty_cache,
    max_memory_allocated,
    max_memory_reserved,
    memory_allocated,
    memory_reserved,
)

__all__ = [
    "Stream", "Event", "current_stream", "synchronize", "device_count",
    "empty_cache", "max_memory_allocated", "max_memory_reserved",
    "memory_allocated", "memory_reserved", "stream_guard",
    "get_device_properties", "get_device_name", "get_device_capability",
]


def synchronize(device=None):
    from . import synchronize as _sync

    return _sync(device)


def device_count() -> int:
    return len(jax.devices())


class Event:
    """Event parity: records a point in dispatch order; query/synchronize map
    to XLA's program-order execution guarantee.

    Semantics differ from CUDA events: with ``enable_timing=True``,
    ``record()`` is a BLOCKING device fence (full ``synchronize()``) so
    ``elapsed_time`` measures host wall-clock between fences — code using
    events for async overlap will serialize at each timed record. With
    ``enable_timing=False`` (default) ``record()`` is a no-op marker:
    XLA's program-order guarantee already provides the cross-stream
    ordering CUDA events exist for, so no fence is needed and nothing
    serializes."""

    def __init__(self, enable_timing: bool = False, blocking: bool = False,
                 interprocess: bool = False):
        self._enable_timing = enable_timing
        self._recorded_at: Optional[float] = None
        self._fenced = True  # nothing recorded yet → trivially complete

    def record(self, stream: "Stream" = None):
        if self._enable_timing:
            synchronize()  # blocking fence so the timestamp is meaningful
            self._fenced = True
        else:
            self._fenced = False  # async marker; fence deferred to query/sync
        self._recorded_at = time.perf_counter()

    def query(self) -> bool:
        self.synchronize()  # conservative: fence, then truthfully report done
        return True

    def synchronize(self):
        if not self._fenced:
            synchronize()  # wait for work dispatched before record()
            self._fenced = True

    _warned_untimed = False

    def elapsed_time(self, end_event: "Event") -> float:
        if self._recorded_at is None or end_event._recorded_at is None:
            raise RuntimeError("both events must be recorded first")
        if not (self._enable_timing and end_event._enable_timing):
            # non-timing events never fenced at record(): the delta is host
            # dispatch wall-clock, not device time — warn once rather than
            # silently passing it off as a device measurement
            if not Event._warned_untimed:
                Event._warned_untimed = True
                import warnings

                warnings.warn(
                    "Event.elapsed_time on events created with "
                    "enable_timing=False measures host dispatch wall-clock, "
                    "not device time; create Event(enable_timing=True) for "
                    "fenced timestamps")
        return (end_event._recorded_at - self._recorded_at) * 1e3


class Stream:
    """Stream parity: XLA serializes per-device dispatch, so every Stream is
    a view of the one device stream (the reference's multi-stream overlap is
    what XLA's latency-hiding scheduler does automatically)."""

    def __init__(self, device=None, priority: int = 2):
        self.device = device
        self.priority = priority

    def record_event(self, event: Event = None) -> Event:
        # timing-enabled by default: record_event's dominant use in ported
        # code is stream timing, and a non-timing event here could never
        # legally reach elapsed_time
        event = event or Event(enable_timing=True)
        event.record(self)
        return event

    def wait_event(self, event: Event):
        return None  # program order already guarantees it

    def wait_stream(self, stream: "Stream"):
        return None

    def synchronize(self):
        synchronize(self.device)

    def query(self) -> bool:
        return True


_current = Stream()


def current_stream(device=None) -> Stream:
    return _current


def set_stream(stream: Stream) -> Stream:
    """Install ``stream`` as the current handle; returns the previous one."""
    global _current
    prev = _current
    _current = stream
    return prev


@contextlib.contextmanager
def stream_guard(stream: Stream):
    """parity: device.cuda.stream_guard — a no-op scope (one device stream)."""
    global _current
    prev = _current
    _current = stream
    try:
        yield stream
    finally:
        _current = prev


class _DeviceProperties:
    def __init__(self, d):
        self.name = f"{d.platform}:{d.device_kind}" if hasattr(d, "device_kind") else str(d)
        st = _mem.memory_stats(d)
        self.total_memory = int(st.get("bytes_limit", 0))
        self.major, self.minor = 0, 0
        self.multi_processor_count = 1

    def __repr__(self):
        return (f"_DeviceProperties(name='{self.name}', "
                f"total_memory={self.total_memory // (1 << 20)}MB)")


def get_device_properties(device=None) -> _DeviceProperties:
    return _DeviceProperties(_mem._device(device))


def get_device_name(device=None) -> str:
    return get_device_properties(device).name


def get_device_capability(device=None):
    return (0, 0)  # CUDA compute capability has no TPU analog
