"""Device management (parity: python/paddle/device).

TPU-native: devices are jax devices; a ``Place`` is a thin descriptor. There is
no allocator/stream surface — XLA owns both. ``set_device`` selects the default
jax device for new tensors.
"""
from __future__ import annotations

from typing import Optional

import jax

from . import cuda  # noqa: F401
from .memory import (  # noqa: F401
    empty_cache,
    get_memory_info,
    max_memory_allocated,
    max_memory_reserved,
    memory_allocated,
    memory_reserved,
    memory_stats,
    reset_max_memory_allocated,
    reset_max_memory_reserved,
)

__all__ = [
    "Place", "TPUPlace", "CPUPlace", "CUDAPlace", "CUDAPinnedPlace",
    "get_device", "set_device",
    "get_all_devices", "device_count", "is_compiled_with_cuda", "is_compiled_with_xpu",
    "is_compiled_with_rocm", "is_compiled_with_custom_device", "synchronize",
    "memory_stats", "memory_allocated", "max_memory_allocated",
    "memory_reserved", "max_memory_reserved", "reset_max_memory_allocated",
    "reset_max_memory_reserved", "get_memory_info", "empty_cache",
]


class Place:
    def __init__(self, kind: str, index: int = 0):
        self.kind = kind
        self.index = index

    def __repr__(self):
        return f"Place({self.kind}:{self.index})"

    def __eq__(self, other):
        return isinstance(other, Place) and (self.kind, self.index) == (other.kind, other.index)

    def is_cpu_place(self):
        return self.kind == "cpu"

    def is_gpu_place(self):
        return False

    def is_tpu_place(self):
        return self.kind in ("tpu", "axon")


def TPUPlace(idx: int = 0) -> Place:
    return Place("tpu", idx)


def CPUPlace() -> Place:
    return Place("cpu", 0)


def CUDAPinnedPlace() -> Place:
    """Pinned host memory place (PJRT manages host staging; alias of CPU)."""
    return Place("cpu")


def CUDAPlace(idx: int = 0) -> Place:
    # Accepted for API compatibility; maps to the accelerator jax exposes.
    return Place(jax.default_backend(), idx)


def _place_of(value) -> Place:
    try:
        devs = value.devices() if hasattr(value, "devices") else None
        if devs:
            d = next(iter(devs))
            return Place(d.platform, d.id)
    except Exception:
        pass
    return Place(jax.default_backend(), 0)


_current = None


def get_device() -> str:
    if _current is not None:
        return _current
    b = jax.default_backend()
    return f"{b}:0"


def set_device(device: str):
    global _current
    _current = device
    return Place(*_split(device))


def _split(device: str):
    if ":" in device:
        k, i = device.split(":")
        return k, int(i)
    return device, 0


def get_all_devices():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def device_count() -> int:
    return jax.device_count()


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_custom_device(device_type: str) -> bool:
    return device_type in ("tpu", "axon")


def synchronize(device=None):
    """Block until all dispatched work completes (stream sync analog)."""
    (jax.device_put(0) + 0).block_until_ready()


# ---------------------------------------------------- surface-parity tail
# (parity: python/paddle/device/__init__.py __all__)
from .cuda import Event, Stream  # noqa: E402,F401


class XPUPlace(Place):
    def __init__(self, index: int = 0):
        super().__init__("xpu", index)


class IPUPlace(Place):
    def __init__(self, index: int = 0):
        super().__init__("ipu", index)


def current_stream(device=None) -> Stream:
    """The one device stream view (XLA serializes per-device dispatch);
    shares device.cuda's registry so both spellings agree."""
    from . import cuda as _cuda

    return _cuda.current_stream(device)


def get_all_device_type():
    return ["cpu", "tpu"]


def get_all_custom_device_type():
    return ["tpu"]  # the PJRT-plugin device (reference: CustomDevice slot)


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices() if d.platform != "cpu"]


def get_cudnn_version():
    return None  # no cudnn on TPU (reference returns None when absent)


def is_compiled_with_cinn() -> bool:
    return False  # the XLA stack replaces CINN wholesale


def is_compiled_with_distribute() -> bool:
    return True  # collectives are always compiled in (XLA)


__all__ += ["Event", "Stream", "XPUPlace", "IPUPlace", "current_stream",
            "get_all_device_type", "get_all_custom_device_type",
            "get_available_device", "get_available_custom_device",
            "get_cudnn_version", "is_compiled_with_cinn",
            "is_compiled_with_distribute"]


def is_compiled_with_ipu() -> bool:
    return False


def set_stream(stream: Stream = None) -> Stream:
    """parity: device.set_stream — XLA exposes one serialized device stream;
    the call records the handle (in device.cuda's single registry) and
    returns the previous one."""
    from . import cuda as _cuda

    prev = _cuda.current_stream()
    if stream is not None:
        _cuda.set_stream(stream)
    return prev


class stream_guard:
    """parity: device.stream_guard — scope a 'current' stream handle (all
    handles view the same XLA dispatch stream)."""

    def __init__(self, stream: Stream = None):
        self.stream = stream

    def __enter__(self):
        self._prev = set_stream(self.stream)
        return self.stream

    def __exit__(self, *exc):
        set_stream(self._prev)
        return False


__all__ += ["is_compiled_with_ipu", "set_stream", "stream_guard"]
