"""Device management (parity: python/paddle/device).

TPU-native: devices are jax devices; a ``Place`` is a thin descriptor. There is
no allocator/stream surface — XLA owns both. ``set_device`` selects the default
jax device for new tensors.
"""
from __future__ import annotations

from typing import Optional

import jax

from . import cuda  # noqa: F401
from .memory import (  # noqa: F401
    empty_cache,
    get_memory_info,
    max_memory_allocated,
    max_memory_reserved,
    memory_allocated,
    memory_reserved,
    memory_stats,
    reset_max_memory_allocated,
    reset_max_memory_reserved,
)

__all__ = [
    "Place", "TPUPlace", "CPUPlace", "CUDAPlace", "CUDAPinnedPlace",
    "get_device", "set_device",
    "get_all_devices", "device_count", "is_compiled_with_cuda", "is_compiled_with_xpu",
    "is_compiled_with_rocm", "is_compiled_with_custom_device", "synchronize",
    "memory_stats", "memory_allocated", "max_memory_allocated",
    "memory_reserved", "max_memory_reserved", "reset_max_memory_allocated",
    "reset_max_memory_reserved", "get_memory_info", "empty_cache",
]


class Place:
    def __init__(self, kind: str, index: int = 0):
        self.kind = kind
        self.index = index

    def __repr__(self):
        return f"Place({self.kind}:{self.index})"

    def __eq__(self, other):
        return isinstance(other, Place) and (self.kind, self.index) == (other.kind, other.index)

    def is_cpu_place(self):
        return self.kind == "cpu"

    def is_gpu_place(self):
        return False

    def is_tpu_place(self):
        return self.kind in ("tpu", "axon")


def TPUPlace(idx: int = 0) -> Place:
    return Place("tpu", idx)


def CPUPlace() -> Place:
    return Place("cpu", 0)


def CUDAPinnedPlace() -> Place:
    """Pinned host memory place (PJRT manages host staging; alias of CPU)."""
    return Place("cpu")


def CUDAPlace(idx: int = 0) -> Place:
    # Accepted for API compatibility; maps to the accelerator jax exposes.
    return Place(jax.default_backend(), idx)


def _place_of(value) -> Place:
    try:
        devs = value.devices() if hasattr(value, "devices") else None
        if devs:
            d = next(iter(devs))
            return Place(d.platform, d.id)
    except Exception:
        pass
    return Place(jax.default_backend(), 0)


_current = None


def get_device() -> str:
    if _current is not None:
        return _current
    b = jax.default_backend()
    return f"{b}:0"


def set_device(device: str):
    global _current
    _current = device
    return Place(*_split(device))


def _split(device: str):
    if ":" in device:
        k, i = device.split(":")
        return k, int(i)
    return device, 0


def get_all_devices():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def device_count() -> int:
    return jax.device_count()


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_custom_device(device_type: str) -> bool:
    return device_type in ("tpu", "axon")


def synchronize(device=None):
    """Block until all dispatched work completes (stream sync analog)."""
    (jax.device_put(0) + 0).block_until_ready()
