"""Device memory observability.

Reference capability: the memory stat registry + peak trackers
(/root/reference/paddle/fluid/memory/stats.h) surfaced through the
python/paddle/device/cuda memory APIs (max_memory_allocated etc.).

TPU-native: XLA owns the allocator, so the numbers come from
``jax.Device.memory_stats()`` (PJRT per-device counters: bytes_in_use,
peak_bytes_in_use, bytes_limit, ...). The hardware peak counter is
process-lifetime; ``reset_max_memory_allocated`` therefore switches that
device to a software-observed peak (max over every subsequent stats call),
the same observable-point semantics the reference's HostMemoryStatResetPeak
gives when no allocation happens between observations.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax

__all__ = [
    "memory_stats", "memory_allocated", "max_memory_allocated",
    "memory_reserved", "max_memory_reserved",
    "reset_max_memory_allocated", "reset_max_memory_reserved",
    "get_memory_info", "empty_cache",
]

# device id -> software peak tracking state (set by reset_max_memory_*)
_sw_peak_alloc: Dict[int, int] = {}
_sw_peak_reserved: Dict[int, int] = {}


def _device(device=None) -> "jax.Device":
    if device is None:
        return jax.devices()[0]
    if isinstance(device, int):
        return jax.devices()[device]
    if isinstance(device, str):
        idx = int(device.split(":")[1]) if ":" in device else 0
        return jax.devices()[idx]
    if hasattr(device, "index"):  # Place
        return jax.devices()[device.index]
    return device


def memory_stats(device=None) -> dict:
    """Raw PJRT memory counters for the device (empty dict on backends that
    do not report, e.g. CPU)."""
    d = _device(device)
    try:
        return dict(d.memory_stats() or {})
    except Exception:
        return {}


def _observe(d) -> dict:
    st = memory_stats(d)
    in_use = int(st.get("bytes_in_use", 0))
    reserved = int(st.get("bytes_reserved", st.get("pool_bytes", in_use)) or in_use)
    i = d.id
    if i in _sw_peak_alloc:
        _sw_peak_alloc[i] = max(_sw_peak_alloc[i], in_use)
    if i in _sw_peak_reserved:
        _sw_peak_reserved[i] = max(_sw_peak_reserved[i], reserved)
    return st


def memory_allocated(device=None) -> int:
    """Bytes currently held by live buffers on the device."""
    d = _device(device)
    return int(_observe(d).get("bytes_in_use", 0))


def max_memory_allocated(device=None) -> int:
    """Peak bytes_in_use — the hardware process-lifetime counter, or the
    software-observed peak after reset_max_memory_allocated()."""
    d = _device(device)
    st = _observe(d)
    if d.id in _sw_peak_alloc:
        return _sw_peak_alloc[d.id]
    return int(st.get("peak_bytes_in_use", st.get("bytes_in_use", 0)))


def memory_reserved(device=None) -> int:
    d = _device(device)
    st = _observe(d)
    in_use = int(st.get("bytes_in_use", 0))
    return int(st.get("bytes_reserved", st.get("pool_bytes", in_use)) or in_use)


def max_memory_reserved(device=None) -> int:
    d = _device(device)
    st = _observe(d)
    if d.id in _sw_peak_reserved:
        return _sw_peak_reserved[d.id]
    in_use = int(st.get("bytes_in_use", 0))
    cur_reserved = int(st.get("bytes_reserved", st.get("pool_bytes", in_use)) or in_use)
    # no reserved-peak counter in PJRT: never report less than current reserved
    return max(int(st.get("peak_bytes_in_use", in_use)), cur_reserved)


def reset_max_memory_allocated(device=None) -> None:
    d = _device(device)
    _sw_peak_alloc[d.id] = int(memory_stats(d).get("bytes_in_use", 0))


def reset_max_memory_reserved(device=None) -> None:
    d = _device(device)
    st = memory_stats(d)
    in_use = int(st.get("bytes_in_use", 0))
    _sw_peak_reserved[d.id] = int(st.get("bytes_reserved", st.get("pool_bytes", in_use)) or in_use)


def get_memory_info(device=None) -> dict:
    """{'total': bytes_limit, 'free': limit - in_use, 'used': in_use} —
    cudaMemGetInfo-style summary."""
    st = memory_stats(device)
    total = int(st.get("bytes_limit", 0))
    used = int(st.get("bytes_in_use", 0))
    return {"total": total, "used": used, "free": max(total - used, 0)}


def empty_cache() -> None:
    """XLA's allocator has no user-facing cache-drop; provided for API parity."""
    return None
