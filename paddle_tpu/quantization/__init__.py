"""paddle.quantization parity (/root/reference/python/paddle/quantization:
QuantConfig / BaseObserver / BaseQuanter / QAT / PTQ surface, observers/
abs_max.py, quanters/abs_max.py).

TPU-native: fake-quant runs through the tape with a straight-through
estimator (x + stop_gradient(q(x) - x)) so QAT trains with plain autograd;
weight-only int8 keeps int8 storage with per-channel scales and dequantizes
into bf16 matmuls (the MXU path) — the reference's cuBLAS int8 GEMM tier
(paddle/phi/kernels/fusion/cutlass) collapses to XLA's int8->bf16 fusion.
"""
from __future__ import annotations

import copy
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..nn.layer.layers import Layer
from ..ops.dispatch import apply
from ..tensor.tensor import Tensor

__all__ = [
    "QuantConfig", "BaseQuanter", "BaseObserver", "quanter", "QAT", "PTQ",
    "AbsmaxObserver", "GroupWiseWeightObserver", "FakeQuanterWithAbsMaxObserver",
    "QuantedLinear", "weight_quantize", "weight_dequantize", "weight_only_linear",
]


# ------------------------------------------------------------ base classes
class BaseObserver(Layer):
    """Collects statistics during calibration; produces scales."""

    def __init__(self):
        super().__init__()
        self._scale = None

    def scales(self):
        return self._scale

    def forward(self, x):
        raise NotImplementedError


class BaseQuanter(BaseObserver):
    """An observer that also simulates quantization in forward."""


def quanter(name):
    """Class decorator registering a quanter factory (parity:
    quantization/factory.py quanter)."""

    def deco(cls):
        globals()[name] = cls
        return cls

    return deco


class AbsmaxObserver(BaseObserver):
    """Per-tensor abs-max calibration observer (observers/abs_max.py)."""

    def __init__(self, quant_bits=8):
        super().__init__()
        self.quant_bits = quant_bits
        self._absmax = 0.0

    def forward(self, x):
        self._absmax = max(self._absmax, float(jnp.max(jnp.abs(x._value))))
        self._scale = self._absmax / (2 ** (self.quant_bits - 1) - 1)
        return x


class GroupWiseWeightObserver(BaseObserver):
    """Per-group abs-max for weights (observers/groupwise.py)."""

    def __init__(self, quant_bits=8, group_size=128):
        super().__init__()
        self.quant_bits = quant_bits
        self.group_size = group_size

    def forward(self, x):
        v = np.asarray(x._value)
        g = self.group_size
        rows = v.reshape(-1, v.shape[-1])
        pad = (-rows.shape[0]) % g
        if pad:
            rows = np.concatenate([rows, np.zeros((pad, rows.shape[1]), v.dtype)])
        grouped = np.abs(rows.reshape(-1, g, rows.shape[1])).max(axis=1)
        self._scale = grouped / (2 ** (self.quant_bits - 1) - 1)
        return x


class FakeQuanterWithAbsMaxObserver(BaseQuanter):
    """Moving-average abs-max fake quantization with STE gradients
    (quanters/abs_max.py FakeQuanterWithAbsMaxObserverLayer)."""

    def __init__(self, moving_rate=0.9, quant_bits=8, dtype="float32", name=None):
        super().__init__()
        self.moving_rate = moving_rate
        self.quant_bits = quant_bits
        self._state = 1.0
        self._accum = None

    def forward(self, x):
        qmax = 2 ** (self.quant_bits - 1) - 1
        cur = float(jnp.max(jnp.abs(jax.lax.stop_gradient(x._value))))
        if self.training:
            r = self.moving_rate
            self._accum = cur if self._accum is None else r * self._accum + (1 - r) * cur
            self._state = r * self._state + (1 - r)
            scale = self._accum / self._state
        else:
            scale = self._accum / self._state if self._accum is not None else cur
        self._scale = scale / qmax if scale else 1.0 / qmax
        s = max(self._scale, 1e-9)

        def f(v):
            q = jnp.clip(jnp.round(v / s), -qmax - 1, qmax) * s
            return v + jax.lax.stop_gradient(q - v)  # straight-through

        return apply(f, x, op_name="fake_quant_absmax")


# --------------------------------------------------------------- QuantConfig
class QuantConfig:
    """parity: quantization/config.py — which quanter to apply to weights /
    activations, with per-layer overrides."""

    def __init__(self, activation: Optional[BaseQuanter] = None,
                 weight: Optional[BaseQuanter] = None):
        self.activation = activation
        self.weight = weight
        self._layer_configs = {}
        self._type_configs = {}

    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for l in layers:
            self._layer_configs[id(l)] = (activation, weight)

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = layer_type if isinstance(layer_type, (list, tuple)) else [layer_type]
        for t in types:
            self._type_configs[t] = (activation, weight)

    def _for(self, layer):
        if id(layer) in self._layer_configs:
            return self._layer_configs[id(layer)]
        for t, cfg in self._type_configs.items():
            if isinstance(layer, t):
                return cfg
        return (self.activation, self.weight)


def _fresh(q):
    return copy.deepcopy(q) if q is not None else None


class QuantedLinear(Layer):
    """Linear wrapped with activation/weight quanters (wrapper.py analog)."""

    def __init__(self, linear, act_quanter, weight_quanter):
        super().__init__()
        self.linear = linear
        self.activation_quanter = act_quanter
        self.weight_quanter = weight_quanter

    @property
    def weight(self):
        return self.linear.weight

    @property
    def bias(self):
        return self.linear.bias

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.linear.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        import paddle_tpu.nn.functional as F

        return F.linear(x, w, self.linear.bias)


class QuantedConv2D(Layer):
    """Conv2D wrapped with quanters: the fake-quanted weight is swapped into
    the conv's parameter dict for the call, so the conv's own forward (and
    the tape through the quanter) are reused unchanged."""

    def __init__(self, conv, act_quanter, weight_quanter):
        super().__init__()
        self.conv = conv
        self.activation_quanter = act_quanter
        self.weight_quanter = weight_quanter

    @property
    def weight(self):
        return self.conv.weight

    @property
    def bias(self):
        return self.conv.bias

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        if self.weight_quanter is None:
            return self.conv(x)
        q_w = self.weight_quanter(self.conv.weight)
        saved = self.conv._parameters["weight"]
        self.conv._parameters["weight"] = q_w
        try:
            return self.conv(x)
        finally:
            self.conv._parameters["weight"] = saved


class Quantization:
    def __init__(self, config: QuantConfig):
        self._config = config

    def _wrap_model(self, model: Layer, inplace: bool) -> Layer:
        from ..nn import Conv2D, Linear

        if not inplace:
            model = copy.deepcopy(model)

        def wrap(parent):
            for name, sub in list(parent._sub_layers.items()):
                if isinstance(sub, Linear):
                    act_q, w_q = self._config._for(sub)
                    parent._sub_layers[name] = QuantedLinear(
                        sub, _fresh(act_q), _fresh(w_q))
                elif isinstance(sub, Conv2D):
                    act_q, w_q = self._config._for(sub)
                    parent._sub_layers[name] = QuantedConv2D(
                        sub, _fresh(act_q), _fresh(w_q))
                else:
                    wrap(sub)

        wrap(model)
        return model

    def convert(self, model: Layer, inplace: bool = False) -> Layer:
        """Bake observed scales: replace fake-quant wrappers with plain layers
        whose weights are quantize->dequantize'd constants (deploy form)."""
        if not inplace:
            model = copy.deepcopy(model)

        def unwrap(parent):
            for name, sub in list(parent._sub_layers.items()):
                if isinstance(sub, (QuantedLinear, QuantedConv2D)):
                    lin = sub.linear if isinstance(sub, QuantedLinear) else sub.conv
                    scales = (sub.weight_quanter.scales()
                              if sub.weight_quanter is not None else None)
                    if scales is not None and np.any(np.asarray(scales)):
                        s = max(float(np.max(np.asarray(scales))), 1e-9)
                        qmax = 127
                        w = np.asarray(lin.weight._value)
                        lin.weight.set_value(
                            (np.clip(np.round(w / s), -128, qmax) * s).astype(w.dtype))
                    parent._sub_layers[name] = lin
                else:
                    unwrap(sub)

        unwrap(model)
        return model


class QAT(Quantization):
    """quantization-aware training: insert fake quanters (qat.py:27)."""

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        return self._wrap_model(model, inplace)


class PTQ(Quantization):
    """post-training quantization: insert observers, calibrate, convert
    (ptq.py:28)."""

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        return self._wrap_model(model, inplace)


# ------------------------------------------- weight-only int8 / fp8 tier
_FP8_MAX = 448.0  # e4m3fn finite max


def weight_quantize(w, algo="weight_only_int8", group_size=-1):
    """-> (quantized weight, per-out-channel fp scales). w: [in, out].

    ``algo='weight_only_int8'`` → int8 rows scaled to ±127;
    ``algo='weight_only_fp8'`` (or 'fp8'/'float8_e4m3fn') → float8_e4m3fn
    storage scaled to ±448 (reference fp8 gemm tier:
    /root/reference/paddle/phi/kernels/fusion/fp8_gemm/)."""
    wv = np.asarray(w._value if isinstance(w, Tensor) else w)
    if algo in ("weight_only_fp8", "fp8", "float8_e4m3fn"):
        scale = np.maximum(np.abs(wv).max(axis=0), 1e-9) / _FP8_MAX
        q = jnp.asarray(np.clip(wv / scale, -_FP8_MAX, _FP8_MAX),
                        jnp.float8_e4m3fn)
        return Tensor(q), Tensor(jnp.asarray(scale.astype(np.float32)))
    if algo not in ("weight_only_int8", "int8"):
        # an unknown algo must not silently produce int8 output labelled as
        # something else (e.g. 'weight_only_int4' mislabelling the storage)
        raise ValueError(
            f"weight_quantize: unrecognized algo {algo!r}; supported: "
            "'weight_only_int8'/'int8', "
            "'weight_only_fp8'/'fp8'/'float8_e4m3fn'")
    scale = np.maximum(np.abs(wv).max(axis=0), 1e-9) / 127.0
    q = np.clip(np.round(wv / scale), -128, 127).astype(np.int8)
    return Tensor(jnp.asarray(q)), Tensor(jnp.asarray(scale.astype(np.float32)))


def weight_dequantize(qw, scale, algo="weight_only_int8"):
    def f(q, s):
        return q.astype(jnp.float32) * s

    return apply(f, qw, scale, op_name="weight_dequantize")


def weight_only_linear(x, qweight, bias=None, weight_scale=None, weight_dtype="int8"):
    """x @ dequant(qweight) + bias — quantized HBM storage, bf16/fp32 MXU
    compute. int8 rides the Pallas per-tile-dequant kernel; fp8 (e4m3)
    upcasts to the activation dtype at the matmul (weight-only fp8 = an HBM
    bandwidth/footprint play; the MXU computes in bf16 either way on v5e)."""
    if weight_dtype in ("fp8", "float8_e4m3fn", "weight_only_fp8"):
        def f8(xv, q, s):
            w = q.astype(xv.dtype) * s.astype(xv.dtype)
            return xv @ w

        out = apply(f8, x, qweight, weight_scale, op_name="weight_only_linear_fp8")
    else:
        def f(xv, q, s):
            from ..ops.pallas.int8_matmul import int8_matmul

            return int8_matmul(xv, q, s)

        out = apply(f, x, qweight, weight_scale, op_name="weight_only_linear")
    if bias is not None:
        from ..tensor import math as _m

        out = _m.add(out, bias)
    return out
