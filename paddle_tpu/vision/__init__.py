"""paddle_tpu.vision (parity: python/paddle/vision)."""
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from . import ops  # noqa: F401
from . import transforms  # noqa: F401

# image backend registry (parity: python/paddle/vision/image.py —
# set_image_backend/get_image_backend/image_load over PIL|cv2)
_image_backend = "pil"


def set_image_backend(backend: str):
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(f"unsupported image backend {backend!r} "
                         "(expected 'pil'|'cv2'|'tensor')")
    global _image_backend
    _image_backend = backend


def get_image_backend() -> str:
    return _image_backend


def image_load(path: str, backend: str = None):
    """Load an image via the configured backend (parity: image.py:image_load).
    'tensor' returns an HWC uint8 paddle Tensor; 'pil' a PIL.Image; 'cv2' a
    BGR ndarray when cv2 is installed."""
    b = backend or _image_backend
    if b not in ("pil", "cv2", "tensor"):
        raise ValueError(f"unsupported image backend {b!r} "
                         "(expected 'pil'|'cv2'|'tensor')")
    if b == "cv2":
        try:
            import cv2
        except ImportError as e:
            raise RuntimeError("cv2 backend requested but OpenCV is not "
                               "installed") from e
        return cv2.imread(path)
    from PIL import Image

    img = Image.open(path)
    if b == "tensor":
        import numpy as np

        from .. import to_tensor as _tt

        return _tt(np.asarray(img))
    return img


__all__ = ["datasets", "models", "ops", "transforms", "set_image_backend",
           "get_image_backend", "image_load"]
