"""Pretrained-weight loading mechanics (reference analog: the
get_weights_path_from_url + load_dict flow every factory in
python/paddle/vision/models/*.py runs when ``pretrained=True``).

Sandbox stance: no network — weights come from LOCAL files:
  * ``pretrained=<path>``: load that file directly;
  * ``pretrained=True``: look for ``<arch>.npz`` / ``<arch>.pdparams`` under
    ``$PADDLE_TPU_PRETRAINED_HOME`` (default ``~/.cache/paddle_tpu/weights``).
Formats: ``.npz`` archives of named arrays, or ``paddle.save``d state_dicts.
"""
from __future__ import annotations

import os
from typing import Union

import numpy as np

__all__ = ["load_pretrained"]


def _weights_home() -> str:
    return os.environ.get(
        "PADDLE_TPU_PRETRAINED_HOME",
        os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu", "weights"))


def load_pretrained(model, arch: str, pretrained: Union[bool, str]):
    """Fill ``model`` with pretrained weights; returns the model."""
    if not pretrained:
        return model
    if isinstance(pretrained, str):
        path = pretrained
    else:
        home = _weights_home()
        for ext in (".npz", ".pdparams"):
            cand = os.path.join(home, arch + ext)
            if os.path.exists(cand):
                path = cand
                break
        else:
            raise RuntimeError(
                f"pretrained weights for {arch!r} not found under {home} "
                "(downloading is disabled in this environment; place "
                f"{arch}.npz or {arch}.pdparams there, or pass "
                "pretrained='/path/to/weights')")
    if not os.path.exists(path):
        raise FileNotFoundError(f"pretrained weight file not found: {path}")

    from ...tensor.tensor import Tensor

    if path.endswith(".npz"):
        arrays = dict(np.load(path))
        state = {k: Tensor(v) for k, v in arrays.items()}
    else:
        from ...framework.framework_io import load as p_load

        state = p_load(path)
        state = {k: (v if isinstance(v, Tensor) else Tensor(np.asarray(v)))
                 for k, v in state.items()}
    model.set_state_dict(state)
    return model
