"""ShuffleNetV2 (parity: vision/models/shufflenetv2.py)."""
from __future__ import annotations

from ... import nn
from ...tensor.manipulation import concat, flatten, split
from ._utils import load_pretrained

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_5",
           "shufflenet_v2_x1_0", "shufflenet_v2_x1_5", "shufflenet_v2_x2_0"]

_STAGE_OUT = {
    0.25: [24, 24, 48, 96, 512],
    0.5: [24, 48, 96, 192, 1024],
    1.0: [24, 116, 232, 464, 1024],
    1.5: [24, 176, 352, 704, 1024],
    2.0: [24, 244, 488, 976, 2048],
}


def _conv_bn(inp, out, k, stride=1, groups=1, act=True):
    layers = [nn.Conv2D(inp, out, k, stride=stride, padding=k // 2, groups=groups,
                        bias_attr=False), nn.BatchNorm2D(out)]
    if act:
        layers.append(nn.ReLU())
    return nn.Sequential(*layers)


class ShuffleUnit(nn.Layer):
    def __init__(self, inp, out, stride):
        super().__init__()
        self.stride = stride
        branch = out // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                _conv_bn(inp // 2, branch, 1),
                _conv_bn(branch, branch, 3, groups=branch, act=False),
                _conv_bn(branch, branch, 1),
            )
            self.branch1 = None
        else:
            self.branch1 = nn.Sequential(
                _conv_bn(inp, inp, 3, stride=2, groups=inp, act=False),
                _conv_bn(inp, branch, 1),
            )
            self.branch2 = nn.Sequential(
                _conv_bn(inp, branch, 1),
                _conv_bn(branch, branch, 3, stride=2, groups=branch, act=False),
                _conv_bn(branch, branch, 1),
            )
        self.shuffle = nn.ChannelShuffle(2)

    def forward(self, x):
        if self.stride == 1:
            x1, x2 = split(x, 2, axis=1)
            out = concat([x1, self.branch2(x2)], axis=1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return self.shuffle(out)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000, with_pool=True):
        super().__init__()
        chans = _STAGE_OUT[scale]
        self.num_classes = num_classes
        self.conv1 = _conv_bn(3, chans[0], 3, stride=2)
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        inp = chans[0]
        for out, reps in zip(chans[1:4], (4, 8, 4)):
            units = [ShuffleUnit(inp, out, 2)]
            units += [ShuffleUnit(out, out, 1) for _ in range(reps - 1)]
            stages.append(nn.Sequential(*units))
            inp = out
        self.stages = nn.Sequential(*stages)
        self.conv_last = _conv_bn(inp, chans[4], 1)
        self.pool = nn.AdaptiveAvgPool2D(1) if with_pool else None
        if num_classes > 0:
            self.fc = nn.Linear(chans[4], num_classes)

    def forward(self, x):
        x = self.conv_last(self.stages(self.maxpool(self.conv1(x))))
        if self.pool is not None:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


def _factory(scale):
    def f(pretrained=False, **kwargs):
        model = ShuffleNetV2(scale=scale, **kwargs)
        return load_pretrained(model, (f"shufflenet_v2_x{scale}".replace(".", "_") if scale != 1.0 else "shufflenet_v2_x1_0"), pretrained)

    return f


shufflenet_v2_x0_25 = _factory(0.25)
shufflenet_v2_x0_5 = _factory(0.5)
shufflenet_v2_x1_0 = _factory(1.0)
shufflenet_v2_x1_5 = _factory(1.5)
shufflenet_v2_x2_0 = _factory(2.0)
