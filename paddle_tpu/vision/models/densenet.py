"""DenseNet (parity: vision/models/densenet.py)."""
from __future__ import annotations

from ... import nn
from ...tensor.manipulation import concat, flatten
from ._utils import load_pretrained

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169", "densenet201",
           "densenet264"]

_CFG = {
    121: (64, 32, (6, 12, 24, 16)),
    161: (96, 48, (6, 12, 36, 24)),
    169: (64, 32, (6, 12, 32, 32)),
    201: (64, 32, (6, 12, 48, 32)),
    264: (64, 32, (6, 12, 64, 48)),
}


class DenseLayer(nn.Layer):
    def __init__(self, inp, growth, bn_size=4, dropout=0.0):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(inp)
        self.relu = nn.ReLU()
        self.conv1 = nn.Conv2D(inp, bn_size * growth, 1, bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth)
        self.conv2 = nn.Conv2D(bn_size * growth, growth, 3, padding=1, bias_attr=False)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        out = self.conv1(self.relu(self.norm1(x)))
        out = self.conv2(self.relu(self.norm2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return concat([x, out], axis=1)


class Transition(nn.Layer):
    def __init__(self, inp, out):
        super().__init__()
        self.norm = nn.BatchNorm2D(inp)
        self.relu = nn.ReLU()
        self.conv = nn.Conv2D(inp, out, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.norm(x))))


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000, with_pool=True):
        super().__init__()
        init_c, growth, blocks = _CFG[layers]
        self.num_classes = num_classes
        feats = [nn.Conv2D(3, init_c, 7, stride=2, padding=3, bias_attr=False),
                 nn.BatchNorm2D(init_c), nn.ReLU(), nn.MaxPool2D(3, stride=2, padding=1)]
        ch = init_c
        for bi, reps in enumerate(blocks):
            for _ in range(reps):
                feats.append(DenseLayer(ch, growth, bn_size, dropout))
                ch += growth
            if bi != len(blocks) - 1:
                feats.append(Transition(ch, ch // 2))
                ch //= 2
        feats += [nn.BatchNorm2D(ch), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        self.pool = nn.AdaptiveAvgPool2D(1) if with_pool else None
        if num_classes > 0:
            self.fc = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.pool is not None:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


def _factory(n):
    def f(pretrained=False, **kwargs):
        model = DenseNet(layers=n, **kwargs)
        return load_pretrained(model, f"densenet{n}", pretrained)

    return f


densenet121 = _factory(121)
densenet161 = _factory(161)
densenet169 = _factory(169)
densenet201 = _factory(201)
densenet264 = _factory(264)
