"""MobileNetV1 (parity: vision/models/mobilenetv1.py) — depthwise-separable
conv stacks; depthwise = grouped conv, which XLA maps to MXU-friendly
batch-grouped contractions."""
from __future__ import annotations

from ... import nn
from ...tensor.manipulation import flatten
from ._utils import load_pretrained

__all__ = ["MobileNetV1", "mobilenet_v1"]


def _dw_sep(inp, out, stride):
    return nn.Sequential(
        nn.Conv2D(inp, inp, 3, stride=stride, padding=1, groups=inp, bias_attr=False),
        nn.BatchNorm2D(inp), nn.ReLU(),
        nn.Conv2D(inp, out, 1, bias_attr=False),
        nn.BatchNorm2D(out), nn.ReLU(),
    )


class MobileNetV1(nn.Layer):
    def __init__(self, scale: float = 1.0, num_classes: int = 1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes

        def c(ch):
            return max(int(ch * scale), 8)

        self.features = nn.Sequential(
            nn.Conv2D(3, c(32), 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(c(32)), nn.ReLU(),
            _dw_sep(c(32), c(64), 1),
            _dw_sep(c(64), c(128), 2), _dw_sep(c(128), c(128), 1),
            _dw_sep(c(128), c(256), 2), _dw_sep(c(256), c(256), 1),
            _dw_sep(c(256), c(512), 2),
            *[_dw_sep(c(512), c(512), 1) for _ in range(5)],
            _dw_sep(c(512), c(1024), 2), _dw_sep(c(1024), c(1024), 1),
        )
        self.pool = nn.AdaptiveAvgPool2D(1) if with_pool else None
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.pool is not None:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    model = MobileNetV1(scale=scale, **kwargs)
    return load_pretrained(model, "mobilenet_v1", pretrained)
