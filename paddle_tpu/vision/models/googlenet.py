"""GoogLeNet / Inception v1 (parity: vision/models/googlenet.py)."""
from __future__ import annotations

from ... import nn
from ...tensor.manipulation import concat, flatten
from ._utils import load_pretrained

__all__ = ["GoogLeNet", "googlenet"]


def _cbr(inp, out, k, **kw):
    return nn.Sequential(nn.Conv2D(inp, out, k, **kw), nn.ReLU())


class Inception(nn.Layer):
    def __init__(self, inp, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = _cbr(inp, c1, 1)
        self.b2 = nn.Sequential(_cbr(inp, c3r, 1), _cbr(c3r, c3, 3, padding=1))
        self.b3 = nn.Sequential(_cbr(inp, c5r, 1), _cbr(c5r, c5, 5, padding=2))
        self.b4 = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1), _cbr(inp, proj, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)], axis=1)


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes: int = 1000, with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.stem = nn.Sequential(
            _cbr(3, 64, 7, stride=2, padding=3), nn.MaxPool2D(3, stride=2, padding=1),
            _cbr(64, 64, 1), _cbr(64, 192, 3, padding=1),
            nn.MaxPool2D(3, stride=2, padding=1),
        )
        self.inc3 = nn.Sequential(
            Inception(192, 64, 96, 128, 16, 32, 32),
            Inception(256, 128, 128, 192, 32, 96, 64),
            nn.MaxPool2D(3, stride=2, padding=1),
        )
        self.inc4 = nn.Sequential(
            Inception(480, 192, 96, 208, 16, 48, 64),
            Inception(512, 160, 112, 224, 24, 64, 64),
            Inception(512, 128, 128, 256, 24, 64, 64),
            Inception(512, 112, 144, 288, 32, 64, 64),
            Inception(528, 256, 160, 320, 32, 128, 128),
            nn.MaxPool2D(3, stride=2, padding=1),
        )
        self.inc5 = nn.Sequential(
            Inception(832, 256, 160, 320, 32, 128, 128),
            Inception(832, 384, 192, 384, 48, 128, 128),
        )
        self.pool = nn.AdaptiveAvgPool2D(1) if with_pool else None
        if num_classes > 0:
            self.dropout = nn.Dropout(0.4)
            self.fc = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.inc5(self.inc4(self.inc3(self.stem(x))))
        if self.pool is not None:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(flatten(x, 1)))
        # paddle returns (out, aux1, aux2); aux heads are train-time only
        # extras — mirrored as the main logits for API shape parity
        return x, x, x


def googlenet(pretrained=False, **kwargs):
    model = GoogLeNet(**kwargs)
    return load_pretrained(model, "googlenet", pretrained)
