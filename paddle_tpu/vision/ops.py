"""paddle.vision.ops parity (/root/reference/python/paddle/vision/ops.py:47
export surface: nms/matrix_nms/roi_align/roi_pool/psroi_pool/box_coder/
prior_box/deform_conv2d/yolo_box/distribute_fpn_proposals).

TPU-native formulations: NMS as a fixed-iteration lax.scan over a
score-sorted IoU matrix (no data-dependent loops), RoI ops as bilinear
gathers (XLA batch-gather), deformable conv as an im2col of offset bilinear
samples followed by one MXU matmul — replacing the reference's CUDA kernels
(paddle/phi/kernels/{nms_kernel,roi_align_kernel,deformable_conv_kernel}.h).
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .. import nn
from ..ops.dispatch import apply
from ..tensor._helpers import to_tensor_like as _t
from ..tensor.tensor import Tensor

__all__ = [
    "nms", "matrix_nms", "roi_align", "RoIAlign", "roi_pool", "RoIPool",
    "psroi_pool", "PSRoIPool", "box_coder", "prior_box", "deform_conv2d",
    "DeformConv2D", "yolo_box", "yolo_loss", "distribute_fpn_proposals",
    "generate_proposals", "read_file", "decode_jpeg",
]


def _iou_matrix(boxes):
    """[N,4] xyxy -> [N,N] IoU."""
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    xx1 = jnp.maximum(x1[:, None], x1[None, :])
    yy1 = jnp.maximum(y1[:, None], y1[None, :])
    xx2 = jnp.minimum(x2[:, None], x2[None, :])
    yy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.maximum(xx2 - xx1, 0) * jnp.maximum(yy2 - yy1, 0)
    return inter / jnp.maximum(area[:, None] + area[None, :] - inter, 1e-9)


def nms(boxes, iou_threshold: float = 0.3, scores=None, category_idxs=None,
        categories=None, top_k: Optional[int] = None):
    """Greedy hard-NMS. Compiled form: sort by score, one pass of a scan
    suppressing boxes with IoU > thr against any earlier KEPT box."""
    boxes = _t(boxes)
    n = boxes._value.shape[0]
    if scores is None:
        scores_v = jnp.arange(n, 0, -1, dtype=jnp.float32)  # keep input order
    else:
        scores_v = _t(scores)._value.astype(jnp.float32)
    if category_idxs is not None:
        # category-aware: shift coordinates non-negative, then offset each
        # category by more than the full coordinate span so cross-class IoU
        # is exactly 0 (the standard batched-NMS trick; abs-based spans
        # overlap for negative coordinates)
        cat = _t(category_idxs)._value.astype(jnp.float32)
        lo = float(jnp.min(boxes._value))
        span = float(jnp.max(boxes._value)) - lo + 1.0
        off = (cat * span)[:, None]
        shifted = (boxes._value - lo) + off
    else:
        shifted = boxes._value

    def f(bv):
        order = jnp.argsort(-scores_v)
        b = bv[order]
        iou = _iou_matrix(b)

        def body(keep, i):
            # suppressed if any kept earlier (higher-score) box overlaps it
            earlier = jnp.where(jnp.arange(n) < i, iou[i] * keep, 0.0)
            sup = jnp.any(earlier > iou_threshold)
            keep = keep.at[i].set(jnp.where(sup, 0.0, 1.0))
            return keep, None

        keep, _ = lax.scan(body, jnp.ones((n,), jnp.float32), jnp.arange(n))
        return order, keep

    order, keep = f(shifted)  # single scan pass; jit would retrace per call
    order_np = np.asarray(order)
    keep_np = np.asarray(keep) > 0  # keep[j] refers to sorted position j
    kept = order_np[keep_np]  # original indices, score-descending
    if top_k is not None:
        kept = kept[:top_k]
    return Tensor(jnp.asarray(kept.astype(np.int64)))


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (SOLOv2): soft decay by the min pairwise-IoU statistic."""
    bb = _t(bboxes)._value
    sc = _t(scores)._value
    if bb.ndim == 3:
        bb, sc = bb[0], sc[0]
    out_boxes, out_idx = [], []
    for cls in range(sc.shape[0]):
        if cls == background_label:
            continue
        s = np.asarray(sc[cls])
        sel = np.where(s > score_threshold)[0]
        if sel.size == 0:
            continue
        order = sel[np.argsort(-s[sel])][:nms_top_k]
        b = np.asarray(bb[order])
        iou = np.asarray(_iou_matrix(jnp.asarray(b)))
        n = len(order)
        decay = np.ones(n)
        for i in range(1, n):
            ious_i = iou[i, :i]
            max_iou = ious_i.max() if i else 0.0
            if use_gaussian:
                decay[i] = np.exp(-(max_iou ** 2) / gaussian_sigma)
            else:
                decay[i] = 1 - max_iou
        dec_scores = s[order] * decay
        keep = dec_scores > post_threshold
        for j in np.where(keep)[0]:
            out_boxes.append([cls, dec_scores[j], *b[j]])
            out_idx.append(order[j])
    if not out_boxes:
        outs = [Tensor(jnp.zeros((0, 6), jnp.float32))]
        if return_index:
            outs.append(Tensor(jnp.zeros((0,), jnp.int64)))
        if return_rois_num:
            outs.append(Tensor(jnp.asarray([0])))
        return tuple(outs) if len(outs) > 1 else outs[0]
    arr = np.asarray(out_boxes, np.float32)
    order = np.argsort(-arr[:, 1])[:keep_top_k]
    res = Tensor(jnp.asarray(arr[order]))
    outs = [res]
    if return_index:
        outs.append(Tensor(jnp.asarray(np.asarray(out_idx)[order].astype(np.int64))))
    if return_rois_num:
        outs.append(Tensor(jnp.asarray([len(order)])))
    return tuple(outs) if len(outs) > 1 else res


def _bilinear_sample(feat, y, x):
    """feat [C,H,W]; y/x arbitrary-shape coords -> [C, *coords.shape]."""
    H, W = feat.shape[-2], feat.shape[-1]
    y0 = jnp.clip(jnp.floor(y), 0, H - 1)
    x0 = jnp.clip(jnp.floor(x), 0, W - 1)
    y1 = jnp.clip(y0 + 1, 0, H - 1)
    x1 = jnp.clip(x0 + 1, 0, W - 1)
    wy = jnp.clip(y - y0, 0, 1)
    wx = jnp.clip(x - x0, 0, 1)
    y0i, y1i, x0i, x1i = (v.astype(jnp.int32) for v in (y0, y1, x0, x1))
    v00 = feat[:, y0i, x0i]
    v01 = feat[:, y0i, x1i]
    v10 = feat[:, y1i, x0i]
    v11 = feat[:, y1i, x1i]
    return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
            + v10 * wy * (1 - wx) + v11 * wy * wx)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign via bilinear gathers (reference roi_align_kernel.h)."""
    x = _t(x)
    boxes = _t(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    bn = np.asarray(_t(boxes_num)._value)
    batch_of_roi = np.repeat(np.arange(len(bn)), bn).astype(np.int32)
    ratio = 2 if sampling_ratio <= 0 else sampling_ratio
    off = 0.5 if aligned else 0.0

    def f(feat, bxs):
        def one_roi(bi, box):
            fm = feat[bi]
            x1, y1, x2, y2 = box * spatial_scale - off
            rh = jnp.maximum((y2 - y1) / ph, 1e-6)
            rw = jnp.maximum((x2 - x1) / pw, 1e-6)
            iy = y1 + (jnp.arange(ph)[:, None, None, None] + 0.0) * rh + \
                rh * (jnp.arange(ratio)[None, None, :, None] + 0.5) / ratio
            ix = x1 + (jnp.arange(pw)[None, :, None, None] + 0.0) * rw + \
                rw * (jnp.arange(ratio)[None, None, None, :] + 0.5) / ratio
            iy = jnp.broadcast_to(iy, (ph, pw, ratio, ratio))
            ix = jnp.broadcast_to(ix, (ph, pw, ratio, ratio))
            vals = _bilinear_sample(fm, iy, ix)  # [C, ph, pw, r, r]
            return jnp.mean(vals, axis=(-2, -1))

        return jax.vmap(one_roi)(jnp.asarray(batch_of_roi), bxs)

    return apply(f, x, boxes, op_name="roi_align")


class RoIAlign(nn.Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size, self.spatial_scale)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Max-pool RoI (reference roi_pool_kernel.h): dense sample grid + max."""
    x = _t(x)
    boxes = _t(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    bn = np.asarray(_t(boxes_num)._value)
    batch_of_roi = np.repeat(np.arange(len(bn)), bn).astype(np.int32)
    # dense integer sampling: every cell of a bin up to R px/bin is visited
    # (bins larger than R px are max'd over an R-strided subsample)
    R = 16

    def f(feat, bxs):
        def one_roi(bi, box):
            fm = feat[bi]
            x1, y1, x2, y2 = jnp.round(box * spatial_scale)
            rh = jnp.maximum((y2 - y1 + 1) / ph, 1.0)
            rw = jnp.maximum((x2 - x1 + 1) / pw, 1.0)
            jgrid = jnp.arange(R).astype(jnp.float32)
            iy = y1 + jnp.arange(ph)[:, None, None, None] * rh + \
                jnp.minimum(jgrid * jnp.maximum(rh / R, 1.0), rh - 1)[None, None, :, None]
            ix = x1 + jnp.arange(pw)[None, :, None, None] * rw + \
                jnp.minimum(jgrid * jnp.maximum(rw / R, 1.0), rw - 1)[None, None, None, :]
            iy = jnp.broadcast_to(iy, (ph, pw, R, R))
            ix = jnp.broadcast_to(ix, (ph, pw, R, R))
            H, W = fm.shape[-2:]
            valid = (iy[None] <= y2) & (ix[None] <= x2)
            vals = fm[:, jnp.clip(iy, 0, H - 1).astype(jnp.int32),
                      jnp.clip(ix, 0, W - 1).astype(jnp.int32)]
            vals = jnp.where(valid, vals, -jnp.inf)
            return jnp.max(vals, axis=(-2, -1))

        return jax.vmap(one_roi)(jnp.asarray(batch_of_roi), bxs)

    return apply(f, x, boxes, op_name="roi_pool")


class RoIPool(nn.Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size, self.spatial_scale)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Position-sensitive RoI pool: channel group (i,j) feeds bin (i,j)."""
    x = _t(x)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    C = x._value.shape[1]
    if C % (ph * pw):
        raise ValueError(f"channels {C} must be divisible by {ph}x{pw}")
    co = C // (ph * pw)
    pooled = roi_align(x, boxes, boxes_num, output_size, spatial_scale, aligned=False)

    def _ps_gather(r, ph, pw):
        # reference layout (phi/kernels/cpu/psroi_pool_kernel.cc:151):
        # input_channel = (c * pooled_height + i) * pooled_width + j, i.e. the
        # channel axis decomposes as (co, ph, pw) — bin (i, j) reads channel
        # group [:, :, i, j] of that decomposition.
        outs = []
        for i in range(ph):
            row = []
            for j in range(pw):
                row.append(r[:, :, i, j, i, j])  # [N, co]
            outs.append(jnp.stack(row, axis=-1))  # [N, co, pw]
        return jnp.stack(outs, axis=-2)  # [N, co, ph, pw]

    return apply(lambda p: _ps_gather(p.reshape(p.shape[0], co, ph, pw, ph, pw), ph, pw),
                 pooled, op_name="psroi_pool")


class PSRoIPool(nn.Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size, self.spatial_scale)


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, name=None):
    """Encode/decode boxes against priors (reference box_coder op)."""
    pb = _t(prior_box)._value.astype(jnp.float32)
    tb = _t(target_box)._value.astype(jnp.float32)
    if prior_box_var is None:
        var = jnp.ones((4,), jnp.float32)
    elif isinstance(prior_box_var, (list, tuple)):
        var = jnp.asarray(prior_box_var, jnp.float32)
    else:
        var = _t(prior_box_var)._value.astype(jnp.float32)
    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    ph_ = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw * 0.5
    pcy = pb[:, 1] + ph_ * 0.5
    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + norm
        th = tb[:, 3] - tb[:, 1] + norm
        tcx = tb[:, 0] + tw * 0.5
        tcy = tb[:, 1] + th * 0.5
        out = jnp.stack([
            (tcx[:, None] - pcx[None, :]) / pw[None, :],
            (tcy[:, None] - pcy[None, :]) / ph_[None, :],
            jnp.log(tw[:, None] / pw[None, :]),
            jnp.log(th[:, None] / ph_[None, :]),
        ], axis=-1) / var
        return Tensor(out)
    # decode_center_size: tb [N, M, 4] deltas (axis selects broadcast dim)
    if tb.ndim == 2:
        tb = tb[:, None, :]
    d = tb * var
    if axis == 0:
        cw, ch_, cx, cy = pw[None, :], ph_[None, :], pcx[None, :], pcy[None, :]
    else:
        cw, ch_, cx, cy = pw[:, None], ph_[:, None], pcx[:, None], pcy[:, None]
    ocx = d[..., 0] * cw + cx
    ocy = d[..., 1] * ch_ + cy
    ow = jnp.exp(d[..., 2]) * cw
    oh = jnp.exp(d[..., 3]) * ch_
    out = jnp.stack([ocx - ow / 2, ocy - oh / 2,
                     ocx + ow / 2 - norm, ocy + oh / 2 - norm], axis=-1)
    return Tensor(out)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),  # noqa: A002
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior boxes (reference prior_box op) — host-side static grid."""
    fh, fw = _t(input)._value.shape[-2:]
    ih, iw = _t(image)._value.shape[-2:]
    step_h = steps[1] or ih / fh
    step_w = steps[0] or iw / fw
    ars = [1.0]
    for ar in aspect_ratios:
        if ar != 1.0:
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
    boxes = []
    for i in range(fh):
        for j in range(fw):
            cx = (j + offset) * step_w
            cy = (i + offset) * step_h
            cell = []
            for k, ms in enumerate(min_sizes):
                cell.append((cx, cy, ms, ms))
                if max_sizes:
                    bs = math.sqrt(ms * max_sizes[k])
                    cell.append((cx, cy, bs, bs))
                for ar in ars:
                    if ar == 1.0:
                        continue
                    cell.append((cx, cy, ms * math.sqrt(ar), ms / math.sqrt(ar)))
            for cx_, cy_, bw, bh in cell:
                boxes.append([(cx_ - bw / 2) / iw, (cy_ - bh / 2) / ih,
                              (cx_ + bw / 2) / iw, (cy_ + bh / 2) / ih])
    arr = np.asarray(boxes, np.float32).reshape(fh, fw, -1, 4)
    if clip:
        arr = arr.clip(0, 1)
    var = np.broadcast_to(np.asarray(variance, np.float32), arr.shape).copy()
    return Tensor(jnp.asarray(arr)), Tensor(jnp.asarray(var))


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0, dilation=1,
                  deformable_groups=1, groups=1, mask=None, name=None):
    """Deformable conv v1/v2: bilinear-sample the input at offset positions
    (im2col of deformed samples), then one dense matmul — the MXU mapping of
    the reference's deformable_conv CUDA kernel."""
    x, offset, weight = _t(x), _t(offset), _t(weight)
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    padding = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dilation = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    kh, kw = weight._value.shape[-2:]
    args = [x, offset, weight] + ([_t(mask)] if mask is not None else []) + \
        ([_t(bias)] if bias is not None else [])
    has_mask = mask is not None
    has_bias = bias is not None

    def f(xv, ov, wv, *rest):
        mv = rest[0] if has_mask else None
        bv = rest[-1] if has_bias else None
        N, C, H, W = xv.shape
        ph_, pw_ = padding
        xp = jnp.pad(xv, ((0, 0), (0, 0), (ph_, ph_), (pw_, pw_)))
        Hp, Wp = xp.shape[-2:]
        oh = (H + 2 * ph_ - dilation[0] * (kh - 1) - 1) // stride[0] + 1
        ow = (W + 2 * pw_ - dilation[1] * (kw - 1) - 1) // stride[1] + 1
        # base sampling grid [oh, ow, kh, kw]
        by = (jnp.arange(oh) * stride[0])[:, None, None, None] + \
            (jnp.arange(kh) * dilation[0])[None, None, :, None]
        bx = (jnp.arange(ow) * stride[1])[None, :, None, None] + \
            (jnp.arange(kw) * dilation[1])[None, None, None, :]
        by = jnp.broadcast_to(by, (oh, ow, kh, kw)).astype(jnp.float32)
        bx = jnp.broadcast_to(bx, (oh, ow, kh, kw)).astype(jnp.float32)
        # offsets: [N, 2*dg*kh*kw, oh, ow] (y then x per kernel point)
        off = ov.reshape(N, deformable_groups, kh * kw, 2, oh, ow)
        oy = jnp.transpose(off[:, :, :, 0], (0, 1, 3, 4, 2)).reshape(
            N, deformable_groups, oh, ow, kh, kw)
        ox = jnp.transpose(off[:, :, :, 1], (0, 1, 3, 4, 2)).reshape(
            N, deformable_groups, oh, ow, kh, kw)

        cg = C // deformable_groups

        def sample_one(xp_n, oy_n, ox_n, m_n=None):
            cols = []
            for g in range(deformable_groups):
                yy = by + oy_n[g]
                xx = bx + ox_n[g]
                v = _bilinear_sample(xp_n[g * cg:(g + 1) * cg], yy, xx)
                if m_n is not None:
                    v = v * m_n[g]
                cols.append(v)
            return jnp.concatenate(cols, axis=0)  # [C, oh, ow, kh, kw]

        if mv is not None:
            mm = jnp.transpose(
                mv.reshape(N, deformable_groups, kh * kw, oh, ow), (0, 1, 3, 4, 2)
            ).reshape(N, deformable_groups, oh, ow, kh, kw)
            cols = jax.vmap(sample_one)(xp, oy, ox, mm)
        else:
            cols = jax.vmap(lambda a, b, c: sample_one(a, b, c))(xp, oy, ox)
        # cols: [N, C, oh, ow, kh, kw] -> matmul with weight [O, C/groups, kh, kw]
        O = wv.shape[0]
        if groups == 1:
            wflat = wv.reshape(O, -1)
            cflat = jnp.transpose(cols, (0, 2, 3, 1, 4, 5)).reshape(N, oh, ow, -1)
            out = jnp.einsum("nhwc,oc->nohw", cflat, wflat)
        else:
            # grouped conv: output-channel group g reads input-channel
            # slice g (reference layout: weight [O, C/groups, kh, kw] with
            # output channels blocked by group)
            cgrp = C // groups
            wg = wv.reshape(groups, O // groups, cgrp * kh * kw)
            cflat = jnp.transpose(cols, (0, 2, 3, 1, 4, 5)).reshape(
                N, oh, ow, groups, cgrp * kh * kw)
            out = jnp.einsum("nhwgc,goc->ngohw", cflat, wg).reshape(
                N, O, oh, ow)
        if bv is not None:
            out = out + bv[None, :, None, None]
        return out

    return apply(f, *args, op_name="deform_conv2d")


class DeformConv2D(nn.Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, deformable_groups=1, groups=1, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        k = (kernel_size, kernel_size) if isinstance(kernel_size, int) else kernel_size
        import jax.numpy as jnp2

        from ..nn.initializer import XavierNormal

        w = Tensor(jnp2.zeros((out_channels, in_channels // groups, *k), jnp2.float32),
                   stop_gradient=False)
        XavierNormal()(w)
        w.is_parameter = True
        self.weight = w
        self.add_parameter("weight", w)
        if bias_attr is not False:
            b = Tensor(jnp2.zeros((out_channels,), jnp2.float32), stop_gradient=False)
            b.is_parameter = True
            self.bias = b
            self.add_parameter("bias", b)
        else:
            self.bias = None
        self.stride, self.padding, self.dilation = stride, padding, dilation
        self.deformable_groups, self.groups = deformable_groups, groups

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias, self.stride,
                             self.padding, self.dilation, self.deformable_groups,
                             self.groups, mask)


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5):
    """Decode YOLOv3 head outputs to boxes+scores (reference yolo_box op)."""
    xv = _t(x)._value
    N, _, H, W = xv.shape
    na = len(anchors) // 2
    an = np.asarray(anchors, np.float32).reshape(na, 2)
    pred = jnp.transpose(xv.reshape(N, na, 5 + class_num, H, W), (0, 1, 3, 4, 2))
    gx = (jax.nn.sigmoid(pred[..., 0]) * scale_x_y - (scale_x_y - 1) / 2
          + jnp.arange(W)[None, None, None, :]) / W
    gy = (jax.nn.sigmoid(pred[..., 1]) * scale_x_y - (scale_x_y - 1) / 2
          + jnp.arange(H)[None, None, :, None]) / H
    input_h = downsample_ratio * H
    input_w = downsample_ratio * W
    bw = jnp.exp(pred[..., 2]) * an[None, :, None, None, 0] / input_w
    bh = jnp.exp(pred[..., 3]) * an[None, :, None, None, 1] / input_h
    conf = jax.nn.sigmoid(pred[..., 4])
    probs = jax.nn.sigmoid(pred[..., 5:]) * conf[..., None]
    imgs = _t(img_size)._value.astype(jnp.float32)  # [N, 2] (h, w)
    ih = imgs[:, 0][:, None, None, None]
    iw = imgs[:, 1][:, None, None, None]
    x1 = (gx - bw / 2) * iw
    y1 = (gy - bh / 2) * ih
    x2 = (gx + bw / 2) * iw
    y2 = (gy + bh / 2) * ih
    if clip_bbox:
        x1 = jnp.clip(x1, 0, iw - 1)
        y1 = jnp.clip(y1, 0, ih - 1)
        x2 = jnp.clip(x2, 0, iw - 1)
        y2 = jnp.clip(y2, 0, ih - 1)
    boxes = jnp.stack([x1, y1, x2, y2], -1).reshape(N, -1, 4)
    scores = probs.reshape(N, -1, class_num)
    mask = conf.reshape(N, -1) > conf_thresh
    boxes = jnp.where(mask[..., None], boxes, 0.0)
    scores = jnp.where(mask[..., None], scores, 0.0)
    return Tensor(boxes), Tensor(scores)


def _xywh_iou(b1, b2):
    """[..., 4] center-form xywh IoU, broadcasting leading dims."""
    l1 = b1[..., 0] - b1[..., 2] / 2
    r1 = b1[..., 0] + b1[..., 2] / 2
    t1 = b1[..., 1] - b1[..., 3] / 2
    bo1 = b1[..., 1] + b1[..., 3] / 2
    l2 = b2[..., 0] - b2[..., 2] / 2
    r2 = b2[..., 0] + b2[..., 2] / 2
    t2 = b2[..., 1] - b2[..., 3] / 2
    bo2 = b2[..., 1] + b2[..., 3] / 2
    # clamp at 0 only: decoded pred boxes (exp(logit)*anchor) can exceed 1
    # in normalized coords, and capping the intersection would underestimate
    # their IoU against the ignore threshold
    iw = jnp.maximum(jnp.minimum(r1, r2) - jnp.maximum(l1, l2), 0.0)
    ih = jnp.maximum(jnp.minimum(bo1, bo2) - jnp.maximum(t1, t2), 0.0)
    inter = iw * ih
    union = (r1 - l1) * (bo1 - t1) + (r2 - l2) * (bo2 - t2) - inter
    return inter / jnp.maximum(union, 1e-10)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss (parity:
    /root/reference/python/paddle/vision/ops.py:69, kernel
    paddle/phi/kernels/cpu/yolo_loss_kernel.cc): per-gt anchor matching by
    wh-IoU, sigmoid-CE on x/y, L1 on w/h (scaled by 2-gw*gh), objectness CE
    with ignore region (pred IoU > ignore_thresh), class CE with optional
    label smoothing. x [N, mask*(5+C), H, W]; gt_box [N, B, 4] normalized
    center-xywh; returns per-image loss [N]."""
    x_t, gtb_t, gtl_t = _t(x), _t(gt_box), _t(gt_label)
    gts_t = _t(gt_score) if gt_score is not None else None
    mask = list(anchor_mask)
    an_num = len(anchors) // 2
    mask_num = len(mask)

    def f(xv, gtb, gtl, *rest):
        gts = rest[0] if gts_t is not None else None
        N, _, h, w = xv.shape
        B = gtb.shape[1]
        input_size = downsample_ratio * h
        xr = xv.reshape(N, mask_num, 5 + class_num, h, w).transpose(
            0, 1, 3, 4, 2).astype(jnp.float32)
        if gts is None:
            gts = jnp.ones((N, B), jnp.float32)
        gts = gts.astype(jnp.float32)
        bias_xy = -0.5 * (scale_x_y - 1.0)
        smooth = min(1.0 / class_num, 1.0 / 40)
        pos_l = 1.0 - smooth if use_label_smooth else 1.0
        neg_l = smooth if use_label_smooth else 0.0

        def sce(logit, label):
            # stable sigmoid cross-entropy
            return jnp.maximum(logit, 0) - logit * label + jnp.log1p(
                jnp.exp(-jnp.abs(logit)))

        # ---- decoded pred boxes (for the ignore mask only: the decision is
        # argmax-like, so it rides stop_gradient)
        gx = jnp.arange(w, dtype=jnp.float32)[None, :]
        gy = jnp.arange(h, dtype=jnp.float32)[:, None]
        ms = jnp.asarray([(anchors[2 * m] / input_size,
                           anchors[2 * m + 1] / input_size) for m in mask],
                         jnp.float32)
        px = (gx + jax.nn.sigmoid(xr[..., 0]) * scale_x_y + bias_xy) / w
        py = (gy + jax.nn.sigmoid(xr[..., 1]) * scale_x_y + bias_xy) / h
        pw = jnp.exp(xr[..., 2]) * ms[None, :, None, None, 0]
        ph = jnp.exp(xr[..., 3]) * ms[None, :, None, None, 1]
        pred_box = jax.lax.stop_gradient(
            jnp.stack([px, py, pw, ph], -1).reshape(N, -1, 4))
        ious = _xywh_iou(pred_box[:, :, None, :], gtb[:, None, :, :])
        ious_max = jnp.max(ious, axis=-1)  # [N, mask*h*w]
        ignore = ious_max > ignore_thresh

        # ---- gt -> anchor matching by wh IoU against ALL anchors
        all_an = jnp.asarray([(anchors[2 * i] / input_size,
                               anchors[2 * i + 1] / input_size)
                              for i in range(an_num)], jnp.float32)
        gshift = jnp.concatenate([jnp.zeros_like(gtb[..., :2]),
                                  gtb[..., 2:]], -1)
        abox = jnp.concatenate([jnp.zeros_like(all_an), all_an], -1)
        an_iou = _xywh_iou(gshift[:, :, None, :], abox[None, None, :, :])
        best = jnp.argmax(an_iou, axis=-1)  # [N, B]
        mask_arr = jnp.asarray(mask, jnp.int32)
        in_mask = (best[:, :, None] == mask_arr[None, None, :])
        an_idx = jnp.argmax(in_mask, axis=-1)  # [N, B] position in mask
        valid = (gtb[..., 2] + gtb[..., 3] > 0) & in_mask.any(-1)

        gi = jnp.clip((gtb[..., 0] * w).astype(jnp.int32), 0, w - 1)
        gj = jnp.clip((gtb[..., 1] * h).astype(jnp.int32), 0, h - 1)
        tx = gtb[..., 0] * w - gi
        ty = gtb[..., 1] * h - gj
        man_w = ms[an_idx, 0]
        man_h = ms[an_idx, 1]
        tw = jnp.log(jnp.maximum(gtb[..., 2], 1e-9) / man_w)
        th = jnp.log(jnp.maximum(gtb[..., 3], 1e-9) / man_h)
        scale = (2.0 - gtb[..., 2] * gtb[..., 3]) * gts
        bidx = jnp.arange(N)[:, None]
        picked = xr[bidx, an_idx, gj, gi]  # [N, B, 5+C]
        coord = (sce(picked[..., 0], tx) + sce(picked[..., 1], ty)
                 + jnp.abs(picked[..., 2] - tw)
                 + jnp.abs(picked[..., 3] - th)) * scale
        onehot = (jnp.arange(class_num)[None, None, :]
                  == gtl[..., None].astype(jnp.int32))
        cls_t = jnp.where(onehot, pos_l, neg_l)
        cls = jnp.sum(sce(picked[..., 5:], cls_t), -1) * gts
        loss = jnp.sum(jnp.where(valid, coord + cls, 0.0), axis=1)

        # ---- objectness: positives overwrite in gt order (last wins, the
        # reference's sequential semantics); ignores contribute nothing
        objness = jnp.where(ignore, -1.0, 0.0)
        flat = an_idx * h * w + gj * w + gi  # [N, B]
        for j in range(B):
            tgt = jnp.where(valid[:, j], gts[:, j],
                            objness[bidx[:, 0], flat[:, j]])
            objness = objness.at[bidx[:, 0], flat[:, j]].set(tgt)
        pred_obj = xr[..., 4].reshape(N, -1)
        obj_l = jnp.where(objness > 0, sce(pred_obj, 1.0) * objness,
                          jnp.where(objness == 0, sce(pred_obj, 0.0), 0.0))
        return loss + jnp.sum(obj_l, axis=1)

    args = [x_t, gtb_t, gtl_t] + ([gts_t] if gts_t is not None else [])
    return apply(f, *args, op_name="yolo_loss")


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None,
                             name=None):
    """Assign RoIs to FPN levels by scale (reference op)."""
    rois = np.asarray(_t(fpn_rois)._value)
    off = 1.0 if pixel_offset else 0.0
    w = rois[:, 2] - rois[:, 0] + off
    h = rois[:, 3] - rois[:, 1] + off
    scale = np.sqrt(np.maximum(w * h, 1e-9))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-9)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(int)
    outs, idxs = [], []
    for level in range(min_level, max_level + 1):
        sel = np.where(lvl == level)[0]
        outs.append(Tensor(jnp.asarray(rois[sel])))
        idxs.append(sel)
    order = np.concatenate(idxs) if idxs else np.zeros(0, int)
    restore = np.argsort(order).astype(np.int32)
    nums = [Tensor(jnp.asarray([len(i)])) for i in idxs]
    return outs, Tensor(jnp.asarray(restore)), nums


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000, nms_thresh=0.5,
                       min_size=0.1, eta=1.0, pixel_offset=False,
                       return_rois_num=False, name=None):
    """RPN proposal generation (parity:
    /root/reference/python/paddle/vision/ops.py:2108, kernel
    paddle/phi/kernels/cpu/generate_proposals_kernel.cc): decode anchors with
    variance-scaled deltas, clip to image, drop tiny boxes, top-k -> NMS ->
    top-k. Detection post-processing is host-side (the serving pattern), so
    this composes numpy decode + the repo's nms.

    scores [N, A, H, W]; bbox_deltas [N, 4A, H, W]; anchors/variances
    [H, W, A, 4]. Returns (rpn_rois [R, 4], rpn_roi_probs [R, 1][, rois_num
    [N]])."""
    sc = np.asarray(_t(scores)._value, np.float32)
    dl = np.asarray(_t(bbox_deltas)._value, np.float32)
    im = np.asarray(_t(img_size)._value, np.float32)
    an = np.asarray(_t(anchors)._value, np.float32).reshape(-1, 4)
    va = np.asarray(_t(variances)._value, np.float32).reshape(-1, 4)
    N, A, H, W = sc.shape
    offset = 1.0 if pixel_offset else 0.0
    clip_w = float(np.log(1000.0 / 16.0))

    all_rois, all_probs, nums = [], [], []
    for i in range(N):
        # [A,H,W] -> [H,W,A] -> flat, matching the anchors' [H,W,A,4] layout
        s = sc[i].transpose(1, 2, 0).reshape(-1)
        d = dl[i].reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        aw = an[:, 2] - an[:, 0] + offset
        ah = an[:, 3] - an[:, 1] + offset
        ax = an[:, 0] + 0.5 * aw
        ay = an[:, 1] + 0.5 * ah
        cx = va[:, 0] * d[:, 0] * aw + ax
        cy = va[:, 1] * d[:, 1] * ah + ay
        bw = np.exp(np.minimum(va[:, 2] * d[:, 2], clip_w)) * aw
        bh = np.exp(np.minimum(va[:, 3] * d[:, 3], clip_w)) * ah
        x1 = cx - 0.5 * bw
        y1 = cy - 0.5 * bh
        x2 = cx + 0.5 * bw - offset
        y2 = cy + 0.5 * bh - offset
        ih, iw = im[i, 0], im[i, 1]
        x1 = np.clip(x1, 0, iw - offset)
        y1 = np.clip(y1, 0, ih - offset)
        x2 = np.clip(x2, 0, iw - offset)
        y2 = np.clip(y2, 0, ih - offset)
        keep = ((x2 - x1 + offset) >= min_size) & ((y2 - y1 + offset) >= min_size)
        boxes = np.stack([x1, y1, x2, y2], 1)[keep]
        probs = s[keep]
        order = np.argsort(-probs, kind="stable")[: int(pre_nms_top_n)]
        boxes, probs = boxes[order], probs[order]
        if len(boxes):
            kept = np.asarray(nms(Tensor(jnp.asarray(boxes)),
                                  iou_threshold=float(nms_thresh),
                                  scores=Tensor(jnp.asarray(probs)),
                                  top_k=int(post_nms_top_n))._value)
        else:
            kept = np.zeros((0,), np.int64)
        all_rois.append(boxes[kept])
        all_probs.append(probs[kept].reshape(-1, 1))
        nums.append(len(kept))
    rois = Tensor(jnp.asarray(np.concatenate(all_rois, 0)
                              if all_rois else np.zeros((0, 4), np.float32)))
    probs = Tensor(jnp.asarray(np.concatenate(all_probs, 0)
                               if all_probs else np.zeros((0, 1), np.float32)))
    if return_rois_num:
        return rois, probs, Tensor(jnp.asarray(np.asarray(nums, np.int32)))
    return rois, probs


def read_file(filename, name=None):
    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8)
    return Tensor(jnp.asarray(data))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to CHW uint8 (parity:
    /root/reference/python/paddle/vision/ops.py decode_jpeg, nvjpeg-backed).
    TPU-native stance: image decode is host-side data-pipeline work (the
    DataLoader tier), so this rides the bundled PIL codec; the device never
    sees JPEG bytes."""
    import io

    from PIL import Image

    data = np.asarray(_t(x)._value, np.uint8).tobytes()
    img = Image.open(io.BytesIO(data))
    if mode in ("unchanged", "rgb", "RGB"):
        if mode != "unchanged" and img.mode != "RGB":
            img = img.convert("RGB")
    elif mode in ("gray", "grey", "L"):
        img = img.convert("L")
    else:
        raise ValueError(f"decode_jpeg: unsupported mode {mode!r}")
    arr = np.asarray(img, np.uint8)
    if arr.ndim == 2:
        arr = arr[None, :, :]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))
