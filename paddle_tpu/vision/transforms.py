"""Vision transforms (parity: python/paddle/vision/transforms) — numpy/host-side,
composable; HWC numpy in, CHW float out by default (ToTensor contract)."""
from __future__ import annotations

import numbers
from typing import List, Sequence

import numpy as np

__all__ = [
    "Compose", "ToTensor", "Normalize", "Resize", "CenterCrop", "RandomCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose", "Pad", "RandomRotation",
    "BrightnessTransform", "ContrastTransform",
]


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor:
    """HWC uint8/float → CHW float32 in [0,1] numpy (device put happens at collate)."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if arr.dtype == np.uint8:
            arr = arr.astype(np.float32) / 255.0
        else:
            arr = arr.astype(np.float32)
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return arr


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            m = self.mean.reshape(-1, 1, 1)
            s = self.std.reshape(-1, 1, 1)
        else:
            m = self.mean
            s = self.std
        return (arr - m) / s


def _resize_hwc(arr, size):
    """Nearest-neighbor resize without external deps."""
    h, w = arr.shape[:2]
    if isinstance(size, numbers.Number):
        if h < w:
            oh, ow = int(size), int(size * w / h)
        else:
            oh, ow = int(size * h / w), int(size)
    else:
        oh, ow = size
    rows = (np.arange(oh) * h / oh).astype(int)
    cols = (np.arange(ow) * w / ow).astype(int)
    return arr[rows][:, cols]


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = size

    def __call__(self, img):
        return _resize_hwc(np.asarray(img), self.size)


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, numbers.Number) else size

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i : i + th, j : j + tw]


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed=False):
        self.size = (size, size) if isinstance(size, numbers.Number) else size
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) else [self.padding] * 4
            pad_cfg = [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (arr.ndim - 2)
            arr = np.pad(arr, pad_cfg)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return arr[i : i + th, j : j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[::-1].copy()
        return np.asarray(img)


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(self.order)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = padding if isinstance(padding, (list, tuple)) else [padding] * 4
        self.fill = fill

    def __call__(self, img):
        arr = np.asarray(img)
        p = self.padding
        cfg = [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (arr.ndim - 2)
        return np.pad(arr, cfg, constant_values=self.fill)


class RandomRotation:
    def __init__(self, degrees, interpolation="nearest", expand=False, center=None, fill=0):
        self.degrees = (-degrees, degrees) if isinstance(degrees, numbers.Number) else degrees

    def __call__(self, img):
        # k*90 rotations only (no scipy dependency); sampled angle snapped
        angle = np.random.uniform(*self.degrees)
        k = int(round(angle / 90.0)) % 4
        return np.rot90(np.asarray(img), k).copy()


class BrightnessTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        factor = 1 + np.random.uniform(-self.value, self.value)
        return np.clip(arr * factor, 0, 255 if arr.max() > 1 else 1.0)


class ContrastTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        factor = 1 + np.random.uniform(-self.value, self.value)
        mean = arr.mean()
        return np.clip((arr - mean) * factor + mean, 0, 255 if arr.max() > 1 else 1.0)


# ---------------------------------------------------------------- functional
def to_tensor(pic, data_format="CHW"):
    raw = np.asarray(pic)
    arr = raw.astype(np.float32)
    if raw.dtype == np.uint8:  # dtype decides scaling, never image content
        arr = arr / 255.0
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    from ..tensor.tensor import Tensor

    return Tensor(arr)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32).reshape(-1)
    std = np.asarray(std, np.float32).reshape(-1)
    if data_format == "CHW":
        return (arr - mean[:, None, None]) / std[:, None, None]
    return (arr - mean) / std


def resize(img, size, interpolation="bilinear"):
    return _resize_hwc(np.asarray(img), size)


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = np.asarray(img)
    if isinstance(padding, numbers.Number):
        padding = [padding] * 4
    if len(padding) == 2:
        padding = [padding[0], padding[1], padding[0], padding[1]]
    l, t, r, b = padding  # noqa: E741
    cfg = [(t, b), (l, r)] + [(0, 0)] * (arr.ndim - 2)
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(arr, cfg, mode=mode, **kw)


def crop(img, top, left, height, width):
    return np.asarray(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    arr = np.asarray(img)
    th, tw = (output_size, output_size) if isinstance(output_size, numbers.Number) \
        else output_size
    i = max((arr.shape[0] - th) // 2, 0)
    j = max((arr.shape[1] - tw) // 2, 0)
    return arr[i:i + th, j:j + tw]


def hflip(img):
    return np.asarray(img)[:, ::-1]


def vflip(img):
    return np.asarray(img)[::-1]


def _inv_affine_sample(arr, mat, fill=0):
    """Sample arr (HWC) at inverse-affine-mapped coordinates (nearest)."""
    h, w = arr.shape[:2]
    ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
    coords = np.stack([xs - cx, ys - cy, np.ones_like(xs)], -1) @ mat.T
    sx = np.clip(np.round(coords[..., 0] + cx), 0, w - 1).astype(int)
    sy = np.clip(np.round(coords[..., 1] + cy), 0, h - 1).astype(int)
    valid = ((coords[..., 0] + cx >= 0) & (coords[..., 0] + cx <= w - 1)
             & (coords[..., 1] + cy >= 0) & (coords[..., 1] + cy <= h - 1))
    out = arr[sy, sx]
    return np.where(valid[..., None] if arr.ndim == 3 else valid, out, fill)


def rotate(img, angle, interpolation="nearest", expand=False, center=None, fill=0):
    arr = np.asarray(img)
    a = np.deg2rad(angle)
    mat = np.array([[np.cos(a), np.sin(a), 0], [-np.sin(a), np.cos(a), 0]], np.float64)
    return _inv_affine_sample(arr, mat, fill)


def affine(img, angle=0.0, translate=(0, 0), scale=1.0, shear=(0.0, 0.0),
           interpolation="nearest", fill=0, center=None):
    arr = np.asarray(img)
    a = np.deg2rad(angle)
    if isinstance(shear, numbers.Number):
        shear = (shear, 0.0)
    sx, sy = np.deg2rad(shear[0]), np.deg2rad(shear[1]) if len(shear) > 1 else 0.0
    # forward matrix; invert for sampling
    m = np.array([[np.cos(a + sx), -np.sin(a + sy), translate[0]],
                  [np.sin(a + sx), np.cos(a + sy), translate[1]]], np.float64)
    m[:2, :2] *= scale
    full = np.vstack([m, [0, 0, 1]])
    inv = np.linalg.inv(full)[:2]
    return _inv_affine_sample(arr, inv, fill)


def perspective(img, startpoints, endpoints, interpolation="nearest", fill=0):
    arr = np.asarray(img)
    # solve the 8-dof homography endpoints -> startpoints (inverse mapping)
    A, B = [], []
    for (ex, ey), (sx, sy) in zip(endpoints, startpoints):
        A.append([ex, ey, 1, 0, 0, 0, -sx * ex, -sx * ey])
        A.append([0, 0, 0, ex, ey, 1, -sy * ex, -sy * ey])
        B.extend([sx, sy])
    hvec = np.linalg.lstsq(np.asarray(A, np.float64), np.asarray(B, np.float64),
                           rcond=None)[0]
    H = np.append(hvec, 1.0).reshape(3, 3)
    h, w = arr.shape[:2]
    ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    pts = np.stack([xs, ys, np.ones_like(xs)], -1) @ H.T
    px = pts[..., 0] / pts[..., 2]
    py = pts[..., 1] / pts[..., 2]
    sxc = np.clip(np.round(px), 0, w - 1).astype(int)
    syc = np.clip(np.round(py), 0, h - 1).astype(int)
    # half-pixel tolerance: nearest sampling + fp error must not void borders
    valid = (px >= -0.5) & (px <= w - 0.5) & (py >= -0.5) & (py <= h - 0.5)
    out = arr[syc, sxc]
    return np.where(valid[..., None] if arr.ndim == 3 else valid, out, fill)


def erase(img, i, j, h, w, v, inplace=False):
    from ..tensor.tensor import Tensor

    if isinstance(img, Tensor):
        import jax.numpy as jnp

        val = jnp.asarray(v, img._value.dtype)
        new = img._value.at[..., i:i + h, j:j + w].set(val)
        if inplace:
            img._value = new
            return img
        return Tensor(new)
    arr = np.asarray(img).copy()
    arr[..., i:i + h, j:j + w] = v
    return arr


def to_grayscale(img, num_output_channels=1):
    arr = np.asarray(img, np.float32)
    gray = arr[..., 0] * 0.299 + arr[..., 1] * 0.587 + arr[..., 2] * 0.114
    return np.repeat(gray[..., None], num_output_channels, axis=-1)


def adjust_brightness(img, brightness_factor):
    arr = np.asarray(img, np.float32)
    hi = 255 if arr.max() > 1 else 1.0
    return np.clip(arr * brightness_factor, 0, hi)


def adjust_contrast(img, contrast_factor):
    arr = np.asarray(img, np.float32)
    hi = 255 if arr.max() > 1 else 1.0
    mean = to_grayscale(arr).mean()
    return np.clip((arr - mean) * contrast_factor + mean, 0, hi)


def _rgb_hsv_roundtrip(arr, hue_shift):
    """Vectorized RGB->HSV->RGB hue rotation (no per-pixel Python loop)."""
    hi = 255.0 if arr.max() > 1 else 1.0
    rgb = (arr / hi).astype(np.float64)
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    mx = rgb.max(-1)
    mn = rgb.min(-1)
    diff = mx - mn
    safe = np.where(diff == 0, 1.0, diff)
    h = np.zeros_like(mx)
    h = np.where(mx == r, ((g - b) / safe) % 6.0, h)
    h = np.where(mx == g, (b - r) / safe + 2.0, h)
    h = np.where(mx == b, (r - g) / safe + 4.0, h)
    h = np.where(diff == 0, 0.0, h / 6.0)
    s = np.where(mx == 0, 0.0, diff / np.where(mx == 0, 1.0, mx))
    v = mx
    h = (h + hue_shift) % 1.0
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    i = i.astype(int) % 6
    out = np.empty_like(rgb)
    conds = [(v, t, p), (q, v, p), (p, v, t), (p, q, v), (t, p, v), (v, p, q)]
    for k, (rr, gg, bb) in enumerate(conds):
        m = i == k
        out[..., 0] = np.where(m, rr, out[..., 0])
        out[..., 1] = np.where(m, gg, out[..., 1])
        out[..., 2] = np.where(m, bb, out[..., 2])
    return (out * hi).astype(np.float32)


def adjust_hue(img, hue_factor):
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    return _rgb_hsv_roundtrip(np.asarray(img, np.float32), hue_factor)


# ------------------------------------------------------------------ classes
class BaseTransform:
    """parity: transforms.BaseTransform — keys-aware __call__."""

    def __init__(self, keys=None):
        self.keys = keys

    def _apply_image(self, img):
        raise NotImplementedError

    def __call__(self, inputs):
        if self.keys is None:
            return self._apply_image(inputs)
        outs = []
        for key, data in zip(self.keys, inputs):
            outs.append(self._apply_image(data) if key == "image" else data)
        return tuple(outs)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        shift = np.random.uniform(-self.value, self.value)
        return adjust_hue(img, shift)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        factor = 1 + np.random.uniform(-self.value, self.value)
        gray = to_grayscale(arr)
        hi = 255 if arr.max() > 1 else 1.0
        return np.clip(gray + (arr - gray) * factor, 0, hi)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0, keys=None):
        super().__init__(keys)
        self.brightness, self.contrast = brightness, contrast
        self.saturation, self.hue = saturation, hue

    def _apply_image(self, img):
        out = np.asarray(img, np.float32)
        if self.brightness:
            out = adjust_brightness(out, 1 + np.random.uniform(-self.brightness, self.brightness))
        if self.contrast:
            out = adjust_contrast(out, 1 + np.random.uniform(-self.contrast, self.contrast))
        if self.saturation:
            out = SaturationTransform(self.saturation)._apply_image(out)
        if self.hue:
            out = adjust_hue(out, np.random.uniform(-self.hue, self.hue))
        return out


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        self.degrees = (-degrees, degrees) if isinstance(degrees, numbers.Number) else degrees
        self.translate, self.scale_rng, self.shear = translate, scale, shear

    def _apply_image(self, img):
        arr = np.asarray(img)
        angle = np.random.uniform(*self.degrees)
        tx = ty = 0
        if self.translate:
            tx = np.random.uniform(-self.translate[0], self.translate[0]) * arr.shape[1]
            ty = np.random.uniform(-self.translate[1], self.translate[1]) * arr.shape[0]
        sc = np.random.uniform(*self.scale_rng) if self.scale_rng else 1.0
        sh = (np.random.uniform(-self.shear, self.shear), 0.0) if isinstance(
            self.shear, numbers.Number) else (self.shear or (0.0, 0.0))
        return affine(arr, angle, (tx, ty), sc, sh)


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3), value=0,
                 inplace=False, keys=None):
        super().__init__(keys)
        self.prob, self.scale, self.ratio, self.value = prob, scale, ratio, value

    def _apply_image(self, img):
        arr = np.asarray(img)
        if np.random.rand() > self.prob:
            return arr
        # CHW or HWC: erase over the last two dims per the erase() contract
        h, w = arr.shape[-2], arr.shape[-1]
        if arr.ndim == 3 and arr.shape[-1] in (1, 3):  # HWC
            h, w = arr.shape[0], arr.shape[1]
            area = h * w
            for _ in range(10):
                ta = np.random.uniform(*self.scale) * area
                ar = np.random.uniform(*self.ratio)
                eh, ew = int(round(np.sqrt(ta * ar))), int(round(np.sqrt(ta / ar)))
                if eh < h and ew < w:
                    i = np.random.randint(0, h - eh)
                    j = np.random.randint(0, w - ew)
                    out = arr.copy()
                    out[i:i + eh, j:j + ew] = self.value
                    return out
            return arr
        area = h * w
        for _ in range(10):
            ta = np.random.uniform(*self.scale) * area
            ar = np.random.uniform(*self.ratio)
            eh, ew = int(round(np.sqrt(ta * ar))), int(round(np.sqrt(ta / ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh)
                j = np.random.randint(0, w - ew)
                return erase(arr, i, j, eh, ew, self.value)
        return arr


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5, interpolation="nearest",
                 fill=0, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.distortion_scale = distortion_scale

    def _apply_image(self, img):
        arr = np.asarray(img)
        if np.random.rand() > self.prob:
            return arr
        h, w = arr.shape[:2]
        d = self.distortion_scale
        tl = (np.random.uniform(0, d * w / 2), np.random.uniform(0, d * h / 2))
        tr = (w - 1 - np.random.uniform(0, d * w / 2), np.random.uniform(0, d * h / 2))
        br = (w - 1 - np.random.uniform(0, d * w / 2), h - 1 - np.random.uniform(0, d * h / 2))
        bl = (np.random.uniform(0, d * w / 2), h - 1 - np.random.uniform(0, d * h / 2))
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        return perspective(arr, start, [tl, tr, br, bl])


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else size
        self.scale, self.ratio = scale, ratio

    def _apply_image(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            ta = np.random.uniform(*self.scale) * area
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            ch = int(round(np.sqrt(ta / ar)))
            cw = int(round(np.sqrt(ta * ar)))
            if ch <= h and cw <= w:
                i = np.random.randint(0, h - ch + 1)
                j = np.random.randint(0, w - cw + 1)
                return _resize_hwc(arr[i:i + ch, j:j + cw], self.size)
        return _resize_hwc(center_crop(arr, min(h, w)), self.size)


__all__ += [
    "BaseTransform", "ColorJitter", "Grayscale", "HueTransform",
    "SaturationTransform", "RandomAffine", "RandomErasing", "RandomPerspective",
    "RandomResizedCrop", "to_tensor", "normalize", "resize", "pad", "crop",
    "center_crop", "hflip", "vflip", "rotate", "affine", "perspective", "erase",
    "to_grayscale", "adjust_brightness", "adjust_contrast", "adjust_hue",
]
