"""Vision transforms (parity: python/paddle/vision/transforms) — numpy/host-side,
composable; HWC numpy in, CHW float out by default (ToTensor contract)."""
from __future__ import annotations

import numbers
from typing import List, Sequence

import numpy as np

__all__ = [
    "Compose", "ToTensor", "Normalize", "Resize", "CenterCrop", "RandomCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose", "Pad", "RandomRotation",
    "BrightnessTransform", "ContrastTransform",
]


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor:
    """HWC uint8/float → CHW float32 in [0,1] numpy (device put happens at collate)."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if arr.dtype == np.uint8:
            arr = arr.astype(np.float32) / 255.0
        else:
            arr = arr.astype(np.float32)
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return arr


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            m = self.mean.reshape(-1, 1, 1)
            s = self.std.reshape(-1, 1, 1)
        else:
            m = self.mean
            s = self.std
        return (arr - m) / s


def _resize_hwc(arr, size):
    """Nearest-neighbor resize without external deps."""
    h, w = arr.shape[:2]
    if isinstance(size, numbers.Number):
        if h < w:
            oh, ow = int(size), int(size * w / h)
        else:
            oh, ow = int(size * h / w), int(size)
    else:
        oh, ow = size
    rows = (np.arange(oh) * h / oh).astype(int)
    cols = (np.arange(ow) * w / ow).astype(int)
    return arr[rows][:, cols]


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = size

    def __call__(self, img):
        return _resize_hwc(np.asarray(img), self.size)


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, numbers.Number) else size

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i : i + th, j : j + tw]


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed=False):
        self.size = (size, size) if isinstance(size, numbers.Number) else size
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) else [self.padding] * 4
            pad_cfg = [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (arr.ndim - 2)
            arr = np.pad(arr, pad_cfg)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return arr[i : i + th, j : j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[::-1].copy()
        return np.asarray(img)


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(self.order)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = padding if isinstance(padding, (list, tuple)) else [padding] * 4
        self.fill = fill

    def __call__(self, img):
        arr = np.asarray(img)
        p = self.padding
        cfg = [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (arr.ndim - 2)
        return np.pad(arr, cfg, constant_values=self.fill)


class RandomRotation:
    def __init__(self, degrees, interpolation="nearest", expand=False, center=None, fill=0):
        self.degrees = (-degrees, degrees) if isinstance(degrees, numbers.Number) else degrees

    def __call__(self, img):
        # k*90 rotations only (no scipy dependency); sampled angle snapped
        angle = np.random.uniform(*self.degrees)
        k = int(round(angle / 90.0)) % 4
        return np.rot90(np.asarray(img), k).copy()


class BrightnessTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        factor = 1 + np.random.uniform(-self.value, self.value)
        return np.clip(arr * factor, 0, 255 if arr.max() > 1 else 1.0)


class ContrastTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        factor = 1 + np.random.uniform(-self.value, self.value)
        mean = arr.mean()
        return np.clip((arr - mean) * factor + mean, 0, 255 if arr.max() > 1 else 1.0)
