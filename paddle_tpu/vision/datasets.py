"""Vision datasets (parity: python/paddle/vision/datasets).

Zero-egress environment: datasets read from local files when present
(``image_path``/``label_path`` args, standard IDX/cifar formats); otherwise
``download=True`` raises and ``mode='synthetic'`` (or env
PADDLE_TPU_SYNTHETIC_DATA=1) yields deterministic synthetic samples with the
real shapes — enough for pipeline tests and benchmarks.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ..io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "Flowers", "VOC2012"]


def _synthetic_ok():
    return os.environ.get("PADDLE_TPU_SYNTHETIC_DATA", "1") == "1"


class MNIST(Dataset):
    """IDX-format reader with synthetic fallback (parity: vision/datasets/mnist.py)."""

    NUM_CLASSES = 10
    IMAGE_SHAPE = (28, 28)

    def __init__(self, image_path=None, label_path=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = mode
        self.transform = transform
        if image_path and os.path.exists(image_path):
            self.images = self._read_images(image_path)
            self.labels = self._read_labels(label_path)
        elif _synthetic_ok():
            n = 60000 if mode == "train" else 10000
            n = min(n, int(os.environ.get("PADDLE_TPU_SYNTHETIC_N", "2048")))
            rng = np.random.RandomState(0 if mode == "train" else 1)
            self.labels = rng.randint(0, 10, n).astype(np.int64)
            self.images = np.zeros((n, 28, 28), np.uint8)
            # class-dependent pattern so models can actually learn
            for i, y in enumerate(self.labels):
                img = rng.randint(0, 40, (28, 28))
                r = 2 + int(y) * 2
                img[r : r + 5, 4:24] += 180
                self.images[i] = np.clip(img, 0, 255)
        else:
            raise RuntimeError("no local MNIST files and downloads are disabled in this environment")

    @staticmethod
    def _read_images(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            return np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)

    @staticmethod
    def _read_labels(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            return np.frombuffer(f.read(), np.uint8).astype(np.int64)

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        label = self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32)[None] / 255.0
        return img, int(label)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    NUM_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None, download=True, backend=None):
        self.transform = transform
        if data_file and os.path.exists(data_file):
            raw = np.load(data_file, allow_pickle=True)
            self.images, self.labels = raw["images"], raw["labels"]
        elif _synthetic_ok():
            n = min(50000 if mode == "train" else 10000, int(os.environ.get("PADDLE_TPU_SYNTHETIC_N", "2048")))
            rng = np.random.RandomState(0 if mode == "train" else 1)
            self.labels = rng.randint(0, self.NUM_CLASSES, n).astype(np.int64)
            self.images = rng.randint(0, 255, (n, 32, 32, 3)).astype(np.uint8)
            for i, y in enumerate(self.labels):
                c = int(y) % 3
                self.images[i, 2 + y : 10 + y, :, c] = 250
        else:
            raise RuntimeError("no local CIFAR file and downloads are disabled")

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32).transpose(2, 0, 1) / 255.0
        return img, int(self.labels[idx])


class Cifar100(Cifar10):
    NUM_CLASSES = 100


class Flowers(Cifar10):
    NUM_CLASSES = 102


class VOC2012(Dataset):
    def __init__(self, *a, **k):
        raise NotImplementedError("VOC2012 requires local data; not bundled in this environment")
