"""Hybrid-parallel topology (parity:
/root/reference/python/paddle/distributed/fleet/base/topology.py:65
CommunicateTopology + :178 HybridCommunicateGroup).

The reference builds a 5-D cartesian rank topology [data, pipe, sharding, sep,
model] and derives per-axis process groups. TPU-native: the SAME axis algebra
produces a ``jax.sharding.Mesh`` with named axes — groups become mesh axes and
collectives become XLA collectives over those axes. This is the single most
direct "ancestor" mapping in the whole rebuild (SURVEY.md §2.2).
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh

from .placements import ProcessMesh

__all__ = ["CommunicateTopology", "HybridCommunicateGroup", "ParallelMode"]


class ParallelMode:
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3
    SEGMENT_PARALLEL = 4


class CommunicateTopology:
    """Axis-order bookkeeping (reference axis order
    ["data", "pipe", "sharding", "sep", "model"], topology.py:68)."""

    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "sep", "model"),
                 dims=(1, 1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = list(itertools.product(*[range(d) for d in self._dims]))
        self._world_size = int(np.prod(self._dims))
        self._rank2coord = {self._coord_to_rank(c): c for c in self.coordinate}

    def _coord_to_rank(self, coord) -> int:
        rank = 0
        for c, d in zip(coord, self._dims):
            rank = rank * d + c
        return rank

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name: str) -> int:
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self) -> int:
        return self._world_size

    def get_rank(self, **kwargs) -> int:
        coord = tuple(kwargs[name] for name in self._parallel_names)
        return self._coord_to_rank(coord)

    def get_coord(self, rank: int):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name: str, index: int) -> List[int]:
        axis = self._parallel_names.index(axis_name)
        return sorted(self._coord_to_rank(c) for c in self.coordinate if c[axis] == index)

    def get_comm_list(self, axis_name: str) -> List[List[int]]:
        """All groups along axis_name: ranks varying on that axis only."""
        axis = self._parallel_names.index(axis_name)
        others = [i for i in range(len(self._dims)) if i != axis]
        groups = []
        for fixed in itertools.product(*[range(self._dims[i]) for i in others]):
            group = []
            for a in range(self._dims[axis]):
                coord = [0] * len(self._dims)
                for i, f in zip(others, fixed):
                    coord[i] = f
                coord[axis] = a
                group.append(self._coord_to_rank(tuple(coord)))
            groups.append(sorted(group))
        return groups


class HybridCommunicateGroup:
    """parity: topology.py:178. Holds the named-axis mesh and exposes the
    reference's per-axis rank/world-size query surface."""

    # reference axis order; jax mesh axis names use the fleet short names.
    # 'ep' (expert parallel) extends the reference's 5-D topology — the
    # reference gives MoE its own group built from dp ranks
    # (moe_layer.py:263); here it is a first-class mesh axis so the ragged
    # all-to-all dispatch rides ICI like every other collective.
    AXES = ("dp", "pp", "sharding", "sep", "ep", "mp")

    def __init__(self, dp=1, mp=1, pp=1, sharding=1, sep=1, ep=1, devices=None):
        dims = dict(dp=dp, pp=pp, sharding=sharding, sep=sep, ep=ep, mp=mp)
        self._dims = dims
        n_needed = int(np.prod(list(dims.values())))
        devs = np.asarray(devices if devices is not None else jax.devices())
        if n_needed > devs.size:
            raise ValueError(
                f"topology {dims} needs {n_needed} devices, only {devs.size} visible"
            )
        grid = devs[:n_needed].reshape([dims[a] for a in self.AXES])
        self._mesh = Mesh(grid, self.AXES)
        self._topo = CommunicateTopology(
            ("data", "pipe", "sharding", "sep", "expert", "model"),
            [dims[a] for a in self.AXES])
        self.global_rank = jax.process_index()

    # ---- mesh access (TPU-native surface) ----
    @property
    def mesh(self) -> Mesh:
        return self._mesh

    @property
    def process_mesh(self) -> ProcessMesh:
        return ProcessMesh(self._mesh)

    def axis_size(self, axis: str) -> int:
        return self._dims[axis]

    # ---- reference query surface ----
    def get_parallel_mode(self):
        if self._dims["mp"] == 1 and self._dims["pp"] == 1 and self._dims["sharding"] == 1:
            return ParallelMode.DATA_PARALLEL
        if self._dims["mp"] > 1:
            return ParallelMode.TENSOR_PARALLEL
        if self._dims["pp"] > 1:
            return ParallelMode.PIPELINE_PARALLEL
        return ParallelMode.SHARDING_PARALLEL

    def topology(self):
        return self._topo

    def get_global_rank(self) -> int:
        return self.global_rank

    def _axis_rank(self, axis: str) -> int:
        # single-controller SPMD: per-axis coordinate of this process is only
        # meaningful multi-host; return 0 on a single process.
        world = jax.process_count()
        if world == 1:
            return 0
        coord = self._topo.get_coord(self.global_rank)
        return coord[self.AXES.index(axis)]

    def get_data_parallel_rank(self):
        return self._axis_rank("dp")

    def get_data_parallel_world_size(self):
        return self._dims["dp"]

    def get_model_parallel_rank(self):
        return self._axis_rank("mp")

    def get_model_parallel_world_size(self):
        return self._dims["mp"]

    def get_stage_id(self):
        return self._axis_rank("pp")

    def get_pipe_parallel_world_size(self):
        return self._dims["pp"]

    def get_sharding_parallel_rank(self):
        return self._axis_rank("sharding")

    def get_sharding_parallel_world_size(self):
        return self._dims["sharding"]

    def get_sep_parallel_rank(self):
        return self._axis_rank("sep")

    def get_sep_parallel_world_size(self):
        return self._dims["sep"]

    # group objects (Group facade over a mesh axis)
    def get_data_parallel_group(self):
        from .communication.group import Group

        return Group.for_axis(self, "dp")

    def get_model_parallel_group(self):
        from .communication.group import Group

        return Group.for_axis(self, "mp")

    def get_pipe_parallel_group(self):
        from .communication.group import Group

        return Group.for_axis(self, "pp")

    def get_sharding_parallel_group(self):
        from .communication.group import Group

        return Group.for_axis(self, "sharding")

    def get_sep_parallel_group(self):
        from .communication.group import Group

        return Group.for_axis(self, "sep")

    def get_expert_parallel_rank(self):
        return self._axis_rank("ep")

    def get_expert_parallel_world_size(self):
        return self._dims["ep"]

    def get_expert_parallel_group(self):
        from .communication.group import Group

        return Group.for_axis(self, "ep")


_global_hcg: Optional[HybridCommunicateGroup] = None


def set_hybrid_communicate_group(hcg: HybridCommunicateGroup):
    global _global_hcg
    _global_hcg = hcg


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _global_hcg
