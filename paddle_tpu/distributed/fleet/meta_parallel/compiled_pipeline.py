"""Compiled pipeline parallelism — the whole microbatch schedule in ONE XLA
program, for REAL models (heterogeneous stages, tied embeddings, stateful
optimizers).

Reference analog: the static-graph pipeline scheduler passes
(/root/reference/python/paddle/distributed/passes/pipeline_scheduler_pass/)
which compile 1F1B/ZB orderings into a single program per rank, plus
SharedLayerDesc's shared-grad allreduce
(/root/reference/python/paddle/distributed/fleet/meta_parallel/parallel_layers/pp_layers.py:76).

TPU-native formulation (the GSPMD/shard_map pipeline):

* **Partial-manual shard_map**: only the 'pp' axis is manual
  (``jax.shard_map(..., axis_names={'pp'})``); dp/mp/sharding stay AUTO
  inside, so the model's GSPMD sharding annotations (TP layers'
  ``with_sharding_constraint``) keep working verbatim inside the pipeline
  body and XLA inserts the mp collectives — no manual rewrite of the layer
  library.
* **Head/body/tail decomposition**: a real LM pipeline is [embedding]
  + P×[k uniform decoder layers] + [norm+lm_head]. The homogeneous BODY is
  stacked ``[P, ...]`` and pp-sharded — each device holds exactly its
  stage's decoder weights. The HEAD (first-stage prefix) and TAIL
  (last-stage suffix) ride as ordinary pp-replicated (auto) arrays; under
  SPMD every rank executes head/tail in lockstep and masks by the stage
  id (a pp-sharded arange argument), so the redundant compute costs no
  wall-clock
  (all ranks would be in that program region anyway) and ``jnp.where``
  keeps gradients exact.
* **Tied embeddings (SharedLayerDesc)**: the shared layer's weight enters
  the program ONCE as an auto array used by both the head lookup (live on
  stage 0) and the tail logits matmul (live on stage P-1); shard_map's
  reverse rule psums the cotangent over the manual 'pp' axis — exactly the
  reference's shared-grad allreduce, derived by AD instead of hand-wired.
* **Schedule**: activations advance around the pp ring with
  ``lax.ppermute`` inside a ``lax.scan`` over T = num_micro + P - 1 ticks;
  XLA's latency-hiding scheduler overlaps the ppermute with the next tick's
  compute. Per-tick ``jax.checkpoint`` keeps saved state to stage-boundary
  activations (1F1B-grade memory).

Composes with TrainStep: stacked body weights + head/tail params form the
parameter set; the optimizer's param groups are REWIRED onto them (per-group
hyperparameters preserved — group membership must be uniform across stages
for each body slot) and any pre-existing accumulator/master state is
restacked ``[P, ...]`` so a mid-training switch to the compiled engine keeps
optimizer momentum.

VPP chunks (num_chunks > 1) compile too: weights stack [C, P, ...] (dim 0 =
virtual chunk). Two schedules exist:

* **Interleaved-1F1B (default when legal)**: ONE scan whose stage-0 feed
  alternates chunks in groups of P microbatches (Megatron's interleaved
  order), reaching a (P-1)/C bubble. The tick body is BRANCH-FREE: the
  active chunk's weights are selected from the stacked [C, P, ...] arrays
  with ``lax.dynamic_index_in_dim`` — one fused program per tick, no
  ``lax.switch`` over per-chunk branches (the r5 switch formulation paid
  +43% steady-state per-microbatch time; see PROFILE_r05 §1 / r06 §1).
  Requires ``num_micro % P == 0``. Chunk-program homogeneity is a hard
  constructor invariant (every schedule path runs ONE body program per
  tick); ``PADDLE_TPU_VPP_INTERLEAVED_IMPL=switch`` selects ``lax.switch``
  weight selection instead of the gather, for A/B profiling of the
  branch cost.
* **Chunk-sequential rings**: each microbatch set circles the pp ring once
  per chunk, exits hopping from the last stage back to stage 0 via one
  extra ppermute; bubble ~(P-1) microbatch-times. Forced with
  ``PADDLE_TPU_VPP_INTERLEAVED=0`` and used whenever the interleaved feed
  cannot tile (``num_micro % P != 0``).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec

from ...multihost import global_device_put

from ....autograd import tape
from ....nn.layer.layers import Layer
from ....tensor.tensor import Tensor

__all__ = ["CompiledPipelineTrainStep", "pipeline_bubble_fraction"]


def pipeline_bubble_fraction(num_micro: int, num_stages: int) -> float:
    """Idle fraction of the synchronous pipeline: (P-1)/(M+P-1)."""
    return (num_stages - 1) / (num_micro + num_stages - 1)


def _shard_map_pp(fn, mesh, in_specs, out_specs):
    """Manual over 'pp' only; every other mesh axis stays auto (GSPMD)."""
    from ...shard_map_compat import shard_map_manual

    return shard_map_manual(fn, mesh, in_specs, out_specs, {"pp"})


def _pp_collectives_native(mesh) -> bool:
    """Whether the ring collectives lower inside partial-manual shard_map
    over 'pp' on this jax (see shard_map_compat.partial_manual_supported —
    the constructor refuses unsupported meshes up front because the
    failure mode is a fatal XLA abort, not an exception)."""
    from ...shard_map_compat import partial_manual_supported

    return partial_manual_supported(mesh, {"pp"})


def _layer_sig(layer, ffunc):
    cfg = repr(layer) if isinstance(layer, Layer) else getattr(
        layer, "__name__", str(layer))
    fid = ffunc if isinstance(ffunc, str) or ffunc is None else getattr(
        ffunc, "__qualname__", repr(ffunc))
    return (type(layer).__name__, cfg, fid)


class _Swap:
    """Temporarily install traced values into param Tensors."""

    def __init__(self, tensors, values):
        self.tensors, self.values = tensors, values

    def __enter__(self):
        self.saved = [t._value for t in self.tensors]
        for t, v in zip(self.tensors, self.values):
            t._value = v

    def __exit__(self, *exc):
        for t, v in zip(self.tensors, self.saved):
            t._value = v
        return False


class _Segment:
    """A contiguous run of (layer, fwd_func) pairs + its parameter list."""

    def __init__(self, pairs: Sequence[Tuple]):
        self.pairs = list(pairs)
        self.params: List[Tensor] = []
        seen = set()
        for layer, _ in self.pairs:
            if isinstance(layer, Layer):
                for p in layer.parameters():
                    if id(p) not in seen:
                        seen.add(id(p))
                        self.params.append(p)

    def sig(self):
        return ([_layer_sig(l, f) for l, f in self.pairs]
                + [(tuple(p.shape), str(p.dtype)) for p in self.params])

    def run(self, param_leaves, x_val):
        """Pure function: swap in leaves, run the chain on a raw value."""
        with _Swap(self.params, list(param_leaves)):
            t = Tensor(x_val, stop_gradient=True)
            for layer, ffunc in self.pairs:
                if ffunc == "plain_fn":
                    t = layer(t)
                elif ffunc is not None:
                    t = ffunc(layer, t)
                else:
                    t = layer(t)
            return t._value


def _decompose(pipe) -> Tuple[_Segment, List[_Segment], _Segment]:
    """Split the pipeline's stages into (head, per-stage body, tail).

    The body layer type is the one whose instances appear on more than one
    stage (the repeated trunk — e.g. the decoder layer); the head is stage
    0's prefix before its first body layer, the tail is the last stage's
    suffix after its last body layer. Every stage must carry the same number
    of body layers with identical signatures."""
    n_seg = pipe._num_segments  # = num_stages * num_chunks (VPP)
    P = pipe._num_stages
    pairs = [list(zip(pipe._stage_layers[s], pipe._stage_fwd_funcs[s]))
             for s in range(n_seg)]
    shared_ids = {id(l) for l in pipe._shared_layers.values()}
    type_stages: Dict[str, set] = {}
    for s in range(n_seg):
        for layer, _ in pairs[s]:
            if id(layer) in shared_ids:
                continue  # one OBJECT on many stages (tied weights) ≠ a body
            type_stages.setdefault(type(layer).__name__, set()).add(s)
    body_types = {t for t, ss in type_stages.items() if len(ss) == n_seg}
    if not body_types and n_seg > 1:
        # fall back: types on >1 stage (short pipes where the trunk doesn't
        # reach every stage can't be stacked)
        body_types = {t for t, ss in type_stages.items() if len(ss) > 1}
    if not body_types:
        raise ValueError(
            "compiled pipeline: no layer type spans multiple stages — cannot "
            "identify a homogeneous body to stack; use the eager engine")

    def is_body(layer):
        return id(layer) not in shared_ids and type(layer).__name__ in body_types

    head_pairs, first_body = [], None
    for i, (layer, f) in enumerate(pairs[0]):
        if is_body(layer):
            first_body = i
            break
        head_pairs.append((layer, f))
    if first_body is None:
        raise ValueError("compiled pipeline: stage 0 has no body layers")

    tail_pairs, last_body = [], None
    for i in range(len(pairs[-1]) - 1, -1, -1):
        if is_body(pairs[-1][i][0]):
            last_body = i
            break
    if last_body is None:
        raise ValueError(f"compiled pipeline: segment {n_seg - 1} has no body layers")
    tail_pairs = pairs[-1][last_body + 1:]

    body_segs = []
    for s in range(n_seg):
        lo = first_body if s == 0 else 0
        hi = last_body + 1 if s == n_seg - 1 else len(pairs[s])
        seg_pairs = pairs[s][lo:hi]
        if any(not is_body(l) for l, _ in seg_pairs):
            raise ValueError(
                f"compiled pipeline: stage {s} interleaves body and non-body "
                "layers; head/tail must be contiguous prefixes/suffixes")
        body_segs.append(_Segment(seg_pairs))

    ref = body_segs[0].sig()
    for s in range(1, n_seg):
        if body_segs[s].sig() != ref:
            raise ValueError(
                f"compiled pipeline needs a homogeneous body; stage {s} "
                f"{body_segs[s].sig()} != stage 0 {ref}. Choose a seg_method "
                "that gives every stage the same decoder count")
    return _Segment(head_pairs), body_segs, _Segment(tail_pairs)


def _full_mesh_put(p: Tensor, mesh):
    """Move a head/tail param from its stage submesh onto the full mesh,
    keeping axis-name sharding dims that exist there (mp etc.)."""
    if isinstance(p._value, jax.core.Tracer):
        return
    try:
        old = p._value.sharding.spec
    except Exception:
        old = None
    spec = PartitionSpec(*[
        e if (e in mesh.axis_names or isinstance(e, tuple)) else None
        for e in (old or [None] * p.ndim)
    ]) if old else PartitionSpec(*([None] * p.ndim))
    p._value = global_device_put(np.asarray(p._value), NamedSharding(mesh, spec))


class _PipeParams(Layer):
    """Parameter container the TrainStep compiles against: stacked body
    weights — [P, ...] pp-sharded, or [C, P, ...] with VPP chunks (dim 0 =
    virtual chunk, dim 1 = pp) — plus the head/tail params."""

    def __init__(self, body_segs: List[_Segment], aux_params: List[Tensor],
                 mesh, num_stages: int):
        super().__init__()
        self._mesh = mesh
        P = num_stages
        C = len(body_segs) // P
        self.num_chunks = C
        self.stacked: List[Tensor] = []
        self.stacked_specs: List[PartitionSpec] = []
        for j, p0 in enumerate(body_segs[0].params):
            vals = np.stack([np.asarray(seg.params[j]._value) for seg in body_segs])
            try:
                inner = tuple(
                    e if (e in mesh.axis_names and e != "pp") or isinstance(e, tuple)
                    else None
                    for e in (p0._value.sharding.spec or ()))
            except Exception:
                inner = ()
            inner = tuple(inner) + (None,) * (p0.ndim - len(inner))
            if C > 1:
                # segment g = c*P + d  ->  [C, P, ...]
                vals = vals.reshape(C, P, *vals.shape[1:])
                spec = PartitionSpec(None, "pp", *inner)
            else:
                spec = PartitionSpec("pp", *inner)
            sh = NamedSharding(mesh, spec)
            t = Tensor(global_device_put(vals, sh), stop_gradient=False)
            t.name = f"pipe_stacked_{j}"
            self.stacked.append(t)
            self.stacked_specs.append(spec)
            setattr(self, f"w{j}", t)  # registers as parameter
        self.aux: List[Tensor] = list(aux_params)
        for k, p in enumerate(self.aux):
            _full_mesh_put(p, mesh)
            setattr(self, f"aux{k}", p)

    def parameters(self, include_sublayers=True):
        return list(self.stacked) + list(self.aux)


def _remesh_value(v, mesh):
    """Move a pre-existing state array from a stage submesh onto the full
    mesh, keeping sharding dims whose axis names exist there."""
    try:
        old = v.sharding.spec
    except Exception:
        old = None
    spec = PartitionSpec(*[
        e if (e in mesh.axis_names or isinstance(e, tuple)) else None
        for e in (old or [None] * np.ndim(v))
    ]) if old else PartitionSpec(*([None] * np.ndim(v)))
    return global_device_put(np.asarray(v), NamedSharding(mesh, spec))


def _rewire_optimizer(optimizer, body_segs: List[_Segment],
                      stacked: List[Tensor], aux_ids: set, mesh,
                      stacked_specs: List[PartitionSpec], num_stages: int):
    """Re-point param groups at stacked weights (per-group hyperparameters
    kept) and restack any pre-existing optimizer state [P, ...] (or
    [C, P, ...] with VPP chunks, matching _PipeParams)."""
    P = len(body_segs)  # total SEGMENTS = num_stages * num_chunks
    C = P // num_stages
    slot_of: Dict[int, Tuple[int, int]] = {}
    for s, seg in enumerate(body_segs):
        for j, p in enumerate(seg.params):
            slot_of[id(p)] = (s, j)

    # group membership per body slot, from each group's original params
    group_of_slot: Dict[int, int] = {}
    for gi, g in enumerate(optimizer._param_groups):
        for p in g["params"]:
            slot = slot_of.get(id(p))
            if slot is None:
                continue
            s, j = slot
            prev = group_of_slot.setdefault(j, gi)
            if prev != gi:
                raise ValueError(
                    f"compiled pipeline: body slot {j} belongs to different "
                    f"param groups on different stages ({prev} vs {gi}); "
                    "group membership must be uniform across stages")

    new_groups = []
    for gi, g in enumerate(optimizer._param_groups):
        new_params, seen = [], set()
        for p in g["params"]:
            slot = slot_of.get(id(p))
            if slot is not None:
                j = slot[1]
                if j not in seen and group_of_slot[j] == gi:
                    seen.add(j)
                    new_params.append(stacked[j])
            else:
                # aux (head/tail) params and any params outside the pipeline
                # stay as-is (aux already re-placed by _full_mesh_put)
                new_params.append(p)
        new_groups.append({**{k: v for k, v in g.items() if k != "params"},
                           "params": new_params})
    optimizer._param_groups = new_groups
    optimizer._parameter_list = [p for g in new_groups for p in g["params"]]

    # restack pre-existing state so momentum survives the engine switch
    def restack(d: Dict[int, jnp.ndarray], j: int, target: Tensor):
        vals, found = [], 0
        for s in range(P):
            v = d.pop(id(body_segs[s].params[j]), None)
            if v is not None:
                found += 1
            vals.append(v)
        if found == 0:
            return
        if found != P:
            raise ValueError(
                f"compiled pipeline: optimizer state for body slot {j} exists "
                f"on {found}/{P} stages — cannot restack partial state")
        if np.ndim(vals[0]) == 0:
            # scalar accumulators (step counters like beta_pow) advanced in
            # lockstep across stages — keep one, don't stack (stacking would
            # break broadcasting against the [P, ...] moments)
            d[id(target)] = global_device_put(
                np.asarray(vals[0]), NamedSharding(mesh, PartitionSpec()))
            return
        # per-stage values live on different stage submeshes — stack on host
        arr = np.stack([np.asarray(v) for v in vals])
        if C > 1:
            arr = arr.reshape(C, num_stages, *arr.shape[1:])  # match [C,P,...]
        spec = (stacked_specs[j] if arr.ndim == len(stacked_specs[j])
                else PartitionSpec(*([None] * arr.ndim)))
        d[id(target)] = global_device_put(arr, NamedSharding(mesh, spec))

    for name, d in optimizer._accumulators.items():
        for j, t in enumerate(stacked):
            restack(d, j, t)
        # head/tail params moved to the full mesh — their existing state must
        # follow or jit sees mixed device sets
        for pid in list(d):
            if pid in aux_ids:
                d[pid] = _remesh_value(d[pid], mesh)
    for j, t in enumerate(stacked):
        restack(optimizer._master_weights, j, t)
    for pid in list(optimizer._master_weights):
        if pid in aux_ids:
            optimizer._master_weights[pid] = _remesh_value(
                optimizer._master_weights[pid], mesh)


class CompiledPipelineTrainStep:
    """loss + grads + optimizer update for the FULL microbatch pipeline
    schedule, compiled into one donated-buffer XLA program. Handles
    heterogeneous stages (embedding head / lm-head tail), SharedLayerDesc
    tied weights, and optimizers with existing state / multiple groups.

    VPP schedule selection (r6): with ``num_chunks > 1`` the interleaved
    ordering is chosen AUTOMATICALLY when ``num_micro % num_stages == 0``
    (chunk-program homogeneity is a constructor invariant — every
    schedule runs one body program per tick); its
    tick body is branch-free — the active chunk's weights are gathered
    from the stacked ``[C, P, ...]`` parameters with
    ``lax.dynamic_index_in_dim`` instead of ``lax.switch`` over per-chunk
    branches, which erased the r5 switch tick's +43% steady-state
    per-microbatch tax (PROFILE_r06 §1). Chunk-sequential rings remain the
    fallback (and can be forced with ``PADDLE_TPU_VPP_INTERLEAVED=0``);
    ``PADDLE_TPU_VPP_INTERLEAVED_IMPL=switch`` selects ``lax.switch``
    weight selection for A/B profiling of the branch cost. Optimizer
    state restacks ``[C, P, ...]`` alongside the
    weights and round-trips through :meth:`sync_to_model` unchanged under
    either schedule."""

    def __init__(self, pipe, optimizer, num_micro: int, scaler=None, remat: bool = True):
        from ....jit.api import TrainStep
        from ...topology import get_hybrid_communicate_group
        from .pipeline_parallel import PipelineParallel

        model = pipe._layers if isinstance(pipe, PipelineParallel) else pipe
        hcg = get_hybrid_communicate_group()
        if hcg is None or hcg.axis_size("pp") <= 1:
            raise ValueError("compiled pipeline needs an active mesh with pp > 1")
        self.mesh = mesh = hcg.mesh
        if not _pp_collectives_native(mesh):
            # on old jax the SPMD partitioner ABORTS the process (fatal
            # check, not an exception) when the ring collectives' backward
            # meets a real auto axis — refuse cleanly up front
            raise NotImplementedError(
                "compiled pipeline: this jax version cannot mix the manual "
                "'pp' axis with size>1 auto mesh axes (dp/mp/sharding) — "
                "XLA's SPMD partitioner aborts on the ring collectives' "
                "backward. Use a pp-only mesh (dp=mp=sharding=1) or a jax "
                "with top-level jax.shard_map (>=0.8).")
        self.num_micro = num_micro
        self.num_stages = P = model._num_stages
        # VPP: C virtual chunks per device, weights [C, P, ...]; the compiled
        # schedule runs chunk-SEQUENTIAL rings (each microbatch set circles
        # the ring once per chunk, exits hop from the last stage back to
        # stage 0). The interleaved-1F1B ORDERING is a scheduling choice the
        # reference makes explicitly; here cross-chunk overlap is left to
        # XLA's scheduler within the single program — the memory/partition
        # semantics (per-device virtual stages) are the VPP contract kept.
        C = self.num_chunks = model._num_chunks
        self._pipe = model
        if model._loss_fn is None:
            raise ValueError("PipelineLayer built without loss_fn")
        loss_fn_t = model._loss_fn

        head, body_segs, tail = _decompose(model)
        self._body_segs = body_segs
        # chunk-program homogeneity: EVERY schedule path (branch-free
        # gather, lax.switch, chunk-sequential rings) compiles body0's ONE
        # program and varies only the weights, which is only sound when
        # segment c*P + d runs the same program for every chunk c.
        # _decompose's body check guarantees this today; re-checked as a
        # hard error so a future relaxation of _decompose (e.g. per-chunk
        # special layers) cannot silently mis-run chunks through any of
        # the schedules — all of them would need extending first.
        self._chunks_homogeneous = all(
            body_segs[c * P + d].sig() == body_segs[d].sig()
            for c in range(C) for d in range(P))
        if not self._chunks_homogeneous:
            raise ValueError(
                "compiled pipeline: chunk programs differ across virtual "
                "chunks; every schedule runs one body program per tick — "
                "heterogeneous chunks are not supported")
        # head/tail params deduped — a SharedLayerDesc layer appearing in
        # both (tied embedding) enters the program exactly once
        aux, seen = [], set()
        for p in head.params + tail.params:
            if id(p) not in seen:
                seen.add(id(p))
                aux.append(p)
        self._params_layer = _PipeParams(body_segs, aux, mesh, P)
        stacked = self._params_layer.stacked
        n_stacked = len(stacked)
        n_aux = len(aux)
        aux_index = {id(p): k for k, p in enumerate(aux)}
        head_idx = [aux_index[id(p)] for p in head.params]
        tail_idx = [aux_index[id(p)] for p in tail.params]

        _rewire_optimizer(optimizer, body_segs, stacked, set(aux_index), mesh,
                          self._params_layer.stacked_specs, P)

        body0 = body_segs[0]

        # ring activation shape = the body input (head output when a head
        # exists, else the data microbatch itself)
        self._head = head
        self._tail = tail

        stk_specs = tuple(
            PartitionSpec("pp") if C == 1 else PartitionSpec(None, "pp")
            for _ in range(n_stacked))

        def local(stacked_vals, aux_vals, xs, ys, stage_ids):
            # stage index arrives as a pp-sharded arange(P) argument — each
            # device sees its own id — instead of lax.axis_index('pp'),
            # which older jax cannot lower next to real auto axes
            stage = stage_ids[0]
            head_vals = [aux_vals[k] for k in head_idx]
            tail_vals = [aux_vals[k] for k in tail_idx]
            M = xs.shape[0]
            T = M + P - 1

            def run_head(x):
                return head.run(head_vals, x) if head.pairs else x

            body_fwd = (jax.checkpoint(body0.run) if remat else body0.run)
            ring_perm = [(i, (i + 1) % P) for i in range(P)]

            def ring_shift(v):
                """Advance v one hop around the pp ring (stage s receives
                stage s-1's value)."""
                return lax.ppermute(v, "pp", ring_perm)

            def run_chunk(p_chunk, xs_in, first_chunk):
                def tick(h, t):
                    x_t = lax.dynamic_index_in_dim(xs_in, jnp.clip(t, 0, M - 1),
                                                   0, keepdims=False)
                    inp0 = run_head(x_t) if first_chunk else x_t
                    inp = jnp.where(stage == 0, inp0, h)
                    out = body_fwd(p_chunk, inp)
                    return ring_shift(out), out

                h_struct = jax.eval_shape(
                    run_head if first_chunk else (lambda v: v), xs_in[0])
                h0 = jnp.zeros(h_struct.shape, h_struct.dtype)
                _, outs = lax.scan(tick, h0, jnp.arange(T))
                # microbatch m exits the LAST stage at tick m + P - 1
                return jnp.take(outs, jnp.arange(M) + P - 1, axis=0)

            import os as _os

            # Schedule selection (r6): the interleaved-VPP ordering is
            # AUTOMATIC whenever it is legal — VPP chunks and a feed that
            # tiles exactly (M % P == 0); chunk-program homogeneity is
            # already a constructor invariant.
            # r5 shipped it opt-in because its per-tick lax.switch over
            # chunk programs cost +43% steady-state per-microbatch time
            # (PROFILE_r05 §1); the r6 tick instead gathers the active
            # chunk's weights from the stacked [C, P, ...] arrays with
            # lax.dynamic_index_in_dim — one fused, branch-free tick body
            # (VERDICT r5 rec #8, measured in PROFILE_r06 §1).
            # Env overrides:
            #   PADDLE_TPU_VPP_INTERLEAVED=0  force chunk-sequential rings
            #   PADDLE_TPU_VPP_INTERLEAVED=1  request interleaved (warns
            #       when the schedule is illegal)
            #   PADDLE_TPU_VPP_INTERLEAVED_IMPL=switch  select weights by
            #       lax.switch instead of the gather (A/B isolating the
            #       branch cost — NOT the full r5 tick: the pending-buffer
            #       removal applies to both impls)
            env_il = _os.environ.get("PADDLE_TPU_VPP_INTERLEAVED")
            can_interleave = C > 1 and M % P == 0
            interleave = can_interleave and env_il != "0"
            if env_il == "1" and not can_interleave:
                import warnings

                warnings.warn(
                    f"PADDLE_TPU_VPP_INTERLEAVED=1 ignored: needs VPP "
                    f"chunks (C={C}) and num_micro divisible by pp stages "
                    f"(M={M}, P={P}); running chunk-sequential",
                    stacklevel=2)
            use_indexed = (_os.environ.get(
                "PADDLE_TPU_VPP_INTERLEAVED_IMPL", "indexed") != "switch")
            if interleave:
                # ---- explicit interleaved-VPP ordering (r5, VERDICT item
                # 5): ONE scan whose stage-0 feed alternates chunks in
                # groups of P microbatches — (c, m)'s dependency, chunk
                # c-1's exit of the same microbatch, is fed exactly P ticks
                # earlier and rides the ring's P-1→0 wrap back to stage 0
                # just in time, so the feed is dense (zero stalls) and the
                # whole-schedule bubble is P-1 CHUNK-times = (P-1)/C
                # microbatch-times, the Megatron interleaved bound — instead
                # of the chunk-sequential C*(P-1).
                CM = C * M
                feed_c = np.zeros(CM, np.int32)
                feed_m = np.zeros(CM, np.int32)
                pos = 0
                for blk in range(M // P):
                    for c in range(C):
                        for off in range(P):
                            feed_c[pos] = c
                            feed_m[pos] = blk * P + off
                            pos += 1
                c_arr = jnp.asarray(feed_c)
                m_arr = jnp.asarray(feed_m)
                T_i = CM + P - 1

                if use_indexed:
                    # branch-free body: gather the active chunk's weights
                    # from the [C, 1, ...] local shards INSIDE the remat'd
                    # function — the checkpoint then saves the
                    # loop-invariant stacked arrays (no per-tick gathered
                    # copies) and the backward recomputes the cheap gather
                    def body_idx(stacked_local, c_idx, v):
                        p_c = [lax.dynamic_index_in_dim(a, c_idx, 0,
                                                        keepdims=False)[0]
                               for a in stacked_local]
                        return body0.run(p_c, v)

                    body_idx = jax.checkpoint(body_idx) if remat else body_idx
                else:
                    branches = [
                        (lambda c: (lambda v: body_fwd(
                            [a[c, 0] for a in stacked_vals], v)))(c)
                        for c in range(C)
                    ]

                def itick(h, t):
                    # this stage's work item: the one stage 0 fed s ticks ago
                    ti = jnp.clip(t - stage, 0, CM - 1)
                    my_c = c_arr[ti]
                    my_m = jnp.clip(m_arr[ti], 0, M - 1)
                    x_t = lax.dynamic_index_in_dim(xs, my_m, 0,
                                                   keepdims=False)
                    # the blocked feed is DENSE: (my_c, my_m)'s dependency
                    # — chunk my_c-1's exit of the same microbatch — was
                    # fed exactly P ticks earlier, so its last-stage output
                    # rides the ring's (P-1)→0 wrap and IS the h arriving
                    # at stage 0 THIS tick. No parking buffer is needed
                    # (r6: the r5 formulation carried an [M, ...] pending
                    # scatter/gather through the scan — pure overhead, and
                    # a large share of its +43% steady-state tax).
                    inp0 = jnp.where(my_c == 0, run_head(x_t), h)
                    inp = jnp.where(stage == 0, inp0, h)
                    if use_indexed:
                        out = body_idx(stacked_vals, my_c, inp)
                    else:
                        out = lax.switch(my_c, branches, inp)
                    return ring_shift(out), out

                h_struct = jax.eval_shape(run_head, xs[0])
                h0 = jnp.zeros(h_struct.shape, h_struct.dtype)
                _, outs = lax.scan(itick, h0, jnp.arange(T_i))
                # final-chunk microbatch m finishes the last stage at
                # t_fed(C-1, m) + P - 1
                t_fed = np.zeros(M, np.int64)
                for pos in range(CM):
                    if feed_c[pos] == C - 1:
                        t_fed[feed_m[pos]] = pos
                exit_outs = jnp.take(outs, jnp.asarray(t_fed + P - 1), axis=0)
            else:
                xs_c = xs
                for c in range(C):
                    if C == 1:
                        p_chunk = [a[0] for a in stacked_vals]      # [P,...] local
                    else:
                        p_chunk = [a[c, 0] for a in stacked_vals]   # [C,P,...] local
                    exit_outs = run_chunk(p_chunk, xs_c, c == 0)
                    if c < C - 1:
                        # exits live on the last stage; one ring hop delivers
                        # them to stage 0 as the next chunk's inputs
                        xs_c = ring_shift(exit_outs)
            # merge microbatches for the tail + loss: every rank computes in
            # SPMD lockstep; only the last stage's value survives the mask
            mb = exit_outs.shape[1]
            merged = exit_outs.reshape(M * mb, *exit_outs.shape[2:])
            logits = tail.run(tail_vals, merged) if tail.pairs else merged
            ys_m = ys.reshape(M * ys.shape[1], *ys.shape[2:])
            with tape.no_grad():
                loss = loss_fn_t(Tensor(logits, stop_gradient=True),
                                 Tensor(ys_m, stop_gradient=True))._value
            loss = jnp.where(stage == P - 1, loss.astype(jnp.float32), 0.0)
            return lax.psum(loss, "pp")

        def pipelined_loss(model_, x, y):
            from ....ops.dispatch import apply

            def f(xv, yv, *param_vals):
                stacked_vals = tuple(param_vals[:n_stacked])
                aux_vals = tuple(param_vals[n_stacked:])
                mb = xv.shape[0] // num_micro
                xs = xv.reshape(num_micro, mb, *xv.shape[1:])
                ys = yv.reshape(num_micro, mb, *yv.shape[1:])
                fn = _shard_map_pp(
                    local, mesh,
                    in_specs=(stk_specs, (PartitionSpec(),) * n_aux,
                              PartitionSpec(), PartitionSpec(),
                              PartitionSpec("pp")),
                    out_specs=PartitionSpec())
                stage_ids = jnp.arange(P, dtype=jnp.int32)
                return fn(stacked_vals, aux_vals, xs, ys, stage_ids)

            return apply(f, x, y, *model_.parameters(), op_name="compiled_pipeline")

        self._step = TrainStep(self._params_layer, pipelined_loss, optimizer,
                               scaler=scaler)

    @property
    def bubble_fraction(self) -> float:
        return pipeline_bubble_fraction(self.num_micro, self.num_stages)

    def sync_to_model(self):
        """Write the stacked weights back into the per-stage Tensors and
        re-place head/tail params on their stage submeshes, so the eager
        per-stage engine (state_dict / eval parity) sees a consistent
        placement again. A tied (shared head+tail) param belongs to two
        stages at once and stays on the full mesh — the eager engine treats
        shared layers as one object, so mixed-submesh eager eval of a tied
        model should go through the compiled step instead."""
        from ...multihost import is_multi_controller

        if is_multi_controller():
            # materializing the pp-sharded stack needs shards owned by other
            # processes; use the distributed checkpoint (per-host shards +
            # reshard-on-load) to move state between engines across hosts
            raise NotImplementedError(
                "sync_to_model under multi-controller: save with "
                "paddle_tpu.distributed.save_state_dict (per-host shards) "
                "and reload instead")

        def put_sub(p, sub):
            if sub is None:
                return
            try:
                old = p._value.sharding.spec
            except Exception:
                old = None
            spec = PartitionSpec(*[
                e if e in sub.axis_names else None
                for e in (old or [None] * p.ndim)
            ]) if old else PartitionSpec(*([None] * p.ndim))
            p._value = jax.device_put(np.asarray(p._value), NamedSharding(sub, spec))

        P = self._pipe._num_stages
        for j, t in enumerate(self._params_layer.stacked):
            host = np.asarray(t._value)
            if self.num_chunks > 1:  # [C, P, ...] -> flat segment order
                host = host.reshape(-1, *host.shape[2:])
            for s, seg in enumerate(self._body_segs):
                p = seg.params[j]
                p._value = jnp.asarray(host[s])
                put_sub(p, self._pipe._submeshes[s % P])
        head_ids = {id(p) for p in self._head.params}
        tail_ids = {id(p) for p in self._tail.params}
        shared = head_ids & tail_ids
        for p in self._head.params:
            if id(p) not in shared:
                put_sub(p, self._pipe._submeshes[0])
        for p in self._tail.params:
            if id(p) not in shared:
                put_sub(p, self._pipe._submeshes[self._pipe._num_stages - 1])
        return self._pipe

    def __call__(self, x, y):
        return self._step(x, y)
