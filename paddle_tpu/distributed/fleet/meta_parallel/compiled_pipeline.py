"""Compiled pipeline parallelism — the whole microbatch schedule in ONE XLA
program.

Reference analog: the static-graph pipeline scheduler passes
(/root/reference/python/paddle/distributed/passes/pipeline_scheduler_pass/)
which compile 1F1B/ZB orderings into a single program per rank, vs. the eager
per-op engine (meta_parallel/pipeline_parallel.py).

TPU-native formulation (the GSPMD/shard_map pipeline): every pp rank runs the
SAME program — stage identity is ``lax.axis_index('pp')``; per-stage weights
are STACKED on a leading axis sharded over 'pp' (the stacked arrays are the
canonical storage, so each device holds exactly its stage's weights and
optimizer state); activations advance around the ring with ``lax.ppermute``
inside a ``lax.scan`` over T = num_micro + P - 1 ticks. XLA's latency-hiding
scheduler overlaps the ppermute with the next tick's compute — the
1F1B/zero-bubble distinction collapses into data dependencies the compiler
schedules (SURVEY §7.2 item 5). Per-tick ``jax.checkpoint`` keeps saved state
to stage-boundary activations (1F1B-grade memory, not GPipe-grade).

Composes with TrainStep: the optimizer's param groups are re-pointed at the
stacked weights, so the framework's own update rules, GradScaler, and donated
buffers apply unchanged — optimizer accumulators come out [P, ...] and
pp-sharded automatically.

Requirements (checked): homogeneous stages (identical param trees), one chunk
per stage (no VPP interleave), activation shape == stage input shape. The
eager engine remains the general fallback.
"""
from __future__ import annotations

from typing import List

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec

from ....autograd import tape
from ....nn.layer.layers import Layer
from ....tensor.tensor import Tensor

__all__ = ["CompiledPipelineTrainStep", "pipeline_bubble_fraction"]


from ...shard_map_compat import shard_map_compat as _shard_map


def pipeline_bubble_fraction(num_micro: int, num_stages: int) -> float:
    """Idle fraction of the synchronous pipeline: (P-1)/(M+P-1)."""
    return (num_stages - 1) / (num_micro + num_stages - 1)


def _stage_param_lists(pipe) -> List[List]:
    """Per-stage parameter lists, with homogeneity checks."""
    if pipe._num_chunks != 1:
        raise ValueError("compiled pipeline does not support VPP chunks; "
                         "use the eager engine for interleaved schedules")
    if pipe._shared_layers:
        raise ValueError("compiled pipeline does not support SharedLayerDesc")
    stages = []
    for s in range(pipe._num_stages):
        ps = []
        for layer in pipe._stage_layers[s]:
            if isinstance(layer, Layer):
                ps.extend(layer.parameters())
        stages.append(ps)

    def _sig(s):
        # every stage runs stage 0's FORWARD program, so layer types (and
        # their configuration) must match, not just param shapes
        out = []
        for layer, f in zip(pipe._stage_layers[s], pipe._stage_fwd_funcs[s]):
            cfg = repr(layer) if isinstance(layer, Layer) else getattr(
                layer, "__name__", str(layer))
            fid = f if isinstance(f, str) or f is None else getattr(
                f, "__qualname__", repr(f))
            out.append((type(layer).__name__, cfg, fid))
        return out + [(tuple(p.shape), str(p.dtype)) for p in stages[s]]

    ref = _sig(0)
    for s in range(1, pipe._num_stages):
        got = _sig(s)
        if got != ref:
            raise ValueError(
                f"compiled pipeline needs homogeneous stages; stage {s} "
                f"{got} != stage 0 {ref}")
    return stages


class _StackedStages(Layer):
    """Holds the canonical [P, ...] pp-sharded weights as parameters."""

    def __init__(self, stage_params, mesh):
        super().__init__()
        self._mesh = mesh
        n_per_stage = len(stage_params[0])
        self.stacked: List[Tensor] = []
        for j in range(n_per_stage):
            vals = np.stack([np.asarray(ps[j]._value) for ps in stage_params])
            sh = NamedSharding(mesh, PartitionSpec("pp", *([None] * stage_params[0][j].ndim)))
            t = Tensor(jax.device_put(jnp.asarray(vals), sh), stop_gradient=False)
            self.stacked.append(t)
            setattr(self, f"w{j}", t)  # registers as parameter

    def parameters(self, include_sublayers=True):
        return list(self.stacked)


class CompiledPipelineTrainStep:
    """loss + grads + optimizer update for the FULL microbatch pipeline
    schedule, compiled into one donated-buffer XLA program."""

    def __init__(self, pipe, optimizer, num_micro: int, scaler=None, remat: bool = True):
        from ....jit.api import TrainStep
        from ...topology import get_hybrid_communicate_group
        from .pipeline_parallel import PipelineParallel

        model = pipe._layers if isinstance(pipe, PipelineParallel) else pipe
        hcg = get_hybrid_communicate_group()
        if hcg is None or hcg.axis_size("pp") <= 1:
            raise ValueError("compiled pipeline needs an active mesh with pp > 1")
        self.mesh = mesh = hcg.mesh
        self.num_micro = num_micro
        self.num_stages = P = model._num_stages
        self._pipe = model
        self._stage_params = _stage_param_lists(model)
        n_per_stage = len(self._stage_params[0])
        self._stacked = _StackedStages(self._stage_params, mesh)
        if model._loss_fn is None:
            raise ValueError("PipelineLayer built without loss_fn")
        loss_fn_t = model._loss_fn

        # re-point the optimizer's param groups at the stacked weights (the
        # update rules are elementwise, so [P, ...] arrays work unchanged)
        if optimizer._accumulators or optimizer._master_weights:
            raise ValueError("pass a fresh optimizer (no accumulated state)")
        if len(optimizer._param_groups) != 1:
            raise ValueError(
                "compiled pipeline supports a single param group (per-group "
                "hyperparameters cannot be mapped onto the stacked weights)")
        stacked_list = self._stacked.parameters()
        optimizer._param_groups = [
            {**{k: v for k, v in g.items() if k != "params"}, "params": stacked_list}
            for g in optimizer._param_groups
        ]

        stage0_layers = model._stage_layers[0]
        stage0_funcs = model._stage_fwd_funcs[0]
        stage0_params = self._stage_params[0]
        dp_axes = tuple(a for a in ("dp", "sharding")
                        if a in mesh.axis_names and mesh.shape[a] > 1)
        b_entry = dp_axes if len(dp_axes) > 1 else (dp_axes[0] if dp_axes else None)
        other_axes = tuple(a for a in mesh.axis_names if a != "pp")

        class _Swap:
            def __init__(self, tensors, values):
                self.tensors, self.values = tensors, values

            def __enter__(self):
                self.saved = [t._value for t in self.tensors]
                for t, v in zip(self.tensors, self.values):
                    t._value = v

            def __exit__(self, *exc):
                for t, v in zip(self.tensors, self.saved):
                    t._value = v
                return False

        def run_stage0(param_leaves, x):
            with _Swap(stage0_params, list(param_leaves)):
                t = Tensor(x, stop_gradient=True)
                for layer, ffunc in zip(stage0_layers, stage0_funcs):
                    if ffunc == "plain_fn":
                        t = layer(t)
                    elif ffunc is not None:
                        t = ffunc(layer, t)
                    else:
                        t = layer(t)
                return t._value

        def loss_of_micro(out, y):
            with tape.no_grad():
                return loss_fn_t(Tensor(out, stop_gradient=True),
                                 Tensor(y, stop_gradient=True))._value

        def local(stacked, xs, ys):
            p_local = [a[0] for a in stacked]  # this stage's weights
            stage = lax.axis_index("pp")
            M = xs.shape[0]
            T = M + P - 1
            fwd = jax.checkpoint(run_stage0) if remat else run_stage0

            def tick(h, t):
                x_t = lax.dynamic_index_in_dim(xs, jnp.clip(t, 0, M - 1), 0,
                                               keepdims=False)
                inp = jnp.where(stage == 0, x_t, h)
                out = fwd(p_local, inp)
                h_next = lax.ppermute(
                    out, "pp", [(i, (i + 1) % P) for i in range(P)])
                return h_next, out

            h0 = jnp.zeros_like(xs[0])
            _, outs = lax.scan(tick, h0, jnp.arange(T))
            # microbatch m exits the last stage at tick m + P - 1
            exit_outs = jnp.take(outs, jnp.arange(M) + P - 1, axis=0)
            per = jax.vmap(loss_of_micro)(exit_outs, ys)
            loss = jnp.mean(per.astype(jnp.float32))
            loss = jnp.where(stage == P - 1, loss, 0.0)
            loss = lax.psum(loss, "pp")
            if other_axes:
                loss = lax.pmean(loss, other_axes)
            return loss

        stk_specs = tuple(
            PartitionSpec("pp", *([None] * stage0_params[j].ndim))
            for j in range(n_per_stage)
        )

        def pipelined_loss(model_, x, y):
            from ....ops.dispatch import apply

            def f(xv, yv, *stacked_vals):
                mb = xv.shape[0] // num_micro
                xs = xv.reshape(num_micro, mb, *xv.shape[1:])
                ys = yv.reshape(num_micro, mb, *yv.shape[1:])
                data_spec = PartitionSpec(None, b_entry)
                fn = _shard_map(local, mesh,
                                in_specs=(tuple(stk_specs), data_spec, data_spec),
                                out_specs=PartitionSpec())
                return fn(tuple(stacked_vals), xs, ys)

            return apply(f, x, y, *model_.parameters(), op_name="compiled_pipeline")

        self._step = TrainStep(self._stacked, pipelined_loss, optimizer,
                               scaler=scaler)

    @property
    def bubble_fraction(self) -> float:
        return pipeline_bubble_fraction(self.num_micro, self.num_stages)

    def sync_to_model(self):
        """Write the stacked weights back into the per-stage Tensors (for
        state_dict / eager eval parity)."""
        for j, t in enumerate(self._stacked.stacked):
            host = np.asarray(t._value)
            for s, ps in enumerate(self._stage_params):
                sub = self._pipe._submeshes[s]
                val = jnp.asarray(host[s])
                if sub is not None:
                    val = jax.device_put(
                        val, NamedSharding(sub, PartitionSpec(*([None] * val.ndim))))
                ps[j]._value = val
        return self._pipe

    def __call__(self, x, y):
        return self._step(x, y)
