"""Pipeline-parallel training engine (parity:
/root/reference/python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py:229
PipelineParallel.forward_backward_pipeline — 1F1B; :1136 interleaved VPP;
static-graph schedules python/paddle/distributed/passes/pipeline_scheduler_pass/).

TPU-native scheduling model: a single controller dispatches every stage's ops
asynchronously (XLA async dispatch = the reference's comm/comp streams), so a
schedule is an *ordering of dispatches* rather than per-rank send/recv loops:

- FThenB (GPipe): forward all microbatches through all stages, then backward
  all — max overlap, activations for all microbatches live.
- 1F1B: depth-first — forward microbatch i through all stages then immediately
  backward it; in-flight activations stay O(1) microbatch per stage while
  consecutive microbatches overlap across stages through async dispatch.

Cross-stage tensor movement is a device_put onto the next stage's submesh
(ICI copy) — the reference's p2p SendRecvMeta + batch_isend_irecv
(pp_utils/p2p_communication.py:51) collapses into this.

Gradient accumulation across microbatches rides the eager tape (leaf .grad
accumulation), matching the reference's contract that train_batch leaves
summed grads for the optimizer step.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ....nn.layer.layers import Layer
from ....tensor.tensor import Tensor
from ...topology import get_hybrid_communicate_group
from .pp_layers import PipelineLayer

__all__ = ["PipelineParallel"]


class PipelineParallel(Layer):
    def __init__(self, layers: PipelineLayer, hcg=None, strategy=None):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel expects a PipelineLayer")
        self._layers = layers
        self._hcg = hcg or get_hybrid_communicate_group()
        cfg = {}
        if strategy is not None:
            cfg = getattr(strategy, "pipeline_configs", {})
        self.accumulate_steps = cfg.get("accumulate_steps", 1)
        self.micro_batch_size = cfg.get("micro_batch_size", None)
        self.schedule = cfg.get("schedule_mode", "1F1B")
        self.total_loss = None

    # -------------------------------------------------------------- helpers
    def _split_micro(self, data: Tensor, num_micro: int) -> List[Tensor]:
        from ....tensor.manipulation import split

        return split(data, num_micro, axis=0)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def forward(self, x):
        return self._layers(x)

    # -------------------------------------------------------------- engine
    def forward_backward_pipeline(self, data, scaler=None):
        """Run one global batch: returns the averaged loss tensor."""
        x, label = data
        num_micro = self.accumulate_steps
        if self.micro_batch_size is not None:
            num_micro = max(1, x.shape[0] // self.micro_batch_size)
        xs = self._split_micro(x, num_micro) if num_micro > 1 else [x]
        ys = self._split_micro(label, num_micro) if num_micro > 1 else [label]

        losses = []

        def run_one(mb_x, mb_y):
            out = mb_x
            for s in range(self._layers.num_stages):
                out = self._layers.forward_stage(out, s)
            loss = self._layers.loss_fn(out, mb_y)
            scaled = loss / num_micro
            if scaler is not None:
                scaled = scaler.scale(scaled)
            return loss, scaled

        if self.schedule.upper() in ("1F1B", "VPP"):
            # depth-first: fwd mb_i then bwd mb_i; async dispatch overlaps
            # stage s of mb_{i+1} with stage s+1 of mb_i
            for mb_x, mb_y in zip(xs, ys):
                loss, scaled = run_one(mb_x, mb_y)
                scaled.backward()
                losses.append(loss)
        else:  # FThenB / GPipe
            pending = []
            for mb_x, mb_y in zip(xs, ys):
                loss, scaled = run_one(mb_x, mb_y)
                pending.append(scaled)
                losses.append(loss)
            for scaled in pending:
                scaled.backward()

        from ....tensor.manipulation import stack
        from ....tensor.math import mean

        with __import__("paddle_tpu").no_grad():
            self.total_loss = mean(stack([l.detach() for l in losses]))
        return self.total_loss

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """parity: PipelineParallel.train_batch."""
        self._layers.train()
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        self._layers.eval()
        x, label = data
        out = self._layers(x)
        if compute_loss:
            return self._layers.loss_fn(out, label)
        return out
