"""Pipeline-parallel training engine (parity:
/root/reference/python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py:229
PipelineParallel.forward_backward_pipeline — 1F1B; :1136 interleaved VPP;
static-graph schedules python/paddle/distributed/passes/pipeline_scheduler_pass/).

TPU-native scheduling model: a single controller dispatches every stage's ops
asynchronously (XLA async dispatch = the reference's comm/comp streams), so a
schedule is an *ordering of dispatches* rather than per-rank send/recv loops:

- FThenB (GPipe): forward all microbatches through all stages, then backward
  all — max overlap, activations for all microbatches live.
- 1F1B: depth-first — forward microbatch i through all stages then immediately
  backward it; in-flight activations stay O(1) microbatch per stage while
  consecutive microbatches overlap across stages through async dispatch.

Cross-stage tensor movement is a device_put onto the next stage's submesh
(ICI copy) — the reference's p2p SendRecvMeta + batch_isend_irecv
(pp_utils/p2p_communication.py:51) collapses into this.

Gradient accumulation across microbatches rides the eager tape (leaf .grad
accumulation), matching the reference's contract that train_batch leaves
summed grads for the optimizer step.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ....nn.layer.layers import Layer
from ....tensor.tensor import Tensor
from ...topology import get_hybrid_communicate_group
from .pp_layers import PipelineLayer

__all__ = ["PipelineParallel"]


class PipelineParallel(Layer):
    def __init__(self, layers: PipelineLayer, hcg=None, strategy=None):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel expects a PipelineLayer")
        self._layers = layers
        self._hcg = hcg or get_hybrid_communicate_group()
        cfg = {}
        if strategy is not None:
            cfg = getattr(strategy, "pipeline_configs", {})
        self.accumulate_steps = cfg.get("accumulate_steps", 1)
        self.micro_batch_size = cfg.get("micro_batch_size", None)
        self.schedule = cfg.get("schedule_mode", "1F1B")
        self.total_loss = None

    # -------------------------------------------------------------- helpers
    def _split_micro(self, data: Tensor, num_micro: int) -> List[Tensor]:
        from ....tensor.manipulation import split

        return split(data, num_micro, axis=0)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def forward(self, x):
        return self._layers(x)

    # -------------------------------------------------------------- engine
    def _build_schedule(self, num_micro: int):
        from .schedules import (
            fthenb_schedule,
            interleaved_1f1b_schedule,
            one_f_one_b_schedule,
            zero_bubble_schedule,
        )

        from .schedules import BWD, FWD, ScheduleOp

        mode = self.schedule.upper()
        p = self._layers.num_stages
        v = self._layers.num_chunks
        if mode == "VPP" or (mode == "1F1B" and v > 1):
            return interleaved_1f1b_schedule(num_micro, p, v)
        if mode == "1F1B":
            return one_f_one_b_schedule(num_micro, p)
        if mode in ("ZBH1", "ZB", "ZEROBUBBLE", "ZERO_BUBBLE"):
            if v > 1:
                raise ValueError(
                    "zero-bubble schedule does not support virtual pipeline "
                    "chunks; use schedule_mode='VPP' for interleaved stages")
            return zero_bubble_schedule(num_micro, p)
        if v > 1:  # chunk-aware GPipe: all chunks forward, reverse backward
            return (
                [ScheduleOp(FWD, m, c) for m in range(num_micro) for c in range(v)]
                + [ScheduleOp(BWD, m, c) for m in range(num_micro)
                   for c in range(v - 1, -1, -1)]
            )
        return fthenb_schedule(num_micro, p)

    def forward_backward_pipeline(self, data, scaler=None):
        """Run one global batch by executing the explicit schedule op list
        (schedules.py — 1F1B / interleaved VPP / ZB-H1 / FThenB as distinct
        programs). Returns the averaged loss tensor."""
        from ....autograd import tape
        from .schedules import BWD, BWD_INPUT, BWD_WEIGHT, FWD

        x, label = data
        num_micro = self.accumulate_steps
        if self.micro_batch_size is not None:
            num_micro = max(1, x.shape[0] // self.micro_batch_size)
        xs = self._split_micro(x, num_micro) if num_micro > 1 else [x]
        ys = self._split_micro(label, num_micro) if num_micro > 1 else [label]

        v = self._layers.num_chunks
        last_chunk = v - 1
        losses = [None] * num_micro
        # (micro, chunk) -> {"in": boundary leaf, "out": chunk output,
        #                    "scaled": scaled loss (last chunk only)}
        state = {}

        for op in self._build_schedule(num_micro):
            m, c = op.micro, op.chunk
            if op.kind == FWD:
                if c == 0:
                    inp = xs[m]
                else:
                    # chunk boundary: detach into a leaf so each chunk's
                    # backward runs independently (the eager analog of the
                    # reference's p2p activation handoff)
                    prev_out = state[(m, c - 1)]["out"]
                    inp = prev_out.detach()
                    inp.stop_gradient = False
                out = self._layers.forward_chunk(inp, c)
                ent = {"in": inp, "out": out}
                if c == last_chunk:
                    loss = self._layers.loss_fn(out, ys[m])
                    scaled = loss / num_micro
                    if scaler is not None:
                        scaled = scaler.scale(scaled)
                    ent["scaled"] = scaled
                    losses[m] = loss.detach()
                state[(m, c)] = ent
            elif op.kind in (BWD, BWD_INPUT):
                # BWD_INPUT (zero-bubble Bx) runs the combined backward here:
                # under single-controller SPMD, XLA's latency-hiding scheduler
                # floats the weight-grad matmuls into bubbles on its own, so
                # the Bx/Bw split survives as schedule order, not split kernels
                ent = state.pop((m, c))
                if c == last_chunk:
                    ent["scaled"].backward()
                elif ent["in"] is not None:
                    down_cot = ent.pop("_cot", None)
                    if down_cot is None:
                        raise RuntimeError(
                            f"pipeline schedule ran B({m},{c}) before its "
                            f"downstream chunk's backward")
                    tape.run_backward([ent["out"]], [down_cot], accumulate_leaf=True)
                # hand this chunk's input-grad up to the previous chunk
                if c > 0:
                    g = ent["in"].grad
                    if g is not None and (m, c - 1) in state:
                        state[(m, c - 1)]["_cot"] = g._value
            elif op.kind == BWD_WEIGHT:
                pass  # folded into BWD_INPUT (see above)

        from ....tensor.manipulation import stack
        from ....tensor.math import mean

        with __import__("paddle_tpu").no_grad():
            self.total_loss = mean(stack(losses))
        return self.total_loss

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """parity: PipelineParallel.train_batch."""
        self._layers.train()
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        self._layers.eval()
        x, label = data
        out = self._layers(x)
        if compute_loss:
            return self._layers.loss_fn(out, label)
        return out
