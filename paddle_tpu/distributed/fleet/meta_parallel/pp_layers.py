"""Pipeline model description (parity:
/root/reference/python/paddle/distributed/fleet/meta_parallel/parallel_layers/pp_layers.py —
LayerDesc:56, SharedLayerDesc:76, SegmentLayers:92, PipelineLayer:257).

TPU-native placement: single-controller SPMD sees every stage, so
PipelineLayer builds ALL stages and pins each stage's parameters onto that
stage's slice of the 'pp' mesh axis (a per-stage submesh NamedSharding).
SharedLayerDesc's cross-stage weight sharing (tied embeddings) becomes literal
object sharing — no broadcast/allreduce bookkeeping needed.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Union

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ...multihost import global_device_put, is_multi_controller

from ....nn.layer.layers import Layer
from ...topology import get_hybrid_communicate_group

__all__ = ["LayerDesc", "SharedLayerDesc", "SegmentLayers", "PipelineLayer"]


class LayerDesc:
    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer):
            raise TypeError("LayerDesc expects a Layer subclass")

    def build_layer(self) -> Layer:
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_func, forward_func=None, shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """parity: SegmentLayers:92 — split N layer descs into num_parts segments,
    uniformly or by a seg_method ('layer:<ClassName>' cuts at class
    occurrences, 'uniform' by count)."""

    def __init__(self, layers_desc, num_parts, method="uniform", num_virtual_pipeline_stage=None):
        self.layers_desc = layers_desc
        self.num_items = len(layers_desc)
        self.num_parts = num_parts
        self.method = method
        assert self.num_items >= self.num_parts

    def do_segment(self) -> List[int]:
        if self.method == "uniform":
            return self._uniform(self.num_items, self.num_parts)
        if self.method.startswith("layer:"):
            cls_name = self.method.split(":", 1)[1]
            marks = [
                i for i, d in enumerate(self.layers_desc)
                if (d.layer_func.__name__ if isinstance(d, LayerDesc) else type(d).__name__) == cls_name
            ]
            if len(marks) >= self.num_parts:
                # segment boundaries fall on marked-layer starts, spread evenly
                chunks = np.array_split(marks, self.num_parts)
                return [0] + [int(c[0]) for c in chunks[1:]] + [self.num_items]
        return self._uniform(self.num_items, self.num_parts)

    @staticmethod
    def _uniform(n, parts) -> List[int]:
        base, extra = divmod(n, parts)
        bounds = [0]
        for i in range(parts):
            bounds.append(bounds[-1] + base + (1 if i < extra else 0))
        return bounds


class PipelineLayer(Layer):
    """parity: PipelineLayer:257 — sequential model described by layer descs,
    segmented into pp stages."""

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, recompute_ctx=None,
                 num_virtual_pipeline_stages=None):
        super().__init__()
        hcg = get_hybrid_communicate_group()
        if num_stages is None:
            num_stages = hcg.axis_size("pp") if hcg is not None else 1
        self._num_stages = num_stages
        # interleaved VPP: each device owns num_chunks virtual stages; global
        # segment g lives on device g % num_stages (Megatron assignment,
        # reference pp_layers.py num_virtual_pipeline_stage)
        self._num_chunks = num_virtual_pipeline_stages or 1
        num_segments = num_stages * self._num_chunks
        self._loss_fn = loss_fn
        self._recompute_interval = recompute_interval
        self._descs = list(layers)
        seg = SegmentLayers(self._descs, num_segments, seg_method)
        self.segment_parts = seg.do_segment()
        self._shared_layers: Dict[str, Layer] = {}
        self._stage_layers: List[List] = []
        self._stage_fwd_funcs: List[List] = []
        from ....nn.layer.container import LayerList

        all_built = []
        for s in range(num_segments):
            stage = []
            fwd_funcs = []
            for i in range(self.segment_parts[s], self.segment_parts[s + 1]):
                desc = self._descs[i]
                if isinstance(desc, SharedLayerDesc):
                    if desc.layer_name not in self._shared_layers:
                        self._shared_layers[desc.layer_name] = desc.build_layer()
                    layer = self._shared_layers[desc.layer_name]
                    fwd_funcs.append(desc.forward_func)
                elif isinstance(desc, LayerDesc):
                    layer = desc.build_layer()
                    fwd_funcs.append(None)
                elif isinstance(desc, Layer):
                    layer = desc
                    fwd_funcs.append(None)
                elif callable(desc):
                    stage.append(desc)
                    fwd_funcs.append("plain_fn")
                    continue
                else:
                    raise TypeError(f"unsupported layer desc: {desc}")
                stage.append(layer)
            self._stage_layers.append(stage)
            self._stage_fwd_funcs.append(fwd_funcs)
            built = LayerList([l for l in stage if isinstance(l, Layer)])
            all_built.append(built)
            self.add_sublayer(f"stage_{s}", built)
        # segment g -> device (g % num_stages)'s submesh
        self._submeshes = [self._stage_submesh(s % num_stages) for s in range(num_segments)]
        self._num_segments = num_segments
        self._place_stages()

    # ---------------------------------------------------------------- place
    def _stage_submesh(self, stage: int) -> Optional[Mesh]:
        hcg = get_hybrid_communicate_group()
        if hcg is None or hcg.axis_size("pp") == 1:
            return None
        mesh = hcg.mesh
        pp_index = mesh.axis_names.index("pp")
        devs = np.take(mesh.devices, stage, axis=pp_index)
        names = tuple(n for n in mesh.axis_names if n != "pp")
        return Mesh(devs, names)

    def _place_stages(self):
        if is_multi_controller():
            # multi-process job: eager per-stage placement would pin params
            # on submeshes other processes cannot address (breaking the host
            # materialization the compiled engine's stacking needs). Leave
            # params process-local-replicated; the compiled pipeline's
            # [P, ...] pp-sharded stacking is the real placement.
            for s in range(self._num_segments):
                for layer in self._stage_layers[s]:
                    if isinstance(layer, Layer):
                        for p in layer.parameters():
                            p._pp_stage = s  # type: ignore[attr-defined]
            return
        for s in range(self._num_segments):
            sub = self._submeshes[s]
            if sub is None:
                continue
            for layer in self._stage_layers[s]:
                if not isinstance(layer, Layer):
                    continue
                for p in layer.parameters():
                    if isinstance(p._value, jax.core.Tracer):
                        continue
                    # keep any existing mp sharding dims, restricted to this
                    # stage's submesh
                    try:
                        old_spec = p._value.sharding.spec
                    except Exception:
                        old_spec = None
                    spec = PartitionSpec(*[
                        e if e in sub.axis_names or (isinstance(e, tuple)) else None
                        for e in (old_spec or [None] * p.ndim)
                    ]) if old_spec else PartitionSpec(*([None] * p.ndim))
                    p._value = global_device_put(p._value,
                                                 NamedSharding(sub, spec))
                    p._pp_stage = s  # type: ignore[attr-defined]

    # ---------------------------------------------------------------- run
    @property
    def num_stages(self) -> int:
        return self._num_stages

    @property
    def num_chunks(self) -> int:
        return self._num_chunks

    @property
    def num_segments(self) -> int:
        return self._num_segments

    def get_stage_from_index(self, layer_idx: int) -> int:
        for s in range(self._num_segments):
            if self.segment_parts[s] <= layer_idx < self.segment_parts[s + 1]:
                return s % self._num_stages
        raise IndexError(layer_idx)

    def forward_chunk(self, x, chunk: int):
        """Run virtual chunk ``chunk`` = global segments
        [chunk*p, (chunk+1)*p) across all p devices in order."""
        for seg in range(chunk * self._num_stages, (chunk + 1) * self._num_stages):
            x = self.forward_stage(x, seg)
        return x

    def forward_stage(self, x, stage: int):
        """Run one stage's chain; input is moved onto the stage submesh by a
        TAPED device_put (the ICI hop that p2p send/recv does in the
        reference) — its vjp moves the cotangent back to the previous stage.
        The batch dim keeps its dp/sharding split on the submesh so dp×pp
        composes (data parallelism inside each stage)."""
        sub = self._submeshes[stage]
        from ....tensor.tensor import Tensor

        if sub is not None and isinstance(x, Tensor) and not isinstance(x._value, jax.core.Tracer):
            from ....ops.dispatch import apply

            batch_axes = tuple(a for a in ("dp", "sharding")
                               if a in sub.axis_names and sub.shape[a] > 1)
            b_entry = batch_axes if len(batch_axes) > 1 else (batch_axes[0] if batch_axes else None)
            sharding = NamedSharding(sub, PartitionSpec(b_entry, *([None] * (x.ndim - 1))))
            x = apply(lambda v: jax.device_put(v, sharding), x, op_name="pp_transfer")
        for layer, ffunc in zip(self._stage_layers[stage], self._stage_fwd_funcs[stage]):
            if ffunc == "plain_fn":
                x = layer(x)
            elif ffunc is not None:
                x = ffunc(layer, x)
            else:
                x = layer(x)
        return x

    def forward(self, x):
        for s in range(self._num_segments):
            x = self.forward_stage(x, s)
        return x

    def loss_fn(self, output, label):
        if self._loss_fn is None:
            raise RuntimeError("PipelineLayer built without loss_fn")
        return self._loss_fn(output, label)

    def get_shared_layer(self, key: str) -> Layer:
        return self._shared_layers[key]
